"""Mega-doc write scale-out — serve ONE document's merge from sharded
device lanes (ROADMAP item 3, the round-15 tentpole).

The viewer plane (round 13) scaled one hot doc to 100k READERS and the
pipelined tick (round 14) hid the fsync, but the write path of a single
document was still one pool row fed by one sequential op stream: the
storm cohort takes at most ONE frame per doc per tick (acks are
positional per frame and per-doc total order is one sequencer row), so a
mass-editing event or an AI-agent swarm co-writing a doc serialized on a
single lane no matter how big the pool was.

This module is the serving-path wiring for the sequence-parallel tier:

* **promotion** — when a doc's writer count / op rate crosses a
  threshold (or by explicit pin), the doc is PROMOTED: it gets ``L``
  lane sub-rows (``<doc>::~mg<i>``) in the sequencer host and the map
  pool, and (for text channels) its block-table row migrates to the
  segment-sharded flat layout through the existing ``from_block_state``
  seam (``KernelMergeHost.promote_merge_row``). Demotion reverses both
  through ``mergetree_blocks.from_flat`` / the cross-lane fold when the
  doc cools — both conversions exact and pinned.
* **per-range sub-sequencers** — each writer hashes to a lane
  (``crc32(client) % L``); a lane's frames sequence on the lane's OWN
  device sequencer row (the sub-sequencer), so up to L writer frames of
  one doc serve in ONE tick instead of one.
* **the combiner** — a host-side scalar twin of the closed-form storm
  ticket (:class:`DocSequencerMirror`, the exact algebra of
  ``ops.sequencer.storm_tickets`` in DOC seq space) decides every
  batch's dup/gap/refseq/MSN outcome against the doc-level contract and
  stamps the doc's total order: sequenced lane batches take consecutive
  doc seqs in COHORT ADMISSION ORDER — exactly the order the single-lane
  path would have served the same frames across consecutive ticks, which
  is why sharded ≡ single-lane holds byte-for-byte. The lane↔doc seq
  mapping is a per-lane segment log (:class:`LaneCombineLog`), the
  analog of per-block summaries: position (seq) transforms stay O(log
  segments) lookups, never a rescan.
* **per-range summaries / reads** — a promoted doc's converged map is
  the LWW fold ACROSS lanes by translated doc seq
  (:func:`fold_map_rows` — per-range summaries rolling up exactly like
  block summaries), with the pre-promotion row kept frozen as the
  baseline range. Catch-up records translate lane windows to doc
  windows through the same log.

Division of labor with the device kernels: the lane sub-sequencer rows
run the REAL ``storm_tickets`` on device (their per-client cseq planes
are the dedup authority for cleaned batches) and the map fold runs the
real VMEM kernel per lane row; only the doc-LEVEL algebra (one scalar
update per frame — O(1), nowhere near the device critical path) runs on
the host, because doc seqs depend on admission order across lanes which
no single lane can see. The lane rows are fed CLEANED batches: the
mirror trims the dup prefix and rejects gap/refseq/inactive outcomes
before the device sees them, so lane-space cseq streams stay contiguous
and lane rows never NACK (their refs are pinned to 0; the doc-space
refseq law lives in the mirror, where the doc MSN actually is).

Durability: promoted serving rides the SAME storm WAL — lane entries
appear in tick headers under their lane ids (lane-space seqs; reads
translate), and promote/demote (and the rare refseq-NACK client mark,
the only zero-op outcome with state effects) append CONTROL records
(``"mg"`` header field) so replay re-decides the entire lifecycle
identically. Chaos kill points: ``megadoc.mid_promotion``,
``megadoc.mid_combine``, ``megadoc.mid_demotion``.

Known bounds (documented, not silent): the combine log grows one
segment per combined batch; with ``trim_combine_logs=True`` the
maintenance pass retires segments below the translated doc-MSN horizon
(converged reads stay exact through slot-aligned vseq floors; catch-up
reads below the horizon raise a reload-from-snapshot error — the
``doc_index_retention_ticks`` contract). A client that JOINS while
the doc is promoted is adopted by the mirror with join-at-current-MSN
semantics, but the join op itself sequences on the (frozen) doc row and
its seq-rev is discarded at demotion — join/leave churn belongs before
promotion or after demotion; quarantine of any lane freezes the whole
doc (readmission of a promoted doc means demote-after-readmit). A
demoted doc RE-promotes into a fresh lane EPOCH (``::~mg<e>.<i>`` ids),
so both cycles' records translate forever and replay re-decides both
identically. Viewer rooms key by the PARENT doc at harvest, so
per-tick viewer frames keep flowing for promoted docs (doc-space
windows via the combiner's ack quads).
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Any, Callable, NamedTuple

import numpy as np

from ..ops import opcodes as oc
from ..utils import faults

INT32_MAX = int(oc.INT32_MAX)

#: Lane sub-doc id separator: ``<doc>::~mg<i>`` (promotion epoch 0, the
#: round-15 wire format) or ``<doc>::~mg<e>.<i>`` (re-promotion epochs —
#: a demoted doc that promotes AGAIN gets fresh lane seq spaces, so its
#: second-cycle lane ids must never alias the first cycle's WAL entries
#: or combine logs). The marker can't appear in user doc ids submitted
#: through the validated storm front door without *being* a lane id, and
#: parse/format stay exact inverses in both shapes.
LANE_SEP = "::~mg"


def lane_id(doc: str, lane: int, epoch: int = 0) -> str:
    if epoch:
        return f"{doc}{LANE_SEP}{epoch}.{lane}"
    return f"{doc}{LANE_SEP}{lane}"


def parse_lane_full(doc_id: str) -> tuple[str, int, int] | None:
    """(parent doc, epoch, lane index) for a lane sub-doc id, else
    None. Epoch-0 ids keep the round-15 ``<doc>::~mg<i>`` shape."""
    base, sep, idx = doc_id.rpartition(LANE_SEP)
    if not sep:
        return None
    epoch_s, dot, lane_s = idx.partition(".")
    try:
        if dot:
            return base, int(epoch_s), int(lane_s)
        return base, 0, int(epoch_s)
    except ValueError:
        return None


def parse_lane(doc_id: str) -> tuple[str, int] | None:
    """(parent doc, lane index) for a lane sub-doc id, else None."""
    full = parse_lane_full(doc_id)
    return None if full is None else (full[0], full[2])


def lane_of_writer(client_id: str, lanes: int) -> int:
    """Stable writer→lane assignment (the range partition): stateless,
    so ingress, replay and every host compute the same lane."""
    return zlib.crc32(client_id.encode()) % lanes


class Decision(NamedTuple):
    """One batch's doc-space ticket: the scalar twin of a
    ``storm_tickets`` row. ``n_seq == 0`` rows synthesize their ack
    without touching a lane; ``ack_row`` is the (n_seq, first, last,
    msn) i32 quad the client sees either way."""

    dups: int
    n_seq: int
    first: int     # doc seq of the first sequenced op (INT32_MAX if none)
    last: int      # doc seq of the last sequenced op (0 if none)
    msn: int       # doc MSN after this batch
    refnack: bool = False  # the state-bearing zero-op outcome

    @property
    def ack_row(self) -> tuple[int, int, int, int]:
        return (self.n_seq, self.first, self.last, self.msn)


class _Writer:
    """Doc-space mirror of one client's sequencer lane + its lane
    placement. ``offset`` maps lane-space cseqs back to the client's
    original stream (orig = lane + offset): it is fixed at adoption —
    both spaces advance together — so WAL lane entries round-trip."""

    __slots__ = ("cseq", "ref", "clu", "nack", "summarize", "evict",
                 "active", "lane", "offset")

    def __init__(self, cseq: int = 0, ref: int = 0, clu: int = 0,
                 nack: bool = False, summarize: bool = True,
                 evict: bool = True, active: bool = True,
                 lane: int = 0, offset: int = 0) -> None:
        self.cseq = cseq
        self.ref = ref
        self.clu = clu
        self.nack = nack
        self.summarize = summarize
        self.evict = evict
        self.active = active
        self.lane = lane
        self.offset = offset


class DocSequencerMirror:
    """The doc-level combiner's sequencer: an EXACT scalar twin of the
    closed-form storm ticket (``ops.sequencer.storm_tickets``) in doc
    seq space. One :meth:`decide` call per lane batch, in cohort
    admission order, IS the deterministic combiner — the interleaving it
    stamps is the same one the single-lane path produces when the same
    frames serve one per tick (buffer order), which the differential
    fuzz pins byte-for-byte.

    The doc-level ``SequencerState`` contract — dup/gap NACKs, the
    refseq-below-MSN mark, MSN/last_sent_msn law — is unchanged from the
    client's point of view; only WHERE it is computed moves (one scalar
    update per frame on the host instead of one vector row on device).

    The MSN (min ref over active writers) is tracked with a LAZY
    MIN-HEAP instead of an O(writers) scan per batch — at 10k writers
    the scan would dominate every combining tick. Correctness rests on
    the sequencer's own law: every ACCEPTED ref is >= the current MSN
    (refs below it refnack; ``ref == -1`` resolves to the head seq; the
    refnack mark itself writes cref = MSN), so the global minimum never
    decreases and stale heap entries can be popped lazily against a
    value->count map.
    """

    __slots__ = ("seq", "msn", "last_sent_msn", "nack_future", "writers",
                 "_ref_heap", "_ref_counts")

    def __init__(self, seq: int = 0, msn: int = 0,
                 last_sent_msn: int = 0,
                 nack_future: bool = False) -> None:
        self.seq = seq
        self.msn = msn
        self.last_sent_msn = last_sent_msn
        self.nack_future = nack_future
        self.writers: dict[str, _Writer] = {}
        self._ref_heap: list[int] = []
        self._ref_counts: dict[int, int] = {}

    def _track_ref(self, old: int | None, new: int) -> None:
        """Move one active writer's cref in the lazy-min structures."""
        import heapq
        if old is not None:
            self._ref_counts[old] -= 1
        c = self._ref_counts.get(new, 0)
        self._ref_counts[new] = c + 1
        if c == 0:
            heapq.heappush(self._ref_heap, new)

    @classmethod
    def from_checkpoint(cls, cp, lanes: int) -> "DocSequencerMirror":
        """Seed from a ``SequencerCheckpoint`` (the promotion source):
        every active client keeps its cseq/ref/nack state; lane
        placement hashes; offset = current cseq (lane streams restart at
        1 in lane space)."""
        m = cls(seq=cp.sequence_number, msn=cp.minimum_sequence_number,
                last_sent_msn=cp.last_sent_msn,
                nack_future=cp.nack_future)
        for c in cp.clients:
            m.writers[c["client_id"]] = _Writer(
                cseq=c["client_seq"], ref=c["ref_seq"],
                clu=c["last_update"], nack=c["nack"],
                summarize=c["can_summarize"], evict=c["can_evict"],
                active=True,
                lane=lane_of_writer(c["client_id"], lanes),
                offset=c["client_seq"])
            m._track_ref(None, c["ref_seq"])
        return m

    def adopt(self, client: str, lanes: int, clu: int) -> _Writer:
        """Register a writer that joined AFTER promotion: join-at-MSN
        semantics (cref = current msn, cseq = 0), exactly what a
        sequenced CLIENT_JOIN upserts on device."""
        w = _Writer(cseq=0, ref=self.msn, clu=clu,
                    lane=lane_of_writer(client, lanes), offset=0)
        self.writers[client] = w
        self._track_ref(None, w.ref)
        return w

    def decide(self, client: str, cseq0: int, ref: int, count: int,
               ts: int) -> Decision:
        """One batch through the doc-space ticket. Mirrors
        ``storm_tickets`` branch for branch (see its docstring for the
        deli/lambda.ts derivation); mutates the mirror exactly as the
        device mutates its row."""
        n = max(int(count), 0)
        w = self.writers.get(client)
        ok = (n > 0 and w is not None and w.active and not w.nack
              and not self.nack_future)
        if not ok:
            # Whole-batch reject (inactive / nacked / nack_future): no
            # state change; the ack quad reports the unchanged doc head.
            return Decision(0, 0, INT32_MAX, 0, self.msn)
        expected = w.cseq + 1
        no_gap = cseq0 <= expected
        dups = min(max(expected - cseq0, 0), n)
        m = (n - dups) if no_gap else 0
        refnack = no_gap and m > 0 and ref != -1 and ref < self.msn
        n_seq = 0 if refnack else m
        if refnack:
            # The refseq-below-MSN mark (deli lambda.ts:305-312): the
            # client is upserted nacked at refSeq=MSN. MSN itself does
            # not move (not a sequenced batch).
            w.cseq = cseq0 + dups
            self._track_ref(w.ref, self.msn)
            w.ref = self.msn
            w.clu = ts
            w.nack = True
            return Decision(dups, 0, INT32_MAX, 0, self.msn,
                            refnack=True)
        if n_seq == 0:
            # Gap or pure dup resend: no state change.
            return Decision(dups, 0, INT32_MAX, 0, self.msn)
        seq2 = self.seq + n_seq
        ref_eff = seq2 if ref == -1 else ref
        w.cseq = cseq0 + n - 1
        self._track_ref(w.ref, ref_eff)
        w.ref = ref_eff
        w.clu = ts
        w.nack = False
        self.seq = seq2
        self.msn = self._min_ref()
        self.last_sent_msn = self.msn
        return Decision(dups, n_seq, seq2 - n_seq + 1, seq2, self.msn)

    def _min_ref(self) -> int:
        """Min cref over active writers via the lazy heap (stale heads
        popped against the count map); the head seq with no writers —
        the kernel's no-active-clients branch."""
        import heapq
        heap = self._ref_heap
        while heap and self._ref_counts.get(heap[0], 0) <= 0:
            self._ref_counts.pop(heap[0], None)
            heapq.heappop(heap)
        return heap[0] if heap else self.seq

    def checkpoint(self, client_timeout_ms: int):
        """The doc row's restore source at demotion — byte-comparable to
        an unpromoted twin's ``KernelSequencerHost.checkpoint`` (clients
        sorted by id, the same field law)."""
        from .sequencer import SequencerCheckpoint
        clients = [{
            "client_id": cid, "client_seq": w.cseq, "ref_seq": w.ref,
            "last_update": w.clu, "can_evict": w.evict,
            "can_summarize": w.summarize, "nack": w.nack,
        } for cid, w in sorted(self.writers.items()) if w.active]
        return SequencerCheckpoint(
            sequence_number=self.seq,
            minimum_sequence_number=self.msn,
            last_sent_msn=self.last_sent_msn,
            no_active_clients=not clients,
            clients=clients,
            nack_future=self.nack_future,
            client_timeout_ms=client_timeout_ms,
            log_offset=-1,
        )

    def export(self) -> dict:
        return {
            "seq": self.seq, "msn": self.msn,
            "last_sent_msn": self.last_sent_msn,
            "nack_future": self.nack_future,
            "writers": {cid: [w.cseq, w.ref, w.clu, int(w.nack),
                              int(w.summarize), int(w.evict),
                              int(w.active), w.lane, w.offset]
                        for cid, w in self.writers.items()},
        }

    @classmethod
    def load(cls, snap: dict) -> "DocSequencerMirror":
        m = cls(seq=snap["seq"], msn=snap["msn"],
                last_sent_msn=snap["last_sent_msn"],
                nack_future=snap["nack_future"])
        for cid, f in snap["writers"].items():
            m.writers[cid] = _Writer(
                cseq=f[0], ref=f[1], clu=f[2], nack=bool(f[3]),
                summarize=bool(f[4]), evict=bool(f[5]),
                active=bool(f[6]), lane=f[7], offset=f[8])
            if f[6]:
                m._track_ref(None, f[1])
        return m


class LaneCombineLog:
    """One lane's combined-batch segments: contiguous lane-seq windows
    mapped to their doc-seq windows — the per-range summary the seq
    transforms roll up through. Lane seqs tile [1, seq] with no holes
    (every sequenced lane op was combined exactly once), so lane→doc
    translation is one binary search + an affine offset.

    Bounded memory (ROADMAP mega-doc residue): the log grows one segment
    per combined batch, so a long-lived promotion would accumulate the
    doc's whole lane-era history. :meth:`trim_below` retires segments
    wholly below a lane horizon (the translated doc MSN) AFTER capturing
    the exact doc-space translation of every live map-plane entry at or
    below it into a slot-aligned floor — the per-slot rebased vseq the
    LWW fold keeps using, so converged reads stay exact forever while
    the segment list is bounded by the collab window. Catch-up record
    translation below the floor becomes impossible (the
    ``doc_index_retention_ticks`` contract: readers that far behind
    reload from a snapshot)."""

    __slots__ = ("seq", "lane_firsts", "doc_firsts", "lane_lasts",
                 "msns", "floor_lane", "floor_doc", "_vseq_floor",
                 "_cleared_floor")

    def __init__(self) -> None:
        self.seq = 0               # lane seq high water
        self.lane_firsts: list[int] = []
        self.lane_lasts: list[int] = []
        self.doc_firsts: list[int] = []
        self.msns: list[int] = []  # doc MSN after each combined batch
        #: Lane seqs <= floor_lane have had their segments retired; the
        #: slot-aligned floors below carry their exact doc translations.
        self.floor_lane = 0
        self.floor_doc = 0
        self._vseq_floor: np.ndarray | None = None
        self._cleared_floor = -1

    def append(self, n: int, doc_first: int, msn: int) -> tuple[int, int]:
        """Combine one cleaned batch of ``n`` ops; returns its
        (lane_first, lane_last) window."""
        lane_first = self.seq + 1
        self.seq += n
        self.lane_firsts.append(lane_first)
        self.lane_lasts.append(self.seq)
        self.doc_firsts.append(doc_first)
        self.msns.append(msn)
        return lane_first, self.seq

    def to_doc(self, lane_seq: int) -> int:
        """Doc seq of one lane seq (total over (floor_lane, seq])."""
        import bisect
        if 1 <= lane_seq <= self.floor_lane:
            raise ValueError(
                f"lane seq {lane_seq} is below the trimmed combine-log "
                f"floor {self.floor_lane} (doc seq {self.floor_doc}); "
                "readers that far behind reload from a snapshot")
        i = bisect.bisect_right(self.lane_firsts, lane_seq) - 1
        if i < 0 or lane_seq > self.lane_lasts[i]:
            raise ValueError(f"lane seq {lane_seq} outside combined "
                             f"windows (high water {self.seq})")
        return self.doc_firsts[i] + (lane_seq - self.lane_firsts[i])

    def to_doc_array(self, lane_seqs: np.ndarray) -> np.ndarray:
        """Vectorized :meth:`to_doc` for a SLOT-ALIGNED vseq plane;
        entries < 1 (absent slots / unset cleared_seq) pass through
        unchanged. Entries at or below a trimmed floor resolve through
        the slot-aligned floor captured at trim time (exact: it was
        translated while the segments were still live)."""
        out = np.asarray(lane_seqs, np.int64).copy()
        low = (out >= 1) & (out <= self.floor_lane)
        if low.any():
            assert self._vseq_floor is not None, "floor without capture"
            out[low] = self._vseq_floor[low]
        mask = out > self.floor_lane
        # NB ``mask`` re-reads OUT, so floor-resolved doc seqs (already
        # > floor_lane numerically) must not re-translate: restrict to
        # the untouched entries.
        mask &= ~low
        if mask.any():
            firsts = np.asarray(self.lane_firsts, np.int64)
            idx = np.searchsorted(firsts, out[mask], side="right") - 1
            docs = np.asarray(self.doc_firsts, np.int64)
            out[mask] = docs[idx] + (out[mask] - firsts[idx])
        return out

    def translate_cleared(self, cleared_seq: int) -> int:
        """Doc-space cleared_seq: < 1 passes through; at/below the floor
        resolves to the cleared translation captured at trim time."""
        if cleared_seq < 1:
            return cleared_seq
        if cleared_seq <= self.floor_lane:
            return self._cleared_floor
        return self.to_doc(cleared_seq)

    def trim_below(self, lane_horizon: int,
                   vseq_plane: np.ndarray | None = None,
                   cleared_seq: int = -1) -> int:
        """Retire segments wholly at/below ``lane_horizon`` (the lane
        floor of the translated doc MSN). ``vseq_plane`` is the lane's
        live map-row vseq plane (lane seqs, slot-aligned); its entries
        at/below the new floor are translated NOW — while the segments
        still exist — into the slot floor future translations read.
        Returns the number of segments dropped. New lane seqs are always
        above the high water (hence above any floor), so a trimmed entry
        can only go stale by being overwritten, never resurrected."""
        import bisect
        cut = bisect.bisect_right(self.lane_lasts, lane_horizon)
        if cut == 0:
            return 0
        if vseq_plane is not None:
            plane = np.asarray(vseq_plane, np.int64)
            translated = self.to_doc_array(plane)
            new_floor = self.lane_lasts[cut - 1]
            capture = (plane >= 1) & (plane <= new_floor)
            if self._vseq_floor is None:
                self._vseq_floor = np.full(plane.shape[0], -1, np.int64)
            self._vseq_floor[capture] = translated[capture]
        if 1 <= cleared_seq <= self.lane_lasts[cut - 1]:
            self._cleared_floor = self.translate_cleared(cleared_seq)
        self.floor_lane = self.lane_lasts[cut - 1]
        self.floor_doc = (self.doc_firsts[cut - 1]
                          + (self.lane_lasts[cut - 1]
                             - self.lane_firsts[cut - 1]))
        del self.lane_firsts[:cut]
        del self.lane_lasts[:cut]
        del self.doc_firsts[:cut]
        del self.msns[:cut]
        return cut

    def to_lane_floor(self, doc_seq: int) -> int:
        """Largest lane seq whose doc seq is <= ``doc_seq`` (0 when the
        lane has none) — the doc→lane window bound for catch-up reads.
        At/above a trimmed floor but below the first live segment the
        answer is exactly ``floor_lane``; BELOW the trimmed floor the
        exact lane seq is gone and -1 is returned (callers detect the
        reload-from-snapshot case against ``floor_lane``)."""
        import bisect
        i = bisect.bisect_right(self.doc_firsts, doc_seq) - 1
        if i < 0:
            if doc_seq >= self.floor_doc:
                return self.floor_lane
            return -1 if self.floor_lane else 0
        span = self.lane_lasts[i] - self.lane_firsts[i]
        return self.lane_firsts[i] + min(
            max(doc_seq - self.doc_firsts[i], 0), span)

    def segment_at(self, lane_first: int) -> tuple[int, int]:
        """(doc_first, msn_after) of the combined batch whose window
        STARTS at ``lane_first`` (records translation: one WAL record ==
        one combined batch)."""
        import bisect
        i = bisect.bisect_left(self.lane_firsts, lane_first)
        if i >= len(self.lane_firsts) or self.lane_firsts[i] != lane_first:
            raise ValueError(f"no combined batch starts at lane seq "
                             f"{lane_first}")
        return self.doc_firsts[i], self.msns[i]

    def export(self) -> dict:
        out = {"seq": self.seq, "lf": self.lane_firsts,
               "ll": self.lane_lasts, "df": self.doc_firsts,
               "msn": self.msns}
        if self.floor_lane:
            out["floor"] = [self.floor_lane, self.floor_doc,
                            self._cleared_floor]
            if self._vseq_floor is not None:
                out["vfloor"] = [int(v) for v in self._vseq_floor]
        return out

    @classmethod
    def load(cls, snap: dict) -> "LaneCombineLog":
        log = cls()
        log.seq = snap["seq"]
        log.lane_firsts = list(snap["lf"])
        log.lane_lasts = list(snap["ll"])
        log.doc_firsts = list(snap["df"])
        log.msns = list(snap["msn"])
        floor = snap.get("floor")
        if floor:
            log.floor_lane, log.floor_doc, log._cleared_floor = floor
            if snap.get("vfloor") is not None:
                log._vseq_floor = np.asarray(snap["vfloor"], np.int64)
        return log


def fold_map_rows(sources: list[dict]) -> dict[str, np.ndarray]:
    """Cross-lane LWW fold — per-range summaries rolled up to the doc:
    each source is one range's map planes with vseq/cleared ALREADY in
    doc seq space ({"present", "value", "vseq", "cleared_seq"}). The
    map kernel keeps ``vseq`` on DELETED slots (present=False, vseq =
    the delete's seq — map_kernel._apply_doc), so delete tombstones are
    real candidates: a slot's winner is the max-doc-vseq EVENT (set or
    delete) across sources, and it renders present iff it was a set
    that post-dates the latest clear across sources. Doc seqs are
    globally distinct, so this is exactly LWW by the doc's total
    order — the same law the single-lane kernel fold applies."""
    slots = sources[0]["present"].shape[0]
    best_vseq = np.full(slots, -1, np.int64)
    best_value = np.zeros(slots, np.int64)
    best_present = np.zeros(slots, np.bool_)
    clear = max(int(s["cleared_seq"]) for s in sources)
    for s in sources:
        vseq = np.asarray(s["vseq"], np.int64)
        take = vseq > best_vseq
        best_vseq = np.where(take, vseq, best_vseq)
        best_value = np.where(take, np.asarray(s["value"], np.int64),
                              best_value)
        best_present = np.where(take, np.asarray(s["present"], np.bool_),
                                best_present)
    # clear defaults to -1 (never cleared), so ``> clear`` is exactly
    # "an event happened" then, and "post-dates the latest clear"
    # otherwise; a delete winner renders absent either way.
    present = best_present & (best_vseq > clear)
    return {"present": present,
            "value": np.where(present, best_value, 0).astype(np.int32),
            # vseq keeps delete tombstones (the kernel does too): a
            # demoted row's future LWW compares stay exact.
            "vseq": best_vseq,
            "cleared_seq": np.int64(clear)}


class _MegaDoc:
    """Per-doc promotion state for ONE promotion epoch (mirror +
    per-lane combine logs). Retained after demotion with
    ``promoted=False`` — the lane combine logs keep translating the
    doc's lane-era WAL records. Re-promotion pushes the retired state
    into the manager's past-epoch list and starts a fresh epoch with
    EPOCHED lane ids, so the new cycle's lane seq spaces never alias
    the old cycle's records."""

    __slots__ = ("lanes", "mirror", "logs", "promoted", "epoch")

    def __init__(self, lanes: int, mirror: DocSequencerMirror,
                 epoch: int = 0) -> None:
        self.lanes = lanes
        self.mirror = mirror
        self.logs = [LaneCombineLog() for _ in range(lanes)]
        self.promoted = True
        self.epoch = epoch


class _FramePlanItem(NamedTuple):
    """One ORIGINAL frame entry's ack source after the mega transform:
    either a synthesized doc-space row (zero-op outcome) or the index of
    the kept desc whose harvested row (rewritten to doc space) it is."""

    synth: tuple | None   # (n_seq, first, last, msn) or None
    desc_rel: int         # index within the frame's kept descs (-1)


class MegaDocManager:
    """The storm controller's mega-doc plane. Attach once::

        manager = MegaDocManager(storm, default_lanes=4)

    ``storm.megadoc`` is set; submit/flush/harvest call back into the
    manager only when it is attached (a controller without one pays a
    single ``is None`` check per hook). ``writer_threshold`` /
    ``demote_idle_ticks`` arm automatic promotion/demotion from the
    observed distinct-writer rate; ``promote()``/``demote()`` are the
    explicit pins."""

    def __init__(self, storm, default_lanes: int = 4,
                 writer_threshold: int | None = None,
                 demote_idle_ticks: int | None = None,
                 writer_window_ticks: int = 64,
                 trim_combine_logs: bool = False) -> None:
        self.storm = storm
        self.default_lanes = max(1, default_lanes)
        self.writer_threshold = writer_threshold
        self.demote_idle_ticks = demote_idle_ticks
        self.writer_window_ticks = max(1, writer_window_ticks)
        # Opt-in combine-log retention (the doc_index_retention_ticks
        # contract): trim each promoted doc's per-lane segments below
        # the translated MSN horizon on the flush-cadence maintenance
        # pass. Catch-up reads below the horizon then raise a clear
        # reload-from-snapshot error; converged reads stay exact via
        # the slot-aligned vseq floors.
        self.trim_combine_logs = trim_combine_logs
        self.docs: dict[str, _MegaDoc] = {}
        #: Retired promotion epochs per doc (re-promotion pushes the
        #: previous cycle here) — their combine logs keep translating
        #: that epoch's WAL records forever.
        self.past_epochs: dict[str, list[_MegaDoc]] = {}
        #: doc -> {client, ...} seen in the current observation window
        #: (auto-promotion signal) and doc -> idle harvests (demotion).
        self._writers_seen: dict[str, set[str]] = {}
        self._window_ticks = 0
        self._idle_ticks: dict[str, int] = {}
        self._in_replay_control = False
        # Promotion-window membership ops that arrived INSIDE a storm
        # round (the pump the round runs drains the idle-eject path):
        # the pipeline cannot settle mid-round, so the op parks here and
        # the flush maintenance cadence orders it through the FULL
        # mirror path once the round completes — no more falling back to
        # legacy adopt-at-decide for promotion-window joins/leaves.
        self._deferred_members: list[tuple[str, Any]] = []
        self._draining_members = False
        # promote() settles via storm.flush(), whose tail calls
        # maybe_adapt() — the guard keeps the cycle from re-entering.
        self._adapting = False
        m = storm.merge_host.metrics
        self._g_promoted = m.gauge("megadoc.promoted_docs")
        self._g_lanes = m.gauge("megadoc.total_lanes")
        self._g_occupancy = m.gauge("megadoc.combiner_occupancy")
        self._c_promotions = m.counter("megadoc.promotions")
        self._c_demotions = m.counter("megadoc.demotions")
        self._c_combined_ops = m.counter("megadoc.combined_ops")
        self._c_combined_batches = m.counter("megadoc.combined_batches")
        self._c_synth = m.counter("megadoc.synth_acks")
        self._c_deferred = m.counter("megadoc.deferred_members")
        storm.megadoc = self

    # -- directory -------------------------------------------------------------

    def is_promoted(self, doc: str) -> bool:
        st = self.docs.get(doc)
        return st is not None and st.promoted

    def has_history(self, doc: str) -> bool:
        return doc in self.docs

    def parent_of(self, doc_id: str) -> str | None:
        """Parent doc of a lane id known to this manager (else None)."""
        parsed = parse_lane_full(doc_id)
        if parsed is not None and parsed[0] in self.docs:
            return parsed[0]
        return None

    def _state_for(self, doc: str, epoch: int) -> "_MegaDoc | None":
        """The promotion-epoch state a lane id's records translate
        through: the current epoch or a retired one."""
        st = self.docs.get(doc)
        if st is not None and st.epoch == epoch:
            return st
        for past in self.past_epochs.get(doc, ()):
            if past.epoch == epoch:
                return past
        return None

    def lane_ids(self, doc: str) -> list[str]:
        st = self.docs[doc]
        return [lane_id(doc, i, st.epoch) for i in range(st.lanes)]

    # -- lifecycle -------------------------------------------------------------

    def promote(self, doc: str, lanes: int | None = None) -> None:
        """Pin a doc into the mega class. Idempotent; settles the
        pipeline first; journals a WAL control record so replay
        re-promotes at the identical point. A doc demoted earlier this
        life RE-promotes into a fresh EPOCH: new lane ids
        (``::~mg<e>.<i>``), fresh sub-sequencer seq spaces, the retired
        cycle's combine logs kept for its records' translation — replay
        re-decides both cycles identically."""
        if self.is_promoted(doc):
            return
        lanes = max(1, lanes or self.default_lanes)
        storm = self.storm
        if doc in storm.quarantined:
            raise RuntimeError(f"cannot promote quarantined doc {doc!r}")
        prior = self.docs.get(doc)
        epoch = prior.epoch + 1 if prior is not None else 0
        storm.flush()
        now = int(storm.service._clock())
        event = {"op": "promote", "doc": doc, "lanes": lanes}
        if epoch:
            event["epoch"] = epoch
        self._append_control(event, now)
        # Kill window: control journaled, lane rows NOT yet seeded —
        # recovery replays the control and re-seeds from the identical
        # recovered doc checkpoint.
        faults.crashpoint("megadoc.mid_promotion")
        self._apply_promote(doc, lanes, epoch)

    def _apply_promote(self, doc: str, lanes: int, epoch: int = 0) -> None:
        prior = self.docs.get(doc)
        if prior is not None:
            assert not prior.promoted and epoch == prior.epoch + 1, (
                doc, epoch, prior.epoch, prior.promoted)
            self.past_epochs.setdefault(doc, []).append(prior)
        seq_host = self.storm.seq_host
        seq_host._row(doc)  # a never-served doc promotes from an empty row
        cp = seq_host.checkpoint(doc)
        st = _MegaDoc(lanes, DocSequencerMirror.from_checkpoint(cp, lanes),
                      epoch=epoch)
        self.docs[doc] = st
        for i in range(lanes):
            self._sync_lane_row(doc, i)
        self._c_promotions.inc()
        self._export_gauges()
        # Text channels ride the merge-host promotion seam when present
        # (block row -> segment-sharded flat layout across device lanes).
        mh = self.storm.merge_host
        if getattr(mh, "seg_mesh", None) is not None:
            for key in list(mh._merge_rows):
                if key.doc_id == doc and not mh.is_mega_row(key):
                    mh.promote_merge_row(key)

    def demote(self, doc: str) -> None:
        """Fold the lanes back into the single-lane doc: doc map row :=
        cross-lane fold (doc-space vseqs), doc sequencer row := the
        mirror's checkpoint, lane rows released. The combine logs stay
        (they translate the doc's lane-era records forever)."""
        st = self.docs.get(doc)
        assert st is not None and st.promoted, f"{doc!r} not promoted"
        storm = self.storm
        storm.flush()
        now = int(storm.service._clock())
        self._append_control({"op": "demote", "doc": doc}, now)
        # Kill window: control journaled, fold NOT yet applied —
        # recovery replays promote + every lane tick + this control and
        # re-folds the identical lane states.
        faults.crashpoint("megadoc.mid_demotion")
        self._apply_demote(doc)

    def _apply_demote(self, doc: str) -> None:
        st = self.docs[doc]
        storm = self.storm
        fold = self._fold_doc(doc)
        self._write_doc_map_row(doc, fold)
        storm.seq_host.restore(
            doc, st.mirror.checkpoint(
                storm.seq_host.DEFAULT_TIMEOUT_MS))
        from .merge_host import ChannelKey
        for lid in self.lane_ids(doc):
            if lid in storm.seq_host._rows:
                storm.seq_host.release_doc(lid)
            key = ChannelKey(lid, storm.datastore, storm.channel)
            if key in storm.merge_host._map_rows:
                storm.merge_host.release_map_row(key)
        st.promoted = False
        self._idle_ticks.pop(doc, None)
        self._c_demotions.inc()
        self._export_gauges()
        mh = storm.merge_host
        for key in list(mh._merge_rows):
            if key.doc_id == doc and mh.is_mega_row(key):
                mh.demote_merge_row(key)

    def _export_gauges(self) -> None:
        promoted = [d for d, s in self.docs.items() if s.promoted]
        self._g_promoted.set(len(promoted))
        self._g_lanes.set(sum(self.docs[d].lanes for d in promoted))

    # -- WAL control records ---------------------------------------------------

    def _append_control(self, event: dict, now: int) -> None:
        """Journal one lifecycle event as a docs-less tick record (the
        ``"mg"`` header field): tick ids stay 1:1 with WAL record
        indices and replay re-applies the event at the same point."""
        if self._in_replay_control:
            return  # the record being replayed IS the journal entry
        storm = self.storm
        # Replay applies controls strictly by WAL position, so every
        # tick DISPATCHED before this control must have its record (and
        # tick id) in the WAL first. promote/demote settle via flush();
        # a refseq mark fires inside a cohort, where the harvest-first
        # loop has settled only the DUE tick — at pipeline_depth >= 2 a
        # later tick can still be in flight, and appending past it
        # would replay the mark ahead of ops it logically followed.
        storm._harvest()
        from .storm import STORM_WAL_VERSION
        header = json.dumps(
            {"v": STORM_WAL_VERSION, "ts": now, "docs": [],
             "mg": event}, separators=(",", ":")).encode()
        blob = struct.pack("<I", len(header)) + header
        tick_id = storm._tick_counter
        storm._tick_counter += 1
        if storm._group_wal is not None:
            idx = storm._group_wal.append([blob])
            assert idx == tick_id, (idx, tick_id)
        elif storm._blob_log is not None:
            idx = storm._blob_log.append(blob)
            assert idx == tick_id, (idx, tick_id)
        else:
            storm._tick_blobs[tick_id] = blob

    def apply_control(self, event: dict, ts: int) -> None:
        """Replay one journaled lifecycle event (``_replay_wal``)."""
        self._in_replay_control = True
        try:
            op = event["op"]
            if op == "promote":
                self._apply_promote(event["doc"], event["lanes"],
                                    event.get("epoch", 0))
            elif op == "demote":
                self._apply_demote(event["doc"])
            elif op == "mark":
                # Re-apply a refseq-NACK client mark (the only zero-op
                # outcome with state effects — it never rode a tick).
                # The event is SELF-DESCRIBING: it carries the cref the
                # mark captured (the doc MSN at DECISION time), so its
                # effect is position-independent — the mark may replay
                # before or after same-cohort entries that move the MSN
                # and still land the exact live value. (Records from
                # before the field existed fall back to apply-time MSN.)
                st = self.docs[event["doc"]]
                w = st.mirror.writers.get(event["client"])
                if w is None:
                    w = st.mirror.adopt(event["client"], st.lanes, ts)
                w.cseq = event["cseq"]
                new_ref = event.get("ref", st.mirror.msn)
                st.mirror._track_ref(w.ref, new_ref)
                w.ref = new_ref
                w.clu = event["ts"]
                w.nack = True
            elif op == "member":
                # Re-apply a promotion-window CLIENT_JOIN/LEAVE at the
                # identical WAL position (the bus holds the op itself
                # for history; row/mirror state rebuilds from here — a
                # bus-side re-sequence of an already-active client is an
                # IGNORED dup-join, so the two replay domains compose).
                self._apply_member(event)
            else:
                raise ValueError(f"unknown megadoc control {op!r}")
        finally:
            self._in_replay_control = False

    # -- ingress (submit_frame) ------------------------------------------------

    def ingress_frame(self, docs: list[tuple]) -> list[dict] | None:
        """Map promoted-doc entries to their writers' lane ids (pure,
        stateless — decisions wait for cohort selection so doc-seq
        assignment order equals WAL order equals replay order). Returns
        the per-entry mega descriptors (None when nothing in the frame
        is promoted); entries are rewritten IN PLACE in ``docs``."""
        infos: list[dict] | None = None
        for i, (doc, client, cseq0, ref, count) in enumerate(docs):
            if not self.is_promoted(doc):
                continue
            st = self.docs[doc]
            w = st.mirror.writers.get(client)
            lane = (w.lane if w is not None
                    else lane_of_writer(client, st.lanes))
            if infos is None:
                infos = [None] * len(docs)  # type: ignore[list-item]
            infos[i] = {"doc": doc, "lane": lane}
            docs[i] = (lane_id(doc, lane, st.epoch), client, cseq0, ref,
                       count)
        return infos

    # -- promotion-window membership (round-17 satellite) ----------------------
    #
    # ROADMAP item 3 residue: a CLIENT_JOIN/LEAVE that lands while the
    # doc is promoted used to sequence on the FROZEN doc row — a stale
    # doc seq that collides with the lane-combined stream, discarded at
    # demotion (adopt-without-sequence). Routerlicious now routes
    # membership ops through this seam: the doc row is fast-forwarded to
    # the combiner mirror's head (seq/msn + every active writer's
    # doc-space cseq/ref), the op sequences at mirror.seq + 1 through
    # the NORMAL deli path (history, quorum and audience all see it),
    # and the mirror absorbs the outcome + journals a control record so
    # replay re-applies it at the identical WAL position — promoted ≡
    # single-lane holds for membership churn too (the join-mid-promotion
    # differential test pins it).

    def _sync_doc_row(self, doc: str) -> None:
        """Pin the (frozen) doc sequencer row to the mirror's doc-space
        head — the demotion restore, run early so a membership op
        sequences at the doc's TRUE head instead of the stale
        at-promotion seq."""
        st = self.docs[doc]
        self.storm.seq_host.restore(
            doc, st.mirror.checkpoint(
                self.storm.seq_host.DEFAULT_TIMEOUT_MS))

    def intercept_membership(self, doc: str, raw):
        """Pre-order hook for one CLIENT_JOIN/LEAVE: False for
        unpromoted docs (the caller proceeds unintercepted). For a
        promoted doc: settle the pipeline (the mirror's head must be
        final, and the control journaled later must land after every
        already-composed tick's record), then fast-forward the doc row
        so the deli path stamps the op the correct doc seq. Returns the
        string ``"deferred"`` when the op arrived INSIDE a storm round:
        the pipeline cannot settle mid-round, so the op parks on the
        deferred-membership queue and the flush maintenance cadence
        orders it through this same mirror path right after the round —
        the caller must NOT order it now."""
        if not self.is_promoted(doc):
            return False
        if self.storm._in_round:
            # Idle-eject cadence firing inside a round (the round's pump
            # drains the eject path): defer — never legacy-adopt, never
            # recurse into the cohort being assembled.
            self._deferred_members.append((doc, raw))
            self._c_deferred.inc()
            return "deferred"
        self.storm.flush()
        self._sync_doc_row(doc)
        return True

    def _drain_deferred_membership(self) -> None:
        """Order the membership ops a storm round deferred — now at top
        level, so the full intercept path (settle + fast-forward +
        mirror absorb + "member" control) runs for each. A doc demoted
        meanwhile just orders through the normal deli path."""
        if self._draining_members or not self._deferred_members:
            return
        if self.storm._in_round or self.storm._replay:
            return
        self._draining_members = True
        try:
            while self._deferred_members:
                doc, raw = self._deferred_members.pop(0)
                self.storm.service._order_membership(doc, raw)
        finally:
            self._draining_members = False

    def complete_membership(self, doc: str, raw) -> None:
        """Post-sequence hook (the service pumped the intercepted op):
        absorb the outcome into the mirror + lane rows and journal the
        ``"member"`` control so recovery re-applies it identically."""
        from ..protocol.messages import MessageType
        storm = self.storm
        cp = storm.seq_host.checkpoint(doc)
        join = raw.type == MessageType.CLIENT_JOIN
        client = (getattr(raw.data, "client_id", raw.data) if join
                  else raw.data)
        event = {"op": "member", "doc": doc, "client": str(client),
                 "join": bool(join), "ts": raw.timestamp,
                 "seq": cp.sequence_number,
                 "msn": cp.minimum_sequence_number,
                 "lsm": cp.last_sent_msn}
        if join:
            event["can_summarize"] = bool(raw.can_summarize)
            event["can_evict"] = bool(raw.can_evict)
        self._append_control(event, raw.timestamp)
        self._apply_member(event)

    def _apply_member(self, event: dict) -> None:
        """One journaled membership event into the mirror (+ the lane
        and doc rows) — shared by the live path and WAL replay, so both
        converge on identical state. The doc-space scalars come from the
        RECORD (the sequenced outcome), never recomputed."""
        st = self.docs[event["doc"]]
        m = st.mirror
        client = event["client"]
        m.seq = event["seq"]
        m.msn = event["msn"]
        m.last_sent_msn = event["lsm"]
        w = m.writers.get(client)
        if event["join"]:
            if w is None or not w.active:
                w = m.adopt(client, st.lanes, event["ts"])
            w.summarize = bool(event.get("can_summarize", True))
            w.evict = bool(event.get("can_evict", True))
            w.clu = event["ts"]
            self._sync_lane_row(event["doc"], w.lane)
        elif w is not None and w.active:
            # Retire: drop the writer's cref from the MSN tracking (the
            # removal half of _track_ref) — the recorded msn above
            # already reflects the post-leave minimum.
            w.active = False
            m._ref_counts[w.ref] = m._ref_counts.get(w.ref, 1) - 1
            self._sync_lane_row(event["doc"], w.lane)
        # Pin the doc row to the post-membership mirror state: the live
        # path just sequenced on it, replay never did — the restore
        # makes both byte-identical.
        self._sync_doc_row(event["doc"])

    def observe_writers(self, docs: list[tuple]) -> None:
        """Auto-promotion signal: distinct writers per doc over a
        sliding tick window (called from submit_frame BEFORE the lane
        rewrite, so the ids are parent doc ids)."""
        if self.writer_threshold is None:
            return
        for doc, client, *_ in docs:
            self._writers_seen.setdefault(doc, set()).add(client)

    # -- cohort transform (the combiner) ---------------------------------------

    def decide_frame(self, frame, now: int):
        """Run the doc-space ticket over one selected frame's promoted
        entries (cohort admission order == doc seq order), trim dup
        prefixes out of the words, and return the transformed cohort
        contribution::

            (docs', words', counts', meta', plan, desc_rows)

        ``plan`` aligns with the ORIGINAL entries (ack reconstruction);
        ``desc_rows`` aligns with the KEPT descs — the doc-space ack
        quad for lane descs, None for pass-through descs (harvest
        rewrites the device ack matrix rows to the quads). Entries whose
        outcome is zero-op (dup/gap/refseq/inactive) are dropped from
        the cohort entirely — their ack rows are synthesized."""
        st_by_idx: list[dict | None] = frame.mega
        kept_docs: list[tuple] = []
        kept_words: list[np.ndarray] = []
        plan: list[_FramePlanItem] = []
        desc_rows: list[tuple | None] = []
        words = frame.words
        off = 0
        changed = False
        combined = 0
        for i, entry in enumerate(frame.docs):
            doc_id, client, cseq0, ref, count = entry
            chunk = words[off:off + count]
            off += count
            info = st_by_idx[i]
            if info is None:
                plan.append(_FramePlanItem(None, len(kept_docs)))
                kept_docs.append(entry)
                kept_words.append(chunk)
                desc_rows.append(None)
                continue
            st = self.docs[info["doc"]]
            mirror = st.mirror
            w = mirror.writers.get(client)
            if w is None:
                seq_row = self.storm.seq_host._rows.get(info["doc"])
                if seq_row is not None and client in \
                        self.storm.seq_host._slots[seq_row]:
                    # Joined the (frozen) doc row after promotion:
                    # adopt with join-at-MSN semantics.
                    w = mirror.adopt(client, st.lanes, now)
                    self._sync_lane_row(info["doc"], w.lane)
            dec = mirror.decide(client, cseq0, ref, count, now)
            if dec.n_seq == 0:
                changed = True
                self._c_synth.inc()
                if dec.refnack:
                    # Journal the refseq mark (the only state-bearing
                    # zero-op outcome) so replay re-marks identically.
                    # The captured cref (the MSN at this decision) rides
                    # the event, making its replay position-independent;
                    # journaling BEFORE this cohort's tick record keeps
                    # the mark under the tick's durability watermark, so
                    # the frame's withheld nack ack never outruns it.
                    self._append_control(
                        {"op": "mark", "doc": info["doc"],
                         "client": client, "cseq": w.cseq,
                         "ref": w.ref, "ts": now},
                        now)
                plan.append(_FramePlanItem(dec.ack_row, -1))
                continue
            lane = w.lane  # a sequenced decision implies a known writer
            log = st.logs[lane]
            log.append(dec.n_seq, dec.first, dec.msn)
            lane_cseq0 = (cseq0 + dec.dups) - w.offset
            if dec.dups or lane_cseq0 != cseq0:
                # A trim or an offset-shifted lane cseq invalidates the
                # frame's own meta columns.
                changed = True
            if dec.dups:
                chunk = chunk[dec.dups:]
            plan.append(_FramePlanItem(None, len(kept_docs)))
            desc_rows.append(dec.ack_row)
            kept_docs.append((lane_id(info["doc"], lane, st.epoch),
                              client, lane_cseq0, ref, dec.n_seq))
            kept_words.append(chunk)
            combined += dec.n_seq
        if combined:
            self._c_combined_ops.inc(combined)
            self._c_combined_batches.inc(
                sum(1 for row in desc_rows if row is not None))
            # Kill window: combiner state advanced (doc seqs assigned,
            # mirrors moved), device tick NOT yet dispatched and the
            # tick's WAL record NOT yet appended — everything here is
            # volatile; clients resend and the re-decide is identical.
            faults.crashpoint("megadoc.mid_combine")
        if not changed and len(kept_docs) == len(frame.docs):
            # Pure pass-through (clean batches, zero lane-cseq offsets —
            # the steady-state shape): reuse the frame's zero-copy views
            # AND its meta/counts columns verbatim. The meta ref column
            # still carries doc refs for the lane descs; _flush_round
            # force-zeroes the device feed for lane rows either way
            # (the cached lane_seq_rows store), so the device contract
            # holds without a per-entry rebuild on the hot path.
            return (kept_docs, frame.words, frame.counts, frame.meta,
                    plan, desc_rows)
        counts = np.array([d[4] for d in kept_docs], np.int32)
        flat = (np.concatenate(kept_words) if kept_words
                else np.empty(0, np.uint32))
        meta = self._meta_for(kept_docs)
        return kept_docs, flat, counts, meta, plan, desc_rows

    @staticmethod
    def _meta_for(docs: list[tuple]) -> np.ndarray:
        """Device-feed columns for transformed descs. Lane rows take
        ref 0 — their cref planes stay pinned at 0 so the device's
        refseq/MSN law never fires on a lane (the doc-space law already
        ran in the mirror); the DESC tuple keeps the doc-space ref for
        the WAL header and records translation."""
        meta = np.zeros((len(docs), 3), np.int32)
        for i, (doc, _c, cseq0, ref, count) in enumerate(docs):
            meta[i, 0] = cseq0
            meta[i, 1] = 0 if parse_lane(doc) else ref
            meta[i, 2] = count
        return meta

    def replay_decide(self, descs: list[tuple], now: int) -> None:
        """WAL replay twin of :meth:`decide_frame`: lane entries in a
        replayed tick are already cleaned (all-sequenced), so re-apply
        the sequenced branch of the algebra to rebuild mirrors and
        combine logs deterministically."""
        for doc_id, client, lane_cseq0, ref, count in descs:
            parsed = parse_lane_full(doc_id)
            if parsed is None or parsed[0] not in self.docs:
                continue
            doc, epoch, lane = parsed
            st = self.docs[doc]
            # Controls replay strictly by WAL position, so the current
            # epoch at any lane entry's replay equals its live epoch.
            assert st.epoch == epoch, (doc_id, st.epoch)
            mirror = st.mirror
            w = mirror.writers.get(client)
            if w is None:
                w = mirror.adopt(client, st.lanes, now)
            cseq0 = lane_cseq0 + w.offset
            n = count
            seq2 = mirror.seq + n
            ref_eff = seq2 if ref == -1 else ref
            w.cseq = cseq0 + n - 1
            mirror._track_ref(w.ref, ref_eff)
            w.ref = ref_eff
            w.clu = now
            w.nack = False
            mirror.seq = seq2
            mirror.msn = mirror._min_ref()
            mirror.last_sent_msn = mirror.msn
            st.logs[lane].append(n, seq2 - n + 1, mirror.msn)

    def finish_cohort(self, descs: list[tuple]) -> None:
        """Combiner occupancy gauge: lane descs this tick / total lanes
        of currently promoted docs."""
        total = sum(s.lanes for s in self.docs.values() if s.promoted)
        if not total:
            return
        active = sum(1 for d, *_ in descs if parse_lane(d) is not None)
        self._g_occupancy.set(active / total)

    def lane_seq_rows(self, descs: list[tuple], seq_rows: np.ndarray
                      ) -> np.ndarray:
        """Sequencer rows of the lane descs in a cohort (the device-feed
        ref column is force-zeroed for exactly these rows — replay feeds
        metas rebuilt from WAL entries, whose ref column carries the
        doc-space ref)."""
        idx = [i for i, (d, *_r) in enumerate(descs)
               if parse_lane(d) is not None]
        return seq_rows[np.asarray(idx, np.int32)] if idx else \
            np.empty(0, np.int32)

    # -- lane row maintenance --------------------------------------------------

    def _sync_lane_row(self, doc: str, lane: int) -> None:
        """(Re)install one lane's device sequencer row from the mirror:
        every writer assigned to the lane, active, cseq in LANE space,
        cref pinned 0 (see :meth:`_meta_for`), lane seq = the combine
        log's high water. Deterministic in the mirror, so promotion,
        post-promotion adoption and replay all converge on the same
        row."""
        from .sequencer import SequencerCheckpoint
        st = self.docs[doc]
        clients = [{
            "client_id": cid, "client_seq": w.cseq - w.offset,
            "ref_seq": 0, "last_update": w.clu, "can_evict": w.evict,
            "can_summarize": w.summarize, "nack": False,
        } for cid, w in sorted(st.mirror.writers.items())
            if w.active and w.lane == lane]
        self.storm.seq_host.restore(
            lane_id(doc, lane, st.epoch), SequencerCheckpoint(
            sequence_number=st.logs[lane].seq,
            minimum_sequence_number=0,
            last_sent_msn=0,
            no_active_clients=not clients,
            clients=clients,
            nack_future=False,
            client_timeout_ms=self.storm.seq_host.DEFAULT_TIMEOUT_MS,
            log_offset=-1,
        ))

    # -- reads -----------------------------------------------------------------

    def _lane_map_sources(self, doc: str) -> list[dict]:
        """Doc-space map planes of every range: the frozen pre-promotion
        row (already doc-space) + each lane row translated through its
        combine log."""
        storm = self.storm
        mh = storm.merge_host
        st = self.docs[doc]
        xs = mh._xstate
        sources = []

        def row_planes(row: int) -> dict:
            return {"present": np.asarray(xs.present[row]),
                    "value": np.asarray(xs.value[row]),
                    "vseq": np.asarray(xs.vseq[row], np.int64),
                    "cleared_seq": int(np.asarray(xs.cleared_seq[row]))}

        from .merge_host import ChannelKey
        base_key = ChannelKey(doc, storm.datastore, storm.channel)
        if base_key in mh._map_rows:
            sources.append(row_planes(mh._map_rows[base_key].row))
        for i in range(st.lanes):
            key = ChannelKey(lane_id(doc, i, st.epoch), storm.datastore,
                             storm.channel)
            mrow = mh._map_rows.get(key)
            if mrow is None:
                continue
            planes = row_planes(mrow.row)
            log = st.logs[i]
            planes["vseq"] = log.to_doc_array(planes["vseq"])
            planes["cleared_seq"] = log.translate_cleared(
                planes["cleared_seq"])
            sources.append(planes)
        return sources

    def _fold_doc(self, doc: str) -> dict[str, np.ndarray]:
        sources = self._lane_map_sources(doc)
        if not sources:
            s = self.storm.merge_host._map_slots
            return {"present": np.zeros(s, np.bool_),
                    "value": np.zeros(s, np.int32),
                    "vseq": np.full(s, -1, np.int64),
                    "cleared_seq": np.int64(-1)}
        return fold_map_rows(sources)

    def map_entries(self, doc: str) -> dict[str, int]:
        """Converged doc map of a promoted doc (the cross-lane fold) in
        the storm literal-value shape — byte-comparable to an unpromoted
        twin's ``merge_host.map_entries``."""
        self.storm.flush()
        fold = self._fold_doc(doc)
        return {f"k{s}": int(fold["value"][s])
                for s in np.flatnonzero(fold["present"])}

    def _write_doc_map_row(self, doc: str,
                           fold: dict[str, np.ndarray]) -> None:
        """Demotion: materialize the fold into the doc's live map row
        (vseq in DOC space, so single-lane serving resumes exact LWW)."""
        from ..ops import map_kernel as mk
        storm = self.storm
        row = storm._storm_map_row(doc)
        xs = storm.merge_host._xstate
        s_live = xs.present.shape[1]
        vseq = np.full(s_live, -1, np.int32)
        value = np.zeros(s_live, np.int32)
        present = np.zeros(s_live, np.bool_)
        n = fold["present"].shape[0]
        present[:n] = fold["present"]
        value[:n] = fold["value"]
        vseq[:n] = np.clip(fold["vseq"], -1, INT32_MAX).astype(np.int32)
        storm.merge_host._xstate = mk.MapState(
            present=xs.present.at[row].set(present),
            value=xs.value.at[row].set(value),
            vseq=xs.vseq.at[row].set(vseq),
            cleared_seq=xs.cleared_seq.at[row].set(
                np.int32(min(int(fold["cleared_seq"]), INT32_MAX))))

    def records(self, doc: str, from_seq: int, to_seq: int | None,
                base_fn: Callable) -> list[dict]:
        """Doc-space catch-up records of a (once-)promoted doc: the
        doc's own tick records (pre-promotion / post-demotion, already
        doc-space) merged with every lane's records translated through
        its combine log, sorted by doc first_seq. ``base_fn`` is the
        controller's untranslated per-id record resolver."""
        out = list(base_fn(doc, from_seq, to_seq))
        epochs = (*self.past_epochs.get(doc, ()), self.docs[doc])
        for st in epochs:
            for i in range(st.lanes):
                log = st.logs[i]
                # Bound the lane query to the requested doc window
                # (floor translation) — an incremental catch-up read
                # must not scan a long-lived promoted doc's full lane
                # history per call.
                lane_from = log.to_lane_floor(from_seq)
                if lane_from < log.floor_lane:
                    raise ValueError(
                        f"{doc!r} catch-up from doc seq {from_seq} is "
                        f"below the trimmed combine-log horizon (doc "
                        f"seq {log.floor_doc}); reload from a snapshot")
                lane_to = (None if to_seq is None
                           else log.to_lane_floor(to_seq))
                for rec in base_fn(lane_id(doc, i, st.epoch), lane_from,
                                   lane_to):
                    if rec["n_seq"] <= 0:
                        continue
                    doc_first, msn = log.segment_at(rec["first_seq"])
                    w = st.mirror.writers.get(rec["client"])
                    offset = w.offset if w is not None else 0
                    doc_rec = dict(rec)
                    doc_rec["first_seq"] = doc_first
                    doc_rec["last_seq"] = doc_first + rec["n_seq"] - 1
                    doc_rec["msn"] = msn
                    doc_rec["first_cseq"] = rec["first_cseq"] + offset
                    if doc_rec["last_seq"] <= from_seq or (
                            to_seq is not None and doc_first > to_seq):
                        continue
                    out.append(doc_rec)
        out.sort(key=lambda r: (r["first_seq"], r["tick"]))
        return out

    # -- harvest hooks ---------------------------------------------------------

    def note_harvest(self, descs: list[tuple]) -> None:
        """Demotion idleness: promoted docs absent from this harvest's
        cohort age toward ``demote_idle_ticks``; present ones reset."""
        self._window_ticks += 1
        touched: set[str] = set()
        for d, *_ in descs:
            parsed = parse_lane(d)
            if parsed is not None:
                touched.add(parsed[0])
        for doc, st in self.docs.items():
            if not st.promoted:
                continue
            if doc in touched:
                self._idle_ticks[doc] = 0
            else:
                self._idle_ticks[doc] = self._idle_ticks.get(doc, 0) + 1

    def maybe_adapt(self) -> None:
        """Flush-cadence auto promotion/demotion (thresholds armed in
        the constructor; explicit pins always win)."""
        self._drain_deferred_membership()
        if self._adapting:
            return
        self._adapting = True
        try:
            self._maybe_adapt_locked()
        finally:
            self._adapting = False

    def _maybe_adapt_locked(self) -> None:
        if self.writer_threshold is not None \
                and self._window_ticks >= self.writer_window_ticks:
            for doc, writers in list(self._writers_seen.items()):
                # A doc demoted earlier this life may RE-promote: lane
                # epoching forks the new cycle's seq spaces away from
                # the retired one's records.
                if (len(writers) >= self.writer_threshold
                        and not self.is_promoted(doc)
                        and doc not in self.storm.quarantined):
                    self.promote(doc)
            self._writers_seen.clear()
            self._window_ticks = 0
        if self.demote_idle_ticks is not None:
            for doc in [d for d, n in self._idle_ticks.items()
                        if n >= self.demote_idle_ticks
                        and self.is_promoted(d)]:
                self.demote(doc)
        if self.trim_combine_logs:
            self.trim_logs()

    def trim_logs(self, doc: str | None = None) -> int:
        """Bounded-memory maintenance for promoted docs' combine logs
        (ROADMAP mega-doc residue): retire each lane's segments below
        the lane floor of the doc MSN — the collab-window floor below
        which no active writer can reference — capturing the lane map
        row's live vseq plane translations first so the cross-lane LWW
        fold stays exact. Returns segments dropped."""
        from .merge_host import ChannelKey
        storm = self.storm
        mh = storm.merge_host
        dropped = 0
        for d, st in self.docs.items():
            if (doc is not None and d != doc) or not st.promoted:
                continue
            msn = st.mirror.msn
            for i in range(st.lanes):
                log = st.logs[i]
                horizon = log.to_lane_floor(msn)
                if horizon <= log.floor_lane:
                    continue
                key = ChannelKey(lane_id(d, i, st.epoch),
                                 storm.datastore, storm.channel)
                mrow = mh._map_rows.get(key)
                plane = cleared = None
                if mrow is not None:
                    xs = mh._xstate
                    plane = np.asarray(xs.vseq[mrow.row])
                    cleared = int(np.asarray(xs.cleared_seq[mrow.row]))
                dropped += log.trim_below(horizon, plane,
                                          -1 if cleared is None
                                          else cleared)
        return dropped

    # -- snapshot --------------------------------------------------------------

    @staticmethod
    def _export_epoch(st: _MegaDoc) -> dict:
        out = {"lanes": st.lanes, "promoted": st.promoted,
               "mirror": st.mirror.export(),
               "logs": [log.export() for log in st.logs]}
        if st.epoch:
            out["epoch"] = st.epoch
        return out

    @staticmethod
    def _load_epoch(rec: dict) -> _MegaDoc:
        st = _MegaDoc(rec["lanes"],
                      DocSequencerMirror.load(rec["mirror"]),
                      epoch=rec.get("epoch", 0))
        st.logs = [LaneCombineLog.load(s) for s in rec["logs"]]
        st.promoted = rec["promoted"]
        return st

    def export_state(self) -> dict:
        out: dict = {"docs": {}}
        for doc, st in self.docs.items():
            rec = self._export_epoch(st)
            past = self.past_epochs.get(doc)
            if past:
                rec["past"] = [self._export_epoch(p) for p in past]
            out["docs"][doc] = rec
        return out

    def import_state(self, snap: dict | None) -> None:
        if not snap:
            return
        assert not self.docs, "import_state needs a fresh manager"
        for doc, rec in snap["docs"].items():
            self.docs[doc] = self._load_epoch(rec)
            if rec.get("past"):
                self.past_epochs[doc] = [self._load_epoch(p)
                                         for p in rec["past"]]
        self._export_gauges()


__all__ = ["MegaDocManager", "DocSequencerMirror", "LaneCombineLog",
           "fold_map_rows", "lane_id", "parse_lane", "parse_lane_full",
           "lane_of_writer", "LANE_SEP"]
