"""Read-replica tier — scale the ENTIRE read surface across follower
hosts (ROADMAP item 2, the round-20 tentpole).

A :class:`ReadReplica` is a read-only serving host built over one PR 19
replication follower (:class:`~.replication.ReplicaNode`): it tails the
replica WAL through the node's subscribe seam + its own poll loop,
maintains per-doc SCALAR state (the history plane's ``_FoldState`` twin
— no device rows, no JAX anywhere on the replica), and serves every
read class the leader serves:

* **viewer rooms** — the replica runs its OWN
  :class:`~.broadcaster.ViewerPlane` (this object is the duck-typed
  service it attaches to) and re-broadcasts each tailed tick's
  ``(doc, n_seq, first, last, msn, count, words)`` window exactly as
  the leader's harvest would, so a room re-homed here via the existing
  ``viewer_resync``/``moved_to`` machinery sees byte-identical frames;
* **viewer catch-up resync** + **cold get_deltas** —
  :meth:`get_deltas` materializes the tailed records through the SAME
  ``materialize_storm_records`` the leader's cold path uses;
* **read_at historical reads** and **branch reads** — :meth:`read_at`
  is the history plane's exact read path (``summary_base_for`` +
  ``fold_storm_records`` over the shared snapshot store and the tailed
  WAL), so replica-served state is byte-identical by construction.

Staleness is explicit, never silent: the replica tracks its applied
frontier against what the leader shipped (``lag``, per-doc
:meth:`doc_seq`, the ``replica.staleness_s`` apply-latency histogram),
and a read addressing seqs ABOVE the replica's watermark first waits
``read_wait_s`` for the stream to catch up, then sheds a retryable
``moved`` redirect naming the leader (:class:`ReplicaRedirect` — the
client's existing redial machinery lands it there). Reads the replica
can never serve — mega-promoted docs, whose lane-era records translate
only through the leader's live ``LaneCombineLog`` state — redirect
immediately (the documented scope limit; the leader keeps serving
them).

The :class:`ReplicaDirectory` maps rooms/read-classes to replica
labels in the SHARED snapshot store (upload-then-``set_head``, so
under a :class:`~.replication.ReplicatedHeadStore` every flip is
ship-then-flip for free, like ``__placement__``), and the leader's
front door consults a :class:`ReplicaRouter` over it: viewer connects
and cold reads for directory-assigned docs answer ``moved`` with a
replica label — a room's audience spreads across N replicas by hashing
each client's key over the doc's label list while writer traffic never
leaves the leader.

Chaos kill classes (tools/chaos.py ``--replicas``):
``replica.mid_apply`` (records indexed, broadcast not yet published)
and ``replica.mid_read`` (inside a replica-served read) — a restarted
replica rebuilds its whole index by re-polling its own durable WAL
from zero, and the digest-vs-twin bar proves replica reads never
change bytes.
"""

from __future__ import annotations

import json
import struct
import threading
import time
import zlib
from typing import Any

from ..utils import MetricsRegistry, faults
from .history import (
    HistoryError,
    fold_storm_records,
    load_summary_record,
    summary_base_for,
)

#: Shared-store key of the replica directory record (the
#: ``__placement__`` pattern — upload then set_head, ship-then-flip
#: under a ReplicatedHeadStore).
REPLICA_DIRECTORY_KEY = "__replicas__"

#: Read classes the directory can route (writes NEVER route to a
#: replica — the leader owns sequencing).
READ_KINDS = ("viewer", "read_at", "get_deltas")


class ReplicaRedirect(RuntimeError):
    """This read must be served elsewhere (stale replica, or a read
    class this replica cannot serve): carries the ``moved_to`` host
    label + retry hint, the same shape placement's live-migration
    redirects use — the front door maps it to a retryable ``moved``
    response and the client's existing redial machinery converges."""

    def __init__(self, message: str, moved_to: str | None,
                 retry_after_s: float = 0.05) -> None:
        super().__init__(message)
        self.moved_to = moved_to
        self.retry_after_s = retry_after_s


class ReplicaDirectory:
    """Rooms/read-classes → replica labels, in the shared store.

    One record under :data:`REPLICA_DIRECTORY_KEY`:
    ``{"replicas": {label: meta}, "rooms": {doc: [label, ...]},
    "reads": {kind: [label, ...]}}``. A doc's room assignment wins over
    the read-class default; a multi-label assignment spreads clients by
    ``crc32(client_key) % len(labels)`` (the ``genesis_owner`` idiom),
    which is how ONE hot doc's audience lands on N replicas."""

    def __init__(self, store) -> None:
        self.store = store
        self._rec: dict[str, Any] = {"kind": "replica-directory",
                                     "replicas": {}, "rooms": {},
                                     "reads": {}}
        self.reload()

    def reload(self) -> None:
        """Re-read the shared head (cross-host visibility: another
        host's assignment is live here after its flip)."""
        handle = self.store.head(REPLICA_DIRECTORY_KEY)
        if handle is None:
            return
        rec = self.store.get(REPLICA_DIRECTORY_KEY, handle)
        if rec is not None:
            self._rec = rec

    def _save(self) -> None:
        # Upload-then-flip: under a ReplicatedHeadStore the set_head
        # ships to the follower quorum BEFORE the backend flips, so a
        # failover never resurrects a stale directory.
        handle = self.store.upload(REPLICA_DIRECTORY_KEY, self._rec)
        self.store.set_head(REPLICA_DIRECTORY_KEY, handle)

    # -- membership ------------------------------------------------------------

    @property
    def replicas(self) -> dict[str, dict]:
        return dict(self._rec["replicas"])

    def register(self, label: str, **meta: Any) -> None:
        self._rec["replicas"][label] = dict(meta)
        self._save()

    def deregister(self, label: str) -> None:
        """Drop a dead replica: its room/read assignments fall back to
        the surviving labels (or the leader when none remain)."""
        self._rec["replicas"].pop(label, None)
        for key in ("rooms", "reads"):
            table = self._rec[key]
            for name in list(table):
                table[name] = [l for l in table[name] if l != label]
                if not table[name]:
                    del table[name]
        self._save()

    # -- assignment ------------------------------------------------------------

    def assign_room(self, doc: str, labels) -> None:
        labels = [labels] if isinstance(labels, str) else list(labels)
        self._rec["rooms"][doc] = labels
        self._save()

    def unassign_room(self, doc: str) -> None:
        if self._rec["rooms"].pop(doc, None) is not None:
            self._save()

    def assign_reads(self, kind: str, labels) -> None:
        """Default routing for one read class (``read_at`` /
        ``get_deltas`` / ``viewer``) when a doc has no room
        assignment."""
        if kind not in READ_KINDS:
            raise ValueError(f"unknown read class {kind!r} "
                             f"(one of {READ_KINDS})")
        labels = [labels] if isinstance(labels, str) else list(labels)
        self._rec["reads"][kind] = labels
        self._save()

    def rooms_on(self, label: str) -> list[str]:
        return [doc for doc, labels in self._rec["rooms"].items()
                if label in labels]

    def rooms(self) -> dict[str, list[str]]:
        return {doc: list(labels)
                for doc, labels in self._rec["rooms"].items()}

    def replica_for(self, doc: str, kind: str | None = None,
                    key: str | None = None) -> str | None:
        """The serving replica for one (doc, read-class, client): the
        doc's room assignment wins, else the read-class default; None
        = the leader serves. Deregistered labels never route."""
        labels = self._rec["rooms"].get(doc)
        if not labels and kind is not None:
            labels = self._rec["reads"].get(kind)
        if not labels:
            return None
        labels = [l for l in labels if l in self._rec["replicas"]]
        if not labels:
            return None
        ident = key if key else doc
        return labels[zlib.crc32(ident.encode()) % len(labels)]


class ReplicaRouter:
    """Leader-side read routing (``service.read_router``, consulted by
    the front door): writes always serve locally; directory-assigned
    read classes answer with the replica label to redirect to."""

    def __init__(self, directory: ReplicaDirectory,
                 local_label: str | None = None,
                 retry_after_s: float = 0.05,
                 metrics: MetricsRegistry | None = None) -> None:
        self.directory = directory
        self.local_label = local_label
        self.retry_after_s = retry_after_s
        self.metrics = metrics if metrics is not None \
            else MetricsRegistry()
        self._c_redirects = self.metrics.counter("replica.redirects")

    def route_read(self, doc: str, kind: str,
                   key: str | None = None) -> str | None:
        if kind not in READ_KINDS:
            return None  # writes (and unknown classes) stay local
        self.directory.reload()
        target = self.directory.replica_for(doc, kind, key)
        if target is None or target == self.local_label:
            return None
        self._c_redirects.inc()
        return target


class _SelfRouter:
    """Replica-side routing: writes (and reads this replica cannot
    serve) shed back to the leader; everything else serves here."""

    def __init__(self, replica: "ReadReplica") -> None:
        self.replica = replica

    @property
    def retry_after_s(self) -> float:
        return self.replica.retry_after_s

    def route_read(self, doc: str, kind: str,
                   key: str | None = None) -> str | None:
        if kind in READ_KINDS and self.replica.can_serve(doc):
            return None
        return self.replica.leader_label


class ReadReplica:
    """One read-only serving host over a replication follower.

    Duck-types the slice of the service surface the front door's read
    ops touch (``read_at``/``get_deltas``/``viewers``/``metrics``), so
    an :class:`~.alfred.AlfredServer` can mount it directly; write
    verbs raise :class:`ReplicaRedirect` toward the leader.

    No JAX, no device rows, no merge host: state is the history
    plane's scalar fold over the shared snapshot store + the follower's
    own durable WAL. ``get_deltas`` serves the STORM record tier (the
    replicated total order); the leader-local per-op JSON tier (bus
    join/leave messages) stays with the leader — the same subset the
    chaos replication digests compare.
    """

    def __init__(self, node, snapshots, label: str,
                 leader_label: str | None = None,
                 datastore: str = "default", channel: str = "root",
                 read_wait_s: float = 0.25,
                 retry_after_s: float = 0.05,
                 metrics: MetricsRegistry | None = None,
                 fanout=None, viewer_plane: bool = True,
                 **viewer_kw: Any) -> None:
        self.node = node
        self.snapshots = snapshots
        self.label = label
        self.leader_label = leader_label
        self.datastore = datastore
        self.channel = channel
        self.read_wait_s = read_wait_s
        self.retry_after_s = retry_after_s
        self.metrics = metrics if metrics is not None \
            else MetricsRegistry()
        self.fanout = fanout  # ViewerPlane's lazy-fanout seam
        node.role = "read-replica"
        #: WAL records applied (index frontier into the follower WAL).
        self.applied = 0
        #: doc -> [record dict, ...] in first_seq order — the replica's
        #: twin of the storm tick index (``n_seq > 0`` entries only,
        #: exactly what ``storm._doc_ticks`` indexes).
        self._doc_records: dict[str, list[dict]] = {}
        #: doc -> applied sequenced frontier (max last_seq tailed).
        self._doc_seq: dict[str, int] = {}
        #: branch doc -> {"parent", "seq", "name"} from tailed "hp"
        #: fork controls (lifecycle controls are never trimmed, so a
        #: from-zero re-poll always rebuilds this).
        self.branches: dict[str, dict] = {}
        #: Docs (and their lanes) ever mega-promoted: lane-era records
        #: translate only through the leader's combine logs, so these
        #: redirect — the documented replica scope limit.
        self._mega: set[str] = set()
        self._poll_lock = threading.Lock()
        # Arrival stamps from the subscribe seam (leader WAL-writer
        # thread → CHEAP: one dict store per record), drained by poll()
        # into the apply-latency histogram. Bounded: a replica that
        # stops polling must not grow this forever.
        self._arrivals: dict[int, float] = {}
        self._arrival_cap = 8192
        self.stats = {"polls": 0, "records_applied": 0,
                      "bad_records": 0, "stale_redirects": 0,
                      "room_stale_sheds": 0,
                      "reads": 0, "deltas": 0, "broadcast_ticks": 0}
        m = self.metrics
        self._g_applied = m.gauge("replica.applied")
        self._g_lag = m.gauge("replica.lag")
        self._h_staleness = m.histogram("replica.staleness_s")
        self._c_stale = m.counter("replica.stale_redirects")
        self._c_room_stale = m.counter("replica.room_stale_sheds")
        self.viewers = None
        if viewer_plane:
            from .broadcaster import ViewerPlane
            ViewerPlane(self, metrics=m, **viewer_kw)  # sets .viewers
        node.subscribe(self._on_shipped)
        self.read_router = _SelfRouter(self)
        self.poll()  # adopt whatever the follower WAL already holds

    # -- tail loop -------------------------------------------------------------

    def _on_shipped(self, start: int, records: list) -> None:
        """Subscribe-seam notifier (leader's WAL writer thread): stamp
        arrival times only — folding happens in :meth:`poll` on the
        replica's own time."""
        now = time.monotonic()
        arrivals = self._arrivals
        for i in range(start, start + len(records)):
            arrivals[i] = now
        while len(arrivals) > self._arrival_cap:
            arrivals.pop(next(iter(arrivals)), None)

    def poll(self, max_records: int | None = None) -> int:
        """Apply newly shipped WAL records: parse each storm header,
        register lifecycle controls, index per-doc records, and
        re-broadcast viewer tick windows to this replica's rooms.
        Returns records applied. Idempotent and restart-safe: a fresh
        replica over an existing follower directory re-polls from zero
        (retention fillers parse to docs-less no-ops)."""
        applied = 0
        with self._poll_lock:
            have = self.node.log_len
            stop = have if max_records is None \
                else min(have, self.applied + max_records)
            while self.applied < stop:
                idx = self.applied
                self._apply_record(idx)
                self.applied = idx + 1
                applied += 1
        if applied or self.stats["polls"] % 16 == 0:
            self._g_applied.set(self.applied)
            self._g_lag.set(self.lag)
        self.stats["polls"] += 1
        return applied

    def _apply_record(self, idx: int) -> None:
        data = bytes(self.node.read(idx))
        try:
            hlen = struct.unpack_from("<I", data)[0]
            header = json.loads(data[4:4 + hlen])
        except Exception:
            self.stats["bad_records"] += 1
            return  # never die on one bad blob; the index stays 1:1
        hp = header.get("hp")
        if hp is not None:
            self._apply_history_control(hp)
        mg = header.get("mg")
        if mg is not None:
            self._apply_mega_control(mg)
        ts = header.get("ts", 0)
        items = []
        viewers = self.viewers
        for entry in header.get("docs", ()):
            doc, client, cseq0, ref, count, ns, fs, ls, m, w_off = entry
            if ns <= 0:
                continue  # fully rejected batch: storm never indexes it
            self._doc_records.setdefault(doc, []).append({
                "client": client, "first_cseq": cseq0, "ref_seq": ref,
                "count": count, "n_seq": ns, "first_seq": fs,
                "last_seq": ls, "msn": m, "timestamp": ts,
                "tick": idx, "w_off": w_off})
            if ls > self._doc_seq.get(doc, 0):
                self._doc_seq[doc] = ls
            if viewers is not None and viewers._rooms.get(doc) \
                    and doc not in self._mega:
                words = data[4 + hlen + w_off:4 + hlen + w_off
                             + 4 * count]
                items.append((doc, ns, fs, ls, m, count, words))
        # Chaos kill class "mid-apply": records indexed/durable-applied
        # but this tick's viewer broadcast NOT yet published — a
        # restarted replica re-derives the identical index and the
        # re-homed viewers catch up through get_deltas, byte-identical.
        faults.crashpoint("replica.mid_apply")
        if items:
            viewers.publish_ticks(items)
            self.stats["broadcast_ticks"] += 1
        arrival = self._arrivals.pop(idx, None)
        if arrival is not None:
            self._h_staleness.observe(time.monotonic() - arrival)
        self.stats["records_applied"] += 1

    def _apply_history_control(self, event: dict) -> None:
        op = event.get("op")
        if op == "fork" and event["branch"] not in self.branches:
            self.branches[event["branch"]] = {
                "parent": event["parent"], "seq": int(event["seq"]),
                "name": event.get("name", event["branch"])}
        # pin/unpin/"trimmed" affect compaction policy, not reads —
        # the summary record's tail_floor is the read-side authority.

    def _apply_mega_control(self, event: dict) -> None:
        op = event.get("op")
        if op == "promote":
            doc = event["doc"]
            self._mega.add(doc)
            # Lane ids (megadoc.lane_id format, count + epoch ride the
            # control) — addressed directly they redirect too.
            lanes = int(event.get("lanes", 1))
            epoch = int(event.get("epoch", 0))
            pre = f"{doc}::~mg{epoch}." if epoch else f"{doc}::~mg"
            self._mega.update(f"{pre}{i}" for i in range(lanes))
        # A demoted doc STAYS redirected: its lane-era records still
        # translate only through the leader's combine logs.

    @property
    def lag(self) -> int:
        """Shipped-but-unapplied records (the replica's staleness bound
        in WAL ticks against what the leader has shipped here)."""
        return max(0, self.node.log_len - self.applied)

    def doc_seq(self, doc: str) -> int:
        """This replica's applied sequenced frontier for ``doc`` — what
        per-room staleness is measured against the leader's watermark."""
        return self._doc_seq.get(doc, 0)

    def room_staleness(self, doc: str,
                       leader_seq: int | None = None) -> int:
        """PER-ROOM staleness for ``doc`` in sequence numbers: a known
        leader sequenced watermark (the balancer scrapes it off the
        leader's doc ticks; a read carries it as the requested seq)
        minus this replica's addressable frontier, floored at 0.
        Without one, the shipped-but-unapplied record lag is the only
        local bound — shipping is FIFO, so zero lag means every room
        is exactly as fresh as the stream itself."""
        if leader_seq is None:
            return self.lag
        return max(0, int(leader_seq) - self.head_seq(doc))

    def can_serve(self, doc: str) -> bool:
        return doc not in self._mega

    # -- record access (the storm cold-path twins) -----------------------------

    def read_tick_words(self, tick: int) -> bytes:
        """Raw op-word bytes of one tailed WAL record (the replica's
        ``storm.read_tick_words``): header stripped, ``w_off`` byte
        offsets index straight in."""
        data = bytes(self.node.read(tick))
        hlen = struct.unpack_from("<I", data)[0]
        return data[4 + hlen:]

    def _records_for(self, doc: str, from_seq: int,
                     to_seq: int | None = None) -> list[dict]:
        hi = float("inf") if to_seq is None else to_seq
        floor = self._tail_floor(doc)
        lo = max(int(from_seq), floor)
        return [r for r in self._doc_records.get(doc, ())
                if not (r["last_seq"] <= lo or r["first_seq"] > hi)]

    def _tail_floor(self, doc: str) -> int:
        rec = load_summary_record(self.snapshots, doc)
        return int(rec.get("tail_floor", 0)) if rec is not None else 0

    # -- the read surface ------------------------------------------------------

    def head_seq(self, doc: str) -> int:
        """Newest seq addressable HERE: applied record frontier, the
        shared-store summary head, or a branch's fork seq."""
        last = self._doc_seq.get(doc, 0)
        rec = load_summary_record(self.snapshots, doc)
        if rec is not None:
            last = max(last, int(rec["seq"]))
        meta = self.branches.get(doc)
        if meta is not None:
            last = max(last, int(meta["seq"]))
        return last

    def read_at(self, doc: str, seq: int) -> dict:
        """Materialize ``doc``'s converged state at ``seq`` — the
        history plane's exact read path over the shared store + tailed
        records. A seq above this replica's watermark waits up to
        ``read_wait_s`` for the stream, then sheds a ``moved`` redirect
        to the leader (who alone may rule it beyond-head)."""
        self.poll()
        faults.crashpoint("replica.mid_read")
        seq = int(seq)
        self._require_servable(doc)
        deadline = time.monotonic() + self.read_wait_s
        shipped = self.node.log_len
        polls = 0
        while True:
            head = self.head_seq(doc)
            if seq <= head:
                state = self._state_at(doc, seq)
                self.stats["reads"] += 1
                return {"doc": doc, "seq": seq, "head_seq": head,
                        "entries": state.entries()}
            if time.monotonic() >= deadline:
                self._shed_stale(
                    f"seq {seq} is above this replica's watermark "
                    f"({head}) for {doc!r}")
            if polls and self.lag == 0 \
                    and self.node.log_len == shipped:
                # Early shed: everything shipped is applied and nothing
                # new arrived across a full grace poll — the missing seq
                # cannot materialize from records already here, so
                # burning the rest of ``read_wait_s`` only delays the
                # client's redial to the leader (who alone may rule the
                # seq beyond-head). The wait-then-shed decision is thus
                # per-ROOM: a busy stream keeps the wait alive, an idle
                # one sheds at once.
                self.stats["room_stale_sheds"] += 1
                self._c_room_stale.inc()
                self._shed_stale(
                    f"seq {seq} is above this replica's watermark "
                    f"({head}) for {doc!r} and the stream is idle")
            shipped = self.node.log_len
            time.sleep(0.002)
            self.poll()
            polls += 1

    def _state_at(self, doc: str, seq: int):
        meta = self.branches.get(doc)
        if meta is not None and seq < meta["seq"]:
            # History below the fork lives with the parent.
            return self._state_at(meta["parent"], seq)
        if seq < 0:
            raise HistoryError(f"negative seq {seq}")
        rec = load_summary_record(self.snapshots, doc)
        if rec is None and meta is not None:
            # Fork control tailed before the leader's seed summary
            # reached the shared store: momentarily stale, not absent.
            self._shed_stale(
                f"branch {doc!r} seed summary not yet visible")
        base = summary_base_for(self.snapshots, doc, seq, rec)
        if base.seq == seq:
            return base
        floor = int(rec.get("tail_floor", 0)) if rec is not None else 0
        if base.seq < floor and seq > base.seq:
            raise HistoryError(
                f"history of {doc!r} below seq {floor} is compacted "
                f"away (tail retention); only the summary chain's "
                f"exact states remain addressable there")
        state = base.copy()
        fold_storm_records(state,
                           self._records_for(doc, state.seq, seq),
                           seq, self.read_tick_words)
        state.seq = seq
        return state

    def get_deltas(self, doc: str, from_seq: int,
                   to_seq: int | None = None) -> list:
        """Sequenced messages in ``(from_seq, to_seq]`` from the tailed
        record tier (the replicated total order — the leader-local
        per-op JSON tier stays with the leader). A bounded ``to_seq``
        above the watermark waits briefly, then sheds to the leader;
        unbounded catch-up serves the applied frontier (the viewer
        resync contract: the live stream continues from wherever the
        reply ends)."""
        from .storm import materialize_storm_records
        self.poll()
        faults.crashpoint("replica.mid_read")
        self._require_servable(doc)
        if to_seq is not None:
            deadline = time.monotonic() + self.read_wait_s
            shipped = self.node.log_len
            polls = 0
            while self.head_seq(doc) < to_seq:
                if time.monotonic() >= deadline:
                    self._shed_stale(
                        f"get_deltas to_seq {to_seq} is above this "
                        f"replica's watermark "
                        f"({self.head_seq(doc)}) for {doc!r}")
                if polls and self.lag == 0 \
                        and self.node.log_len == shipped:
                    # Same early shed as read_at: an idle, fully
                    # applied stream cannot produce to_seq.
                    self.stats["room_stale_sheds"] += 1
                    self._c_room_stale.inc()
                    self._shed_stale(
                        f"get_deltas to_seq {to_seq} is above this "
                        f"replica's watermark "
                        f"({self.head_seq(doc)}) for {doc!r} and the "
                        f"stream is idle")
                shipped = self.node.log_len
                time.sleep(0.002)
                self.poll()
                polls += 1
        records = self._records_for(doc, from_seq, to_seq)
        messages = materialize_storm_records(
            records, self.datastore, self.channel,
            blob_reader=self.read_tick_words)
        messages.sort(key=lambda m: m.sequence_number)
        self.stats["deltas"] += 1
        return [m for m in messages
                if m.sequence_number > from_seq
                and (to_seq is None or m.sequence_number <= to_seq)]

    # -- write verbs: always the leader's --------------------------------------

    def connect(self, *_args, **kwargs):
        mode = kwargs.get("mode", "write")
        raise ReplicaRedirect(
            f"replica {self.label!r} is read-only: {mode!r} connects "
            f"are served by the leader", self.leader_label,
            self.retry_after_s)

    def fork_doc(self, doc: str, seq: int, name: str | None = None):
        raise ReplicaRedirect(
            f"fork of {doc!r} is a write — served by the leader",
            self.leader_label, self.retry_after_s)

    def merge_back(self, branch: str):
        raise ReplicaRedirect(
            f"merge_back of {branch!r} is a write — served by the "
            f"leader", self.leader_label, self.retry_after_s)

    # -- plumbing --------------------------------------------------------------

    def _require_servable(self, doc: str) -> None:
        if not self.can_serve(doc):
            raise ReplicaRedirect(
                f"{doc!r} is mega-promoted: lane-era records translate "
                f"only through the leader's combine logs",
                self.leader_label, self.retry_after_s)

    def _shed_stale(self, message: str) -> None:
        self.stats["stale_redirects"] += 1
        self._c_stale.inc()
        raise ReplicaRedirect(message, self.leader_label,
                              self.retry_after_s)

    def staleness(self) -> dict:
        """One scrape of this replica's staleness surface: WAL-record
        lag plus every tracked doc's applied seq frontier."""
        return {"lag_records": self.lag,
                "applied": self.applied,
                "doc_seq": dict(self._doc_seq)}

    def close(self) -> None:
        pass  # the follower node owns the durable state


__all__ = ["ReadReplica", "ReplicaDirectory", "ReplicaRouter",
           "ReplicaRedirect", "REPLICA_DIRECTORY_KEY", "READ_KINDS"]
