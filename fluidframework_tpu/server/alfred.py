"""Alfred — the network front door: a socket server over the ordering
service.

Reference parity: server/routerlicious/packages/lambdas/src/alfred/
index.ts:140-477 — the socket handler exposing ``connect_document``
(→ :343), ``submitOp`` (→ :367-385), ``submitSignal`` (→ :427) plus the
REST-ish storage/delta reads (routerlicious-base alfred app). Transport is
length-prefixed JSON over TCP (asyncio) instead of socket.io — the DCN hop
of SURVEY.md §5.8; the ordering service behind it is unchanged
(RouterliciousService or LocalCollabServer, duck-typed).

Wire protocol (all frames = 4-byte BE length + JSON, protocol.codec):
  client→server requests carry ``rid``; the response echoes it:
    {rid, op: "connect", doc_id, mode, scopes?}     → {rid, client_id}
    {rid, op: "submit", messages: [DocumentMessage]} → {rid, ok}
    {rid, op: "signal", content}                     → {rid, ok}
    {rid, op: "get_deltas", from_seq, to_seq}        → {rid, messages}
    {rid, op: "upload_snapshot", snapshot}           → {rid, handle}
    {rid, op: "get_latest_snapshot"}                 → {rid, snapshot}
    {rid, op: "disconnect"}                          → {rid, ok}
  server→client events (no rid):
    {event: "ops", messages: [SequencedDocumentMessage]}
    {event: "nack", nack: NackMessage}
    {event: "signal", signal}

Run standalone (the tinylicious analog):
    python -m fluidframework_tpu.server.alfred --port 7070
"""

from __future__ import annotations

import argparse
import asyncio
import sys
import time
from typing import Any

from ..protocol.codec import (
    MAX_FRAME,
    decode_body,
    encode_ops_event,
    encode_push,
    frame_body,
    is_storm_body,
)
from ..utils import MetricsRegistry, NullLogger, TelemetryLogger


async def read_frame_raw(reader: asyncio.StreamReader) -> bytes:
    header = await reader.readexactly(4)
    length = int.from_bytes(header, "big")
    if length > MAX_FRAME:
        raise ConnectionError(f"oversized frame: {length}")
    return await reader.readexactly(length)


async def read_frame(reader: asyncio.StreamReader) -> Any:
    return decode_body(await read_frame_raw(reader))


def read_frame_raw_sync(sock) -> bytes:
    """Blocking-socket twin of :func:`read_frame_raw` — one definition
    of the length-prefixed wire format for synchronous callers (the
    replication transport's client half, ``server/transport.py``).
    Raises ``ConnectionError`` on a closed or over-limit peer; socket
    timeouts propagate for the caller's deadline/retry policy."""

    def recv_exact(n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed mid-frame")
            buf += chunk
        return bytes(buf)

    length = int.from_bytes(recv_exact(4), "big")
    if length > MAX_FRAME:
        raise ConnectionError(f"oversized frame: {length}")
    return recv_exact(length)


class RequestSession:
    """One connection = one (doc, client) session, mirroring the
    reference's per-socket connection state (alfred index.ts:278).
    Transport-agnostic: subclasses own ``push`` (asyncio writer here,
    the native bridge in server.bridge_host)."""

    def __init__(self, server) -> None:
        self.server = server
        self.connection = None  # service-side live connection
        self.doc_id: str | None = None
        self.tenant_id = "default"  # set from token claims on connect
        # mode="viewer" sessions register on the viewer plane instead of
        # the ordering service (server/broadcaster.py): no CLIENT_JOIN,
        # no admission token debit, no ack bookkeeping.
        self.viewer_id: str | None = None

    def push(self, payload: dict) -> None:
        raise NotImplementedError

    def push_ops(self, messages) -> None:
        """Broadcast one sequenced-op batch. A BroadcastBatch shared by
        many sessions is serialized ONCE (codec.encode_ops_event caches
        the body on the batch); every subscriber then pays only a
        transport write."""
        self.push(encode_ops_event(messages))

    def drop(self) -> None:
        """Close this session's transport (service-initiated disconnect,
        e.g. slow-consumer eviction). Subclasses owning a socket override."""

    def close_viewer(self) -> None:
        """Tear down this session's viewer-plane registration (transport
        death / explicit disconnect)."""
        if self.viewer_id is not None:
            viewers = getattr(self.server.service, "viewers", None)
            if viewers is not None:
                viewers.leave(self.viewer_id)
            self.viewer_id = None

    def _pending_probe(self):
        """Transport-outbox depth probe for the viewer plane's lag
        detection; None when the transport cannot report one (the
        fan-out queue bound still applies)."""
        return None

    def _on_viewer_connected(self) -> None:
        """Transport hook after a viewer connect: subclasses shrink the
        connection's outbox bound to the viewer class (the native bridge
        sets its per-connection -2 threshold here)."""

    def handle_binary(self, body: bytes,
                      ingress_ns: int | None = None) -> dict | None:
        """A storm frame (codec.is_storm_body): columnar op batch into the
        service's fast path. The ack is pushed after the tick that
        sequences it; None = no immediate response. ``ingress_ns`` is the
        transport's receive timestamp (monotonic ns) so the stage ledger
        attributes the codec decode to ingress_decode (None is fine —
        submit_frame defaults to its own entry time)."""
        from ..protocol.codec import decode_storm_body

        storm = getattr(self.server.service, "storm", None)
        if storm is None:
            return {"rid": None, "error": "storm path not enabled"}
        try:
            header, payload = decode_storm_body(body)
        except Exception as err:
            return {"rid": None, "error": f"bad storm frame: {err!r}"}
        try:
            # Admission identities come from the SESSION (validated
            # tenant, service-assigned client id), never the frame's
            # client-controlled header.
            storm.submit_frame(
                self.push, header, payload, tenant_id=self.tenant_id,
                client_id=getattr(self.connection, "client_id", None),
                ingress_ns=ingress_ns)
        except Exception as err:
            # The error must answer the offending frame and keep the
            # socket alive — exactly like the JSON request path.
            return {"rid": header.get("rid"), "error": repr(err)}
        return None

    def handle_request(self, req: dict) -> dict:
        """Dispatch one request synchronously against the service."""
        service = self.server.service
        op = req["op"]
        rid = req.get("rid")
        if op == "connect":
            # Symmetric guard: one session, one registration — a viewer
            # session re-connecting in write mode would otherwise leak
            # its plane registration and overwrite doc_id under it.
            assert self.connection is None and self.viewer_id is None, \
                "already connected"
            self.doc_id = req["doc_id"]
            if req.get("mode") == "viewer":
                return self._connect_viewer(req, rid)
            kwargs: dict = {"mode": req.get("mode", "write")}
            if self.server.tenants is not None:
                # Auth-enabled front door (alfred index.ts:343): the token
                # is the ONLY source of scopes; client-requested scopes are
                # ignored.
                from .riddler import AuthError
                token = req.get("token")
                if not token:
                    raise AuthError("connect requires a token")
                claims = self.server.tenants.validate_token(
                    token, document_id=self.doc_id)
                kwargs["scopes"] = tuple(claims["scopes"])
                self.tenant_id = claims.get("tenantId", "default")
            elif req.get("scopes") is not None:
                kwargs["scopes"] = tuple(req["scopes"])
            redirect = self._placement_redirect(rid)
            if redirect is None:
                # A write connect dialed at a read replica sheds to the
                # leader (the replica's self-router names it).
                redirect = self._read_redirect(rid, "write")
            if redirect is not None:
                return redirect
            admission = self.server.admission
            if admission is not None:
                # The client-tier key is the driver's stable per-client
                # id (claimable reservations must survive a redial's new
                # socket AND must not be shared by a doc's other clients
                # — a doc-keyed reservation would let neighbours steal a
                # refused client's slot). Absent (old clients), fall
                # back to tenant-only admission.
                retry = admission.admit_connect(self.tenant_id,
                                                req.get("client_key"))
                if retry is not None:
                    return {"rid": rid, "error": "throttled",
                            "retry_after_s": retry}
            elif self.server.throttler is not None:
                retry = self.server.throttler.try_consume(
                    f"connect/{self.doc_id}")
                if retry is not None:
                    return {"rid": rid, "error": "throttled",
                            "retry_after_s": retry}
            residency = getattr(getattr(service, "storm", None),
                                "residency", None)
            if residency is not None:
                # Cold-doc connect hydrates through the admission-gated
                # path: a hydration stampede busy-nacks with the
                # bucket's laddered retry hint instead of serializing
                # every cold connect behind snapshot restores.
                retry = residency.ensure_resident(self.doc_id)
                if retry is not None:
                    return {"rid": rid, "error": "hydrating",
                            "retryable": True, "retry_after_s": retry}
            self.connection = service.connect(
                self.doc_id,
                self.push_ops,
                on_nack=lambda n: self.push({"event": "nack", "nack": n}),
                on_signal=lambda s: self.push({"event": "signal",
                                              "signal": s}),
                **kwargs)
            self.server.metrics.counter("alfred.connects").inc()
            self.connection.on_closed = self.drop
            return {"rid": rid, "client_id": self.connection.client_id}
        if op == "submit":
            if self.server.admission is not None:
                retry = self.server.admission.admit_write(
                    self.tenant_id, self.connection.client_id,
                    weight=len(req["messages"]))
                if retry is not None:
                    return {"rid": rid, "error": "throttled",
                            "retry_after_s": retry}
            elif self.server.throttler is not None:
                retry = self.server.throttler.try_consume(
                    f"submit/{self.connection.client_id}",
                    weight=len(req["messages"]))
                if retry is not None:
                    return {"rid": rid, "error": "throttled",
                            "retry_after_s": retry}
            self.connection.submit(req["messages"])
            return {"rid": rid, "ok": True}
        if op == "signal":
            if self.server.admission is not None:
                # Deterministic shed order: signals are the FIRST class
                # dropped under queue pressure (they are transient by
                # contract — a shed signal loses nothing durable).
                retry = self.server.admission.admit_signal(self.tenant_id)
                if retry is not None:
                    return {"rid": rid, "error": "throttled",
                            "retry_after_s": retry}
            self.connection.signal(req["content"])
            return {"rid": rid, "ok": True}
        if op == "get_deltas":
            if self.server.admission is not None:
                # Reads shed second (before writes): a catch-up read can
                # retry; an admitted write the tick can't absorb cannot.
                retry = self.server.admission.admit_read(self.tenant_id)
                if retry is not None:
                    return {"rid": rid, "error": "throttled",
                            "retry_after_s": retry}
            doc = req.get("doc_id", self.doc_id)
            redirect = self._read_redirect(rid, "get_deltas", doc=doc,
                                           key=req.get("client_key"))
            if redirect is not None:
                return redirect
            return self._serve_read(rid, lambda: {
                "rid": rid, "messages": service.get_deltas(
                    doc, req["from_seq"], req.get("to_seq"))})
        if op == "read_at":
            # Historical read (the history plane): sheds like any other
            # catch-up read — it is a read, and it must never outrank
            # admitted writes under pressure.
            if self.server.admission is not None:
                retry = self.server.admission.admit_read(self.tenant_id)
                if retry is not None:
                    return {"rid": rid, "error": "throttled",
                            "retry_after_s": retry}
            doc = req.get("doc_id", self.doc_id)
            redirect = self._read_redirect(rid, "read_at", doc=doc,
                                           key=req.get("client_key"))
            if redirect is not None:
                return redirect
            return self._serve_read(rid, lambda: {
                "rid": rid, **service.read_at(doc, req["seq"])})
        if op in ("fork", "merge_back"):
            # Branch verbs are WRITE-class: fork settles the pipeline
            # and uploads seeds, merge_back re-submits a branch's whole
            # delta history through the sequencer — a throttled tenant
            # must not route the write load admission is shedding
            # through this door (the storm-side _admit still gates the
            # individual merge frames).
            if self.server.admission is not None:
                retry = self.server.admission.admit_write(
                    self.tenant_id,
                    getattr(self.connection, "client_id", None))
                if retry is not None:
                    return {"rid": rid, "error": "throttled",
                            "retry_after_s": retry}
            if op == "fork":
                doc = req.get("doc_id", self.doc_id)
                return self._serve_read(rid, lambda: {
                    "rid": rid,
                    "branch": service.fork_doc(doc, req["seq"],
                                               req.get("name"))})
            return self._serve_read(rid, lambda: {
                "rid": rid,
                **service.merge_back(req.get("branch",
                                             self.doc_id))})
        if op == "upload_snapshot":
            doc = req.get("doc_id", self.doc_id)
            return {"rid": rid,
                    "handle": service.upload_snapshot(doc, req["snapshot"],
                                                      req.get("parent"))}
        if op == "get_latest_snapshot":
            doc = req.get("doc_id", self.doc_id)
            return {"rid": rid, "snapshot": service.get_latest_snapshot(doc)}
        if op == "create_blob":
            import base64
            doc = req.get("doc_id", self.doc_id)
            blob_id = service.create_blob(
                doc, req["blob_id"], base64.b64decode(req["data"]))
            return {"rid": rid, "blob_id": blob_id}
        if op == "read_blob":
            import base64
            doc = req.get("doc_id", self.doc_id)
            data = service.read_blob(doc, req["blob_id"])
            return {"rid": rid, "data": base64.b64encode(data).decode()}
        if op == "get_help":
            # Headless agent runners poll assignments; doc_id None spans
            # all documents (the agent-pool discovery shape). With auth
            # enabled this is privileged: assignment records expose doc and
            # client ids across tenants, so an agent-scoped token gates it.
            self._require_agent_scope(req)
            return {"rid": rid,
                    "tasks": service.help_tasks(req.get("doc_id"))}
        if op == "complete_help":
            self._require_agent_scope(req)
            service.complete_help(req["key"])
            return {"rid": rid, "ok": True}
        if op == "get_metrics":
            # service-monitor surface: one scrape = front-door counters +
            # the assembly's shared registry (deli/scribe/merge-host/...).
            snap = dict(self.server.metrics.snapshot())
            service_metrics = getattr(service, "metrics", None)
            if service_metrics is not None and service_metrics \
                    is not self.server.metrics:
                snap.update(service_metrics.snapshot())
            return {"rid": rid, "metrics": snap}
        if op == "disconnect":
            if self.connection is not None:
                self.connection.close()
                self.connection = None
            self.close_viewer()
            return {"rid": rid, "ok": True}
        if op == "viewer_resume":
            # Re-enter the live stream after a lag-drop (the client has
            # caught up via snapshot + get_deltas). A resync storm is a
            # join storm: the same reservation gate applies.
            viewers = getattr(service, "viewers", None)
            if viewers is None or self.viewer_id is None:
                return {"rid": rid, "error": "no viewer session"}
            # Directory-aware resume: a room spread across replicas
            # hands each resuming viewer ITS hash-assigned host — the
            # client redials the label and re-joins there, which is how
            # one hot doc's audience lands on N replicas.
            redirect = self._read_redirect(rid, "viewer",
                                           key=req.get("client_key"))
            if redirect is not None:
                return redirect
            retry = viewers.admit_join(self.doc_id, req.get("client_key"),
                                       tenant_id=self.tenant_id)
            if retry is not None:
                return {"rid": rid, "error": "throttled",
                        "retry_after_s": retry}
            hello = viewers.resume(self.viewer_id)
            return {"rid": rid, **hello}
        if op == "storm_flush":
            storm = getattr(service, "storm", None)
            if storm is None:
                return {"rid": rid, "error": "storm path not enabled"}
            storm.flush()
            return {"rid": rid, "ok": True}
        return {"rid": rid, "error": f"unknown op {op!r}"}

    def _placement_redirect(self, rid) -> dict | None:
        """Cluster-aware connect (ROADMAP item 2 residue): consult the
        placement directory so a client dialing the wrong host learns
        the owner AT CONNECT TIME (``moved_to``) instead of connecting
        locally and only discovering the move from per-frame nacks; a
        doc mid-migration answers "migrating" with the blackout hint.
        Runs AFTER token validation (and claims the tenant) — placement
        is cluster topology, and an unauthenticated prober must not
        enumerate doc→host mappings through the connect path."""
        placement = getattr(getattr(self.server.service, "storm", None),
                            "placement", None)
        if placement is None:
            return None
        code, owner = placement.route(self.doc_id)
        if code == "moved":
            return {"rid": rid, "error": "moved", "retryable": True,
                    "moved_to": owner,
                    "retry_after_s": placement.retry_after_s}
        if code == "migrating":
            return {"rid": rid, "error": "migrating", "retryable": True,
                    "retry_after_s": placement.retry_after_s}
        return None

    def _read_redirect(self, rid, kind: str, doc: str | None = None,
                       key: str | None = None) -> dict | None:
        """Read-tier routing (server/read_replica.py): on a leader with
        a replica directory, directory-assigned read classes answer
        ``moved`` with the serving replica's label (clients hash-spread
        across a doc's label list by ``client_key``); on a replica, the
        self-router sheds writes — and reads it cannot serve — back to
        the leader. No router attached = no redirect (every assembly
        without a replica tier)."""
        router = getattr(self.server.service, "read_router", None)
        if router is None:
            return None
        target = router.route_read(doc if doc is not None
                                   else self.doc_id, kind, key=key)
        if target is None:
            return None
        return {"rid": rid, "error": "moved", "retryable": True,
                "moved_to": target,
                "retry_after_s": router.retry_after_s}

    def _serve_read(self, rid, fn) -> dict:
        """Run one service read/branch verb, mapping a replica-raised
        redirect (anything carrying ``moved_to`` — duck-typed so no
        replica import rides every assembly) to the retryable ``moved``
        response the drivers' redial machinery already understands."""
        try:
            return fn()
        except Exception as err:
            moved = getattr(err, "moved_to", None)
            if moved is None:
                raise
            return {"rid": rid, "error": "moved", "retryable": True,
                    "moved_to": moved,
                    "retry_after_s": getattr(err, "retry_after_s",
                                             0.05)}

    def _connect_viewer(self, req: dict, rid) -> dict:
        """``mode="viewer"`` connect (the broadcast viewer plane,
        server/broadcaster.py): token-authenticated like any connect but
        NEVER debits write/connect admission, never sequences a
        CLIENT_JOIN, never allocates merge/ack state — the session joins
        the doc's fan-out room and drains broadcast frames. Join storms
        gate through the plane's own TokenBucket with claimable
        reservations."""
        # Mirror the write-path connect guard: a second connect on one
        # socket must not leak the first plane registration (an orphaned
        # viewer would double-push frames and outlive the session).
        assert self.viewer_id is None, "already connected"
        service = self.server.service
        viewers = getattr(service, "viewers", "unsupported")
        if viewers == "unsupported":
            return {"rid": rid, "error": "viewer plane not enabled"}
        if viewers is None:
            # Assemblies that carry the seam but were built without a
            # plane (bare RouterliciousService) get the default lazily —
            # same contract as an in-process mode="viewer" connect.
            from .broadcaster import ViewerPlane
            viewers = ViewerPlane(service,
                                  metrics=getattr(service, "metrics",
                                                  None))
        if self.server.tenants is not None:
            from .riddler import AuthError
            token = req.get("token")
            if not token:
                raise AuthError("connect requires a token")
            claims = self.server.tenants.validate_token(
                token, document_id=self.doc_id)
            self.tenant_id = claims.get("tenantId", "default")
        redirect = self._placement_redirect(rid)
        if redirect is None:
            # Replica-directory routing: a directory-assigned room's
            # viewers land on their hash-assigned replica at CONNECT
            # time (writer traffic never routes here).
            redirect = self._read_redirect(rid, "viewer",
                                           key=req.get("client_key"))
        if redirect is not None:
            return redirect
        retry = viewers.admit_join(self.doc_id, req.get("client_key"),
                                   tenant_id=self.tenant_id)
        if retry is not None:
            return {"rid": rid, "error": "throttled",
                    "retry_after_s": retry}
        hello = viewers.join(self.doc_id, self.push,
                             pending_probe=self._pending_probe())
        self.viewer_id = hello["viewer_id"]
        self._on_viewer_connected()
        self.server.metrics.counter("alfred.viewer_connects").inc()
        return {"rid": rid, "client_id": hello["viewer_id"],
                "viewer": True, "seq": hello["seq"],
                "viewers": hello["viewers"]}

    def _require_agent_scope(self, req: dict) -> None:
        if self.server.tenants is None:
            return
        from ..protocol.messages import ScopeType
        from .riddler import AuthError
        token = req.get("token")
        if not token:
            raise AuthError("agent control requires a token")
        claims = self.server.tenants.validate_token(token)
        if ScopeType.AGENT not in claims.get("scopes", ()):
            raise AuthError("agent scope required")


class _ClientSession(RequestSession):
    """RequestSession over an asyncio stream writer."""

    def __init__(self, server: "AlfredServer",
                 writer: asyncio.StreamWriter) -> None:
        super().__init__(server)
        self.writer = writer
        self.outbox: asyncio.Queue = asyncio.Queue()

    def push(self, payload: dict) -> None:
        self.outbox.put_nowait(payload)

    def _pending_probe(self):
        # Viewer lag detection: the session outbox depth IS the
        # transport backlog for the asyncio door.
        return self.outbox.qsize

    async def writer_loop(self) -> None:
        while True:
            payload = await self.outbox.get()
            if payload is None:
                break
            # encode_push: pre-encoded RawBody / columnar StormAck go out
            # without a JSON pass; plain dicts encode as before.
            self.writer.write(frame_body(encode_push(payload)))
            await self.writer.drain()

    def drop(self) -> None:
        # Runs on the event-loop thread (service pumps happen inside
        # handle_request): closing the transport unblocks the session's
        # read_frame, whose teardown path finishes the cleanup.
        self.push(None)
        try:
            self.writer.close()
        except RuntimeError:
            pass  # loop already torn down


class AlfredServer:
    def __init__(self, service, host: str = "127.0.0.1", port: int = 0,
                 logger: TelemetryLogger | None = None,
                 metrics: MetricsRegistry | None = None,
                 tenants=None, throttler=None, admission=None) -> None:
        self.service = service
        self.host = host
        self.port = port
        self.logger = logger if logger is not None else NullLogger()
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        # Optional riddler integration: a TenantManager enforces token auth
        # on connect; an AdmissionController (token buckets + pressure
        # shed) rate-limits connects/submits/reads/signals. ``throttler``
        # (the legacy fixed-window surface) is honored when no admission
        # controller is given.
        self.tenants = tenants
        self.throttler = throttler
        self.admission = admission
        self._server: asyncio.AbstractServer | None = None

    async def start(self) -> int:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self.logger.send_event("AlfredListening", port=self.port)
        return self.port

    async def serve_forever(self) -> None:
        assert self._server is not None
        async with self._server:
            await self._server.serve_forever()

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        session = _ClientSession(self, writer)
        writer_task = asyncio.create_task(session.writer_loop())
        try:
            while True:
                try:
                    body = await read_frame_raw(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                if is_storm_body(body):
                    resp = session.handle_binary(
                        body, ingress_ns=time.monotonic_ns())
                    if resp is not None:
                        session.push(resp)
                    continue
                req = decode_body(body)
                try:
                    resp = session.handle_request(req)
                except Exception as err:  # report, keep the socket alive
                    self.logger.send_error("AlfredRequestFailed", err,
                                           op=req.get("op"))
                    resp = {"rid": req.get("rid"), "error": repr(err)}
                session.push(resp)
        finally:
            if session.connection is not None:
                session.connection.close()
            session.close_viewer()
            try:
                session.push(None)
                await writer_task
            except RuntimeError:
                pass  # event loop already torn down mid-disconnect
            finally:
                try:
                    writer.close()
                except RuntimeError:
                    pass  # transport.close on an already-closed loop


def build_default_service(data_dir: str | None = None, merge_host=True,
                          native_bus: bool = False,
                          batched_cadence: bool = False,
                          native_fanout: bool = False):
    """Standalone assembly: routerlicious lambdas (+ device merge host,
    + durable file-backed storage when ``data_dir`` is given, + the C++
    shuttle bus with ``native_bus`` in in-memory mode). With
    ``batched_cadence`` the service never pumps inline — the operator
    ticks it (alfred --cadence-ms runs the tick loop) and deli sequences
    through the device-batched host, the BASELINE throughput shape."""
    from ..utils import MetricsRegistry
    from .routerlicious import RouterliciousService
    metrics = MetricsRegistry()  # one registry spans the whole assembly
    kwargs: dict = {"metrics": metrics}
    if merge_host:
        from .merge_host import KernelMergeHost
        kwargs["merge_host"] = KernelMergeHost()
    if batched_cadence:
        from .kernel_host import KernelSequencerHost
        kwargs["auto_pump"] = False
        kwargs["batched_deli_host"] = KernelSequencerHost()
    if native_bus and data_dir is None:
        from .native_bus import make_message_bus
        kwargs["bus"] = make_message_bus()
    if native_fanout:
        from ..native.fanout import make_fanout
        kwargs["fanout"] = make_fanout()
    if data_dir is not None:
        from .durable_store import (
            DurableMessageBus, FileStateStore, GitSnapshotStore)
        from .historian import Historian
        kwargs["bus"] = DurableMessageBus(f"{data_dir}/bus")
        kwargs["store"] = FileStateStore(f"{data_dir}/state")
        kwargs["snapshots"] = Historian(GitSnapshotStore(f"{data_dir}/git"),
                                        metrics=metrics)
    service = RouterliciousService(**kwargs)
    # The broadcast viewer plane (mode="viewer" connects) rides every
    # standalone assembly: construction is O(1) — its fan-out spine is
    # lazy, so a deployment that never sees a viewer pays nothing.
    from .broadcaster import ViewerPlane
    ViewerPlane(service, metrics=metrics)
    return service


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=7070)
    parser.add_argument("--no-merge-host", action="store_true",
                        help="skip the device kernel host (CPU-only box)")
    parser.add_argument("--data-dir", default=None,
                        help="directory for durable bus/state/snapshots; "
                             "omitted = in-memory (tinylicious mode)")
    parser.add_argument("--native-bus", action="store_true",
                        help="run the in-memory bus on the C++ shuttle")
    parser.add_argument("--native-fanout", action="store_true",
                        help="broadcast through the C++ fan-out service "
                             "(Redis pub/sub analog)")
    parser.add_argument("--cadence-ms", type=int, default=None,
                        help="batched-cadence mode: sequence through the "
                             "device host on this tick interval instead "
                             "of inline per submit")
    args = parser.parse_args(argv)
    if args.native_bus and args.data_dir is not None:
        parser.error("--native-bus is in-memory only; it cannot be "
                     "combined with --data-dir (the durable bus)")
    if args.cadence_ms is not None and args.cadence_ms <= 0:
        parser.error("--cadence-ms must be a positive interval")

    service = build_default_service(args.data_dir,
                                    merge_host=not args.no_merge_host,
                                    native_bus=args.native_bus,
                                    batched_cadence=args.cadence_ms
                                    is not None,
                                    native_fanout=args.native_fanout)

    async def run() -> None:
        server = AlfredServer(service, args.host, args.port)
        port = await server.start()
        if args.cadence_ms is not None:
            async def tick_loop() -> None:
                while True:
                    await asyncio.sleep(args.cadence_ms / 1000)
                    try:
                        service.pump()  # one batched device tick
                    except Exception as err:  # a dead loop halts ALL
                        print(f"TICK ERROR {err!r}",  # sequencing
                              file=sys.stderr, flush=True)
            # The loop keeps only a weak reference to tasks; anchor it on
            # the server so GC can never silently stop the tick loop.
            server._tick_task = asyncio.get_running_loop().create_task(
                tick_loop())
        print(f"READY {port}", flush=True)
        await server.serve_forever()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main(sys.argv[1:])
