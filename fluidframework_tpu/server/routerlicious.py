"""Routerlicious-equivalent service assembly over the partitioned bus.

Reference parity: server/routerlicious — alfred front door (connect /
submitOp → produce to ``rawdeltas``: alfred/index.ts:367), deli sequencer
lambda (rawdeltas → ticket → ``deltas``: deli/lambda.ts:82), scriptorium
(durable op log: scriptorium/lambda.ts:16), broadcaster (fan-out:
broadcaster/lambda.ts:42) and scribe (summary ack flow:
scribe/lambda.ts:40), each an independently checkpointed consumer of the
same ``deltas`` stream — restartable from its own offsets.

The assembly exposes the same duck-typed surface as ``LocalCollabServer``
(connect/submit/signal/get_deltas/upload_snapshot/...), so the whole
client stack runs over it unchanged via ``LocalDocumentService``. Pumping
is synchronous after every produce (deterministic for tests); a real
deployment pumps each lambda on its own cadence.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable

from ..ops import opcodes as oc
from ..protocol.messages import (
    ClientDetail,
    DocumentMessage,
    MessageType,
    NackMessage,
    ScopeType,
    SequencedDocumentMessage,
    Trace,
)
from ..utils import MetricsRegistry, NullLogger, TelemetryLogger
from .bus import BusMessage, MessageBus, StateStore
from .lambdas import PartitionManager
from .sequencer import DocumentSequencer, RawOperation, SequencerCheckpoint

from .orderer import RAWDELTAS  # single source of the topic name
DELTAS = "deltas"


class StoreSnapshotBackend:
    """Default snapshot backend over the StateStore (in-memory historian).
    The durable content-addressed alternative is
    server.durable_store.GitSnapshotStore — same four-method surface."""

    def __init__(self, store: StateStore) -> None:
        self._store = store

    def upload(self, doc_id: str, snapshot: dict) -> str:
        snapshots: dict = self._store.get(f"snapshots/{doc_id}", {})
        handle = f"{doc_id}/snapshots/{len(snapshots)}"
        snapshots[handle] = snapshot
        self._store.put(f"snapshots/{doc_id}", snapshots)
        return handle

    def get(self, doc_id: str, handle: str | None) -> dict | None:
        if handle is None:
            return None
        return self._store.get(f"snapshots/{doc_id}", {}).get(handle)

    def head(self, doc_id: str) -> str | None:
        return self._store.get(f"summary_head/{doc_id}")

    def set_head(self, doc_id: str, handle: str) -> None:
        self._store.put(f"summary_head/{doc_id}", handle)


# -- deli ---------------------------------------------------------------------


class DeliDocumentLambda:
    """Per-document sequencer lambda (deli/lambda.ts ticket loop)."""

    def __init__(self, doc_id: str, store: StateStore, bus: MessageBus,
                 sequencer_factory: Callable[[], DocumentSequencer],
                 metrics: MetricsRegistry | None = None) -> None:
        self.doc_id = doc_id
        self._store = store
        self._bus = bus
        self._sequencer_factory = sequencer_factory
        self._metrics = metrics if metrics is not None else MetricsRegistry()
        cp = store.get(f"deli/{doc_id}")
        if cp is not None:
            cp = dict(cp)
            self._summary_responded = cp.pop("summary_responded", 0)
            self._last_offset = cp["log_offset"]
            self.sequencer = self._make_sequencer(SequencerCheckpoint(**cp))
        else:
            self._summary_responded = 0
            self._last_offset = -1
            self.sequencer = self._make_sequencer(None)

    def _make_sequencer(self, cp: SequencerCheckpoint | None):
        """Build the document sequencer, from a checkpoint if one exists.
        Subclasses override to route state into a shared device host."""
        if cp is not None:
            return DocumentSequencer.restore(cp)
        return self._sequencer_factory()

    def handler(self, message: BusMessage) -> None:
        raw = self._admit(message)
        if raw is None:
            return
        trace_start = Trace("deli", "start")  # stamped at receipt, pre-ticket
        ticket = self.sequencer.ticket(raw)
        self._emit(raw, ticket, trace_start)

    def _admit(self, message: BusMessage) -> RawOperation | None:
        """Offset + summary-response dedup; None = silently dropped."""
        if message.offset <= self._last_offset:
            return None  # replayed below our checkpoint (lambda.ts:148-151)
        self._last_offset = message.offset
        raw: RawOperation = message.value
        if raw.client_id is None and raw.type in (MessageType.SUMMARY_ACK,
                                                  MessageType.SUMMARY_NACK):
            # Scribe crash-replay can re-produce its response to the same
            # SUMMARIZE op as a NEW raw message (fresh offset, so the offset
            # guard above can't catch it). Proposal seqs are unique and
            # monotonic — dedupe here, where the checkpoint is atomic with
            # the consumed offset, so the drop survives our own replay too.
            # Service-produced only (client_id None): a client-forged ack is
            # NACKed by the sequencer and must not poison the watermark.
            sseq = (raw.contents or {}).get(
                "summary_proposal", {}).get("summary_sequence_number", 0)
            if sseq <= self._summary_responded:
                return None
            self._summary_responded = sseq
        return raw

    def _emit(self, raw: RawOperation, ticket,
              trace_start: Trace) -> None:
        if ticket.kind == oc.OUT_NACK:
            self._metrics.counter("deli.nacks").inc()
            self._bus.produce(DELTAS, self.doc_id, {
                "kind": "nack",
                "target": raw.client_id,
                "operation": raw,
                "seq": ticket.seq,
                "code": ticket.nack_code,
            })
        elif ticket.kind == oc.OUT_SEQUENCED:
            self._metrics.counter("deli.sequenced_ops").inc()
            self._bus.produce(DELTAS, self.doc_id, {
                "kind": "op",
                "message": SequencedDocumentMessage(
                    client_id=raw.client_id,
                    sequence_number=ticket.seq,
                    minimum_sequence_number=ticket.msn,
                    client_sequence_number=raw.client_seq,
                    reference_sequence_number=raw.ref_seq,
                    type=raw.type,
                    contents=raw.contents,
                    timestamp=raw.timestamp,
                    data=raw.data,
                    traces=tuple(raw.traces) + (trace_start,
                                                Trace("deli", "end")),
                ),
            })

    def checkpoint(self, next_offset: int) -> None:
        cp = self.sequencer.checkpoint(self._last_offset)
        self._store.put(f"deli/{self.doc_id}", {
            "sequence_number": cp.sequence_number,
            "minimum_sequence_number": cp.minimum_sequence_number,
            "last_sent_msn": cp.last_sent_msn,
            "no_active_clients": cp.no_active_clients,
            "clients": cp.clients,
            "nack_future": cp.nack_future,
            "client_timeout_ms": cp.client_timeout_ms,
            "log_offset": cp.log_offset,
            "summary_responded": self._summary_responded,
        })


class _DeliFactory:
    def __init__(self, store: StateStore, bus: MessageBus,
                 sequencer_factory: Callable[[], DocumentSequencer],
                 metrics: MetricsRegistry | None = None) -> None:
        self._store, self._bus = store, bus
        self._sequencer_factory = sequencer_factory
        self._metrics = metrics

    def create(self, doc_id: str) -> DeliDocumentLambda:
        return DeliDocumentLambda(doc_id, self._store, self._bus,
                                  self._sequencer_factory, self._metrics)


class BatchedDeliDocumentLambda(DeliDocumentLambda):
    """Deli over the device sequencer's BATCH path: admitted raw ops buffer
    in the KernelSequencerHost during the pump and sequence in ONE device
    call at checkpoint — the lambda batch is the device tick (the
    throughput shape of BASELINE.json; contrast the base class's
    per-op ticket()). Cross-document batching happens in the host: every
    document's lambda shares one flush."""

    def __init__(self, doc_id: str, store: StateStore, bus: MessageBus,
                 factory: "_BatchedDeliFactory",
                 metrics: MetricsRegistry | None = None) -> None:
        self._factory = factory
        self._inflight: list[tuple[RawOperation, Trace]] = []
        super().__init__(doc_id, store, bus, sequencer_factory=None,
                         metrics=metrics)

    def _make_sequencer(self, cp: SequencerCheckpoint | None):
        from .kernel_host import KernelDocumentSequencer
        if cp is not None:
            # Route checkpointed state into the device host. restore()
            # overwrites any live row — the checkpoint + committed offset
            # are the consistent pair; a stale row from a prior service
            # life must not survive (its post-checkpoint ops replay from
            # the bus).
            self._factory.host.restore(self.doc_id, cp)
        return KernelDocumentSequencer(self._factory.host, self.doc_id)

    def handler(self, message: BusMessage) -> None:
        raw = self._admit(message)
        if raw is None:
            return
        self._inflight.append((raw, Trace("deli", "start")))
        self._factory.host.submit(self.doc_id, raw)

    def checkpoint(self, next_offset: int) -> None:
        self._factory.flush_ready()
        tickets = self._factory.take_ready(self.doc_id)
        if len(tickets) != len(self._inflight):
            raise RuntimeError(
                f"deli/{self.doc_id}: {len(self._inflight)} inflight ops but "
                f"{len(tickets)} tickets — the shared sequencer host was "
                "flushed outside the lambda pump")
        for (raw, trace_start), ticket in zip(self._inflight, tickets):
            self._emit(raw, ticket, trace_start)
        self._inflight = []
        super().checkpoint(next_offset)


class _BatchedDeliFactory:
    def __init__(self, store: StateStore, bus: MessageBus, host,
                 metrics: MetricsRegistry | None = None) -> None:
        self._store, self._bus = store, bus
        self.host = host
        self._metrics = metrics
        self._ready: dict[str, list] = {}

    def create(self, doc_id: str) -> BatchedDeliDocumentLambda:
        return BatchedDeliDocumentLambda(doc_id, self._store, self._bus,
                                         self, self._metrics)

    def flush_ready(self) -> None:
        """One host flush distributes tickets to every document's lambda
        (first checkpointing lambda pays; the rest just collect)."""
        for doc_id, tickets in self.host.flush().items():
            self._ready.setdefault(doc_id, []).extend(tickets)

    def take_ready(self, doc_id: str) -> list:
        return self._ready.pop(doc_id, [])


# -- scriptorium --------------------------------------------------------------


class ScriptoriumDocumentLambda:
    """Durable op log writer (scriptorium/lambda.ts insertOp). Idempotent on
    replay: ops at-or-below the stored tail sequence number drop.

    ``retention_ops`` (opt-in) bounds the per-doc ops store: past 2x the
    horizon the head trims back to the horizon (amortized — one rewrite
    per horizon's worth of appends). Catch-up reads older than the
    horizon become impossible (clients that far behind reload from a
    snapshot) — the same trade the storm tier's
    ``doc_index_retention_ticks`` makes, and the rest of BENCH_r12's
    service-plane RAM slope."""

    def __init__(self, doc_id: str, store: StateStore,
                 retention_ops: int | None = None) -> None:
        self.doc_id = doc_id
        self._store = store
        self._retention_ops = retention_ops

    def handler(self, message: BusMessage) -> None:
        if message.value["kind"] != "op":
            return
        op: SequencedDocumentMessage = message.value["message"]
        log: list = self._store.get(f"ops/{self.doc_id}", [])
        if log and op.sequence_number <= log[-1].sequence_number:
            return  # replay after crash-before-commit
        retention = self._retention_ops
        if retention is not None and len(log) >= 2 * retention:
            # Amortized horizon trim: ONE put per retention-window of
            # appends rewrites the key to its newest `retention` ops.
            self._store.put(f"ops/{self.doc_id}", log[-retention:])
        self._store.append(f"ops/{self.doc_id}", [op])

    def checkpoint(self, next_offset: int) -> None:
        # The op log IS the durable state; group-commit it here: the whole
        # batch's appends share one fsync, BEFORE the pump commits the
        # consumer offset (a committed offset must never claim an op the
        # journal could still lose). The in-memory StateStore has no sync.
        sync = getattr(self._store, "sync", None)
        if sync is not None:
            sync()


class _ScriptoriumFactory:
    def __init__(self, store: StateStore,
                 retention_ops: int | None = None) -> None:
        self._store = store
        self._retention_ops = retention_ops

    def create(self, doc_id: str) -> ScriptoriumDocumentLambda:
        return ScriptoriumDocumentLambda(doc_id, self._store,
                                         self._retention_ops)


# -- broadcaster --------------------------------------------------------------


@dataclass
class _LiveConnection:
    client_id: str
    doc_id: str
    service: "RouterliciousService"
    handler: Callable[[list[SequencedDocumentMessage]], None]
    on_nack: Callable[[NackMessage], None] | None = None
    on_signal: Callable[[Any], None] | None = None
    open: bool = True
    mode: str = "write"
    #: Transport hook set by the owning front-door session: invoked when
    #: the SERVICE closes the connection (e.g. slow-consumer eviction) so
    #: the client's socket actually drops and its reconnect path runs.
    on_closed: Callable[[], None] | None = None

    def submit(self, messages: list[DocumentMessage]) -> None:
        assert self.open, "submit on closed connection"
        self.service.submit(self.doc_id, self.client_id, messages)

    def signal(self, content: Any) -> None:
        assert self.open, "signal on closed connection"
        self.service.signal(self.doc_id, self.client_id, content)

    def close(self) -> None:
        if self.open:
            self.open = False
            self.service.disconnect(self.doc_id, self.client_id)


class BroadcasterDocumentLambda:
    """Fan-out to live connections (broadcaster/lambda.ts emit). Delivery is
    per-connection resumable: each connection tracks the last seq it saw, so
    replayed messages after a crash dedupe naturally."""

    def __init__(self, doc_id: str,
                 connections: dict[str, _LiveConnection],
                 viewers=None) -> None:
        self.doc_id = doc_id
        self._connections = connections
        # Zero-arg callable resolving the service's viewer plane at
        # delivery time (the plane may attach after this lambda exists).
        self._viewers = viewers
        self._delivered_seq: dict[str, int] = {}

    def handler(self, message: BusMessage) -> None:
        value = message.value
        if value["kind"] == "nack":
            # Nacks are targeted (socket.io emits to ONE socket, never a
            # room), so they bypass any pub/sub hop in every mode.
            conn = self._connections.get(value["target"])
            if conn is not None and conn.on_nack is not None:
                raw: RawOperation = value["operation"]
                conn.on_nack(NackMessage(
                    operation=DocumentMessage(
                        type=raw.type,
                        contents=raw.contents,
                        client_sequence_number=raw.client_seq,
                        reference_sequence_number=raw.ref_seq,
                    ),
                    sequence_number=value["seq"],
                    code=403 if value["code"] == oc.NACK_NO_SUMMARY_SCOPE
                    else 400,
                    error_type=value["code"],
                    message=f"nack:{value['code']}",
                ))
            return
        self._deliver_op(value["message"])
        # Viewer plane (read-only audience): the sequenced op fans out
        # to the doc's viewer room, encoded once per batch (the plane
        # dedupes crash-replay by sequence number).
        viewers = self._viewers() if self._viewers is not None else None
        if viewers is not None and viewers.has_viewers(self.doc_id):
            viewers.publish_ops(self.doc_id, [value["message"]])

    def _deliver_op(self, op: SequencedDocumentMessage) -> None:
        # ONE shared batch for every subscriber: sessions serialize the
        # broadcast body once per doc (codec.BroadcastBatch caches the
        # encoded frame), not once per connection.
        from ..protocol.codec import BroadcastBatch
        batch = None
        for client_id, conn in list(self._connections.items()):
            if not conn.open:
                continue
            if op.sequence_number <= self._delivered_seq.get(client_id, 0):
                continue
            self._delivered_seq[client_id] = op.sequence_number
            if batch is None:
                batch = BroadcastBatch((op,))
            conn.handler(batch)

    def checkpoint(self, next_offset: int) -> None:
        pass  # live fan-out has no durable state


class FanoutBroadcasterDocumentLambda(BroadcasterDocumentLambda):
    """Broadcaster over the native fan-out service: ops publish ONCE to
    the document's room (services-shared redisSocketIoAdapter shape); the
    service's frontend drain delivers each subscriber queue to its
    connection. Per-connection crash-replay dedup moves to the drain."""

    def __init__(self, doc_id: str, connections: dict[str, _LiveConnection],
                 fanout, viewers=None) -> None:
        super().__init__(doc_id, connections, viewers)
        self._fanout = fanout

    def _deliver_op(self, op: SequencedDocumentMessage) -> None:
        import json as _json

        from ..protocol.codec import to_wire
        self._fanout.publish(self.doc_id,
                             _json.dumps(to_wire(op)).encode())


class _BroadcasterFactory:
    def __init__(self, service: "RouterliciousService") -> None:
        self._service = service

    def create(self, doc_id: str) -> BroadcasterDocumentLambda:
        viewers = lambda: self._service.viewers  # noqa: E731
        if self._service.fanout is not None:
            return FanoutBroadcasterDocumentLambda(
                doc_id, self._service._connections_for(doc_id),
                self._service.fanout, viewers)
        return BroadcasterDocumentLambda(
            doc_id, self._service._connections_for(doc_id), viewers)


# -- merger (device merge host consumer) --------------------------------------


class MergerDocumentLambda:
    """Feeds the sequenced stream into the device-resident KernelMergeHost
    (server/merge_host.py). The analogue of hosting the merge kernels
    behind the IPartitionLambdaFactory seam (BASELINE.json): ops buffer in
    the host during the batch and hit the device once per checkpoint — the
    lambda batch IS the device tick. Replayed messages dedupe inside the
    host (per-channel last_seq guards).

    Restart recovery: the host's device state is memory-only, but the
    consumer group's offsets are durable — so a fresh lambda (fresh host
    after a crash) first replays the scriptorium durable op log into the
    host, then consumes from the committed offset. Overlap dedupes in the
    host."""

    def __init__(self, doc_id: str, host, store: StateStore) -> None:
        self.doc_id = doc_id
        self._host = host
        for op in store.get(f"ops/{doc_id}", []):
            host.ingest(doc_id, op)

    def handler(self, message: BusMessage) -> None:
        if message.value["kind"] != "op":
            return
        self._host.ingest(self.doc_id, message.value["message"])

    def checkpoint(self, next_offset: int) -> None:
        self._host.flush()


class _MergerFactory:
    def __init__(self, host, store: StateStore) -> None:
        self._host = host
        self._store = store

    def create(self, doc_id: str) -> MergerDocumentLambda:
        return MergerDocumentLambda(doc_id, self._host, self._store)


# -- copier -------------------------------------------------------------------


class CopierDocumentLambda:
    """Raw-op archival (copier/lambda.ts): every RAWDELTAS message lands in
    a durable per-document raw log before sequencing touches it — the
    forensic/replay trail for debugging sequencer behavior. Idempotent on
    replay via the stored high-water offset."""

    def __init__(self, doc_id: str, store: StateStore) -> None:
        self.doc_id = doc_id
        self._store = store
        self._archived_offset = int(
            self._store.get(f"copier_offset/{doc_id}", -1))

    def handler(self, message: BusMessage) -> None:
        if message.offset <= self._archived_offset:
            return
        self._archived_offset = message.offset
        self._store.append(f"rawops/{self.doc_id}", [message.value])

    def checkpoint(self, next_offset: int) -> None:
        self._store.put(f"copier_offset/{self.doc_id}",
                        self._archived_offset)


class _CopierFactory:
    def __init__(self, store: StateStore) -> None:
        self._store = store

    def create(self, doc_id: str) -> CopierDocumentLambda:
        return CopierDocumentLambda(doc_id, self._store)


# -- foreman ------------------------------------------------------------------


class ForemanDocumentLambda:
    """Background help-task assignment (foreman/lambda.ts): REMOTE_HELP
    ops request agent work (spellcheck, intelligence...); the foreman
    assigns each task to a registered agent pool round-robin and records
    the assignment durably. Idempotent per sequence number."""

    def __init__(self, doc_id: str, store: StateStore,
                 agents: list[str]) -> None:
        self.doc_id = doc_id
        self._store = store
        self._agents = agents or ["default-agent"]
        self._assigned_seq = int(
            self._store.get(f"foreman_seq/{doc_id}", 0))

    def handler(self, message: BusMessage) -> None:
        if message.value.get("kind") != "op":
            return
        op: SequencedDocumentMessage = message.value["message"]
        if op.type != MessageType.REMOTE_HELP:
            return
        if op.sequence_number <= self._assigned_seq:
            return
        self._assigned_seq = op.sequence_number
        tasks = (op.contents or {}).get("tasks", [])
        assignments = self._store.get(f"help/{self.doc_id}", [])
        for i, task in enumerate(tasks):
            agent = self._agents[(len(assignments) + i) % len(self._agents)]
            self._store.append(f"help/{self.doc_id}", [{
                "task": task, "agent": agent,
                "client_id": op.client_id,
                "sequence_number": op.sequence_number}])

    def checkpoint(self, next_offset: int) -> None:
        self._store.put(f"foreman_seq/{self.doc_id}", self._assigned_seq)


class _ForemanFactory:
    def __init__(self, store: StateStore, agents: list[str]) -> None:
        self._store, self._agents = store, agents

    def create(self, doc_id: str) -> ForemanDocumentLambda:
        return ForemanDocumentLambda(doc_id, self._store, self._agents)


# -- scribe -------------------------------------------------------------------


class ScribeDocumentLambda:
    """Summary validation + durable head + ack (scribe/lambda.ts:190-250).
    The ack/nack is produced into RAWDELTAS so deli sequences it — the same
    loop the reference uses (scribe → deli → deltas)."""

    def __init__(self, doc_id: str, store: StateStore, bus: MessageBus,
                 clock: Callable[[], int], snapshots) -> None:
        self.doc_id = doc_id
        self._store = store
        self._bus = bus
        self._clock = clock
        self._snapshots = snapshots
        self._handled_seq = int(
            self._store.get(f"scribe/{self.doc_id}", {}).get("seq", 0))

    def handler(self, message: BusMessage) -> None:
        value = message.value
        if value["kind"] != "op":
            return
        op: SequencedDocumentMessage = value["message"]
        if op.sequence_number <= self._handled_seq:
            return  # replayed
        self._handled_seq = op.sequence_number
        if op.type != MessageType.SUMMARIZE:
            return
        handle = (op.contents or {}).get("handle")
        proposal = {"summary_proposal": {
            "summary_sequence_number": op.sequence_number}}
        offered = self._snapshots.get(self.doc_id, handle)
        current = self._snapshots.get(self.doc_id,
                                      self._snapshots.head(self.doc_id))
        offered_seq = (offered or {}).get("sequence_number")

        def produce_raw(mtype: MessageType, contents: dict) -> None:
            self._bus.produce(RAWDELTAS, self.doc_id, RawOperation(
                client_id=None, type=mtype, contents=contents,
                timestamp=self._clock()))

        if offered is None:
            produce_raw(MessageType.SUMMARY_NACK, {
                "message": f"unknown summary handle {handle!r}",
                "handle": handle, **proposal})
        elif not isinstance(offered_seq, int):
            produce_raw(MessageType.SUMMARY_NACK, {
                "message": "summary content missing sequence_number",
                "handle": handle, **proposal})
        elif current is not None and \
                offered_seq < current["sequence_number"]:
            produce_raw(MessageType.SUMMARY_NACK, {
                "message": f"stale summary at seq {offered_seq} < "
                           f"current {current['sequence_number']}",
                "handle": handle, **proposal})
        else:
            self._snapshots.set_head(self.doc_id, handle)
            produce_raw(MessageType.SUMMARY_ACK,
                        {"handle": handle, **proposal})

    def checkpoint(self, next_offset: int) -> None:
        self._store.put(f"scribe/{self.doc_id}", {"seq": self._handled_seq})


class _ScribeFactory:
    def __init__(self, store: StateStore, bus: MessageBus,
                 clock: Callable[[], int], snapshots) -> None:
        self._store, self._bus, self._clock = store, bus, clock
        self._snapshots = snapshots

    def create(self, doc_id: str) -> ScribeDocumentLambda:
        return ScribeDocumentLambda(doc_id, self._store, self._bus,
                                    self._clock, self._snapshots)


# -- service assembly ---------------------------------------------------------


class RouterliciousService:
    """The assembled ordering service. Same duck-typed surface as
    LocalCollabServer, so drivers/containers run over it unchanged.

    Durability boundary: ``bus`` + ``store`` survive a service restart
    (pass them to a new instance = recover from checkpoints); connections
    and lambda instances do not.
    """

    def __init__(self, bus: MessageBus | None = None,
                 store: StateStore | None = None,
                 num_partitions: int = 4,
                 sequencer_factory: Callable[[], DocumentSequencer]
                 = DocumentSequencer, merge_host=None,
                 logger: TelemetryLogger | None = None,
                 metrics: MetricsRegistry | None = None,
                 snapshots=None,
                 help_agents: list[str] | None = None,
                 batched_deli_host=None,
                 auto_pump: bool = True,
                 fanout=None,
                 idle_check_interval: int = 64,
                 ops_retention: int | None = None) -> None:
        self.bus = bus if bus is not None else MessageBus()
        self.merge_host = merge_host
        # Optional columnar fast path (server/storm.py attaches itself).
        self.storm = None
        # Broadcast viewer plane (server/broadcaster.py attaches itself;
        # connect(mode="viewer") lazily builds a default one): read-only
        # audiences ride fan-out rooms, never the merge/ack path.
        self.viewers = None
        # Optional native pub/sub broadcast hop (native/fanout.py — the
        # Redis + socket.io-adapter analog). None = direct callbacks.
        self.fanout = fanout
        self._fanout_subs: dict[tuple[str, str], int] = {}
        self._fanout_last_seq: dict[tuple[str, str], int] = {}
        self.logger = logger if logger is not None else NullLogger()
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        if merge_host is not None:
            # One registry per service: hosted components report into it so
            # a single snapshot covers the whole assembly (and the per-mesh
            # psum aggregation sees merge-host counters too).
            merge_host.metrics = self.metrics
        self.store = store if store is not None else StateStore()
        self.snapshots = snapshots if snapshots is not None \
            else StoreSnapshotBackend(self.store)
        self.bus.create_topic(RAWDELTAS, num_partitions)
        self.bus.create_topic(DELTAS, num_partitions)
        # The producer boundary (kafka-orderer seam): front-door writes
        # reach deli only through the orderer, never the bus directly.
        from .orderer import BusOrderer
        self.orderer = BusOrderer(self.bus, RAWDELTAS)
        self._connections: dict[str, dict[str, _LiveConnection]] = {}
        # Client ids must never repeat across service restarts (a reused id
        # would make old ops look local to a new client), so the counter is
        # durable like the reference's UUID ids are globally unique.
        self._client_counter = itertools.count(
            int(self.store.get("client_counter", 0)) + 1)
        clock_start = int(self.store.get("clock", 0))
        self._clock_iter = itertools.count(clock_start + 1)
        self._pumping = False
        # deli checkIdleClients cadence: every Nth pump crafts leaves for
        # clients idle past their timeout (a stuck client must not pin the
        # MSN forever — zamboni would starve).
        self.idle_check_interval = max(1, idle_check_interval)
        self._pumps_since_idle_check = 0
        self._batched_deli_host = batched_deli_host

        # auto_pump=False is the batched-cadence mode: submits only produce
        # to the bus; the operator (or load harness) pumps on its own tick,
        # so lambda batches — and the device sequencer tick, when
        # batched_deli_host is given — span many ops/documents.
        self._auto_pump = auto_pump
        deli_factory = (_BatchedDeliFactory(self.store, self.bus,
                                            batched_deli_host, self.metrics)
                        if batched_deli_host is not None else
                        _DeliFactory(self.store, self.bus,
                                     sequencer_factory, self.metrics))
        self._deli = PartitionManager(self.bus, RAWDELTAS, "deli",
                                      deli_factory)
        self._scriptorium = PartitionManager(
            self.bus, DELTAS, "scriptorium",
            _ScriptoriumFactory(self.store, ops_retention))
        self._broadcaster = PartitionManager(
            self.bus, DELTAS, "broadcaster", _BroadcasterFactory(self))
        self._scribe = PartitionManager(
            self.bus, DELTAS, "scribe",
            _ScribeFactory(self.store, self.bus, self._clock,
                           self.snapshots))
        self._merger = (PartitionManager(
            self.bus, DELTAS, "merger",
            _MergerFactory(merge_host, self.store))
            if merge_host is not None else None)
        self._copier = PartitionManager(
            self.bus, RAWDELTAS, "copier", _CopierFactory(self.store))
        self._foreman = PartitionManager(
            self.bus, DELTAS, "foreman",
            _ForemanFactory(self.store, list(help_agents or [])))

    # -- internals -------------------------------------------------------------

    def _clock(self) -> int:
        tick = next(self._clock_iter)
        self.store.put("clock", tick)  # restarts keep timestamps monotonic
        return tick

    def _connections_for(self, doc_id: str) -> dict[str, _LiveConnection]:
        return self._connections.setdefault(doc_id, {})

    def _order_membership(self, doc_id: str, raw: RawOperation) -> None:
        """Order one CLIENT_JOIN/LEAVE system op — through the mega-doc
        membership seam when the doc is promoted (the frozen doc row's
        head is stale; the mirror fast-forwards it, the op sequences at
        the TRUE doc head through the normal deli path below, and the
        mirror absorbs + journals the outcome), straight to the orderer
        otherwise. Promoted-doc membership forces an immediate pump:
        the mirror must see the sequenced outcome before any later lane
        frame combines against it."""
        mega = getattr(self.storm, "megadoc", None)
        if mega is not None:
            verdict = mega.intercept_membership(doc_id, raw)
            if verdict == "deferred":
                # Arrived inside a storm round (idle-eject fired during
                # the round's pump): parked on the deferred-membership
                # queue; the flush maintenance cadence orders it through
                # the FULL mirror path right after the round — never
                # the legacy adopt-at-decide fallback.
                return
            if verdict:
                self.orderer.order_system(doc_id, raw)
                self.pump()
                mega.complete_membership(doc_id, raw)
                return
        self.orderer.order_system(doc_id, raw)

    def _maybe_pump(self) -> None:
        """Front-door writes pump inline only in auto mode; batched-cadence
        deployments pump on their own tick (the load harness / operator)."""
        if self._auto_pump:
            self.pump()

    def pump(self) -> None:
        """Drain every lambda until quiescent (scribe may feed deli)."""
        if self._pumping:
            return  # re-entrant submit during broadcast; outer loop drains
        self._pumping = True
        try:
            while True:
                moved = self._deli.pump()
                moved += self._scriptorium.pump()
                moved += self._scribe.pump()
                moved += self._broadcaster.pump()
                moved += self._copier.pump()
                moved += self._foreman.pump()
                if self._merger is not None:
                    moved += self._merger.pump()
                if self.fanout is not None:
                    moved += self._drain_fanout()
                if moved == 0:
                    break
        finally:
            self._pumping = False
        self._pumps_since_idle_check += 1
        if self._pumps_since_idle_check >= self.idle_check_interval:
            self._pumps_since_idle_check = 0
            self.eject_idle_clients()

    def eject_idle_clients(self,
                           timeout_ms: int | None = None
                           ) -> list[tuple[str, str]]:
        """Craft CLIENT_LEAVE for every client idle past its timeout
        (deli/lambda.ts:171 checkIdleClients): the leave sequences through
        the normal path, freeing the MSN so zamboni proceeds. Returns the
        (doc_id, client_id) pairs ejected."""
        now = self._clock()
        ejected: list[tuple[str, str]] = []
        if self._batched_deli_host is not None:
            ejected = self._batched_deli_host.idle_clients(now, timeout_ms)
        else:
            for doc_id, doc_lambda in self._deli._docs.items():
                sequencer = getattr(doc_lambda, "sequencer", None)
                if sequencer is None:
                    continue
                # One ejection per doc per check (the reference's
                # getIdleClient shape); the next check catches the rest.
                client_id = sequencer.get_idle_client(now, timeout_ms)
                if client_id is not None:
                    ejected.append((doc_id, client_id))
        for doc_id, client_id in ejected:
            self.logger.send_event("IdleClientEjected", docId=doc_id,
                                   clientId=client_id)
            self._order_membership(doc_id, RawOperation(
                client_id=None,
                type=MessageType.CLIENT_LEAVE,
                data=client_id,
                timestamp=now,
            ))
        if ejected:
            self._maybe_pump()
        # Doc-granularity idle ejection rides the same cadence: resident
        # docs idle past the residency timeout demote to the cold tier
        # (snapshot + WAL tail), freeing their device pool slots for the
        # next hydration. Refusals (quarantined, degraded WAL) skip.
        # Bounded per pass: each eviction pays a flush + fsync barrier +
        # snapshot upload on the serving thread, so a lull that idles
        # thousands of docs at once must drain over several passes, not
        # stall serving for one giant sweep.
        residency = getattr(self.storm, "residency", None)
        if residency is not None:
            residency.evict_idle(max_evictions=32)
        return ejected

    def _drain_fanout(self) -> int:
        """Frontend drain: deliver each subscriber's queued room payloads
        to its connection (the socket-server side of the pub/sub hop)."""
        import json as _json

        from ..protocol.codec import from_wire
        delivered = 0
        for (doc_id, client_id), sub in list(self._fanout_subs.items()):
            if self.fanout.was_evicted(sub):
                # Slow-consumer drop in the fan-out: the sub will never
                # receive again, so close the connection (the client's
                # reconnect path resyncs from the durable log) instead of
                # leaving it silently deaf.
                self.logger.send_event("FanoutSubscriberEvicted",
                                       docId=doc_id, clientId=client_id)
                self.disconnect(doc_id, client_id)
                continue
            batch: list[SequencedDocumentMessage] = []
            last_key = (doc_id, client_id)
            while (payload := self.fanout.poll(sub)) is not None:
                if payload[:1] == b"\x00":
                    # Compact storm tick frame (server/storm.py): consumed
                    # by storm-aware frontends; the per-op connections here
                    # catch up via get_deltas materialization instead.
                    continue
                op = from_wire(_json.loads(payload.decode()))
                if op.sequence_number <= self._fanout_last_seq.get(
                        last_key, 0):
                    continue  # crash-replay dedup, as in direct mode
                self._fanout_last_seq[last_key] = op.sequence_number
                batch.append(op)
            if not batch:
                continue
            conn = self._connections_for(doc_id).get(client_id)
            if conn is not None and conn.open:
                delivered += len(batch)
                conn.handler(batch)
        return delivered

    # -- alfred front door -----------------------------------------------------

    def connect(
        self,
        doc_id: str,
        handler: Callable[[list[SequencedDocumentMessage]], None],
        on_nack: Callable[[NackMessage], None] | None = None,
        on_signal: Callable[[Any], None] | None = None,
        mode: str = "write",
        scopes: tuple[str, ...] = ScopeType.ALL,
    ) -> _LiveConnection:
        if mode == "viewer":
            # Viewer-plane connect: no CLIENT_JOIN, no quorum, no deli
            # row, no residency hydration (reads must not churn the
            # pool) — the handler receives broadcast payloads exactly as
            # the wire carries them (server/broadcaster.py).
            if self.viewers is None:
                from .broadcaster import ViewerPlane
                ViewerPlane(self, metrics=self.metrics)
            hello = self.viewers.join(doc_id, handler)
            from .broadcaster import ViewerConnection
            connection = ViewerConnection(self.viewers,
                                          hello["viewer_id"], doc_id)
            self.logger.send_event("ViewerConnect", docId=doc_id,
                                   clientId=hello["viewer_id"])
            return connection
        residency = getattr(self.storm, "residency", None)
        if residency is not None:
            # Tiered residency: the first connect against a cold doc
            # hydrates it (PAPER §2.6: routerlicious loads the document
            # on connect). In-process connects bypass the hydration
            # bucket — the front doors (alfred/bridge) gate BEFORE
            # calling here and nack with retry_after_s.
            residency.ensure_resident(doc_id, gate=False)
        client_number = next(self._client_counter)
        self.store.put("client_counter", client_number)
        client_id = f"client-{client_number}"
        connection = _LiveConnection(client_id, doc_id, self, handler,
                                     on_nack, on_signal, mode=mode)
        self._connections_for(doc_id)[client_id] = connection
        if self.fanout is not None:
            sub = self.fanout.connect()
            self.fanout.join(sub, doc_id)
            self._fanout_subs[(doc_id, client_id)] = sub
        self.logger.send_event("ClientConnect", docId=doc_id,
                               clientId=client_id, mode=mode)
        self._announce_audience(doc_id, connection)
        if mode != "read":
            self._order_membership(doc_id, RawOperation(
                client_id=None,
                type=MessageType.CLIENT_JOIN,
                data=ClientDetail(client_id=client_id, mode=mode,
                                  scopes=scopes),
                timestamp=self._clock(),
                can_summarize=ScopeType.SUMMARY_WRITE in scopes,
            ))
            self._maybe_pump()
        return connection

    def _announce_audience(self, doc_id: str, connection) -> None:
        from .audience import MAX_ROSTER, announce_connect
        # Interest-sampled presence: a pathological writer/reader fan-in
        # on one doc gets a bounded roster sample + exact total instead
        # of a join event per member (read-only VIEWERS never reach this
        # map at all — server/broadcaster.py).
        announce_connect(self._connections_for(doc_id), connection,
                         max_roster=MAX_ROSTER)

    def disconnect(self, doc_id: str, client_id: str) -> None:
        residency = getattr(self.storm, "residency", None)
        if residency is not None:
            # The CLIENT_LEAVE below sequences through the deli row — a
            # cold doc must hydrate into a TRACKED pool slot first, or
            # the leave would lazily allocate a row residency never sees
            # (an untracked slot leak past max_resident). The doc goes
            # idle (no clients) and re-evicts on the next sweep.
            residency.ensure_resident(doc_id, gate=False)
        if self.fanout is not None:
            sub = self._fanout_subs.pop((doc_id, client_id), None)
            if sub is not None:
                self.fanout.disconnect(sub)
            self._fanout_last_seq.pop((doc_id, client_id), None)
        connection = self._connections_for(doc_id).pop(client_id, None)
        if connection is not None:
            from .audience import MAX_ROSTER, announce_leave
            announce_leave(self._connections_for(doc_id), client_id,
                           max_roster=MAX_ROSTER)
        if connection is not None and connection.open:
            # Service-initiated close (the client-initiated path flips
            # `open` before calling us): mark it dead so further submits
            # fail fast, and drop the owning transport so the client sees
            # a real disconnect instead of going silently deaf.
            connection.open = False
            if connection.on_closed is not None:
                try:
                    connection.on_closed()
                except Exception as err:
                    self.logger.send_error("ConnectionDropFailed", err)
        self.logger.send_event("ClientDisconnect", docId=doc_id,
                               clientId=client_id)
        if connection is not None and connection.mode == "read":
            return
        self._order_membership(doc_id, RawOperation(
            client_id=None,
            type=MessageType.CLIENT_LEAVE,
            data=client_id,
            timestamp=self._clock(),
        ))
        self._maybe_pump()

    def submit(self, doc_id: str, client_id: str,
               messages: list[DocumentMessage]) -> None:
        residency = getattr(self.storm, "residency", None)
        if residency is not None:
            # Per-op traffic must refresh the doc's idle clock (or an
            # ACTIVE doc could idle-evict mid-session) and a cold doc
            # must hydrate into a TRACKED row before the orderer's deli
            # submit lazily allocates one residency never sees — the
            # same contract as connect()/disconnect(). Resident docs pay
            # one dict re-insert (touch); only genuinely cold docs pay a
            # restore.
            residency.ensure_resident(doc_id, gate=False)
        self.metrics.counter("alfred.submitted_ops").inc(len(messages))
        self.orderer.connect(doc_id, client_id).order([
            RawOperation(
                client_id=client_id,
                type=message.type,
                client_seq=message.client_sequence_number,
                ref_seq=message.reference_sequence_number,
                timestamp=self._clock(),
                contents=message.contents,
                traces=tuple(message.traces) + (Trace("alfred", "submit"),),
            ) for message in messages])
        self._maybe_pump()

    def signal(self, doc_id: str, client_id: str, content: Any) -> None:
        for connection in list(self._connections_for(doc_id).values()):
            if connection.on_signal is not None:
                connection.on_signal({"client_id": client_id,
                                      "content": content})

    # -- storage (historian/gitrest + scriptorium reads) -----------------------

    def get_deltas(self, doc_id: str, from_seq: int,
                   to_seq: int | None = None) -> list[SequencedDocumentMessage]:
        # Batched-cadence mode must not let readers force a device tick
        # out of cadence; a reader that misses in-flight ops catches up on
        # the next broadcast (gap fetch retries).
        self._maybe_pump()
        log: list[SequencedDocumentMessage] = self.store.get(
            f"ops/{doc_id}", [])
        storm = self.storm
        wanted = (storm.records_overlapping(doc_id, from_seq, to_seq)
                  if storm is not None else [])
        if wanted:
            # Columnar scriptorium records (storm fast path) materialize
            # per-op messages lazily — only the catch-up read path pays,
            # and only for records overlapping the requested range (a
            # tip reader must not rebuild the whole history).
            from .storm import materialize_storm_records
            log = sorted(
                log + materialize_storm_records(
                    wanted, storm.datastore, storm.channel,
                    blob_reader=storm.read_tick_words),
                key=lambda m: m.sequence_number)
        return [m for m in log
                if m.sequence_number > from_seq
                and (to_seq is None or m.sequence_number <= to_seq)]

    # -- history plane (time travel / branches, server/history.py) -------------

    def _history(self):
        history = getattr(self.storm, "history", None)
        if history is None:
            raise RuntimeError(
                "history plane not enabled (attach a HistoryPlane to "
                "the storm controller)")
        return history

    def read_at(self, doc_id: str, seq: int) -> dict:
        """Materialize ``doc_id``'s converged state at historical
        ``seq`` — served entirely from summaries + durable records (a
        cold doc stays cold; no device row hydrates)."""
        self._maybe_pump()
        return self._history().read_at(doc_id, seq)

    def fork_doc(self, doc_id: str, seq: int,
                 name: str | None = None) -> str:
        """Fork ``doc_id`` at ``seq`` into a named branch doc (a full
        citizen: residency/QoS/viewers serve it like any doc)."""
        self._maybe_pump()
        return self._history().fork(doc_id, seq, name)

    def merge_back(self, branch: str) -> dict:
        """Re-submit a branch's delta ops into its parent through the
        ordinary sequencer."""
        self._maybe_pump()
        return self._history().merge_back(branch)

    def upload_snapshot(self, doc_id: str, snapshot: dict,
                        parent: str | None = None) -> str:
        if parent is not None:
            # Incremental summary (summary.ts:53): the client uploaded
            # handle stubs for unchanged subtrees; resolve them against
            # the stored parent so every reader sees a full tree (the
            # content-addressed store dedups the unchanged subtrees).
            from ..protocol.summary import resolve_handles
            parent_tree = self.snapshots.get(doc_id, parent)
            if parent_tree is None:
                raise KeyError(f"unknown parent summary {parent!r}")
            snapshot = resolve_handles(snapshot, parent_tree)
        handle = self.snapshots.upload(doc_id, snapshot)
        if self.snapshots.head(doc_id) is None:
            self.snapshots.set_head(doc_id, handle)
        return handle

    def get_latest_snapshot(self, doc_id: str) -> dict | None:
        return self.snapshots.get(doc_id, self.snapshots.head(doc_id))

    def create_blob(self, doc_id: str, blob_id: str, data: bytes) -> str:
        """Attachment-blob storage (blobManager.ts upload; stored base64 so
        the durable journal stays JSON)."""
        import base64
        blobs: dict = self.store.get(f"blobs/{doc_id}", {})
        blobs[blob_id] = base64.b64encode(bytes(data)).decode()
        self.store.put(f"blobs/{doc_id}", blobs)
        return blob_id

    def read_blob(self, doc_id: str, blob_id: str) -> bytes:
        import base64
        return base64.b64decode(self.store.get(f"blobs/{doc_id}", {})[blob_id])

    # -- agent control surface (headless-agent ↔ foreman) ----------------------

    def help_tasks(self, doc_id: str | None = None) -> list[dict]:
        """Pending foreman assignments with stable claim keys;
        doc_id None = across all documents (agent-pool discovery)."""
        keys = ([f"help/{doc_id}"] if doc_id is not None
                else self.store.keys("help/"))
        out = []
        for key in keys:
            doc = key[len("help/"):]
            done = set(self.store.get(f"help_done/{doc}", []))
            for index, assignment in enumerate(self.store.get(key, [])):
                task_key = f"{doc}#{index}"
                if task_key not in done:
                    out.append({**assignment, "doc_id": doc,
                                "key": task_key})
        return out

    def complete_help(self, task_key: str) -> None:
        """Durably mark one assignment done (idempotent)."""
        doc = task_key.rsplit("#", 1)[0]
        done = self.store.get(f"help_done/{doc}", [])
        if task_key not in done:
            self.store.put(f"help_done/{doc}", done + [task_key])
