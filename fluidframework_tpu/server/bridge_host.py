"""Native-bridge front door — alfred's request surface over the C++
socket bridge.

Reference parity: the alfred socket handler (alfred/index.ts:140-477)
with the transport owned by native code (SURVEY.md §2.9's front-door ↔
TPU-host bridge): bridge.cpp accepts connections and does all framed
socket IO; this host pumps decoded request frames through the SAME
request dispatch the asyncio alfred uses (one wire protocol, two
transports — the network driver connects to either unchanged).

Run standalone::

    python -m fluidframework_tpu.server.bridge_host --port 7071
"""

from __future__ import annotations

import argparse
import sys
import threading
import time
from typing import Any

from ..native.bridge import EV_CLOSE, EV_DATA, EV_OPEN, start_bridge
from ..protocol.codec import decode_body, encode_push, is_storm_body
from ..utils import MetricsRegistry, NullLogger, TelemetryLogger
from .alfred import RequestSession


class _BridgeSession(RequestSession):
    """Alfred request session whose outbox is the native bridge."""

    def __init__(self, server: "BridgeFrontDoor", conn_id: int) -> None:
        super().__init__(server)
        self.conn_id = conn_id

    def push(self, payload: dict) -> None:
        if payload is None:
            return
        rc = self.server._bridge.send(self.conn_id, encode_push(payload))
        if rc == -2:
            # Outbox full: the peer stopped reading. A frame we cannot
            # deliver must never be dropped SILENTLY under a connection
            # that stays up — disconnect the slow consumer (its reconnect
            # path resyncs from the durable log) and close the service
            # side now rather than waiting for the reaped EV_CLOSE.
            self.server.metrics.counter(
                "bridge.slow_consumer_drops").inc()
            self.server.logger.send_event("BridgeSlowConsumerDropped",
                                          conn=self.conn_id)
            self.drop()
            if self.connection is not None:
                connection, self.connection = self.connection, None
                connection.close()

    def drop(self) -> None:
        # Service-initiated disconnect: close the native connection; the
        # resulting EV_CLOSE finishes session cleanup in the pump.
        self.server._bridge.close_conn(self.conn_id)

    def _on_viewer_connected(self) -> None:
        # Viewer connection class: shrink THIS connection's outbox bound
        # (bridge_set_conn_max_outbox) so a stalled viewer trips the
        # slow-consumer -2 early and resyncs, without touching writer
        # connections' deep default.
        bound = self.server.viewer_max_outbox
        if bound is not None:
            self.server._bridge.set_conn_max_outbox(self.conn_id, bound)


class BridgeFrontDoor:
    """Pumps bridge events through the alfred request dispatch."""

    def __init__(self, service, port: int = 0,
                 logger: TelemetryLogger | None = None,
                 metrics: MetricsRegistry | None = None,
                 tenants=None, throttler=None, admission=None,
                 viewer_max_outbox: int | None = 1024) -> None:
        bridge = start_bridge(port)
        if bridge is None:
            raise RuntimeError("native bridge unavailable (no toolchain)")
        self.service = service
        self.logger = logger if logger is not None else NullLogger()
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.tenants = tenants
        self.throttler = throttler
        # Same admission seam as AlfredServer (RequestSession reads it).
        self.admission = admission
        # Viewer-class outbox bound (per-connection override of the
        # bridge's -2 threshold); None keeps viewers at the default.
        self.viewer_max_outbox = viewer_max_outbox
        self._bridge = bridge
        self.port = bridge.port
        self._sessions: dict[int, _BridgeSession] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._pump_loop, daemon=True)
        self._thread.start()

    # -- event pump ------------------------------------------------------------

    def _pump_loop(self) -> None:
        while not self._stop.is_set():
            # Blocking poll (cv in the C++ side): no busy-wait, and the
            # bounded timeout keeps close() responsive.
            event = self._bridge.poll(wait_ms=50)
            if event is None:
                # Idle: non-blocking storm service — drain acks whose
                # group commit completed, run partial-cohort tails, and
                # harvest ready in-flight ticks. Deliberately NOT a full
                # flush(): a windowed (flow-controlled) sender goes
                # quiet between frames while ticks are still in flight,
                # and a forced settle on every quiet poll would collapse
                # the dispatch/fsync overlap back into lockstep ticks.
                storm = getattr(self.service, "storm", None)
                if storm is not None and (storm._frames or storm._inflight
                                          or storm._unacked):
                    try:
                        storm.idle_drain()
                    except Exception as err:
                        self.logger.send_error("BridgeStormFlushFailed", err)
                # Idle residency sweep on the serving thread: docs idle
                # past the timeout demote to the cold tier here (the
                # bridge deployment never pumps the service's own idle
                # pass — this IS its idle cadence), freeing pool slots
                # for the next cold-doc hydration.
                residency = getattr(getattr(self.service, "storm", None),
                                    "residency", None)
                if residency is not None:
                    try:
                        # Bounded per pass (each eviction is a flush +
                        # fsync + upload on this serving thread); the
                        # next idle poll continues the drain.
                        residency.evict_idle(max_evictions=32)
                    except Exception as err:
                        self.logger.send_error("BridgeEvictIdleFailed",
                                               err)
                # Viewer-plane idle drain: flush queued broadcast frames
                # to viewer transports between ticks (resumed viewers,
                # per-op traffic on otherwise-quiet docs).
                viewers = getattr(self.service, "viewers", None)
                if viewers is not None and viewers.active_rooms:
                    try:
                        viewers.drain_all()
                    except Exception as err:
                        self.logger.send_error("BridgeViewerDrainFailed",
                                               err)
                continue
            try:
                self._dispatch(*event)
            except Exception as err:  # the pump must never die
                self.logger.send_error("BridgePumpFailed", err)

    def _dispatch(self, conn_id: int, kind: int, body: bytes) -> None:
        if kind == EV_OPEN:
            self._sessions[conn_id] = _BridgeSession(self, conn_id)
        elif kind == EV_CLOSE:
            session = self._sessions.pop(conn_id, None)
            if session is not None:
                if session.connection is not None:
                    session.connection.close()
                session.close_viewer()
            # Reap the native side (fd + writer thread) too.
            self._bridge.close_conn(conn_id)
        elif kind == EV_DATA:
            self._handle_data(conn_id, body)

    def _handle_data(self, conn_id: int, body: bytes) -> None:
        # Bridge-ingress timestamp: stamped BEFORE the codec decode so a
        # sampled trace's first hop (and the ledger's ingress_decode
        # split) covers the decode itself.
        t_rx = time.monotonic_ns()
        session = self._sessions.get(conn_id)
        if session is None:
            return
        if is_storm_body(body):
            try:
                resp = session.handle_binary(body, ingress_ns=t_rx)
            except Exception as err:
                self.logger.send_error("BridgeStormFailed", err)
                resp = {"rid": None, "error": repr(err)}
            if resp is not None:
                session.push(resp)
            return
        try:
            req: Any = decode_body(body)
        except Exception:
            self._bridge.close_conn(conn_id)
            return
        if not isinstance(req, dict):
            session.push({"rid": None, "error": "request must be an object"})
            return
        try:
            resp = session.handle_request(req)
        except Exception as err:  # keep the socket alive, report
            self.logger.send_error("BridgeRequestFailed", err,
                                   op=req.get("op"))
            resp = {"rid": req.get("rid"), "error": repr(err)}
        session.push(resp)

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=30)
        for session in list(self._sessions.values()):
            if session.connection is not None:
                session.connection.close()
            session.close_viewer()
        self._sessions.clear()
        if self._thread.is_alive():
            # A request is wedged inside the service; freeing the native
            # bridge under the pump would be a use-after-free. Leak it —
            # process teardown reclaims the fds.
            self.logger.send_event("BridgeStopLeaked")
            return
        self._bridge.stop()


def main(argv: list[str] | None = None) -> None:
    from .alfred import build_default_service

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--port", type=int, default=7071)
    parser.add_argument("--no-merge-host", action="store_true")
    parser.add_argument("--data-dir", default=None)
    args = parser.parse_args(argv)
    service = build_default_service(args.data_dir,
                                    merge_host=not args.no_merge_host)
    front = BridgeFrontDoor(service, args.port)
    print(f"READY {front.port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        front.close()


if __name__ == "__main__":
    main(sys.argv[1:])
