"""Background agents — headless clients doing work on documents.

Reference parity: server/headless-agent (a headless client that loads
documents and runs agents against them) + packages/agents/
intelligence-runner-agent (text analytics writing into the document's
insights map) + spellchecker-agent. Work arrives through the foreman
lambda's help assignments (REMOTE_HELP ops → durable assignment records);
agents claim assignments, edit the document through a perfectly ordinary
client stack, and mark them complete.
"""

from .headless import HeadlessAgentRunner, INSIGHTS_CHANNEL
from .intelligence import SpellCheckerAgent, TextAnalyticsAgent

__all__ = [
    "HeadlessAgentRunner", "INSIGHTS_CHANNEL",
    "SpellCheckerAgent", "TextAnalyticsAgent",
]
