"""Intelligence agents — deterministic document analytics.

Reference parity: packages/agents/intelligence-runner-agent (text
analytics run over SharedString content, results written to the insights
map) and spellchecker-agent. The analytics here are deterministic local
computations — the reference's cloud-service calls are out of scope, the
agent *plumbing* (load → analyze → write insights) is the component.
"""

from __future__ import annotations

import re
from collections import Counter

from ..dds.sequence import SharedString

_WORD_RE = re.compile(r"[A-Za-z']+")


def _document_texts(container) -> list[str]:
    """Every SharedString channel's text across all data stores."""
    texts = []
    for datastore in container.runtime.datastores.values():
        for channel_id in datastore.channel_ids():
            if datastore.channel_type(channel_id) \
                    != SharedString.channel_type:
                continue  # non-string channels stay unrealized
            channel = datastore.get_channel(channel_id)
            if isinstance(channel, SharedString):
                texts.append(channel.get_text())
    return texts


class TextAnalyticsAgent:
    """Word/char statistics + top terms (intelligence-runner's
    textAnalytics shape)."""

    name = "intelligence"

    def __init__(self, top_n: int = 5) -> None:
        self._top_n = top_n

    def run(self, container) -> dict:
        texts = _document_texts(container)
        words = [w.lower() for text in texts
                 for w in _WORD_RE.findall(text)]
        # Deterministic order: count desc, then alphabetical.
        top = sorted(Counter(words).items(), key=lambda kv: (-kv[1], kv[0]))
        return {
            "char_count": sum(len(t) for t in texts),
            "word_count": len(words),
            "string_count": len(texts),
            "top_words": [w for w, _ in top[:self._top_n]],
        }


class SpellCheckerAgent:
    """Flags words not in the dictionary (spellchecker-agent shape)."""

    name = "spell"

    DEFAULT_DICTIONARY = frozenset(
        "a an and are hello is of the this to world word words write"
        .split())

    def __init__(self, dictionary=None) -> None:
        self._dictionary = frozenset(
            dictionary if dictionary is not None else
            self.DEFAULT_DICTIONARY)

    def run(self, container) -> dict:
        words = {w.lower() for text in _document_texts(container)
                 for w in _WORD_RE.findall(text)}
        return {"misspelled": sorted(words - self._dictionary)}
