"""Headless agent runner — claims foreman help assignments and runs agents.

Reference parity: server/headless-agent/src — a headless client process
that, told a document needs agent work, loads the document with a normal
client stack, runs the agent, and writes results back INTO the document
(the insights map convention), so every collaborator sees the analysis as
ordinary converged state. Assignment flow: clients submit REMOTE_HELP ops
→ the foreman lambda records durable assignments → this runner polls,
claims, runs, completes (at-least-once; completion is recorded durably via
the service control surface).
"""

from __future__ import annotations

from ..dds.map import SharedMap
from ..runtime.container import Container

INSIGHTS_CHANNEL = "insights"


class HeadlessAgentRunner:
    """Polls help assignments and runs matching agents against documents.

    ``control`` — the service control surface: ``help_tasks(doc_id=None)``
    returning assignment dicts with stable ``key``s, and
    ``complete_help(key)``; RouterliciousService implements it in-proc
    and alfred exposes it over the wire (get_help / complete_help ops).
    ``service_factory`` — doc_id → DocumentService, the same driver seam
    every client uses.
    """

    def __init__(self, control, service_factory, agents,
                 agent_name: str | None = None) -> None:
        self._control = control
        self._service_factory = service_factory
        self._agents = {agent.name: agent for agent in agents}
        self._agent_name = agent_name  # claim only tasks assigned to us

    def run_once(self, doc_id: str | None = None) -> int:
        """Process every claimable pending assignment; returns how many.
        Tasks are grouped per document so each document loads once."""
        by_doc: dict[str, list] = {}
        for task in self._control.help_tasks(doc_id):
            agent = self._agents.get(task["task"])
            if agent is None:
                continue
            if (self._agent_name is not None
                    and task.get("agent") != self._agent_name):
                continue
            by_doc.setdefault(task["doc_id"], []).append((task, agent))
        processed = 0
        for doc, doc_tasks in by_doc.items():
            processed += self._run_doc_tasks(doc, doc_tasks)
        return processed

    def _run_doc_tasks(self, doc_id: str, doc_tasks: list) -> int:
        service = self._service_factory(doc_id)
        container = Container.load(service)
        completed = []
        try:
            for task, agent in doc_tasks:
                result = agent.run(container)
                self._insights(container).set(agent.name, result)
                completed.append(task["key"])
        finally:
            container.close()
            close = getattr(service, "close", None)
            if close is not None:
                close()  # a network service holds a socket + threads
        # Complete AFTER the insights writes are submitted: a crash in
        # between re-runs tasks (at-least-once), never loses them.
        for key in completed:
            self._control.complete_help(key)
        return len(completed)

    @staticmethod
    def _insights(container) -> SharedMap:
        """The document's insights map, created on first agent visit."""
        runtime = container.runtime
        for datastore in runtime.datastores.values():
            if INSIGHTS_CHANNEL in datastore.channel_ids():
                return datastore.get_channel(INSIGHTS_CHANNEL)
        if not runtime.datastores:
            raise RuntimeError("document has no data stores to annotate")
        datastore = runtime.datastores[sorted(runtime.datastores)[0]]
        return datastore.create_channel(INSIGHTS_CHANNEL,
                                        SharedMap.channel_type)
