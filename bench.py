"""Benchmark: merged ops/sec across concurrent documents (BASELINE config 3).

Workload: the SharedMap op-storm — B documents × K sequenced set/delete/clear
ops per tick, merged by the batched LWW kernel on the accelerator — measured
against the single-node scalar CPU merge loop (the reference's architecture:
one op at a time per document on a CPU, reference mapKernel.ts:510).

Prints exactly ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
"""

from __future__ import annotations

import json
import time

import numpy as np


def device_ops_per_sec(num_docs: int, k: int, num_slots: int,
                       ticks: int) -> float:
    import jax

    from fluidframework_tpu.ops import map_kernel as mk

    rng = np.random.default_rng(0)

    def random_tick(tick_index: int):
        kinds = rng.choice(
            [mk.MAP_SET, mk.MAP_DELETE, mk.MAP_CLEAR],
            p=[0.75, 0.2, 0.05], size=(num_docs, k)).astype(np.int32)
        slots = rng.integers(0, num_slots, (num_docs, k)).astype(np.int32)
        kind_slot = (kinds | (slots << 2)).astype(np.int16)
        value = rng.integers(1, 1 << 20, (num_docs, k)).astype(np.int32)
        counts = np.full((num_docs,), k, np.int32)
        base_seq = np.full((num_docs,), tick_index * k, np.int32)
        return kind_slot, value, counts, base_seq

    # Host-resident op batches: the timed loop INCLUDES the host→device
    # transfer of each tick's op stream (packed wire encoding, no overlap
    # credit), as the real server pipeline pays it.
    batches = [random_tick(t) for t in range(ticks)]
    state = mk.init_state(num_docs, num_slots)
    # Warm-up / compile.
    state = mk.apply_tick_packed(state, *map(jax.device_put, batches[0]))
    jax.block_until_ready(state)

    rates = []
    for _rep in range(3):
        start = time.perf_counter()
        for batch in batches:
            state = mk.apply_tick_packed(state, *map(jax.device_put, batch))
        jax.block_until_ready(state)
        elapsed = time.perf_counter() - start
        rates.append((num_docs * k * ticks) / elapsed)
    return sorted(rates)[1]  # median of 3 (the transfer link is jittery)


def scalar_ops_per_sec(total_ops: int, num_slots: int) -> float:
    """Single-node CPU baseline: the scalar per-document merge loop."""
    from fluidframework_tpu.dds.map_data import MapData

    rng = np.random.default_rng(1)
    kinds = rng.choice(["set", "delete", "clear"], p=[0.75, 0.2, 0.05],
                       size=total_ops)
    slots = rng.integers(0, num_slots, total_ops)
    values = rng.integers(1, 1 << 20, total_ops)
    data = MapData()
    start = time.perf_counter()
    for i in range(total_ops):
        kind = kinds[i]
        if kind == "set":
            data.process({"type": "set", "key": f"k{slots[i]}",
                          "value": int(values[i])}, False, None)
        elif kind == "delete":
            data.process({"type": "delete", "key": f"k{slots[i]}"},
                         False, None)
        else:
            data.process({"type": "clear"}, False, None)
    elapsed = time.perf_counter() - start
    return total_ops / elapsed


def main() -> None:
    num_docs, k, num_slots, ticks = 8192, 256, 32, 12
    device_rate = device_ops_per_sec(num_docs, k, num_slots, ticks)
    scalar_rate = scalar_ops_per_sec(200_000, num_slots)
    print(json.dumps({
        "metric": "merged map ops/sec across 8k concurrent docs",
        "value": round(device_rate, 1),
        "unit": "ops/s",
        "vs_baseline": round(device_rate / scalar_rate, 2),
    }))


if __name__ == "__main__":
    main()
