"""Benchmark: all four device kernels + sequencer at BASELINE-config scale.

Workloads (BASELINE.md configs):
  3. SharedMap op-storm, 10,240 concurrent docs  — the HEADLINE metric
  2. merge-tree insert/remove stress (deep segment tables, splits)
  4. SharedMatrix row/col OT + LWW cell writes (composed kernel)
  5. SharedTree batched edit apply/validity (1k docs)
  +  total-order sequencer (deli ticket loop)

Each workload reports device merged-ops/sec AND p50/p99 device tick
latency (one tick = one batched apply; an op waits at most one tick, so
p99 tick latency bounds the queueing delay an op sees at the kernel).

Baselines (single-node CPU, measured here, in BENCH_DETAIL.json):
  * scalar_python: per-op scalar loop through this repo's own scalar
    engines (MergeEngine / MapData / PermutationVector / Transaction /
    DocumentSequencer) — the reference's ARCHITECTURE (one op at a time
    per document), interpreted by CPython.
  * numpy_batched_cpu (map storm only): the batched-kernel semantics
    vectorized with numpy on CPU — the strongest same-machine CPU
    contender; a fairer floor than the interpreted loop.
  CAVEAT: the reference's real merge loop is JIT-compiled TypeScript on
  V8, typically 10-50x faster than the CPython scalar loop but well below
  the numpy batched path for this workload; the honest reference-vs-TPU
  multiplier lies between the two ratios reported.

Prints exactly ONE JSON line to stdout (headline = config 3 vs the
strongest measured CPU baseline); full per-kernel detail goes to
BENCH_DETAIL.json and stderr.
"""

from __future__ import annotations

import json
import random
import sys
import time

import numpy as np


def _tile(arr: np.ndarray, b: int) -> np.ndarray:
    """Tile a single-doc [1, K] plane across the batch axis."""
    return np.ascontiguousarray(np.broadcast_to(arr, (b,) + arr.shape[1:]))


def _force(state) -> None:
    """True device sync: fetch one scalar of the result to host.

    jax.block_until_ready does not reliably block through remote-tunneled
    TPU attachments, which silently turns "blocked" timings into enqueue
    timings; a scalar readback forces the whole dependency chain.
    """
    import jax

    leaf = jax.tree_util.tree_leaves(state)[0]
    np.asarray(leaf[(0,) * leaf.ndim])


def _cadence_series(step_fn, state0, depth: int, ticks: int,
                    attempts: int = 3) -> list[np.ndarray]:
    """Pipelined completion cadence: keep ``depth`` ticks in flight (each
    tick's one-scalar probe starts its device→host copy at enqueue, so
    the harvest is a wait, not a fresh transport round trip) and measure
    the interval between successive completions over a ``ticks``-long
    series, ``attempts`` times. Returns one ms-interval array per
    attempt; callers rank them (median-by-p99 headline, best reported
    separately) because tunneled-attachment delivery jitter varies by
    the minute."""
    import jax

    out = []
    for _attempt in range(attempts):
        st = state0
        inflight: list = []
        completions: list = []
        for i in range(ticks + depth):
            st = step_fn(st, i)
            leaf = jax.tree_util.tree_leaves(st)[0]
            probe = leaf[(0,) * leaf.ndim]
            copy_async = getattr(probe, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
            inflight.append(probe)
            if len(inflight) > depth:
                np.asarray(inflight.pop(0))
                completions.append(time.perf_counter())
        while inflight:
            np.asarray(inflight.pop(0))
            completions.append(time.perf_counter())
        out.append(np.diff(np.asarray(completions[:ticks])) * 1000.0)
    return out


def _run_device(apply_fn, state, batches, ops_per_tick: int,
                latency_ticks: int = 36, passes: int = 4,
                pipeline_ticks: int = 120) -> dict:
    """Throughput (free-running, sync at end) + per-tick blocked latency.

    Each rep cycles the batch list ``passes`` times between host syncs so
    the sync round trip (~100ms on a tunneled attachment) amortizes below
    the per-tick device time being measured.
    """
    state0 = state
    # Warm-up / compile.
    state = apply_fn(state, batches[0])
    _force(state)

    rates = []
    for _rep in range(3):
        st = state0
        start = time.perf_counter()
        for _pass in range(passes):
            for batch in batches:
                st = apply_fn(st, batch)
        _force(st)
        elapsed = time.perf_counter() - start
        rates.append(ops_per_tick * len(batches) * passes / elapsed)

    lat = []
    st = state0
    for i in range(latency_ticks):
        batch = batches[i % len(batches)]
        start = time.perf_counter()
        st = apply_fn(st, batch)
        _force(st)
        lat.append((time.perf_counter() - start) * 1000.0)
    lat_arr = np.asarray(lat)
    best_rate = float(sorted(rates)[1])  # median of 3
    # Pipelined completion CADENCE: keep `depth` ticks in flight (the
    # serving controller's harvest deque) and measure the interval
    # between successive tick completions. With enough depth the
    # transport RTT of each sync hides under the in-flight ticks'
    # compute, so the cadence converges to the per-tick device time —
    # the latency an op actually sees at a kept-fed kernel. Depth is
    # ADAPTIVE: hiding an RTT of ~R ms behind t-ms ticks needs R/t ticks
    # in flight — the fixed depth-4 pipe of earlier rounds stalled for a
    # full RTT whenever the tick time was far below RTT/4 (VERDICT r4
    # weak #1), and a ~12-tick series made "p99" the max of a tiny
    # sample; the series here is >=120 ticks so p99 is a percentile.
    tick_ms = 1000.0 * ops_per_tick / best_rate
    depth = int(min(32, max(4, np.ceil(180.0 / max(tick_ms, 0.1)))))
    attempts = _cadence_series(
        lambda st, i: apply_fn(st, batches[i % len(batches)]),
        state0, depth, pipeline_ticks)
    # Headline = MEDIAN attempt by p99 (what a typical window sustains);
    # the best attempt is reported under its own name, never as the
    # plain p99.
    ranked = sorted(attempts, key=lambda a: float(np.percentile(a, 99)))
    pipe_arr = ranked[len(ranked) // 2]
    pipe_best = ranked[0]
    return {
        "device_ops_per_sec": best_rate,
        # Free-running per-tick time — the pure device cost of one batched
        # apply when the pipeline is kept fed (the serving cadence floor).
        "tick_ms_freerun": 1000.0 * ops_per_tick / best_rate,
        # Blocked round-trip latency per tick: submit one tick, sync to
        # host. On a tunneled/remote attachment this includes transport
        # RTT, so it upper-bounds the device tick latency.
        "tick_ms_p50": float(np.percentile(lat_arr, 50)),
        "tick_ms_p99": float(np.percentile(lat_arr, 99)),
        # Adaptive-depth pipelined cadence (serving shape): per-tick wall
        # time with enough later ticks in flight to hide the RTT, over a
        # >=120-tick series.
        "tick_ms_pipelined_p50": float(np.percentile(pipe_arr, 50)),
        "tick_ms_pipelined_p99": float(np.percentile(pipe_arr, 99)),
        "tick_ms_pipelined_p50_best": float(np.percentile(pipe_best, 50)),
        "tick_ms_pipelined_p99_best": float(np.percentile(pipe_best, 99)),
        "tick_ms_pipelined_attempts": [
            {"p50": round(float(np.percentile(a, 50)), 2),
             "p99": round(float(np.percentile(a, 99)), 2),
             "max": round(float(a.max()), 2)} for a in attempts],
        "pipeline_depth": depth,
        "pipeline_samples": int(pipe_arr.shape[0]),
        "ops_per_tick": ops_per_tick,
    }


# -- config 3: SharedMap op-storm ---------------------------------------------


def _cpu_batched_rate(apply_fn, state, batches, ops_per_tick: int) -> float:
    """The SAME batched program on XLA-CPU (this machine's strongest
    general baseline: identical semantics, compiled, vectorized) at a
    scaled-down doc batch — rates normalize per op."""
    import jax

    cpu = jax.devices("cpu")[0]
    state = jax.device_put(state, cpu)
    batches = [jax.device_put(b, cpu) for b in batches[:2]]
    for batch in batches:  # compile EVERY distinct batch shape untimed
        st = apply_fn(state, batch)
    jax.block_until_ready(st)
    start = time.perf_counter()
    reps = 2
    for _ in range(reps):
        for batch in batches:
            st = apply_fn(st, batch)
    jax.block_until_ready(st)
    return ops_per_tick * len(batches) * reps / (
        time.perf_counter() - start)


# Peak int32 element-op rate of one v5e chip's VPU (8 sublanes x 128
# lanes x ~4 ALUs x ~940 MHz) — the denominator for the utilization
# ESTIMATE reported per workload (elems_per_op models in notes).
_VPU_PEAK_ELEMS = 3.9e12


def bench_map(num_docs: int = 10_240, k: int = 1024, num_slots: int = 32,
              ticks: int = 12) -> dict:
    import jax

    from fluidframework_tpu.ops import map_kernel as mk

    rng = np.random.default_rng(0)

    def random_tick(t: int):
        kinds = rng.choice([mk.MAP_SET, mk.MAP_DELETE, mk.MAP_CLEAR],
                           p=[0.75, 0.2, 0.05],
                           size=(num_docs, k)).astype(np.uint32)
        slots = rng.integers(0, num_slots, (num_docs, k)).astype(np.uint32)
        value = rng.integers(1, 1 << 20, (num_docs, k)).astype(np.uint32)
        words = kinds | (slots << 2) | (value << 12)
        counts = np.full((num_docs,), k, np.int32)
        base_seq = np.full((num_docs,), t * k, np.int32)
        return words, counts, base_seq

    # Op streams are staged on device ahead of the timed loop (the fused
    # 4-byte/op wire format), matching the other kernel benches: a real
    # serving pipeline overlaps the feed with compute, while this harness
    # may sit behind a tunneled TPU attachment where a synchronous
    # per-tick host->device hop measures the tunnel, not the pipeline.
    host_batches = [random_tick(t) for t in range(ticks)]
    batches = [tuple(jax.device_put(a) for a in batch)
               for batch in host_batches]

    def apply(state, batch):
        # Pallas VMEM LWW fold on TPU (ops/map_pallas.py); the XLA
        # dense-winner path elsewhere.
        from fluidframework_tpu.ops import map_pallas as mpx
        return mpx.apply_tick_words_best(state, *batch)

    out = _run_device(apply, mk.init_state(num_docs, num_slots), batches,
                      num_docs * k)

    # Baseline A: per-op scalar loop (reference architecture on CPython).
    from fluidframework_tpu.dds.map_data import MapData
    n = 200_000
    kinds = rng.choice(["set", "delete", "clear"], p=[0.75, 0.2, 0.05],
                       size=n)
    slots = rng.integers(0, num_slots, n)
    values = rng.integers(1, 1 << 20, n)
    keys = [f"k{s}" for s in range(num_slots)]
    data = MapData()
    start = time.perf_counter()
    for i in range(n):
        kind = kinds[i]
        if kind == "set":
            data.process({"type": "set", "key": keys[slots[i]],
                          "value": int(values[i])}, False, None)
        elif kind == "delete":
            data.process({"type": "delete", "key": keys[slots[i]]},
                         False, None)
        else:
            data.process({"type": "clear"}, False, None)
    out["scalar_python_ops_per_sec"] = n / (time.perf_counter() - start)

    # Baseline B: batched LWW semantics vectorized with numpy on CPU.
    present = np.zeros((num_docs, num_slots), bool)
    value_tab = np.zeros((num_docs, num_slots), np.int32)
    docs = np.arange(num_docs)
    start = time.perf_counter()
    for words, _counts, _base in host_batches:  # pure-numpy CPU floor
        kind_plane = (words & 3).astype(np.int32)
        slot_plane = ((words >> 2) & 0x3FF).astype(np.int32)
        value = ((words >> 12) & 0xFFFFF).astype(np.int32)
        for i in range(k):
            kind_col = kind_plane[:, i]
            slot_col = slot_plane[:, i]
            cleared = kind_col == mk.MAP_CLEAR
            if cleared.any():
                present[cleared] = False
            sets = kind_col == mk.MAP_SET
            present[docs[sets], slot_col[sets]] = True
            value_tab[docs[sets], slot_col[sets]] = value[sets, i]
            dels = kind_col == mk.MAP_DELETE
            present[docs[dels], slot_col[dels]] = False
    elapsed = time.perf_counter() - start
    out["numpy_batched_cpu_ops_per_sec"] = num_docs * k * ticks / elapsed
    # Winner compute touches S slots per op (dense masked-max).
    out["vpu_util_est"] = round(
        out["device_ops_per_sec"] * num_slots / _VPU_PEAK_ELEMS, 4)
    out["num_docs"] = num_docs
    return out


# -- config 2: merge-tree stress ----------------------------------------------


def _gen_merge_stream(rng: random.Random, n_ops: int,
                      n_writers: int = 8) -> list[dict]:
    """Fully-acked sequenced insert/remove stream for one document."""
    from fluidframework_tpu.ops import mergetree_kernel as mtk

    ops, length, pool = [], 0, 0
    for seq in range(1, n_ops + 1):
        client = rng.randrange(n_writers)
        if length > 16 and rng.random() < 0.3:
            start = rng.randrange(length - 8)
            end = start + rng.randint(1, 8)
            ops.append(dict(kind=mtk.MT_REMOVE, pos=start, end=end, seq=seq,
                            ref_seq=seq - 1, client=client))
            length -= end - start
        else:
            tlen = rng.randint(1, 8)
            ops.append(dict(kind=mtk.MT_INSERT, pos=rng.randint(0, length),
                            seq=seq, ref_seq=seq - 1, client=client,
                            pool_start=pool, text_len=tlen))
            pool += tlen
            length += tlen
    return ops


def bench_mergetree(num_docs: int = 8192, k: int = 32, ticks: int = 6,
                    num_slots: int = 512, n_writers: int = 8) -> dict:
    # num_slots is sized to the stream's worst case (k*ticks ops x 2 slots
    # + margin) the way the serving host sizes device capacity. n_writers
    # sets the distinct-client count (BASELINE config 2 runs this at 128 —
    # the overlap planes widen to match, ops/mergetree_kernel.py).
    import jax.numpy as jnp

    from fluidframework_tpu.ops import mergetree_blocks as mtb
    from fluidframework_tpu.ops import mergetree_blocks_pallas as mtbp
    from fluidframework_tpu.ops import mergetree_kernel as mtk
    from fluidframework_tpu.ops import mergetree_pallas as mtp

    rng = random.Random(0)
    stream = _gen_merge_stream(rng, k * ticks, n_writers)

    batches = []
    for t in range(ticks):
        chunk = [stream[t * k:(t + 1) * k]]
        one = mtk.make_merge_op_batch(chunk, 1, k)
        batches.append(mtk.MergeOpBatch(
            *[jnp.asarray(_tile(np.asarray(f), num_docs)) for f in one]))

    # THE serving path (ISSUE 2): the block-structured table with the
    # conditional per-tick rebalance fused exactly as storm._mixed_tick
    # runs it (rebalance fires only when a block runs low on worst-case
    # headroom).
    nb, bk = mtb.choose_block_geometry(num_slots, k)
    zero_ms = jnp.zeros((num_docs,), jnp.int32)

    def apply_blocks(state, batch):
        state, _ovf = mtbp.apply_tick_blocks_best(state, batch)
        return mtb.maybe_rebalance(state, zero_ms, k)

    out = _run_device(
        apply_blocks,
        mtb.init_state(num_docs, nb, bk,
                       overlap_words=mtk.overlap_words_for(n_writers)),
        batches, num_docs * k)
    out["n_writers"] = n_writers
    out["block_geometry"] = {"num_blocks": nb, "block_slots": bk}
    out["kernel_path"] = ("blocks_xla_scan" if mtbp.default_interpret()
                          else "blocks_pallas_vmem")
    # The displaced flat per-op kernel, same stream and doc count — the
    # round-5 serving path as the in-round baseline.
    flat = _run_device(
        mtp.apply_tick_best,
        mtk.init_state(num_docs, num_slots,
                       overlap_words=mtk.overlap_words_for(n_writers)),
        batches, num_docs * k)
    out["flat_kernel_ops_per_sec"] = flat["device_ops_per_sec"]
    out["block_vs_flat_speedup"] = round(
        out["device_ops_per_sec"] / flat["device_ops_per_sec"], 3)
    # XLA-CPU twin of the same batched program (strongest CPU contender).
    cpu_docs = 256
    cpu_batches = [mtk.MergeOpBatch(
        *[jnp.asarray(_tile(np.asarray(f)[:1], cpu_docs)) for f in b])
        for b in batches[:2]]  # _cpu_batched_rate uses two ticks
    out["xla_cpu_batched_ops_per_sec"] = _cpu_batched_rate(
        mtk.apply_tick,
        mtk.init_state(cpu_docs, num_slots,
                       overlap_words=mtk.overlap_words_for(n_writers)),
        cpu_batches, cpu_docs * k)
    # Each op's split/place/mark machinery touches ~6 planes of S slots.
    out["vpu_util_est"] = round(
        out["device_ops_per_sec"] * 6 * num_slots / _VPU_PEAK_ELEMS, 4)

    # Scalar baseline: the same stream through the scalar MergeEngine.
    from fluidframework_tpu.dds.mergetree import MergeEngine
    reps = 20
    start = time.perf_counter()
    for _ in range(reps):
        engine = MergeEngine()
        for op in stream:
            if op["kind"] == mtk.MT_INSERT:
                engine.apply_remote(
                    {"type": "insert", "pos": op["pos"],
                     "text": "x" * op["text_len"]},
                    op["seq"], op["ref_seq"], f"c{op['client']}")
            else:
                engine.apply_remote(
                    {"type": "remove", "start": op["pos"], "end": op["end"]},
                    op["seq"], op["ref_seq"], f"c{op['client']}")
    elapsed = time.perf_counter() - start
    out["scalar_python_ops_per_sec"] = len(stream) * reps / elapsed
    out["num_docs"] = num_docs
    return out


def bench_mergetree_windowed(num_docs: int = 8192, k: int = 32,
                             rounds: int = 20, num_slots: int = 256,
                             window: int = 64) -> dict:
    """The LONG-LIVED serving shape: a typing-style stream (appends +
    range removes, fully acked behind a ``window``-deep collab window)
    with the device zamboni — drop + offset repack + COALESCE — fused
    into EVERY tick, so the segment table tracks the window, not the
    document's edit count, and there is no stop-the-world compaction
    cliff (VERDICT r4 weak #4): the reference amortizes its zamboni the
    same way (mergeTree.ts:1412 runs on minSeq advance). The log-shift
    pack + scan-based coalesce (no sort, no scatter) make the per-tick
    zamboni cheap enough that the ALWAYS-compacted table at S=256
    out-serves the old 4-tick cadence at S=512. The rate INCLUDES the
    compaction; ``tick_ms_incl_compact_*`` is a pipelined cadence series
    over every tick (each one pays apply + zamboni)."""
    import jax
    import jax.numpy as jnp

    from fluidframework_tpu.ops import mergetree_kernel as mtk
    from fluidframework_tpu.ops import mergetree_pallas as mtp

    rng = random.Random(1)
    ticks = []
    length = 0
    pool = 0
    seq = 0
    for _ in range(rounds):
        ops = []
        for _ in range(k):
            seq += 1
            if length > 64 and rng.random() < 0.35:
                start = rng.randrange(length - 16)
                end = start + rng.randint(1, 16)
                ops.append(dict(kind=mtk.MT_REMOVE, pos=start, end=end,
                                seq=seq, ref_seq=seq - 1,
                                client=rng.randrange(4)))
                length -= end - start
            else:
                tlen = rng.randint(1, 8)
                # The typist appends at the END: document order equals
                # pool order, the shape coalescing exploits.
                ops.append(dict(kind=mtk.MT_INSERT, pos=length, seq=seq,
                                ref_seq=seq - 1, client=rng.randrange(4),
                                pool_start=pool, text_len=tlen))
                pool += tlen
                length += tlen
        one = mtk.make_merge_op_batch([ops], 1, k)
        batch = mtk.MergeOpBatch(
            *[jnp.asarray(_tile(np.asarray(f), num_docs)) for f in one])
        ticks.append((batch, jnp.full((num_docs,), max(0, seq - window),
                                      jnp.int32)))

    @jax.jit
    def zamboni(state, ms):
        """One jitted pass: device twin of the host text repack (offsets
        become the exclusive cumsum of lengths in table order, making
        adjacent document-order segments pool-contiguous) followed by the
        coalescing compact."""
        lens = jnp.where(state.valid, state.length, 0)
        repacked = state._replace(
            pool_start=jnp.cumsum(lens, axis=1) - lens)
        return mtk.compact(repacked, ms, coalesce=True)

    def serve_tick(state, index):
        batch, ms = ticks[index]
        state = mtp.apply_tick_best(state, batch)
        return zamboni(state, ms)

    # Warm pass doubles as the OVERFLOW check: capacity_margin's
    # contract is that over-capacity ticks drop segments SILENTLY, and
    # the table is deepest right before each zamboni — so assert the
    # pre-tick margin covers the worst case (2 slots/op) at every warm
    # tick, where the readback is untimed.
    state = mtk.init_state(num_docs, num_slots)
    for i in range(rounds):
        margin = mtk.capacity_margin(state)
        assert (margin >= 2 * k).all(), (
            f"windowed bench would overflow at tick {i}: "
            f"min margin {int(margin.min())} < {2 * k}")
        state = serve_tick(state, i)
    _force(state)
    # Zamboni cost alone (one blocked sync: includes a transport RTT on a
    # tunneled attachment — the pipelined cadence below is the honest
    # per-tick figure).
    zstart = time.perf_counter()
    z = zamboni(state, ticks[0][1])
    _force(z)
    zamboni_ms = (time.perf_counter() - zstart) * 1000.0
    reps = 3
    rates = []
    slots_after = 0
    for _ in range(reps):
        st = mtk.init_state(num_docs, num_slots)
        start = time.perf_counter()
        for i in range(rounds):
            st = serve_tick(st, i)
        _force(st)
        rates.append(num_docs * k * rounds
                     / (time.perf_counter() - start))
        slots_after = int(np.asarray(st.count[0]))
    # Pipelined completion cadence over EVERY tick — each one includes
    # the fused zamboni, so max() is the honest worst-tick latency
    # including compaction.
    attempts = _cadence_series(
        lambda st, i: serve_tick(st, i % rounds),
        mtk.init_state(num_docs, num_slots), depth=16, ticks=120)
    ranked = sorted(attempts, key=lambda a: float(np.percentile(a, 99)))
    cadence = ranked[len(ranked) // 2]  # median attempt by p99
    return {
        "device_ops_per_sec": float(sorted(rates)[1]),
        "zamboni_ms_per_pass_blocked": round(zamboni_ms, 2),
        "compact_every_ticks": 1,
        "tick_ms_incl_compact_p50": float(np.percentile(cadence, 50)),
        "tick_ms_incl_compact_p99": float(np.percentile(cadence, 99)),
        "tick_ms_incl_compact_max": float(cadence.max()),
        "cadence_samples": int(cadence.shape[0]),
        "ops_total_per_doc": k * rounds,
        "live_slots_after": slots_after,
        "window_depth": window,
        "num_docs": num_docs,
        "note": ("slot demand stays near the collab window "
                 f"({slots_after} slots after {k * rounds} ops/doc) — "
                 "the per-tick log-shift zamboni keeps long-lived "
                 "documents device-resident at bounded size with NO "
                 "stop-the-world pass; rate and cadence include "
                 "compaction on every tick"),
    }


def bench_client_walk(segments: int = 26_000, walks: int = 400) -> dict:
    """Client-side walk cost on a 26k-segment document: the settled-block
    index (dds/mergetree.py) vs the index-disabled linear walk — the
    committed artifact behind round 5's "remote applies drop 25x" claim
    (VERDICT r5 weak #7d: it lived only in commit 9258b85's message).
    Pure host/CPU; independent of the accelerator."""
    import random as _random

    from fluidframework_tpu.dds.mergetree import MergeEngine, Segment

    class _NoIndexEngine(MergeEngine):
        """Identical engine with block skipping disabled — every walk
        degenerates to the pre-index linear scan."""

        def _scan_ready(self, b, base):  # noqa: D102
            return False

    def build(cls) -> MergeEngine:
        engine = cls("bench")
        # Alternating props prevent zamboni/snapshot coalescing, so the
        # table genuinely holds `segments` entries, all settled baseline.
        engine.segments = [
            Segment(content="x" * 4, seq=0, client=None,
                    props={"p": i & 1})
            for i in range(segments)]
        engine.current_seq = engine.min_seq = 1
        engine._rebuild_index()
        return engine

    rng = _random.Random(5)
    length = 4 * segments
    positions = [rng.randrange(length) for _ in range(walks)]
    out: dict = {"segments": segments, "walks": walks}
    for name, cls in (("indexed", MergeEngine),
                      ("linear", _NoIndexEngine)):
        engine = build(cls)
        seq = 1
        spent = 0.0
        for pos in positions:
            seq += 1
            start = time.perf_counter()
            engine.apply_remote({"type": "insert", "pos": pos,
                                 "text": "y"}, seq, seq - 1, "remote")
            spent += time.perf_counter() - start
            # The serving shape: the collab window advances with acks,
            # so fresh segments settle and their blocks return to the
            # skippable set. The window maintenance (zamboni) is the
            # same cost for both engines and is NOT the walk under
            # measurement, so it stays outside the timer.
            engine.update_min_seq(seq)
        out[f"{name}_ms_per_apply"] = round(1000 * spent / walks, 4)
    out["speedup"] = round(out["linear_ms_per_apply"]
                           / out["indexed_ms_per_apply"], 1)
    return out


# -- config 4: matrix ---------------------------------------------------------


def bench_mixed_serving(num_docs: int = 8192, ticks: int = 12,
                        map_k: int = 64, text_k: int = 16,
                        matrix_k: int = 16, tree_k: int = 8) -> dict:
    """ALL-DDS fused serving (VERDICT r4 item 1): one SPMD device program
    tickets AND applies a MIXED document population — map, merge-tree
    text, matrix and tree rows, a quarter each — through the closed-form
    deli + every family's apply leg (server/storm.py ``_mixed_tick``,
    the reference's one-deltas-stream contract, deli/lambda.ts:82).

    Two rates, bench-map style: ``device_ops_per_sec`` with tick inputs
    staged ahead (the kept-fed serving pipeline's device rate — the
    harness's tunneled attachment would otherwise measure the tunnel),
    and ``assembly_ops_per_sec`` through the REAL ShardedServing front
    door (submit → pack → feed → tick → pipelined harvest + durable log)
    including every host-side leg and transfer."""
    import jax

    from fluidframework_tpu.ops import matrix_kernel as mxk
    from fluidframework_tpu.ops import mergetree_kernel as mtk
    from fluidframework_tpu.ops import tree_kernel as tk
    from fluidframework_tpu.parallel.mesh import make_mesh
    from fluidframework_tpu.parallel.serving import ShardedServing
    from fluidframework_tpu.server import storm as storm_mod

    mesh = make_mesh(jax.devices()[:1])
    families = ["map", "text", "matrix", "tree"]
    fam_of = lambda row: families[row % 4]
    fam_k = {"map": map_k, "text": text_k, "matrix": matrix_k,
             "tree": tree_k}
    ops_per_tick = sum(fam_k[fam_of(r)] for r in range(num_docs))
    text_slots = 2 * text_k * ticks + 64
    kwargs = dict(
        num_docs=num_docs, k=map_k, num_hosts=1, num_clients=2,
        map_slots=32, text_slots=text_slots, text_k=text_k,
        matrix_vec_slots=4 * ticks + 16, matrix_cell_slots=256,
        matrix_k=matrix_k, tree_slots=2 * tree_k, tree_k=tree_k)

    rng = np.random.default_rng(11)

    def text_ops(t: int) -> list[dict]:
        ops = [dict(kind=mtk.MT_INSERT, pos=0, text="ab")
               for _ in range(text_k - 8)]
        ops += [dict(kind=mtk.MT_REMOVE, pos=i, end=i + 1)
                for i in range(4)]
        ops += [dict(kind=mtk.MT_ANNOTATE, pos=0, end=2, prop_key=1,
                     prop_val=t + 1) for _ in range(4)]
        return ops

    def matrix_ops(t: int) -> list[dict]:
        ops = [dict(target=mxk.MX_ROWS, kind=mtk.MT_INSERT, pos=0,
                    count=1),
               dict(target=mxk.MX_COLS, kind=mtk.MT_INSERT, pos=0,
                    count=1)]
        ops += [dict(target=mxk.MX_CELL, row=rng.integers(0, t + 1),
                     col=rng.integers(0, t + 1),
                     value=int(rng.integers(1, 1 << 16)))
                for _ in range(matrix_k - 2)]
        return ops

    def tree_ops(t: int) -> list[dict]:
        if t == 0:
            return [dict(kind=tk.TREE_INSERT, node=i + 1, parent=0,
                         trait=1, payload=i) for i in range(tree_k)]
        return [dict(kind=tk.TREE_SET_VALUE, node=i + 1,
                     payload=t * 100 + i) for i in range(tree_k)]

    # Script ONE canonical per-family tick sequence (rows of a family
    # see identical traffic — the batch axis is the scale dimension) and
    # build the full-tick device inputs for the staged-rate measurement.
    pack_fields = {"text": storm_mod.TEXT_PACK,
                   "matrix": storm_mod.MATRIX_PACK,
                   "tree": storm_mod.TREE_PACK}
    fam_rows = {f: np.array([r for r in range(num_docs)
                             if fam_of(r) == f]) for f in families}

    def encode(fam, ops, handle_next, pool_len):
        planes = {name: np.zeros(fam_k[fam], np.int32)
                  for name in pack_fields[fam][1:]}
        for i, op in enumerate(ops):
            op = dict(op)
            if fam == "text" and op.get("kind") == mtk.MT_INSERT:
                text = op.pop("text")
                op["pool_start"] = pool_len
                op["text_len"] = len(text)
                pool_len += len(text)
            if (fam == "matrix"
                    and op.get("target") in (mxk.MX_ROWS, mxk.MX_COLS)
                    and op.get("kind") == mtk.MT_INSERT):
                op["handle_base"] = handle_next
                handle_next += op.get("count", 1)
            for name in planes:
                planes[name][i] = op.get(name, 0)
        return planes, handle_next, pool_len

    batches_host = []
    tick_meta = []  # per tick: {fam: (planes, text_blob)}
    state_script = dict(handle=0, pool=0, cseq={f: 0 for f in families},
                        ref={f: 1 for f in families})
    for t in range(ticks):
        scalars = np.zeros((num_docs, 6), np.int32)
        map_words = np.zeros((num_docs, map_k), np.uint32)
        packs = {f: np.zeros((num_docs, len(pack_fields[f]), fam_k[f]),
                             np.int32) for f in ("text", "matrix", "tree")}
        words = (rng.integers(0, 1 << 20, map_k).astype(np.uint32) << 12
                 | (rng.integers(0, 32, map_k).astype(np.uint32) << 2))
        per_fam = {}
        blob = ""
        for fam in families:
            if fam == "map":
                per_fam[fam] = words
                continue
            ops = {"text": text_ops, "matrix": matrix_ops,
                   "tree": tree_ops}[fam](t)
            if fam == "text":
                planes, _, new_pool = encode(fam, ops, 0,
                                             state_script["pool"])
                blob = "ab" * (text_k - 8)
            elif fam == "matrix":
                planes, state_script["handle"], _ = encode(
                    fam, ops, state_script["handle"], 0)
            else:
                planes, _, _ = encode(fam, ops, 0, 0)
            if "ref_seq" in planes:
                planes["ref_seq"][:len(ops)] = state_script["ref"][fam]
            per_fam[fam] = planes
        state_script["pool"] += len(blob)
        for fam in families:
            rows = fam_rows[fam]
            n = fam_k[fam]
            scalars[rows, 1] = state_script["cseq"][fam] + 1
            scalars[rows, 2] = state_script["ref"][fam]
            scalars[rows, 3] = 2 + t
            scalars[rows, 4] = n
            if fam == "map":
                scalars[rows, 5] = n
                map_words[rows] = per_fam[fam]
            else:
                packs[fam][rows, 0, :n] = 1
                for i, name in enumerate(pack_fields[fam][1:]):
                    packs[fam][rows, i + 1, :n] = per_fam[fam][name]
            state_script["cseq"][fam] += n
            state_script["ref"][fam] = 1 + state_script["cseq"][fam]
        batches_host.append((scalars, map_words, packs["text"],
                             packs["matrix"], packs["tree"]))
        tick_meta.append((per_fam, blob))

    # -- (a) staged device rate ------------------------------------------------
    from fluidframework_tpu.server.storm import _mixed_tick
    mixed_nodonate = jax.jit(_mixed_tick.__wrapped__)

    def fresh_states():
        serving = ShardedServing(mesh, **kwargs)
        serving.join_all()
        return (serving.seq_state, serving.map_state, serving.merge_state,
                serving.matrix_state, serving.tree_state)

    state0 = fresh_states()
    # The measured series must never replay a consumed cseq window — the
    # device deli dedups it and the tick degenerates to a no-op (every
    # tick must sequence AND apply real ops). Payload planes cycle (the
    # apply cost is shape-driven), but the sequencer scalars are distinct
    # closed-form per tick: cseq/ref advance by the family width each
    # tick, exactly as the 12 scripted ticks do.
    payloads = [tuple(jax.device_put(a) for a in b[1:])
                for b in batches_host]

    def scalars_for(t: int) -> np.ndarray:
        s = np.zeros((num_docs, 6), np.int32)
        for fam in families:
            rows, n = fam_rows[fam], fam_k[fam]
            s[rows, 1] = t * n + 1
            s[rows, 2] = t * n + 1
            s[rows, 3] = 2 + t
            s[rows, 4] = n
            if fam == "map":
                s[rows, 5] = n
        return s

    series_len = 200  # >= latency series + pipeline series + max depth
    batches = [(jax.device_put(scalars_for(t)),)
               + payloads[t % len(payloads)] for t in range(series_len)]

    def apply(states, batch):
        out = mixed_nodonate(*states, *batch)
        return out[:5]

    out = _run_device(apply, state0, batches, ops_per_tick, passes=1)

    # -- (b) the REAL front door (submit → pack → feed → tick → harvest) -------
    serving = ShardedServing(mesh, pipeline_depth=4, **kwargs)
    serving.join_all()
    # Warm the trace with tick 0 (untimed), then time the remainder.
    def play(serving, t):
        per_fam, blob = tick_meta[t]
        for fam in families:
            rows = fam_rows[fam]
            n = fam_k[fam]
            cseq0 = t * n + 1
            ref = 1 + t * n
            if fam == "map":
                for row in rows:
                    serving.submit(row, per_fam[fam], cseq0, ref)
            else:
                for row in rows:
                    serving.submit_planes(
                        int(row), fam, per_fam[fam], n, cseq0, ref,
                        text=blob if fam == "text" else "")
        return serving.tick()

    play(serving, 0)
    serving.flush()
    start = time.perf_counter()
    for t in range(1, ticks):
        play(serving, t)
    serving.flush()
    elapsed = time.perf_counter() - start
    out["assembly_ops_per_sec"] = ops_per_tick * (ticks - 1) / elapsed
    out["assembly_tick_ms"] = 1000.0 * elapsed / (ticks - 1)
    out["num_docs"] = num_docs
    out["population"] = {f: int(len(fam_rows[f])) for f in families}
    out["ops_per_tick_by_family"] = {
        f: int(len(fam_rows[f])) * fam_k[f] for f in families}
    # Durable log covered every tick for every row (scriptorium leg).
    out["durable_records"] = int(sum(len(v) for v in serving.durable.values()))
    return out


def bench_matrix_config4(num_docs: int = 8192, grid: int = 1024,
                         n_writers: int = 256, k: int = 1024,
                         ticks: int = 6) -> dict:
    """BASELINE config 4 AT ITS STATED SHAPE: a 1k x 1k SharedMatrix with
    256 concurrent clients issuing cell writes (the grid settled, no
    structural ops in flight), device-served through the scan-free
    cell-run kernel (ops/matrix_kernel.apply_cell_run): one [R, S]
    handle-resolution pass per axis, then ONE [B, R]-tile append into
    the cell log at a shared offset. ``num_docs`` such matrices batch on
    the doc axis — every one is the stated 1k x 1k / 256-writer shape."""
    import jax
    import jax.numpy as jnp

    from fluidframework_tpu.ops import matrix_kernel as mxk
    from fluidframework_tpu.ops import mergetree_kernel as mtk

    rng = np.random.default_rng(4)
    state = mxk.init_state(num_docs, vec_slots=8,
                           cell_slots=2 * k * (ticks + 1))
    setup = [[dict(target=mxk.MX_ROWS, kind=mtk.MT_INSERT, pos=0,
                   count=grid, handle_base=0, seq=1, ref_seq=0, client=0),
              dict(target=mxk.MX_COLS, kind=mtk.MT_INSERT, pos=0,
                   count=grid, handle_base=0, seq=2, ref_seq=1, client=0)]
             for _ in range(num_docs)]
    state = mxk.apply_tick(state, mxk.make_matrix_op_batch(
        setup, num_docs, 2))

    batches = []
    seq0 = 3
    for t in range(ticks):
        run = mxk.CellRunBatch(
            valid=jnp.ones((num_docs, k), jnp.bool_),
            row=jnp.asarray(rng.integers(0, grid, (num_docs, k)),
                            jnp.int32),
            col=jnp.asarray(rng.integers(0, grid, (num_docs, k)),
                            jnp.int32),
            value=jnp.asarray(rng.integers(1, 1 << 20, (num_docs, k)),
                              jnp.int32),
            seq=jnp.asarray(
                np.broadcast_to(seq0 + t * k + np.arange(k, dtype=np.int32),
                                (num_docs, k)).copy()),
            ref_seq=jnp.full((num_docs,), seq0 + t * k - 1, jnp.int32),
            client=jnp.asarray(rng.integers(0, n_writers, num_docs),
                               jnp.int32),
        )
        batches.append(run)

    out = _run_device(mxk.apply_cell_run, state, batches, num_docs * k,
                      passes=4)
    # One clean pass from the setup state proves the stated shape fits
    # device capacity (the timed loops recycle batches purely for rate —
    # a full cell log clamps appends without changing the work).
    final = state
    for b in batches:
        final = mxk.apply_cell_run(final, b)
    m = mxk.capacity_margin(final)
    assert (m["cells"] > 0).all(), "config-4 bench overflowed the cell log"
    out["overflow_routed"] = 0

    # Scalar baseline: the same shape through the scalar engines —
    # PermutationVector.handle_at + LWW dict (the reference architecture
    # interpreted by CPython), measured on a slice and rate-normalized.
    from fluidframework_tpu.dds.matrix import PermutationVector
    rows_v, cols_v = PermutationVector(), PermutationVector()
    rows_v.apply_remote({"type": "insert", "pos": 0, "count": grid},
                        1, 0, "c0")
    cols_v.apply_remote({"type": "insert", "pos": 0, "count": grid},
                        2, 1, "c0")
    cells: dict = {}
    n_scalar = 50_000
    srows = rng.integers(0, grid, n_scalar)
    scols = rng.integers(0, grid, n_scalar)
    svals = rng.integers(1, 1 << 20, n_scalar)
    start = time.perf_counter()
    for i in range(n_scalar):
        rh = rows_v.handle_at(int(srows[i]), seq0 + i, "c1")
        ch = cols_v.handle_at(int(scols[i]), seq0 + i, "c1")
        if rh is not None and ch is not None:
            cells[(rh, ch)] = int(svals[i])
    out["scalar_python_ops_per_sec"] = n_scalar / (
        time.perf_counter() - start)
    out["num_docs"] = num_docs
    out["grid"] = f"{grid}x{grid}"
    out["n_writers"] = n_writers
    # Handle resolution (2 x [R, S]) + LWW sort + pack per cell.
    out["vpu_util_est"] = round(
        out["device_ops_per_sec"] * (2 * 8 + 60 + 40) / _VPU_PEAK_ELEMS, 4)
    return out


def _gen_matrix_stream(rng: random.Random, n_ops: int) -> list[dict]:
    from fluidframework_tpu.ops import matrix_kernel as mxk
    from fluidframework_tpu.ops import mergetree_kernel as mtk

    ops, rows, cols, next_rh, next_ch = [], 0, 0, 0, 0
    for seq in range(1, n_ops + 1):
        client = rng.randrange(8)
        base = dict(seq=seq, ref_seq=seq - 1, client=client)
        r = rng.random()
        if rows and cols and r < 0.7:
            ops.append(dict(base, target=mxk.MX_CELL,
                            row=rng.randrange(rows), col=rng.randrange(cols),
                            value=rng.randrange(1, 1000)))
        elif r < 0.8 or not rows:
            count = rng.randint(1, 2)
            ops.append(dict(base, target=mxk.MX_ROWS, kind=mtk.MT_INSERT,
                            pos=rng.randint(0, rows), count=count,
                            handle_base=next_rh))
            next_rh += count
            rows += count
        elif r < 0.9 or not cols:
            count = rng.randint(1, 2)
            ops.append(dict(base, target=mxk.MX_COLS, kind=mtk.MT_INSERT,
                            pos=rng.randint(0, cols), count=count,
                            handle_base=next_ch))
            next_ch += count
            cols += count
        elif rows > 2 and r < 0.95:
            pos = rng.randrange(rows - 1)
            ops.append(dict(base, target=mxk.MX_ROWS, kind=mtk.MT_REMOVE,
                            pos=pos, end=pos + 1))
            rows -= 1
        else:
            ops.append(dict(base, target=mxk.MX_CELL,
                            row=rng.randrange(max(rows, 1)),
                            col=rng.randrange(max(cols, 1)),
                            value=rng.randrange(1, 1000)))
    return ops


def bench_matrix(num_docs: int = 16384, k: int = 64, ticks: int = 6) -> dict:
    import jax.numpy as jnp

    from fluidframework_tpu.ops import matrix_kernel as mxk
    from fluidframework_tpu.ops import matrix_pallas as mxp

    rng = random.Random(0)
    stream = _gen_matrix_stream(rng, k * ticks)
    # STEP/RUN layout (matrix_kernel.MatrixStepBatch): consecutive cells
    # between vector ops share one visibility frame, so the two-axis
    # prefix scan is paid per RUN. Measured ~1.15x the per-op kernel at
    # this shape — the per-step floor (walk + two frame scans + the
    # per-cell table writes) bounds the win; both layouts stay
    # differentially pinned.
    batches = []
    lvs = [0]
    for t in range(ticks):
        chunk = [stream[t * k:(t + 1) * k]]
        steps = mxk.make_matrix_step_batch(chunk, 1, r_max=8,
                                           last_vec_seq=lvs)
        batches.append(type(steps)(
            *[jnp.asarray(_tile(np.asarray(f), num_docs))
              for f in steps]))
        for op in chunk[0]:
            if op["target"] != mxk.MX_CELL:
                lvs[0] = max(lvs[0], op["seq"])

    out = _run_device(mxp.apply_tick_steps_best,
                      mxk.init_state(num_docs, vec_slots=256, cell_slots=256),
                      batches, num_docs * k)
    out["kernel_path"] = ("xla_step_scan" if mxp.default_interpret()
                          else "pallas_vmem_steps")
    cpu_docs = 128
    cpu_batches = [type(b)(
        *[jnp.asarray(_tile(np.asarray(f)[:1], cpu_docs)) for f in b])
        for b in batches[:2]]  # _cpu_batched_rate uses two ticks
    out["xla_cpu_batched_ops_per_sec"] = _cpu_batched_rate(
        mxk.apply_tick_steps,
        mxk.init_state(cpu_docs, vec_slots=256, cell_slots=256),
        cpu_batches, cpu_docs * k)
    # Two embedded merge states (6 planes x 256 vec slots) + cell table.
    out["vpu_util_est"] = round(
        out["device_ops_per_sec"] * (2 * 6 * 256 + 4 * 256)
        / _VPU_PEAK_ELEMS, 4)

    # Scalar baseline: PermutationVectors + LWW cell dict (scalar engine).
    from fluidframework_tpu.dds.matrix import PermutationVector
    reps = 20
    start = time.perf_counter()
    for _ in range(reps):
        rows_v, cols_v = PermutationVector(), PermutationVector()
        cells: dict = {}
        for op in stream:
            client = f"c{op['client']}"
            if op["target"] == mxk.MX_CELL:
                rh = rows_v.handle_at(op["row"], op["ref_seq"], client)
                ch = cols_v.handle_at(op["col"], op["ref_seq"], client)
                if rh is not None and ch is not None:
                    cells[(rh, ch)] = op["value"]
            else:
                vec = rows_v if op["target"] == mxk.MX_ROWS else cols_v
                if "count" in op and op.get("kind") == 0:
                    vec.apply_remote(
                        {"type": "insert", "pos": op["pos"],
                         "count": op["count"]},
                        op["seq"], op["ref_seq"], client)
                else:
                    vec.apply_remote(
                        {"type": "remove", "start": op["pos"],
                         "end": op["end"]},
                        op["seq"], op["ref_seq"], client)
    elapsed = time.perf_counter() - start
    out["scalar_python_ops_per_sec"] = len(stream) * reps / elapsed
    out["num_docs"] = num_docs
    return out


# -- config 5: tree -----------------------------------------------------------


def _gen_tree_stream(rng: random.Random, n_ops: int,
                     num_slots: int) -> list[dict]:
    from fluidframework_tpu.ops import tree_kernel as tk

    ops = []
    existing = [0]
    free = list(range(1, num_slots))
    for _ in range(n_ops):
        r = rng.random()
        if free and (r < 0.45 or len(existing) < 3):
            slot = free.pop(0)
            ops.append(dict(kind=tk.TREE_INSERT, node=slot,
                            parent=rng.choice(existing),
                            payload=rng.randrange(1, 1000)))
            existing.append(slot)
        elif r < 0.9:
            ops.append(dict(kind=tk.TREE_SET_VALUE,
                            node=rng.choice(existing),
                            payload=rng.randrange(1, 1000)))
        else:
            victims = [s for s in existing if s != 0]
            if not victims:
                continue
            node = rng.choice(victims)
            ops.append(dict(kind=tk.TREE_DETACH, node=node))
            # Conservative host view: only drop the node itself (the device
            # drops the subtree; later ops on orphans just mask invalid).
            existing.remove(node)
    return ops


def bench_tree(num_docs: int = 8192, k: int = 32, ticks: int = 6,
               num_slots: int = 256) -> dict:
    import jax.numpy as jnp

    from fluidframework_tpu.ops import tree_kernel as tk

    rng = random.Random(0)
    stream = _gen_tree_stream(rng, k * ticks, num_slots)
    batches = []
    for t in range(ticks):
        one = tk.make_tree_op_batch([stream[t * k:(t + 1) * k]], 1, k)
        batches.append(tk.TreeOpBatch(
            *[jnp.asarray(_tile(np.asarray(f), num_docs)) for f in one]))

    def apply(state, batch):
        new_state, _applied = tk.apply_tick(state, batch)
        return new_state

    out = _run_device(apply, tk.init_state(num_docs, num_slots), batches,
                      num_docs * k)
    cpu_docs = 128
    cpu_batches = [tk.TreeOpBatch(
        *[jnp.asarray(_tile(np.asarray(f)[:1], cpu_docs)) for f in b])
        for b in batches[:2]]  # _cpu_batched_rate uses two ticks
    out["xla_cpu_batched_ops_per_sec"] = _cpu_batched_rate(
        apply, tk.init_state(cpu_docs, num_slots), cpu_batches,
        cpu_docs * k)
    # 5 planes of N node slots + the [N, N] one-hot subtree matvec on
    # detach/move ops (amortized ~N/4 per op in this mix).
    out["vpu_util_est"] = round(
        out["device_ops_per_sec"]
        * (5 * num_slots + num_slots * num_slots // 4)
        / _VPU_PEAK_ELEMS, 4)

    # Scalar baseline: the same ops through the scalar Transaction.
    from tests.test_tree_kernel import scalar_apply
    from fluidframework_tpu.dds.tree_core import ROOT_ID, TreeSnapshot
    slot_names = {0: ROOT_ID, **{i: f"s{i}" for i in range(1, num_slots)}}
    reps = 3
    start = time.perf_counter()
    for _ in range(reps):
        scalar_apply(TreeSnapshot(), stream, slot_names)
    elapsed = time.perf_counter() - start
    out["scalar_python_ops_per_sec"] = len(stream) * reps / elapsed
    out["num_docs"] = num_docs
    return out


# -- end-to-end: the serving path ---------------------------------------------

_STORM_CLIENT_SRC = r"""
import json, socket, struct, sys, time
import numpy as np

cfg = json.loads(sys.stdin.readline())
sock = socket.create_connection(("127.0.0.1", cfg["port"]))
rng = np.random.default_rng(cfg["seed"])
docs = cfg["docs"]  # [[doc_id, client_id], ...]
k = cfg["k"]
trace_every = cfg.get("trace_every", 0)
cseqs = {d: c0 for (d, _cl), c0 in zip(docs, cfg["cseq0"])}

def frame(rid):
    # (bytes, tc): every trace_every-th frame carries a sampled trace
    # id ("tc" header field); the server timestamps it at every hop and
    # the traced ack carries the joined marks back.
    hdr_docs, chunks = [], []
    for doc_id, client_id in docs:
        kinds = rng.choice([0, 0, 0, 1, 2], size=k).astype(np.uint32)
        slots = rng.integers(0, cfg["num_slots"], k).astype(np.uint32)
        vals = rng.integers(0, 1 << 20, k).astype(np.uint32)
        chunks.append(kinds | (slots << 2) | (vals << 12))
        hdr_docs.append([doc_id, client_id, cseqs[doc_id], 1, k])
        cseqs[doc_id] += k
    header = {"op": "storm", "rid": rid, "docs": hdr_docs}
    tc = None
    if trace_every and rid % trace_every == 0:
        tc = cfg["seed"] * 1_000_000 + rid
        header["tc"] = tc
    head = json.dumps(header, separators=(",", ":")).encode()
    body = (bytes((0, 1)) + struct.pack("<I", len(head)) + head
            + b"".join(c.tobytes() for c in chunks))
    return struct.pack(">I", len(body)) + body, tc

def recv_exact(n):
    raw = b""
    while len(raw) < n:
        chunk = sock.recv(n - len(raw))
        if not chunk:
            raise SystemExit("server closed the connection")
        raw += chunk
    return raw

def read_ack():
    length = struct.unpack(">I", recv_exact(4))[0]
    body = recv_exact(length)
    if body[:1] == b"\x00":
        # Binary columnar storm ack: header JSON + i32[n,4] rows. The
        # client only needs the header (no per-doc JSON parse on the
        # ack path).
        hlen = struct.unpack_from("<I", body, 2)[0]
        hdr = json.loads(body[6:6 + hlen].decode())
        if hdr.get("op") == "storm_ack":
            hdr["storm"] = True
        return hdr
    return json.loads(body.decode())

frames = [frame(t) for t in range(cfg["ticks"])]  # pre-built, untimed
window = cfg.get("window", 0)
print("READY", flush=True)
assert sys.stdin.readline().strip() == "GO"
t0 = time.perf_counter()
send_ns = {}
ack_times, acked, hop_rows, nacked = [], 0, [], 0
# Windowed flow control (round 14): at most `window` frames in flight,
# keyed off the ack stream — measured ack latency is then SERVER
# latency, not the client's own send backlog (BENCH_r10 put 4.0s of
# "latency" in client-side send->ingress queueing). window <= 0 keeps
# the legacy blast-everything shape (the A/B baseline). A busy-nack
# frees its window slot but the frame resends after the hint — it was
# never sequenced, so it must never count toward the acked total.
to_send = list(range(cfg["ticks"]))
inflight = 0
while acked < cfg["ticks"]:
    if to_send and (window <= 0 or inflight < window):
        data, tc = frames[to_send.pop(0)]
        if tc is not None:
            send_ns[tc] = time.monotonic_ns()  # server hops share clock
        sock.sendall(data)
        inflight += 1
        continue
    ack = read_ack()
    rx_ns = time.monotonic_ns()
    if not ack.get("storm"):
        continue
    inflight -= 1
    if ack.get("error"):
        nacked += 1
        time.sleep(float(ack.get("retry_after_s", 0.01)))
        to_send.append(int(ack["rid"]))
        continue
    acked += 1
    ack_times.append(time.perf_counter() - t0)
    tc, hops = ack.get("tc"), ack.get("hops")
    if tc in send_ns and hops:
        # End-to-end join: client send -> server hop marks -> client
        # rx, one monotonic clock domain (same host), ms per hop.
        marks = ([("client_send", send_ns.pop(tc))]
                 + list(hops.items()) + [("client_rx", rx_ns)])
        hop_rows.append({"%s_to_%s" % (a, b): (tb - ta) / 1e6
                         for (a, ta), (b, tb) in zip(marks, marks[1:])})
print(json.dumps({"elapsed": time.perf_counter() - t0,
                  "ack_times": ack_times, "hop_rows": hop_rows,
                  "nacked": nacked}),
      flush=True)
"""


def bench_e2e_storm(num_docs: int = 10_240, k: int = 512, ticks: int = 10,
                    n_conns: int = 8, num_slots: int = 32,
                    durability: str | None = None,
                    spill_dir: str | None = None,
                    trace_every: int = 0,
                    pipeline_depth: int = 1,
                    window: int = 0) -> dict:
    """End-to-end merged-ops/sec through the REAL serving path: client
    processes → framed TCP → C++ bridge front door → alfred dispatch →
    deli (device sequencer kernel, full NACK/MSN semantics) → merger (map
    kernel fold, fused with the ticket seqs) → durable columnar op log +
    fan-out publish + acks back over the wire. Contrast with the
    kernel-only map number: this pays framing, sockets, host scatter,
    host→device transfer and durability on every tick."""
    import subprocess

    from fluidframework_tpu.native.bridge import _load_library
    if _load_library() is None:
        # Fail-soft: the e2e path NEEDS the C++ bridge; report the skip
        # instead of crashing the whole bench run.
        return {"skipped": "no C++ toolchain / prebuilt native bridge"}

    from fluidframework_tpu.native.fanout import make_fanout
    from fluidframework_tpu.server.bridge_host import BridgeFrontDoor
    from fluidframework_tpu.server.kernel_host import KernelSequencerHost
    from fluidframework_tpu.server.merge_host import KernelMergeHost
    from fluidframework_tpu.server.routerlicious import RouterliciousService
    from fluidframework_tpu.server.storm import StormController

    seq_host = KernelSequencerHost(num_slots=2, initial_capacity=num_docs)
    merge_host = KernelMergeHost(map_slots=num_slots, row_capacity=num_docs,
                                 flush_threshold=10**9)
    service = RouterliciousService(merge_host=merge_host,
                                   batched_deli_host=seq_host,
                                   auto_pump=False, fanout=make_fanout())
    # Durability column: None = in-RAM tick records (no WAL);
    # "group" = the async group-commit WAL (acks withheld until fsync —
    # the crash-safe production shape); "sync"/"none" = inline append
    # with/without per-tick fsync ("none" is the round-5 shape whose
    # synchronous serialize+append sat on the harvest path).
    owned_spill = None
    if durability is not None and spill_dir is None:
        import tempfile
        spill_dir = owned_spill = tempfile.mkdtemp(prefix="storm-bench-")
    storm = StormController(service, seq_host, merge_host,
                            flush_threshold_docs=num_docs,
                            spill_dir=spill_dir,
                            pipeline_depth=pipeline_depth,
                            durability=durability or "none")
    front = BridgeFrontDoor(service, 0)

    # Setup (untimed): one writer joins per document through the service
    # front door; the joins sequence through the batched deli host.
    docs = [f"storm-doc-{i}" for i in range(num_docs)]
    clients = {}
    for d in docs:
        clients[d] = service.connect(d, lambda msgs: None).client_id
    service.pump()

    # Warm-up (untimed): one full-shape tick compiles the fused program.
    rng = np.random.default_rng(123)
    chunks = []
    hdr_docs = []
    for d in docs:
        chunks.append(rng.integers(0, 1 << 20, k).astype(np.uint32) << 12)
        hdr_docs.append([d, clients[d], 1, 1, k])
    storm.submit_frame(None, {"op": "storm", "docs": hdr_docs},
                       memoryview(b"".join(c.tobytes() for c in chunks)))
    storm.flush()
    assert storm.stats["sequenced_ops"] == num_docs * k
    storm.tick_seconds.clear()
    storm.harvest_intervals.clear()
    storm.ledger.clear()  # the compile tick would skew attribution
    storm._last_harvest = None  # the client-setup gap is not a cadence

    # Timed run: client processes (no GIL sharing with the server) send
    # `ticks` frames each, pipelined; every doc's tick needs all conns.
    per_conn = num_docs // n_conns
    procs = []
    for c in range(n_conns):
        conn_docs = docs[c * per_conn:(c + 1) * per_conn]
        proc = subprocess.Popen(
            [sys.executable, "-c", _STORM_CLIENT_SRC],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
        proc.stdin.write(json.dumps({
            "port": front.port, "k": k, "ticks": ticks, "seed": c,
            "num_slots": num_slots, "trace_every": trace_every,
            "window": window,
            "docs": [[d, clients[d]] for d in conn_docs],
            "cseq0": [k + 1] * len(conn_docs),
        }) + "\n")
        proc.stdin.flush()
        procs.append(proc)
    for proc in procs:
        assert proc.stdout.readline().strip() == "READY"
    before = storm.stats["sequenced_ops"]
    ticks_before = storm.stats["ticks"]
    start = time.perf_counter()
    for proc in procs:
        proc.stdin.write("GO\n")
        proc.stdin.flush()
    results = [json.loads(proc.stdout.readline()) for proc in procs]
    elapsed = time.perf_counter() - start
    for proc in procs:
        proc.wait(timeout=30)
    sequenced = storm.stats["sequenced_ops"] - before
    tick_ms = 1000.0 * np.asarray(storm.tick_seconds)
    ack_gaps = []
    for res in results:
        times = [0.0] + res["ack_times"]
        ack_gaps.extend(b - a for a, b in zip(times, times[1:]))
    front.close()  # freerun below DONATES the live host states

    # Measure the host->device link (the axon tunnel in this harness):
    # every e2e tick must move 4 bytes/op across it, so link_MBps/4 is an
    # absolute ops/s ceiling FOR THIS ATTACHMENT — a locally-attached
    # chip (PCIe, GB/s) lifts it by two orders of magnitude.
    import jax

    probe = np.zeros((num_docs, k), np.uint32)
    put_times = []
    for _ in range(3):
        t0 = time.perf_counter()
        arr = jax.device_put(probe)
        np.asarray(arr[0, 0])
        put_times.append(time.perf_counter() - t0)
    link_mbps = probe.nbytes / 1e6 / min(put_times)

    # Device-only freerun of the SAME fused program (deli + merger) with
    # inputs resident: what this serving tick does when the link is not
    # the bottleneck.
    import jax.numpy as jnp

    from fluidframework_tpu.server.storm import _storm_tick
    b_seq = seq_host._capacity
    b_map = merge_host._map_capacity
    rng2 = np.random.default_rng(7)
    fr_words = jnp.asarray(
        rng2.integers(0, 1 << 20, (b_map, k)).astype(np.uint32) << 12)
    fr_counts = jnp.asarray(
        np.where(np.arange(b_seq) < num_docs, k, 0).astype(np.int32))
    fr_slot = jnp.zeros(b_seq, jnp.int32)
    fr_ref = jnp.ones(b_seq, jnp.int32)
    fr_ts = jnp.full(b_seq, 1, jnp.int32)
    fr_gather = jnp.arange(b_map, dtype=jnp.int32)
    ss, ms = seq_host._state, merge_host._xstate
    cseq = int(1e6)
    # Enough chained reps that the single end-of-chain sync RTT (~120ms
    # through the tunnel) amortizes below the per-tick device time.
    reps = 24
    # Prestage EVERY per-rep input: a jnp.full inside the timed loop is
    # its own device dispatch, and on a tunneled attachment each costs
    # ~a full RTT — it would measure the tunnel, not the tick.
    cseqs = [jnp.asarray(np.full(b_seq, cseq + r * k, np.int32))
             for r in range(reps + 1)]
    res = _storm_tick(ss, ms, fr_slot, cseqs[0],
                      fr_ref, fr_ts, fr_counts, fr_gather, fr_words,
                      fr_counts[:b_map])
    ss, ms = res[0], res[1]
    np.asarray(res[2][0])
    t0 = time.perf_counter()
    for r in range(reps):
        res = _storm_tick(ss, ms, fr_slot, cseqs[r + 1], fr_ref, fr_ts,
                          fr_counts, fr_gather, fr_words,
                          fr_counts[:b_map])
        ss, ms = res[0], res[1]
    np.asarray(res[2][0])
    fused_rate = num_docs * k * reps / (time.perf_counter() - t0)

    cadence_ms = 1000.0 * np.asarray(storm.harvest_intervals or [0.0])
    out = {
        "durability": durability if durability is not None else "off",
        "e2e_ops_per_sec": sequenced / elapsed,
        "sequenced_ops": sequenced,
        "elapsed_s": elapsed,
        "link_MBps_measured": round(link_mbps, 1),
        "link_implied_ops_ceiling": round(link_mbps * 1e6 / 4, 1),
        "fused_tick_device_ops_per_sec": round(fused_rate, 1),
        "tick_ms_p50": float(np.percentile(tick_ms, 50)),
        "tick_ms_p99": float(np.percentile(tick_ms, 99)),
        # Completion cadence under the depth-N harvest pipeline — the
        # storm-path per-tick latency once the transport RTT is hidden
        # behind in-flight ticks (submit→harvest above includes it).
        "tick_cadence_ms_p50": float(np.percentile(cadence_ms, 50)),
        "tick_cadence_ms_p99": float(np.percentile(cadence_ms, 99)),
        "ack_interval_ms_p50": float(np.percentile(ack_gaps, 50)) * 1000,
        "ack_interval_ms_p99": float(np.percentile(ack_gaps, 99)) * 1000,
        # Fraction of serving-path channel ops that ran on the scalar
        # fallback (0.0 = fully device-served) — the silent-degradation
        # gauge (VERDICT r3 weak #6).
        "scalar_fraction": merge_host.scalar_fraction(),
        "num_docs": num_docs,
        "ops_per_tick": num_docs * k,
        "ticks": int(storm.stats["ticks"] - ticks_before),
        "trace_every": trace_every,
        "pipeline_depth": pipeline_depth,
        "client_window": window,
        "path": "client procs -> TCP -> C++ bridge -> alfred -> "
                "sequencer kernel -> map kernel (fused) -> durable log "
                "+ fanout + acks",
    }
    out["fraction_of_link_ceiling"] = round(
        out["e2e_ops_per_sec"] / out["link_implied_ops_ceiling"], 3)
    # Stage-attribution columns (the round-10 ledger): per-stage share of
    # the tick's attributed time + p50/p99 over the measured window.
    out["stage_attribution"] = storm.ledger.attribution()
    # Sampled per-op hop decomposition of ack latency: client send →
    # bridge ingress → admit → dispatch → sequenced → durable → ack tx →
    # client rx, joined across processes in one monotonic clock domain.
    hop_rows = [r for res in results for r in res.get("hop_rows", [])]
    if hop_rows:
        from fluidframework_tpu.utils.metrics import percentile
        names = sorted({name for r in hop_rows for name in r})
        decomp = {}
        for name in names:
            vals = sorted(r[name] for r in hop_rows if name in r)
            decomp[name] = {
                "p50_ms": round(percentile(vals, 0.50), 3),
                "p99_ms": round(percentile(vals, 0.99), 3),
                "count": len(vals)}
        out["ack_hop_decomposition_ms"] = decomp
    # The WAL writer thread/fd and the bench's own tick blobs (~hundreds
    # of MB at this shape) must not outlive the row.
    if storm._group_wal is not None:
        storm._group_wal.close()
    elif storm._blob_log is not None:
        storm._blob_log.close()
    if owned_spill is not None:
        import shutil
        shutil.rmtree(owned_spill, ignore_errors=True)
    return out


def emit_round14(path: str = "BENCH_r14.json") -> dict:
    """ISSUE 11 acceptance bars: the PIPELINED durable serving tick
    (tick N+1's scatter+dispatch overlapping tick N's group fsync;
    acks still withheld on the durable watermark) plus client windowed
    flow control, A/B'd against the unpipelined serial fallback
    (pipeline_depth=0, blast-all clients — the BENCH_r10 sequential
    shape) at the same 10k-doc durable-ON CPU shape. Columns: the r10
    stage attribution plus wall_ms/overlap_ms (the ledger no longer
    double-counts concurrent commit-wait and dispatch), pipeline depth,
    and the ack-hop decomposition — send→ingress must collapse from
    r10's 4.0s client backlog to below the flow-control window bound
    (window × tick cadence). Fail-soft without the native bridge."""
    import jax

    from fluidframework_tpu.utils import compile_cache

    compile_cache.enable()
    backend = jax.default_backend()
    out: dict = {"round": 14, "environment": {"backend": backend}}
    #: BENCH_r10's recorded durable-ON 10k-doc rate (its machine) — the
    #: cross-round reference; the same-machine bar is the A/B ratio.
    r10_rate = 3_976_925.5
    pipe = bench_e2e_storm(durability="group", trace_every=4,
                           pipeline_depth=1, window=2)
    out["e2e_storm_10k_docs_pipelined"] = pipe
    skipped = "skipped" in pipe
    if not skipped:
        base = bench_e2e_storm(durability="group", trace_every=4,
                               pipeline_depth=0, window=0)
        out["e2e_storm_10k_docs_unpipelined"] = base
        out["pipelined_vs_unpipelined"] = round(
            pipe["e2e_ops_per_sec"] / base["e2e_ops_per_sec"], 3)
        out["vs_bench_r10_recorded"] = round(
            pipe["e2e_ops_per_sec"] / r10_rate, 3)
        # The honest ceiling: durable e2e cannot exceed the device-only
        # fused-tick rate on the same attachment — report how much of
        # it each arm converts (r10 converted 0.643 on an identical
        # 6.18M device rate; a 1.7x-of-r10 target would EXCEED the
        # device rate at this shape, so the fraction is the bounded
        # figure of merit).
        out["pipelined_fraction_of_device_rate"] = round(
            pipe["e2e_ops_per_sec"]
            / pipe["fused_tick_device_ops_per_sec"], 3)
        out["unpipelined_fraction_of_device_rate"] = round(
            base["e2e_ops_per_sec"]
            / base["fused_tick_device_ops_per_sec"], 3)
        win = pipe["stage_attribution"]["_window"]
        out["overlap_ms"] = win.get("overlap_ms", 0.0)
        out["wall_ms"] = win.get("wall_ms", 0.0)
        # Flow-control evidence: a frame waits at most ~window ticks
        # client-side before the bridge ingests it, so send→ingress must
        # sit BELOW window × tick cadence — versus r10's 4.0s unbounded
        # blast backlog at a 1.2s cadence.
        hop = pipe.get("ack_hop_decomposition_ms", {}).get(
            "client_send_to_ingress", {})
        bound_ms = pipe["client_window"] * pipe["tick_cadence_ms_p50"]
        out["send_to_ingress_p50_ms"] = hop.get("p50_ms")
        out["flow_control_window_bound_ms"] = round(bound_ms, 1)
        out["send_to_ingress_below_bound"] = (
            hop.get("p50_ms") is not None
            and hop["p50_ms"] < bound_ms)
        # Depth scaling at the r07-comparability shape: serial (0) vs
        # overlapped (1) vs deeper (2) — where the next win would come
        # from (or that depth 1 already saturates the overlap).
        depth_rows = {}
        for depth, win_sz in ((0, 0), (1, 2), (2, 3)):
            depth_rows[f"depth_{depth}"] = bench_e2e_storm(
                num_docs=2048, k=256, ticks=8, n_conns=4,
                durability="group", pipeline_depth=depth, window=win_sz)
        out["e2e_storm_cpu_2048x256_depth_scaling"] = {
            name: {"e2e_ops_per_sec": round(r["e2e_ops_per_sec"], 1),
                   "tick_cadence_ms_p50": round(
                       r["tick_cadence_ms_p50"], 1),
                   "overlap_ms": r["stage_attribution"]["_window"].get(
                       "overlap_ms", 0.0),
                   "client_window": r["client_window"]}
            for name, r in depth_rows.items() if "skipped" not in r}
        out["environment"]["note"] = (
            "Backend %s. Round-14 tentpole: the durable serving tick is "
            "PIPELINED — harvest-first rounds start tick N's WAL append "
            "(and group fsync, on the writer thread) the moment its "
            "readback lands, so the fsync runs concurrent with tick "
            "N+1's scatter+dispatch into a double-buffered staging "
            "generation; acks stay withheld on the durable watermark "
            "(lagging dispatch by <= depth ticks). Clients run windowed "
            "flow control (bounded in-flight frames keyed off the ack "
            "stream; busy-nacks free the slot but arm a retry_after_s "
            "backoff and never count as acked). stage_attribution now "
            "carries wall_ms/overlap_ms per window — summing concurrent "
            "wal_commit_wait and device_dispatch would double-count, so "
            "overlap_ms is reported explicitly instead. The A/B twin "
            "(pipeline_depth=0, window=0) is the fully-serial "
            "dispatch->readback->fsync->ack shape; r10's recorded code "
            "sat between the arms (its harvest lagged one dispatch, so "
            "the fsync started a full device-dispatch late). Durable "
            "e2e is bounded by the device-only fused rate — identical "
            "to r10's machine here (~6.2M ops/s CPU) — so the bounded "
            "figure of merit is fraction_of_device_rate, not a raw "
            "multiple of the r10 number (1.7x of r10 would exceed the "
            "device rate at this shape). At small shapes (the depth-"
            "scaling rows) blobs are small and the fsync cheap, so the "
            "serial arm wins there: pipelining pays where the commit "
            "is commensurate with the dispatch, exactly the 10k shape."
            % backend)
    else:
        out["environment"]["note"] = (
            "native bridge unavailable; e2e rows skipped (fail-soft)")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    return out


# -- sequencer ----------------------------------------------------------------


def bench_sequencer(num_docs: int = 10_240, k: int = 64,
                    ticks: int = 6) -> dict:
    import jax.numpy as jnp

    from fluidframework_tpu.ops import sequencer as seqk
    from fluidframework_tpu.ops import sequencer_pallas as seqp
    from fluidframework_tpu.protocol.messages import MessageType

    n_clients = 4
    stream: list[dict] = [
        dict(kind=int(MessageType.CLIENT_JOIN), slot=-1, target=c,
             timestamp=c + 1) for c in range(n_clients)]
    cseq = [0] * n_clients
    seq_guess = n_clients
    for i in range(k * ticks - n_clients):
        c = i % n_clients
        cseq[c] += 1
        stream.append(dict(kind=int(MessageType.OPERATION), slot=c,
                           client_seq=cseq[c],
                           ref_seq=max(1, seq_guess - rngless(i)),
                           timestamp=n_clients + i + 1))
        seq_guess += 1

    batches = []
    for t in range(ticks):
        one = seqk.make_op_batch([stream[t * k:(t + 1) * k]], 1, k)
        batches.append(seqk.OpBatch(
            *[jnp.asarray(_tile(np.asarray(f), num_docs)) for f in one]))

    def apply(state, batch):
        new_state, _tickets = seqp.process_batch_best(state, batch)
        return new_state

    out = _run_device(apply, seqk.init_state(num_docs, n_clients + 4),
                      batches, num_docs * k)
    out["kernel_path"] = ("xla_scan" if seqp.default_interpret()
                          else "pallas_vmem")
    cpu_docs = 256
    cpu_batches = [seqk.OpBatch(
        *[jnp.asarray(_tile(np.asarray(f)[:1], cpu_docs)) for f in b])
        for b in batches[:2]]  # _cpu_batched_rate uses two ticks

    def cpu_apply(state, batch):
        new_state, _t = seqk.process_batch(state, batch)
        return new_state

    out["xla_cpu_batched_ops_per_sec"] = _cpu_batched_rate(
        cpu_apply, seqk.init_state(cpu_docs, n_clients + 4), cpu_batches,
        cpu_docs * k)
    # Per op: the ticket state machine over C client lanes (~12 planes).
    out["vpu_util_est"] = round(
        out["device_ops_per_sec"] * 12 * (n_clients + 4)
        / _VPU_PEAK_ELEMS, 4)

    # Scalar baseline: the deli ticket loop.
    from fluidframework_tpu.protocol.messages import ClientDetail
    from fluidframework_tpu.server.sequencer import (
        DocumentSequencer, RawOperation)
    reps = 5
    start = time.perf_counter()
    for _ in range(reps):
        ds = DocumentSequencer()
        for op in stream:
            if op["kind"] == int(MessageType.CLIENT_JOIN):
                ds.ticket(RawOperation(
                    client_id=None, type=MessageType.CLIENT_JOIN,
                    data=ClientDetail(client_id=f"c{op['target']}"),
                    timestamp=op["timestamp"]))
            else:
                ds.ticket(RawOperation(
                    client_id=f"c{op['slot']}", type=MessageType.OPERATION,
                    client_seq=op["client_seq"], ref_seq=op["ref_seq"],
                    timestamp=op["timestamp"], contents={"x": 1}))
    elapsed = time.perf_counter() - start
    out["scalar_python_ops_per_sec"] = len(stream) * reps / elapsed
    out["num_docs"] = num_docs
    return out


def rngless(i: int) -> int:
    """Small deterministic ref-seq lag without a shared RNG."""
    return (i * 7919) % 5


def bench_overload(num_docs: int = 256, k: int = 64,
                   rounds: int = 16) -> dict:
    """Overload column (ISSUE 5): graceful-degradation figures of merit
    from the chaos scenarios themselves — the bench IS the invariant run,
    so a regression fails loudly instead of drifting silently.

    * shed_rate / p99 ratio at 2x the bounded tick-ingress capacity
      (tools/chaos.run_overload: every overflow frame busy-nacked, the
      admitted cohort's p99 within 2x the unloaded bar);
    * quarantine recovery: wall-clock of the from-snapshot readmit of a
      poisoned doc (run_poison_quarantine, byte-identical bar inside);
    * reconnect storm: 1k simultaneous redials under a 100/s token
      bucket (run_reconnect_storm: peak attempt rate under the limit).
    """
    import os
    import shutil
    import tempfile

    from fluidframework_tpu.tools import chaos

    workdir = tempfile.mkdtemp(prefix="bench-overload-")
    try:
        ov = chaos.run_overload(os.path.join(workdir, "ov"),
                                num_docs=num_docs, k=k, rounds=rounds)
        pq = chaos.run_poison_quarantine(os.path.join(workdir, "pq"),
                                         num_docs=8, k=32, rounds=6)
        storm = chaos.run_reconnect_storm(n_clients=1000)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    return {
        "offered_x_capacity": ov["offered_x_capacity"],
        "shed_rate": ov["shed_rate"],
        "tick_ms_p99_unloaded": round(ov["tick_ms_p99_unloaded"], 2),
        "tick_ms_p99_at_2x": round(ov["tick_ms_p99_loaded"], 2),
        "p99_ratio_at_2x": round(ov["tick_ms_p99_loaded"]
                                 / max(ov["tick_ms_p99_unloaded"], 1e-9),
                                 3),
        "quarantine_recovery_ms": pq["readmit_ms"],
        "quarantine_replayed_ticks": pq["replayed_ticks"],
        "reconnect_storm_1k_makespan_s": storm["makespan_s"],
        "reconnect_storm_peak_attempts_per_s": storm[
            "peak_attempts_per_s_after_wave"],
        "reconnect_storm_window_limit": storm["window_limit"],
        "num_docs": num_docs,
        "ops_per_tick": num_docs * k,
        "rounds": rounds,
    }


def _service_load_full() -> dict:
    from fluidframework_tpu.native.bridge import _load_library
    from fluidframework_tpu.tools.load_test import run_storm_load

    if _load_library() is None:
        return {"skipped": "no C++ toolchain for the bridge front door"}
    return run_storm_load(10_000_000, num_docs=240, k=256)


def emit_round9(path: str = "BENCH_r09.json") -> dict:
    """ISSUE 6 acceptance bars: re-measure the e2e storm path WITH
    DURABILITY ON after the zero-copy transport work and write the
    link-normalized columns (fraction_of_link_ceiling,
    ack_interval_ms_{p50,p99}) to BENCH_r09.json. Fail-soft: when the
    native libs aren't built the rows record the skip instead of
    crashing."""
    import jax

    from fluidframework_tpu.utils import compile_cache

    compile_cache.enable()
    backend = jax.default_backend()
    out: dict = {"round": 9, "environment": {"backend": backend}}
    # The acceptance-named row: the 10k-doc shape, durability ON (group
    # commit — the crash-safe production mode), through the full socket
    # path. On a TPU-attached harness the link is the axon tunnel; on
    # CPU the "link" is a host memcpy, so the ceiling is enormous and
    # the fraction correspondingly small — the note records which.
    full = bench_e2e_storm(durability="group")
    out["e2e_storm_10k_docs"] = full
    # Round-7 comparability row: the identical CPU-scaled shape r07
    # measured its durability column on (2048 x 256 x 8 ticks, 4 conns),
    # isolating the host-path win from shape effects.
    out["e2e_storm_cpu_2048x256_durable_group"] = bench_e2e_storm(
        num_docs=2048, k=256, ticks=8, n_conns=4, durability="group")
    out["e2e_storm_cpu_2048x256_off"] = bench_e2e_storm(
        num_docs=2048, k=256, ticks=8, n_conns=4)
    skipped = "skipped" in full
    if not skipped:
        r07_group_rate = 3_112_974.0  # BENCH_r07 durable-group, same path
        scaled = out["e2e_storm_cpu_2048x256_durable_group"]
        if "skipped" not in scaled:
            scaled["speedup_vs_r07_same_shape"] = round(
                scaled["e2e_ops_per_sec"] / r07_group_rate, 2)
        out["environment"]["note"] = (
            "Backend %s. The round-9 tentpole is host-side: zero-copy "
            "storm ingress (memoryview-through codec -> bridge -> "
            "submit_frame, no per-doc frombuffer, scatter straight from "
            "the receive buffer), columnar binary acks (one i32[n,4] "
            "slice per frame instead of per-doc JSON lists), and "
            "broadcast fan-out as ONE native fanout_publish_batch call "
            "per tick. fraction_of_link_ceiling divides the e2e rate by "
            "the MEASURED host->device link at 4 bytes/op on THIS "
            "attachment; on a CPU backend the link is a memcpy "
            "(GB/s-class), so the ceiling is ~100x a tunneled TPU "
            "attachment's and the fraction is not comparable to the "
            "round-6 tunneled figure of 0.245 — the like-for-like "
            "evidence is the r07-shape durable-group row and the "
            "ack-interval bars." % backend)
    else:
        out["environment"]["note"] = (
            "native bridge unavailable; e2e rows skipped (fail-soft)")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    return out


def emit_round10(path: str = "BENCH_r10.json") -> dict:
    """ISSUE 7 acceptance bars: the durable-ON e2e storm run with the
    round-10 observability plane live — per-stage attribution columns
    (which hop of the tick eats the budget), the sampled per-op hop
    decomposition of ack latency, and the tracing overhead measured
    trace-off vs trace-EVERY-frame on the same shape (the <2% bar at a
    far denser sample than the 1-in-N default). Fail-soft: without the
    native bridge the rows record the skip instead of crashing."""
    import jax

    from fluidframework_tpu.utils import compile_cache

    compile_cache.enable()
    backend = jax.default_backend()
    out: dict = {"round": 10, "environment": {"backend": backend}}
    # The acceptance-named row: 10k docs, durability ON (group commit),
    # tracing at 1-in-4 frames for decomposition coverage. (The r09 and
    # main() rows keep trace_every=0 — their recorded baselines ran
    # trace-free, so re-runs stay comparable.)
    full = bench_e2e_storm(durability="group", trace_every=4)
    out["e2e_storm_10k_docs"] = full
    skipped = "skipped" in full
    if not skipped:
        # Overhead pair at the r07-comparability shape: identical runs,
        # tracing off vs tracing EVERY frame (strictly worse than the
        # default sample). The arms INTERLEAVE (off, on, off, on, ...)
        # and score best-of-3: a long-lived bench process drifts slower
        # run over run (page cache, allocator fragmentation), so
        # running all of one arm first would bill the drift to whichever
        # arm went second — measured at ~16% fake "overhead" once.
        rows: dict = {0: [], 1: []}
        for _ in range(3):
            for te in (0, 1):
                rows[te].append(bench_e2e_storm(
                    num_docs=2048, k=256, ticks=8, n_conns=4,
                    durability="group", trace_every=te))

        def best(te):
            return max(rows[te],
                       key=lambda r: r.get("e2e_ops_per_sec", 0.0))

        off = best(0)
        on = best(1)
        out["e2e_storm_cpu_2048x256_trace_off"] = off
        out["e2e_storm_cpu_2048x256_trace_on"] = on
        out["tracing_overhead_pct"] = round(
            100.0 * (off["e2e_ops_per_sec"] / on["e2e_ops_per_sec"] - 1.0),
            2)
        out["environment"]["note"] = (
            "Backend %s. Round-10 tentpole is observability: "
            "stage_attribution = per-tick stage ledger (share of "
            "attributed tick time + p50/p99 per stage over the measured "
            "window; ingress decode -> admission -> scatter -> device "
            "dispatch -> readback -> WAL append/commit-wait -> ack pack "
            "-> fanout publish). ack_hop_decomposition_ms = sampled "
            "per-op trace joins (client send -> bridge ingress -> admit "
            "-> dispatch -> sequenced -> durable -> ack tx -> client "
            "rx; same-host monotonic clock domain). "
            "tracing_overhead_pct compares trace-off vs trace-EVERY-"
            "frame on the identical shape, arms interleaved and scored "
            "best-of-3 to cancel process drift (the 1-in-N default "
            "costs proportionally less); negative = under run noise."
            % backend)
    else:
        out["environment"]["note"] = (
            "native bridge unavailable; e2e rows skipped (fail-soft)")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    return out


def _gen_head_stream(rng: random.Random, n_ops: int,
                     n_writers: int = 8) -> list[dict]:
    """The ADVERSARIAL head-concentrated stream (the BENCH_r06 known-loss
    shape): every insert lands at the document head and removes hit the
    head range, so all structural work concentrates in block 0 and the
    rebalance trigger fires at the maximum rate the geometry allows."""
    from fluidframework_tpu.ops import mergetree_kernel as mtk

    ops, length, pool = [], 0, 0
    for seq in range(1, n_ops + 1):
        client = rng.randrange(n_writers)
        if length > 16 and rng.random() < 0.25:
            end = rng.randint(1, 6)
            ops.append(dict(kind=mtk.MT_REMOVE, pos=0, end=end, seq=seq,
                            ref_seq=seq - 1, client=client))
            length -= end
        else:
            tlen = rng.randint(1, 8)
            ops.append(dict(kind=mtk.MT_INSERT, pos=0, seq=seq,
                            ref_seq=seq - 1, client=client,
                            pool_start=pool, text_len=tlen))
            pool += tlen
            length += tlen
    return ops


def bench_rebalance_r11(num_docs: int = 64, k: int = 32, ticks: int = 6,
                        sizes: tuple = (512, 2048, 8192)) -> dict:
    """Round-11 rebalance rows: the serving path (block apply + the
    conditional rebalance exactly as storm._mixed_tick fuses it) against
    the flat kernel across table sizes and op-locality shapes, with the
    OLD from-scratch rebalance as the in-round baseline, per-rebalance
    microbench (incremental spill vs full rebuild on the same danger
    state), and the device fire-rate/blocks-touched columns the kstats
    plane now exports. Same 64-doc XLA-CPU sweep shape as the BENCH_r06
    section this round answers (its S=8192 serving row was 0.65x)."""
    import functools

    import jax
    import jax.numpy as jnp

    from fluidframework_tpu.ops import mergetree_blocks as mtb
    from fluidframework_tpu.ops import mergetree_kernel as mtk
    from fluidframework_tpu.ops import mergetree_pallas as mtp

    @functools.partial(jax.jit, static_argnames=("tick_k",))
    def maybe_full(state, min_seq, tick_k):
        """The round-6 conditional rebalance: from-scratch on danger."""
        bk = state.length.shape[2]
        danger = jnp.any(jnp.max(state.blk_count, axis=1)
                         + 2 * tick_k + 2 > bk)
        return jax.lax.cond(danger,
                            lambda s: mtb._rebalance_impl(s, min_seq),
                            lambda s: s, state)

    def measure(apply_fn, state0, batches, passes=2, reps=2):
        st = apply_fn(state0, batches[0])  # compile + warm
        jax.block_until_ready(jax.tree_util.tree_leaves(st)[0])
        best = 0.0
        for _ in range(reps):
            st = state0
            start = time.perf_counter()
            for _ in range(passes):
                for batch in batches:
                    st = apply_fn(st, batch)
            jax.block_until_ready(jax.tree_util.tree_leaves(st)[0])
            best = max(best, num_docs * k * len(batches) * passes
                       / (time.perf_counter() - start))
        return best

    zero_ms = jnp.zeros((num_docs,), jnp.int32)
    out: dict = {
        "shape": f"{num_docs} docs, k={k}, {ticks} ticks, XLA "
                 f"{jax.default_backend()}",
        "streams": {}}
    for stream_name, gen in (("head_concentrated", _gen_head_stream),
                             ("spread", _gen_merge_stream)):
        stream = gen(random.Random(0), k * ticks)
        batches = []
        for t in range(ticks):
            one = mtk.make_merge_op_batch([stream[t * k:(t + 1) * k]],
                                          1, k)
            batches.append(mtk.MergeOpBatch(
                *[jnp.asarray(_tile(np.asarray(f), num_docs))
                  for f in one]))
        rows: dict = {}
        for s in sizes:
            row: dict = {}
            flat = measure(mtp.apply_tick_best,
                           mtk.init_state(num_docs, s), batches)
            row["flat"] = round(flat, 1)
            configs = [("base", *mtb.choose_block_geometry(s, k), "incr")]
            if stream_name == "head_concentrated":
                nb_t, bk_t = mtb.choose_block_geometry(s, k, 1.0)
                if (nb_t, bk_t) != configs[0][1:3]:
                    # The geometry the serving host retunes to once the
                    # fire rate reveals the head concentration — the
                    # round-11 serving configuration for this stream.
                    configs.append(("autotuned", nb_t, bk_t, "incr"))
                    configs.append(("autotuned_full_rebalance", nb_t,
                                    bk_t, "full"))
                # r06-sweep comparability: the S-exact lane-width grid
                # its apply-only table used — isolates the incremental
                # lever from the geometry lever.
                configs.append((f"r06_grid_{s // 128}x128", s // 128,
                                128, "incr"))
                configs.append((f"r06_grid_{s // 128}x128_full_rebalance",
                                s // 128, 128, "full"))
            for label, nb, bk, reb in configs:
                def apply_blocks(state, batch, reb=reb):
                    state, _ovf = mtb.apply_tick_blocks(state, batch)
                    if reb == "full":
                        return maybe_full(state, zero_ms, k)
                    return mtb.maybe_rebalance(state, zero_ms, k)
                rate = measure(apply_blocks,
                               mtb.init_state(num_docs, nb, bk), batches)
                row[f"blocks_{label}"] = {
                    "geometry": f"{nb}x{bk}",
                    "ops_per_sec": round(rate, 1),
                    "block_vs_flat": round(rate / flat, 3)}
            # Fire-rate / blocks-touched columns (device rstats, one
            # instrumented double pass — the kstats the serving hosts
            # export as storm.device.*).
            for label, nb, bk, _reb in configs:
                if "full" in label:
                    continue
                st = mtb.init_state(num_docs, nb, bk)
                fired = touched = 0
                for batch in batches * 2:
                    st, _ovf = mtb.apply_tick_blocks(st, batch)
                    st, rs = mtb.maybe_rebalance_stats(st, zero_ms, k)
                    rs = np.asarray(rs)
                    fired += int(rs[0])
                    touched += int(rs[1])
                row[f"blocks_{label}"]["rebalance_fired_per_tick"] = \
                    round(fired / (2 * ticks), 3)
                row[f"blocks_{label}"]["blocks_touched_per_fire"] = \
                    round(touched / max(1, fired), 1)
            rows[f"S={s}"] = row
        out["streams"][stream_name] = rows

    # Per-rebalance microbench: drive the head stream WITH the fused
    # maintenance to a steady state, stop at a tick where the danger
    # trigger is armed and the local spill is feasible, then time the
    # incremental spill vs the full rebuild FROM THE SAME STATE.
    stream = _gen_head_stream(random.Random(0), k * ticks)
    batches = []
    for t in range(ticks):
        one = mtk.make_merge_op_batch([stream[t * k:(t + 1) * k]], 1, k)
        batches.append(mtk.MergeOpBatch(
            *[jnp.asarray(_tile(np.asarray(f), num_docs)) for f in one]))
    micro: dict = {}
    for s in sizes:
        nb, bk = s // 128, 128
        cap = bk - (2 * k + 2)
        st = mtb.init_state(num_docs, nb, bk)
        danger_state = None
        for batch in batches * 2:
            st, _ovf = mtb.apply_tick_blocks(st, batch)
            # The kernel's OWN conveyor plan decides feasibility (no
            # drifting host replica), and the tomb-pressure predicate
            # must be false too — otherwise maybe_rebalance takes the
            # full branch and the "incremental" column would silently
            # time the rebuild.
            c = st.blk_count
            nb_i = jax.lax.broadcasted_iota(jnp.int32, c.shape, 1)
            c1, _e, h = mtb._spill_counts(c, jnp.int32(cap), nb_i)
            c2 = c1 - h + jnp.roll(h, -1, axis=-1)
            feasible = bool(jnp.all(c2 <= cap))
            tomb_light = bool(jnp.all(
                st.blk_tomb.sum(axis=1) * mtb.TOMB_PRESSURE_DEN
                < nb * bk))
            if int(jnp.max(c)) > cap and feasible and tomb_light:
                danger_state = st  # armed, feasible, drops deferred
            st = mtb.maybe_rebalance(st, zero_ms, k)
        if danger_state is None:
            micro[f"S={s}"] = {"skipped": "no armed feasible state"}
            continue

        def t_ms(fn):
            fn()  # compile/warm
            best = float("inf")
            for _ in range(5):
                start = time.perf_counter()
                out_state = fn()
                jax.block_until_ready(
                    jax.tree_util.tree_leaves(out_state)[0])
                best = min(best, (time.perf_counter() - start) * 1e3)
            return best

        full_ms = t_ms(lambda: mtb.rebalance(danger_state, zero_ms))
        incr_ms = t_ms(lambda: mtb.maybe_rebalance_stats(
            danger_state, zero_ms, k)[0])
        micro[f"S={s}"] = {
            "geometry": f"{nb}x128",
            "ms_per_full_rebalance": round(full_ms, 2),
            "ms_per_incremental_spill": round(incr_ms, 2),
            "incremental_speedup": round(full_ms / max(incr_ms, 1e-9),
                                         2)}
    out["rebalance_microbench"] = micro
    return out


def _residency_stack(tmp_dir, pool_slots: int, clock=None, **res_kw):
    """In-process storm stack with a capped-residency device pool (the
    round-12 tiering shape): group-commit WAL + snapshot store, and a
    ResidencyManager sized to ``pool_slots`` resident docs."""
    import os

    from fluidframework_tpu.server.durable_store import (
        DurableMessageBus,
        FileStateStore,
        GitSnapshotStore,
    )
    from fluidframework_tpu.server.kernel_host import KernelSequencerHost
    from fluidframework_tpu.server.merge_host import KernelMergeHost
    from fluidframework_tpu.server.residency import ResidencyManager
    from fluidframework_tpu.server.routerlicious import RouterliciousService
    from fluidframework_tpu.server.storm import StormController

    seq_host = KernelSequencerHost(num_slots=2,
                                   initial_capacity=pool_slots)
    merge_host = KernelMergeHost(flush_threshold=10**9)
    # Durable bus + store (the production deli/scriptorium pair): the
    # in-memory bus would retain every join/leave MESSAGE in RAM and the
    # RSS rows would measure message history, not doc residency.
    service = RouterliciousService(
        bus=DurableMessageBus(os.path.join(tmp_dir, "bus")),
        store=FileStateStore(os.path.join(tmp_dir, "state")),
        merge_host=merge_host, batched_deli_host=seq_host,
        auto_pump=False, idle_check_interval=10**9)
    storm = StormController(
        service, seq_host, merge_host, flush_threshold_docs=10**9,
        spill_dir=os.path.join(tmp_dir, "spill"), durability="group",
        snapshots=GitSnapshotStore(os.path.join(tmp_dir, "git")))
    kw = dict(max_resident=pool_slots, idle_evict_s=1e9,
              hydration_rate_per_s=1e9)
    kw.update(res_kw)
    if clock is not None:
        kw["clock"] = clock
    res = ResidencyManager(storm, **kw)
    return service, storm, seq_host, merge_host, res


def _residency_words(seed, k):
    rng = np.random.default_rng(seed)
    slots = rng.integers(0, 16, k).astype(np.uint32)
    vals = rng.integers(1, 1 << 18, k).astype(np.uint32)
    return (slots << np.uint32(2)) | (vals << np.uint32(12))


def _connect_in_chunks(service, docs, chunk):
    """Connect + pump in pool-bounded chunks so every doc's JOIN is
    sequenced (and its device row live) BEFORE a later chunk's capacity
    eviction can demote it — the ordering the front door guarantees."""
    clients = {}
    for base in range(0, len(docs), chunk):
        for d in docs[base:base + chunk]:
            clients[d] = service.connect(d, lambda m: None).client_id
        service.pump()
    return clients


def _rss_now_mb():
    import gc

    from fluidframework_tpu.server.residency import _rss_mb
    gc.collect()
    return _rss_mb() or 0.0


def bench_residency_churn(registered: int = 1_000_000,
                          pool_slots: int = 10_000,
                          extra_cold: int = 800,
                          churn_frames: int = 30,
                          frame_docs: int = 64,
                          cold_per_frame: int = 6,
                          k: int = 8) -> dict:
    """THE round-12 scenario: a 1M-doc registered namespace served from
    a ``pool_slots``-resident device pool. ``pool_slots + extra_cold``
    docs are ever served (the rest of the namespace is open — a
    registered-never-served id has NO entry in any host structure and no
    disk presence, measured below); steady churn re-touches cold docs
    through admission-gated hydration with LRU capacity eviction.
    Reports steady-state RSS vs the hot set (the tiering claim),
    hydration/eviction p50/p99, and the device-pool high-water mark."""
    import tempfile

    tmp = tempfile.mkdtemp(prefix="bench-res-churn-")
    service, storm, seq_host, merge_host, res = _residency_stack(
        tmp, pool_slots)
    ever_served = pool_slots + extra_cold
    docs = [f"r12-doc-{i}" for i in range(ever_served)]
    rng = np.random.default_rng(12)

    t0 = time.perf_counter()
    clients = _connect_in_chunks(service, docs,
                                 chunk=max(256, pool_slots // 8))
    t_join = time.perf_counter() - t0

    # Warm the resident set: two full-cohort ticks (the classic storm
    # shape) so "hot steady" RSS includes served device state.
    hot = list(res.resident)
    cseqs = {d: 1 for d in docs}
    for r in range(2):
        entries = [[d, clients[d], cseqs[d], 1, k] for d in hot]
        payload = b"".join(_residency_words((12, r, i), k).tobytes()
                           for i in range(len(hot)))
        storm.submit_frame(None, {"rid": r, "docs": entries},
                           memoryview(payload))
        storm.flush()
        for d in hot:
            cseqs[d] += k
    rss_hot = _rss_now_mb()
    evictions_before = res.stats["evictions"]
    hydrations_before = res.stats["hydrations"]

    t1 = time.perf_counter()
    ops = 0
    for f in range(churn_frames):
        resident = list(res.resident)
        cold_pool = [d for d in docs if d not in res.resident]
        picks = ([resident[i] for i in
                  rng.choice(len(resident), frame_docs - cold_per_frame,
                             replace=False)]
                 + [cold_pool[i] for i in
                    rng.choice(len(cold_pool), cold_per_frame,
                               replace=False)])
        entries = [[d, clients[d], cseqs[d], 1, k] for d in picks]
        payload = b"".join(_residency_words((13, f, i), k).tobytes()
                           for i in range(len(picks)))
        storm.submit_frame(None, {"rid": 100 + f, "docs": entries},
                           memoryview(payload))
        storm.flush()
        for d in picks:
            cseqs[d] += k
        ops += len(picks) * k
    t_churn = time.perf_counter() - t1
    rss_churn = _rss_now_mb()

    snap = merge_host.metrics.snapshot()
    if storm._group_wal is not None:
        storm._group_wal.close()
    return {
        "registered_docs": registered,
        "pool_slots": pool_slots,
        "ever_served_docs": ever_served,
        "never_served_docs": registered - ever_served,
        # Open namespace: a registered-but-never-served doc id appears
        # in NO host structure (the entries below are the complete
        # per-doc state) and owns no disk until its first eviction.
        "bytes_per_never_served_doc": 0,
        "resident_docs": len(res.resident),
        "doc_index_entries": len(storm._doc_ticks),
        "tick_count_entries": len(storm.doc_tick_counts),
        "seq_row_high_water": seq_host._row_count,
        "join_phase_s": round(t_join, 2),
        "churn_frames": churn_frames,
        "churn_ops_per_sec": round(ops / t_churn, 1),
        "cold_access_fraction": round(cold_per_frame / frame_docs, 3),
        "hydrations": res.stats["hydrations"] - hydrations_before,
        "evictions": res.stats["evictions"] - evictions_before,
        "hydration_ms_p50": round(
            1e3 * snap.get("residency.hydrate_s.p50", 0.0), 3),
        "hydration_ms_p99": round(
            1e3 * snap.get("residency.hydrate_s.p99", 0.0), 3),
        "evict_ms_p50": round(
            1e3 * snap.get("residency.evict_s.p50", 0.0), 3),
        "evict_ms_p99": round(
            1e3 * snap.get("residency.evict_s.p99", 0.0), 3),
        "rss_mb_hot_steady": round(rss_hot, 1),
        "rss_mb_after_churn": round(rss_churn, 1),
        # THE tiering ratio: steady-state RSS tracks the HOT set — churn
        # through the cold tier must not grow it with the ever-served
        # (let alone registered) population.
        "rss_vs_hot_ratio": round(rss_churn / max(rss_hot, 1e-9), 4),
    }


def bench_residency_storm(cold_docs: int = 768, pool_slots: int = 256,
                          rate_per_s: float = 200.0, k: int = 8) -> dict:
    """Hydration-storm row: every cold doc's client returns at the same
    instant. The admission bucket must ladder the stampede out at its
    drain rate — hydration starts per (simulated) second stay under
    rate + burst, everyone converges in ~ideal drain time, and refused
    clients claim their reserved slot on return (no compounding debt).
    Simulated clock; the hydration WORK (snapshot restore into pool
    rows) is real."""
    import heapq
    import tempfile

    tmp = tempfile.mkdtemp(prefix="bench-res-storm-")
    clk = [0.0]
    service, storm, seq_host, merge_host, res = _residency_stack(
        tmp, pool_slots, clock=lambda: clk[0],
        hydration_rate_per_s=rate_per_s)
    docs = [f"storm-doc-{i}" for i in range(cold_docs)]
    clients = _connect_in_chunks(service, docs, chunk=pool_slots)
    cseqs = {d: 1 for d in docs}
    # Give every doc real served state, then demote ALL of them: the
    # storm below hydrates genuine cold snapshots, not fresh rows.
    for base in range(0, cold_docs, pool_slots):
        chunk = docs[base:base + pool_slots]
        for d in chunk:
            res.ensure_resident(d, gate=False)
        entries = [[d, clients[d], cseqs[d], 1, k] for d in chunk]
        payload = b"".join(_residency_words((14, base, i), k).tobytes()
                           for i in range(len(chunk)))
        storm.submit_frame(None, {"rid": base, "docs": entries},
                           memoryview(payload))
        storm.flush()
    for d in list(res.resident):
        res.evict(d)
    assert res.resident == {}
    nacks_before = res.stats["hydration_nacks"]

    # t=0: everyone knocks at once (the worst case admission exists
    # for); refused clients return exactly at their retry hint.
    events = [(0.0, i, docs[i]) for i in range(cold_docs)]
    heapq.heapify(events)
    hydrated_at: dict[str, float] = {}
    attempts = 0
    t0 = time.perf_counter()
    while events:
        t, i, doc = heapq.heappop(events)
        clk[0] = t
        attempts += 1
        retry = res.ensure_resident(doc)
        if retry is None:
            hydrated_at[doc] = t
        else:
            heapq.heappush(events, (t + retry, i, doc))
    wall_s = time.perf_counter() - t0
    if storm._group_wal is not None:
        storm._group_wal.close()

    makespan = max(hydrated_at.values())
    per_sec: dict[int, int] = {}
    for t in hydrated_at.values():
        per_sec[int(t)] = per_sec.get(int(t), 0) + 1
    ideal = cold_docs / rate_per_s
    burst = res.hydrations.burst
    return {
        "cold_docs": cold_docs,
        "pool_slots": pool_slots,
        "hydration_rate_per_s": rate_per_s,
        "hydration_burst": burst,
        "all_converged": len(hydrated_at) == cold_docs,
        "sim_makespan_s": round(makespan, 2),
        "ideal_drain_s": round(ideal, 2),
        # Admission-bounded convergence: ~1.0 means the stampede drained
        # at exactly the bucket rate (the acceptance bar's shape).
        "makespan_vs_ideal_drain": round(makespan / ideal, 3),
        "peak_hydrations_per_sim_s": max(per_sec.values()),
        "admission_bound_per_s": rate_per_s + burst,
        "attempts_total": attempts,
        "hydration_nacks": res.stats["hydration_nacks"] - nacks_before,
        "wall_s_for_real_hydration_work": round(wall_s, 2),
    }


def bench_residency_rss_slope(batches: int = 4, batch_docs: int = 512,
                              pool_slots: int = 64, k: int = 4) -> dict:
    """RSS-per-cold-doc slope: serve-and-evict successive batches
    through a tiny pool and fit RSS against the cold population. The
    tiering claim is slope ~ 0 (a cold doc costs snapshot-store DISK,
    not RAM); the extrapolation row makes the 1M-registered arithmetic
    explicit."""
    import os
    import tempfile

    tmp = tempfile.mkdtemp(prefix="bench-res-slope-")
    service, storm, seq_host, merge_host, res = _residency_stack(
        tmp, pool_slots)
    cseq = 1
    samples = []
    n = 0
    for b in range(batches):
        docs = [f"slope-doc-{b}-{i}" for i in range(batch_docs)]
        clients = _connect_in_chunks(service, docs,
                                     chunk=pool_slots)
        for base in range(0, batch_docs, pool_slots):
            chunk = docs[base:base + pool_slots]
            for d in chunk:
                res.ensure_resident(d, gate=False)
            entries = [[d, clients[d], cseq, 1, k] for d in chunk]
            payload = b"".join(
                _residency_words((15, b, base, i), k).tobytes()
                for i in range(len(chunk)))
            storm.submit_frame(None, {"rid": (b, base), "docs": entries},
                               memoryview(payload))
            storm.flush()
            # Disconnect while the chunk is still RESIDENT (production
            # idle clients leave before their docs go cold; a leave on a
            # cold doc would re-allocate its row through the bus path):
            # the slope must measure COLD DOCS, not live connections.
            for d in chunk:
                service.disconnect(d, clients[d])
            service.pump()
        cseq += k
        n += batch_docs
        samples.append((n, _rss_now_mb()))
    xs = np.array([s[0] for s in samples], np.float64)
    ys = np.array([s[1] for s in samples], np.float64)
    slope_mb_per_doc = float(np.polyfit(xs, ys, 1)[0])
    # A non-positive fit means cold-doc RAM growth is below allocator
    # noise (RSS can DROP between samples as freed arenas return) — the
    # honest extrapolation floor is zero, not a negative number.
    below_noise = slope_mb_per_doc <= 0
    git_dir = os.path.join(tmp, "git")
    disk = sum(os.path.getsize(os.path.join(root, f))
               for root, _dirs, files in os.walk(git_dir) for f in files)
    if storm._group_wal is not None:
        storm._group_wal.close()
    return {
        "pool_slots": pool_slots,
        "cold_docs_final": n,
        "rss_mb_samples": [[int(x), round(y, 1)] for x, y in samples],
        "rss_kb_per_cold_doc": round(1024 * slope_mb_per_doc, 3),
        "slope_below_allocator_noise": below_noise,
        "extrapolated_rss_mb_for_1m_cold": round(
            max(0.0, 1e6 * slope_mb_per_doc), 1),
        # tracemalloc attribution of the residual slope: the SERVICE
        # plane's message history — this in-process harness's bus
        # partitions and per-doc ops store keep codec-decoded
        # joins/leaves/records in RAM by design (the reference parks
        # that tier in Kafka/Mongo). The DEVICE-POOL cost per cold doc
        # is zero: the churn row's pool high-water and doc-index
        # entries stay exactly O(hot). Bounding bus/store RAM is a
        # retention-policy seam, tracked in ROADMAP item 2's residual.
        "residual_slope_is": "service-plane message history "
                             "(bus log + ops store), not device pool",
        "cold_store_disk_mb": round(disk / (1024 * 1024), 1),
        "cold_store_disk_kb_per_doc": round(disk / 1024 / max(n, 1), 2),
    }


def bench_viewers(viewer_counts=(1_000, 10_000, 100_000),
                  ticks: int = 8, k: int = 64) -> dict:
    """THE round-13 scenario: one hot doc, a huge read-only audience.
    For each viewer count: join the audience through the viewer plane
    (native fan-out rooms, shallow per-sub bounds), then drive ``ticks``
    storm ticks from one writer and measure (a) broadcast latency — the
    wall time of the encode-once + one-batched-publish + drain hop, per
    tick, p50/p99 — (b) e2e sequenced ops/s through the serving tick
    with the audience attached, and (c) the serialize-once invariant
    column: encodes per tick == hot docs (1), independent of the
    audience size."""
    from fluidframework_tpu.server.broadcaster import ViewerPlane
    from fluidframework_tpu.server.kernel_host import KernelSequencerHost
    from fluidframework_tpu.server.merge_host import KernelMergeHost
    from fluidframework_tpu.server.routerlicious import RouterliciousService
    from fluidframework_tpu.server.storm import StormController

    rows = {}
    for n_viewers in viewer_counts:
        seq_host = KernelSequencerHost(num_slots=2, initial_capacity=4)
        merge_host = KernelMergeHost(flush_threshold=10**9)
        service = RouterliciousService(merge_host=merge_host,
                                       batched_deli_host=seq_host,
                                       auto_pump=False)
        storm = StormController(service, seq_host, merge_host,
                                flush_threshold_docs=10**9)
        plane = ViewerPlane(service, join_rate_per_s=1e9)
        writer = service.connect("live-doc", lambda m: None)
        service.pump()

        delivered = [0]

        def viewer_push(_payload, _delivered=delivered):
            _delivered[0] += 1

        t0 = time.perf_counter()
        for _ in range(n_viewers):
            plane.join("live-doc", viewer_push)
        join_s = time.perf_counter() - t0
        # Settle the join phase's coalesced presence announces so the
        # measured ticks time the BROADCAST hop, not join backlog.
        plane.drain_all()

        # Time the broadcast hop (encode-once + batched publish + drain)
        # per tick, separately from the device tick.
        broadcast_s: list[float] = []
        orig_publish = plane.publish_ticks

        def timed_publish(items):
            t = time.perf_counter()
            out = orig_publish(items)
            broadcast_s.append(time.perf_counter() - t)
            return out

        plane.publish_ticks = timed_publish
        words = _residency_words((13, n_viewers), k)
        # One untimed warmup tick (jit compile + caches) so the smallest
        # audience row measures the serving shape, not the first-compile.
        storm.submit_frame(None, {"rid": -1,
                                  "docs": [["live-doc", writer.client_id,
                                            1, 1, k]]},
                           memoryview(words.tobytes()))
        storm.flush()
        broadcast_s.clear()
        encodes_before = plane.stats["tick_encodes"]
        delivered_before = delivered[0]
        t1 = time.perf_counter()
        for t in range(1, 1 + ticks):
            storm.submit_frame(
                None, {"rid": t,
                       "docs": [["live-doc", writer.client_id,
                                 1 + t * k, 1, k]]},
                memoryview(words.tobytes()))
            storm.flush()
        total_s = time.perf_counter() - t1
        encodes = plane.stats["tick_encodes"] - encodes_before
        frames = delivered[0] - delivered_before
        lat = np.sort(np.array(broadcast_s))
        rows[f"viewers_{n_viewers}"] = {
            "viewers": n_viewers,
            "ticks": ticks,
            "ops_per_tick": k,
            "join_s": round(join_s, 2),
            "joins_per_sec": round(n_viewers / max(join_s, 1e-9), 1),
            "e2e_ops_per_sec": round(ticks * k / total_s, 1),
            "broadcast_ms_p50": round(
                1e3 * float(lat[len(lat) // 2]), 3),
            "broadcast_ms_p99": round(
                1e3 * float(lat[min(len(lat) - 1,
                                    int(len(lat) * 0.99))]), 3),
            "broadcast_frames_delivered": frames,
            "frames_per_sec_fanout": round(
                frames / max(sum(broadcast_s), 1e-9), 1),
            "broadcast_bytes_total": plane.stats["broadcast_bytes"],
            "lag_drops": plane.stats["lag_drops"],
            # THE serialize-once invariant: encodes per tick == hot docs
            # (1 here), NOT viewers — the column the acceptance bar pins.
            "encodes_per_tick": round(encodes / ticks, 3),
            "hot_docs": 1,
            "serialize_once_holds": encodes == ticks,
            "fanout_native": bool(getattr(plane.fanout, "is_native",
                                          False)),
        }
    return rows


def emit_round13(path: str = "BENCH_r13.json") -> dict:
    """ISSUE 10 acceptance bars: broadcast latency p50/p99 + e2e ops/s
    vs viewer count (1k/10k/100k) on one hot doc, with the
    serialize-once invariant column. Fail-soft writer."""
    import jax

    from fluidframework_tpu.utils import compile_cache

    compile_cache.enable()
    backend = jax.default_backend()
    out: dict = {"round": 13, "environment": {"backend": backend}}
    try:
        out["viewer_fanout"] = bench_viewers()
    except Exception as err:  # fail-soft: record, don't crash
        out["viewer_fanout"] = {"skipped": repr(err)}
    out["environment"]["note"] = (
        "Backend %s. Round-13 tentpole: the broadcast viewer plane "
        "(server/broadcaster.py) — mode='viewer' sessions skip "
        "admission debits, merge, and ack bookkeeping entirely; they "
        "join the doc's room in native/fanout.cpp and receive each "
        "sequenced tick's broadcast frame serialized ONCE per doc per "
        "tick (codec.encode_viewer_tick_body) and fanned out in one "
        "fanout_publish_batch native call with refcounted payloads "
        "(O(members) pointer pushes, not O(members) copies). Slow "
        "viewers lag-drop at the shallow per-sub queue bound to a "
        "snapshot+catch-up resync (the round-12 cold-read path) "
        "instead of stalling the tick; join storms gate through the "
        "TokenBucket reservation ladder. Broadcast latency here is the "
        "in-process fan-out hop (encode + batched native publish + "
        "per-viewer drain to the transport push); real sockets add "
        "their kernel write cost on top, bounded by the bridge's "
        "per-connection viewer outbox. encodes_per_tick == hot_docs "
        "(1) at every audience size is the serialize-once invariant "
        "(pinned by tests/test_broadcaster.py)." % backend)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    return out


def emit_round12(path: str = "BENCH_r12.json") -> dict:
    """ISSUE 9 acceptance bars: the 1M-registered / 10k-hot churn
    scenario (steady-state RSS scales with the hot set, hydration
    p50/p99 in-row), the hydration-storm admission-bounded convergence
    row, and the RSS-per-cold-doc slope. Fail-soft writer."""
    import jax

    from fluidframework_tpu.utils import compile_cache

    compile_cache.enable()
    backend = jax.default_backend()
    out: dict = {"round": 12, "environment": {"backend": backend}}
    # Slope first: it fits RSS against a GROWING cold population and
    # must run before the 10k-pool churn row parks hundreds of MB of
    # allocator arenas that release mid-fit.
    for name, fn in (("cold_rss_slope", bench_residency_rss_slope),
                     ("churn_1m_registered_10k_hot",
                      bench_residency_churn),
                     ("hydration_storm", bench_residency_storm)):
        try:
            out[name] = fn()
        except Exception as err:  # fail-soft: record, don't crash
            out[name] = {"skipped": repr(err)}
    out["environment"]["note"] = (
        "Backend %s. Round-12 tentpole: tiered hot/cold doc residency "
        "(server/residency.py) — a cold doc is ONE content-addressed "
        "snapshot (sequencer checkpoint + map-row planes + compact tick "
        "index) in the GitSnapshotStore plus its WAL tail; hydration "
        "restores it into a recycled pool row "
        "(KernelSequencerHost.release_doc / release_map_row recycle "
        "indices, so device capacity is bounded by PEAK RESIDENT docs); "
        "eviction barriers on the WAL fsync watermark before flipping "
        "the cold head (acked => durable survives eviction, "
        "chaos-proven at residency.mid_hydrate/mid_evict). The churn "
        "row serves a 1M-id registered namespace from a 10k-slot pool: "
        "registration is open (never-served ids cost zero bytes "
        "anywhere, by construction — the per-doc structures counted "
        "in-row are the complete state), and steady-state RSS tracks "
        "the HOT set (rss_vs_hot_ratio ~ 1.0) while ever-served and "
        "registered populations exceed it. The storm row drives every "
        "cold doc's client at t=0 through the TokenBucket hydration "
        "gate with claimable per-doc reservations: convergence at the "
        "bucket drain rate, peak hydrations/s under rate+burst. "
        "Simulated clock for the storm's admission timeline; hydration "
        "restore work and all churn-row timings are real wall time on "
        "this backend." % backend)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    return out


def emit_round11(path: str = "BENCH_r11.json") -> dict:
    """ISSUE 8 acceptance bars: serving-path block_vs_flat at S=8192 on
    the adversarial head-concentrated stream (was 0.65 in BENCH_r06),
    the incremental-vs-full rebalance microbench, and the device
    fire-rate columns. Fail-soft writer."""
    import jax

    from fluidframework_tpu.utils import compile_cache

    compile_cache.enable()
    backend = jax.default_backend()
    out: dict = {"round": 11, "environment": {"backend": backend}}
    try:
        out["rebalance_r11"] = bench_rebalance_r11()
    except Exception as err:  # fail-soft: record, don't crash the writer
        out["rebalance_r11"] = {"skipped": repr(err)}
    out["environment"]["note"] = (
        "Backend %s. Round-11 tentpole: the block table's conditional "
        "rebalance became INCREMENTAL (overfull blocks spill into "
        "neighbors with per-block circular log-shifts; tombstone drops "
        "defer behind the blk_tomb pressure threshold; summaries "
        "refresh only for touched blocks) and the geometry autotunes "
        "from observed op locality (head-concentration fraction = the "
        "rebalance fire rate off the device kstats plane; "
        "choose_block_geometry head_fraction scales Bk so the hot "
        "block absorbs 1-4 ticks per spill). blocks_autotuned is THE "
        "serving configuration for a head-concentrated doc after "
        "retune (parallel/serving.retune_text_geometry / "
        "KernelMergeHost.autotune_block_geometry); blocks_base is the "
        "pre-retune geometry; the r06_grid rows reproduce the "
        "BENCH_r06 sweep's S-exact 64x128-style grid to isolate the "
        "incremental lever (its serving row measured 0.65x at S=8192 "
        "with the from-scratch rebalance). The <=25 ms pipelined-p99 "
        "ledger rows (merge 36.3 / sequencer 35.8 / tree 52.1 / mixed "
        "78.6) are tunneled-TPU quantities and need a TPU hour to "
        "re-measure; the expected mover is the mixed/merge ticks' "
        "rebalance share, which the fire-rate columns here bound."
        % backend)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    return out


# -- mega-doc write scale-out (round 15) --------------------------------------


def _megadoc_arm(writers: int, k: int, lanes: int | None,
                 attach_manager: bool, wave: int = 64,
                 seed: int = 0) -> dict:
    """One doc, ``writers`` co-writers, one frame of ``k`` ops each,
    durable-ON (group WAL), submitted in WAVES of ``wave`` frames (the
    round-14 windowed-client shape — an unbounded same-doc backlog would
    measure the deferral queue, not the serving path). ``lanes`` not
    None promotes the doc; ``attach_manager`` without promotion is the
    no-tax arm (manager checks on the hot path, tier never engaged).
    Waves submit in lane-striped order (clients arrive independently;
    the striping is the well-mixed arrival order that lets L lanes fill
    — a FIFO-fenced combiner serves a prefix of distinct lanes per
    tick)."""
    import tempfile

    from fluidframework_tpu.server.kernel_host import KernelSequencerHost
    from fluidframework_tpu.server.megadoc import (
        MegaDocManager,
        lane_of_writer,
    )
    from fluidframework_tpu.server.merge_host import KernelMergeHost
    from fluidframework_tpu.server.routerlicious import RouterliciousService
    from fluidframework_tpu.server.storm import StormController

    spill = tempfile.mkdtemp(prefix="megadoc-bench-")
    seq_host = KernelSequencerHost(num_slots=256, initial_capacity=4)
    merge_host = KernelMergeHost(flush_threshold=10**9)
    service = RouterliciousService(merge_host=merge_host,
                                   batched_deli_host=seq_host,
                                   auto_pump=False,
                                   idle_check_interval=10**9)
    storm = StormController(service, seq_host, merge_host,
                            flush_threshold_docs=10**9,
                            spill_dir=spill, durability="group")
    mgr = None
    if attach_manager:
        mgr = MegaDocManager(storm, default_lanes=lanes or 8)
    doc = "mega"
    # Setup (untimed): every writer joins through the front door, in
    # chunks so the join scan stays at one compiled K bucket.
    clients = []
    for i in range(writers):
        clients.append(service.connect(doc, lambda m: None).client_id)
        if (i + 1) % 256 == 0:
            service.pump()
    service.pump()
    n_lanes = lanes or 1
    if lanes is not None:
        mgr.promote(doc, lanes=lanes)
    # Lane-striped arrival order: round-robin across the lane buckets,
    # so consecutive frames hit DISTINCT lanes and every FIFO-fenced
    # cohort prefix fills all L lanes.
    if lanes is None:
        order = list(range(writers))
    else:
        buckets: list[list[int]] = [[] for _ in range(n_lanes)]
        for w in range(writers):
            buckets[lane_of_writer(clients[w], n_lanes)].append(w)
        order = []
        depth_max = max(len(b) for b in buckets)
        for i in range(depth_max):
            for b in buckets:
                if i < len(b):
                    order.append(b[i])
    rng = np.random.default_rng(seed)
    words_all = (rng.integers(0, 1 << 20, (writers, k)).astype(np.uint32)
                 << 12) | (rng.integers(0, 32, (writers, k)
                                        ).astype(np.uint32) << 2)
    lat: list[float] = []
    t_submit: dict[int, float] = {}

    def sink(payload):
        rid = payload.get("rid")
        if rid is not None and not payload.get("error"):
            lat.append(time.perf_counter() - t_submit[rid])

    # Warm-up (untimed): one spare frame compiles the tick shapes.
    storm.submit_frame(None, {"rid": None,
                              "docs": [[doc, clients[0], 1, 1, k]]},
                       memoryview(words_all[0].tobytes()))
    storm.flush()
    ticks0 = storm.stats["ticks"]
    seq0 = storm.stats["sequenced_ops"]
    t0 = time.perf_counter()
    for base in range(0, writers, wave):
        for w in order[base:base + wave]:
            cseq0 = k + 1 if w == 0 else 1  # writer 0 warmed with k ops
            t_submit[w] = time.perf_counter()
            storm.submit_frame(sink, {
                "rid": w, "docs": [[doc, clients[w], cseq0, 1, k]]},
                memoryview(words_all[w].tobytes()))
        storm.flush()
    elapsed = time.perf_counter() - t0
    sequenced = storm.stats["sequenced_ops"] - seq0
    assert sequenced == writers * k, (sequenced, writers * k)
    assert len(lat) == writers
    lat_ms = 1000.0 * np.asarray(sorted(lat))
    out = {
        "writers": writers,
        "lanes": n_lanes if lanes is not None else 1,
        "promoted": lanes is not None,
        "manager_attached": attach_manager,
        "merged_ops_per_sec": round(sequenced / elapsed, 1),
        "elapsed_s": round(elapsed, 3),
        "ticks": storm.stats["ticks"] - ticks0,
        "ack_ms_p50": float(np.percentile(lat_ms, 50)),
        "ack_ms_p99": float(np.percentile(lat_ms, 99)),
        "durable_watermark": storm.durable_watermark,
    }
    storm._group_wal.close()
    import shutil
    shutil.rmtree(spill, ignore_errors=True)
    return out


def bench_megadoc_writers(writer_counts=(100, 1_000, 10_000), k: int = 8,
                          lanes: int = 8) -> dict:
    """Durable-ON merged-ops/s + ack p99 on ONE document vs writer
    count, sharded (promoted onto ``lanes`` sub-sequencer lanes) vs the
    single-lane baseline in the same run — the ISSUE 12 acceptance
    columns. Plus the promotion-tax row: a manager attached but never
    engaging its tier must cost <= 5% at the small-doc shape."""
    out: dict = {"k": k, "lanes": lanes}
    for writers in writer_counts:
        single = _megadoc_arm(writers, k, lanes=None, attach_manager=False)
        sharded = _megadoc_arm(writers, k, lanes=lanes,
                               attach_manager=True)
        out[f"writers_{writers}"] = {
            "single_lane": single,
            "sharded": sharded,
            "sharded_vs_single_lane": round(
                sharded["merged_ops_per_sec"]
                / single["merged_ops_per_sec"], 3),
            "ack_p99_ratio": round(
                sharded["ack_ms_p99"] / max(single["ack_ms_p99"], 1e-9),
                3),
        }
    # Promotion-tax: INTERLEAVED best-of-5 at the smallest shape (the
    # runs are ~0.1 s, so a background scheduler blip on either arm
    # would fake a tax; interleaving + min puts both arms under the
    # same weather — the bar is a 5% ceiling, not a race).
    w0 = writer_counts[0]
    plain_runs, managed_runs = [], []
    for _ in range(5):
        plain_runs.append(_megadoc_arm(w0, k, None, False)["elapsed_s"])
        managed_runs.append(_megadoc_arm(w0, k, None, True)["elapsed_s"])
    plain, managed = min(plain_runs), min(managed_runs)
    out["promotion_tax"] = {
        "writers": w0,
        "plain_elapsed_s": round(plain, 4),
        "manager_attached_elapsed_s": round(managed, 4),
        "tax_ratio": round(managed / plain, 3),
    }
    return out


def emit_round15(path: str = "BENCH_r15.json") -> dict:
    """ISSUE 12 acceptance bars: one document's write path widened onto
    sequence-parallel lanes — durable-ON e2e merged-ops/s and ack p99 at
    writer counts 100/1k/10k, sharded vs single-lane in the SAME run on
    the forced multi-lane CPU mesh. Bars: >= 2x merged-ops/s at the
    10k-writer shape; <= 1.05x tax at the 100-writer shape (promotion
    must not tax small docs)."""
    import os

    # Forced multi-lane CPU mesh, programmatically BEFORE first device
    # use: jax 0.4.37 has no jax_num_cpu_devices config, so the host
    # device count rides XLA_FLAGS set from Python pre-init, and the
    # PLATFORM override uses jax.config.update — the JAX_PLATFORMS env
    # var alone does not stick against the installed TPU plugin (it can
    # hang jax init; see tests/conftest.py, which forces the same way).
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    from fluidframework_tpu.utils import compile_cache

    compile_cache.enable()
    assert len(jax.devices()) >= 8, "forced host mesh missing"
    out: dict = {"round": 15,
                 "environment": {"backend": jax.default_backend(),
                                 "devices": len(jax.devices())}}
    rows = bench_megadoc_writers()
    out["megadoc_one_doc"] = rows
    big = rows["writers_10000"]
    small = rows["writers_100"]
    out["sharded_vs_single_lane_10k_writers"] = \
        big["sharded_vs_single_lane"]
    out["bar_10k_writers_2x"] = big["sharded_vs_single_lane"] >= 2.0
    out["promotion_tax_ratio_100_writers"] = \
        rows["promotion_tax"]["tax_ratio"]
    out["bar_small_doc_tax_1_05"] = \
        rows["promotion_tax"]["tax_ratio"] <= 1.05
    # Informational: the PROMOTED arm's win even at 100 writers (the
    # acceptance "no small-doc tax" evidence is promotion_tax above —
    # a manager attached but never engaging its tier).
    out["small_shape_promoted_vs_single_lane"] = \
        small["sharded_vs_single_lane"]
    out["environment"]["note"] = (
        "Round-15 tentpole: one document's merge served from sharded "
        "device lanes. A promoted doc gets L per-lane sub-sequencer "
        "rows; writers hash to lanes; a host-side doc-space scalar twin "
        "of the closed-form storm ticket (the combiner) stamps the "
        "doc's total order in cohort admission order — byte-identical "
        "to the single-lane interleaving (pinned by the differential "
        "fuzz: sharded == single-lane == scalar on converged entries, "
        "ack quads, materialized history, and the demoted sequencer "
        "checkpoint; chaos kill points mid-promotion / mid-combine / "
        "mid-demotion recover byte-identically with zero acked-durable "
        "ops lost). The single-lane baseline serves ONE writer frame "
        "per doc per tick (the pre-round-15 cohort rule), so merged "
        "throughput on one hot doc scales with the lane count until "
        "the per-tick fixed cost dominates; ack p99 drops with the "
        "tick count a writer's frame waits behind. Clients submit in "
        "waves of 64 (the round-14 windowed flow-control shape) in "
        "lane-striped arrival order. Both arms pay the full durable "
        "path: group-commit WAL, acks withheld on the durability "
        "watermark. CPU mesh figures; the sequence-parallel TEXT "
        "kernel's collective walk (ops/mergetree_sharded.py) stays "
        "hardware-gated like every tunneled-TPU bar since round 7.")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    return out


def _cluster_words(seed, k):
    rng = np.random.default_rng(seed)
    kinds = rng.choice([0, 0, 0, 1], size=k).astype(np.uint32)
    slots = rng.integers(0, 16, k).astype(np.uint32)
    vals = rng.integers(0, 1 << 20, k).astype(np.uint32)
    return (kinds | (slots << 2) | (vals << 12)).astype(np.uint32)


def _cluster_build(root, labels, active, num_docs, **storm_kw):
    import os

    from fluidframework_tpu.parallel.placement import (
        StormCluster,
        make_cluster_host,
    )
    from fluidframework_tpu.server.durable_store import GitSnapshotStore

    git = GitSnapshotStore(os.path.join(root, "git"))
    hosts = {label: make_cluster_host(label, os.path.join(root, label),
                                      git, num_docs=num_docs, **storm_kw)
             for label in labels}
    return StormCluster(hosts, git, active=active)


def _cluster_assign_round_robin(cluster, docs, labels):
    """Even doc ownership for the scaling arms (the genesis hash is
    stable but lumpy at small doc counts)."""
    for i, d in enumerate(docs):
        cluster.directory.owners[d] = labels[i % len(labels)]
    cluster.directory._save()


def _cluster_connect(cluster, docs):
    clients = {}
    for d in docs:
        storm = cluster.storm_for(d)
        clients[d] = storm.service.connect(d, lambda m: None).client_id
        storm.service.pump()
    return clients


def _cluster_serve_timed(cluster, clients, cseq, duration_s, k,
                         active):
    """Each ACTIVE host serves its owned docs from its OWN thread —
    per-frame durable barriers (submit + group-commit flush), so a
    host's rate is bounded by its fsync round trip and hosts
    parallelize exactly the way the fleet does. Returns
    (total acked ops, per-host acked ops, elapsed_s)."""
    import threading
    import time as _time

    owned = {label: [d for d in clients
                     if cluster.owner_of(d) == label]
             for label in active}
    acked = {label: 0 for label in active}
    start = _time.perf_counter()

    def run(label):
        storm = cluster.hosts[label]
        docs = owned[label]
        if not docs:
            return
        r = 0
        while _time.perf_counter() - start < duration_s:
            for d in docs:
                acks: list = []
                words = _cluster_words([hash(d) % 2**31, r], k)
                storm.submit_frame(
                    acks.append,
                    {"rid": r, "docs": [[d, clients[d], cseq[d],
                                         1, k]]},
                    memoryview(words.tobytes()))
                storm.flush()
                if acks and not acks[0].get("error"):
                    acked[label] += k
                    cseq[d] += k
            r += 1

    threads = [threading.Thread(target=run, args=(label,))
               for label in active]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = _time.perf_counter() - start
    return sum(acked.values()), acked, elapsed


def bench_cluster_scaling(num_docs: int = 16, k: int = 64,
                          duration_s: float = 6.0,
                          warmup_s: float = 1.0,
                          commit_latency_sweep_ms=(0.0, 10.0, 80.0)
                          ) -> dict:
    """The 2→4 host elastic scale-out: ONE 4-host cluster per arm,
    genesis active on 2 — measure aggregate durable-ON ops/s, activate
    the other 2, converge ownership through the placement controller's
    LIVE migrations (convergence time recorded), measure again.

    The sweep makes the scaling REGIME explicit instead of hiding it:
    per-frame cost = commit latency L (parallel across hosts — each
    host's WAL writer waits independently) + host compute c
    (SERIALIZED on this container's single core), so the in-process
    model predicts scaling_2→4 = 2(L+2c)/(L+4c). Arms: L=0 (this
    box's real fsync — the honest null result: CPU-bound serving
    cannot scale by host count on one core), L=10ms (same-region
    replicated log), L=80ms (geo-replicated quorum commit — the
    regime where one host's commit round trip truly caps the fleet;
    the acceptance bar reads THIS arm). On any multi-core box or a
    real multi-process launch c parallelizes too and every arm
    scales; see the BENCH_r16 note."""
    import tempfile

    from fluidframework_tpu.parallel.placement import PlacementController

    labels = ["h0", "h1", "h2", "h3"]

    def arm(latency_ms: float) -> dict:
        root = tempfile.mkdtemp(prefix="bench-cluster-")
        cluster = _cluster_build(
            root, labels, active=labels[:2], num_docs=num_docs,
            wal_commit_latency_s=latency_ms / 1e3)
        docs = [f"doc-{i}" for i in range(num_docs)]
        _cluster_assign_round_robin(cluster, docs, labels[:2])
        clients = _cluster_connect(cluster, docs)
        cseq = {d: 1 for d in docs}
        # Warmup: pay XLA compile + first-touch rows off the clock.
        _cluster_serve_timed(cluster, clients, cseq, warmup_s, k,
                             labels[:2])
        ops2, per2, t2 = _cluster_serve_timed(cluster, clients, cseq,
                                              duration_s, k, labels[:2])
        cluster.activate_host("h2")
        cluster.activate_host("h3")
        ctrl = PlacementController(cluster, max_moves_per_round=8)
        rebalance = ctrl.rebalance()
        # Warm the new hosts' compile caches off the clock too.
        _cluster_serve_timed(cluster, clients, cseq, warmup_s, k, labels)
        ops4, per4, t4 = _cluster_serve_timed(cluster, clients, cseq,
                                              duration_s, k, labels)
        rate2, rate4 = ops2 / t2, ops4 / t4
        return {
            "wal_commit_latency_ms": latency_ms,
            "aggregate_ops_per_sec_2_hosts": round(rate2, 1),
            "aggregate_ops_per_sec_4_hosts": round(rate4, 1),
            "scaling_2_to_4": round(rate4 / max(rate2, 1e-9), 3),
            "per_host_acked_2": per2,
            "per_host_acked_4": per4,
            "rebalance": rebalance,
            "rebalance_convergence_s": rebalance["elapsed_s"],
            "docs_per_host_after": rebalance["docs_per_host"],
        }

    import os
    out: dict = {
        "num_docs": num_docs, "k": k,
        "duration_s_per_arm": duration_s,
        "cpu_cores": os.cpu_count(),
        "arms": {},
    }
    for latency_ms in commit_latency_sweep_ms:
        name = ("local_disk" if latency_ms == 0
                else f"commit_{latency_ms:g}ms")
        out["arms"][name] = arm(latency_ms)
    bar_arm = out["arms"][
        "local_disk" if max(commit_latency_sweep_ms) == 0
        else f"commit_{max(commit_latency_sweep_ms):g}ms"]
    out["scaling_2_to_4"] = bar_arm["scaling_2_to_4"]
    out["rebalance_convergence_s"] = bar_arm["rebalance_convergence_s"]
    out["aggregate_ops_per_sec_2_hosts"] = \
        bar_arm["aggregate_ops_per_sec_2_hosts"]
    out["aggregate_ops_per_sec_4_hosts"] = \
        bar_arm["aggregate_ops_per_sec_4_hosts"]
    out["docs_per_host_after"] = bar_arm["docs_per_host_after"]
    return out


def bench_cluster_migration(num_docs: int = 6, k: int = 64,
                            migrations: int = 12) -> dict:
    """Migration blackout under concurrent writes: docs keep serving
    round-robin while one doc at a time live-migrates between hosts;
    per-migration blackout (freeze → directory flip) and the FIRST
    post-migration frame's end-to-end resume latency are the columns."""
    import tempfile
    import time as _time

    labels = ["h0", "h1"]
    root = tempfile.mkdtemp(prefix="bench-migrate-")
    cluster = _cluster_build(root, labels, active=labels,
                             num_docs=num_docs)
    docs = [f"doc-{i}" for i in range(num_docs)]
    _cluster_assign_round_robin(cluster, docs, labels)
    clients = _cluster_connect(cluster, docs)
    cseq = {d: 1 for d in docs}

    def serve_round(r):
        for d in docs:
            storm = cluster.storm_for(d)
            acks: list = []
            words = _cluster_words([hash(d) % 2**31, r], k)
            storm.submit_frame(
                acks.append,
                {"rid": r, "docs": [[d, clients[d], cseq[d], 1, k]]},
                memoryview(words.tobytes()))
            storm.flush()
            if acks and not acks[0].get("error"):
                cseq[d] += k

    for r in range(3):  # warmup incl. compile + first eviction paths
        serve_round(r)
    cluster.migrate(docs[0], "h1" if cluster.owner_of(docs[0]) == "h0"
                    else "h0")  # warm the migration path itself
    cluster.blackouts_s.clear()
    resume_ms = []
    for m in range(migrations):
        serve_round(100 + m)  # concurrent writes between migrations
        doc = docs[m % num_docs]
        src = cluster.owner_of(doc)
        dst = next(h for h in labels if h != src)
        t0 = _time.perf_counter()
        cluster.migrate(doc, dst)
        # First frame at the new owner: the client-observed resume.
        acks: list = []
        words = _cluster_words([m, 7], k)
        cluster.hosts[dst].submit_frame(
            acks.append,
            {"rid": f"resume-{m}",
             "docs": [[doc, clients[doc], cseq[doc], 1, k]]},
            memoryview(words.tobytes()))
        cluster.hosts[dst].flush()
        assert acks and not acks[0].get("error"), acks
        cseq[doc] += k
        resume_ms.append(1000.0 * (_time.perf_counter() - t0))
    blk = np.asarray(cluster.blackouts_s) * 1000.0
    return {
        "migrations": migrations, "num_docs": num_docs, "k": k,
        "blackout_ms_p50": round(float(np.percentile(blk, 50)), 3),
        "blackout_ms_p99": round(float(np.percentile(blk, 99)), 3),
        "blackout_ms_max": round(float(blk.max()), 3),
        "freeze_to_first_ack_ms_p50": round(
            float(np.percentile(resume_ms, 50)), 3),
        "freeze_to_first_ack_ms_p99": round(
            float(np.percentile(resume_ms, 99)), 3),
    }


def bench_viewer_rehome(viewers: int = 64, k: int = 32) -> dict:
    """Viewer re-home across hosts: N viewers on the source host's
    room; the migration drops them all with ``moved_to`` directives;
    each viewer then runs the resync dance (merged get_deltas gap +
    join on the target plane). Per-viewer re-home latency = directive
    to live-on-target; the p99 is the acceptance column."""
    import tempfile
    import time as _time

    from fluidframework_tpu.server.broadcaster import ViewerPlane

    labels = ["h0", "h1"]
    root = tempfile.mkdtemp(prefix="bench-rehome-")
    cluster = _cluster_build(root, labels, active=labels, num_docs=4)
    doc = "hot-doc"
    clients = _cluster_connect(cluster, [doc])
    src = cluster.owner_of(doc)
    dst = next(h for h in labels if h != src)
    src_plane = ViewerPlane(cluster.hosts[src].service)
    dst_plane = ViewerPlane(cluster.hosts[dst].service)
    directive_at = {}
    sinks = []
    for v in range(viewers):
        events = []

        def push(p, v=v, events=events):
            if isinstance(p, dict) and p.get("event") == "viewer_resync":
                directive_at[v] = _time.perf_counter()
            events.append(p)

        src_plane.join(doc, push)
        sinks.append(events)
    cseq = 1
    for r in range(3):
        storm = cluster.storm_for(doc)
        words = _cluster_words([r], k)
        storm.submit_frame(None, {"rid": r, "docs": [[doc, clients[doc],
                                                      cseq, 1, k]]},
                           memoryview(words.tobytes()))
        storm.flush()
        cseq += k
    cluster.migrate(doc, dst)
    assert len(directive_at) == viewers
    rehome_ms = []
    for v in range(viewers):
        # The resync dance each re-homed viewer runs: gap fetch off
        # the cold-read path, then join the target plane.
        gap = cluster.get_deltas(doc, 0)
        dst_plane.join(doc, lambda p: None)
        rehome_ms.append(1000.0 * (_time.perf_counter()
                                   - directive_at[v]))
    arr = np.asarray(sorted(rehome_ms))
    # Latency measured from ONE shared directive instant: viewer i's
    # figure includes its predecessors' dances (the sequential drain a
    # single re-join thread would see) — the honest stampede shape.
    return {
        "viewers": viewers,
        "rehomed_viewers": cluster.stats["rehomed_viewers"],
        "gap_messages": len(gap),
        "rehome_ms_p50": round(float(np.percentile(arr, 50)), 3),
        "rehome_ms_p99": round(float(np.percentile(arr, 99)), 3),
    }


def emit_round16(path: str = "BENCH_r16.json") -> dict:
    """ISSUE 13 acceptance bars: live doc migration + load-based
    placement across in-process serving hosts. Columns: migration
    blackout ms (p50/p99) under concurrent writes, 2→4 host rebalance
    convergence time + aggregate durable-ON ops/s scaling (bar:
    ≥ 1.8x on the CPU mesh, per-frame durability barriers), and viewer
    re-home p99."""
    import os

    # Forced CPU platform, programmatically BEFORE first device use
    # (the JAX_PLATFORMS env var alone does not stick against the
    # installed TPU plugin — see tests/conftest.py).
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    from fluidframework_tpu.utils import compile_cache

    compile_cache.enable()
    out: dict = {"round": 16,
                 "environment": {"backend": jax.default_backend(),
                                 "devices": len(jax.devices())}}
    out["migration_blackout"] = bench_cluster_migration()
    out["scaling_2_to_4_hosts"] = bench_cluster_scaling()
    out["viewer_rehome"] = bench_viewer_rehome()
    scaling = out["scaling_2_to_4_hosts"]["scaling_2_to_4"]
    out["bar_scaling_1_8x"] = scaling >= 1.8
    out["environment"]["note"] = (
        "Round-16 tentpole: elastic multi-host serving. Doc placement "
        "is live and load-driven: migration = durable MIGRATING intent "
        "in the shared placement directory -> quarantine-freeze at the "
        "source front door ('migrating' nacks with retry_after_s) -> "
        "evict to the PR 12 cold record in the SHARED content-"
        "addressed store -> hydrate on the target -> directory flip "
        "('moved' nacks carrying moved_to; clients redial through the "
        "PR 8 reconnect path; viewer rooms re-home via the PR 13 "
        "viewer_resync dance). Zero acked-durable ops lost (chaos kill "
        "points at all three phases recover byte-identical to a "
        "never-migrated twin). The scaling section is a COMMIT-LATENCY "
        "SWEEP, one thread per host with per-frame durability "
        "barriers: per-frame cost = commit latency L (parallel across "
        "hosts — each WAL writer waits independently) + host compute "
        "c (serialized on this container's SINGLE core), so the "
        "in-process model predicts scaling_2to4 = 2(L+2c)/(L+4c). "
        "L=0 (real local fsync) is the honest null result — on one "
        "core host count cannot scale CPU-bound serving, in-process "
        "or otherwise; L=10ms (same-region replicated log) and "
        "L=80ms (geo-replicated quorum commit, the regime where one "
        "host's commit round trip truly caps the fleet — ROADMAP "
        "item 2's premise; the bar reads this arm) show the scaling "
        "the architecture buys where commit latency dominates. On a "
        "multi-core box or a real multi-process launch c parallelizes "
        "too and every arm scales — re-measure there (ROADMAP cluster "
        "residue). All figures CPU; tunneled-TPU bars remain "
        "hardware-gated as since r7.")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    return out


def _history_stack(root=None, **hist_kw):
    """In-process storm stack + HistoryPlane (spill-backed when a root
    is given — the disk-amplification arm needs a real file)."""
    import os
    import tempfile

    from fluidframework_tpu.server.durable_store import GitSnapshotStore
    from fluidframework_tpu.server.history import HistoryPlane
    from fluidframework_tpu.server.kernel_host import KernelSequencerHost
    from fluidframework_tpu.server.merge_host import KernelMergeHost
    from fluidframework_tpu.server.routerlicious import (
        RouterliciousService,
    )
    from fluidframework_tpu.server.storm import StormController

    seq_host = KernelSequencerHost(num_slots=2, initial_capacity=4)
    merge_host = KernelMergeHost(flush_threshold=10**9)
    service = RouterliciousService(merge_host=merge_host,
                                   batched_deli_host=seq_host,
                                   auto_pump=False,
                                   idle_check_interval=10**9)
    kw: dict = {}
    snap_root = root if root is not None else tempfile.mkdtemp()
    if root is not None:
        kw.update(spill_dir=os.path.join(root, "spill"),
                  durability="group")
    storm = StormController(
        service, seq_host, merge_host, flush_threshold_docs=10**9,
        pipeline_depth=0,
        snapshots=GitSnapshotStore(os.path.join(snap_root, "git")), **kw)
    hist = HistoryPlane(storm, **hist_kw)
    return service, storm, hist


def _history_words(seed, r, k, slots=16, churn=False):
    rng = np.random.default_rng([seed, r])
    kinds = rng.choice([0, 0, 0, 1], size=k).astype(np.uint32)
    width = 8 if churn else slots  # churn: few slots overwritten forever
    s = rng.integers(0, width, k).astype(np.uint32)
    vals = rng.integers(0, 1 << 20, k).astype(np.uint32)
    return (kinds | (s << 2) | (vals << 12)).astype(np.uint32)


def bench_history_reads(rounds: int = 192, k: int = 64,
                        interval_ops: int = 2048,
                        reps: int = 15) -> dict:
    """Historical-read latency vs depth behind head, with and without
    summaries. Without summaries every read folds the records from seq
    0 (cost grows with the ABSOLUTE position, i.e. shrinks with depth);
    with the summarizer on cadence every read folds at most one
    summary interval — the p99 curve goes FLAT across depths (the
    acceptance bar)."""
    import time as _time

    def arm(summarize: bool) -> dict:
        service, storm, hist = _history_stack(
            summary_interval_ops=interval_ops if summarize else None,
            compact_check_every=1)
        client = service.connect("h0", lambda m: None).client_id
        service.pump()
        for r in range(rounds):
            storm.submit_frame(
                None, {"rid": r,
                       "docs": [["h0", client, 1 + r * k, 1, k]]},
                memoryview(_history_words(3, r, k).tobytes()))
            storm.flush()
        head = hist.head_seq("h0")
        rows = {}
        for depth in (1, 64, 512, 4096, head - 1):
            seq = max(1, head - depth)
            samples = []
            for _ in range(reps):
                t0 = _time.perf_counter()
                hist.read_at("h0", seq)
                samples.append(1e3 * (_time.perf_counter() - t0))
            rows[f"depth_{depth}"] = {
                "seq": seq,
                "read_ms_p50": round(float(np.percentile(samples, 50)),
                                     4),
                "read_ms_p99": round(float(np.percentile(samples, 99)),
                                     4),
            }
        p99s = [row["read_ms_p99"] for row in rows.values()]
        return {"head_seq": head, "ops_total": rounds * k,
                "summaries": hist.stats["compactions"],
                "rows": rows,
                "worst_read_ms_p50": max(row["read_ms_p50"]
                                         for row in rows.values()),
                "p99_flatness_max_over_min": round(max(p99s)
                                                   / max(min(p99s),
                                                         1e-9), 2)}

    out = {"no_summaries": arm(False), "summarized": arm(True)}
    # The flat-once-covered bar: with summaries, the WORST read across
    # every depth is bounded by one summary-interval fold — it no
    # longer scales with the absolute history length, which is exactly
    # what the uncompacted arm's worst (near-head) read does. p50-based
    # so a single scheduler hiccup cannot flip the bar.
    out["flat_once_covered"] = (
        out["summarized"]["worst_read_ms_p50"]
        <= 0.5 * out["no_summaries"]["worst_read_ms_p50"])
    return out


def bench_history_compaction_disk(rounds: int = 96, k: int = 64) -> dict:
    """Disk amplification on a long-tail churn workload (a few slots
    overwritten forever, so history >> live state): spill bytes before
    vs after summarization compaction + tail trim. Bar: after/before
    < 0.5x — the churn tail collapses to its summary."""
    import os
    import tempfile
    root = tempfile.mkdtemp()
    service, storm, hist = _history_stack(
        root, tail_retention_summaries=0, trim_batch_ticks=1)
    client = service.connect("churn", lambda m: None).client_id
    service.pump()
    storm.checkpoint()
    for r in range(rounds):
        storm.submit_frame(
            None, {"rid": r,
                   "docs": [["churn", client, 1 + r * k, 1, k]]},
            memoryview(_history_words(5, r, k, churn=True).tobytes()))
        storm.flush()
    storm.checkpoint()  # the trim floor: recovery never replays below
    spill = os.path.join(root, "spill", "storm_tick_words.log")
    before = os.path.getsize(spill)
    live_entries = storm.merge_host.map_entries("churn", storm.datastore,
                                                storm.channel)
    t0 = time.perf_counter()
    hist.compact("churn")
    hist.trim_now()
    compact_ms = 1e3 * (time.perf_counter() - t0)
    after = os.path.getsize(spill)
    # State-preservation sanity: the summary serves the identical head.
    head = hist.head_seq("churn")
    assert hist.read_at("churn", head)["entries"] == live_entries
    if storm._group_wal is not None:
        storm._group_wal.close()
    ratio = after / max(1, before)
    return {"ops_total": rounds * k, "live_keys": len(live_entries),
            "spill_bytes_before": before, "spill_bytes_after": after,
            "disk_amplification_after_over_before": round(ratio, 4),
            "trimmed_ticks": hist.stats["trimmed_ticks"],
            "compact_ms": round(compact_ms, 2),
            "bar_half_x": ratio < 0.5}


def bench_history_fork_merge(rounds: int = 48, k: int = 64) -> dict:
    """Branch verbs: fork cost at mid-history, branch serving, and
    merge-back of the branch's delta ops through the ordinary
    sequencer."""
    service, storm, hist = _history_stack()
    client = service.connect("f0", lambda m: None).client_id
    service.pump()
    for r in range(rounds):
        storm.submit_frame(
            None, {"rid": r, "docs": [["f0", client, 1 + r * k, 1, k]]},
            memoryview(_history_words(7, r, k).tobytes()))
        storm.flush()
    fork_seq = 1 + (rounds // 2) * k
    t0 = time.perf_counter()
    branch = hist.fork("f0", fork_seq, name="f0-branch", writer="w")
    fork_ms = 1e3 * (time.perf_counter() - t0)
    for r in range(4):
        storm.submit_frame(
            None, {"rid": ("b", r),
                   "docs": [[branch, "w", 1 + r * k, fork_seq, k]]},
            memoryview(_history_words(11, r, k).tobytes()))
        storm.flush()
    t0 = time.perf_counter()
    report = hist.merge_back(branch)
    merge_ms = 1e3 * (time.perf_counter() - t0)
    return {"fork_seq": fork_seq, "fork_ms": round(fork_ms, 3),
            "branch_ops": 4 * k, "merged_ops": report["merged_ops"],
            "merge_ms": round(merge_ms, 2),
            "parent_seq_after": report["parent_seq"]}


def emit_round18(path: str = "BENCH_r18.json") -> dict:
    """ISSUE 15 acceptance bars: the history plane. (1) historical-read
    p99 vs depth behind head — flat once a summary covers the gap;
    (2) disk amplification before/after summarization compaction on a
    long-tail churn workload < 0.5x; plus the branch-verbs row.
    Fail-soft: an arm that crashes records its error."""
    import jax

    from fluidframework_tpu.utils import compile_cache

    compile_cache.enable()
    out: dict = {"round": 18,
                 "environment": {"backend": jax.default_backend(),
                                 "devices": len(jax.devices())}}
    for name, fn in (("historical_reads", bench_history_reads),
                     ("compaction_disk", bench_history_compaction_disk),
                     ("fork_merge", bench_history_fork_merge)):
        try:
            out[name] = fn()
        except Exception as err:  # fail-soft: record, keep the file
            out[name] = {"error": repr(err)}
    out["environment"]["note"] = (
        "Round-18 tentpole: the history plane (server/history.py). "
        "read_at materializes any historical seq from the nearest "
        "summary at-or-below it + a scalar fold of the WAL records in "
        "between, entirely off the cold path (no device row hydrates). "
        "Without summaries the fold starts at seq 0, so read cost "
        "tracks the absolute position; the background summarizer "
        "bounds it by one summary interval — the flat-p99 bar. "
        "Compaction flips summary heads through the existing "
        "Historian.set_head/release refcount GC and (with tail "
        "retention) trims superseded WAL tick blobs to fillers under "
        "the checkpoint watermark — the disk bar; chaos --history "
        "proves kill-safety mid-compaction/mid-fork against a "
        "never-compacted twin. Branches: fork seeds a cold-doc record "
        "through the normal residency path; merge_back re-submits "
        "branch deltas through the ordinary sequencer. All figures "
        "CPU; tunneled-TPU bars remain hardware-gated as since r7.")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    return out


def _qos_arm(fair: bool, abuse: bool, rounds: int = 6, group: int = 4,
             k: int = 32, budget_groups: int = 3) -> dict:
    """One arm of the noisy-neighbor A/B: three tenants (the first at
    10x offered doc slots when ``abuse``), served through the deficit
    scheduler (``fair``) or a tenant-blind FIFO composer under the SAME
    tick slot budget. Ack latency is reported BOTH ways: wall-clock ms
    (the per-tenant SLO histograms get_metrics exports) and serving
    ticks (deterministic — the p99-shift bar is pinned on ticks)."""
    import math as _math

    from fluidframework_tpu.server.kernel_host import KernelSequencerHost
    from fluidframework_tpu.server.merge_host import KernelMergeHost
    from fluidframework_tpu.server.routerlicious import (
        RouterliciousService,
    )
    from fluidframework_tpu.server.storm import StormController

    tenants = {"abuser": 10 if abuse else 1, "vic1": 1, "vic2": 1}
    docs = {t: [f"{t}-d{i}" for i in range(n * group)]
            for t, n in tenants.items()}
    all_docs = [d for ds in docs.values() for d in ds]
    seq_host = KernelSequencerHost(num_slots=2,
                                   initial_capacity=len(all_docs))
    merge_host = KernelMergeHost(flush_threshold=10**9)
    service = RouterliciousService(merge_host=merge_host,
                                   batched_deli_host=seq_host,
                                   auto_pump=False,
                                   idle_check_interval=10**9)
    kw: dict = dict(flush_threshold_docs=10**9, pipeline_depth=0,
                    tick_slot_budget=budget_groups * group)
    if fair:
        kw["tenant_weights"] = {t: 1.0 for t in tenants}
    storm = StormController(service, seq_host, merge_host, **kw)
    clients = {d: service.connect(d, lambda m: None).client_id
               for d in all_docs}
    service.pump()
    idx = {d: i for i, d in enumerate(all_docs)}
    delays: dict = {t: [] for t in tenants}
    t0 = time.perf_counter()
    served_ops = 0
    for r in range(rounds):
        base = storm.stats["ticks"]
        for t, n in tenants.items():
            for g in range(n):
                chunk = docs[t][g * group:(g + 1) * group]
                entries = [[d, clients[d], 1 + r * k, 1, k]
                           for d in chunk]
                payload = b"".join(
                    _qos_words(3, r, idx[d], k).tobytes()
                    for d in chunk)

                def sink(p, t=t, base=base):
                    delays[t].append(storm.stats["ticks"] - base)

                storm.submit_frame(sink, {"rid": (r, t, g),
                                          "docs": entries},
                                   memoryview(payload),
                                   tenant_id=t if fair else "default")
        storm.flush()
        served_ops += sum(n for n in tenants.values()) * group * k
    elapsed = time.perf_counter() - t0
    snap = merge_host.metrics.snapshot()

    def p99(xs):
        xs = sorted(xs)
        return xs[min(len(xs) - 1,
                      max(0, _math.ceil(0.99 * len(xs)) - 1))] if xs else 0

    out: dict = {"ops_per_sec": round(served_ops / max(elapsed, 1e-9), 1),
                 "ticks": storm.stats["ticks"], "tenants": {}}
    att = storm.qos.attribution()
    for t in tenants:
        prefix = f"storm.tenant.{t if fair else 'default'}"
        row = {
            "ack_ticks_p50": sorted(delays[t])[len(delays[t]) // 2]
            if delays[t] else 0,
            "ack_ticks_p99": p99(delays[t]),
        }
        if fair:
            row["ack_ms_p50"] = round(
                snap.get(f"{prefix}.ack_s.p50", 0.0) * 1e3, 3)
            row["ack_ms_p99"] = round(
                snap.get(f"{prefix}.ack_s.p99", 0.0) * 1e3, 3)
            row["slot_share"] = att.get(t, {}).get("share", 0.0)
        out["tenants"][t] = row
    return out


def _qos_words(seed, r, i, k):
    rng = np.random.default_rng([seed, r, i])
    return ((rng.integers(0, 16, k).astype(np.uint32) << 2)
            | (rng.integers(0, 1 << 20, k).astype(np.uint32) << 12))


def emit_round17(path: str = "BENCH_r17.json") -> dict:
    """ISSUE 14 acceptance bars: multi-tenant QoS. The A/B: per-tenant
    ack p99 at 1x (baseline) vs one tenant at 10x through the
    deficit-fair composer, plus a fairness-OFF row (same slot budget,
    tenant-blind FIFO) showing the inversion the scheduler prevents.
    Bar: the victims' p99 (serving ticks) shifts <= 1.25x under abuse
    while the abuser is confined to its weighted share. Fail-soft:
    an arm that crashes records its error instead of killing the
    round file."""
    import jax

    from fluidframework_tpu.utils import compile_cache

    compile_cache.enable()
    out: dict = {"round": 17,
                 "environment": {"backend": jax.default_backend(),
                                 "devices": len(jax.devices())}}
    for name, kw in (("baseline_1x_fair", dict(fair=True, abuse=False)),
                     ("abusive_10x_fair", dict(fair=True, abuse=True)),
                     ("abusive_10x_fairness_off",
                      dict(fair=False, abuse=True))):
        try:
            out[name] = _qos_arm(**kw)
        except Exception as err:  # fail-soft: record, keep the file
            out[name] = {"error": repr(err)}
    try:
        base = out["baseline_1x_fair"]["tenants"]
        fair = out["abusive_10x_fair"]["tenants"]
        blind = out["abusive_10x_fairness_off"]["tenants"]
        ratios = [max(1, fair[v]["ack_ticks_p99"])
                  / max(1, base[v]["ack_ticks_p99"])
                  for v in ("vic1", "vic2")]
        out["victim_p99_shift_fair"] = round(max(ratios), 3)
        out["victim_p99_shift_fairness_off"] = round(
            max(max(1, blind[v]["ack_ticks_p99"])
                / max(1, base[v]["ack_ticks_p99"])
                for v in ("vic1", "vic2")), 3)
        out["bar_victim_p99_1_25x"] = out["victim_p99_shift_fair"] <= 1.25
        out["abuser_confined"] = (
            fair["abuser"]["ack_ticks_p99"]
            >= 3 * fair["vic1"]["ack_ticks_p99"])
    except (KeyError, TypeError):
        pass  # an arm failed; its error row is the evidence
    out["environment"]["note"] = (
        "Round-17 tentpole: multi-tenant QoS. Tick composition is a "
        "deficit round robin over per-tenant pending queues (weights "
        "x quantum doc-slot credit per tick, capped at one quantum — "
        "no banked burst; work-conserving borrow phase for leftover "
        "slots), so an abusive tenant saturates only its own share. "
        "Latency columns are in SERVING TICKS (deterministic — wall "
        "clock on a shared CI box would alias scheduler noise); the "
        "ack_ms columns are the same per-tenant SLO histograms "
        "get_metrics exports and tools/monitor.py render_tenants "
        "renders. Weighted shed: past its weighted pending share (and "
        "the global borrow threshold) a tenant busy-nacks with a "
        "retry_after_s scaled by ITS OWN backlog. Scheduler state "
        "rides every multi-tenant tick's WAL header + the snapshot; "
        "chaos --qos kill points (incl. storm.qos_mid_compose) "
        "recover byte-identical to a tenant-BLIND twin with zero "
        "acked-durable ops lost. All figures CPU; tunneled-TPU bars "
        "remain hardware-gated as since r7.")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    return out


def main() -> None:
    from fluidframework_tpu.utils import compile_cache

    compile_cache.enable()
    detail = {
        "map_storm_10k_docs": bench_map(),
        "map_storm_saturated_k4096": bench_map(k=4096, ticks=6),
        "e2e_storm_10k_docs": bench_e2e_storm(),
        # Durability-mode column (ISSUE 4): the same e2e path with the
        # crash-safe WAL ON — group commit must hold the rate while
        # "sync" shows what per-tick fsync would cost.
        "e2e_storm_10k_docs_durable_group": bench_e2e_storm(
            durability="group"),
        "e2e_storm_10k_docs_durable_sync": bench_e2e_storm(
            durability="sync"),
        # The reference's FULL load profile (testConfig.json:10-16): 240
        # clients, 10M ops through the real socket path, with RSS + rate
        # series as soak evidence (tools/load_test.py). Needs the C++
        # bridge; skipped (not crashed) without a toolchain.
        "service_load_full_profile": _service_load_full(),
        # Overload column (ISSUE 5): shed rate + p99 at 2x admission
        # capacity, quarantine recovery, reconnect-storm convergence.
        "overload": bench_overload(),
        "mixed_all_dds_serving": bench_mixed_serving(),
        "mergetree_stress": bench_mergetree(),
        "mergetree_128_writers": bench_mergetree(num_docs=4096,
                                                 n_writers=128),
        "mergetree_serving_window": bench_mergetree_windowed(),
        "client_walk_26k_segments": bench_client_walk(),
        "matrix_composed": bench_matrix(),
        "matrix_config4_1kx1k_256writers": bench_matrix_config4(),
        "tree_rebase_1k_docs": bench_tree(),
        "sequencer_10k_docs": bench_sequencer(),
        "notes": (
            "scalar_python = reference architecture (per-op loop) on "
            "CPython; the reference's actual V8-JIT loop is est. 10-50x "
            "faster than CPython but far below the device rate. "
            "numpy_batched_cpu = this framework's own batched semantics "
            "on CPU (strongest same-machine contender for the map storm). "
            "xla_cpu_batched = the SAME batched program compiled by XLA "
            "on this machine's CPU at a scaled doc batch (rates "
            "normalize per op). vpu_util_est = device_ops_per_sec x a "
            "per-op elems-touched model / 3.9e12 peak int32 elem-ops "
            "(v5e VPU estimate) — a coarse utilization indicator, not a "
            "measurement. tick_ms_* = blocked latency of one batched "
            "device apply INCLUDING one transport round trip (upper "
            "bound; ~100ms of it is the tunnel RTT on this harness). "
            "tick_ms_pipelined_* = depth-4 pipelined completion cadence "
            "— the per-tick latency of the kept-fed serving shape, with "
            "the RTT hidden under in-flight ticks; this is the "
            "storm-path p99 figure of merit. The map storm runs the "
            "Pallas VMEM LWW fold (ops/map_pallas.py); the fused "
            "e2e/serving tick runs the closed-form storm ticket "
            "(sequencer.storm_tickets) + the same fold. "
            "mergetree_128_writers = BASELINE config 2's writer count "
            "on one doc, device-served via 4 overlap bitmask words. "
            "mergetree_* device paths run the BLOCK-structured table "
            "(ops/mergetree_blocks.py, kernel_path 'blocks_*' — the "
            "serving path since round 6) with the conditional fused "
            "rebalance; flat_kernel_ops_per_sec is the displaced "
            "round-5 per-op kernel on the same stream. "
            "e2e_storm = "
            "sustained rate through the REAL path (client processes -> "
            "TCP -> C++ bridge -> alfred -> device deli -> device merger "
            "-> durable log + fanout + acks); it is bounded by the "
            "harness's tunneled TPU attachment, whose measured bandwidth "
            "(link_MBps_measured, varies by hour) implies the reported "
            "ops ceiling at 4 bytes/op — fused_tick_device_ops_per_sec "
            "is the same serving program with inputs resident, i.e. the "
            "rate a locally-attached chip's serving loop sustains."),
    }
    head = detail["map_storm_10k_docs"]
    for name, res in detail.items():
        if isinstance(res, dict) and "scalar_python_ops_per_sec" in res:
            res["speedup_vs_scalar_python"] = round(
                res["device_ops_per_sec"] / res["scalar_python_ops_per_sec"],
                2)
    head["speedup_vs_numpy_batched_cpu"] = round(
        head["device_ops_per_sec"] / head["numpy_batched_cpu_ops_per_sec"],
        2)
    for key in ("e2e_storm_10k_docs", "e2e_storm_10k_docs_durable_group",
                "e2e_storm_10k_docs_durable_sync"):
        e2e_row = detail[key]
        if "skipped" in e2e_row:
            continue  # fail-soft: no native bridge on this machine
        e2e_row["fraction_of_kernel_only_rate"] = round(
            e2e_row["e2e_ops_per_sec"] / head["device_ops_per_sec"], 4)
        e2e_row["fraction_of_link_ceiling"] = round(
            e2e_row["e2e_ops_per_sec"]
            / e2e_row["link_implied_ops_ceiling"], 3)
    e2e = detail["e2e_storm_10k_docs"]
    if "skipped" in e2e:
        e2e = {"tick_ms_p99": 0.0, "e2e_ops_per_sec": 0.0,
               "fraction_of_kernel_only_rate": 0.0}
    with open("BENCH_DETAIL.json", "w") as f:
        json.dump(detail, f, indent=2)
    print(json.dumps(detail, indent=2), file=sys.stderr)
    # vs_baseline = the BASELINE.json comparison (single-node CPU scalar
    # merge loop, i.e. the reference architecture); the numpy-batched-CPU
    # ratio and the V8 caveat are in BENCH_DETAIL.json.
    print(json.dumps({
        "metric": "merged map ops/sec across 10240 concurrent docs "
                  "(p99 tick %.2fms; %sx vs numpy-batched CPU; "
                  "e2e through sockets+deli+merger %.1fM ops/s = %.1f%% "
                  "of kernel rate)"
                  % (head["tick_ms_p99"],
                     head["speedup_vs_numpy_batched_cpu"],
                     e2e["e2e_ops_per_sec"] / 1e6,
                     100 * e2e["fraction_of_kernel_only_rate"]),
        "value": round(head["device_ops_per_sec"], 1),
        "unit": "ops/s",
        "vs_baseline": head["speedup_vs_scalar_python"],
    }))


def bench_replication_overhead(num_docs: int = 4, k: int = 64,
                               rounds: int = 250, warmup: int = 25,
                               pipeline_depth: int = 2) -> dict:
    """Round-19 acceptance: REAL quorum replication vs none on the same
    pipelined single-host serving path — per-frame ack latency (submit
    → ack callback, which gates on min(durable, replicated)) and e2e
    acked ops/s, with in-process followers doing real appends + fsyncs
    into their own replica WALs. Arms: OFF / F=1 (2-of-2, chain) /
    F=2 (majority, 1-of-2 follower acks). Supersedes the BENCH_r16
    wal_commit_latency_ms sweep, which MODELED the commit wait."""
    import os
    import shutil
    import tempfile

    from fluidframework_tpu.parallel.placement import make_cluster_host
    from fluidframework_tpu.server.durable_store import GitSnapshotStore
    from fluidframework_tpu.server.replication import (
        make_replicated_host,
    )

    def run_arm(followers: int) -> dict:
        root = tempfile.mkdtemp(prefix=f"repl-bench-f{followers}-")
        try:
            git = GitSnapshotStore(os.path.join(root, "git"))
            plane = None
            if followers:
                storm, plane = make_replicated_host(
                    "hostA", os.path.join(root, "hostA"), git,
                    [os.path.join(root, f"f{i}")
                     for i in range(followers)],
                    num_docs=num_docs, pipeline_depth=pipeline_depth)
            else:
                storm = make_cluster_host(
                    "hostA", os.path.join(root, "hostA"), git,
                    num_docs=num_docs, pipeline_depth=pipeline_depth)
            docs = [f"doc-{i}" for i in range(num_docs)]
            clients = {d: storm.service.connect(
                d, lambda m: None).client_id for d in docs}
            storm.service.pump()
            cseq = {d: 1 for d in docs}
            lat: list = []

            def serve(n: int) -> None:
                # Kept-fed pipeline: frames submit back-to-back; acks
                # arrive on later harvests once the batch is durable
                # AND quorum-replicated. flush() drains the tail.
                for r in range(n):
                    for i, d in enumerate(docs):
                        words = _cluster_words([r, i], k)
                        t0 = time.perf_counter()
                        storm.submit_frame(
                            lambda p, t0=t0: lat.append(
                                time.perf_counter() - t0),
                            {"rid": (r, d),
                             "docs": [[d, clients[d], cseq[d], 1, k]]},
                            memoryview(words.tobytes()))
                        cseq[d] += k
                storm.flush()

            serve(warmup)
            lat.clear()
            start = time.perf_counter()
            serve(rounds)
            elapsed = time.perf_counter() - start
            assert len(lat) == rounds * num_docs, (len(lat), rounds)
            arr = np.asarray(lat) * 1e3
            out = {
                "followers": followers,
                "acks_required": (plane.acks_required
                                  if plane is not None else None),
                "ack_ms_p50": float(np.percentile(arr, 50)),
                "ack_ms_p99": float(np.percentile(arr, 99)),
                "acked_ops_per_s": rounds * num_docs * k / elapsed,
                "frames": int(arr.shape[0]),
            }
            if plane is not None:
                assert plane.replicated_len \
                    == storm._group_wal.durable_len
                out["batches_shipped"] = plane.stats["batches_shipped"]
                out["ship_failures"] = plane.stats["ship_failures"]
            storm._group_wal.close()
            return out
        finally:
            shutil.rmtree(root, ignore_errors=True)

    arms = {"off": run_arm(0), "f1": run_arm(1), "f2": run_arm(2)}
    off, f1, f2 = arms["off"], arms["f1"], arms["f2"]
    return {
        "shape": {"num_docs": num_docs, "k": k, "rounds": rounds,
                  "pipeline_depth": pipeline_depth},
        "arms": arms,
        "ack_p99_f1_over_off": f1["ack_ms_p99"]
        / max(off["ack_ms_p99"], 1e-9),
        "ack_p99_f2_over_off": f2["ack_ms_p99"]
        / max(off["ack_ms_p99"], 1e-9),
        "ops_f1_over_off": f1["acked_ops_per_s"]
        / max(off["acked_ops_per_s"], 1e-9),
        "ops_f2_over_off": f2["acked_ops_per_s"]
        / max(off["acked_ops_per_s"], 1e-9),
    }


def bench_replica_broadcast(n_viewers: int = 10_000,
                            replica_counts=(0, 1, 2, 4),
                            ticks: int = 8, k: int = 64) -> dict:
    """Round-20 headline: ONE hot doc's 10k-viewer audience spread
    across N read replicas vs all on the leader. Per arm: the full
    audience joins (leader's ViewerPlane at N=0; hash-sharded across
    each replica's own plane otherwise), one writer drives storm
    ticks, and the measured column is the per-HOST broadcast hop
    (encode-once + batched publish + drain) — max across hosts per
    tick, i.e. the parallel-deployment bound where each replica is its
    own host draining its shard concurrently. In-process, real
    follower WAL tails; no network."""
    import os
    import shutil
    import tempfile

    from fluidframework_tpu.server.broadcaster import ViewerPlane
    from fluidframework_tpu.server.durable_store import GitSnapshotStore
    from fluidframework_tpu.server.read_replica import ReadReplica
    from fluidframework_tpu.server.replication import (
        make_replicated_host,
    )

    doc = "live-doc"
    rows = {}
    for n_rep in replica_counts:
        root = tempfile.mkdtemp(prefix=f"replica-bench-n{n_rep}-")
        try:
            git = GitSnapshotStore(os.path.join(root, "git"))
            storm, plane = make_replicated_host(
                "hostA", os.path.join(root, "hostA"), git,
                [os.path.join(root, f"f{i}")
                 for i in range(max(1, n_rep))], num_docs=4)
            writer = storm.service.connect(doc, lambda m: None)
            storm.service.pump()
            delivered = [0]

            def push(_payload, _d=delivered):
                _d[0] += 1

            reps = []
            if n_rep == 0:
                leader_plane = ViewerPlane(storm.service,
                                           join_rate_per_s=1e9)
                for _ in range(n_viewers):
                    leader_plane.join(doc, push)
                leader_plane.drain_all()
                planes = [leader_plane]
            else:
                reps = [ReadReplica(plane.links[i].node, git,
                                    f"replica{i}", leader_label="hostA",
                                    join_rate_per_s=1e9)
                        for i in range(n_rep)]
                # The directory's crc32 spread, precomputed: viewer j
                # lands on replica j % n (uniform keys hash uniform).
                for j in range(n_viewers):
                    reps[j % n_rep].viewers.join(doc, push)
                for rep in reps:
                    rep.viewers.drain_all()
                planes = [rep.viewers for rep in reps]

            # Per-host publish-hop timing (the bench_viewers column).
            host_s: list[list[float]] = [[] for _ in planes]
            for hi, p in enumerate(planes):
                orig = p.publish_ticks

                def timed(items, _orig=orig, _sink=host_s[hi]):
                    t = time.perf_counter()
                    out = _orig(items)
                    _sink.append(time.perf_counter() - t)
                    return out

                p.publish_ticks = timed

            words = _cluster_words((20, n_rep), k)

            def tick(t):
                storm.submit_frame(
                    None, {"rid": t,
                           "docs": [[doc, writer.client_id,
                                     1 + t * k, 1, k]]},
                    memoryview(words.tobytes()))
                storm.flush()
                for rep in reps:
                    rep.poll()

            tick(0)  # warmup (compile + caches)
            for s in host_s:
                s.clear()
            delivered_before = delivered[0]
            for t in range(1, 1 + ticks):
                tick(t)
            # Deployment bound: every host drains its shard in
            # parallel; the tick's broadcast cost is the slowest host.
            per_tick = [max(s[t] for s in host_s)
                        for t in range(ticks)]
            lat = np.sort(np.array(per_tick))
            stale = [rep.metrics.histogram("replica.staleness_s")
                     for rep in reps if rep.metrics.histogram(
                         "replica.staleness_s").count]
            rows[f"replicas_{n_rep}"] = {
                "replicas": n_rep,
                "viewers": n_viewers,
                "viewers_per_host": n_viewers // max(1, n_rep),
                "broadcast_ms_p50": round(
                    1e3 * float(lat[len(lat) // 2]), 3),
                "broadcast_ms_p99": round(
                    1e3 * float(lat[min(len(lat) - 1,
                                        int(len(lat) * 0.99))]), 3),
                "frames_delivered": delivered[0] - delivered_before,
                "staleness_s_p99": (round(max(
                    h.quantile(0.99) for h in stale), 6)
                    if stale else None),
            }
            storm._group_wal.close()
        finally:
            shutil.rmtree(root, ignore_errors=True)
    base = rows["replicas_0"]["broadcast_ms_p99"]
    for row in rows.values():
        row["speedup_vs_leader_only"] = round(
            base / max(row["broadcast_ms_p99"], 1e-9), 2)
    return {
        "shape": {"n_viewers": n_viewers, "ticks": ticks, "k": k},
        "arms": rows,
        "p99_speedup_4_replicas": rows.get(
            "replicas_4", {}).get("speedup_vs_leader_only"),
    }


def bench_replica_writer_tax(num_docs: int = 4, k: int = 64,
                             rounds: int = 250, warmup: int = 25,
                             pipeline_depth: int = 2) -> dict:
    """Round-20 non-interference bar: writer ack p50/p99 on the
    replicated leader (F=1) with a ReadReplica ATTACHED — tailing the
    follower WAL, a live viewer room, polling every round — vs the
    same leader with no replica. The replica is pull-based (the
    subscribe seam only stamps arrivals on the WAL thread), so the ack
    path must stay within 1.1x."""
    import os
    import shutil
    import tempfile

    from fluidframework_tpu.server.durable_store import GitSnapshotStore
    from fluidframework_tpu.server.read_replica import ReadReplica
    from fluidframework_tpu.server.replication import (
        make_replicated_host,
    )

    root = tempfile.mkdtemp(prefix="replica-tax-")
    docs = [f"doc-{i}" for i in range(num_docs)]

    def build(attach: bool, sub: str) -> dict:
        git = GitSnapshotStore(os.path.join(root, sub, "git"))
        storm, plane = make_replicated_host(
            "hostA", os.path.join(root, sub, "hostA"), git,
            [os.path.join(root, sub, "f0")], num_docs=num_docs,
            pipeline_depth=pipeline_depth)
        clients = {d: storm.service.connect(
            d, lambda m: None).client_id for d in docs}
        storm.service.pump()
        rep = None
        if attach:
            rep = ReadReplica(plane.links[0].node, git, "replica0",
                              leader_label="hostA",
                              join_rate_per_s=1e9)
            rep.viewers.join(docs[0], lambda payload: None)
        return {"storm": storm, "clients": clients, "rep": rep,
                "cseq": {d: 1 for d in docs}, "lat": [],
                "elapsed": 0.0}

    def serve_round(st: dict, r: int) -> None:
        storm, lat = st["storm"], st["lat"]
        t_round = time.perf_counter()
        for i, d in enumerate(docs):
            words = _cluster_words([r, i], k)
            t0 = time.perf_counter()
            storm.submit_frame(
                lambda p, t0=t0: lat.append(
                    time.perf_counter() - t0),
                {"rid": (r, d),
                 "docs": [[d, st["clients"][d], st["cseq"][d], 1, k]]},
                memoryview(words.tobytes()))
            st["cseq"][d] += k
        if st["rep"] is not None:
            st["rep"].poll()
        st["elapsed"] += time.perf_counter() - t_round

    try:
        # Interleaved paired design: both stacks live in this process
        # and alternate round-by-round, so fsync stalls / GC pauses /
        # host drift land on both arms instead of skewing the ratio.
        stacks = {"replica_off": build(False, "off"),
                  "replica_on": build(True, "on")}
        for r in range(warmup):
            for st in stacks.values():
                serve_round(st, r)
        for st in stacks.values():
            st["storm"].flush()
            st["lat"].clear()
            st["elapsed"] = 0.0
        # Blocked measurement: the WAL-fsync tail makes a single p99
        # swing +/-30% run to run, drowning the (small) interference
        # signal. Per-block p99 ratios + median across blocks is
        # robust to which block a stall happens to land in.
        blocks = 5
        per_block = max(1, rounds // blocks)
        ratios: list = []
        pooled = {name: [] for name in stacks}
        for b in range(blocks):
            for st in stacks.values():
                st["lat"].clear()
            lo = warmup + b * per_block
            for r in range(lo, lo + per_block):
                for st in stacks.values():
                    serve_round(st, r)
            for st in stacks.values():
                st["storm"].flush()
            p99 = {name: float(np.percentile(
                np.asarray(st["lat"]) * 1e3, 99))
                for name, st in stacks.items()}
            ratios.append(p99["replica_on"]
                          / max(p99["replica_off"], 1e-9))
            for name, st in stacks.items():
                pooled[name].extend(st["lat"])
        arms = {}
        for name, st in stacks.items():
            arr = np.asarray(pooled[name]) * 1e3
            out = {
                "replica_attached": st["rep"] is not None,
                "ack_ms_p50": float(np.percentile(arr, 50)),
                "ack_ms_p99": float(np.percentile(arr, 99)),
                "acked_ops_per_s": blocks * per_block * num_docs * k
                / max(st["elapsed"], 1e-9),
            }
            rep = st["rep"]
            if rep is not None:
                rep.poll()
                out["replica_lag_end"] = rep.lag
                stale = rep.metrics.histogram("replica.staleness_s")
                out["staleness_s_p99"] = (
                    round(stale.quantile(0.99), 6)
                    if stale.count else 0.0)
            st["storm"]._group_wal.close()
            arms[name] = out
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return {
        "shape": {"num_docs": num_docs, "k": k, "rounds": rounds,
                  "pipeline_depth": pipeline_depth, "blocks": blocks},
        "arms": arms,
        "block_p99_ratios": [round(x, 3) for x in ratios],
        "ack_p99_on_over_off": float(np.median(ratios)),
    }


def bench_replica_read_throughput(ticks: int = 24, k: int = 64,
                                  reads: int = 400) -> dict:
    """Round-20 ``read_at`` column: historical-read throughput served
    by the leader's HistoryPlane vs a ReadReplica over the follower
    WAL — the SAME scalar fold over the same summaries and records, so
    replica reads should match leader throughput while costing the
    leader nothing."""
    import os
    import shutil
    import tempfile

    from fluidframework_tpu.server.durable_store import GitSnapshotStore
    from fluidframework_tpu.server.history import HistoryPlane
    from fluidframework_tpu.server.read_replica import ReadReplica
    from fluidframework_tpu.server.replication import (
        make_replicated_host,
    )

    root = tempfile.mkdtemp(prefix="replica-read-bench-")
    try:
        git = GitSnapshotStore(os.path.join(root, "git"))
        storm, plane = make_replicated_host(
            "hostA", os.path.join(root, "hostA"), git,
            [os.path.join(root, "f0")], num_docs=4)
        hist = HistoryPlane(storm, summary_interval_ops=4 * k)
        doc = "doc-0"
        client = storm.service.connect(doc, lambda m: None).client_id
        storm.service.pump()
        cseq = 1
        for t in range(ticks):
            words = _cluster_words((20, t), k)
            storm.submit_frame(
                None, {"rid": t, "docs": [[doc, client, cseq, 1, k]]},
                memoryview(words.tobytes()))
            cseq += k
            storm.flush()
        rep = ReadReplica(plane.links[0].node, git, "replica0",
                          leader_label="hostA", viewer_plane=False)
        head = hist.head_seq(doc)
        rng = np.random.default_rng(20)
        seqs = rng.integers(0, head + 1, reads).tolist()

        def measure(read_fn) -> dict:
            read_fn(doc, head)  # warmup
            t0 = time.perf_counter()
            for s in seqs:
                read_fn(doc, int(s))
            dt = time.perf_counter() - t0
            return {"reads_per_s": round(reads / dt, 1),
                    "read_ms_mean": round(1e3 * dt / reads, 4)}

        leader = measure(hist.read_at)
        replica = measure(rep.read_at)
        assert rep.read_at(doc, head) == hist.read_at(doc, head)
        storm._group_wal.close()
        return {
            "shape": {"ticks": ticks, "k": k, "reads": reads,
                      "head_seq": head},
            "leader": leader,
            "replica": replica,
            "replica_over_leader_throughput": round(
                replica["reads_per_s"]
                / max(leader["reads_per_s"], 1e-9), 3),
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def emit_round20(path: str = "BENCH_r20.json") -> dict:
    """ISSUE 18 acceptance bars: the read-replica tier. Columns:
    viewer broadcast p99 @10k viewers vs replica count (0/1/2/4 — the
    >=2x bar at 4), writer ack p99 with a replica attached vs OFF (the
    <=1.1x non-interference bar), replica staleness p99 (the explicit
    bound), and leader-vs-replica ``read_at`` throughput."""
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    from fluidframework_tpu.utils import compile_cache

    compile_cache.enable()
    out: dict = {"round": 20,
                 "environment": {"backend": jax.default_backend(),
                                 "devices": len(jax.devices())}}
    out["viewer_broadcast_spread"] = bench_replica_broadcast()
    out["writer_ack_tax"] = bench_replica_writer_tax()
    out["read_at_throughput"] = bench_replica_read_throughput()
    out["environment"]["note"] = (
        "Round-20 tentpole: the read-replica tier. ReadReplica hosts "
        "tail the PR 19 follower WAL (pull-based poll; the subscribe "
        "seam only stamps arrivals on the leader's WAL thread) and "
        "serve the whole read surface — viewer rooms re-homed through "
        "the existing viewer_resync/moved_to machinery, read_at and "
        "branch reads via the history plane's exact fold helpers over "
        "the shared snapshot store, get_deltas catch-up via "
        "materialize_storm_records — byte-identical by construction "
        "(pinned by tests/test_read_replica.py and the chaos "
        "--replicas twin digests). The broadcast arms shard ONE 10k-"
        "viewer room across N replica planes and report max-per-host "
        "publish time per tick: the parallel-deployment bound (each "
        "replica is its own host), with real follower-WAL tails in-"
        "process and no network. Staleness is explicit: shipped-but-"
        "unapplied lag + the staleness_s apply-latency histogram, and "
        "reads above a replica's watermark wait read_wait_s then shed "
        "a moved redirect to the leader.")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    return out


def emit_round19(path: str = "BENCH_r19.json") -> dict:
    """ISSUE 17 acceptance bars: quorum-replicated WAL + leader
    failover. Columns: replication-ON (F=1 chain, F=2 majority) vs OFF
    ack p50/p99 and e2e acked ops/s under the pipelined tick (REAL
    in-process followers, real fsyncs — superseding BENCH_r16's
    modeled wal_commit_latency arms); failover blackout numbers ride
    the chaos harness reports (tests/test_chaos.py REPLICATION)."""
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    from fluidframework_tpu.utils import compile_cache

    compile_cache.enable()
    out: dict = {"round": 19,
                 "environment": {"backend": jax.default_backend(),
                                 "devices": len(jax.devices())}}
    out["replication_overhead"] = bench_replication_overhead()
    out["supersedes"] = ("BENCH_r16 wal_commit_latency_ms sweep "
                         "(modeled commit wait; these arms replicate "
                         "for real)")
    out["environment"]["note"] = (
        "Round-19 tentpole: shared-nothing HA. Every fsynced group-"
        "commit batch ships synchronously to F follower replica WALs "
        "over the storm codec framing; client acks gate on min("
        "durable, quorum-replicated), so the pipelined tick hides the "
        "replication round trip exactly as it hides the fsync. Head "
        "flips (placement directory, checkpoints, cold residency, "
        "history summaries) journal on the quorum BEFORE the backend "
        "flips; failover promotes the most advanced follower over its "
        "storm-shaped replica log through the ordinary recover() path "
        "and fences the old incarnation (moved_to shedding). In-"
        "process CPU arms: real fsyncs, zero network — the replication "
        "tax shown is the serialization + follower-fsync floor.")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    return out


def bench_net_ack_overhead(num_docs: int = 4, k: int = 64,
                           rounds: int = 150, warmup: int = 20,
                           pipeline_depth: int = 2) -> dict:
    """Round-21 headline: the acked-write path with followers in
    OTHER OS PROCESSES over localhost TCP vs the in-process arms of
    BENCH_r19. Same serving loop, same quorum gating — the delta is
    the wire: storm-codec frames over the length-prefixed transport,
    one socket round trip per shipped batch per follower. Bar:
    net F=1 ack p99 within 2x in-process F=1."""
    import os
    import shutil
    import tempfile

    from fluidframework_tpu.parallel.placement import make_cluster_host
    from fluidframework_tpu.server.durable_store import GitSnapshotStore
    from fluidframework_tpu.server.replication import (
        make_replicated_host,
    )
    from fluidframework_tpu.tools.launch_cluster import (
        launch_follower,
        reap_all,
    )

    def run_arm(followers: int, net: bool) -> dict:
        root = tempfile.mkdtemp(
            prefix=f"net-bench-{'net' if net else 'inproc'}"
                   f"-f{followers}-")
        children, links, plane = [], [], None
        try:
            git = GitSnapshotStore(os.path.join(root, "git"))
            if net:
                for i in range(followers):
                    children.append(launch_follower(
                        os.path.join(root, f"f{i}"), label=f"f{i}"))
                links = [c.link() for c in children]
            else:
                links = [os.path.join(root, f"f{i}")
                         for i in range(followers)]
            if followers:
                storm, plane = make_replicated_host(
                    "hostA", os.path.join(root, "hostA"), git, links,
                    num_docs=num_docs, pipeline_depth=pipeline_depth)
            else:
                storm = make_cluster_host(
                    "hostA", os.path.join(root, "hostA"), git,
                    num_docs=num_docs, pipeline_depth=pipeline_depth)
            docs = [f"doc-{i}" for i in range(num_docs)]
            clients = {d: storm.service.connect(
                d, lambda m: None).client_id for d in docs}
            storm.service.pump()
            cseq = {d: 1 for d in docs}
            lat: list = []

            def serve(n: int) -> None:
                for r in range(n):
                    for i, d in enumerate(docs):
                        words = _cluster_words([r, i], k)
                        t0 = time.perf_counter()
                        storm.submit_frame(
                            lambda p, t0=t0: lat.append(
                                time.perf_counter() - t0),
                            {"rid": (r, d),
                             "docs": [[d, clients[d], cseq[d], 1, k]]},
                            memoryview(words.tobytes()))
                        cseq[d] += k
                storm.flush()

            serve(warmup)
            lat.clear()
            start = time.perf_counter()
            serve(rounds)
            elapsed = time.perf_counter() - start
            assert len(lat) == rounds * num_docs, (len(lat), rounds)
            arr = np.asarray(lat) * 1e3
            out = {
                "followers": followers,
                "net": net,
                "ack_ms_p50": float(np.percentile(arr, 50)),
                "ack_ms_p99": float(np.percentile(arr, 99)),
                "acked_ops_per_s": rounds * num_docs * k / elapsed,
            }
            if plane is not None:
                assert plane.replicated_len \
                    == storm._group_wal.durable_len
                out["acks_required"] = plane.acks_required
                out["ship_failures"] = plane.stats["ship_failures"]
                rtts: list = []
                for lk in plane.links:
                    ts = getattr(lk, "transport_stats", None)
                    if ts is not None:
                        rtts.extend(ts()["rtt_s"])
                if rtts:
                    rarr = np.asarray(rtts) * 1e3
                    out["ship_rtt_ms_p50"] = float(
                        np.percentile(rarr, 50))
                    out["ship_rtt_ms_p99"] = float(
                        np.percentile(rarr, 99))
            storm._group_wal.close()
            return out
        finally:
            for lk in links:
                close = getattr(lk, "close", None)
                if close is not None:
                    close()
            for child in children:
                child.shutdown()
            reap_all()
            shutil.rmtree(root, ignore_errors=True)

    arms = {"inproc_f1": run_arm(1, net=False),
            "net_f1": run_arm(1, net=True),
            "net_f2": run_arm(2, net=True)}
    ratio = arms["net_f1"]["ack_ms_p99"] \
        / max(arms["inproc_f1"]["ack_ms_p99"], 1e-9)
    return {
        "shape": {"num_docs": num_docs, "k": k, "rounds": rounds,
                  "pipeline_depth": pipeline_depth,
                  "transport": "localhost TCP, follower subprocesses"},
        "arms": arms,
        "ack_p99_net_f1_over_inproc_f1": ratio,
        "bar_within_2x": bool(ratio <= 2.0),
    }


def bench_net_failover_blackout(num_docs: int = 4, k: int = 64,
                                rounds: int = 30) -> dict:
    """Round-21 failover: leader lives end, promotion runs OVER THE
    WIRE — hello every surviving follower child, shut the most
    advanced one down (releasing its WAL), recover a serving host from
    its directory. Per-life blackout = shutdown + recover + rearm,
    measured inside promote_over_wire."""
    import os
    import shutil
    import tempfile

    from fluidframework_tpu.server.durable_store import GitSnapshotStore
    from fluidframework_tpu.tools.launch_cluster import (
        launch_cluster,
        promote_over_wire,
        reap_all,
    )

    root = tempfile.mkdtemp(prefix="net-failover-")
    try:
        cluster = launch_cluster(root, followers=2, detector=False,
                                 num_docs=num_docs)
        git = GitSnapshotStore(os.path.join(root, "git"))
        storm, children = cluster.storm, list(cluster.children)
        docs = [f"doc-{i}" for i in range(num_docs)]
        clients = {d: storm.service.connect(
            d, lambda m: None).client_id for d in docs}
        storm.service.pump()
        cseq = {d: 1 for d in docs}

        def serve(n: int) -> None:
            for r in range(n):
                for i, d in enumerate(docs):
                    words = _cluster_words([r, i], k)
                    storm.submit_frame(
                        lambda p: None,
                        {"rid": (r, d),
                         "docs": [[d, clients[d], cseq[d], 1, k]]},
                        memoryview(words.tobytes()))
                    cseq[d] += k
            storm.flush()

        serve(rounds)
        storm.checkpoint()
        blackouts: list = []
        lives = []
        life = 0
        while children:
            # The leader "dies": abandon it (close its WAL) and
            # promote whatever the survivors hold, over real sockets.
            # Each promotion consumes the most advanced child (its
            # directory becomes the new leader); a fresh in-process
            # follower dir keeps the plane legal as children thin out.
            for lk in cluster.plane.links:
                close = getattr(lk, "close", None)
                if close is not None:
                    close()
            storm._group_wal.close()
            life += 1
            storm, plane, rep = promote_over_wire(
                children, git, num_docs=num_docs,
                follower_dirs=[os.path.join(root, f"fresh{life}")])
            cluster.storm, cluster.plane = storm, plane
            children = [c for c in children if c.alive]
            blackouts.append(rep["blackout_ms"])
            lives.append({"promoted": rep["promoted_node"],
                          "blackout_ms": rep["blackout_ms"],
                          "surviving_followers": len(children)})
            clients = {d: storm.service.connect(
                d, lambda m: None).client_id for d in docs}
            storm.service.pump()
            serve(4)
        storm._group_wal.close()
        return {
            "shape": {"num_docs": num_docs, "k": k,
                      "warm_rounds": rounds, "followers": 2},
            "lives": lives,
            "blackout_ms_per_life": blackouts,
            "blackout_ms_worst": max(blackouts),
        }
    finally:
        reap_all()
        shutil.rmtree(root, ignore_errors=True)


def bench_parked_write_recovery(num_docs: int = 2, k: int = 64,
                                writes: int = 6) -> dict:
    """Round-21 degraded mode: partition the only follower (quorum
    lost), submit writes — they PARK (durable locally, no acks, no
    shed) — then heal and measure heal -> last-parked-ack. Bar: the
    parked backlog drains within 1 s of heal (the detector's next
    heartbeat renews the lease and resyncs; the next flush ships)."""
    import os
    import shutil
    import tempfile

    from fluidframework_tpu.tools.launch_cluster import (
        launch_cluster,
        reap_all,
    )

    root = tempfile.mkdtemp(prefix="net-parked-")
    try:
        cluster = launch_cluster(
            root, followers=1, detector=True, hb_interval_s=0.05,
            lease_s=0.25, park_max_s=3600.0,
            fault_plan={"f0": {}}, num_docs=num_docs)
        storm, plane = cluster.storm, cluster.plane
        ft = plane.links[0]
        docs = [f"doc-{i}" for i in range(num_docs)]
        clients = {d: storm.service.connect(
            d, lambda m: None).client_id for d in docs}
        storm.service.pump()
        cseq = {d: 1 for d in docs}
        acked: list = []

        def submit(r: int) -> None:
            for i, d in enumerate(docs):
                words = _cluster_words([r, i], k)
                storm.submit_frame(
                    lambda p: acked.append(time.perf_counter()),
                    {"rid": (r, d),
                     "docs": [[d, clients[d], cseq[d], 1, k]]},
                    memoryview(words.tobytes()))
                cseq[d] += k
            storm.flush()

        submit(0)  # healthy warmup
        assert len(acked) == num_docs
        acked.clear()
        ft.install("partition")
        deadline = time.monotonic() + 10.0
        while plane.quorum_ok:  # lease expiry -> degraded
            assert time.monotonic() < deadline, "never degraded"
            time.sleep(0.02)
        for r in range(1, writes + 1):
            submit(r)
        parked = writes * num_docs - len(acked)
        assert len(acked) == 0, "acked without a quorum"
        assert storm.stats.get("quorum_rejects", 0) == 0  # parked, not shed
        t_heal = time.perf_counter()
        ft.heal()
        deadline = time.monotonic() + 10.0
        while len(acked) < writes * num_docs:
            assert time.monotonic() < deadline, \
                f"parked writes never drained ({len(acked)})"
            storm.flush()
            time.sleep(0.01)
        recovery_s = max(acked) - t_heal
        cluster.close()
        return {
            "shape": {"num_docs": num_docs, "k": k, "writes": writes,
                      "lease_s": 0.25, "hb_interval_s": 0.05},
            "parked_writes": parked,
            "recovery_s_after_heal": recovery_s,
            "bar_under_1s": bool(recovery_s < 1.0),
        }
    finally:
        reap_all()
        shutil.rmtree(root, ignore_errors=True)


def emit_round21(path: str = "BENCH_r21.json") -> dict:
    """ISSUE 20 acceptance bars: the networked replication transport.
    Columns: acked-write p50/p99 with followers as real OS processes
    over localhost TCP (F=1/F=2) vs the in-process F=1 arm (bar: net
    F=1 p99 within 2x), per-life failover blackout with promotion over
    the wire, and parked-write recovery after a healed partition (bar:
    drained within 1 s of heal)."""
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    from fluidframework_tpu.utils import compile_cache

    compile_cache.enable()
    out: dict = {"round": 21,
                 "environment": {"backend": jax.default_backend(),
                                 "devices": len(jax.devices())}}
    out["net_ack_overhead"] = bench_net_ack_overhead()
    out["failover_blackout"] = bench_net_failover_blackout()
    out["parked_write_recovery"] = bench_parked_write_recovery()
    out["environment"]["note"] = (
        "Round-21 tentpole: cutting the in-process cord. Followers run "
        "as real OS subprocesses serving ReplicaNode over asyncio TCP "
        "(length-prefixed frames, the alfred framing); the leader "
        "ships the SAME storm-codec replication frames through "
        "NetworkReplicaLink — per-call deadlines, bounded retransmits "
        "with jittered exponential backoff, transparent reconnection — "
        "so every byte on the wire is the byte the in-process tier "
        "ships. Lease-based failure detection (heartbeat probes, "
        "follower leases) feeds the plane's degraded mode: quorum loss "
        "PARKS writes (locally durable, acks withheld, shed only past "
        "park_max_s with retry_after_s) and heal drains through the "
        "detector's resync. Failover promotes over the wire: hello "
        "every survivor, shut down the most advanced child (releasing "
        "its WAL lock), recover a serving host from its directory, "
        "fence the old incarnation on the wire (lower-stamped frames "
        "nack `fenced` from a durable floor). The fault matrix "
        "(partitions, one-way partitions, drop/dup/reorder/slow) rides "
        "tests/test_chaos.py --netsplit with twin-digest equality.")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    return out


if __name__ == "__main__":
    if "--net-r21" in sys.argv:
        res = emit_round21()
        net = res.get("net_ack_overhead", {})
        fo = res.get("failover_blackout", {})
        park = res.get("parked_write_recovery", {})
        print(json.dumps({
            "metric": "networked replication: acked-write p99 over "
                      "localhost TCP follower processes vs in-process "
                      "+ wire failover blackout (BENCH_r21)",
            "value": net.get("ack_p99_net_f1_over_inproc_f1"),
            "unit": "net F=1 ack p99 / in-process F=1 ack p99 "
                    "(bar <= 2x)",
            "bar_within_2x": net.get("bar_within_2x"),
            "net_f1_ack_ms_p99": net.get("arms", {}).get(
                "net_f1", {}).get("ack_ms_p99"),
            "blackout_ms_per_life": fo.get("blackout_ms_per_life"),
            "parked_recovery_s": park.get("recovery_s_after_heal"),
            "parked_bar_under_1s": park.get("bar_under_1s"),
        }))
    elif "--history-r18" in sys.argv:
        res = emit_round18()
        reads = res.get("historical_reads", {})
        disk = res.get("compaction_disk", {})
        print(json.dumps({
            "metric": "history plane: historical-read p99 vs depth "
                      "behind head + disk amplification after "
                      "summarization compaction (BENCH_r18)",
            "value": disk.get("disk_amplification_after_over_before"),
            "unit": "spill_bytes_after / before (churn workload)",
            "bar_half_x": disk.get("bar_half_x"),
            "flat_once_covered": reads.get("flat_once_covered"),
            "p99_flatness_summarized": reads.get(
                "summarized", {}).get("p99_flatness_max_over_min"),
            "p99_flatness_no_summaries": reads.get(
                "no_summaries", {}).get("p99_flatness_max_over_min"),
            "trimmed_ticks": disk.get("trimmed_ticks"),
            "fork_ms": res.get("fork_merge", {}).get("fork_ms"),
            "merged_ops": res.get("fork_merge", {}).get("merged_ops"),
        }))
    elif "--replicas-r20" in sys.argv:
        res = emit_round20()
        spread = res.get("viewer_broadcast_spread", {})
        tax = res.get("writer_ack_tax", {})
        reads = res.get("read_at_throughput", {})
        print(json.dumps({
            "metric": "read-replica tier: viewer broadcast p99 @10k "
                      "viewers vs replica count + writer ack "
                      "non-interference (BENCH_r20)",
            "value": spread.get("p99_speedup_4_replicas"),
            "unit": "leader-only broadcast p99 / 4-replica p99 "
                    "(bar >= 2x)",
            "ack_p99_on_over_off": tax.get("ack_p99_on_over_off"),
            "staleness_s_p99": tax.get("arms", {}).get(
                "replica_on", {}).get("staleness_s_p99"),
            "read_at_replica_over_leader": reads.get(
                "replica_over_leader_throughput"),
        }))
    elif "--qos-r17" in sys.argv:
        res = emit_round17()
        fair = res.get("abusive_10x_fair", {}).get("tenants", {})
        print(json.dumps({
            "metric": "multi-tenant QoS: victims' ack p99 shift with "
                      "one tenant at 10x, deficit-fair vs baseline "
                      "(BENCH_r17)",
            "value": res.get("victim_p99_shift_fair"),
            "unit": "p99_abuse / p99_baseline (serving ticks)",
            "bar_victim_p99_1_25x": res.get("bar_victim_p99_1_25x"),
            "fairness_off_shift": res.get(
                "victim_p99_shift_fairness_off"),
            "abuser_confined": res.get("abuser_confined"),
            "abuser_ack_ticks_p99": fair.get("abuser", {}).get(
                "ack_ticks_p99"),
            "victim_ack_ticks_p99": fair.get("vic1", {}).get(
                "ack_ticks_p99"),
        }))
    elif "--replication-r19" in sys.argv:
        res = emit_round19()
        ov = res.get("replication_overhead", {})
        arms = ov.get("arms", {})
        print(json.dumps({
            "metric": "quorum-replicated WAL: ack p99 + e2e acked "
                      "ops/s, real F=1/F=2 followers vs replication "
                      "OFF under the pipelined tick (BENCH_r19)",
            "value": ov.get("ack_p99_f2_over_off"),
            "unit": "ack_p99_F2 / ack_p99_off",
            "ack_ms_p99_off": arms.get("off", {}).get("ack_ms_p99"),
            "ack_ms_p99_f1": arms.get("f1", {}).get("ack_ms_p99"),
            "ack_ms_p99_f2": arms.get("f2", {}).get("ack_ms_p99"),
            "ops_f1_over_off": ov.get("ops_f1_over_off"),
            "ops_f2_over_off": ov.get("ops_f2_over_off"),
            "supersedes": res.get("supersedes"),
        }))
    elif "--cluster-r16" in sys.argv:
        res = emit_round16()
        scale = res.get("scaling_2_to_4_hosts", {})
        blackout = res.get("migration_blackout", {})
        print(json.dumps({
            "metric": "elastic multi-host serving: aggregate durable-ON "
                      "ops/s going 2->4 hosts via live load-based "
                      "rebalance (BENCH_r16)",
            "value": scale.get("aggregate_ops_per_sec_4_hosts", 0.0),
            "unit": "ops/s",
            "scaling_2_to_4": scale.get("scaling_2_to_4"),
            "bar_scaling_1_8x": res.get("bar_scaling_1_8x"),
            "rebalance_convergence_s": scale.get(
                "rebalance_convergence_s"),
            "migration_blackout_ms_p50": blackout.get("blackout_ms_p50"),
            "migration_blackout_ms_p99": blackout.get("blackout_ms_p99"),
            "viewer_rehome_ms_p99": res.get("viewer_rehome", {}).get(
                "rehome_ms_p99"),
        }))
    elif "--megadoc-r15" in sys.argv:
        res = emit_round15()
        rows = res.get("megadoc_one_doc", {})
        big = rows.get("writers_10000", {})
        print(json.dumps({
            "metric": "one doc, 10k concurrent writers: durable-ON "
                      "merged ops/s, sharded lanes vs single-lane "
                      "(BENCH_r15)",
            "value": big.get("sharded", {}).get("merged_ops_per_sec",
                                                0.0),
            "unit": "ops/s",
            "sharded_vs_single_lane": big.get("sharded_vs_single_lane"),
            "bar_10k_writers_2x": res.get("bar_10k_writers_2x"),
            "ack_ms_p99_sharded": big.get("sharded", {}).get(
                "ack_ms_p99"),
            "ack_ms_p99_single_lane": big.get("single_lane", {}).get(
                "ack_ms_p99"),
            "promotion_tax_ratio": res.get(
                "promotion_tax_ratio_100_writers"),
            "bar_small_doc_tax_1_05": res.get("bar_small_doc_tax_1_05"),
        }))
    elif "--viewers-r13" in sys.argv:
        res = emit_round13()
        fan = res.get("viewer_fanout", {})
        big = fan.get("viewers_100000", {})
        print(json.dumps({
            "metric": "one hot doc broadcast to 100k read-only viewers: "
                      "fan-out frames/s + broadcast p50/p99 + "
                      "serialize-once invariant (BENCH_r13)",
            "value": big.get("frames_per_sec_fanout", 0.0),
            "unit": "frames/s",
            "broadcast_ms_p50": big.get("broadcast_ms_p50"),
            "broadcast_ms_p99": big.get("broadcast_ms_p99"),
            "e2e_ops_per_sec": big.get("e2e_ops_per_sec"),
            "encodes_per_tick": big.get("encodes_per_tick"),
            "serialize_once_holds": all(
                row.get("serialize_once_holds", False)
                for row in fan.values() if isinstance(row, dict)),
        }))
    elif "--residency-r12" in sys.argv:
        res = emit_round12()
        churn = res.get("churn_1m_registered_10k_hot", {})
        storm_row = res.get("hydration_storm", {})
        print(json.dumps({
            "metric": "1M-registered / 10k-hot churn: steady-state RSS "
                      "vs hot set + hydration latency (BENCH_r12)",
            "value": churn.get("rss_vs_hot_ratio", 0.0),
            "unit": "rss_after_churn / rss_hot_steady",
            "hydration_ms_p50": churn.get("hydration_ms_p50"),
            "hydration_ms_p99": churn.get("hydration_ms_p99"),
            "churn_ops_per_sec": churn.get("churn_ops_per_sec"),
            "storm_makespan_vs_ideal_drain": storm_row.get(
                "makespan_vs_ideal_drain"),
            "rss_kb_per_cold_doc": res.get("cold_rss_slope", {}).get(
                "rss_kb_per_cold_doc"),
        }))
    elif "--rebalance-r11" in sys.argv:
        res = emit_round11()
        r11 = res.get("rebalance_r11", {})
        head = r11.get("streams", {}).get("head_concentrated", {})
        row = head.get("S=8192", {})
        serving = row.get("blocks_autotuned", row.get("blocks_base", {}))
        print(json.dumps({
            "metric": "serving-path block-table ops/sec at S=8192, "
                      "head-concentrated stream, incremental rebalance "
                      "+ autotuned geometry (BENCH_r11)",
            "value": serving.get("ops_per_sec", 0.0),
            "unit": "ops/s",
            "block_vs_flat": serving.get("block_vs_flat"),
            "rebalance_fired_per_tick": serving.get(
                "rebalance_fired_per_tick"),
            "microbench": r11.get("rebalance_microbench", {}).get(
                "S=8192"),
        }))
    elif "--e2e-r14" in sys.argv:
        res = emit_round14()
        row = res.get("e2e_storm_10k_docs_pipelined", {})
        print(json.dumps({
            "metric": "e2e storm ops/sec, durability ON, pipelined tick "
                      "(WAL commit-wait overlapped with device dispatch) "
                      "+ client windowed flow control (BENCH_r14)",
            "value": round(row.get("e2e_ops_per_sec", 0.0), 1),
            "unit": "ops/s",
            "pipelined_vs_unpipelined": res.get("pipelined_vs_unpipelined"),
            "vs_bench_r10_recorded": res.get("vs_bench_r10_recorded"),
            "overlap_ms": res.get("overlap_ms"),
            "send_to_ingress_p50_ms": res.get("send_to_ingress_p50_ms"),
            "flow_control_window_bound_ms": res.get(
                "flow_control_window_bound_ms"),
            "depth_scaling": res.get("e2e_storm_cpu_2048x256_depth_"
                                     "scaling"),
        }))
    elif "--e2e-r10" in sys.argv:
        res = emit_round10()
        row = res["e2e_storm_10k_docs"]
        att = row.get("stage_attribution", {})
        print(json.dumps({
            "metric": "e2e storm ops/sec, durability ON, stage-attributed "
                      "(BENCH_r10)",
            "value": round(row.get("e2e_ops_per_sec", 0.0), 1),
            "unit": "ops/s",
            "stage_shares": {s: v["share"] for s, v in att.items()
                             if s != "_window"},
            "ack_hops": row.get("ack_hop_decomposition_ms"),
            "tracing_overhead_pct": res.get("tracing_overhead_pct"),
        }))
    elif "--e2e-r09" in sys.argv:
        res = emit_round9()
        row = res["e2e_storm_10k_docs"]
        print(json.dumps({
            "metric": "e2e storm ops/sec, durability ON (BENCH_r09)",
            "value": round(row.get("e2e_ops_per_sec", 0.0), 1),
            "unit": "ops/s",
            "fraction_of_link_ceiling": row.get("fraction_of_link_ceiling"),
            "ack_interval_ms_p50": row.get("ack_interval_ms_p50"),
        }))
    else:
        main()
