"""C++ shuttle bus: differential vs the Python MessageBus, and the full
service running over it (services-ordering-rdkafka parity)."""

import random

import pytest

from fluidframework_tpu.native.shuttle import shuttle_available
from fluidframework_tpu.server.bus import (
    Consumer,
    MessageBus,
    partition_for,
)
from fluidframework_tpu.server.native_bus import (
    NativeMessageBus,
    make_message_bus,
)

pytestmark = pytest.mark.skipif(not shuttle_available(),
                                reason="no native toolchain")


class TestShuttleBus:
    def test_differential_against_python_bus(self):
        rng = random.Random(0)
        native = NativeMessageBus()
        python = MessageBus()
        for bus in (native, python):
            bus.create_topic("t", num_partitions=4)
        keys = [f"doc-{i}" for i in range(10)]
        for step in range(300):
            key = rng.choice(keys)
            value = {"step": step, "payload": rng.randrange(1000)}
            assert native.produce("t", key, value) == \
                python.produce("t", key, value)
        for partition in range(4):
            got = native.topic("t").read(partition, 0)
            want = python.topic("t").read(partition, 0)
            assert [(m.offset, m.key, m.value) for m in got] == \
                [(m.offset, m.key, m.value) for m in want]

    def test_partitioner_matches_crc32(self):
        bus = NativeMessageBus()
        bus.create_topic("t", num_partitions=8)
        for key in ("a", "doc-123", "ü-unicode", ""):
            pid, _ = bus.produce("t", key, {"v": 1})
            assert pid == partition_for(key, 8)

    def test_consumer_group_offsets_independent(self):
        bus = NativeMessageBus()
        bus.create_topic("t", num_partitions=1)
        for i in range(5):
            bus.produce("t", "k", i)
        a = Consumer(bus, "t", "group-a")
        b = Consumer(bus, "t", "group-b")
        assert [m.value for m in a.poll(0)] == [0, 1, 2, 3, 4]
        a.commit(0, 3)
        assert [m.value for m in a.poll(0)] == [3, 4]
        assert [m.value for m in b.poll(0)] == [0, 1, 2, 3, 4]  # fan-out
        assert [m.value for m in a.poll(0, max_messages=1)] == [3]

    def test_wire_codec_roundtrips_protocol_objects(self):
        from fluidframework_tpu.server.sequencer import RawOperation
        from fluidframework_tpu.protocol.messages import MessageType

        bus = NativeMessageBus()
        bus.create_topic("t", num_partitions=2)
        raw = RawOperation(client_id="c1", type=MessageType.OPERATION,
                           client_seq=1, ref_seq=0, timestamp=5,
                           contents={"x": [1, 2]})
        pid, _ = bus.produce("t", "doc", raw)
        message = bus.topic("t").read(pid, 0)[0]
        assert message.value == raw

    def test_service_end_to_end_on_native_bus(self):
        from fluidframework_tpu.dds.map import SharedMap
        from fluidframework_tpu.drivers.local_driver import (
            LocalDocumentService)
        from fluidframework_tpu.runtime.container import Container
        from fluidframework_tpu.server.routerlicious import (
            RouterliciousService)

        service = RouterliciousService(bus=make_message_bus())
        c1 = Container.create_detached(LocalDocumentService(service, "doc"))
        ds = c1.runtime.create_datastore("default")
        ds.create_channel("root", SharedMap.channel_type)
        c1.attach()
        c2 = Container.load(LocalDocumentService(service, "doc"))
        ds.get_channel("root").set("a", 1)
        c2.runtime.get_datastore("default").get_channel("root").set("b", 2)
        root1 = ds.get_channel("root")
        root2 = c2.runtime.get_datastore("default").get_channel("root")
        assert dict(root1.items()) == dict(root2.items()) == \
            {"a": 1, "b": 2}
        assert c1.summarize() == c2.summarize()
