"""SharedMatrix tests: row/col OT via permutation vectors + LWW cells.

Port of the reference's matrix suite intent (packages/dds/matrix/src/test):
concurrent row/col insert/remove with cell writes, pending-write shadowing,
and the matrix farm — random concurrent grid edits with convergence and
byte-identical summaries (BASELINE config 4 model).
"""

import random

import pytest

from fluidframework_tpu.dds.matrix import SharedMatrix
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.local_server import LocalCollabServer


def make_matrix_doc(server, doc_id="doc", rows=0, cols=0):
    service = LocalDocumentService(server, doc_id)
    container = Container.create_detached(service)
    datastore = container.runtime.create_datastore("default")
    matrix = datastore.create_channel("grid", SharedMatrix.channel_type)
    if rows:
        matrix.insert_rows(0, rows)
    if cols:
        matrix.insert_cols(0, cols)
    container.attach()
    return container


def get_matrix(container) -> SharedMatrix:
    return container.runtime.get_datastore("default").get_channel("grid")


def grid_of(matrix: SharedMatrix):
    return [[matrix.get_cell(r, c) for c in range(matrix.col_count)]
            for r in range(matrix.row_count)]


class TestMatrixBasics:
    def test_set_get_converges(self):
        server = LocalCollabServer()
        c1 = make_matrix_doc(server, rows=2, cols=2)
        c2 = Container.load(LocalDocumentService(server, "doc"))
        m1, m2 = get_matrix(c1), get_matrix(c2)
        m1.set_cell(0, 0, "a")
        m2.set_cell(1, 1, "d")
        assert grid_of(m1) == grid_of(m2) == [["a", None], [None, "d"]]
        assert c1.summarize() == c2.summarize()

    def test_concurrent_row_insert_shifts_cell_targets(self):
        server = LocalCollabServer()
        c1 = make_matrix_doc(server, rows=2, cols=1)
        c2 = Container.load(LocalDocumentService(server, "doc"))
        m1, m2 = get_matrix(c1), get_matrix(c2)
        m1.set_cell(1, 0, "bottom")
        # c2 hasn't seen a row insert when it writes to row 1.
        c2.inbound.pause()
        m1.insert_rows(0, 1)          # shifts old row 1 -> row 2
        m2.set_cell(1, 0, "updated")  # still targets the ORIGINAL row
        c2.inbound.resume()
        assert grid_of(m1) == grid_of(m2)
        # The write followed the row through the insert (row/col OT).
        assert m1.get_cell(2, 0) == "updated"
        assert c1.summarize() == c2.summarize()

    def test_cell_write_to_concurrently_removed_row_is_dropped(self):
        server = LocalCollabServer()
        c1 = make_matrix_doc(server, rows=2, cols=1)
        c2 = Container.load(LocalDocumentService(server, "doc"))
        m1, m2 = get_matrix(c1), get_matrix(c2)
        c2.inbound.pause()
        m1.remove_rows(0, 1)
        m2.set_cell(0, 0, "ghost")  # targets the removed row
        c2.inbound.resume()
        assert m1.row_count == m2.row_count == 1
        assert grid_of(m1) == grid_of(m2)
        assert c1.summarize() == c2.summarize()

    def test_pending_local_write_shadows_remote(self):
        server = LocalCollabServer()
        c1 = make_matrix_doc(server, rows=1, cols=1)
        c2 = Container.load(LocalDocumentService(server, "doc"))
        m1, m2 = get_matrix(c1), get_matrix(c2)
        c1.inbound.pause()
        m2.set_cell(0, 0, "theirs")  # sequenced FIRST
        m1.set_cell(0, 0, "mine")    # pending at c1, sequenced second
        assert m1.get_cell(0, 0) == "mine"  # remote shadowed by pending
        c1.inbound.resume()
        # c1's write sequenced later: wins on both.
        assert m1.get_cell(0, 0) == m2.get_cell(0, 0) == "mine"
        assert c1.summarize() == c2.summarize()

    def test_out_of_bounds_cell_raises(self):
        server = LocalCollabServer()
        c1 = make_matrix_doc(server, rows=1, cols=1)
        with pytest.raises(IndexError):
            get_matrix(c1).set_cell(5, 0, "x")


@pytest.mark.parametrize("seed", range(4))
def test_matrix_farm(seed):
    rng = random.Random(seed)
    server = LocalCollabServer()
    c1 = make_matrix_doc(server, rows=3, cols=3)
    containers = [c1] + [Container.load(LocalDocumentService(server, "doc"))
                         for _ in range(2)]
    matrices = [get_matrix(c) for c in containers]

    for _round in range(6):
        paused = [c for c in containers if rng.random() < 0.35]
        for c in paused:
            c.inbound.pause()
        for _ in range(rng.randrange(3, 9)):
            m = matrices[rng.randrange(len(matrices))]
            r = rng.random()
            if r < 0.5 and m.row_count and m.col_count:
                m.set_cell(rng.randrange(m.row_count),
                           rng.randrange(m.col_count),
                           rng.randrange(100))
            elif r < 0.65:
                m.insert_rows(rng.randrange(m.row_count + 1), 1)
            elif r < 0.8:
                m.insert_cols(rng.randrange(m.col_count + 1), 1)
            elif r < 0.9 and m.row_count > 1:
                m.remove_rows(rng.randrange(m.row_count), 1)
            elif m.col_count > 1:
                m.remove_cols(rng.randrange(m.col_count), 1)
        for c in paused:
            c.inbound.resume()
        grids = [grid_of(m) for m in matrices]
        assert grids[0] == grids[1] == grids[2], (seed, _round)
    summaries = [c.summarize() for c in containers]
    assert summaries[0] == summaries[1] == summaries[2], seed


def test_multisegment_remove_resubmit():
    # Regression: a remove spanning segments from two separate inserts,
    # submitted offline, must regenerate ALL its segments on reconnect.
    server = LocalCollabServer()
    c1 = make_matrix_doc(server, rows=0, cols=1)
    c2 = Container.load(LocalDocumentService(server, "doc"))
    m1, m2 = get_matrix(c1), get_matrix(c2)
    m1.insert_rows(0, 2)   # segment A
    m1.insert_rows(2, 2)   # segment B
    assert m2.row_count == 4
    c1.disconnect()
    m1.remove_rows(1, 2)   # spans A[1] and B[0] — two segments
    assert m1.row_count == 2
    c1.reconnect()
    assert m1.row_count == m2.row_count == 2
    assert c1.summarize() == c2.summarize()


@pytest.mark.parametrize("seed", range(2))
def test_matrix_reconnect_farm(seed):
    rng = random.Random(50 + seed)
    server = LocalCollabServer()
    c1 = make_matrix_doc(server, rows=2, cols=2)
    c2 = Container.load(LocalDocumentService(server, "doc"))
    containers = [c1, c2]
    matrices = [get_matrix(c) for c in containers]

    for _round in range(4):
        if rng.random() < 0.7:
            c2.disconnect()
        for _ in range(rng.randrange(2, 7)):
            m = matrices[rng.randrange(2)]
            r = rng.random()
            if r < 0.6 and m.row_count and m.col_count:
                m.set_cell(rng.randrange(m.row_count),
                           rng.randrange(m.col_count), rng.randrange(100))
            elif r < 0.8:
                m.insert_rows(rng.randrange(m.row_count + 1), 1)
            else:
                m.insert_cols(rng.randrange(m.col_count + 1), 1)
        if not c2.connected:
            c2.reconnect()
        grids = [grid_of(m) for m in matrices]
        assert grids[0] == grids[1], (seed, _round)
    assert c1.summarize() == c2.summarize()


def test_stashed_insert_group_acks_every_fragment():
    """A stashed insertGroup spans several engine groups; its single
    sequenced echo must ack ALL of them (one remap covering every
    fragment's temp handles) — vector_multi metadata, mirroring the
    sequence DDS's stashed_group shape."""
    from fluidframework_tpu.dds.matrix import SharedMatrix
    from fluidframework_tpu.protocol.messages import (
        MessageType,
        SequencedDocumentMessage,
    )

    m = SharedMatrix("grid", None)
    contents = {"target": "rows", "type": "insertGroup",
                "ranges": [[0, 2], [2, 3]]}
    meta = m.apply_stashed_op(contents)
    assert meta[0] == "vector_multi" and len(meta[2]) == 2
    assert len(m.rows.engine.pending_groups) == 2
    echo = SequencedDocumentMessage(
        client_id="me", sequence_number=7, minimum_sequence_number=0,
        client_sequence_number=1, reference_sequence_number=0,
        type=MessageType.OPERATION, contents=contents, timestamp=0)
    m.process_core(echo, True, meta)
    assert not m.rows.engine.pending_groups  # every fragment acked
    assert m.rows.next_handle == 5           # all temp handles remapped
    assert m.row_count == 5
