"""Agent scheduler, undo-redo, interceptions, last-edited tests.

Reference parity model: packages/runtime/agent-scheduler tests (task claims,
leader election, reassignment on leave), packages/framework/undo-redo,
dds-interceptions, last-edited.
"""

from fluidframework_tpu.dds.cell import SharedCell
from fluidframework_tpu.dds.counter import SharedCounter
from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.dds.summary_block import SharedSummaryBlock
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.framework.interceptions import (
    create_map_with_interception,
    create_string_with_interception,
)
from fluidframework_tpu.framework.last_edited import LastEditedTracker
from fluidframework_tpu.framework.undo_redo import UndoRedoStackManager
from fluidframework_tpu.runtime.agent_scheduler import AgentScheduler
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.local_server import LocalCollabServer


def _doc(server, *channels, doc_id="doc"):
    service = LocalDocumentService(server, doc_id)
    container = Container.create_detached(service)
    ds = container.runtime.create_datastore("default")
    for name, cls in channels:
        ds.create_channel(name, cls.channel_type)
    container.attach()
    return container


def _open(server, doc_id="doc"):
    return Container.load(LocalDocumentService(server, doc_id))


def _chan(container, name):
    return container.runtime.get_datastore("default").get_channel(name)


class TestAgentScheduler:
    def test_single_claimant_wins(self):
        server = LocalCollabServer()
        c1 = _doc(server)
        c2 = _open(server)
        s1, s2 = AgentScheduler.get(c1), AgentScheduler.get(c2)

        won = []
        s1.pick("summarizer", lambda: won.append("c1"))
        s2.pick("summarizer", lambda: won.append("c2"))
        assert won == ["c1"]
        assert s1.claimant("summarizer") == c1.client_id
        assert s2.claimant("summarizer") == c1.client_id
        assert s1.picked_tasks() == ["summarizer"]
        assert s2.picked_tasks() == []

    def test_reassign_on_leave(self):
        server = LocalCollabServer()
        c1 = _doc(server)
        c2 = _open(server)
        s1, s2 = AgentScheduler.get(c1), AgentScheduler.get(c2)

        elected = []
        s1.volunteer_for_leadership(lambda: elected.append("c1"))
        s2.volunteer_for_leadership(lambda: elected.append("c2"))
        assert s1.is_leader and not s2.is_leader

        c1.disconnect()
        assert s2.is_leader
        assert elected == ["c1", "c2"]

    def test_release_reassigns_to_interested_client(self):
        server = LocalCollabServer()
        c1 = _doc(server)
        c2 = _open(server)
        s1, s2 = AgentScheduler.get(c1), AgentScheduler.get(c2)

        s1.pick("task")
        s2.pick("task")
        s1.release("task")
        # c2 re-volunteers automatically when it sees the release land.
        assert s2.claimant("task") == c2.client_id
        assert s2.picked_tasks() == ["task"]

    def test_callback_may_pick_more_tasks(self):
        server = LocalCollabServer()
        c1 = _doc(server)
        s1 = AgentScheduler.get(c1)
        won = []
        s1.pick("first", lambda: (won.append("first"),
                                  s1.pick("second",
                                          lambda: won.append("second"))))
        assert won == ["first", "second"]


class TestUndoRedo:
    def test_map_undo_redo(self):
        server = LocalCollabServer()
        c1 = _doc(server, ("m", SharedMap))
        c2 = _open(server)
        m1 = _chan(c1, "m")
        undo = UndoRedoStackManager()
        undo.subscribe_map(m1)

        m1.set("a", 1)
        undo.close_current_operation()
        m1.set("a", 2)
        undo.close_current_operation()

        undo.undo()
        assert m1.get("a") == 1
        undo.undo()
        assert not m1.has("a")
        undo.redo()
        assert m1.get("a") == 1
        undo.redo()
        assert m1.get("a") == 2
        assert _chan(c2, "m").get("a") == 2
        assert c1.summarize() == c2.summarize()

    def test_grouped_operation_undoes_atomically(self):
        server = LocalCollabServer()
        c1 = _doc(server, ("m", SharedMap))
        m = _chan(c1, "m")
        undo = UndoRedoStackManager()
        undo.subscribe_map(m)

        m.set("x", 1)
        m.set("y", 2)
        undo.close_current_operation()
        undo.undo()
        assert not m.has("x") and not m.has("y")

    def test_counter_and_cell(self):
        server = LocalCollabServer()
        c1 = _doc(server, ("n", SharedCounter), ("c", SharedCell))
        counter, cell = _chan(c1, "n"), _chan(c1, "c")
        undo = UndoRedoStackManager()
        undo.subscribe_counter(counter)
        undo.subscribe_cell(cell)

        counter.increment(5)
        undo.close_current_operation()
        cell.set("v1")
        undo.close_current_operation()

        undo.undo()
        assert cell.empty
        undo.undo()
        assert counter.value == 0
        undo.redo()
        assert counter.value == 5
        undo.redo()
        assert cell.get() == "v1"

    def test_map_stored_none_restored_not_deleted(self):
        server = LocalCollabServer()
        c1 = _doc(server, ("m", SharedMap))
        m = _chan(c1, "m")
        undo = UndoRedoStackManager()
        undo.subscribe_map(m)
        m.set("k", None)
        undo.close_current_operation()
        m.set("k", 1)
        undo.close_current_operation()
        undo.undo()
        assert m.has("k") and m.get("k") is None

    def test_string_undo_restores_markers(self):
        server = LocalCollabServer()
        c1 = _doc(server, ("s", SharedString))
        s = _chan(c1, "s")
        undo = UndoRedoStackManager()
        undo.subscribe_string(s)
        s.insert_text(0, "ab")
        undo.close_current_operation()
        s.insert_marker(1, "simple", "mk")
        undo.close_current_operation()
        s.remove_text(2, 3)  # removes 'b' (marker occupies position 1)
        undo.close_current_operation()
        assert s.get_text() == "a"
        undo.undo()
        assert s.get_text() == "ab"

    def test_string_undo_restores_annotation_props(self):
        server = LocalCollabServer()
        c1 = _doc(server, ("s", SharedString))
        s = _chan(c1, "s")
        undo = UndoRedoStackManager()
        undo.subscribe_string(s)
        s.insert_text(0, "bold", {"weight": "bold"})
        undo.close_current_operation()
        s.remove_text(0, 4)
        undo.close_current_operation()
        undo.undo()
        assert s.get_text() == "bold"
        seg = next(seg for seg in s.engine.segments
                   if seg.length and seg.removed_seq is None)
        assert seg.props == {"weight": "bold"}

    def test_string_annotate_undo_redo(self):
        server = LocalCollabServer()
        c1 = _doc(server, ("s", SharedString))
        c2 = _open(server)
        s = _chan(c1, "s")
        undo = UndoRedoStackManager()
        undo.subscribe_string(s)
        s.insert_text(0, "hello world")
        undo.close_current_operation()
        # Range spans two differently-propped regions: each segment must
        # revert to ITS prior value, not a blanket one.
        s.annotate_range(0, 5, {"weight": "bold"})
        undo.close_current_operation()
        s.annotate_range(3, 8, {"weight": "heavy", "style": "italic"})
        undo.close_current_operation()

        def props_at(i):
            pos = 0
            for seg in s.engine.segments:
                vis = s.engine._vis_len(seg, s.engine.current_seq,
                                        s.engine.local_client)
                if vis and pos <= i < pos + vis:
                    return dict(seg.props or {})
                pos += vis
            raise IndexError(i)

        assert props_at(0) == {"weight": "bold"}
        assert props_at(4) == {"weight": "heavy", "style": "italic"}
        assert props_at(7) == {"weight": "heavy", "style": "italic"}
        undo.undo()
        assert props_at(4) == {"weight": "bold"}
        assert props_at(7) == {}
        undo.undo()
        assert props_at(0) == {} and props_at(4) == {}
        undo.redo()
        assert props_at(0) == {"weight": "bold"}
        undo.redo()
        assert props_at(4) == {"weight": "heavy", "style": "italic"}
        assert c1.summarize() == c2.summarize()

    def test_string_undo_redo_converges(self):
        server = LocalCollabServer()
        c1 = _doc(server, ("s", SharedString))
        c2 = _open(server)
        s1 = _chan(c1, "s")
        undo = UndoRedoStackManager()
        undo.subscribe_string(s1)

        s1.insert_text(0, "hello world")
        undo.close_current_operation()
        s1.remove_text(5, 11)
        undo.close_current_operation()
        assert s1.get_text() == "hello"

        undo.undo()
        assert s1.get_text() == "hello world"
        undo.undo()
        assert s1.get_text() == ""
        undo.redo()
        undo.redo()
        assert s1.get_text() == "hello"
        assert _chan(c2, "s").get_text() == "hello"
        assert c1.summarize() == c2.summarize()


class TestInterceptions:
    def test_map_attribution_stamp(self):
        server = LocalCollabServer()
        c1 = _doc(server, ("m", SharedMap))
        m = _chan(c1, "m")
        wrapped = create_map_with_interception(
            m, lambda key, value: {"value": value, "author": "alice"})
        wrapped.set("k", 42)
        assert m.get("k") == {"value": 42, "author": "alice"}
        assert wrapped.get("k") == {"value": 42, "author": "alice"}

    def test_string_props_stamp(self):
        server = LocalCollabServer()
        c1 = _doc(server, ("s", SharedString))
        s = _chan(c1, "s")
        wrapped = create_string_with_interception(
            s, lambda props: {**(props or {}), "author": "bob"})
        wrapped.insert_text(0, "hi")
        assert s.get_text() == "hi"
        seg = next(seg for seg in s.engine.segments if seg.length > 0)
        assert seg.props["author"] == "bob"


class TestLastEdited:
    def test_tracks_latest_op_identically_on_replicas(self):
        server = LocalCollabServer()
        c1 = _doc(server, ("m", SharedMap), ("b", SharedSummaryBlock))
        c2 = _open(server)
        t1 = LastEditedTracker(c1, _chan(c1, "b"))
        t2 = LastEditedTracker(c2, _chan(c2, "b"))

        _chan(c1, "m").set("k", 1)
        _chan(c2, "m").set("k", 2)
        assert t1.last_edited is not None
        assert t1.last_edited["client_id"] == c2.client_id
        assert t1.last_edited == t2.last_edited
        assert c1.summarize() == c2.summarize()
