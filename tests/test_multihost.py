"""Multi-host seam (parallel/multihost.py): single-process degenerate
case + the virtual 8-device mesh, through the same addressable-shard
APIs a multi-host deployment uses."""

from __future__ import annotations

import numpy as np
import pytest

from fluidframework_tpu.ops import sequencer as seqk
from fluidframework_tpu.parallel import multihost
from fluidframework_tpu.parallel.mesh import make_mesh
from fluidframework_tpu.protocol.messages import MessageType


def test_initialize_single_process_is_noop():
    assert multihost.initialize() is False
    assert multihost.initialize(num_processes=1) is False


def test_local_docs_covers_full_range_single_process(cpu_mesh_devices):
    mesh = make_mesh(cpu_mesh_devices)
    num_docs = 32
    start, stop = multihost.local_docs(mesh, num_docs)
    assert (start, stop) == (0, num_docs)


def test_feed_assembles_sharded_batch_and_ticks(cpu_mesh_devices):
    mesh = make_mesh(cpu_mesh_devices)
    n = len(cpu_mesh_devices)
    num_docs = n * 2
    start, stop = multihost.local_docs(mesh, num_docs)

    state = seqk.init_state(num_docs, num_slots=4)
    ops = seqk.make_op_batch(
        [[dict(kind=int(MessageType.CLIENT_JOIN), slot=-1, target=0,
               timestamp=1)] for _ in range(stop - start)],
        stop - start, 2)

    state_g = multihost.feed(mesh, __np_tree(state))
    ops_g = multihost.feed(mesh, __np_tree(ops))

    # Inputs actually landed sharded over the docs axis...
    assert len({s.device for s in state_g.seq.addressable_shards}) == n

    import jax
    new_state, tickets = jax.jit(seqk.process_batch)(state_g, ops_g)
    # ...and the tick ran over the mesh: every doc sequenced its join.
    assert np.asarray(new_state.seq).tolist() == [1] * num_docs
    assert len({s.device for s in new_state.seq.addressable_shards}) == n


def __np_tree(tree):
    import jax
    return jax.tree.map(np.asarray, tree)


def test_two_process_distributed_serving():
    """REAL multi-process DCN path (VERDICT r3 item 5): coordinator +
    worker processes, each with 4 virtual CPU devices, build one global
    8-device mesh via jax.distributed, feed only their local_docs rows,
    run the fused SPMD storm tick and verify shard-local harvests plus
    cross-process psum totals. The per-process partition consumer model
    of the reference (kafka-service/partitionManager.ts:24)."""
    import socket
    import subprocess
    import sys
    from pathlib import Path

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    worker = Path(__file__).parent / "multihost_worker.py"
    env = {k: v for k, v in __import__("os").environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = str(Path(__file__).parent.parent)
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out)
    if any("Multiprocess computations aren't implemented" in out
           for out in outs):
        # This jaxlib's CPU backend cannot run cross-process collectives
        # at all (0.4.x limitation) — an environment capability gap, not
        # a serving regression; the multi-chip dryrun covers the SPMD
        # path single-process.
        pytest.skip("CPU backend lacks multiprocess collectives "
                    "(jaxlib 0.4.x)")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out}"
        assert f"OK process {pid}" in out, out
