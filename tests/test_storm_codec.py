"""Storm wire codec (protocol/codec.py binary frames): round-trip
properties, the ZERO-COPY contract (the decoded payload memoryview
aliases the receive buffer), malformed-frame rejection, and the columnar
storm-ack push format the session fast paths emit."""

import struct

import numpy as np
import pytest

from fluidframework_tpu.protocol.codec import (
    MAX_FRAME,
    TRACE_KEY,
    BroadcastBatch,
    RawBody,
    StormAck,
    decode_storm_body,
    decode_storm_push,
    encode_ops_event,
    encode_push,
    encode_storm_body,
    encode_storm_frame,
    is_storm_body,
    ops_event_encode_count,
    stamp_trace,
    trace_context,
)


class TestStormFrameRoundTrip:
    def test_roundtrip_property(self):
        """Random headers x random payload sizes survive encode→decode
        byte-identically, framed and unframed."""
        rng = np.random.default_rng(42)
        for trial in range(25):
            n = int(rng.integers(0, 512))
            words = rng.integers(0, 1 << 32, n, dtype=np.uint64)
            payload = words.astype(np.uint32).tobytes()
            header = {"op": "storm", "rid": int(rng.integers(0, 1 << 30)),
                      "docs": [[f"d{i}", f"c{i}", int(rng.integers(1, 99)),
                                1, n] for i in range(int(rng.integers(1, 5)))],
                      "trial": trial}
            body = encode_storm_body(header, payload)
            assert is_storm_body(body) or n == 0 and len(body) <= 6
            got_header, got_payload = decode_storm_body(body)
            assert got_header == header
            assert bytes(got_payload) == payload
            # Framed variant = 4-byte BE length + the identical body.
            frame = encode_storm_frame(header, payload)
            assert struct.unpack(">I", frame[:4])[0] == len(body)
            assert frame[4:] == body

    def test_empty_payload_roundtrip(self):
        header, payload = decode_storm_body(
            encode_storm_body({"op": "storm", "docs": []}, b""))
        assert header["docs"] == [] and len(payload) == 0

    def test_decode_is_zero_copy(self):
        """The payload memoryview ALIASES the receive buffer — no byte
        copy between the socket read and np.frombuffer."""
        words = np.arange(64, dtype=np.uint32)
        buf = bytearray(encode_storm_body({"op": "storm"}, words.tobytes()))
        _header, payload = decode_storm_body(buf)
        assert isinstance(payload, memoryview)
        assert payload.obj is buf  # alias, not a copy
        arr = np.frombuffer(payload, np.uint32)
        assert np.shares_memory(arr, np.frombuffer(buf, np.uint8))
        # Writes through the buffer are visible in the decoded view —
        # only possible when nothing was copied.
        buf[-4:] = (np.uint32(0xDEADBEEF)).tobytes()
        assert arr[-1] == 0xDEADBEEF

    def test_decode_of_memoryview_input_stays_zero_copy(self):
        buf = bytearray(encode_storm_body({"a": 1}, b"\x01\x02\x03\x04"))
        _h, payload = decode_storm_body(memoryview(buf))
        assert payload.obj is buf


class TestStormFrameRejection:
    def test_wrong_magic_or_version(self):
        good = bytearray(encode_storm_body({"x": 1}, b"\0\0\0\0"))
        bad_magic = bytes([1]) + bytes(good[1:])
        with pytest.raises(ValueError, match="not a v1 storm frame"):
            decode_storm_body(bad_magic)
        bad_version = bytes(good[:1]) + bytes([9]) + bytes(good[2:])
        with pytest.raises(ValueError, match="not a v1 storm frame"):
            decode_storm_body(bad_version)

    def test_truncated_bodies_rejected(self):
        body = encode_storm_body({"op": "storm", "pad": "x" * 32}, b"")
        for cut in (0, 1, 5, 6, 10, len(body) - 1):
            with pytest.raises(ValueError):
                decode_storm_body(body[:cut])

    def test_header_length_past_buffer_rejected(self):
        # A header-length field pointing past the body must fail loudly,
        # never slice into nonsense.
        body = bytes((0, 1)) + struct.pack("<I", 1 << 20) + b"{}"
        with pytest.raises(ValueError, match="truncated"):
            decode_storm_body(body)

    def test_oversize_frame_rejected_both_directions(self):
        with pytest.raises(AssertionError, match="too large"):
            encode_storm_body({}, b"\0" * (MAX_FRAME + 1))
        # Decode side: an attacker-length buffer above MAX_FRAME is
        # refused before any header parse.
        fake = bytearray(MAX_FRAME + 7)
        fake[0] = 0
        fake[1] = 1
        with pytest.raises(ValueError, match="oversized"):
            decode_storm_body(fake)


class TestStormAckCodec:
    def test_columnar_ack_roundtrip(self):
        rows = np.array([[8, 2, 9, 1], [0, 2**31 - 1, 0, 0], [3, 10, 12, 5]],
                        np.int32)
        ack = StormAck(7, rows)
        ack["dw"] = 42
        body = encode_push(ack)
        assert is_storm_body(body)
        out = decode_storm_push(body)
        assert out["rid"] == 7 and out["storm"] and out["dw"] == 42
        assert out["acks"] == rows.tolist()

    def test_ack_quarantine_fields_ride_the_header(self):
        ack = StormAck(None, np.zeros((1, 4), np.int32))
        ack["quarantined"] = ["doc-x"]
        ack["retry_after_s"] = 0.05
        out = decode_storm_push(encode_push(ack))
        assert out["quarantined"] == ["doc-x"]
        assert out["retry_after_s"] == 0.05

    def test_inprocess_ack_is_legacy_dict_shaped(self):
        """In-process consumers (chaos, tests) index the ack like the
        round-8 dict payload; the lists materialize lazily."""
        rows = np.array([[4, 1, 4, 1]], np.int32)
        ack = StormAck(3, rows)
        assert ack.get("storm") is True and ack["rid"] == 3
        assert ack["acks"] == [[4, 1, 4, 1]]

    def test_malformed_ack_payload_rejected(self):
        body = encode_storm_body({"op": "storm_ack"}, b"\0" * 10)
        with pytest.raises(ValueError, match="i32"):
            decode_storm_push(body)


class TestTraceContext:
    def test_stamp_and_extract(self):
        header = {"op": "storm", "docs": []}
        assert trace_context(header) is None
        assert stamp_trace(header, 1234) is header
        assert header[TRACE_KEY] == 1234
        assert trace_context(header) == 1234

    def test_roundtrip_property_with_and_without_trace(self):
        """Property: any header x payload round-trips byte-identically
        whether or not a trace context rides along, and the trace id
        survives arbitrary JSON-able types (the field is opaque)."""
        rng = np.random.default_rng(7)
        ids = [0, 1, 2**31 - 1, -5, "hex-abc", [3, "x"], None, 1.5]
        for trial in range(25):
            n = int(rng.integers(0, 256))
            payload = rng.integers(0, 1 << 31, n,
                                   dtype=np.int64).astype(np.uint32).tobytes()
            header = {"op": "storm", "rid": trial,
                      "docs": [["d", "c", 1, 1, n]]}
            tc = ids[trial % len(ids)]
            traced = stamp_trace(dict(header), tc)
            got, got_payload = decode_storm_body(
                encode_storm_body(traced, payload))
            assert got == traced and trace_context(got) == tc
            assert bytes(got_payload) == payload
            # The untraced twin decodes to a header WITHOUT the field —
            # tracing adds bytes only to sampled frames.
            got_plain, _ = decode_storm_body(
                encode_storm_body(header, payload))
            assert TRACE_KEY not in got_plain

    def test_old_decoder_ignores_the_new_field(self):
        """Version tolerance: the storm binary layout is UNCHANGED (the
        trace context is a JSON header key), so a consumer that predates
        the field — it only reads magic/version/docs — parses a traced
        frame identically. Simulated by the pre-round-10 read sequence
        over the raw bytes."""
        import json as _json

        payload = np.arange(16, dtype=np.uint32).tobytes()
        header = stamp_trace({"op": "storm", "rid": 3,
                              "docs": [["d", "c", 1, 1, 16]]}, 99)
        body = encode_storm_body(header, payload)
        # The round-9 decoder logic, verbatim: magic, version, hlen, JSON.
        assert body[0] == 0 and body[1] == 1
        hlen = struct.unpack_from("<I", body, 2)[0]
        old_header = _json.loads(bytes(body[6:6 + hlen]).decode())
        assert old_header["docs"] == [["d", "c", 1, 1, 16]]
        assert old_header["rid"] == 3
        assert bytes(body[6 + hlen:]) == payload

    def test_traced_ack_hops_ride_the_header(self):
        """The server's joined hop marks come back on the columnar ack
        exactly like the quarantine fields — header keys, not payload —
        so untraced consumers never see them."""
        ack = StormAck(5, np.array([[8, 1, 8, 1]], np.int32))
        ack["tc"] = 99
        ack["hops"] = {"ingress": 10, "admit": 20, "sequenced": 30,
                       "ack_tx": 40}
        out = decode_storm_push(encode_push(ack))
        assert out["tc"] == 99
        assert out["hops"] == {"ingress": 10, "admit": 20,
                               "sequenced": 30, "ack_tx": 40}
        assert list(out["hops"]) == ["ingress", "admit", "sequenced",
                                     "ack_tx"]  # JSON keeps hop order
        assert out["acks"] == [[8, 1, 8, 1]]


class TestBroadcastEncodeOnce:
    def test_shared_batch_encodes_once(self):
        batch = BroadcastBatch(({"fake": "op"},))
        before = ops_event_encode_count()
        bodies = [encode_ops_event(batch) for _ in range(5)]
        assert ops_event_encode_count() - before == 1
        assert all(b is bodies[0] for b in bodies)  # the SAME bytes object
        assert isinstance(bodies[0], RawBody)

    def test_unshared_list_encodes_each_time(self):
        before = ops_event_encode_count()
        encode_ops_event([{"fake": "op"}])
        encode_ops_event([{"fake": "op"}])
        assert ops_event_encode_count() - before == 2
