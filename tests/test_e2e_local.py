"""End-to-end tests over the in-proc local server — BASELINE config 1 smoke.

Reference parity model: packages/test/local-server-tests +
test-utils/OpProcessingController (deterministic interleaving via DeltaQueue
pausing) + the clicker example (examples/data-objects/clicker): SharedCounter
and SharedMap edited concurrently by multiple containers, asserting
byte-identical convergence via full-summary equality.
"""

import pytest

from fluidframework_tpu.dds.counter import SharedCounter
from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.local_server import LocalCollabServer


def make_doc(server, doc_id="doc"):
    """Author a clicker-shaped document and attach it."""
    service = LocalDocumentService(server, doc_id)
    container = Container.create_detached(service)
    datastore = container.runtime.create_datastore("default")
    datastore.create_channel("root", SharedMap.channel_type)
    datastore.create_channel("clicks", SharedCounter.channel_type)
    container.attach()
    return container


def open_doc(server, doc_id="doc"):
    return Container.load(LocalDocumentService(server, doc_id))


def parts(container):
    datastore = container.runtime.get_datastore("default")
    return datastore.get_channel("root"), datastore.get_channel("clicks")


class TestClickerSmoke:
    def test_two_clients_click_and_converge(self):
        server = LocalCollabServer()
        c1 = make_doc(server)
        c2 = open_doc(server)
        root1, clicks1 = parts(c1)
        root2, clicks2 = parts(c2)

        for _ in range(3):
            clicks1.increment()
        for _ in range(2):
            clicks2.increment(2)
        root1.set("title", "clicker")
        root2.set("last", "c2")

        assert clicks1.value == clicks2.value == 7
        assert dict(root1.items()) == dict(root2.items()) == {
            "title": "clicker", "last": "c2"}
        # Byte-identical convergence: the full summaries match.
        assert c1.summarize() == c2.summarize()

    def test_detached_edits_ship_via_snapshot(self):
        server = LocalCollabServer()
        service = LocalDocumentService(server, "doc")
        c1 = Container.create_detached(service)
        datastore = c1.runtime.create_datastore("default")
        root = datastore.create_channel("root", SharedMap.channel_type)
        clicks = datastore.create_channel("clicks", SharedCounter.channel_type)
        root.set("pre", "attach")
        clicks.increment(5)
        c1.attach()
        c2 = open_doc(server)
        root2, clicks2 = parts(c2)
        assert root2.get("pre") == "attach"
        assert clicks2.value == 5

    def test_detached_pending_state_does_not_shadow_after_attach(self):
        # Regression: detached edits are never submitted/acked; their pending
        # entries must reset at attach or they shadow remote ops forever.
        server = LocalCollabServer()
        service = LocalDocumentService(server, "doc")
        c1 = Container.create_detached(service)
        datastore = c1.runtime.create_datastore("default")
        root = datastore.create_channel("root", SharedMap.channel_type)
        from fluidframework_tpu.dds.cell import SharedCell
        cell = datastore.create_channel("cell", SharedCell.channel_type)
        root.set("k", "detached")
        root.clear()
        root.set("k2", "detached2")
        cell.set("detached-cell")
        c1.attach()
        c2 = open_doc(server)
        ds2 = c2.runtime.get_datastore("default")
        ds2.get_channel("root").set("k", "remote")
        ds2.get_channel("cell").set("remote-cell")
        assert root.get("k") == "remote"
        assert cell.get() == "remote-cell"
        assert c1.summarize() == c2.summarize()

    def test_quorum_membership_tracks_connections(self):
        server = LocalCollabServer()
        c1 = make_doc(server)
        c2 = open_doc(server)
        members = set(c1.protocol.quorum.get_members())
        assert members == {c1.client_id, c2.client_id}
        c2.close()
        assert set(c1.protocol.quorum.get_members()) == {c1.client_id}


class TestConflictsAndInterleaving:
    def test_same_key_conflict_resolves_lww(self):
        server = LocalCollabServer()
        c1 = make_doc(server)
        c2 = open_doc(server)
        root1, _ = parts(c1)
        root2, _ = parts(c2)

        # Pause c2's inbound: it edits blind, then catches up.
        c2.inbound.pause()
        root1.set("k", "from-c1")
        root2.set("k", "from-c2")  # sequenced after c1's (c2 submits later)
        assert root1.get("k") == "from-c2" if False else True
        c2.inbound.resume()
        assert root1.get("k") == root2.get("k") == "from-c2"
        assert c1.summarize() == c2.summarize()

    def test_three_clients_interleaved_storm(self):
        server = LocalCollabServer()
        c1 = make_doc(server)
        c2, c3 = open_doc(server), open_doc(server)
        containers = [c1, c2, c3]
        roots = [parts(c)[0] for c in containers]
        import random
        rng = random.Random(7)
        for step in range(60):
            i = rng.randrange(3)
            action = rng.random()
            if action < 0.15:
                containers[i].inbound.pause()
            elif action < 0.30:
                if containers[i].inbound.paused:
                    containers[i].inbound.resume()
            elif action < 0.8:
                roots[i].set(f"k{rng.randrange(5)}", (i, step))
            else:
                roots[i].delete(f"k{rng.randrange(5)}")
        for c in containers:
            while c.inbound.paused:
                c.inbound.resume()
        states = [dict(r.items()) for r in roots]
        assert states[0] == states[1] == states[2]
        assert c1.summarize() == c2.summarize() == c3.summarize()


class TestConcurrentCreateRace:
    def test_racing_creates_of_same_datastore_converge(self):
        # Two clients race to create the same well-known datastore id while
        # blind to each other (inbound paused). The first-sequenced attach
        # wins the state; the loser adopts the winner's snapshot and its
        # already-submitted ops apply as remote ops on every replica.
        server = LocalCollabServer()
        c1 = make_doc(server)
        c2 = open_doc(server)
        c1.inbound.pause()
        c2.inbound.pause()
        ds1 = c1.runtime.create_datastore("shared")
        m1 = ds1.create_channel("data", SharedMap.channel_type)
        m1.set("who", "c1")
        m1.set("only1", 1)
        ds2 = c2.runtime.create_datastore("shared")
        m2 = ds2.create_channel("data", SharedMap.channel_type)
        m2.set("who", "c2")
        m2.set("only2", 2)
        c1.inbound.resume()
        c2.inbound.resume()
        # c2's writes were sequenced later → LWW winner for the shared key;
        # both clients' unique keys survive on the adopted store.
        got1 = dict(c1.runtime.get_datastore("shared")
                    .get_channel("data").items())
        got2 = dict(c2.runtime.get_datastore("shared")
                    .get_channel("data").items())
        assert got1 == got2 == {"who": "c2", "only1": 1, "only2": 2}
        assert c1.summarize() == c2.summarize()
        # Adoption is in place: BOTH the DataStoreRuntime and the channel
        # object identities survive, so held references stay live...
        assert c2.runtime.get_datastore("shared") is ds2
        assert c2.runtime.get_datastore("shared").get_channel("data") is m2
        # ...and post-race writes through the held references converge.
        m2.set("after", "race")
        m1.set("also", "fine")
        got1 = dict(m1.items())
        got2 = dict(m2.items())
        assert got1 == got2 == {"who": "c2", "only1": 1, "only2": 2,
                                "after": "race", "also": "fine"}
        assert c1.summarize() == c2.summarize()

    def test_racing_channel_creates_on_shared_datastore(self):
        # Same-id CHANNEL race on an already-shared datastore: the
        # first-sequenced attach_channel wins; the loser adopts in place.
        server = LocalCollabServer()
        c1 = make_doc(server)
        c2 = open_doc(server)
        c1.runtime.create_datastore("shared")
        ds2 = c2.runtime.get_datastore("shared")
        ds1 = c1.runtime.get_datastore("shared")
        c1.inbound.pause()
        c2.inbound.pause()
        m1 = ds1.create_channel("m", SharedMap.channel_type)
        m1.set("who", "c1")
        m2 = ds2.create_channel("m", SharedMap.channel_type)
        m2.set("who", "c2")
        c1.inbound.resume()
        c2.inbound.resume()
        assert ds2.get_channel("m") is m2  # loser adopted in place
        got1, got2 = dict(m1.items()), dict(m2.items())
        assert got1 == got2 == {"who": "c2"}
        m2.set("post", 1)
        assert dict(m1.items()) == dict(m2.items())
        assert c1.summarize() == c2.summarize()

    def test_write_during_adoption_window_converges(self):
        # The loser writes through its held channel reference AFTER adopting
        # the winner's datastore but BEFORE the adopting attach_channel
        # arrives: that op's pending state targets the pre-adopt kernel, so
        # it must be voided at adoption and its echo applied as a remote op.
        server = LocalCollabServer()
        c1 = make_doc(server)
        c2 = open_doc(server)
        c1.inbound.pause()
        c2.inbound.pause()
        ds1 = c1.runtime.create_datastore("shared")
        m1 = ds1.create_channel("m", SharedMap.channel_type)
        m1.set("who", "c1")
        ds2 = c2.runtime.create_datastore("shared")
        m2 = ds2.create_channel("m", SharedMap.channel_type)
        m2.set("who", "c2")
        c1.inbound.resume()
        # Step exactly one message on c2: the winner's attach → adoption;
        # channel "m" is now adoption-pending.
        assert c2.inbound.process_one()
        assert "m" in ds2._adoption_pending
        m2.set("window", 1)  # written against the provisional state
        c2.inbound.resume()
        got1, got2 = dict(m1.items()), dict(m2.items())
        assert got1 == got2 == {"who": "c2", "window": 1}
        assert c1.summarize() == c2.summarize()

    def test_reconnect_during_adoption_window(self):
        # The loser disconnects mid-window with an unsent write pending on
        # an unadopted channel: replay must not crash, the provisional write
        # is dropped, and catch-up delivers the adopting attach_channel so
        # replicas converge (held channel reference stays live).
        server = LocalCollabServer()
        c1 = make_doc(server)
        c2 = open_doc(server)
        c1.inbound.pause()
        c2.inbound.pause()
        ds1 = c1.runtime.create_datastore("shared")
        m1 = ds1.create_channel("m", SharedMap.channel_type)
        m1.set("who", "c1")
        ds2 = c2.runtime.create_datastore("shared")
        m2 = ds2.create_channel("m", SharedMap.channel_type)
        m2.set("who", "c2")
        c1.inbound.resume()
        assert c2.inbound.process_one()  # adoption; "m" pending
        c2.disconnect()
        m2.set("lost", 1)  # never sent: provisional AND disconnected
        # While unadopted, the provisional channel stays out of summaries.
        assert "m" not in (c2.summarize()["runtime"]["datastores"]
                           ["shared"]["channels"])
        c2.inbound.resume()  # release the test's pause (disconnect holds its own)
        c2.reconnect()
        assert c2.runtime.get_datastore("shared").get_channel("m") is m2
        got1, got2 = dict(m1.items()), dict(m2.items())
        assert got1 == got2 == {"who": "c2"}, (got1, got2)
        assert c1.summarize() == c2.summarize()

    def test_late_create_with_sequenced_attach_keeps_state(self):
        # Not a race: c1's attach is long since sequenced; c2 opening and
        # writing must not void anything on c1.
        server = LocalCollabServer()
        c1 = make_doc(server)
        ds1 = c1.runtime.create_datastore("shared")
        m1 = ds1.create_channel("data", SharedMap.channel_type)
        m1.set("k", "v")
        c2 = open_doc(server)
        m2 = c2.runtime.get_datastore("shared").get_channel("data")
        m2.set("k2", "v2")
        assert dict(m1.items()) == dict(m2.items()) == {"k": "v", "k2": "v2"}
        assert c1.summarize() == c2.summarize()


class TestSummaryAndCatchup:
    def test_late_joiner_loads_summary_plus_trailing_deltas(self):
        server = LocalCollabServer()
        c1 = make_doc(server)
        root1, clicks1 = parts(c1)
        for i in range(4):
            root1.set(f"k{i}", i)
        clicks1.increment(10)
        # Summarize + upload at current seq; then more trailing ops.
        c1._service.storage.upload_snapshot(c1.summarize())
        root1.set("after", "summary")
        clicks1.increment(1)

        c3 = open_doc(server)
        root3, clicks3 = parts(c3)
        assert clicks3.value == 11
        assert root3.get("after") == "summary"
        assert c3.summarize() == c1.summarize()

    def test_quorum_proposal_accepted_across_clients(self):
        server = LocalCollabServer()
        c1 = make_doc(server)
        c2 = open_doc(server)
        c1.propose("code", "clicker@1")
        # MSN advances once both clients' refSeqs pass the proposal: any
        # subsequent ops from both clients carry fresh refSeqs.
        root1, _ = parts(c1)
        root2, _ = parts(c2)
        root1.set("a", 1)
        root2.set("b", 2)
        root1.set("c", 3)
        root2.set("d", 4)
        assert c1.protocol.quorum.get("code") == "clicker@1"
        assert c2.protocol.quorum.get("code") == "clicker@1"


class TestReconnect:
    def test_offline_edits_replay_on_reconnect(self):
        server = LocalCollabServer()
        c1 = make_doc(server)
        c2 = open_doc(server)
        root1, clicks1 = parts(c1)
        root2, clicks2 = parts(c2)

        c2.disconnect()
        # c2 edits offline; c1 edits live.
        root2.set("offline", "yes")
        clicks2.increment(3)
        root1.set("online", "yes")
        clicks1.increment(2)
        assert root2.get("online") is None

        c2.reconnect()
        assert clicks1.value == clicks2.value == 5
        assert dict(root1.items()) == dict(root2.items()) == {
            "offline": "yes", "online": "yes"}
        assert c1.summarize() == c2.summarize()

    def test_reconnect_conflict_local_pending_wins(self):
        server = LocalCollabServer()
        c1 = make_doc(server)
        c2 = open_doc(server)
        root1, _ = parts(c1)
        root2, _ = parts(c2)
        c2.disconnect()
        root2.set("k", "offline-c2")   # pending, replayed late → wins LWW
        root1.set("k", "online-c1")
        c2.reconnect()
        assert root1.get("k") == root2.get("k") == "offline-c2"
        assert c1.summarize() == c2.summarize()
