"""Example apps (SURVEY layer 6) driven end-to-end through the host's
code-proposal boundary."""

import argparse

import pytest

from fluidframework_tpu.examples import (clicker, collab_text,
                                         dice_roller, host,
                                         table_document, task_board,
                                         whiteboard)


def _args(**overrides):
    namespace = argparse.Namespace(host="127.0.0.1", port=None, doc=None)
    for key, value in overrides.items():
        setattr(namespace, key, value)
    return namespace


class TestExamples:
    def test_clicker_main(self, capsys):
        clicker.main([])
        assert "creator sees 10" in capsys.readouterr().out

    def test_collab_text_main(self, capsys):
        collab_text.main([])
        out = capsys.readouterr().out
        assert "'doc: hello world'" in out
        assert "greeting" in out

    def test_task_board_main(self, capsys):
        task_board.main([])
        assert "'done': True" in capsys.readouterr().out

    def test_dice_roller_main(self, capsys):
        dice_roller.main([])
        assert "both clients see" in capsys.readouterr().out

    def test_whiteboard_main(self, capsys):
        whiteboard.main([])
        out = capsys.readouterr().out
        assert "2 strokes" in out
        assert "'x': 30" in out

    def test_table_document_main(self, capsys):
        table_document.main([])
        assert "table_document:" in capsys.readouterr().out

    def test_exactly_once_claiming_under_race(self):
        with host.open_document("task-board", _args()) as (
                creator, joiner, settle):
            for i in range(6):
                creator.add_task(f"t{i}", f"task {i}")
            settle()
            # Both clients greedily try to claim everything.
            for _ in range(6):
                creator.claim_next()
                joiner.claim_next()
            settle()
            claimed_tasks = (list(creator.claimed().values())
                             + list(joiner.claimed().values()))
            assert sorted(claimed_tasks) == [f"t{i}" for i in range(6)]

    def test_host_routes_by_quorum_code(self):
        # A document's package comes from ITS quorum, not the opener.
        from fluidframework_tpu.drivers.local_driver import (
            LocalDocumentService)
        from fluidframework_tpu.runtime.loader import Loader
        from fluidframework_tpu.server.routerlicious import (
            RouterliciousService)

        service = RouterliciousService()
        loader = Loader(lambda doc: LocalDocumentService(service, doc),
                        host.build_code_loader())
        host.create_document(loader, "@examples/clicker",
                             "fluid://localhost/doc-a")
        host.create_document(loader, "@examples/collab-text",
                             "fluid://localhost/doc-b",
                             props={"initial_text": "hi"})

        _, obj_a = host.open_existing(loader, "fluid://localhost/doc-a")
        _, obj_b = host.open_existing(loader, "fluid://localhost/doc-b")
        assert isinstance(obj_a, clicker.Clicker)
        assert isinstance(obj_b, collab_text.CollabText)
        assert obj_b.read() == "hi"

    def test_unknown_package_rejected(self):
        from fluidframework_tpu.drivers.local_driver import (
            LocalDocumentService)
        from fluidframework_tpu.runtime.loader import Loader
        from fluidframework_tpu.server.routerlicious import (
            RouterliciousService)

        service = RouterliciousService()
        loader = Loader(lambda doc: LocalDocumentService(service, doc),
                        host.build_code_loader())
        with pytest.raises(KeyError):
            host.create_document(loader, "@examples/nope",
                                 "fluid://localhost/doc-x")
