"""Networked replication transport (round 21 tentpole,
server/transport.py): length-prefixed frames over real TCP sockets,
deadline/retry/reconnect policy, seeded link-fault injection, and
lease-based failure detection feeding the plane's degraded mode.

The bars under test here (the multi-process story rides
tests/test_chaos.py's --netsplit scenarios):

* **wire fidelity** — a replication frame shipped through a
  ``NetworkReplicaLink`` lands on the follower byte-for-byte identical
  to the same frame delivered in-process; the replica WAL files are
  bitwise equal afterwards;
* **deadline / retry** — a dead or silent peer costs bounded time:
  jittered exponential backoff, ``retransmits``/``timeouts`` counted,
  ``ReplicationLinkDown`` once the budget is spent; a bounced server
  is redialed transparently;
* **fault semantics** — every ``FaultyTransport`` pathology surfaces
  exactly as a real network would (partitions fail, ``partition_recv``
  delivers-then-fails so the retransmit is a REAL duplicate, reorder
  holds the frame and nacks with the follower's true length) and the
  node's idempotent-redelivery machinery absorbs all of them;
* **fencing on the wire** — after a follower adopts a higher
  incarnation, lower-stamped frames are refused with a ``fenced``
  nack over the socket, and the floor survives in ``hello``;
* **degraded mode** — quorum loss parks writes (no acks, no loss);
  heal + heartbeat drains the parked backlog; parking past
  ``park_max_s`` sheds loudly with a ``retry_after_s`` hint.
"""

from __future__ import annotations

import json
import socket
import threading
import time

import numpy as np
import pytest

from fluidframework_tpu.server.durable_store import GitSnapshotStore
from fluidframework_tpu.server.replication import (
    REPLICA_WAL_RELPATH,
    ReplicaLink,
    ReplicaNode,
    ReplicationLinkDown,
    _frame,
    make_replicated_host,
)
from fluidframework_tpu.server.transport import (
    LINK_FAULTS,
    FaultyTransport,
    NetworkReplicaLink,
    ReplicaServerThread,
)
from fluidframework_tpu.utils import faults

K = 8


def _words(seed, k=K):
    rng = np.random.default_rng(seed)
    kinds = rng.choice([0, 0, 0, 1], size=k).astype(np.uint32)
    slots = rng.integers(0, 16, k).astype(np.uint32)
    vals = rng.integers(0, 1 << 20, k).astype(np.uint32)
    return (kinds | (slots << 2) | (vals << 12)).astype(np.uint32)


def _batch(seq, records, **extra):
    return _frame("batch", {"seq": seq, "lens": [len(r) for r in records],
                            **extra}, b"".join(records))


def _records(n, seed=0, size=24):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 256, size).astype(np.uint8).tobytes()
            for _ in range(n)]


def _wal_bytes(data_dir):
    from pathlib import Path
    return (Path(data_dir) / REPLICA_WAL_RELPATH).read_bytes()


@pytest.fixture()
def served(tmp_path):
    """A ReplicaNode behind a real TCP socket; yields (node, server)."""
    node = ReplicaNode(tmp_path / "fnet", node_id="fnet")
    server = ReplicaServerThread(node)
    yield node, server
    server.close()
    node.close()


# -- wire round trip -----------------------------------------------------------


class TestWireRoundTrip:

    def test_batch_lands_byte_identical_to_in_process(self, served,
                                                      tmp_path):
        """The same frames, shipped over TCP and in-process, leave the
        two follower WALs bitwise equal — the transport carries
        ``on_frame`` byte-for-byte, adding nothing, reordering
        nothing."""
        node, server = served
        twin = ReplicaNode(tmp_path / "floc", node_id="floc")
        link = NetworkReplicaLink(server.port)
        local = ReplicaLink(twin)
        try:
            recs = _records(6, seed=1)
            for lk in (link, local):
                hdr = lk.call(_batch(0, recs[:4]))
                assert hdr["k"] == "ack" and hdr["len"] == 4
                hdr = lk.call(_batch(4, recs[4:]))
                assert hdr["k"] == "ack" and hdr["len"] == 6
                lk.call(_frame("heads", {"entries": [[3, "doc/a", "h3"]]}))
            assert node.log_len == twin.log_len == 6
            assert node.heads == twin.heads == {"doc/a": (3, "h3")}
            assert _wal_bytes(node.data_dir) == _wal_bytes(twin.data_dir)
        finally:
            link.close()
            twin.close()

    def test_hello_handshake_populates_node_surface(self, served):
        node, server = served
        link = NetworkReplicaLink(server.port)
        try:
            assert link.node is link  # plane reads link.node.<attr>
            assert link.node_id == "fnet"
            assert link.role == "follower"
            assert link.log_len == 0 and link.max_hseq == 0
            d = link.hello()
            assert d["leader_silence_s"] is None  # never heard a leader
            link.call(_batch(0, _records(2)))
            link.call(_frame("heads", {"entries": [[7, "doc/b", "h7"]]}))
            d = link.hello()
            assert d["len"] == 2 and d["hseq"] == 7
            assert link.heads == {"doc/b": (7, "h7")}
            assert d["leader_silence_s"] is not None
        finally:
            link.close()

    def test_control_ping_unknown_op_and_custom_handler(self, tmp_path):
        node = ReplicaNode(tmp_path / "f0")
        server = ReplicaServerThread(
            node, handlers={"echo": lambda req: {"back": req["x"]}})
        link = NetworkReplicaLink(server.port)
        try:
            assert link.control("ping") == {"ok": True}
            assert "error" in link.control("no_such_verb")
            assert link.control("echo", x=41)["back"] == 41
            # A handler that raises must not kill the connection.
            server.server.handlers["boom"] = lambda req: 1 / 0
            assert "ZeroDivisionError" in link.control("boom")["error"]
            assert link.control("ping") == {"ok": True}  # link survives
        finally:
            link.close()
            server.close()
            node.close()

    def test_shutdown_closes_node_and_releases_wal(self, tmp_path):
        """The promotion prerequisite: ``shutdown`` closes the node
        BEFORE responding, so the caller can immediately reopen the
        directory locally (the over-the-wire failover path)."""
        node = ReplicaNode(tmp_path / "f0")
        server = ReplicaServerThread(node)
        link = NetworkReplicaLink(server.port)
        try:
            link.call(_batch(0, _records(3, seed=2)))
            out = link.control("shutdown")
            assert out == {"ok": True, "closed": True}
            reopened = ReplicaNode(tmp_path / "f0")  # WAL lock released
            assert reopened.log_len == 3
            reopened.close()
        finally:
            link.close()
            server.close()


# -- deadline / retry / reconnect ----------------------------------------------


class TestRetryReconnect:

    def test_dead_port_raises_linkdown_within_budget(self):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        # Nothing listens on `port` now: connect refused, every retry.
        t0 = time.monotonic()
        with pytest.raises(ReplicationLinkDown):
            NetworkReplicaLink(port, retries=2, backoff_base_s=0.01,
                               call_timeout_s=0.5)
        assert time.monotonic() - t0 < 5.0

    def test_silent_peer_times_out_not_hangs(self):
        """A peer that accepts but never answers costs bounded time —
        the per-call deadline, not a hung link."""
        gate = socket.socket()
        gate.bind(("127.0.0.1", 0))
        gate.listen(1)
        try:
            t0 = time.monotonic()
            with pytest.raises(ReplicationLinkDown):
                NetworkReplicaLink(gate.getsockname()[1], retries=1,
                                   call_timeout_s=0.2,
                                   backoff_base_s=0.01)
            elapsed = time.monotonic() - t0
            assert 0.2 <= elapsed < 3.0
        finally:
            gate.close()

    def test_reconnects_transparently_after_server_bounce(self, tmp_path):
        node = ReplicaNode(tmp_path / "f0")
        server = ReplicaServerThread(node)
        port = server.port
        link = NetworkReplicaLink(port, retries=3, backoff_base_s=0.02)
        try:
            assert link.call(_batch(0, _records(2)))["k"] == "ack"
            dials = link.stats["reconnects"]
            server.close()
            server = ReplicaServerThread(node, port=port)
            # Same address, new server: the stale socket errors, the
            # retry loop redials, the call succeeds — no caller-visible
            # failure.
            hdr = link.call(_batch(2, _records(2, seed=5)))
            assert hdr["k"] == "ack" and hdr["len"] == 4
            assert link.stats["reconnects"] > dials
            assert link.stats["retransmits"] >= 1
        finally:
            link.close()
            server.close()
            node.close()

    def test_transport_stats_shape(self, served):
        node, server = served
        link = NetworkReplicaLink(server.port)
        try:
            link.call(_frame("probe", {}))
            ts = link.transport_stats()
            assert ts["calls"] >= 2  # hello + probe
            assert len(ts["rtt_s"]) >= 2
            assert all(r >= 0 for r in ts["rtt_s"])
            for key in ("retransmits", "reconnects", "timeouts"):
                assert key in ts
        finally:
            link.close()


# -- fault semantics -----------------------------------------------------------


class TestFaultSemantics:
    """In-process inner link — the fault wrapper's contract is
    transport-agnostic, and these must stay fast."""

    @pytest.fixture()
    def edge(self, tmp_path):
        node = ReplicaNode(tmp_path / "f0")
        ft = FaultyTransport(ReplicaLink(node), edge="f0", seed=7)
        yield node, ft
        node.close()

    def test_partition_blocks_everything_until_heal(self, edge):
        node, ft = edge
        ft.install("partition")
        with pytest.raises(ReplicationLinkDown):
            ft.call(_batch(0, _records(2)))
        assert node.log_len == 0  # nothing delivered
        assert ft.stats["partition"] == 1
        ft.heal("partition")
        assert ft.call(_batch(0, _records(2)))["len"] == 2

    def test_partition_send_loses_request(self, edge):
        node, ft = edge
        ft.install("partition_send")
        with pytest.raises(ReplicationLinkDown):
            ft.call(_batch(0, _records(2)))
        assert node.log_len == 0

    def test_partition_recv_delivers_then_fails_making_real_dups(self,
                                                                 edge):
        """The response-lost pathology: the frame LANDS, the caller
        sees failure, and the retransmit becomes a genuine duplicate
        the node must absorb idempotently."""
        node, ft = edge
        frame = _batch(0, _records(3, seed=3))
        ft.install("partition_recv")
        with pytest.raises(ReplicationLinkDown):
            ft.call(frame)
        assert node.log_len == 3  # delivered despite the failure
        ft.heal()
        hdr = ft.call(frame)  # the leader's retransmit: a REAL dup
        assert hdr["k"] == "ack" and hdr["len"] == 3
        assert node.stats["dup_records"] == 3

    def test_drop_p1_drops_every_call(self, edge):
        node, ft = edge
        ft.install("drop", p=1.0)
        for _ in range(3):
            with pytest.raises(ReplicationLinkDown):
                ft.call(_batch(0, _records(1)))
        assert node.log_len == 0 and ft.stats["drop"] == 3

    def test_dup_delivers_twice_idempotently(self, edge):
        node, ft = edge
        ft.install("dup", p=1.0)
        hdr = ft.call(_batch(0, _records(4, seed=4)))
        assert hdr["k"] == "ack" and hdr["len"] == 4
        assert node.log_len == 4  # not 8
        assert node.stats["dup_records"] == 4
        assert ft.stats["dup"] == 1

    def test_slow_link_adds_latency(self, edge):
        node, ft = edge
        ft.install("slow", s=0.05)
        t0 = time.perf_counter()
        ft.call(_batch(0, _records(1)))
        assert time.perf_counter() - t0 >= 0.05

    def test_reorder_holds_frame_nacks_true_length_then_delivers(self,
                                                                 edge):
        """Out-of-order arrival: the frame is withheld, the sender sees
        a nack carrying the follower's REAL length (what resync keys
        off), and the held frame lands before the next call."""
        node, ft = edge
        ft.install("reorder", p=1.0)
        hdr = ft.call(_batch(0, _records(2, seed=6)))
        assert hdr["k"] == "nack" and hdr["reason"] == "reorder"
        assert hdr["len"] == 0  # the follower's true length, probed
        assert node.log_len == 0  # held, not delivered
        ft.heal("reorder")
        hdr = ft.call(_frame("probe", {}))
        # The held batch was delivered FIRST, then the probe ran:
        assert node.log_len == 2
        assert hdr["k"] == "ack" and hdr["len"] == 2

    def test_seeded_faults_replay_identically(self, edge):
        node, _ = edge

        class _Sink:
            node = None

            def call(self, frame):
                return {"k": "ack", "len": 0}

        def outcomes(seed):
            ft = FaultyTransport(_Sink(), edge="f1", seed=seed)
            ft.install("drop", p=0.5)
            out = []
            for _ in range(32):
                try:
                    ft.call(b"x")
                    out.append(1)
                except ReplicationLinkDown:
                    out.append(0)
            return out

        assert outcomes(11) == outcomes(11)
        assert outcomes(11) != outcomes(12)

    def test_plan_dict_and_env_parser_install_per_edge(self, edge,
                                                       monkeypatch):
        node, _ = edge
        monkeypatch.setenv(
            "FFTPU_LINKFAULTS",
            "f0:drop@p=0.2;f0:delay@s=0.01,p=0.5;f1:partition")
        plan = faults.link_fault_plan_from_env()
        assert plan == {"f0": {"drop": {"p": 0.2},
                               "delay": {"s": 0.01, "p": 0.5}},
                        "f1": {"partition": {}}}
        ft0 = FaultyTransport(ReplicaLink(node), edge="f0", plan=plan)
        assert set(ft0.faults) == {"drop", "delay"}
        ft1 = FaultyTransport(ReplicaLink(node), edge="f1", plan=plan)
        with pytest.raises(ReplicationLinkDown):
            ft1.call(_frame("probe", {}))
        # An edge the plan doesn't name gets a clean link.
        ft2 = FaultyTransport(ReplicaLink(node), edge="f9", plan=plan)
        assert ft2.faults == {}

    def test_unknown_fault_rejected(self, edge):
        _, ft = edge
        with pytest.raises(ValueError, match="unknown link fault"):
            ft.install("blackhole")
        assert set(LINK_FAULTS) >= {"drop", "partition", "reorder"}

    def test_wrapper_is_transparent_to_plane_attribute_reads(self, edge):
        node, ft = edge
        assert ft.node is node  # link.node passthrough
        ft.call(_frame("heads", {"entries": [[2, "doc/c", "h2"]]}))
        assert ft.node.max_hseq == 2


# -- fencing on the wire -------------------------------------------------------


class TestWireFencing:

    def test_lower_incarnation_refused_over_socket(self, served,
                                                   tmp_path):
        """A zombie ex-leader's frames are refused ON THE WIRE: after
        the follower adopts incarnation N, anything stamped < N nacks
        ``fenced`` — and the floor is durable, surviving restart."""
        node, server = served
        link = NetworkReplicaLink(server.port)
        try:
            # New-regime frame adopts the higher incarnation...
            hdr = link.call(_batch(0, _records(1), inc=3))
            assert hdr["k"] == "ack"
            assert link.hello()["incarnation"] == 3
            # ...and the zombie (stamped lower / unstamped) is refused.
            hdr = link.call(_batch(1, _records(1), inc=2))
            assert hdr["k"] == "nack" and hdr["reason"] == "fenced"
            assert hdr["inc"] == 3  # the floor, for the zombie's logs
            hdr = link.call(_frame("probe", {}))
            assert hdr["k"] == "nack" and hdr["reason"] == "fenced"
            assert node.stats["fenced_frames"] == 2
            assert node.log_len == 1  # nothing fenced ever appended
        finally:
            link.close()
        node.close()
        reopened = ReplicaNode(node.data_dir)
        assert reopened.incarnation == 3  # durable floor
        reopened.close()


# -- degraded mode: park, drain, shed ------------------------------------------


class TestDegradedMode:
    """Manual-drive failure detection (no detector thread): backdate
    the lease book, call ``heartbeat()`` by hand — deterministic."""

    def _build(self, tmp_path, park_max_s=5.0):
        git = GitSnapshotStore(str(tmp_path / "git"))
        node = ReplicaNode(tmp_path / "f0")
        ft = FaultyTransport(ReplicaLink(node), edge="f0", seed=0)
        storm, plane = make_replicated_host(
            "hostA", str(tmp_path / "hostA"), git, [ft], num_docs=8)
        plane.lease_s = 0.2
        plane.park_max_s = park_max_s
        return storm, plane, ft

    def _expire_leases(self, plane):
        for nid in list(plane._last_ok):
            plane._last_ok[nid] -= 10.0

    def _one_write(self, storm, doc, cseq, sink):
        client = storm.service.connect(doc, lambda m: None).client_id
        storm.service.pump()
        w = _words([1, cseq])
        storm.submit_frame(sink, {"rid": cseq,
                                  "docs": [[doc, client, cseq, 1, K]]},
                           memoryview(w.tobytes()))
        storm.flush()

    def test_quorum_loss_parks_writes_then_heal_drains(self, tmp_path):
        storm, plane, ft = self._build(tmp_path)
        acks = []
        try:
            ft.install("partition")
            self._expire_leases(plane)
            assert plane.heartbeat() is False
            assert plane.quorum_ok is False
            assert plane.quorum_degraded_s() >= 0.0
            self._one_write(storm, "doc/p", 1, acks.append)
            # Parked: locally durable, NOT acked, NOT lost.
            assert acks == []
            assert storm.stats.get("quorum_rejects", 0) == 0
            ft.heal()
            assert plane.heartbeat() is True  # lease renewed by probe
            assert plane.quorum_ok is True
            storm.flush()  # drain the parked round
            assert [a["rid"] for a in acks] == [1]
            assert all("error" not in a for a in acks)
            assert plane.quorum_degraded_s() is None
        finally:
            if storm._group_wal is not None:
                storm._group_wal.close()

    def test_park_past_max_sheds_with_retry_hint(self, tmp_path):
        storm, plane, ft = self._build(tmp_path, park_max_s=0.0)
        acks = []
        try:
            ft.install("partition")
            self._expire_leases(plane)
            assert plane.heartbeat() is False
            assert plane.quorum_degraded_s() >= 0.0  # degraded clock on
            self._one_write(storm, "doc/s", 1, acks.append)
            assert storm.stats["quorum_rejects"] >= 1
            assert len(acks) == 1
            assert acks[0]["error"] == "quorum-lost"
            assert acks[0]["retryable"] is True
            assert acks[0]["retry_after_s"] > 0
        finally:
            if storm._group_wal is not None:
                storm._group_wal.close()

    def test_heartbeat_resyncs_lagging_follower(self, tmp_path):
        """The detector is also the repair loop: a follower that missed
        frames (transient outage) is caught up by the next heartbeat,
        not only by the next write."""
        storm, plane, ft = self._build(tmp_path)
        acks = []
        try:
            self._one_write(storm, "doc/r", 1, acks.append)
            assert len(acks) == 1
            shipped = ft.node.log_len
            assert shipped > 0
            # Simulate a missed tail: follower forgets its lease AND
            # the plane's acked watermark says it is behind.
            ft.install("partition")
            self._one_write(storm, "doc/r", 1 + K, acks.append)
            assert ft.node.log_len == shipped  # outage: frame lost
            ft.heal()
            self._expire_leases(plane)
            assert plane.heartbeat() is True
            assert ft.node.log_len == storm._group_wal.durable_len
            assert plane.stats["resyncs"] >= 1
        finally:
            if storm._group_wal is not None:
                storm._group_wal.close()


# -- end to end: a storm serving over real sockets -----------------------------


class TestNetworkedHost:

    def test_replicated_host_over_tcp_matches_in_process_follower(
            self, tmp_path):
        """``make_replicated_host`` with a ``NetworkReplicaLink``
        follower: client acks flow over the socket quorum, and the
        remote replica WAL is bitwise identical to the in-process
        follower fed by the same plane."""
        node = ReplicaNode(tmp_path / "fnet", node_id="fnet")
        server = ReplicaServerThread(node)
        git = GitSnapshotStore(str(tmp_path / "git"))
        link = NetworkReplicaLink(server.port)
        storm, plane = make_replicated_host(
            "hostA", str(tmp_path / "hostA"), git,
            [link, str(tmp_path / "floc")], num_docs=8)
        acks = []
        try:
            docs = ["doc/x", "doc/y"]
            clients = {d: storm.service.connect(d, lambda m: None).client_id
                       for d in docs}
            storm.service.pump()
            cseq = {d: 1 for d in docs}
            for _ in range(3):
                for i, d in enumerate(docs):
                    w = _words([9, cseq[d], i])
                    storm.submit_frame(
                        acks.append,
                        {"rid": (cseq[d], d),
                         "docs": [[d, clients[d], cseq[d], 1, K]]},
                        memoryview(w.tobytes()))
                    cseq[d] += K
                storm.flush()
            assert len(acks) == 6
            assert all("error" not in a for a in acks)
            local = next(lk for lk in plane.links
                         if not isinstance(lk, NetworkReplicaLink))
            assert node.log_len == local.node.log_len > 0
            assert (_wal_bytes(node.data_dir)
                    == _wal_bytes(local.node.data_dir))
            # Wire stats flowed: RTTs recorded, no retransmits needed.
            ts = link.transport_stats()
            assert ts["calls"] >= 4 and len(ts["rtt_s"]) >= 3
            # Checkpoint flips heads through the same socket quorum.
            storm.checkpoint()
            link.hello()
            assert link.max_hseq == local.node.max_hseq > 0
            assert link.heads == local.node.heads
        finally:
            link.close()
            server.close()
            node.close()
            if storm._group_wal is not None:
                storm._group_wal.close()

    def test_transport_gauges_populated(self, tmp_path):
        node = ReplicaNode(tmp_path / "f0")
        server = ReplicaServerThread(node)
        git = GitSnapshotStore(str(tmp_path / "git"))
        link = NetworkReplicaLink(server.port)
        storm, plane = make_replicated_host(
            "hostA", str(tmp_path / "hostA"), git, [link], num_docs=8)
        try:
            link.call(_frame("probe", {}))
            plane._update_gauges()
            snap = storm.merge_host.metrics.snapshot()
            assert snap["transport.links"] == 1
            assert snap["transport.rtt_p50_ms"] >= 0
            assert snap["transport.rtt_p99_ms"] >= snap[
                "transport.rtt_p50_ms"]
            assert snap["transport.calls"] >= 2
            assert snap["transport.open_partitions"] == 0
        finally:
            link.close()
            server.close()
            node.close()
            if storm._group_wal is not None:
                storm._group_wal.close()
