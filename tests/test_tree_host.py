"""SharedTree channels served by the device kernel behind the service.

BASELINE config 5 (batched tree rebase) through the SERVING path: tree
edits flow client → LocalCollabServer → KernelMergeHost → tree_kernel
rows, and the device-materialized snapshot must match every client
replica byte-for-byte — including under slot pressure (reclaim + growth),
rank-midpoint exhaustion (overflow → scalar routing), and edit shapes the
device cannot serve atomically (→ scalar routing).

Reference parity: experimental/dds/tree/src/SharedTree.ts:446 processCore,
Checkout.ts:172 rebase, hosted server-side.
"""

import random

import pytest

from fluidframework_tpu.dds.tree import SharedTree
from fluidframework_tpu.dds.tree_core import ROOT_ID
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.local_server import LocalCollabServer
from fluidframework_tpu.server.merge_host import KernelMergeHost
from fluidframework_tpu.server.routerlicious import RouterliciousService


def make_tree_doc(server, doc_id="doc"):
    service = LocalDocumentService(server, doc_id)
    container = Container.create_detached(service)
    datastore = container.runtime.create_datastore("default")
    datastore.create_channel("tree", SharedTree.channel_type)
    container.attach()
    return container


def get_tree(container) -> SharedTree:
    return container.runtime.get_datastore("default").get_channel("tree")


def node(nid, payload=None, **traits):
    return {"id": nid, "definition": "n", "payload": payload,
            "traits": {k: list(v) for k, v in traits.items()}}


def end_of(parent, label="children"):
    return {"referenceTrait": {"parent": parent, "label": label},
            "side": "end"}


def range_of(nid):
    return {"start": {"referenceSibling": nid, "side": "before"},
            "end": {"referenceSibling": nid, "side": "after"}}


def random_tree_edit(rng, tree, counter):
    """One random typed-builder edit against a replica's current view."""
    view = tree.current_view
    attached = [nid for nid in view.nodes
                if nid == ROOT_ID or view.nodes[nid].parent is not None]
    non_root = [n for n in attached if n != ROOT_ID]
    roll = rng.random()
    if roll < 0.45 or not non_root:
        nid = f"n{next(counter)}"
        spec = node(nid, payload=rng.randrange(100))
        if rng.random() < 0.3:
            spec["traits"]["kids"] = [node(f"{nid}k{i}")
                                      for i in range(rng.randrange(1, 3))]
        anchor = rng.choice(attached)
        if anchor != ROOT_ID and rng.random() < 0.5:
            place = {"referenceSibling": anchor,
                     "side": rng.choice(["before", "after"])}
        else:
            place = {"referenceTrait": {"parent": anchor,
                                        "label": rng.choice(["children",
                                                             "kids"])},
                     "side": rng.choice(["start", "end"])}
        tree.insert_node(spec, place)
    elif roll < 0.65:
        tree.set_payload(rng.choice(non_root), rng.randrange(1000))
    elif roll < 0.8:
        tree.delete_range(range_of(rng.choice(non_root)))
    else:
        src = rng.choice(non_root)
        dest_anchor = rng.choice(attached)
        if dest_anchor != ROOT_ID and rng.random() < 0.5:
            place = {"referenceSibling": dest_anchor,
                     "side": rng.choice(["before", "after"])}
        else:
            place = {"referenceTrait": {"parent": dest_anchor,
                                        "label": "children"},
                     "side": rng.choice(["start", "end"])}
        tree.move_range(range_of(src), place)


@pytest.mark.parametrize("seed", range(4))
def test_tree_farm_device_replica_matches_clients(seed):
    import itertools

    host = KernelMergeHost(flush_threshold=16)
    server = LocalCollabServer(merge_host=host)
    rng = random.Random(seed)
    counter = itertools.count()
    c1 = make_tree_doc(server, "doc")
    others = [Container.load(LocalDocumentService(server, "doc"))
              for _ in range(2)]
    replicas = [c1] + others
    for _round in range(6):
        paused = [c for c in replicas if rng.random() < 0.3]
        for c in paused:
            c.inbound.pause()
        for _ in range(rng.randrange(4, 10)):
            random_tree_edit(rng, get_tree(rng.choice(replicas)), counter)
        for c in paused:
            c.inbound.resume()
    views = [get_tree(c).current_view.serialize() for c in replicas]
    assert all(v == views[0] for v in views), "replicas diverged"
    assert host.tree_snapshot("doc", "default", "tree") == views[0]
    assert host.stats["device_ops"] > 0


def test_tree_slot_pressure_reclaims_then_grows():
    host = KernelMergeHost(flush_threshold=4, tree_slots=8)
    server = LocalCollabServer(merge_host=host)
    c1 = make_tree_doc(server, "doc")
    t1 = get_tree(c1)
    # Churn: insert then delete, forcing dead slots the reclaim pass frees.
    for i in range(6):
        t1.insert_node(node(f"tmp{i}"), end_of(ROOT_ID))
        t1.delete_range(range_of(f"tmp{i}"))
    # Then grow past the original capacity with live nodes.
    for i in range(20):
        t1.insert_node(node(f"live{i}", payload=i), end_of(ROOT_ID))
    expected = t1.current_view.serialize()
    assert host.tree_snapshot("doc", "default", "tree") == expected
    assert host._tree_slots > 8
    assert host.stats["compactions"] > 0  # the reclaim pass ran


def test_tree_unsupported_edit_shape_routes_to_scalar():
    host = KernelMergeHost(flush_threshold=4)
    server = LocalCollabServer(merge_host=host)
    c1 = make_tree_doc(server, "doc")
    c2 = Container.load(LocalDocumentService(server, "doc"))
    t1, t2 = get_tree(c1), get_tree(c2)
    t1.insert_node(node("a", payload=1), end_of(ROOT_ID))
    t1.insert_node(node("b", payload=2), end_of(ROOT_ID))
    assert host.stats["overflow_routed"] == 0
    # Two independent set_values in ONE edit: atomic in the scalar
    # Transaction, not cascade-safe on device → channel leaves the device.
    t2.apply_edit([{"type": "set_value", "node": "a", "payload": 10},
                   {"type": "set_value", "node": "b", "payload": 20}])
    assert host.stats["overflow_routed"] == 1
    expected = t1.current_view.serialize()
    assert expected == t2.current_view.serialize()
    assert host.tree_snapshot("doc", "default", "tree") == expected
    # The scalar-served channel keeps tracking later edits exactly.
    t1.insert_node(node("c"), end_of("a", "sub"))
    t2.move_range(range_of("b"), {"referenceSibling": "a", "side": "before"})
    expected = t1.current_view.serialize()
    assert expected == t2.current_view.serialize()
    assert host.tree_snapshot("doc", "default", "tree") == expected


def test_tree_rank_exhaustion_overflows_to_scalar():
    host = KernelMergeHost(flush_threshold=2)
    server = LocalCollabServer(merge_host=host)
    c1 = make_tree_doc(server, "doc")
    t1 = get_tree(c1)
    t1.insert_node(node("anchor"), end_of(ROOT_ID))
    # Repeated before-the-same-anchor inserts halve the rank gap each
    # time; ~16 splits exhaust the midpoint space → device flags overflow
    # → exact scalar rebuild from the edit log.
    for i in range(24):
        t1.insert_node(node(f"w{i}"),
                       {"referenceSibling": "anchor", "side": "before"})
    expected = t1.current_view.serialize()
    assert host.tree_snapshot("doc", "default", "tree") == expected
    assert host.stats["overflow_routed"] >= 1
    key = ("doc", "default", "tree")
    assert host._tree_rows[key].scalar is not None
    # Still converging post-reroute.
    t1.set_payload("anchor", "end")
    assert host.tree_snapshot("doc", "default", "tree") \
        == t1.current_view.serialize()


def test_tree_depth_cap_overflows_to_scalar():
    """A detach whose subtree is deeper than the kernel's propagation cap
    (MAX_DEPTH_PASSES) must NOT partially apply — the op flags overflow
    and the channel reroutes to the exact scalar replay."""
    from fluidframework_tpu.ops.tree_kernel import MAX_DEPTH_PASSES

    host = KernelMergeHost(flush_threshold=4)
    server = LocalCollabServer(merge_host=host)
    c1 = make_tree_doc(server, "doc")
    t1 = get_tree(c1)
    depth = MAX_DEPTH_PASSES + 8
    spec = node(f"c{depth - 1}", payload=depth - 1)
    for i in reversed(range(depth - 1)):
        spec = node(f"c{i}", payload=i, kids=[spec])
    t1.insert_node(spec, end_of(ROOT_ID))
    assert host.tree_snapshot("doc", "default", "tree") \
        == t1.current_view.serialize()
    t1.delete_range(range_of("c0"))
    expected = t1.current_view.serialize()
    assert host.tree_snapshot("doc", "default", "tree") == expected
    assert "c0" not in expected
    assert host.stats["overflow_routed"] >= 1


def test_tree_invalid_concurrent_edits_match():
    """Concurrent delete + edit-under-deleted-node: the late edit must be
    INVALID (dropped whole) on device exactly as on every client."""
    host = KernelMergeHost(flush_threshold=100)
    server = LocalCollabServer(merge_host=host)
    c1 = make_tree_doc(server, "doc")
    c2 = Container.load(LocalDocumentService(server, "doc"))
    t1, t2 = get_tree(c1), get_tree(c2)
    t1.insert_node(node("a"), end_of(ROOT_ID))
    t1.insert_node(node("b"), end_of("a", "sub"))
    # Concurrently: c1 deletes the subtree, c2 edits inside it.
    c2.inbound.pause()
    t1.delete_range(range_of("a"))
    t2.set_payload("b", "doomed")
    t2.insert_node(node("c"), end_of("b", "sub"))
    c2.inbound.resume()
    expected = t1.current_view.serialize()
    assert expected == t2.current_view.serialize()
    assert "b" not in expected and "c" not in expected
    assert host.tree_snapshot("doc", "default", "tree") == expected


def test_tree_through_routerlicious_and_restart():
    """Tree channels behind the full service; a restarted service with a
    fresh host rebuilds the device replica from the durable op log."""
    import itertools

    host1 = KernelMergeHost(flush_threshold=16)
    server1 = RouterliciousService(merge_host=host1)
    rng = random.Random(5)
    counter = itertools.count()
    c1 = make_tree_doc(server1, "doc")
    c2 = Container.load(LocalDocumentService(server1, "doc"))
    for _ in range(25):
        random_tree_edit(rng, get_tree(rng.choice([c1, c2])), counter)
    expected = get_tree(c1).current_view.serialize()
    assert expected == get_tree(c2).current_view.serialize()
    assert host1.tree_snapshot("doc", "default", "tree") == expected

    host2 = KernelMergeHost(flush_threshold=16)
    server2 = RouterliciousService(bus=server1.bus, store=server1.store,
                                   merge_host=host2)
    server2.connect("doc", lambda msgs: None)
    assert host2.tree_snapshot("doc", "default", "tree") == expected
