"""Cross-process e2e: alfred socket front door + network driver.

Reference parity: the socket path of the reference stack — alfred
index.ts:343-427 front door, driver-base documentDeltaConnection.ts:35 —
exercised across a REAL process boundary: the ordering service runs in a
subprocess; two client stacks in this process converge over TCP.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time

import pytest

from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.drivers.network_driver import NetworkDocumentService
from fluidframework_tpu.protocol.codec import decode_body, encode_frame
from fluidframework_tpu.protocol.messages import (
    DocumentMessage,
    MessageType,
    SequencedDocumentMessage,
    Trace,
)
from fluidframework_tpu.runtime.container import Container


@pytest.fixture(scope="module")
def alfred_port():
    proc = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.server.alfred",
         "--port", "0", "--no-merge-host"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline()
        assert line.startswith("READY "), (line, proc.stderr.read())
        yield int(line.split()[1])
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def wait_until(predicate, timeout=15.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval)
    raise AssertionError("condition not reached before timeout")


def canonical(obj):
    return json.loads(json.dumps(obj, sort_keys=True, default=list))


class TestCodec:
    def test_roundtrip_sequenced_message(self):
        msg = SequencedDocumentMessage(
            client_id="c1", sequence_number=5, minimum_sequence_number=2,
            client_sequence_number=3, reference_sequence_number=4,
            type=MessageType.OPERATION,
            contents={"address": "d", "contents": {"k": [1, 2]}},
            traces=(Trace("alfred", "submit", 1.5),), timestamp=9.0)
        frame = encode_frame({"event": "ops", "messages": [msg]})
        decoded = decode_body(frame[4:])
        assert decoded["messages"][0] == msg

    def test_roundtrip_document_message(self):
        msg = DocumentMessage(client_sequence_number=1,
                              reference_sequence_number=0,
                              type=MessageType.OPERATION,
                              contents={"x": "y"})
        decoded = decode_body(encode_frame({"messages": [msg]})[4:])
        assert decoded["messages"][0] == msg


class TestCrossProcess:
    def test_two_clients_converge_over_tcp(self, alfred_port):
        doc_id = "netdoc"
        svc1 = NetworkDocumentService("127.0.0.1", alfred_port, doc_id)
        c1 = Container.create_detached(svc1)
        ds = c1.runtime.create_datastore("default")
        ds.create_channel("root", SharedMap.channel_type)
        ds.create_channel("text", SharedString.channel_type)
        with svc1.dispatch_lock:
            c1.attach()

        svc2 = NetworkDocumentService("127.0.0.1", alfred_port, doc_id)
        with svc2.dispatch_lock:
            c2 = Container.load(svc2)

        def parts(c):
            datastore = c.runtime.get_datastore("default")
            return (datastore.get_channel("root"),
                    datastore.get_channel("text"))

        root1, text1 = parts(c1)
        root2, text2 = parts(c2)

        with svc1.dispatch_lock:
            text1.insert_text(0, "hello")
            root1.set("from1", 1)
        with svc2.dispatch_lock:
            text2.insert_text(0, "say: ")
            root2.set("from2", 2)

        def converged():
            with svc1.dispatch_lock, svc2.dispatch_lock:
                return (text1.get_text() == text2.get_text()
                        and len(text1.get_text()) == 10
                        and dict(root1.items()) == dict(root2.items())
                        == {"from1": 1, "from2": 2}
                        and c1.delta_manager.last_processed_seq
                        == c2.delta_manager.last_processed_seq)

        wait_until(converged)
        with svc1.dispatch_lock, svc2.dispatch_lock:
            assert canonical(c1.summarize()) == canonical(c2.summarize())
        svc1.close()
        svc2.close()

    def test_idle_connection_survives_socket_timeout(self, alfred_port):
        # The constructor timeout covers connection establishment and RPC
        # waits only — it must NOT double as a recv timeout that kills an
        # idle connection (no broadcasts for `timeout` seconds) from the
        # reader thread.
        svc = NetworkDocumentService("127.0.0.1", alfred_port, "idledoc",
                                     timeout=1.0)
        c = Container.create_detached(svc)
        ds = c.runtime.create_datastore("default")
        ds.create_channel("root", SharedMap.channel_type)
        with svc.dispatch_lock:
            c.attach()
        root = c.runtime.get_datastore("default").get_channel("root")
        time.sleep(2.0)  # > timeout with no inbound traffic
        with svc.dispatch_lock:
            root.set("alive", True)  # would raise ConnectionError pre-fix
        wait_until(lambda: root.get("alive") is True, timeout=5)
        svc.close()

    def test_signals_cross_process(self, alfred_port):
        doc_id = "sigdoc"
        svc1 = NetworkDocumentService("127.0.0.1", alfred_port, doc_id)
        c1 = Container.create_detached(svc1)
        c1.runtime.create_datastore("default").create_channel(
            "root", SharedMap.channel_type)
        with svc1.dispatch_lock:
            c1.attach()
        svc2 = NetworkDocumentService("127.0.0.1", alfred_port, doc_id)
        with svc2.dispatch_lock:
            c2 = Container.load(svc2)

        seen: list = []
        c2.on_signal.append(seen.append)
        with svc1.dispatch_lock:
            c1.submit_signal({"ping": 1})
        wait_until(lambda: any(s.get("content") == {"ping": 1}
                               for s in seen))
        svc1.close()
        svc2.close()

    def test_dead_socket_surfaces_disconnect_and_degrades_readonly(
            self, alfred_port):
        """ISSUE 5 satellite: a socket dying under the reader must NOT
        hang the container — the disconnect event degrades it to
        disconnected/readonly, and an AutoReconnector redials."""
        from fluidframework_tpu.drivers.utils import ReconnectPolicy
        from fluidframework_tpu.runtime.delta_manager import AutoReconnector

        svc = NetworkDocumentService("127.0.0.1", alfred_port, "dropdoc")
        container = Container.create_detached(svc)
        container.runtime.create_datastore("default").create_channel(
            "root", SharedMap.channel_type)
        with svc.dispatch_lock:
            container.attach()
        assert container.connected
        reconnected: list[str] = []
        recon = AutoReconnector(
            container.delta_manager, svc,
            policy=ReconnectPolicy(base_s=0.01, max_s=0.1, seed=1),
            on_reconnected=reconnected.append, spawn_thread=False)
        # Kill the transport out from under the reader (the server sees
        # a close; the client side must notice, not hang).
        svc._sock.shutdown(__import__("socket").SHUT_RDWR)
        wait_until(lambda: recon.disconnects == 1)
        assert not container.connected
        assert container.delta_manager.readonly
        assert container.allocate_client_seq() is None
        # The redial loop restores write mode over a fresh socket.
        recon.run()
        assert reconnected and container.connected
        assert not container.delta_manager.readonly
        with svc.dispatch_lock:
            container.runtime.get_datastore("default").get_channel(
                "root").set("after-reconnect", 1)
        wait_until(lambda: container.runtime.get_datastore("default")
                   .get_channel("root").get("after-reconnect") == 1)
        svc.close()

    def test_nack_round_trip(self, alfred_port):
        """A raw protocol-level bad op gets a NACK event back over TCP."""
        doc_id = "nackdoc"
        svc = NetworkDocumentService("127.0.0.1", alfred_port, doc_id)
        nacks: list = []
        conn = svc.connect(lambda ms: None, on_nack=nacks.append)
        # client_seq far ahead -> gap -> NACK (deli checkOrder).
        conn.submit([DocumentMessage(
            client_sequence_number=999, reference_sequence_number=1,
            type=MessageType.OPERATION, contents={"x": 1})])
        wait_until(lambda: len(nacks) > 0)
        assert nacks[0].operation.client_sequence_number == 999
        svc.close()


def test_malformed_storm_push_fails_loudly_not_silently():
    """A corrupt binary storm push must tear the transport down through
    the normal disconnect path — waiters fail, the disconnect event
    fires — never kill the reader thread silently (the would-be hang:
    every later _request blocks forever on a dead reader)."""
    import socket
    import threading

    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def serve():
        conn, _ = srv.accept()
        # Read the connect request frame, then answer it...
        hdr = conn.recv(4, socket.MSG_WAITALL)
        n = int.from_bytes(hdr, "big")
        req = json.loads(conn.recv(n, socket.MSG_WAITALL).decode())
        resp = json.dumps({"rid": req["rid"], "client_id": "c1"}).encode()
        conn.sendall(len(resp).to_bytes(4, "big") + resp)
        # ...then push a CORRUPT storm body (bad version byte).
        bad = b"\x00\x09" + b"\x02\x00\x00\x00{}"
        conn.sendall(len(bad).to_bytes(4, "big") + bad)
        # Leave the socket open: only the client-side decode failure can
        # end this session.
        threading.Event().wait(10)

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    svc = NetworkDocumentService("127.0.0.1", port, "doc")
    dropped = []
    svc.events.on("disconnect", lambda: dropped.append(True))
    svc.connect(lambda msgs: None)
    wait_until(lambda: dropped, timeout=10)
    assert svc.closed
    with pytest.raises((ConnectionError, RuntimeError)):
        svc._request({"op": "get_deltas", "from_seq": 0})
    srv.close()


class TestConnectTimeRedirect:
    """Round-17 satellite (ROADMAP item 2 residue): alfred consults the
    placement directory AT CONNECT TIME and answers ``moved_to``; the
    driver redials the named owner instead of connecting locally and
    only learning the move from per-frame nacks."""

    def _serve_pair(self):
        import asyncio
        import threading
        from types import SimpleNamespace

        from fluidframework_tpu.server.alfred import (
            AlfredServer,
            build_default_service,
        )

        svc_a = build_default_service(merge_host=False)
        svc_b = build_default_service(merge_host=False)
        # Host A's placement says every doc moved to hostB.
        svc_a.storm = SimpleNamespace(
            placement=SimpleNamespace(
                route=lambda d: ("moved", "hostB"), retry_after_s=0.01),
            residency=None, megadoc=None)
        ports = {}
        ready = threading.Event()

        def runner():
            async def serve():
                a = AlfredServer(svc_a, port=0)
                ports["A"] = await a.start()
                b = AlfredServer(svc_b, port=0)
                ports["B"] = await b.start()
                ready.set()
                await asyncio.Event().wait()

            asyncio.run(serve())

        threading.Thread(target=runner, daemon=True).start()
        assert ready.wait(15)
        return ports

    def test_connect_moved_redials_owner(self):
        from fluidframework_tpu.drivers.network_driver import (
            NetworkDocumentService,
        )

        ports = self._serve_pair()
        svc = NetworkDocumentService(
            "127.0.0.1", ports["A"], "doc-x",
            hosts={"hostB": ("127.0.0.1", ports["B"])})
        conn = svc.connect(lambda msgs: None)
        # The session landed on the OWNER (host B) transparently.
        assert conn.client_id
        assert svc._addr == ("127.0.0.1", ports["B"])
        # The redialed session serves normally end to end.
        assert svc.delta_storage.get_deltas(0) is not None
        svc.close()

    def test_connect_moved_without_address_book_surfaces(self):
        from fluidframework_tpu.drivers.network_driver import (
            NetworkDocumentService,
        )
        from fluidframework_tpu.drivers.utils import DocumentMovedError

        ports = self._serve_pair()
        svc = NetworkDocumentService("127.0.0.1", ports["A"], "doc-y")
        with pytest.raises(DocumentMovedError) as err:
            svc.connect(lambda msgs: None)
        assert err.value.moved_to == "hostB"
        svc.close()
