"""Virtualized snapshot driver (drivers/virtualized_driver.py) — the
odsp-driver depth beyond caching: partial snapshot fetch with lazy blob
resolution through the runtime's lazy channel realization, plus the
summary upload manager's content-addressed handle reuse."""

import random

from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.drivers.cached_driver import CachingDocumentService
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.drivers.virtualized_driver import (
    VirtualizedDocumentService,
    is_virtual_stub,
)
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.local_server import LocalCollabServer


def make_big_doc(server, doc_id="doc", big_chars=4000):
    service = VirtualizedDocumentService(
        LocalDocumentService(server, doc_id), inline_blob_bytes=512)
    c = Container.create_detached(service)
    ds = c.runtime.create_datastore("default")
    ds.create_channel("big", SharedString.channel_type)
    ds.create_channel("small", SharedMap.channel_type)
    big = ds.get_channel("big")
    big.insert_text(0, "x" * big_chars)
    ds.get_channel("small").set("k", 1)
    c.attach()
    return service, c


def test_upload_virtualizes_large_channels_only():
    server = LocalCollabServer()
    service, c1 = make_big_doc(server)
    assert service.stats["blobs_uploaded"] == 1  # only the big channel
    raw = LocalDocumentService(server, "doc").storage.get_latest_snapshot()
    channels = raw["runtime"]["datastores"]["default"]["channels"]
    assert is_virtual_stub(channels["big"])
    assert not is_virtual_stub(channels["small"])


def test_load_defers_blob_fetch_until_channel_access():
    server = LocalCollabServer()
    _, c1 = make_big_doc(server)
    service2 = VirtualizedDocumentService(
        LocalDocumentService(server, "doc"), inline_blob_bytes=512)
    c2 = Container.load(service2)
    # The tree loaded; the big channel's blob did NOT.
    assert service2.stats["blob_fetches"] == 0
    ds = c2.runtime.get_datastore("default")
    assert dict(ds.get_channel("small").data.items()) == {"k": 1}
    assert service2.stats["blob_fetches"] == 0  # small was inline
    text = ds.get_channel("big").get_text()
    assert text == "x" * 4000
    assert service2.stats["blob_fetches"] == 1  # fetched on first access
    # Repeat access hits the realized object, not the wire.
    ds.get_channel("big").get_text()
    assert service2.stats["blob_fetches"] == 1


def test_lazy_channels_keep_converging_after_load():
    server = LocalCollabServer()
    _, c1 = make_big_doc(server, big_chars=2000)
    service2 = VirtualizedDocumentService(
        LocalDocumentService(server, "doc"), inline_blob_bytes=512)
    c2 = Container.load(service2)
    t1 = c1.runtime.get_datastore("default").get_channel("big")
    # A remote op to the lazy channel realizes it (resolving the blob)
    # and applies in order.
    t1.insert_text(0, "HEAD-")
    t2 = c2.runtime.get_datastore("default").get_channel("big")
    assert t2.get_text() == t1.get_text()
    rng = random.Random(4)
    for _ in range(40):
        t = t1 if rng.random() < 0.5 else t2
        t.insert_text(rng.randrange(len(t.get_text())), "ab")
    assert t1.get_text() == t2.get_text()


def test_summary_upload_reuses_unchanged_blobs():
    server = LocalCollabServer()
    service, c1 = make_big_doc(server)
    assert service.stats["blobs_uploaded"] == 1
    # Change ONLY the small channel; the big channel's bytes are
    # unchanged, so re-summarizing reuses its content-addressed blob.
    c1.runtime.get_datastore("default").get_channel("small").set("k", 2)
    service.storage.upload_snapshot(c1.summarize())
    assert service.stats["blobs_uploaded"] == 1
    assert service.stats["blobs_reused"] == 1
    assert service.stats["bytes_saved"] > 0
    # Change the big channel: new content, new blob.
    c1.runtime.get_datastore("default").get_channel("big").insert_text(
        0, "delta")
    service.storage.upload_snapshot(c1.summarize())
    assert service.stats["blobs_uploaded"] == 2


def test_composes_under_caching_driver():
    """odsp shape: cache + epoch over virtualization — a third client
    through the stacked drivers loads and converges."""
    server = LocalCollabServer()
    _, c1 = make_big_doc(server, big_chars=3000)
    stacked = CachingDocumentService(VirtualizedDocumentService(
        LocalDocumentService(server, "doc"), inline_blob_bytes=512))
    c3 = Container.load(stacked)
    t3 = c3.runtime.get_datastore("default").get_channel("big")
    t1 = c1.runtime.get_datastore("default").get_channel("big")
    t3.insert_text(0, "from-three:")
    assert t1.get_text() == t3.get_text()


def test_reload_after_summary_roundtrips_stubs():
    """Summarize → upload (virtualized) → fresh load → identical doc."""
    server = LocalCollabServer()
    service, c1 = make_big_doc(server)
    big = c1.runtime.get_datastore("default").get_channel("big")
    big.insert_text(0, "v2:")
    service.storage.upload_snapshot(c1.summarize())
    c2 = Container.load(VirtualizedDocumentService(
        LocalDocumentService(server, "doc"), inline_blob_bytes=512))
    assert (c2.runtime.get_datastore("default").get_channel("big")
            .get_text() == big.get_text())
    assert c2.summarize() == c1.summarize()
