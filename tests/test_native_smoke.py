"""Tier-1 native smoke: build (or detect) the `native/_build` artifacts
and exercise bridge + fanout TOGETHER once through the real serving
path, so CI catches native/Python frame-layout drift — a bridge whose
event layout, framing, or send rc contract silently diverged from
bridge.py, or a fanout whose batch-publish record layout diverged from
fanout.py, fails here rather than only under bench load."""

from __future__ import annotations

import socket
import time
from pathlib import Path

import numpy as np
import pytest

from fluidframework_tpu.native import _loader
from fluidframework_tpu.native.bridge import _load_library as load_bridge
from fluidframework_tpu.native.fanout import _load_library as load_fanout

pytestmark = pytest.mark.skipif(
    load_bridge() is None or load_fanout() is None,
    reason="no C++ toolchain and no prebuilt native artifacts")


def test_build_artifacts_match_current_sources():
    """Every loaded native lib is the hash-keyed artifact of the CURRENT
    .cpp next to it — a stale or foreign .so must never serve."""
    import hashlib

    native = Path(_loader.__file__).parent
    for name in ("bridge", "fanout"):
        src = native / f"{name}.cpp"
        digest = hashlib.sha256(src.read_bytes()).hexdigest()[:16]
        artifact = native / "_build" / f"lib{name}.{digest}.so"
        assert artifact.exists(), (
            f"{name}: no artifact for the current source hash {digest} — "
            "build_and_load should have produced it")


def test_bridge_and_fanout_serve_one_storm_tick_together():
    """One real tick over both native components: a storm frame enters
    through the C++ bridge socket, sequences on the device, broadcasts
    through the C++ fanout rooms in one batched publish, and acks back
    over the wire as a binary columnar frame — and a mode="viewer"
    session on the same bridge receives the tick's viewer broadcast
    frame (the round-13 plane riding the same native pair)."""
    import json
    from fluidframework_tpu.native.fanout import NativeFanout, make_fanout
    from fluidframework_tpu.protocol.codec import (
        decode_body,
        decode_storm_push,
        encode_storm_frame,
        is_storm_body,
        pack_map_words,
    )
    from fluidframework_tpu.server.bridge_host import BridgeFrontDoor
    from fluidframework_tpu.server.kernel_host import KernelSequencerHost
    from fluidframework_tpu.server.merge_host import KernelMergeHost
    from fluidframework_tpu.server.routerlicious import RouterliciousService
    from fluidframework_tpu.server.storm import StormController

    fanout = make_fanout()
    assert isinstance(fanout, NativeFanout) and fanout.is_native
    seq_host = KernelSequencerHost(num_slots=2, initial_capacity=4)
    merge_host = KernelMergeHost(flush_threshold=10**9)
    service = RouterliciousService(merge_host=merge_host,
                                   batched_deli_host=seq_host,
                                   auto_pump=False, fanout=fanout)
    storm = StormController(service, seq_host, merge_host,
                            flush_threshold_docs=2)
    front = BridgeFrontDoor(service, 0)
    try:
        docs = ["smoke-a", "smoke-b"]
        clients = {d: service.connect(d, lambda m: None).client_id
                   for d in docs}
        service.pump()
        # A read-only audience subscriber on each doc's fanout room.
        subs = {d: fanout.connect() for d in docs}
        for d, sub in subs.items():
            fanout.join(sub, d)

        # A read-only VIEWER session over the same bridge: mode="viewer"
        # hello, then the tick's broadcast frame as a binary push.
        import struct

        def read_frame(s):
            length = struct.unpack(">I",
                                   s.recv(4, socket.MSG_WAITALL))[0]
            return s.recv(length, socket.MSG_WAITALL)

        viewer_sock = socket.create_connection(("127.0.0.1", front.port))
        viewer_sock.settimeout(30)
        hello_req = json.dumps({"rid": 7, "op": "connect",
                                "doc_id": "smoke-a",
                                "mode": "viewer"}).encode()
        viewer_sock.sendall(len(hello_req).to_bytes(4, "big") + hello_req)
        frames = [read_frame(viewer_sock) for _ in range(2)]
        hello = next(decode_body(f) for f in frames
                     if not is_storm_body(f) and b'"rid"' in bytes(f))
        assert hello["viewer"] is True
        assert hello["client_id"].startswith("viewer-")

        k = 8
        words = pack_map_words([0] * k, list(range(k)), [7] * k)
        sock = socket.create_connection(("127.0.0.1", front.port))
        sock.settimeout(30)
        sock.sendall(encode_storm_frame(
            {"op": "storm", "rid": 1,
             "docs": [[d, clients[d], 1, 1, k] for d in docs]},
            words.astype(np.uint32).tobytes() * len(docs)))

        length = struct.unpack(">I", sock.recv(4, socket.MSG_WAITALL))[0]
        body = sock.recv(length, socket.MSG_WAITALL)
        assert is_storm_body(body), "ack must be a binary storm push"
        ack = decode_storm_push(body)
        assert ack["rid"] == 1
        assert [a[0] for a in ack["acks"]] == [k, k]

        # The viewer received the tick's once-per-doc broadcast frame.
        deadline = time.monotonic() + 15
        tick = None
        while tick is None and time.monotonic() < deadline:
            frame = read_frame(viewer_sock)
            if is_storm_body(frame):
                decoded = decode_storm_push(frame)
                if decoded.get("event") == "storm_tick":
                    tick = decoded
        assert tick is not None and tick["doc"] == "smoke-a"
        assert tick["n"] == k
        assert list(tick["words"]) == list(words)
        viewer_sock.close()

        # The batched room publish reached every subscriber.
        deadline = time.monotonic() + 10
        while (any(fanout.pending(s) == 0 for s in subs.values())
               and time.monotonic() < deadline):
            time.sleep(0.01)
        for d, sub in subs.items():
            payload = fanout.poll(sub)
            assert payload is not None and bytes(payload[:1]) == b"\x00", d
        sock.close()
    finally:
        front.close()
