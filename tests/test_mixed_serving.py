"""All-family fused sharded serving (parallel/serving.py + the mixed
storm tick): one SPMD device program tickets AND applies map, merge-tree
text, matrix and tree rows over the mesh — the reference's
one-deltas-stream-for-all-op-types contract (deli/lambda.ts:82 tickets
every op type; scriptorium/lambda.ts:16 consumes them uniformly;
partition scale-out applies to all documents,
lambdas-driver/src/kafka-service/partitionManager.ts:24)."""

import jax
import numpy as np
import pytest

from fluidframework_tpu.ops import matrix_kernel as mxk
from fluidframework_tpu.ops import mergetree_blocks as mtb
from fluidframework_tpu.ops import mergetree_kernel as mtk
from fluidframework_tpu.ops import tree_kernel as tk
from fluidframework_tpu.parallel.mesh import make_mesh
from fluidframework_tpu.parallel.serving import HostPort, ShardedServing
from fluidframework_tpu.parallel.serving import _plane_rows


@pytest.fixture(scope="module")
def mesh():
    devices = jax.devices()
    assert len(devices) >= 8, "conftest provisions a virtual 8-device mesh"
    return make_mesh(devices[:8])


def make_mixed(mesh, num_docs=16, num_hosts=2, k=8):
    serving = ShardedServing(
        mesh, num_docs=num_docs, k=k, num_hosts=num_hosts, num_clients=2,
        map_slots=16, text_slots=64, matrix_vec_slots=32,
        matrix_cell_slots=64, tree_slots=16)
    serving.join_all(slots=(0, 1))
    return serving


def family_of(row):
    return ("map", "text", "matrix", "tree")[row % 4]


TEXT_TICKS = [
    # (client, ops) per tick; inserts carry text, positions exercise
    # splits + concurrent-frame placement across clients.
    (0, [dict(kind=mtk.MT_INSERT, pos=0, text="hello world"),
         dict(kind=mtk.MT_INSERT, pos=5, text=", dear")]),
    (1, [dict(kind=mtk.MT_REMOVE, pos=0, end=5),
         dict(kind=mtk.MT_INSERT, pos=0, text="HI")]),
    (0, [dict(kind=mtk.MT_ANNOTATE, pos=0, end=4, prop_key=1, prop_val=7),
         dict(kind=mtk.MT_INSERT, pos=8, text="!!")]),
]

MATRIX_TICKS = [
    (0, [dict(target=mxk.MX_ROWS, kind=mtk.MT_INSERT, pos=0, count=2),
         dict(target=mxk.MX_COLS, kind=mtk.MT_INSERT, pos=0, count=2),
         dict(target=mxk.MX_CELL, row=0, col=0, value=11),
         dict(target=mxk.MX_CELL, row=1, col=1, value=22)]),
    (1, [dict(target=mxk.MX_CELL, row=0, col=1, value=33),
         dict(target=mxk.MX_ROWS, kind=mtk.MT_REMOVE, pos=1, end=2)]),
]

TREE_TICKS = [
    (0, [dict(kind=tk.TREE_INSERT, node=1, parent=0, trait=1, payload=5),
         dict(kind=tk.TREE_INSERT, node=2, parent=0, trait=1, payload=6)]),
    (1, [dict(kind=tk.TREE_INSERT_BEFORE, node=3, parent=2, trait=1,
              payload=7),
         dict(kind=tk.TREE_SET_VALUE, node=1, payload=9)]),
]


def drive_mixed(serving, num_docs, ticks=3):
    """Submit each row its family's scripted traffic; return last seqs."""
    cseq = {row: {0: 0, 1: 0} for row in range(num_docs)}
    ref = {row: 2 for row in range(num_docs)}  # post-join doc seq
    for t in range(ticks):
        submitted = []
        for row in range(num_docs):
            fam = family_of(row)
            if fam == "map":
                words = ((np.uint32(row % 8) << 2)
                         | (np.arange(4, dtype=np.uint32) + 100 * t) << 12)
                serving.submit(row, words, first_cseq=cseq[row][0] + 1,
                               ref_seq=ref[row], client_slot=0)
                cseq[row][0] += len(words)
                submitted.append((row, len(words)))
            elif fam == "text" and t < len(TEXT_TICKS):
                client, ops = TEXT_TICKS[t]
                serving.submit_text(row, ops, cseq[row][client] + 1,
                                    ref_seq=ref[row], client_slot=client)
                cseq[row][client] += len(ops)
                submitted.append((row, len(ops)))
            elif fam == "matrix" and t < len(MATRIX_TICKS):
                client, ops = MATRIX_TICKS[t]
                serving.submit_matrix(row, ops, cseq[row][client] + 1,
                                      ref_seq=ref[row], client_slot=client)
                cseq[row][client] += len(ops)
                submitted.append((row, len(ops)))
            elif fam == "tree" and t < len(TREE_TICKS):
                client, ops = TREE_TICKS[t]
                serving.submit_tree(row, ops, cseq[row][client] + 1,
                                    ref_seq=ref[row], client_slot=client)
                cseq[row][client] += len(ops)
                submitted.append((row, len(ops)))
        harvest = serving.tick()
        merged = {}
        for rows in harvest.values():
            merged.update(rows)
        for row, n in submitted:
            n_ok, first, last = merged[row]
            assert n_ok == n, (t, row, merged[row])
            ref[row] = last  # client saw its ack: next frame refs it
    return ref


def reference_text_state(slots=64):
    """The same text stream through the raw kernel with host-assigned
    seqs — the oracle for the on-device ticket windows."""
    state = mtk.init_state(1, slots, 4, mtk.overlap_words_for(2))
    pool = mtk.TextPool(1)
    seq = 2  # post-join
    ref = 2
    for client, ops in TEXT_TICKS:
        encoded = []
        for op in ops:
            op = dict(op)
            seq += 1
            if op.get("kind") == mtk.MT_INSERT:
                text = op.pop("text")
                op["pool_start"] = pool.append(0, text)
                op["text_len"] = len(text)
            op.update(seq=seq, ref_seq=ref, client=client)
            encoded.append(op)
        batch = mtk.make_merge_op_batch([encoded], 1, len(encoded))
        state = mtk.apply_tick(state, batch)
        ref = seq
    return state, pool


def reference_matrix_state(vec_slots=32, cell_slots=64):
    state = mxk.init_state(1, vec_slots, cell_slots,
                           mtk.overlap_words_for(2))
    alloc = mxk.HandleAllocator(1)
    seq, ref = 2, 2
    for client, ops in MATRIX_TICKS:
        encoded = []
        for op in ops:
            op = dict(op)
            seq += 1
            if (op.get("target") in (mxk.MX_ROWS, mxk.MX_COLS)
                    and op.get("kind") == mtk.MT_INSERT):
                op["handle_base"] = alloc.alloc(0, op.get("count", 1))
            op.update(seq=seq, ref_seq=ref, client=client)
            encoded.append(op)
        batch = mxk.make_matrix_op_batch([encoded], 1, len(encoded))
        state = mxk.apply_tick(state, batch)
        ref = seq
    return state


def reference_tree_state(slots=16):
    state = tk.init_state(1, slots)
    for _client, ops in TREE_TICKS:
        batch = tk.make_tree_op_batch([list(ops)], 1, len(ops))
        state, _out = tk.apply_tick(state, batch)
    return state


def row_planes(state, row):
    port = HostPort(-1, row, row + 1)
    return jax.tree.map(lambda a: _plane_rows(a, port), state)


def test_mixed_population_matches_per_family_kernels(mesh):
    """16 docs (4 of each family) served by ONE fused SPMD tick over 8
    devices match the raw per-family kernels run with the oracle seq
    assignment — the ticket windows and every family's apply leg are
    bit-exact under sharding."""
    num_docs = 16
    serving = make_mixed(mesh, num_docs=num_docs)
    drive_mixed(serving, num_docs)

    ref_text, ref_pool = reference_text_state()
    ref_mx = reference_matrix_state()
    ref_tree = reference_tree_state()
    expected_text = mtk.materialize(ref_text, ref_pool, 0)
    assert expected_text  # the script must leave visible text

    first_text = None
    for row in range(num_docs):
        fam = family_of(row)
        if fam == "text":
            # The block serving table rebalances at each tick's MSN, so
            # plane equality against the flat oracle is not meaningful;
            # the contract is byte-identical TEXT vs the flat kernel,
            # bitwise-identical block state across same-traffic rows,
            # and exact summaries (no incremental drift).
            got = jax.tree.map(np.asarray,
                               row_planes(serving.merge_state, row))
            if first_text is None:
                first_text = got
            else:
                for a, b in zip(jax.tree.leaves(got),
                                jax.tree.leaves(first_text)):
                    assert np.array_equal(a, b), row
            rebuilt = mtb.recompute_summaries(got)
            for field in ("blk_live_len", "blk_max_seq", "blk_tomb",
                          "count"):
                assert np.array_equal(
                    np.asarray(getattr(got, field)),
                    np.asarray(getattr(rebuilt, field))), (row, field)
            assert serving.text_of(row) == expected_text
        elif fam == "matrix":
            got = row_planes(serving.matrix_state, row)
            flat_got = jax.tree.leaves(got)
            flat_ref = jax.tree.leaves(jax.tree.map(np.asarray, ref_mx))
            for g, r in zip(flat_got, flat_ref):
                assert np.array_equal(np.asarray(g), np.asarray(r)), row
        elif fam == "tree":
            got = row_planes(serving.tree_state, row)
            for field in tk.TreeState._fields:
                assert np.array_equal(
                    np.asarray(getattr(got, field)),
                    np.asarray(getattr(ref_tree, field))), (row, field)

    # Every family's state stays sharded across all 8 devices.
    for state in (serving.merge_state, serving.matrix_state,
                  serving.tree_state):
        leaf = jax.tree.leaves(state)[0]
        assert len({s.device for s in leaf.addressable_shards}) == 8


def test_mixed_dedup_resend_is_idempotent(mesh):
    """At-least-once delivery: resending an already-acked text frame
    verbatim sequences ZERO ops (clientSeq dedup in the closed-form
    ticket) and leaves the segment table untouched."""
    serving = make_mixed(mesh, num_docs=16)
    row = 1  # text row
    ops = [dict(kind=mtk.MT_INSERT, pos=0, text="abc")]
    serving.submit_text(row, ops, first_cseq=1, ref_seq=2, client_slot=0)
    serving.tick()
    before = jax.tree.map(np.asarray, row_planes(serving.merge_state, row))
    text_before = serving.text_of(row)

    # The resend: same cseq, same ops. Pool grows (the host cannot know
    # it is a dup before the ticket) but NO op sequences and no segment
    # changes.
    serving.submit_text(row, ops, first_cseq=1, ref_seq=2, client_slot=0)
    harvest = serving.tick()
    merged = {}
    for rows in harvest.values():
        merged.update(rows)
    assert merged[row] == (0, 0, 0)
    after = row_planes(serving.merge_state, row)
    for field in mtb.BlockMergeState._fields:
        assert np.array_equal(np.asarray(getattr(after, field)),
                              np.asarray(getattr(before, field))), field
    assert serving.text_of(row) == text_before


def test_mixed_kill_resume_rebalance_with_text(mesh):
    """Serving-host failover over a MIXED population (text + map +
    matrix + tree rows): checkpoint host 1, keep serving, kill it,
    rebalance its range to host 0, restore from checkpoint +
    durable-log replay — the text rows' segment tables, pools and
    materialized strings all survive, and seq assignment resumes with no
    regression."""
    num_docs = 16
    serving = make_mixed(mesh, num_docs=num_docs)
    # Tick 0-1 traffic, checkpoint after tick 1, then tick 2 (the tail).
    cseq = {row: {0: 0, 1: 0} for row in range(num_docs)}
    ref = {row: 2 for row in range(num_docs)}

    def play(serving, cseq, ref, t):
        for row in range(num_docs):
            fam = family_of(row)
            if fam == "map":
                words = ((np.uint32(row % 8) << 2)
                         | (np.arange(4, dtype=np.uint32) + 7 * t) << 12)
                serving.submit(row, words, cseq[row][0] + 1, ref[row], 0)
                cseq[row][0] += 4
            elif fam == "text":
                client, ops = TEXT_TICKS[t]
                serving.submit_text(row, ops, cseq[row][client] + 1,
                                    ref[row], client)
                cseq[row][client] += len(ops)
            elif fam == "matrix":
                client, ops = MATRIX_TICKS[t % len(MATRIX_TICKS)]
                if t < len(MATRIX_TICKS):
                    serving.submit_matrix(row, ops, cseq[row][client] + 1,
                                          ref[row], client)
                    cseq[row][client] += len(ops)
            else:
                client, ops = TREE_TICKS[t % len(TREE_TICKS)]
                if t < len(TREE_TICKS):
                    serving.submit_tree(row, ops, cseq[row][client] + 1,
                                        ref[row], client)
                    cseq[row][client] += len(ops)
        harvest = serving.tick()
        merged = {}
        for rows in harvest.values():
            merged.update(rows)
        for row, (n_ok, _f, last) in merged.items():
            if n_ok:
                ref[row] = last

    for t in range(2):
        play(serving, cseq, ref, t)
    cp = serving.checkpoint_host(1)
    play(serving, cseq, ref, 2)

    final_seq = np.asarray(serving.seq_state.seq).copy()
    final_texts = {row: serving.text_of(row)
                   for row in range(num_docs) if family_of(row) == "text"}
    assert any(final_texts.values())
    durable = serving.durable

    revived = make_mixed(mesh, num_docs=num_docs)
    revived.rebalance_from(1, 0)
    # Host 0's own rows (0-7) recover by re-running their full log.
    cseq2 = {row: {0: 0, 1: 0} for row in range(num_docs)}
    ref2 = {row: 2 for row in range(num_docs)}
    for t in range(3):
        for row in range(8):
            fam = family_of(row)
            if fam == "map":
                words = ((np.uint32(row % 8) << 2)
                         | (np.arange(4, dtype=np.uint32) + 7 * t) << 12)
                revived.submit(row, words, cseq2[row][0] + 1, ref2[row], 0)
                cseq2[row][0] += 4
            elif fam == "text":
                client, ops = TEXT_TICKS[t]
                revived.submit_text(row, ops, cseq2[row][client] + 1,
                                    ref2[row], client)
                cseq2[row][client] += len(ops)
            elif fam == "matrix" and t < len(MATRIX_TICKS):
                client, ops = MATRIX_TICKS[t]
                revived.submit_matrix(row, ops, cseq2[row][client] + 1,
                                      ref2[row], client)
                cseq2[row][client] += len(ops)
            elif fam == "tree" and t < len(TREE_TICKS):
                client, ops = TREE_TICKS[t]
                revived.submit_tree(row, ops, cseq2[row][client] + 1,
                                    ref2[row], client)
                cseq2[row][client] += len(ops)
        harvest = revived.tick()
        merged = {}
        for rows in harvest.values():
            merged.update(rows)
        for row, (n_ok, _f, last) in merged.items():
            if n_ok:
                ref2[row] = last
    # Host 1's rows: checkpoint + durable tail through the real tick.
    revived.restore_host(cp, durable, serving._durable_base)

    assert np.array_equal(np.asarray(revived.seq_state.seq), final_seq)
    for row, text in final_texts.items():
        assert revived.text_of(row) == text, row
    for field in mtb.BlockMergeState._fields:
        assert np.array_equal(
            np.asarray(getattr(revived.merge_state, field)),
            np.asarray(getattr(serving.merge_state, field))), field
    for g, r in zip(jax.tree.leaves(revived.matrix_state),
                    jax.tree.leaves(serving.matrix_state)):
        assert np.array_equal(np.asarray(g), np.asarray(r))
    for field in tk.TreeState._fields:
        assert np.array_equal(
            np.asarray(getattr(revived.tree_state, field)),
            np.asarray(getattr(serving.tree_state, field))), field

    # Continued service on a restored text row: seq extends, text grows.
    row = 9  # host-1 text row, now owned by host 0
    assert revived.route(row).host_id == 0
    revived.submit_text(row, [dict(kind=mtk.MT_INSERT, pos=0, text="Z")],
                        first_cseq=cseq[row][0] + 1,
                        ref_seq=int(final_seq[row]), client_slot=0)
    harvest = revived.tick()
    merged = {}
    for rows in harvest.values():
        merged.update(rows)
    n_ok, first, _last = merged[row]
    assert n_ok == 1 and first == final_seq[row] + 1
    assert revived.text_of(row) == "Z" + final_texts[row]


def test_matrix_handles_survive_failover(mesh):
    """The vector-handle allocator is host state: after restore the next
    submit_matrix insert must NOT reuse a handle live in the restored
    device planes (review finding r5)."""
    serving = make_mixed(mesh, num_docs=16)
    row = 2  # matrix row
    cseq = 0
    ref = 2
    for t in range(2):
        client, ops = MATRIX_TICKS[t]
        # single client lane: renumber cseq over lane 0
        serving.submit_matrix(row, ops, cseq + 1, ref, 0)
        cseq += len(ops)
        harvest = serving.tick()
        merged = {}
        for rows in harvest.values():
            merged.update(rows)
        ref = merged[row][2]
    assert serving._mx_handles[row] == 4
    cp = serving.checkpoint_host(0)

    revived = make_mixed(mesh, num_docs=16)
    revived.restore_host(cp, serving.durable, serving._durable_base)
    assert revived._mx_handles[row] == 4  # rebuilt from device planes
    # A fresh row insert draws handle 4, not 0.
    revived.submit_matrix(
        row, [dict(target=mxk.MX_ROWS, kind=mtk.MT_INSERT, pos=0,
                   count=1),
              dict(target=mxk.MX_CELL, row=0, col=0, value=77)],
        cseq + 1, ref, 0)
    harvest = revived.tick()
    got = jax.tree.map(np.asarray, row_planes(revived.matrix_state, row))
    new_mask = np.asarray(got.rows.pool_start[0]) == 4
    assert new_mask.any()  # the new vector run carries handle 4
    # New row (handle 4) sits at visible index 0 with the cell write;
    # the surviving old row (handle 0) keeps its cells below it.
    grid = mxk.materialize_grid(got, 0, {i: i for i in range(128)})
    assert grid == [[77, None], [11, 33]], grid


def test_pipelined_harvest_matches_sync(mesh):
    """Depth-2 harvest pipeline: acks lag ≤ 2 ticks, flush() drains the
    debt, and the device state + durable log match the synchronous
    assembly bit-for-bit."""
    def drive(depth):
        serving = ShardedServing(mesh, num_docs=8, k=4, num_hosts=2,
                                 num_clients=2, text_slots=32,
                                 pipeline_depth=depth)
        serving.join_all(slots=(0, 1))
        acks = []
        for t in range(5):
            for row in range(8):
                if row % 2:
                    serving.submit_text(
                        row, [dict(kind=mtk.MT_INSERT, pos=0,
                                   text=f"t{t}")],
                        first_cseq=t + 1, ref_seq=2 + t, client_slot=0)
                else:
                    words = np.array([(row << 2) | ((t + 1) << 12)],
                                     np.uint32)
                    serving.submit(row, words, first_cseq=t + 1,
                                   ref_seq=2 + t)
            acks.append(serving.tick())
        tail = serving.flush()  # list of per-tick harvests, oldest first
        return serving, acks, tail

    sync, sync_acks, _ = drive(0)
    piped, piped_acks, piped_tail = drive(2)
    # Sync acks arrive same-tick; pipelined ones lag by exactly depth.
    assert all(rows for h in sync_acks for rows in h.values())
    assert not any(piped_acks[0][h] for h in (0, 1))
    assert not any(piped_acks[1][h] for h in (0, 1))
    assert any(piped_acks[2][h] for h in (0, 1))
    # Every submitted tick is acked once the pipe drains.
    got = {0: [], 1: []}
    for h in piped_acks + piped_tail:
        for host, rows in h.items():
            for row, ack in rows.items():
                got[host].append((row, ack))
    want = {0: [], 1: []}
    for h in sync_acks:
        for host, rows in h.items():
            for row, ack in rows.items():
                want[host].append((row, ack))
    assert sorted(got[0]) == sorted(want[0])
    assert sorted(got[1]) == sorted(want[1])
    for field in mtb.BlockMergeState._fields:
        assert np.array_equal(
            np.asarray(getattr(piped.merge_state, field)),
            np.asarray(getattr(sync.merge_state, field))), field
    assert np.array_equal(piped.map_rows(), sync.map_rows())
    assert {r: len(v) for r, v in piped.durable.items()} == \
        {r: len(v) for r, v in sync.durable.items()}


def test_text_capacity_guard_and_compact(mesh):
    """Admission rejects a text batch whose worst-case slot growth would
    silently overflow the device table; compact_text() (the device
    zamboni at the sequencer's MSN floor) restores headroom."""
    serving = ShardedServing(mesh, num_docs=8, k=4, num_hosts=1,
                             num_clients=2, text_slots=16)
    serving.join_all(slots=(0, 1))
    row, cseq, ref = 0, 0, 2
    # Each tick: insert + remove (the remove tombstones, collab window
    # advances with acks, so compaction can reclaim).
    for t in range(3):
        ops = [dict(kind=mtk.MT_INSERT, pos=0, text="ab"),
               dict(kind=mtk.MT_REMOVE, pos=0, end=2)]
        serving.submit_text(row, ops, cseq + 1, ref, 0)
        cseq += 2
        harvest = serving.tick()
        ref = harvest[0][row][2]
    with pytest.raises(ValueError, match="compact_text"):
        serving.submit_text(
            row, [dict(kind=mtk.MT_INSERT, pos=0, text="x")] * 3,
            cseq + 1, ref, 0)
    serving.compact_text()
    assert serving._text_high[row] < 6
    serving.submit_text(
        row, [dict(kind=mtk.MT_INSERT, pos=0, text="x")] * 3,
        cseq + 1, ref, 0)
    harvest = serving.tick()
    assert harvest[0][row][0] == 3
    assert serving.text_of(row) == "xxx"


def test_retune_text_geometry_live_serving(mesh):
    """Round-11 geometry autotuning on the sharded serving path: a
    head-concentrated stream arms the fused incremental rebalance
    (observable device-true through the kstats plane →
    ``rebalance_stats``), the between-ticks retune re-blocks through the
    packed-flat seam without changing any served byte, and serving
    continues identically on the new geometry."""
    serving = ShardedServing(mesh, num_docs=8, k=32, num_hosts=1,
                             num_clients=2, text_slots=256)
    serving.join_all(slots=(0, 1))
    row, cseq, ref = 0, 0, 2
    for _t in range(2):
        ops = [dict(kind=mtk.MT_INSERT, pos=0, text="x")] * 32
        serving.submit_text(row, ops, cseq + 1, ref, 0)
        cseq += 32
        harvest = serving.tick()
        assert harvest[0][row][0] == 32
        ref = harvest[0][row][2]
    # The rebalance fire count rides the EXISTING kstats readback — the
    # observed-locality signal the retune keys on is device-true.
    assert serving.rebalance_stats["fired"] >= 1
    assert serving.observed_head_fraction() > 0.0
    before = serving.text_of(row)
    geom0 = serving.text_geometry
    geom1 = serving.retune_text_geometry(1.0)
    assert geom1 != geom0
    assert tuple(serving.merge_state.length.shape[1:]) == geom1
    # Pure re-layout: no served byte moved.
    assert serving.text_of(row) == before
    # Deterministic + idempotent in (state, head_fraction).
    assert serving.retune_text_geometry(1.0) == geom1
    ops = [dict(kind=mtk.MT_INSERT, pos=0, text="y")] * 32
    serving.submit_text(row, ops, cseq + 1, ref, 0)
    harvest = serving.tick()
    assert harvest[0][row][0] == 32
    assert serving.text_of(row) == "y" * 32 + before
