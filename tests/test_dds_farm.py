"""Cross-DDS randomized convergence farm.

Reference parity model: the merge-tree "farm" strategy (conflictFarm /
reconnectFarm) generalized across DDS types the way the e2e suites cover
map/directory/matrix/counter together — random concurrent ops with paused
delivery and random reconnects, asserting byte-identical summaries after
every drain. This is the eventual-consistency sanitizer (SURVEY §5.2).
"""

import random

import pytest

from fluidframework_tpu.dds.cell import SharedCell
from fluidframework_tpu.dds.counter import SharedCounter
from fluidframework_tpu.dds.directory import SharedDirectory
from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.dds.matrix import SharedMatrix
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.local_server import LocalCollabServer

CHANNELS = [
    ("map", SharedMap),
    ("dir", SharedDirectory),
    ("grid", SharedMatrix),
    ("count", SharedCounter),
    ("cell", SharedCell),
]


def make_doc(server, doc_id="doc"):
    service = LocalDocumentService(server, doc_id)
    container = Container.create_detached(service)
    datastore = container.runtime.create_datastore("default")
    for name, cls in CHANNELS:
        datastore.create_channel(name, cls.channel_type)
    container.attach()
    return container


def chan(container, name):
    return container.runtime.get_datastore("default").get_channel(name)


def random_op(rng: random.Random, container) -> None:
    which = rng.randrange(5)
    if which == 0:
        m = chan(container, "map")
        r = rng.random()
        key = f"k{rng.randrange(6)}"
        if r < 0.7:
            m.set(key, rng.randrange(100))
        elif r < 0.9:
            m.delete(key)
        else:
            m.clear()
    elif which == 1:
        d = chan(container, "dir")
        sub = rng.choice(["/", "a", "a/b"])
        node = d.root if sub == "/" else d.create_sub_directory(sub) \
            if rng.random() < 0.3 else d.root
        node.set(f"k{rng.randrange(4)}", rng.randrange(100))
    elif which == 2:
        g = chan(container, "grid")
        if g.row_count == 0 or rng.random() < 0.25:
            g.insert_rows(rng.randrange(g.row_count + 1), 1)
        if g.col_count == 0 or rng.random() < 0.25:
            g.insert_cols(rng.randrange(g.col_count + 1), 1)
        if g.row_count and g.col_count:
            g.set_cell(rng.randrange(g.row_count),
                       rng.randrange(g.col_count), rng.randrange(100))
    elif which == 3:
        chan(container, "count").increment(rng.randrange(1, 5))
    else:
        c = chan(container, "cell")
        if rng.random() < 0.8:
            c.set(rng.randrange(100))
        else:
            c.delete()


@pytest.mark.parametrize("seed", range(4))
def test_cross_dds_conflict_farm(seed):
    rng = random.Random(1000 + seed)
    server = LocalCollabServer()
    c1 = make_doc(server)
    containers = [c1] + [Container.load(LocalDocumentService(server, "doc"))
                         for _ in range(3)]

    for _round in range(6):
        paused = [c for c in containers if rng.random() < 0.4]
        for c in paused:
            c.inbound.pause()
        for _ in range(rng.randrange(6, 16)):
            random_op(rng, containers[rng.randrange(len(containers))])
        for c in paused:
            c.inbound.resume()
        summaries = [c.summarize() for c in containers]
        assert all(s == summaries[0] for s in summaries), (seed, _round)
    for c in containers:
        assert not c.nacks


@pytest.mark.parametrize("seed", range(3))
def test_cross_dds_reconnect_farm(seed):
    rng = random.Random(2000 + seed)
    server = LocalCollabServer()
    c1 = make_doc(server)
    containers = [c1] + [Container.load(LocalDocumentService(server, "doc"))
                         for _ in range(2)]

    for _round in range(5):
        offline = [c for c in containers[1:] if rng.random() < 0.5]
        for c in offline:
            c.disconnect()
        for _ in range(rng.randrange(5, 12)):
            random_op(rng, containers[rng.randrange(len(containers))])
        for c in offline:
            c.reconnect()
        summaries = [c.summarize() for c in containers]
        assert all(s == summaries[0] for s in summaries), (seed, _round)
    for c in containers:
        assert not c.nacks
