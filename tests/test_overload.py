"""Graceful degradation under overload (ISSUE 5): token-bucket admission
at the front doors and the batched tick ingress, deterministic shed
(signals/reads before writes), the WAL fsync circuit breaker with
half-open probes, the per-doc quarantine plane, client reconnect
backoff+jitter, and the storm WAL/snapshot format-version compat.
"""

from __future__ import annotations

import json
import struct
from pathlib import Path

import numpy as np
import pytest

from fluidframework_tpu.server.riddler import (
    AdmissionController,
    Throttler,
    TokenBucket,
)

#: WAL-format goldens live beside (not inside) the DDS replay corpus —
#: tests/goldens is scanned as replayable documents.
GOLDENS = Path(__file__).parent / "goldens_wal"


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t


# -- token bucket vs the fixed window -----------------------------------------


class TestBoundaryBurst:
    """The satellite regression: a fixed window admits 2x its budget
    across a window edge; the token bucket must not."""

    BUDGET = 10

    def _offered_across_edge(self, limiter) -> int:
        """Touch the key at t=0 (anchoring any window there), then offer
        BUDGET requests just before the t=1 edge and BUDGET just after;
        return how many were admitted inside that ~10ms burst."""
        self.clock.t = 0.0
        limiter.try_consume("k", weight=0)  # anchor the window at t=0
        admitted = 0
        self.clock.t = 0.995  # last instant of window 0
        for _ in range(self.BUDGET):
            if limiter.try_consume("k") is None:
                admitted += 1
        self.clock.t = 1.005  # first instant of window 1
        for _ in range(self.BUDGET):
            if limiter.try_consume("k") is None:
                admitted += 1
        return admitted

    def test_fixed_window_admits_double_budget_at_the_edge(self):
        """Pins the DEFECT (kept as the regression reference): 2x the
        per-second budget lands inside ~2ms of wall clock."""
        self.clock = FakeClock()
        throttler = Throttler(rate_per_interval=self.BUDGET,
                              interval_s=1.0, clock=self.clock)
        assert self._offered_across_edge(throttler) == 2 * self.BUDGET

    def test_token_bucket_is_burst_safe_at_the_edge(self):
        self.clock = FakeClock()
        bucket = TokenBucket(rate_per_s=self.BUDGET, burst=self.BUDGET,
                             clock=self.clock)
        # burst + rate * 0.002s — no window edge to slip through.
        assert self._offered_across_edge(bucket) <= self.BUDGET + 1

    def test_token_bucket_bounds_any_interval(self):
        """Over ANY window of T seconds admitted weight <= burst+rate*T
        (the property the fixed window lacks), probed at adversarial
        offsets."""
        clock = FakeClock()
        bucket = TokenBucket(rate_per_s=100, burst=20, clock=clock)
        admitted_at: list[float] = []
        for step in range(2000):
            clock.t = step * 0.003
            if bucket.try_consume("k") is None:
                admitted_at.append(clock.t)
        times = np.asarray(admitted_at)
        for T in (0.01, 0.1, 0.5, 1.0):
            counts = [(times >= t0) & (times < t0 + T)
                      for t0 in np.arange(0, 5.5, 0.05)]
            worst = max(int(c.sum()) for c in counts)
            assert worst <= 20 + 100 * T + 1, (T, worst)


class TestTokenBucket:
    def test_refill_and_retry_hint(self):
        clock = FakeClock()
        bucket = TokenBucket(rate_per_s=10, burst=2, clock=clock)
        assert bucket.try_consume("k") is None
        assert bucket.try_consume("k") is None
        retry = bucket.try_consume("k")
        assert retry == pytest.approx(0.1)
        clock.t += retry
        assert bucket.try_consume("k") is None

    def test_keys_are_independent_and_refund_restores(self):
        bucket = TokenBucket(rate_per_s=1, burst=1,
                             clock=FakeClock())
        assert bucket.try_consume("a") is None
        assert bucket.try_consume("b") is None
        assert bucket.try_consume("a") is not None
        bucket.refund("a")
        assert bucket.try_consume("a") is None

    def test_oversized_weight_admits_at_full_bucket_never_livelocks(self):
        """weight > burst can never fit the bucket; it must admit at a
        FULL bucket (carrying the deficit as debt) instead of returning
        a finite hint the caller can never satisfy."""
        clock = FakeClock()
        bucket = TokenBucket(rate_per_s=10, burst=10, clock=clock)
        assert bucket.try_consume("k", weight=30) is None  # full: admit
        retry = bucket.try_consume("k")  # debt: -20 tokens outstanding
        assert retry == pytest.approx(2.1)
        clock.t = 10.0  # long-run rate holds: only now is it full again
        assert bucket.try_consume("k", weight=30) is None
        # Below-full refusals of an oversize request hint time-to-FULL.
        clock.t = 12.0  # debt repaid, bucket at 0 of 10
        assert bucket.try_consume("k", weight=30) == pytest.approx(1.0)

    def test_reserve_ladders_a_synchronized_herd(self):
        """N refusals in one instant get hints laddering at the drain
        rate — the anti-thundering-herd property admit_connect uses."""
        bucket = TokenBucket(rate_per_s=10, burst=1, clock=FakeClock())
        assert bucket.reserve("k") == (None, False)  # burst
        refusals = [bucket.reserve("k") for _ in range(5)]
        assert all(reserved for _hint, reserved in refusals)
        hints = [hint for hint, _reserved in refusals]
        assert hints == sorted(hints)
        steps = np.diff([0.0] + hints)
        assert np.allclose(steps, 0.1), hints

    def test_reserve_past_the_horizon_debits_nothing(self):
        """Beyond RESERVE_HORIZON_S of outstanding debt, refusals are
        hint-only: no debit, flagged not-reserved — admit_connect must
        not record them as claimable."""
        bucket = TokenBucket(rate_per_s=1, burst=1, clock=FakeClock())
        bucket.reserve("k")  # burst
        for _ in range(int(TokenBucket.RESERVE_HORIZON_S)):
            bucket.reserve("k")
        hint1, reserved1 = bucket.reserve("k")
        hint2, reserved2 = bucket.reserve("k")
        assert not reserved1 and not reserved2
        assert hint1 == hint2  # the tail stopped growing


class TestAdmissionController:
    def _controller(self, **kw):
        self.clock = FakeClock()
        kw.setdefault("connect_rate_per_s", 10)
        kw.setdefault("write_rate_per_s", 100)
        return AdmissionController(clock=self.clock, **kw)

    def test_shed_order_is_signals_reads_writes(self):
        """The deterministic shed policy: as queue pressure rises,
        signals shed first, then reads, writes only at a full queue."""
        adm = self._controller()
        pressure = {"v": 0.0}
        adm.add_pressure_probe(lambda: pressure["v"])
        assert adm.admit_signal("t") is None
        assert adm.admit_read("t") is None
        assert adm.admit_write("t", "c") is None
        pressure["v"] = 0.6  # past SHED_SIGNALS_AT
        assert adm.admit_signal("t") is not None
        assert adm.admit_read("t") is None
        assert adm.admit_write("t", "c") is None
        pressure["v"] = 0.8  # past SHED_READS_AT
        assert adm.admit_signal("t") is not None
        assert adm.admit_read("t") is not None
        assert adm.admit_write("t", "c") is None
        pressure["v"] = 1.0  # full queue
        assert adm.admit_write("t", "c") is not None
        assert adm.stats["shed_signals"] == 2
        assert adm.stats["shed_reads"] == 1
        assert adm.stats["shed_writes"] == 1

    def test_client_tier_refusal_refunds_the_tenant(self):
        """One hot client must not drain its tenant's shared bucket."""
        adm = self._controller(write_rate_per_s=100, write_burst=100,
                               client_write_rate_per_s=10,
                               client_write_burst=10)
        assert adm.admit_write("t", "hot", weight=10) is None
        assert adm.admit_write("t", "hot", weight=10) is not None
        # The tenant bucket was refunded: a neighbour still has budget.
        assert adm.admit_write("t", "calm", weight=10) is None

    def test_connect_reservation_is_claimable_not_redebited(self):
        adm = self._controller(connect_rate_per_s=10, connect_burst=1)
        assert adm.admit_connect("t", "c0") is None  # burst
        retry = adm.admit_connect("t", "c1")
        assert retry == pytest.approx(0.1)
        # Coming back EARLY re-issues the same slot, no new debit.
        early = adm.admit_connect("t", "c1")
        assert early == pytest.approx(retry)
        self.clock.t = retry
        assert adm.admit_connect("t", "c1") is None  # claims the slot

    def test_client_tier_connect_refusal_records_no_free_reservation(self):
        """A client-bucket refusal refunds the tenant and must NOT leave
        a claimable reservation — an unbacked one would admit for free
        at claim time, bypassing both buckets' limits."""
        adm = self._controller(connect_rate_per_s=10, connect_burst=10)
        # Drain client K's own bucket via tenant B.
        assert adm.admit_connect("B", "K") is None
        while adm.admit_connect("B", "K") is None:
            pass
        # (A, K): tenant A has budget, client K refuses -> refund, no
        # reservation recorded.
        retry = adm.admit_connect("A", "K")
        assert retry is not None
        assert ("A", "K") not in adm._connect_reservations
        # Tenant A's bucket was refunded: a different client admits, and
        # repeating the refused pair stays rate-bound (no free claims).
        assert adm.admit_connect("A", "other") is None
        admitted = sum(adm.admit_connect("A", "K") is None
                       for _ in range(50))
        assert admitted == 0  # client K's bucket is dry; no bypass


# -- circuit breaker -----------------------------------------------------------


class TestCircuitBreaker:
    def test_closed_open_halfopen_cycle(self):
        from fluidframework_tpu.server.durable_store import CircuitBreaker
        clock = FakeClock()
        breaker = CircuitBreaker(cooldown_s=1.0, clock=clock)
        assert breaker.state == "closed" and breaker.allow()
        breaker.record_failure()
        assert breaker.state == "open"
        assert not breaker.allow()  # cooldown not elapsed
        clock.t = 1.5
        assert breaker.allow()      # the single half-open probe
        assert breaker.state == "half-open"
        assert not breaker.allow()  # only ONE probe in flight
        breaker.record_failure()    # probe failed: re-open
        assert breaker.state == "open"
        clock.t = 3.0
        assert breaker.allow()
        breaker.record_success()
        assert breaker.state == "closed" and breaker.allow()
        assert breaker.stats == {"opens": 1, "probes": 2, "closes": 1}

    def test_failure_threshold(self):
        from fluidframework_tpu.server.durable_store import CircuitBreaker
        breaker = CircuitBreaker(failure_threshold=3, clock=FakeClock())
        breaker.record_failure()
        breaker.record_failure()
        assert breaker.stats["opens"] == 0
        breaker.record_failure()
        assert breaker.stats["opens"] == 1


def test_wal_breaker_degrades_and_heals(tmp_path):
    """GroupCommitLog under injected fsync failure: barrier raises
    WalDegradedError while open; half-open probes heal; queued records
    survive the outage (nothing durable is lost, nothing re-appended)."""
    import time

    from fluidframework_tpu.server.durable_store import (
        GroupCommitLog,
        WalDegradedError,
    )
    from fluidframework_tpu.utils import faults

    log = GroupCommitLog(tmp_path / "wal.log")
    log.breaker.cooldown_s = 0.02
    log.append(b"healthy")
    log.sync()
    faults.install_failure("wal.fsync", times=2)
    faults.arm()
    try:
        log.append(b"through-the-outage")
        deadline = time.monotonic() + 30
        while not log.breaker.is_open and time.monotonic() < deadline:
            time.sleep(0.005)
        assert log.breaker.is_open
        with pytest.raises(WalDegradedError):
            log.sync()
        # Queued records stay readable during the outage.
        assert log.read(1) == b"through-the-outage"
        while log.breaker.is_open and time.monotonic() < deadline:
            time.sleep(0.005)
        assert not log.breaker.is_open
        log.sync()
        assert log.durable_len == 2
    finally:
        faults.clear()
        log.close()
    # Reopen: exactly the two records, no duplicate from the retry path.
    log = GroupCommitLog(tmp_path / "wal.log")
    assert len(log) == 2
    assert [log.read(i) for i in range(2)] == [b"healthy",
                                               b"through-the-outage"]
    log.close()


# -- storm tick-ingress admission ----------------------------------------------


def _storm_stack(num_docs=4, **kw):
    from fluidframework_tpu.server.kernel_host import KernelSequencerHost
    from fluidframework_tpu.server.merge_host import KernelMergeHost
    from fluidframework_tpu.server.routerlicious import RouterliciousService
    from fluidframework_tpu.server.storm import StormController

    seq_host = KernelSequencerHost(num_slots=2, initial_capacity=num_docs)
    merge_host = KernelMergeHost(flush_threshold=10**9)
    service = RouterliciousService(merge_host=merge_host,
                                   batched_deli_host=seq_host,
                                   auto_pump=False)
    kw.setdefault("flush_threshold_docs", 10**9)
    storm = StormController(service, seq_host, merge_host, **kw)
    clients = {}
    docs = [f"doc{i}" for i in range(num_docs)]
    for d in docs:
        clients[d] = service.connect(d, lambda m: None).client_id
    service.pump()
    return service, storm, docs, clients


def _frame(storm, sink, doc, client, cseq0, k=8, rid=0, seed=0):
    rng = np.random.default_rng([seed, cseq0])
    words = ((rng.integers(0, 16, k).astype(np.uint32) << 2)
             | (rng.integers(0, 1 << 20, k).astype(np.uint32) << 12))
    storm.submit_frame(sink, {"rid": rid,
                              "docs": [[doc, client, cseq0, 1, k]]},
                       memoryview(words.tobytes()))


class TestStormIngress:
    def test_bounded_queue_sheds_with_busy_nack(self):
        service, storm, docs, clients = _storm_stack(
            num_docs=4, max_pending_docs=2)
        acks, nacks = [], []
        sink = lambda p: (nacks if p.get("error") else acks).append(p)
        for i, d in enumerate(docs):
            _frame(storm, sink, d, clients[d], 1, rid=i)
        # Bound = 2: the third and fourth frames shed deterministically.
        assert storm._pending_docs == 2
        assert len(nacks) == 2
        assert all(n["error"] == "busy" and n["retryable"]
                   and n["retry_after_s"] > 0 for n in nacks)
        assert storm.stats["shed_frames"] == 2
        storm.flush()
        assert len(acks) == 2  # the admitted cohort served normally
        # Queue drained: the shed docs' retry now admits.
        _frame(storm, sink, docs[2], clients[docs[2]], 1, rid=9)
        storm.flush()
        assert len(acks) == 3

    def test_quarantined_doc_in_mixed_frame_nacks_every_dropped_doc(self):
        """A frame sharing a quarantined doc is refused WHOLE (acks are
        positional per frame) — the nack must list every dropped doc,
        not just the quarantined one, or the client silently loses the
        healthy docs' ops."""
        service, storm, docs, clients = _storm_stack(num_docs=2)
        storm.quarantined["doc0"] = {"reason": "test", "tick": 0}
        nacks = []
        rng = np.random.default_rng(5)
        words = ((rng.integers(0, 16, 8).astype(np.uint32) << 2)
                 | (rng.integers(0, 1 << 20, 8).astype(np.uint32) << 12))
        storm.submit_frame(
            nacks.append,
            {"rid": 7, "docs": [["doc0", clients["doc0"], 1, 1, 8],
                                ["doc1", clients["doc1"], 1, 1, 8]]},
            memoryview(words.tobytes() * 2))
        assert len(nacks) == 1
        assert nacks[0]["error"] == "quarantined"
        assert nacks[0]["docs"] == ["doc0", "doc1"]  # both were dropped
        assert nacks[0]["quarantined"] == ["doc0"]
        assert storm._pending_docs == 0

    def test_admission_bucket_sheds_writes_with_retry_hint(self):
        clock = FakeClock()
        admission = AdmissionController(write_rate_per_s=8,
                                        write_burst=8,
                                        client_write_rate_per_s=8,
                                        client_write_burst=8, clock=clock)
        service, storm, docs, clients = _storm_stack(
            num_docs=2, admission=admission, max_pending_docs=64)
        acks, nacks = [], []
        sink = lambda p: (nacks if p.get("error") else acks).append(p)
        _frame(storm, sink, docs[0], clients[docs[0]], 1, k=8)
        _frame(storm, sink, docs[1], clients[docs[1]], 1, k=8)
        assert [n["error"] for n in nacks] == ["throttled"]
        assert nacks[0]["retry_after_s"] > 0
        storm.flush()
        assert len(acks) == 1

    def test_replay_bypasses_admission(self, tmp_path):
        """Recovery replay re-runs already-admitted history: the gates
        must not shed it (a throttled recovery would be a self-DoS)."""
        from fluidframework_tpu.server.durable_store import GitSnapshotStore
        service, storm, docs, clients = _storm_stack(
            num_docs=2,
            spill_dir=str(tmp_path / "spill"), durability="group",
            snapshots=GitSnapshotStore(str(tmp_path / "git")))
        storm.checkpoint()
        acks, nacks = [], []
        sink = lambda p: (nacks if p.get("error") else acks).append(p)
        _frame(storm, sink, docs[0], clients[docs[0]], 1, k=8)
        storm.flush()
        assert len(acks) == 1 and not nacks
        # Fresh stack over the same dirs: recover() replays the WAL tail
        # through submit_frame with the bucket EMPTY — must not shed.
        storm._group_wal.close()
        service2, storm2, _, _ = (None, None, None, None)
        from fluidframework_tpu.server.kernel_host import KernelSequencerHost
        from fluidframework_tpu.server.merge_host import KernelMergeHost
        from fluidframework_tpu.server.routerlicious import (
            RouterliciousService,
        )
        from fluidframework_tpu.server.storm import StormController
        seq_host = KernelSequencerHost(num_slots=2, initial_capacity=2)
        merge_host = KernelMergeHost(flush_threshold=10**9)
        service2 = RouterliciousService(merge_host=merge_host,
                                        batched_deli_host=seq_host,
                                        auto_pump=False)
        storm2 = StormController(
            service2, seq_host, merge_host, flush_threshold_docs=10**9,
            spill_dir=str(tmp_path / "spill"), durability="group",
            snapshots=GitSnapshotStore(str(tmp_path / "git")),
            admission=AdmissionController(write_rate_per_s=1,
                                          write_burst=1,
                                          clock=FakeClock()))
        info = storm2.recover()
        assert info["replayed_ticks"] == 1
        assert storm2.stats["shed_frames"] == 0
        storm2._group_wal.close()


# -- quarantine invariants (satellite) ----------------------------------------


class TestQuarantineInvariants:
    def test_poisoned_doc_recovers_byte_identical_peers_lose_zero_ticks(
            self, tmp_path):
        """The satellite's two invariants, proven by the chaos scenario:
        (1) the quarantined doc's state — scalar shadow AND post-readmit
        device row — is byte-identical to an uninterrupted twin; (2) its
        batch peers lose zero throughput ticks (telemetry counters)."""
        from fluidframework_tpu.tools import chaos
        report = chaos.run_poison_quarantine(str(tmp_path), num_docs=3,
                                             k=8, rounds=4)
        assert report["stats"] == {"quarantined_docs": 1,
                                   "readmitted_docs": 1}
        assert report["replayed_ticks"] >= 1

    def test_merge_channel_tick_failure_routes_to_scalar(self, monkeypatch):
        """The generalized per-op-path escape hatch: a failing overflow
        replay quarantines ONE channel onto its scalar engine (exact
        tail replay); the flush survives and peers stay device-served."""
        from fluidframework_tpu.dds.mergetree import MergeEngine
        from fluidframework_tpu.server.merge_host import KernelMergeHost

        host = KernelMergeHost(flush_threshold=10**9)
        oracle = MergeEngine(local_client=None)

        def feed(host_key, seq, op):
            from fluidframework_tpu.protocol.messages import (
                MessageType,
                SequencedDocumentMessage,
            )
            host.ingest("doc", SequencedDocumentMessage(
                client_id="c1", sequence_number=seq,
                minimum_sequence_number=0, client_sequence_number=seq,
                reference_sequence_number=seq - 1,
                type=MessageType.OPERATION,
                contents={"address": "default",
                          "contents": {"address": host_key,
                                       "contents": op}},
                timestamp=1.0))

        seq = 0
        for i in range(6):
            seq += 1
            op = {"type": "insert", "pos": 0, "text": f"t{i} "}
            feed("text", seq, op)
            oracle.apply_remote(op, seq, seq - 1, "c1")
            seq += 1
            feed("peer", seq, {"type": "set", "key": "k", "value": i})
        # Simulate a poisoned per-row tick: the device "freezes" the row
        # before op 0 (apply returns the state unchanged, the overflow
        # plane reports index 0) and the overflow replay itself FAILS —
        # the quarantine path must absorb it.
        def boom(row, rest):
            raise RuntimeError("injected per-row tick failure")
        monkeypatch.setattr(host, "_replay_block_overflow", boom)
        from fluidframework_tpu.server.merge_host import ChannelKey
        target = host._merge_rows[ChannelKey("doc", "default", "text")]

        def frozen_apply(pool_self, batch):
            return pool_self.state
        monkeypatch.setattr(type(target.pool), "apply", frozen_apply)

        def fake_take(pool_self):
            from fluidframework_tpu.ops import mergetree_blocks as mtb
            out = np.full(pool_self.capacity, int(mtb.OVF_NONE), np.int32)
            if target.pool is pool_self:
                out[target.row] = 0  # frozen before the first pending op
            return out
        monkeypatch.setattr(type(target.pool), "take_overflow", fake_take)
        host.flush()
        assert target.scalar is not None, "channel not quarantined"
        assert target.pool is None
        assert host.stats["quarantined_channels"] == 1
        # Blast radius: the doc's MAP channel (a batch peer on another
        # plane) stayed device-served and converged.
        assert host.map_entries("doc", "default", "peer") == {"k": 5}
        # Byte-identical: the quarantined channel's scalar text equals
        # the oracle replay of the same sequenced stream.
        assert host.text("doc", "default", "text") == oracle.get_text()
        # And the channel keeps serving scalar-side.
        seq += 1
        op = {"type": "insert", "pos": 0, "text": "after "}
        feed("text", seq, op)
        oracle.apply_remote(op, seq, seq - 1, "c1")
        assert host.text("doc", "default", "text") == oracle.get_text()


# -- WAL / snapshot format versioning (satellite) ------------------------------


class TestFormatVersioning:
    def test_new_wal_headers_carry_the_version(self, tmp_path):
        from fluidframework_tpu.server.storm import STORM_WAL_VERSION
        service, storm, docs, clients = _storm_stack(
            num_docs=1, spill_dir=str(tmp_path), durability="sync")
        _frame(storm, lambda p: None, docs[0], clients[docs[0]], 1)
        storm.flush()
        header, _off = storm._parse_header(storm._read_blob(0))
        assert header["v"] == STORM_WAL_VERSION
        storm._blob_log.close()

    def test_pre_version_golden_replays_through_the_new_reader(
            self, tmp_path):
        """The committed v0 golden (round-7 format, no "v" field) must
        parse, index and materialize identically under the new reader."""
        import shutil

        from fluidframework_tpu.server.storm import (
            materialize_storm_records,
        )
        golden = GOLDENS / "storm-wal-v0"
        expected = json.loads((golden / "expected.json").read_text())
        spill = tmp_path / "spill"
        spill.mkdir()
        shutil.copy(golden / "storm_tick_words.log",
                    spill / "storm_tick_words.log")
        service, storm, _docs, _clients = _storm_stack(
            num_docs=1, spill_dir=str(spill), durability="none")
        # The __init__ scan indexed the golden ticks.
        assert storm._tick_counter == expected["ticks"]
        for doc, want in expected["docs"].items():
            records = storm.records_overlapping(doc, 0)
            assert len(records) == expected["ticks"]
            msgs = materialize_storm_records(
                records, storm.datastore, storm.channel,
                blob_reader=storm.read_tick_words)
            got = [[m.sequence_number, m.client_sequence_number,
                    m.contents["contents"]["contents"]] for m in msgs]
            assert got == want, doc
        storm._blob_log.close()

    def test_newer_wal_version_is_refused(self, tmp_path):
        from fluidframework_tpu.native import OpLog
        from fluidframework_tpu.server.storm import STORM_WAL_VERSION
        header = json.dumps({"v": STORM_WAL_VERSION + 1, "ts": 0,
                             "docs": []}).encode()
        log = OpLog(tmp_path / "storm_tick_words.log")
        log.append(struct.pack("<I", len(header)) + header)
        log.sync()
        log.close()
        with pytest.raises(ValueError, match="newer than this reader"):
            _storm_stack(num_docs=1, spill_dir=str(tmp_path),
                         durability="none")

    def test_snapshot_version_stamped_and_v0_accepted(self, tmp_path):
        from fluidframework_tpu.server.durable_store import GitSnapshotStore
        from fluidframework_tpu.server.storm import (
            STORM_SNAPSHOT_VERSION,
        )
        snapshots = GitSnapshotStore(str(tmp_path / "git"))
        service, storm, docs, clients = _storm_stack(
            num_docs=1, spill_dir=str(tmp_path / "spill"),
            durability="group", snapshots=snapshots)
        _frame(storm, lambda p: None, docs[0], clients[docs[0]], 1)
        storm.flush()
        handle = storm.checkpoint()
        snap = snapshots.get(storm.SNAPSHOT_DOC, handle)
        assert snap["format_version"] == STORM_SNAPSHOT_VERSION
        # A pre-version snapshot (field absent — the committed round-7
        # shape) must restore: strip the stamp and republish.
        snap.pop("format_version")
        snapshots.set_head(storm.SNAPSHOT_DOC,
                           snapshots.upload(storm.SNAPSHOT_DOC, snap))
        storm._group_wal.close()
        from fluidframework_tpu.server.kernel_host import (
            KernelSequencerHost,
        )
        from fluidframework_tpu.server.merge_host import KernelMergeHost
        from fluidframework_tpu.server.routerlicious import (
            RouterliciousService,
        )
        from fluidframework_tpu.server.storm import StormController
        seq_host = KernelSequencerHost(num_slots=2, initial_capacity=1)
        merge_host = KernelMergeHost(flush_threshold=10**9)
        service2 = RouterliciousService(merge_host=merge_host,
                                        batched_deli_host=seq_host,
                                        auto_pump=False)
        storm2 = StormController(
            service2, seq_host, merge_host, flush_threshold_docs=10**9,
            spill_dir=str(tmp_path / "spill"), durability="group",
            snapshots=snapshots)
        info = storm2.recover()
        assert info["restored_from"] is not None
        storm2._group_wal.close()


# -- reconnect policy / auto reconnector ---------------------------------------


class TestReconnectPolicy:
    def test_deterministic_and_bounded(self):
        from fluidframework_tpu.drivers.utils import ReconnectPolicy
        a = ReconnectPolicy(base_s=0.1, max_s=5.0, jitter=0.5, seed=7)
        b = ReconnectPolicy(base_s=0.1, max_s=5.0, jitter=0.5, seed=7)
        delays = [a.next_delay(i) for i in range(10)]
        assert delays == [b.next_delay(i) for i in range(10)]
        for i, d in enumerate(delays):
            raw = min(5.0, 0.1 * 2 ** i)
            assert raw * 0.5 <= d <= raw

    def test_retry_after_is_a_floor_with_jitter_on_top(self):
        from fluidframework_tpu.drivers.utils import ReconnectPolicy
        policy = ReconnectPolicy(base_s=0.1, jitter=0.5, seed=3)
        d = policy.next_delay(0, retry_after_s=2.0)
        assert 2.0 < d <= 2.0 + 0.1

    def test_different_seeds_spread(self):
        from fluidframework_tpu.drivers.utils import ReconnectPolicy
        delays = {round(ReconnectPolicy(jitter=0.9,
                                        seed=s).next_delay(3), 6)
                  for s in range(32)}
        assert len(delays) > 24  # jitter actually de-synchronizes


class _FakeReconnectService:
    """Driver double: scripted connect outcomes, a real event emitter."""

    def __init__(self, script) -> None:
        from fluidframework_tpu.utils.events import TypedEventEmitter
        self.events = TypedEventEmitter()
        self.script = list(script)
        self.redials = 0
        self.delta_storage = self
        self.connected_modes: list[str] = []

    def get_deltas(self, from_seq, to_seq=None):
        return []

    def reconnect(self):
        self.redials += 1

    def connect(self, handler, on_nack=None, on_signal=None, mode="write"):
        outcome = self.script.pop(0)
        if isinstance(outcome, Exception):
            raise outcome
        self.connected_modes.append(mode)

        class _Conn:
            client_id = outcome
            open = True

            def close(self):
                self.open = False
        return _Conn()


class TestAutoReconnector:
    def test_disconnect_degrades_then_backoff_honors_retry_after(self):
        from fluidframework_tpu.drivers.utils import (
            ReconnectPolicy,
            ThrottlingError,
        )
        from fluidframework_tpu.runtime.delta_manager import (
            AutoReconnector,
            DeltaManager,
        )
        service = _FakeReconnectService([
            "cid-1",                                   # initial connect
            ThrottlingError("busy", retry_after_s=3.0),  # redial 1
            ConnectionError("still down"),               # redial 2
            "cid-2",                                     # redial 3
        ])
        dm = DeltaManager(service, process_message=lambda m: None)
        dm.connect()
        assert dm.connected and not dm.readonly
        sleeps: list[float] = []
        recon = AutoReconnector(
            dm, service,
            policy=ReconnectPolicy(base_s=0.1, jitter=0.0, seed=0),
            sleep=sleeps.append, spawn_thread=False)
        service.events.emit("disconnect")
        # Degraded immediately: disconnected AND readonly, no RPC sent.
        assert not dm.connected and dm.readonly
        assert dm.allocate_client_seq() is None
        client_id = recon.run()
        assert client_id == "cid-2" and dm.client_id == "cid-2"
        assert dm.connected and not dm.readonly
        assert service.redials == 3
        # Delay 2 honored the server hint as a floor (3.0 + backoff).
        assert sleeps[0] == pytest.approx(0.1)
        assert sleeps[1] >= 3.0
        assert sleeps[2] == pytest.approx(0.4)

    def test_auth_errors_do_not_retry(self):
        from fluidframework_tpu.drivers.utils import (
            AuthorizationError,
            ReconnectPolicy,
        )
        from fluidframework_tpu.runtime.delta_manager import (
            AutoReconnector,
            DeltaManager,
        )
        service = _FakeReconnectService([
            "cid-1", AuthorizationError("token revoked")])
        dm = DeltaManager(service, process_message=lambda m: None)
        dm.connect()
        recon = AutoReconnector(dm, service,
                                policy=ReconnectPolicy(seed=0),
                                sleep=lambda s: None, spawn_thread=False)
        dm.handle_connection_lost()
        with pytest.raises(AuthorizationError):
            recon.run()
