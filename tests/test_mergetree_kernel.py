"""Batched merge-tree kernel: differential tests against real op streams.

The streams come from the live client stack (SharedString replicas over the
local server — genuine concurrency, splits, overlapping removes, reconnect
group ops); the kernel plays the sequenced log as the server-side merge and
must reproduce the replicas' converged text byte-for-byte.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluidframework_tpu.ops import mergetree_kernel as mtk
from fluidframework_tpu.protocol.messages import MessageType
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.local_server import LocalCollabServer
from tests.test_mergetree import get_string, make_string_doc, random_edit


def encode_log(messages, pool: mtk.TextPool, doc: int, client_slots: dict,
               key_slots: dict, val_ids: dict):
    """Sequenced OPERATION messages → kernel op dicts (+ pool appends)."""
    out = []
    for m in messages:
        if m.type != MessageType.OPERATION:
            continue
        channel_op = m.contents["contents"]["contents"]
        subops = (channel_op["ops"] if channel_op["type"] == "group"
                  else [channel_op])
        slot = client_slots.setdefault(m.client_id, len(client_slots))
        for op in subops:
            base = dict(seq=m.sequence_number,
                        ref_seq=m.reference_sequence_number, client=slot)
            if op["type"] == "insert":
                text = op.get("text", "\x00")  # markers take 1 pool char
                out.append(dict(base, kind=mtk.MT_INSERT, pos=op["pos"],
                                pool_start=pool.append(doc, text),
                                text_len=len(text)))
            elif op["type"] == "remove":
                out.append(dict(base, kind=mtk.MT_REMOVE, pos=op["start"],
                                end=op["end"]))
            else:
                for key, value in sorted(op["props"].items()):
                    kslot = key_slots.setdefault(key, len(key_slots))
                    if value is None:
                        vid = 0
                    else:
                        vid = val_ids.setdefault(repr(value), len(val_ids) + 1)
                    out.append(dict(base, kind=mtk.MT_ANNOTATE,
                                    pos=op["start"], end=op["end"],
                                    prop_key=kslot, prop_val=vid))
    return out


@pytest.mark.parametrize("seed", range(4))
def test_kernel_matches_replicas(seed):
    rng = random.Random(seed)
    n_docs = 3
    server = LocalCollabServer()
    docs = []
    for d in range(n_docs):
        c1 = make_string_doc(server, f"doc{d}")
        others = [Container.load(LocalDocumentService(server, f"doc{d}"))
                  for _ in range(2)]
        docs.append([c1] + others)

    for _round in range(5):
        for containers in docs:
            paused = [c for c in containers if rng.random() < 0.3]
            for c in paused:
                c.inbound.pause()
            for _ in range(rng.randrange(3, 8)):
                random_edit(rng, get_string(
                    containers[rng.randrange(len(containers))]))
            for c in paused:
                c.inbound.resume()

    # Converged replica texts (the oracle).
    expected = []
    for containers in docs:
        texts = [get_string(c).get_text() for c in containers]
        assert all(t == texts[0] for t in texts)
        expected.append(texts[0])

    # Kernel replay of the sequenced logs.
    pool = mtk.TextPool(n_docs)
    client_slots: dict = {}
    key_slots: dict = {}
    val_ids: dict = {}
    streams = [encode_log(server.get_deltas(f"doc{d}", 0), pool, d,
                          client_slots, key_slots, val_ids)
               for d in range(n_docs)]
    state = mtk.init_state(n_docs, num_slots=512)
    k = 16
    longest = max(len(s) for s in streams)
    for start in range(0, longest, k):
        chunk = [s[start:start + k] for s in streams]
        state = mtk.apply_tick(
            state, mtk.make_merge_op_batch(chunk, n_docs, k))

    for d in range(n_docs):
        got = mtk.materialize(state, pool, d)
        # Strip marker placeholder chars from the kernel text.
        got = got.replace("\x00", "")
        assert got == expected[d], (seed, d, got, expected[d])


@pytest.mark.soak  # ~60s/seed: the fused-vs-spec oracle runs in the soak tier
@pytest.mark.slow
@pytest.mark.parametrize("seed", range(4))
def test_fused_apply_op_matches_sequential_spec(seed):
    """_apply_op_spec (sequential split/split/place composition) is the
    executable spec; the fused single-phase _apply_op must equal it on
    every plane for random op streams — including same-segment double
    splits, placements at fresh boundaries, tie-breaks over tombstones,
    and concurrent-window visibility."""
    rng = random.Random(7000 + seed)
    for trial in range(12):
        n_slots = 24
        n_ops = rng.randrange(4, 12)
        ops = []
        length = 0
        for seq in range(1, n_ops + 1):
            client = rng.randrange(4)
            ref_seq = rng.randrange(max(seq - 3, 0), seq)
            if length > 4 and rng.random() < 0.4:
                start = rng.randrange(length - 2)
                # end == start occasionally: the empty-range case must not
                # trigger a second split (the p2 == p1 guard).
                end = start + rng.randint(0, min(3, length - start))
                kind = rng.choice([mtk.MT_REMOVE, mtk.MT_ANNOTATE])
                op = dict(kind=kind, pos=start, end=end, seq=seq,
                          ref_seq=ref_seq, client=client)
                if kind == mtk.MT_ANNOTATE:
                    op.update(prop_key=rng.randrange(2),
                              prop_val=rng.randrange(1, 5))
                else:
                    length -= end - start
                ops.append(op)
            else:
                tlen = rng.randint(1, 4)
                ops.append(dict(kind=mtk.MT_INSERT,
                                pos=rng.randint(0, length), seq=seq,
                                ref_seq=ref_seq, client=client,
                                pool_start=seq * 10, text_len=tlen))
                length += tlen
        batch = mtk.make_merge_op_batch([ops], 1, n_ops)
        fused = jax.tree.map(lambda a: a[0], mtk.init_state(1, n_slots, 2))
        spec = fused
        for k in range(n_ops):
            one = jax.tree.map(lambda a: a[0, k], batch)
            fused = mtk._apply_op(fused, one)
            spec = mtk._apply_op_spec(spec, one)
            for field in mtk.MergeState._fields:
                assert np.array_equal(np.asarray(getattr(fused, field)),
                                      np.asarray(getattr(spec, field))), \
                    (seed, trial, k, field, ops[k])


def test_kernel_basic_concurrent_insert_order():
    # Two concurrent inserts at pos 0: later seq lands left (breakTie).
    pool = mtk.TextPool(1)
    ops = [
        dict(kind=mtk.MT_INSERT, pos=0, seq=1, ref_seq=0, client=0,
             pool_start=pool.append(0, "AAA"), text_len=3),
        dict(kind=mtk.MT_INSERT, pos=0, seq=2, ref_seq=0, client=1,
             pool_start=pool.append(0, "BBB"), text_len=3),
    ]
    state = mtk.init_state(1, num_slots=16)
    state = mtk.apply_tick(state, mtk.make_merge_op_batch([ops], 1, 4))
    assert mtk.materialize(state, pool, 0) == "BBBAAA"


def test_kernel_insert_into_removed_range():
    pool = mtk.TextPool(1)
    ops = [
        dict(kind=mtk.MT_INSERT, pos=0, seq=1, ref_seq=0, client=0,
             pool_start=pool.append(0, "abcdef"), text_len=6),
        dict(kind=mtk.MT_REMOVE, pos=0, end=6, seq=2, ref_seq=1, client=1),
        dict(kind=mtk.MT_INSERT, pos=3, seq=3, ref_seq=1, client=2,
             pool_start=pool.append(0, "NEW"), text_len=3),
    ]
    state = mtk.init_state(1, num_slots=16)
    state = mtk.apply_tick(state, mtk.make_merge_op_batch([ops], 1, 4))
    assert mtk.materialize(state, pool, 0) == "NEW"


def test_kernel_compact_drops_old_tombstones():
    pool = mtk.TextPool(2)
    ops0 = [
        dict(kind=mtk.MT_INSERT, pos=0, seq=1, ref_seq=0, client=0,
             pool_start=pool.append(0, "hello"), text_len=5),
        dict(kind=mtk.MT_REMOVE, pos=1, end=3, seq=2, ref_seq=1, client=0),
    ]
    state = mtk.init_state(2, num_slots=16)
    state = mtk.apply_tick(state, mtk.make_merge_op_batch([ops0, []], 2, 4))
    before = int(np.sum(np.asarray(state.valid[0])))
    state = mtk.compact(state, jnp.asarray([2, 0], np.int32))
    after = int(np.sum(np.asarray(state.valid[0])))
    assert after < before
    assert mtk.materialize(state, pool, 0) == "hlo"
    # Doc 1 untouched.
    assert int(state.count[1]) == 0


@pytest.mark.parametrize("seed", [0, 1])
def test_compact_coalesce_preserves_semantics(seed):
    """The coalescing zamboni (compact(coalesce=True), mergeTree.ts:1412
    pack analog): after merging adjacent acked live runs, (a) the
    materialized text is byte-identical, (b) the slot count drops, and
    (c) FUTURE concurrent ops (refs at/after the window) resolve exactly
    as on the uncoalesced table."""
    rng = random.Random(40 + seed)
    pool = mtk.TextPool(1)
    ops, length, seq = [], 0, 0
    # Fully-acked history: plenty of adjacent same-client inserts.
    for _ in range(120):
        seq += 1
        if length > 12 and rng.random() < 0.3:
            start = rng.randrange(length - 6)
            end = start + rng.randint(1, 6)
            ops.append(dict(kind=mtk.MT_REMOVE, pos=start, end=end,
                            seq=seq, ref_seq=seq - 1,
                            client=rng.randrange(4)))
            length -= end - start
        else:
            text = "".join(rng.choice("abcdefgh")
                           for _ in range(rng.randint(1, 5)))
            ops.append(dict(kind=mtk.MT_INSERT,
                            pos=rng.randint(0, length), seq=seq,
                            ref_seq=seq - 1, client=rng.randrange(4),
                            pool_start=pool.append(0, text),
                            text_len=len(text)))
            length += len(text)
    state = mtk.init_state(1, 512)
    state = mtk.apply_tick(state, mtk.make_merge_op_batch([ops], 1, 128))
    ms = seq  # whole history acked below the window

    # Host text repack (document order becomes pool-contiguous), exactly
    # as the serving host runs before a coalescing compact.
    valid = np.asarray(state.valid[0])
    lens = np.asarray(state.length[0])
    rems = np.asarray(state.rem_seq[0])
    starts = np.asarray(state.pool_start[0]).copy()
    buf = pool.buffer(0)
    pieces, used = [], 0
    for i in range(valid.shape[0]):
        if valid[i] and lens[i] > 0:
            pieces.append(buf[starts[i]:starts[i] + lens[i]])
            starts[i] = used
            used += lens[i]
    pool.chunks[0] = pieces
    pool.used[0] = used
    state = state._replace(
        pool_start=state.pool_start.at[0].set(jnp.asarray(starts)))

    plain = mtk.compact(state, jnp.asarray([ms], np.int32))
    packed = mtk.compact(state, jnp.asarray([ms], np.int32),
                         coalesce=True)
    assert mtk.materialize(packed, pool, 0) == \
        mtk.materialize(plain, pool, 0)
    assert int(packed.count[0]) < int(plain.count[0]), \
        (int(packed.count[0]), int(plain.count[0]))

    # Future concurrent ops on both tables must resolve identically:
    # overlapping removes + inserts from distinct clients sharing refs.
    future, flen = [], len(mtk.materialize(plain, pool, 0))
    fseq = seq
    for _ in range(24):
        fseq += 1
        if flen > 8 and rng.random() < 0.4:
            start = rng.randrange(flen - 4)
            end = start + rng.randint(1, 4)
            future.append(dict(kind=mtk.MT_REMOVE, pos=start, end=end,
                               seq=fseq, ref_seq=rng.randint(ms, fseq - 1),
                               client=rng.randrange(4)))
            flen -= end - start
        else:
            text = rng.choice("xyzw") * rng.randint(1, 3)
            future.append(dict(kind=mtk.MT_INSERT,
                               pos=rng.randint(0, flen), seq=fseq,
                               ref_seq=rng.randint(ms, fseq - 1),
                               client=rng.randrange(4),
                               pool_start=pool.append(0, text),
                               text_len=len(text)))
            flen += len(text)
    batch = mtk.make_merge_op_batch([future], 1, 32)
    out_plain = mtk.apply_tick(plain, batch)
    out_packed = mtk.apply_tick(packed, batch)
    assert mtk.materialize(out_packed, pool, 0) == \
        mtk.materialize(out_plain, pool, 0)
