"""Service load harness + batched-cadence deli path.

Reference parity: packages/test/service-load-test/src/nodeStressTest.ts
(drive the assembled service with many clients and verify convergence) and
the deli lambda's batch contract (server/routerlicious/packages/lambdas/src/
deli/lambda.ts:148-151 offset dedup preserved across the batch boundary).
"""

from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server import kernel_host as kernel_host_module
from fluidframework_tpu.server.kernel_host import KernelSequencerHost
from fluidframework_tpu.server.routerlicious import RouterliciousService
from fluidframework_tpu.tools.load_test import run_load

from test_sequencer import join, op


def _make_doc(service, doc_id):
    container = Container.create_detached(
        LocalDocumentService(service, doc_id))
    datastore = container.runtime.create_datastore("default")
    datastore.create_channel("root", SharedMap.channel_type)
    container.attach()
    return container


class TestLoadHarness:
    def test_smoke_scalar_sequencer(self):
        report = run_load("smoke", use_device_sequencer=False)
        assert report["converged"]
        assert report["ops_sent"] == 120
        assert report["sequenced_ops"] >= report["ops_sent"]

    def test_smoke_device_sequencer(self):
        report = run_load("smoke", use_device_sequencer=True)
        assert report["converged"]
        assert report["ops_sent"] == 120

    def test_batched_cadence_multi_round(self):
        # ops from several rounds buffer in the device host and sequence in
        # fewer, larger ticks — convergence must be cadence-independent.
        report = run_load("smoke", use_device_sequencer=True,
                          pump_every_rounds=5)
        assert report["converged"]


class TestBatchedDeli:
    def test_one_device_tick_spans_partitions(self, monkeypatch):
        # Documents hash onto different rawdeltas partitions, yet one pump
        # round must issue ONE process_batch device call covering all of
        # them (the whole point of the device sequencer host).
        calls = []
        real = kernel_host_module.seqk.process_batch
        monkeypatch.setattr(kernel_host_module.seqk, "process_batch",
                            lambda state, ops: calls.append(1) or
                            real(state, ops))
        service = RouterliciousService(auto_pump=False,
                                       batched_deli_host=KernelSequencerHost())
        docs = [_make_doc(service, f"part-{i}") for i in range(6)]
        service.pump()
        calls.clear()
        for i, container in enumerate(docs):
            container.runtime.get_datastore("default").get_channel(
                "root").set("k", i)
        service.pump()
        assert len(calls) == 1, f"expected 1 device tick, got {len(calls)}"
        for container in docs:
            assert container.runtime.get_datastore("default").get_channel(
                "root").get("k") is not None

    def test_service_restart_reuses_live_host(self):
        # Operators hold the host as a constructor arg; passing the SAME
        # live host to the recovery service must work — restore() replaces
        # the stale device rows with the checkpointed state.
        host = KernelSequencerHost()
        service = RouterliciousService(auto_pump=False,
                                       batched_deli_host=host)
        container = _make_doc(service, "reuse-doc")
        service.pump()
        container.runtime.get_datastore("default").get_channel(
            "root").set("pre", 1)
        service.pump()

        recovered = RouterliciousService(bus=service.bus, store=service.store,
                                         auto_pump=False,
                                         batched_deli_host=host)
        replica = Container.load(LocalDocumentService(recovered, "reuse-doc"))
        recovered.pump()
        replica.runtime.get_datastore("default").get_channel(
            "root").set("post", 2)
        recovered.pump()
        root = replica.runtime.get_datastore("default").get_channel("root")
        assert root.get("pre") == 1 and root.get("post") == 2

    def test_sync_sequence_preserves_pending_tickets(self):
        # A sync sequence() call flushes queued batch ops first; their
        # tickets must surface on the next flush(), never be dropped.
        host = KernelSequencerHost()
        host.submit("doc", join("alice"))
        host.submit("doc", op("alice", 1, 0))
        sync_ticket = host.sequence("doc", op("alice", 2, 0))
        assert sync_ticket.seq == 3
        buffered = host.flush()
        assert [t.seq for t in buffered["doc"]] == [1, 2]


def test_storm_load_harness_small_scale():
    """The full_storm load profile (>=1M ops on real hardware; the
    reference full profile analog) at smoke scale: the harness drives the
    real socket path and verifies against the scalar replay oracle."""
    from fluidframework_tpu.tools.load_test import run_storm_load

    report = run_storm_load(total_ops=8_192, num_docs=32, k=32)
    assert report["converged"]
    assert report["ops_sequenced"] >= 8_192
