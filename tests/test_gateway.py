"""Gateway web host (server/gateway analog): token minting + server-side
document loading over the network front door."""

from __future__ import annotations

import json
import subprocess
import sys
import time
import urllib.request

import pytest

from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.drivers.tinylicious_driver import (
    TinyliciousDocumentServiceFactory,
)
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.gateway import Gateway, serve
from fluidframework_tpu.server.riddler import TenantManager


@pytest.fixture(scope="module")
def alfred():
    proc = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.server.alfred",
         "--port", "0", "--no-merge-host"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    line = proc.stdout.readline()
    assert line.startswith("READY "), (line, proc.stderr.read())
    yield int(line.split()[1])
    proc.terminate()
    proc.wait(timeout=10)


def _get(url: str):
    with urllib.request.urlopen(url, timeout=30) as resp:
        return resp.status, resp.read()


def _make_doc(port: int, doc_id: str) -> None:
    factory = TinyliciousDocumentServiceFactory(port=port)
    svc = factory(doc_id)
    container = Container.create_detached(svc)
    ds = container.runtime.create_datastore("default")
    ds.create_channel("root", SharedMap.channel_type)
    with svc.dispatch_lock:
        container.attach()
        ds.get_channel("root").set("title", "hello-gateway")
    deadline = time.monotonic() + 30
    while (container.runtime.pending.has_pending
           and time.monotonic() < deadline):
        time.sleep(0.02)
    assert not container.runtime.pending.has_pending
    svc.close()


def test_gateway_serves_document_json_and_view(alfred):
    _make_doc(alfred, "gdoc")
    server, _thread = serve(Gateway("127.0.0.1", alfred))
    port = server.server_address[1]
    try:
        status, body = _get(f"http://127.0.0.1:{port}/healthz")
        assert status == 200

        status, body = _get(f"http://127.0.0.1:{port}/doc/gdoc")
        assert status == 200
        summary = json.loads(body)
        assert "hello-gateway" in json.dumps(summary)

        status, body = _get(f"http://127.0.0.1:{port}/doc/gdoc/view")
        assert status == 200
        assert b"hello-gateway" in body and body.startswith(b"<!doctype")
    finally:
        server.shutdown()


def test_gateway_token_minting_and_denial(alfred):
    tenants = TenantManager()
    tenant = tenants.create_tenant("acme")
    server, _thread = serve(Gateway(
        "127.0.0.1", alfred, tenant_id="acme",
        tenant_secret=tenant.secret))
    port = server.server_address[1]
    try:
        status, body = _get(f"http://127.0.0.1:{port}/token?doc=gdoc")
        assert status == 200
        token = json.loads(body)["token"]
        claims = tenants.validate_token(token, document_id="gdoc")
        assert claims["tenantId"] == "acme"
    finally:
        server.shutdown()

    # No secret configured -> 403, not a crash.
    server, _thread = serve(Gateway("127.0.0.1", alfred))
    port = server.server_address[1]
    try:
        try:
            status, _body = _get(f"http://127.0.0.1:{port}/token?doc=x")
        except urllib.error.HTTPError as err:
            status = err.code
        assert status == 403
    finally:
        server.shutdown()
