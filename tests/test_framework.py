"""Aqueduct-equivalent framework layer tests.

Reference parity model: packages/framework/aqueduct tests + the clicker
example (examples/data-objects/clicker) written against DataObject /
DataObjectFactory / ContainerRuntimeFactoryWithDefaultDataStore, and the
fluid-static simplified client.
"""

from fluidframework_tpu.dds.counter import SharedCounter
from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.framework import (
    ContainerRuntimeFactoryWithDefaultDataStore,
    DataObject,
    DataObjectFactory,
    create_container,
    get_container,
)
from fluidframework_tpu.server.local_server import LocalCollabServer


class Clicker(DataObject):
    """The reference's flagship example app (examples/data-objects/clicker):
    a SharedCounter reached via a handle stored in the root directory."""

    def initializing_first_time(self, props=None) -> None:
        counter = self.runtime.create_channel(
            "clicks", SharedCounter.channel_type)
        self.root.set("clicks", counter.handle)

    @property
    def counter(self) -> SharedCounter:
        return self.root.get("clicks").get()

    def click(self) -> None:
        self.counter.increment()


ClickerFactory = DataObjectFactory("clicker", Clicker)


def _runtime_factory():
    return ContainerRuntimeFactoryWithDefaultDataStore(ClickerFactory)


class TestDataObject:
    def test_clicker_two_clients_converge(self):
        server = LocalCollabServer()
        factory = _runtime_factory()
        c1, clicker1 = factory.create_document(
            LocalDocumentService(server, "doc"))
        c1.attach()
        c2, clicker2 = factory.load_document(
            LocalDocumentService(server, "doc"))

        clicker1.click()
        clicker2.click()
        clicker2.click()
        assert clicker1.counter.value == clicker2.counter.value == 3
        assert c1.summarize() == c2.summarize()

    def test_default_object_is_gc_root(self):
        server = LocalCollabServer()
        factory = _runtime_factory()
        c1, clicker = factory.create_document(
            LocalDocumentService(server, "doc"))
        c1.attach()
        gc = c1.runtime.run_gc()
        assert "/default" in gc.referenced
        assert "/default/clicks" in gc.referenced  # via the stored handle

    def test_create_object_at_runtime_reachable_via_handle(self):
        server = LocalCollabServer()
        factory = _runtime_factory()
        c1, clicker1 = factory.create_document(
            LocalDocumentService(server, "doc"))
        c1.attach()
        c2, clicker2 = factory.load_document(
            LocalDocumentService(server, "doc"))

        extra = factory.create_object(c1, "clicker")
        clicker1.root.set("extra", extra.handle)
        extra.click()

        extra2_handle = clicker2.root.get("extra")
        extra2 = factory.get_object(c2, extra2_handle.get().id)
        assert extra2.counter.value == 1
        extra2.click()
        assert extra.counter.value == 2
        assert "/%s" % extra.id in c1.runtime.run_gc().referenced
        assert c1.summarize() == c2.summarize()

    def test_type_attribute_persisted(self):
        server = LocalCollabServer()
        factory = _runtime_factory()
        c1, _ = factory.create_document(LocalDocumentService(server, "doc"))
        c1.attach()
        c2, _ = factory.load_document(LocalDocumentService(server, "doc"))
        ds = c2.runtime.get_datastore("default")
        assert ds.attributes["type"] == "clicker"


class TestFluidStatic:
    def test_initial_objects_roundtrip(self):
        server = LocalCollabServer()
        fc1 = create_container(
            LocalDocumentService(server, "doc"),
            {"kv": SharedMap, "text": SharedString})
        fc2 = get_container(LocalDocumentService(server, "doc"))

        fc1.initial_objects["kv"].set("a", 1)
        fc2.initial_objects["text"].insert_text(0, "hello")
        assert fc2.initial_objects["kv"].get("a") == 1
        assert fc1.initial_objects["text"].get_text() == "hello"
        assert fc1.container.summarize() == fc2.container.summarize()
