"""Huge-document serving through the sequence-parallel pool: a document
whose segment table outgrows the single-chip buckets migrates into a
_ShardedMergePool (segment axis over the virtual mesh) and keeps serving
— device text still byte-identical to every replica."""

from __future__ import annotations

import random

import numpy as np
import pytest

from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.ops.mergetree_sharded import make_seg_mesh
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.local_server import LocalCollabServer
from fluidframework_tpu.server.merge_host import KernelMergeHost, _ShardedMergePool
from tests.test_mergetree import get_string, make_string_doc, random_edit


def test_huge_doc_migrates_to_sharded_pool(cpu_mesh_devices):
    mesh = make_seg_mesh(cpu_mesh_devices)
    # Tiny buckets + low threshold so the migration happens at test scale:
    # merge_slots=16, anything needing >= 64 slots goes sequence-parallel.
    host = KernelMergeHost(merge_slots=16, seg_mesh=mesh,
                           sharded_slot_threshold=64)
    server = LocalCollabServer(merge_host=host)
    c1 = make_string_doc(server, "huge")
    c2 = Container.load(LocalDocumentService(server, "huge"))

    rng = random.Random(3)
    for _ in range(120):
        random_edit(rng, get_string(c1 if rng.random() < 0.5 else c2))
    host.flush()

    t1 = get_string(c1).get_text()
    assert t1 == get_string(c2).get_text()
    assert host.text("huge", "default", "text") == t1

    key = next(iter(host._merge_rows))
    row = host._merge_rows[key]
    assert isinstance(row.pool, _ShardedMergePool), (
        f"doc stayed in a {row.pool.slots}-slot single-chip pool")
    # The serving state is genuinely distributed over the mesh.
    devices = {s.device for s in row.pool.state.length.addressable_shards}
    assert len(devices) == len(cpu_mesh_devices)
    assert host.stats["migrations"] >= 1

    # And the sharded pool keeps serving subsequent edits.
    for _ in range(20):
        random_edit(rng, get_string(c1))
    host.flush()
    t1 = get_string(c1).get_text()
    assert get_string(c2).get_text() == t1
    assert host.text("huge", "default", "text") == t1


def test_writer_count_auto_promotes_and_idle_demotes(cpu_mesh_devices):
    """The mega-doc residency class by OBSERVED load (ISSUE 12): a doc
    whose device-tracked writer set crosses megadoc_writer_threshold
    promotes to a sequence-parallel pool at the next flush (pending ops
    ride the move and serve from the mesh the same tick); a promoted row
    idle long enough demotes back to its block bucket — text identical
    throughout to an untouched twin."""
    mesh = make_seg_mesh(cpu_mesh_devices)

    def play(threshold):
        host = KernelMergeHost(merge_slots=16, seg_mesh=mesh,
                               sharded_slot_threshold=4096,
                               megadoc_writer_threshold=threshold,
                               megadoc_demote_idle_flushes=2)
        server = LocalCollabServer(merge_host=host)
        c1 = make_string_doc(server, "swarm")
        containers = [c1] + [
            Container.load(LocalDocumentService(server, "swarm"))
            for _ in range(3)]
        rng = random.Random(5)
        for _ in range(40):
            random_edit(rng, get_string(rng.choice(containers)))
        host.flush()
        mid = host.text("swarm", "default", "text")
        for _ in range(4):
            host.flush()  # idle flushes: the cooling signal
        for _ in range(10):
            random_edit(rng, get_string(containers[0]))
        host.flush()
        return host, host.text("swarm", "default", "text"), mid

    host, text, _mid = play(threshold=3)   # 4 writers >= 3: promotes
    twin, t_text, _ = play(threshold=None)  # auto tier off
    assert text == t_text
    assert host.stats["megadoc_promotions"] >= 1
    assert host.stats["megadoc_demotions"] >= 1
    key = next(iter(host._merge_rows))
    assert not host.is_mega_row(key)  # cooled back to the block bucket
    assert not twin.stats["megadoc_promotions"]
