"""Loader + code-proposal-driven runtime instantiation
(loader.ts:103 Loader.resolve, container.ts:1700-1835 quorum "code" →
instantiateRuntime, web-code-loader)."""

from __future__ import annotations

import pytest

from fluidframework_tpu.dds.counter import SharedCounterFactory
from fluidframework_tpu.dds.map import SharedMap, SharedMapFactory
from fluidframework_tpu.dds.shared_object import ChannelRegistry
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.runtime.loader import (
    CodeLoader,
    Loader,
    StaticRuntimeFactory,
)
from fluidframework_tpu.server.local_server import LocalCollabServer


def make_loader(server):
    code_loader = CodeLoader()
    registry = ChannelRegistry([SharedMapFactory(), SharedCounterFactory()])
    code_loader.register("@demo/clicker", StaticRuntimeFactory(registry))
    return Loader(lambda doc_id: LocalDocumentService(server, doc_id),
                  code_loader)


class TestLoader:
    def test_create_then_resolve_by_code_proposal(self):
        server = LocalCollabServer()
        loader = make_loader(server)
        c1 = loader.create_detached({"package": "@demo/clicker"},
                                    "fluid://localhost/doc1")
        ds = c1.runtime.create_datastore("default")
        ds.create_channel("root", SharedMap.channel_type)
        c1.attach()
        ds.get_channel("root").set("k", 1)

        # The attach snapshot carries the committed code value; resolve
        # picks the factory from the quorum, NOT from a passed registry.
        c2 = loader.resolve("fluid://localhost/doc1")
        assert c2.protocol.quorum.get("code") == {"package": "@demo/clicker"}
        root2 = c2.runtime.get_datastore("default").get_channel("root")
        assert root2.get("k") == 1
        root2.set("j", 2)
        assert ds.get_channel("root").get("j") == 2

    def test_resolve_unregistered_code_fails(self):
        server = LocalCollabServer()
        loader = make_loader(server)
        c1 = loader.create_detached({"package": "@demo/clicker"},
                                    "fluid://localhost/doc2")
        c1.runtime.create_datastore("default").create_channel(
            "root", SharedMap.channel_type)
        c1.attach()

        empty = Loader(lambda d: LocalDocumentService(server, d),
                       CodeLoader())
        with pytest.raises(KeyError):
            empty.resolve("fluid://localhost/doc2")

    def test_create_unknown_package_fails(self):
        server = LocalCollabServer()
        loader = make_loader(server)
        with pytest.raises(KeyError):
            loader.create_detached({"package": "@nope/missing"},
                                   "fluid://localhost/doc3")

    def test_url_parsing(self):
        assert Loader._doc_id("fluid://host:8080/my-doc") == "my-doc"
        assert Loader._doc_id("plain-doc-id") == "plain-doc-id"
        with pytest.raises(ValueError):
            Loader._doc_id("fluid://host-only/")

    def test_version_selection(self):
        server = LocalCollabServer()
        code_loader = CodeLoader()
        v1 = StaticRuntimeFactory(ChannelRegistry([SharedMapFactory()]))
        v2 = StaticRuntimeFactory(ChannelRegistry([SharedMapFactory()]))
        code_loader.register("@demo/app", v1, version="1.0.0")
        code_loader.register("@demo/app", v2, version="2.0.0")
        assert code_loader.load(
            {"package": "@demo/app", "version": "2.0.0"}) is v2
        assert code_loader.load({"package": "@demo/app"}) is v1
