"""Columnar op-storm fast path (server/storm.py): the batched-cadence
deli → merger pipeline fused into one device tick, fed by binary frames.

Oracles: (1) the device map state must equal a scalar MapData replay of
the messages the catch-up read path materializes from the columnar
durable records; (2) resending an un-acked frame must be fully ignored
(kernel clientSequenceNumber dedup — at-least-once delivery contract);
(3) unknown writers are rejected by the sequencer kernel, not trusted.
"""

import json
import socket
import struct

import numpy as np
import pytest

from fluidframework_tpu.dds.map_data import MapData
from fluidframework_tpu.protocol.codec import (
    decode_storm_body,
    decode_storm_push,
    encode_storm_body,
    encode_storm_frame,
    is_storm_body,
)
from fluidframework_tpu.protocol.messages import MessageType
from fluidframework_tpu.server.kernel_host import KernelSequencerHost
from fluidframework_tpu.server.merge_host import KernelMergeHost
from fluidframework_tpu.server.routerlicious import RouterliciousService
from fluidframework_tpu.server.storm import StormController


def make_service(num_docs=8, flush_threshold_docs=10**9):
    seq_host = KernelSequencerHost(num_slots=2, initial_capacity=num_docs)
    merge_host = KernelMergeHost(flush_threshold=10**9)
    service = RouterliciousService(merge_host=merge_host,
                                   batched_deli_host=seq_host,
                                   auto_pump=False)
    storm = StormController(service, seq_host, merge_host,
                            flush_threshold_docs=flush_threshold_docs)
    return service, storm, merge_host


def join_docs(service, docs):
    clients = {d: service.connect(d, lambda m: None).client_id
               for d in docs}
    service.pump()
    return clients


def make_words(rng, k, num_slots=16):
    kinds = rng.choice([0, 0, 0, 1, 2], size=k).astype(np.uint32)
    slots = rng.integers(0, num_slots, k).astype(np.uint32)
    vals = rng.integers(0, 1 << 20, k).astype(np.uint32)
    return (kinds | (slots << 2) | (vals << 12)).astype(np.uint32)


def read_push(sock):
    """One server push off the wire: binary storm acks decode through
    the codec; JSON control frames through json."""
    length = struct.unpack(">I", sock.recv(4, socket.MSG_WAITALL))[0]
    body = sock.recv(length, socket.MSG_WAITALL)
    if is_storm_body(body):
        return decode_storm_push(body)
    return json.loads(body.decode())


def replay_oracle(service, doc_id):
    """Scalar MapData fold of the materialized catch-up messages."""
    data = MapData()
    for m in service.get_deltas(doc_id, 0):
        if m.type != MessageType.OPERATION or not isinstance(m.contents,
                                                             dict):
            continue
        inner = m.contents.get("contents", {}).get("contents")
        if inner:
            data.process(inner, False, None)
    return dict(data.items())


def test_codec_roundtrip():
    words = np.arange(7, dtype=np.uint32)
    body = encode_storm_body({"op": "storm", "docs": []}, words.tobytes())
    assert is_storm_body(body)
    header, payload = decode_storm_body(body)
    assert header["op"] == "storm"
    assert np.array_equal(np.frombuffer(payload, np.uint32), words)
    assert not is_storm_body(b'{"op": "connect"}')


def test_storm_matches_scalar_replay_and_acks():
    docs = [f"doc{i}" for i in range(8)]
    service, storm, merge_host = make_service()
    clients = join_docs(service, docs)
    rng = np.random.default_rng(0)
    k = 64
    acks = []
    cseqs = {d: 1 for d in docs}
    for _tick in range(3):
        payload, hdr_docs = b"", []
        for d in docs:
            w = make_words(rng, k)
            payload += w.tobytes()
            hdr_docs.append([d, clients[d], cseqs[d], 1, k])
            cseqs[d] += k
        storm.submit_frame(acks.append, {"op": "storm", "rid": _tick,
                                         "docs": hdr_docs},
                           memoryview(payload))
    storm.flush()
    assert storm.stats["sequenced_ops"] == len(docs) * k * 3
    assert len(acks) == 3
    for ack in acks:
        assert all(a[0] == k for a in ack["acks"])
    for d in docs:
        assert merge_host.map_entries(d, "default", "root") \
            == replay_oracle(service, d), d


def test_storm_resend_is_ignored_not_reapplied():
    docs = ["doc0", "doc1"]
    service, storm, merge_host = make_service()
    clients = join_docs(service, docs)
    k = 16
    words = make_words(np.random.default_rng(1), k)
    hdr = {"op": "storm", "rid": 1,
           "docs": [[d, clients[d], 1, 1, k] for d in docs]}
    for _ in range(2):  # first send + verbatim resend (no ack seen)
        storm.submit_frame(None, dict(hdr), memoryview(words.tobytes() * 2))
        storm.flush()
    assert storm.stats["sequenced_ops"] == len(docs) * k
    assert storm.stats["nacked_or_ignored_ops"] == len(docs) * k
    for d in docs:
        assert merge_host.map_entries(d, "default", "root") \
            == replay_oracle(service, d)


def test_storm_unknown_writer_rejected_by_kernel():
    service, storm, merge_host = make_service()
    join_docs(service, ["doc0"])
    k = 8
    words = make_words(np.random.default_rng(2), k)
    acks = []
    storm.submit_frame(acks.append, {
        "op": "storm", "rid": 9,
        "docs": [["doc0", "client-never-joined", 1, 1, k]],
    }, memoryview(words.tobytes()))
    storm.flush()
    assert acks[0]["acks"][0][0] == 0  # zero ops sequenced
    assert storm.stats["sequenced_ops"] == 0
    assert merge_host.map_entries("doc0", "default", "root") == {}


def test_storm_and_dict_paths_share_the_sequencer_state():
    """Per-doc total order is ONE stream: ops submitted through the
    regular front door and storm ops interleave with strictly increasing
    seqs."""
    service, storm, merge_host = make_service()
    docs = ["doc0"]
    clients = join_docs(service, docs)
    k = 8
    words = make_words(np.random.default_rng(3), k)
    storm.submit_frame(None, {"op": "storm", "docs": [
        ["doc0", clients["doc0"], 1, 1, k]]}, memoryview(words.tobytes()))
    storm.flush()
    msgs = service.get_deltas("doc0", 0)
    seqs = [m.sequence_number for m in msgs]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    # join (seq 1) + k storm ops
    assert len([m for m in msgs if m.type == MessageType.OPERATION]) == k


def test_storm_channel_rejects_dict_traffic():
    from fluidframework_tpu.protocol.messages import (
        SequencedDocumentMessage,
    )

    service, storm, merge_host = make_service()
    clients = join_docs(service, ["doc0"])
    words = make_words(np.random.default_rng(4), 4)
    storm.submit_frame(None, {"op": "storm", "docs": [
        ["doc0", clients["doc0"], 1, 1, 4]]}, memoryview(words.tobytes()))
    storm.flush()
    dict_op = SequencedDocumentMessage(
        client_id=clients["doc0"], sequence_number=10**6,
        minimum_sequence_number=0, client_sequence_number=99,
        reference_sequence_number=1, type=MessageType.OPERATION,
        contents={"address": "default",
                  "contents": {"address": "root",
                               "contents": {"type": "set", "key": "x",
                                            "value": 1}}},
        timestamp=0, data=None)
    with pytest.raises(ValueError, match="storm-served"):
        merge_host.ingest("doc0", dict_op)


def test_storm_over_bridge_wire():
    from fluidframework_tpu.server.bridge_host import BridgeFrontDoor

    docs = [f"d{i}" for i in range(4)]
    service, storm, merge_host = make_service(
        flush_threshold_docs=len(docs))
    front = BridgeFrontDoor(service, 0)
    try:
        clients = join_docs(service, docs)
        sock = socket.create_connection(("127.0.0.1", front.port))
        sock.settimeout(30)
        k = 32
        words = (np.arange(k, dtype=np.uint32) << 12)
        hdr = {"op": "storm", "rid": 7,
               "docs": [[d, clients[d], 1, 1, k] for d in docs]}
        sock.sendall(encode_storm_frame(hdr, words.tobytes() * len(docs)))
        ack = read_push(sock)
        assert ack["rid"] == 7 and all(a[0] == k for a in ack["acks"])
        for d in docs:
            assert merge_host.map_entries(d, "default", "root") \
                == {"k0": k - 1}  # LWW: the last set wins
        sock.close()
    finally:
        front.close()


def test_malformed_storm_frames_fail_alone():
    """Bad frames are rejected BEFORE buffering (never poisoning other
    sessions' frames) and the socket answers with an error and lives."""
    from fluidframework_tpu.server.bridge_host import BridgeFrontDoor

    service, storm, merge_host = make_service(flush_threshold_docs=1)
    front = BridgeFrontDoor(service, 0)
    try:
        clients = join_docs(service, ["doc0"])
        sock = socket.create_connection(("127.0.0.1", front.port))
        sock.settimeout(30)

        def roundtrip(hdr, payload):
            sock.sendall(encode_storm_frame(hdr, payload))
            return read_push(sock)

        w4 = np.zeros(4, np.uint32).tobytes()
        # count exceeding the payload
        resp = roundtrip({"op": "storm", "rid": 1,
                          "docs": [["doc0", clients["doc0"], 1, 1, 99]]},
                         w4)
        assert "error" in resp
        # repeated doc within one frame
        resp = roundtrip({"op": "storm", "rid": 2,
                          "docs": [["doc0", clients["doc0"], 1, 1, 4],
                                   ["doc0", clients["doc0"], 5, 1, 4]]},
                         w4 * 2)
        assert "error" in resp
        # key slot out of the configured range
        big_slot = np.full(4, np.uint32(1000 << 2), np.uint32)
        resp = roundtrip({"op": "storm", "rid": 3,
                          "docs": [["doc0", clients["doc0"], 1, 1, 4]]},
                         big_slot.tobytes())
        assert "error" in resp
        # negative count must not slip through np.frombuffer
        resp = roundtrip({"op": "storm", "rid": 4,
                          "docs": [["doc0", clients["doc0"], 1, 1, -1]]},
                         w4)
        assert "error" in resp
        # ...and the connection still works for a GOOD frame.
        resp = roundtrip({"op": "storm", "rid": 5,
                          "docs": [["doc0", clients["doc0"], 1, 1, 4]]},
                         np.full(4, 9 << 12, np.uint32).tobytes())
        assert resp.get("storm") and resp["acks"][0][0] == 4
        assert storm.stats["sequenced_ops"] == 4
        sock.close()
    finally:
        front.close()


def test_storm_tail_frame_drains_on_idle():
    """A frame below the tick threshold must still sequence (bridge idle
    drain) rather than starve waiting for a full cohort."""
    import time

    from fluidframework_tpu.server.bridge_host import BridgeFrontDoor

    service, storm, merge_host = make_service(flush_threshold_docs=1000)
    front = BridgeFrontDoor(service, 0)
    try:
        clients = join_docs(service, ["doc0"])
        sock = socket.create_connection(("127.0.0.1", front.port))
        sock.settimeout(30)
        words = np.full(4, 7 << 12, np.uint32)
        sock.sendall(encode_storm_frame(
            {"op": "storm", "rid": 1,
             "docs": [["doc0", clients["doc0"], 1, 1, 4]]},
            words.tobytes()))
        ack = read_push(sock)
        assert ack["acks"][0][0] == 4
        sock.close()
    finally:
        front.close()


def test_pipeline_depth_streams_acks_behind_compute():
    """pipeline_depth > 1 with a STREAMING sender (not ack-gated): acks
    lag by <= depth ticks while in flight, every frame is eventually
    acked exactly once, and the map state equals the scalar replay."""
    service, storm, merge_host = make_service(flush_threshold_docs=2)
    storm.pipeline_depth = 3
    docs = ["a", "b"]
    clients = join_docs(service, docs)
    rng = np.random.default_rng(5)
    acks = []
    k = 8
    n_ticks = 6
    for t in range(n_ticks):
        header = {"rid": t, "docs": [[d, clients[d], 1 + t * k, 1, k]
                                     for d in docs]}
        payload = b"".join(make_words(rng, k).tobytes() for _ in docs)
        # submit_frame auto-flushes at the 2-doc threshold: each frame
        # IS one tick.
        storm.submit_frame(acks.append, header, memoryview(payload))
        # Acks really are deferred: exactly `depth` ticks stay in
        # flight, and only the ticks behind them have acked.
        assert len(storm._inflight) == min(t + 1, storm.pipeline_depth)
        assert len(acks) == max(0, t + 1 - storm.pipeline_depth)
    storm.flush()  # drain
    assert storm._inflight == []
    assert sorted(a["rid"] for a in acks) == list(range(n_ticks))
    assert storm.stats["sequenced_ops"] == n_ticks * len(docs) * k
    for d in docs:
        assert (merge_host.map_entries(d, "default", "root")
                == replay_oracle(service, d))


def test_spill_log_restart_recovers_history(tmp_path):
    """A storm controller reopening a spill dir rebuilds its tick index:
    catch-up reads still materialize pre-restart ops, and fresh ticks
    never alias stale blobs (tick ids continue past the journal)."""
    import numpy as np

    from fluidframework_tpu.server.kernel_host import KernelSequencerHost
    from fluidframework_tpu.server.merge_host import KernelMergeHost
    from fluidframework_tpu.server.routerlicious import RouterliciousService
    from fluidframework_tpu.server.storm import StormController

    def build(spill):
        seq_host = KernelSequencerHost(num_slots=2, initial_capacity=4)
        merge_host = KernelMergeHost(row_capacity=4,
                                     flush_threshold=10**9)
        service = RouterliciousService(merge_host=merge_host,
                                       batched_deli_host=seq_host,
                                       auto_pump=False)
        storm = StormController(service, seq_host, merge_host,
                                flush_threshold_docs=1, spill_dir=spill)
        return service, storm

    spill = str(tmp_path / "spill")
    service, storm = build(spill)
    client = service.connect("doc", lambda msgs: None).client_id
    service.pump()
    words = np.arange(8, dtype=np.uint32) << 12
    storm.submit_frame(None, {"op": "storm",
                              "docs": [["doc", client, 1, 1, 8]]},
                       memoryview(words.tobytes()))
    storm.flush()
    before = service.get_deltas("doc", 0)
    assert sum(1 for m in before if m.type.name == "OPERATION") >= 8

    # "Restart": a fresh controller stack over the same spill dir. The
    # sequencer state is fresh, but the durable tick history must read
    # back, and new tick ids must continue past the journal.
    service2, storm2 = build(spill)
    assert storm2._tick_counter == storm._tick_counter
    recs = storm2.records_overlapping("doc", 0)
    assert recs and recs[0]["n_seq"] == 8
    words2 = np.asarray(
        np.frombuffer(storm2.read_tick_words(recs[0]["tick"]), np.uint32,
                      recs[0]["count"], recs[0]["w_off"]))
    assert (words2 == words).all()


def test_ingress_is_zero_copy_through_codec_and_submit():
    """THE zero-copy acceptance bar: the payload handed to submit_frame
    is parsed in place — the buffered frame's word view ALIASES the
    receive buffer (codec → submit_frame with no Python-level byte
    copy), and the only staging write is the tick scatter itself."""
    service, storm, merge_host = make_service()
    clients = join_docs(service, ["a", "b"])
    k = 16
    rng = np.random.default_rng(11)
    payload = b"".join(make_words(rng, k).tobytes() for _ in range(2))
    buf = bytearray(encode_storm_body(
        {"op": "storm", "rid": 1,
         "docs": [["a", clients["a"], 1, 1, k],
                  ["b", clients["b"], 1, 1, k]]}, payload))
    header, view = decode_storm_body(buf)
    assert view.obj is buf  # codec: memoryview-through
    storm.submit_frame(None, header, view)
    frame = storm._frames[0]
    base = np.frombuffer(buf, np.uint8)
    # submit_frame: ONE frombuffer view over the receive buffer — no
    # per-doc slicing copies, no re-parse.
    assert np.shares_memory(frame.words, base)
    storm.flush()
    assert storm.stats["sequenced_ops"] == 2 * k
    for d in ("a", "b"):
        assert merge_host.map_entries(d, "default", "root") \
            == replay_oracle(service, d)


def test_broadcast_fanout_is_batched_native_publishes():
    """O(batch) fan-out acceptance bar: one serving tick's broadcasts go
    through the fan-out service as ONE batched publish call (covering
    every doc), never one Python write per subscriber connection."""
    from fluidframework_tpu.native.fanout import make_fanout

    class CountingFanout:
        def __init__(self):
            self.inner = make_fanout(force_python=True)
            self.publish_calls = 0
            self.batch_calls = 0

        def publish(self, room, payload):
            self.publish_calls += 1
            return self.inner.publish(room, payload)

        def publish_batch(self, items):
            self.batch_calls += 1
            return self.inner.publish_batch(items)

        def __getattr__(self, name):
            return getattr(self.inner, name)

    from fluidframework_tpu.server.kernel_host import KernelSequencerHost
    from fluidframework_tpu.server.merge_host import KernelMergeHost

    fanout = CountingFanout()
    seq_host = KernelSequencerHost(num_slots=2, initial_capacity=8)
    merge_host = KernelMergeHost(flush_threshold=10**9)
    service = RouterliciousService(merge_host=merge_host,
                                   batched_deli_host=seq_host,
                                   auto_pump=False, fanout=fanout)
    storm = StormController(service, seq_host, merge_host,
                            flush_threshold_docs=10**9)
    docs = [f"d{i}" for i in range(6)]
    clients = join_docs(service, docs)
    # N read-only subscribers per doc on the fan-out rooms.
    subs = []
    for d in docs:
        for _ in range(4):
            sub = fanout.connect()
            fanout.join(sub, d)
            subs.append(sub)
    rng = np.random.default_rng(12)
    k = 8
    payload = b"".join(make_words(rng, k).tobytes() for _ in docs)
    fanout.batch_calls = fanout.publish_calls = 0
    storm.submit_frame(None, {
        "op": "storm", "docs": [[d, clients[d], 1, 1, k] for d in docs]},
        memoryview(payload))
    storm.flush()
    # ONE native batch call for the whole tick; zero per-room Python
    # publishes on the storm path.
    assert fanout.batch_calls == 1
    assert fanout.publish_calls == 0
    # ...and it really fanned out: every subscriber queue got its doc's
    # compact tick frame.
    for sub in subs:
        assert fanout.pending(sub) == 1
        assert fanout.poll(sub)[:1] == b"\x00"


def test_sequenced_broadcast_serialized_once_per_doc():
    """Satellite pin (delivered-bytes / encode-count): one sequenced op
    fanned to N subscriber sessions is JSON-encoded ONCE — every session
    pushes the SAME cached body bytes."""
    from fluidframework_tpu.protocol.codec import (
        BroadcastBatch,
        encode_ops_event,
        ops_event_encode_count,
    )
    from fluidframework_tpu.server.alfred import RequestSession

    class SinkSession(RequestSession):
        def __init__(self, server):
            super().__init__(server)
            self.sent = []

        def push(self, payload):
            self.sent.append(payload)

    service = RouterliciousService()
    server = type("S", (), {"service": service})()
    sessions = [SinkSession(server) for _ in range(5)]

    # The broadcaster hands EVERY subscriber the same BroadcastBatch
    # object (identity-shared per op delivery)...
    received = []
    for i in range(3):
        service.connect("doc", received.append)
    conn = service.connect("doc", received.append)
    received.clear()
    from fluidframework_tpu.protocol.messages import DocumentMessage
    conn.submit([DocumentMessage(
        client_sequence_number=1, reference_sequence_number=0,
        type=MessageType.OPERATION, contents={"k": 1})])
    assert received, "no broadcast delivered"
    batches = [b for b in received if isinstance(b, BroadcastBatch)]
    assert batches, "broadcast batches are not shared BroadcastBatch objects"
    first = batches[0]
    assert sum(1 for b in batches if b is first) >= 3  # same object, all subs

    # ...so the session push path encodes once however many sessions fan
    # it out, and each delivers the identical bytes.
    before = ops_event_encode_count()
    for s in sessions:
        s.push_ops(first)
    assert ops_event_encode_count() - before == 1
    bodies = [s.sent[0] for s in sessions]
    assert all(b is bodies[0] for b in bodies)
    delivered_bytes = sum(len(b) for b in bodies)
    assert delivered_bytes == len(bodies[0]) * len(sessions)
