"""Handles + reference-graph GC tests.

Reference parity model: packages/runtime/garbage-collector tests
(mark reachable from root over handle routes) and handle round-tripping
through SharedMap/SharedDirectory values.
"""

from fluidframework_tpu.dds.counter import SharedCounter
from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.runtime.garbage_collector import (
    run_garbage_collection,
)
from fluidframework_tpu.runtime.handles import (
    FluidHandle,
    collect_handle_routes,
    encode_value,
)
from fluidframework_tpu.server.local_server import LocalCollabServer


class TestGraph:
    def test_mark_from_root(self):
        graph = {
            "/a": ["/a/ch"],
            "/a/ch": ["/b"],
            "/b": ["/b/ch"],
            "/b/ch": [],
            "/c": ["/c/ch"],
            "/c/ch": [],
        }
        result = run_garbage_collection(graph, ["/a"])
        assert result.referenced == ["/a", "/a/ch", "/b", "/b/ch"]
        assert result.deleted == ["/c", "/c/ch"]

    def test_channel_route_keeps_parent_store_alive(self):
        graph = {"/a": ["/a/ch"], "/a/ch": ["/b/ch"],
                 "/b": ["/b/ch"], "/b/ch": []}
        result = run_garbage_collection(graph, ["/a"])
        assert "/b" in result.referenced

    def test_cycle_not_reachable_from_root_is_deleted(self):
        graph = {"/a": [], "/b": ["/c"], "/c": ["/b"]}
        result = run_garbage_collection(graph, ["/a"])
        assert result.deleted == ["/b", "/c"]


class TestHandleEncoding:
    def test_encode_and_collect_nested(self):
        value = {"x": [1, {"h": FluidHandle("/ds/chan")}],
                 "y": FluidHandle("/other")}
        encoded = encode_value(value)
        assert sorted(collect_handle_routes(encoded)) == ["/ds/chan", "/other"]


def _make(server, doc_id="doc"):
    service = LocalDocumentService(server, doc_id)
    container = Container.create_detached(service)
    ds = container.runtime.create_datastore("default")
    ds.create_channel("root", SharedMap.channel_type)
    container.attach()
    return container


class TestLiveHandles:
    def test_handle_roundtrip_across_clients(self):
        server = LocalCollabServer()
        c1 = _make(server)
        ds1 = c1.runtime.get_datastore("default")
        counter = ds1.create_channel("clicks", SharedCounter.channel_type)
        root1 = ds1.get_channel("root")
        root1.set("counter", counter.handle)
        counter.increment(5)

        c2 = Container.load(LocalDocumentService(server, "doc"))
        root2 = c2.runtime.get_datastore("default").get_channel("root")
        handle = root2.get("counter")
        assert isinstance(handle, FluidHandle)
        assert handle.absolute_path == "/default/clicks"
        assert handle.get().value == 5

    def test_gc_reports_unreferenced_datastore(self):
        server = LocalCollabServer()
        c1 = _make(server)
        # Non-root store with no handle to it anywhere → unreferenced.
        orphan = c1.runtime.create_datastore("orphan", root=False)
        orphan.create_channel("data", SharedMap.channel_type)
        result = c1.runtime.run_gc()
        assert "/orphan" in result.deleted
        assert "/orphan/data" in result.deleted
        assert "/default" in result.referenced

        # Storing a handle to it flips it to referenced.
        root = c1.runtime.get_datastore("default").get_channel("root")
        root.set("link", orphan.handle)
        result = c1.runtime.run_gc()
        assert "/orphan" in result.referenced
        assert "/orphan/data" in result.referenced

    def test_live_datastore_and_channel_attach_propagate(self):
        """Stores/channels created AFTER attach reach already-open peers
        via ATTACH ops (containerRuntime.ts attach message path)."""
        server = LocalCollabServer()
        c1 = _make(server)
        c2 = Container.load(LocalDocumentService(server, "doc"))

        ds = c1.runtime.create_datastore("extra", root=False)
        chan = ds.create_channel("notes", SharedMap.channel_type)
        chan.set("k", 1)

        ds2 = c2.runtime.get_datastore("extra")
        assert ds2.get_channel("notes").get("k") == 1
        # And the reverse direction, onto an existing store.
        c2.runtime.get_datastore("default").create_channel(
            "late", SharedCounter.channel_type).increment(3)
        assert c1.runtime.get_datastore("default").get_channel(
            "late").value == 3
        assert c1.summarize() == c2.summarize()

    def test_disconnected_create_replays_without_double_apply(self):
        """The replayed attach must carry the CREATE-time snapshot; the
        counter increments ride their own replayed ops exactly once."""
        server = LocalCollabServer()
        c1 = _make(server)
        c2 = Container.load(LocalDocumentService(server, "doc"))

        c1.disconnect()
        ds = c1.runtime.create_datastore("offline", root=True)
        counter = ds.create_channel("n", SharedCounter.channel_type)
        counter.increment(5)
        c1.reconnect()

        assert counter.value == 5
        assert c2.runtime.get_datastore("offline").get_channel("n").value == 5
        assert c1.summarize() == c2.summarize()

    def test_gc_state_in_summary_and_roots_persist(self):
        server = LocalCollabServer()
        c1 = _make(server)
        c1.runtime.create_datastore("orphan", root=False)
        summary = c1.summarize()
        assert summary["runtime"]["gc"]["unreferenced"] == ["/orphan"]
        assert summary["runtime"]["roots"] == ["default"]

        c2 = Container.load(LocalDocumentService(server, "doc"))
        assert c2.runtime.root_datastores == {"default"}
        assert c1.summarize() == c2.summarize()
