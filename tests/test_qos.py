"""Multi-tenant QoS (round 17): deficit-weighted fair tick composition,
noisy-neighbor isolation, weighted shed, per-tenant SLO observability.

The acceptance bar is measured in TICKS, not wall clock (deterministic
in CI): with one tenant at 10x its rate, the other tenants' ack p99 —
the number of serving ticks between submit and ack — must shift <= 1.25x
vs the no-abuser baseline, while the abuser is confined to its weighted
share. A fairness-off arm of the same workload shows the inversion the
scheduler exists to prevent.
"""

from __future__ import annotations

import math
from types import SimpleNamespace

import numpy as np
import pytest

from fluidframework_tpu.server.qos import TenantScheduler


def F(tenant, docs, mega=None):
    """A duck-typed frame for scheduler unit tests: doc entries are
    (doc, client, cseq0, ref, count) tuples like storm's."""
    return SimpleNamespace(tenant=tenant,
                           docs=[(d, "c", 1, 1, 4) for d in docs],
                           mega=mega)


class TestTenantScheduler:
    def test_single_tenant_reduces_to_legacy_fifo(self):
        """The compatibility bar: one tenant, no budget — every
        disjoint frame serves in arrival order, same-doc repeats stay
        buffered, and NO scheduler state moves."""
        s = TenantScheduler()
        frames = [F("default", ["a", "b"]), F("default", ["c"]),
                  F("default", ["a"]),  # repeats doc a -> next tick
                  F("default", ["d"])]
        plan = s.compose(frames, None)
        assert plan["selected"] == [frames[0], frames[1], frames[3]]
        assert plan["kept"] == [frames[2]]
        assert plan["charge"] == {}
        s.commit(plan)
        assert s.deficit["default"] == 0.0

    def test_mega_fence_blocks_later_frames_of_same_parent(self):
        """Once any frame of a promoted doc is passed over, every later
        frame of that parent is too (the combiner's FIFO law)."""
        s = TenantScheduler()
        frames = [F("default", ["p::~mg0"], mega=[{"doc": "p",
                                                   "lane": 0}]),
                  F("default", ["p::~mg0"], mega=[{"doc": "p",
                                                   "lane": 0}]),
                  F("default", ["p::~mg1"], mega=[{"doc": "p",
                                                   "lane": 1}])]
        plan = s.compose(frames, None)
        # Frame 1 collides on the lane doc; frame 2 (a DIFFERENT lane,
        # no doc collision) must still be fenced behind it.
        assert plan["selected"] == [frames[0]]
        assert plan["kept"] == [frames[1], frames[2]]

    def test_weighted_drr_splits_budget_by_weight(self):
        """2:1 weights over a deep backlog -> served doc slots converge
        to 2:1 across ticks, each tick bounded by the slot budget."""
        s = TenantScheduler(weights={"a": 2.0, "b": 1.0})
        backlog = {"a": [F("a", [f"a{i}"]) for i in range(30)],
                   "b": [F("b", [f"b{i}"]) for i in range(30)]}
        frames = backlog["a"] + backlog["b"]
        contended = {"a": 0, "b": 0}
        served = {"a": 0, "b": 0}
        for _tick in range(10):
            plan = s.compose(frames, budget=6)
            assert sum(len(f.docs) for f in plan["selected"]) == 6
            s.commit(plan)
            both_pending = all(
                any(f.tenant == t for f in plan["kept"] + plan["selected"])
                for t in ("a", "b"))
            for f in plan["selected"]:
                served[f.tenant] += len(f.docs)
                if both_pending:
                    contended[f.tenant] += len(f.docs)
            frames = plan["kept"]
        # Under CONTENTION the split is 2:1 by weight...
        assert abs(contended["a"] / contended["b"] - 2.0) < 0.35
        # ...and once a tenant's backlog drains, the other absorbs the
        # leftover slots (work conservation): every slot was used.
        assert served["a"] + served["b"] == 60

    def test_oversized_frame_cannot_starve(self):
        """A frame wider than any per-tick quantum still serves (the
        starvation guard); its tenant's deficit goes negative and
        self-heals, so flush(force=True) always terminates."""
        s = TenantScheduler(weights={"a": 1.0, "b": 1.0})
        frames = [F("a", [f"w{i}" for i in range(32)]), F("b", ["x"])]
        selected = []
        for _ in range(4):
            plan = s.compose(frames, budget=4)
            s.commit(plan)
            selected.extend(plan["selected"])
            frames = plan["kept"]
            if not frames:
                break
        assert {f.tenant for f in selected} == {"a", "b"}

    def test_idle_tenant_does_not_bank_unbounded_credit(self):
        """A tenant with no pending frames accrues nothing, and an
        active tenant's credit is capped at one tick's quantum — a
        return from idle gets its fair share immediately, never a
        stored burst that starves everyone else."""
        s = TenantScheduler(weights={"a": 1.0, "b": 1.0},
                            quantum_docs=4)
        # 20 ticks of a-only traffic; b idle.
        frames = [F("a", [f"a{i}"]) for i in range(40)]
        for _ in range(5):
            plan = s.compose(frames, budget=4)
            s.commit(plan)
            frames = plan["kept"]
        assert s.deficit.get("b", 0.0) <= 4.0 + 1e-9
        assert s.deficit["a"] <= 4.0 + 1e-9

    def test_cross_tenant_per_doc_fifo_holds(self):
        """Per-doc FIFO is a CROSS-tenant invariant: when two tenants'
        frames name the same doc, the rotation must never serve the
        later arrival first — the earlier frame is the doc's head, the
        later one waits behind it (review fix: without the global
        arrival-head rule the DRR could reorder a shared doc's total
        order relative to the tenant-blind twin)."""
        s = TenantScheduler(weights={"a": 1.0, "b": 1.0})
        frames = [F("b", ["shared"]), F("a", ["x"]), F("a", ["shared"])]
        # Rotation may visit a first; a's "shared" frame (index 2) must
        # NOT be taken while b's earlier frame (index 0) is pending.
        plan = s.compose(frames, budget=8)
        sel = plan["selected"]
        assert frames[0] in sel and frames[1] in sel
        assert frames[2] not in sel  # waits behind b's earlier frame
        s.commit(plan)
        plan2 = s.compose(plan["kept"], budget=8)
        assert plan2["selected"] == [frames[2]]

    def test_export_import_round_trip(self):
        s = TenantScheduler(weights={"a": 2.0})
        frames = [F("a", ["a1"]), F("b", ["b1"]), F("b", ["b2"])]
        plan = s.compose(frames, budget=2)
        s.commit(plan)
        snap = s.export_state()
        s2 = TenantScheduler()
        s2.import_state(snap)
        assert s2.export_state() == snap
        # Identical state composes identically.
        more = [F("a", ["a9"]), F("b", ["b9"])]
        p1, p2 = s.compose(more, budget=1), s2.compose(more, budget=1)
        assert [f.tenant for f in p1["selected"]] \
            == [f.tenant for f in p2["selected"]]


# -- the serving-stack pin -----------------------------------------------------


def _stack(num_docs, **kw):
    from fluidframework_tpu.server.kernel_host import KernelSequencerHost
    from fluidframework_tpu.server.merge_host import KernelMergeHost
    from fluidframework_tpu.server.routerlicious import RouterliciousService
    from fluidframework_tpu.server.storm import StormController

    seq_host = KernelSequencerHost(num_slots=2,
                                   initial_capacity=num_docs)
    merge_host = KernelMergeHost(flush_threshold=10**9)
    service = RouterliciousService(merge_host=merge_host,
                                   batched_deli_host=seq_host,
                                   auto_pump=False,
                                   idle_check_interval=10**9)
    kw.setdefault("flush_threshold_docs", 10**9)
    kw.setdefault("pipeline_depth", 0)  # serial: ack tick == serve tick
    storm = StormController(service, seq_host, merge_host, **kw)
    return service, storm


def _words(seed, r, i, k=8):
    rng = np.random.default_rng([seed, r, i])
    return ((rng.integers(0, 16, k).astype(np.uint32) << 2)
            | (rng.integers(0, 1 << 20, k).astype(np.uint32) << 12))


#: Tenant layout of the noisy-neighbor workload: the abuser offers 10
#: frame-groups per round, the victims one each.
ABUSE = 10
GROUP = 2  # docs per frame
K = 8


def _noisy_run(fair: bool, abuse: bool, rounds: int = 4):
    """Serve the (optionally abusive) three-tenant workload and return
    per-tenant ack-delay samples measured in serving ticks. The abuser
    submits FIRST each round — the adversarial arrival order a FIFO
    composer is worst at."""
    tenants = {"abuser": ABUSE if abuse else 1, "vic1": 1, "vic2": 1}
    docs = {t: [f"{t}-d{i}" for i in range(n * GROUP)]
            for t, n in tenants.items()}
    all_docs = [d for ds in docs.values() for d in ds]
    kw = {}
    if fair:
        kw = dict(tenant_weights={t: 1.0 for t in tenants},
                  tick_slot_budget=3 * GROUP)
    else:
        # Fairness OFF, same tick capacity: FIFO composition under the
        # identical slot budget — the pre-QoS behavior at this shape.
        kw = dict(tick_slot_budget=3 * GROUP)
    service, storm = _stack(len(all_docs), **kw)
    clients = {d: service.connect(d, lambda m: None).client_id
               for d in all_docs}
    service.pump()
    delays: dict[str, list[int]] = {t: [] for t in tenants}
    idx = {d: i for i, d in enumerate(all_docs)}
    for r in range(rounds):
        base = storm.stats["ticks"]
        for t, n in tenants.items():
            for g in range(n):
                chunk = docs[t][g * GROUP:(g + 1) * GROUP]
                entries = [[d, clients[d], 1 + r * K, 1, K]
                           for d in chunk]
                payload = b"".join(_words(3, r, idx[d]).tobytes()
                                   for d in chunk)

                def sink(p, t=t, base=base):
                    assert not p.get("error"), p
                    delays[t].append(storm.stats["ticks"] - base)

                storm.submit_frame(sink, {"rid": (r, t, g),
                                          "docs": entries},
                                   memoryview(payload),
                                   tenant_id=t if fair else "default")
        storm.flush()
    return delays


def _p99(samples):
    xs = sorted(samples)
    return xs[min(len(xs) - 1, max(0, math.ceil(0.99 * len(xs)) - 1))]


class TestNoisyNeighbor:
    def test_victim_p99_pinned_under_10x_abuse(self):
        """THE acceptance bar: one tenant at 10x, the victims' ack p99
        (in serving ticks) shifts <= 1.25x vs the no-abuser baseline,
        and the abuser is confined to its weighted share (its own
        backlog drains over many ticks instead of front-running)."""
        base = _noisy_run(fair=True, abuse=False)
        abused = _noisy_run(fair=True, abuse=True)
        for vic in ("vic1", "vic2"):
            b = max(1, _p99(base[vic]))
            a = max(1, _p99(abused[vic]))
            assert a <= 1.25 * b, (
                f"{vic} p99 moved {b} -> {a} ticks under abuse")
        # The abuser pays for its own excess: confined to ~1/3 of each
        # tick's slots plus leftovers, its 10x backlog spreads across
        # several ticks instead of front-running the victims.
        assert _p99(abused["abuser"]) >= 3 * _p99(abused["vic1"])

    def test_fairness_off_inverts_the_bar(self):
        """The same abusive workload through a tenant-blind FIFO
        composer (identical slot budget): the victims' p99 blows past
        the 1.25x bound — the mechanism, not luck, holds the pin."""
        base = _noisy_run(fair=True, abuse=False)
        blind = _noisy_run(fair=False, abuse=True)
        b = max(1, _p99(base["vic1"]))
        assert _p99(blind["vic1"]) > 1.25 * b

    def test_per_tenant_slo_surfaces_in_metrics(self):
        """get_metrics-visible SLO slices: ack histograms, sequenced
        counters and tick-doc shares appear per tenant, and the
        windowed attribution sums to 1 over tenants."""
        service, storm = _stack(
            2 * GROUP, tenant_weights={"a": 1.0, "b": 1.0},
            tick_slot_budget=GROUP)
        docs = {"a": [f"a{i}" for i in range(GROUP)],
                "b": [f"b{i}" for i in range(GROUP)]}
        clients = {d: service.connect(d, lambda m: None).client_id
                   for ds in docs.values() for d in ds}
        service.pump()
        sunk = []
        for t, ds in docs.items():
            storm.submit_frame(
                sunk.append, {"rid": t,
                              "docs": [[d, clients[d], 1, 1, K]
                                       for d in ds]},
                memoryview(b"".join(_words(5, 0, i).tobytes()
                                    for i, _ in enumerate(ds))),
                tenant_id=t)
        storm.flush()
        snap = storm.merge_host.metrics.snapshot()
        for t in ("a", "b"):
            assert snap[f"storm.tenant.{t}.submitted_ops"] == GROUP * K
            assert snap[f"storm.tenant.{t}.tick_docs"] == GROUP
            assert snap[f"storm.tenant.{t}.ack_s.count"] >= 1
        att = storm.qos.attribution()
        shares = sum(v["share"] for t, v in att.items()
                     if not t.startswith("_"))
        assert abs(shares - 1.0) < 1e-6


class TestWeightedShed:
    def test_over_share_tenant_sheds_first_with_scaled_hint(self):
        """Queue pressure sheds the over-deficit tenant first: past its
        weighted pending share (and the global borrow threshold) the
        abuser busy-nacks with a retry hint scaled by ITS backlog,
        while the victim keeps buffering inside its share."""
        service, storm = _stack(
            16, max_pending_docs=8, busy_retry_s=0.05,
            tenant_weights={"a": 1.0, "b": 1.0}, tick_slot_budget=4)
        docs = [f"a{i}" for i in range(12)] + [f"b{i}" for i in range(4)]
        clients = {d: service.connect(d, lambda m: None).client_id
                   for d in docs}
        service.pump()
        nacks = {"a": [], "b": []}

        def submit(t, doc, rid):
            def sink(p, t=t):
                if p.get("error"):
                    nacks[t].append(p)
            storm.submit_frame(
                sink, {"rid": rid, "docs": [[doc, clients[doc], 1, 1, K]]},
                memoryview(_words(7, 0, rid).tobytes()), tenant_id=t)

        submit("b", "b0", 0)           # both tenants in play
        for i in range(12):            # the abuser floods
            submit("a", f"a{i}", 1 + i)
        # Share = 8/2 = 4: the abuser buffers to its cap, then sheds
        # (global queue past the borrow threshold), with a hint scaled
        # by its own backlog (> the base retry).
        assert len(nacks["a"]) >= 1
        assert all(n["error"] == "busy" for n in nacks["a"])
        assert nacks["a"][0]["retry_after_s"] > 0.05
        a_pending = storm.qos.pending_docs["a"]
        assert a_pending <= 4 + 1  # confined to ~its share
        # The victim still buffers inside its share despite the flood.
        for i in range(1, 4):
            submit("b", f"b{i}", 100 + i)
        assert not nacks["b"]
        assert storm.merge_host.metrics.snapshot()[
            "storm.tenant.a.shed_frames"] == len(nacks["a"])
        storm.flush()  # everyone admitted still serves

    def test_single_tenant_keeps_legacy_global_bound(self):
        """No second tenant ever appears -> the global bound and base
        retry hint apply exactly as before (no weighted caps)."""
        service, storm = _stack(4, max_pending_docs=2,
                                busy_retry_s=0.05)
        docs = [f"d{i}" for i in range(4)]
        clients = {d: service.connect(d, lambda m: None).client_id
                   for d in docs}
        service.pump()
        nacks = []
        sink = lambda p: nacks.append(p) if p.get("error") else None
        for i, d in enumerate(docs):
            storm.submit_frame(
                sink, {"rid": i, "docs": [[d, clients[d], 1, 1, K]]},
                memoryview(_words(9, 0, i).tobytes()))
        assert len(nacks) == 2
        assert all(n["retry_after_s"] == 0.05 for n in nacks)


# -- replay / durability of scheduler state ------------------------------------


class TestSchedulerReplay:
    def _durable_stack(self, root, **kw):
        from fluidframework_tpu.server.durable_store import (
            GitSnapshotStore,
        )
        return _stack(8, spill_dir=str(root / "spill"),
                      durability="group",
                      snapshots=GitSnapshotStore(str(root / "git")),
                      tenant_weights={"a": 1.0, "b": 2.0},
                      tick_slot_budget=2, **kw)

    def _serve_rounds(self, service, storm, clients, r0, rounds):
        for r in range(r0, r0 + rounds):
            for t, d in (("a", "a0"), ("a", "a1"), ("b", "b0")):
                storm.submit_frame(
                    None, {"rid": (r, d),
                           "docs": [[d, clients[d], 1 + r * K, 1, K]]},
                    memoryview(_words(11, r, hash(d) % 7).tobytes()),
                    tenant_id=t)
            storm.flush()

    def test_deficits_survive_snapshot_and_wal_replay(self, tmp_path):
        """Kill-and-recover equivalence for the SCHEDULER: a fresh
        stack over the same dirs restores the deficit counters and
        rotation byte-identically (snapshot + per-tick WAL headers),
        and the served planes match the live run."""
        service, storm = self._durable_stack(tmp_path)
        docs = ["a0", "a1", "b0"]
        clients = {d: service.connect(d, lambda m: None).client_id
                   for d in docs}
        service.pump()
        storm.checkpoint()
        self._serve_rounds(service, storm, clients, 0, 2)
        storm.checkpoint()  # scheduler state rides the snapshot...
        self._serve_rounds(service, storm, clients, 2, 2)  # ...and WAL
        live_qos = storm.qos.export_state()
        live_map = {d: storm.merge_host.map_entries(
            d, storm.datastore, storm.channel) for d in docs}
        assert live_qos["deficit"]  # fairness state actually moved
        storm._group_wal.close()
        service2, storm2 = self._durable_stack(tmp_path)
        storm2.recover()
        assert storm2.qos.export_state() == live_qos
        assert {d: storm2.merge_host.map_entries(
            d, storm2.datastore, storm2.channel)
            for d in docs} == live_map
        storm2._group_wal.close()

    def test_single_tenant_wal_headers_stay_unstamped(self, tmp_path):
        """Compat: a single-tenant run journals NO "qos" header field —
        pre-QoS readers and goldens parse every tick unchanged."""
        from fluidframework_tpu.server.durable_store import (
            GitSnapshotStore,
        )
        service, storm = _stack(
            2, spill_dir=str(tmp_path / "spill"), durability="group",
            snapshots=GitSnapshotStore(str(tmp_path / "git")))
        clients = {d: service.connect(d, lambda m: None).client_id
                   for d in ("d0", "d1")}
        service.pump()
        for d in ("d0", "d1"):
            storm.submit_frame(
                None, {"rid": d, "docs": [[d, clients[d], 1, 1, K]]},
                memoryview(_words(13, 0, 0).tobytes()))
        storm.flush()
        header, _off = storm._parse_header(storm._read_blob(0))
        assert "qos" not in header
        storm._group_wal.close()


# -- fairness x residency interplay --------------------------------------------


class TestFairnessResidency:
    def test_hydrating_tenant_reclaims_share_immediately(self):
        """A tenant whose docs are cold (hydration-nacked) must not
        donate its tick share to the hot tenant forever: the moment its
        docs are resident, its next frame serves within one composed
        tick — and the eviction/hydration cycle leaves the deficit
        counters untouched."""
        from fluidframework_tpu.server.residency import ResidencyManager
        from fluidframework_tpu.server.durable_store import (
            GitSnapshotStore,
        )
        import tempfile
        root = tempfile.mkdtemp()
        service, storm = _stack(
            8, tenant_weights={"hot": 1.0, "cold": 1.0},
            tick_slot_budget=2,
            spill_dir=root + "/spill", durability="group",
            snapshots=GitSnapshotStore(root + "/git"))
        residency = ResidencyManager(storm, max_resident=4,
                                     idle_evict_s=1e9,
                                     hydration_rate_per_s=1e9)
        hot = [f"h{i}" for i in range(3)]
        cold = ["c0"]
        clients = {d: service.connect(d, lambda m: None).client_id
                   for d in hot + cold}
        service.pump()
        storm.checkpoint()
        # Warm-up round: both tenants compose once (fairness state
        # exists before the eviction under test).
        for t, d in (("hot", "h0"), ("cold", "c0")):
            storm.submit_frame(
                None, {"rid": ("w", d),
                       "docs": [[d, clients[d], 1, 1, K]]},
                memoryview(_words(17, 8, 0).tobytes()), tenant_id=t)
        storm.flush()
        qos_before = storm.qos.export_state()
        # Evict the cold tenant's doc to the cold tier: eviction alone
        # must move NO fairness state.
        residency.evict("c0")
        assert not residency.is_resident("c0")
        assert storm.qos.export_state() == qos_before
        # The hot tenant builds a deep backlog (several rounds' worth).
        for r in range(4):
            for i, d in enumerate(hot):
                storm.submit_frame(
                    None, {"rid": (r, d),
                           "docs": [[d, clients[d], 1 + r * K, 1, K]]},
                    memoryview(_words(17, r, i).tobytes()),
                    tenant_id="hot")
        # The cold tenant's frame hydrates at admission (unmetered
        # bucket) and must serve within the FIRST composed tick of the
        # flush — its share was not donated while it was cold.
        acked_at = []
        base = storm.stats["ticks"]
        storm.submit_frame(
            lambda p: acked_at.append(storm.stats["ticks"] - base),
            {"rid": "cold", "docs": [[
                "c0", clients["c0"], 1 + K, 1, K]]},
            memoryview(_words(17, 9, 9).tobytes()), tenant_id="cold")
        storm.flush()
        assert acked_at and acked_at[0] <= 2, acked_at
        # Eviction + hydration moved no fairness state on their own
        # (only composed ticks do).
        assert storm.qos.export_state()["rr"] \
            == qos_before.get("rr", storm.qos.export_state()["rr"])
        storm._group_wal.close()


# -- viewer-plane per-tenant join budgets --------------------------------------


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t


class TestViewerTenantBudget:
    def _plane(self, **kw):
        from fluidframework_tpu.server.broadcaster import ViewerPlane
        service = SimpleNamespace(metrics=None, fanout=None, viewers=None)
        clock = FakeClock()
        plane = ViewerPlane(service, join_rate_per_s=1000.0,
                            clock=clock, **kw)
        return plane, clock

    def test_tenant_budget_isolates_join_storms(self):
        plane, clock = self._plane(tenant_join_rate_per_s=1.0,
                                   tenant_join_burst=2.0)
        # Tenant A burns its burst...
        assert plane.admit_join("doc", tenant_id="A") is None
        assert plane.admit_join("doc", tenant_id="A") is None
        retry = plane.admit_join("doc", tenant_id="A")
        assert retry is not None and retry > 0
        # ...tenant B is untouched (per-tenant keys).
        assert plane.admit_join("doc", tenant_id="B") is None
        assert plane.metrics.snapshot()[
            "viewer.tenant.A.join_nacks"] == 1

    def test_plane_refusal_refunds_tenant_tier(self):
        from fluidframework_tpu.server.broadcaster import ViewerPlane
        service = SimpleNamespace(metrics=None, fanout=None, viewers=None)
        clock = FakeClock()
        plane = ViewerPlane(service, join_rate_per_s=1.0, join_burst=1.0,
                            tenant_join_rate_per_s=10.0,
                            tenant_join_burst=2.0, clock=clock)
        assert plane.admit_join("doc", tenant_id="A") is None
        # Plane bucket empty now: the keyless refusal must refund A's
        # tenant debit (nothing stayed reserved).
        assert plane.admit_join("doc", tenant_id="A") is not None
        b = plane.tenant_joins._buckets["tenant/A"]
        assert b[0] >= 1.0 - 1e-9  # the second debit was refunded

    def test_cross_tenant_claim_cannot_bypass_tenant_budget(self):
        """client_key is client-controlled: a reservation paid by
        tenant A must not be claimable by tenant B presenting the same
        key (review fix: claims are namespaced by tenant, so B's join
        still debits B's own exhausted budget and nacks)."""
        from fluidframework_tpu.server.broadcaster import ViewerPlane
        service = SimpleNamespace(metrics=None, fanout=None, viewers=None)
        clock = FakeClock()
        plane = ViewerPlane(service, join_rate_per_s=1.0, join_burst=1.0,
                            tenant_join_rate_per_s=0.001,
                            tenant_join_burst=4.0, clock=clock)
        # A's first join drains the PLANE burst; A's second reserves a
        # claimable plane slot (tenant tier paid once).
        assert plane.admit_join("doc", "K", tenant_id="A") is None
        assert plane.admit_join("doc", "K2", tenant_id="A") is not None
        # B exhausts its own tenant budget...
        for key in ("b1", "b2", "b3", "b4"):
            plane.admit_join("doc", key, tenant_id="B")
        clock.t += 100.0  # A's reservation is claimable now
        # ...and presenting A's key must NOT ride A's reservation: B
        # pays (and fails) its own tenant tier.
        assert plane.admit_join("doc", "K2", tenant_id="B") is not None
        # A itself claims its slot without a re-debit.
        assert plane.admit_join("doc", "K2", tenant_id="A") is None

    def test_default_plane_has_no_tenant_budget(self):
        plane, clock = self._plane()
        assert plane.tenant_joins is None
        for _ in range(5):
            assert plane.admit_join("doc", tenant_id="A") is None


# -- monitor line --------------------------------------------------------------


def test_render_tenants_line():
    from fluidframework_tpu.tools.monitor import render_tenants
    metrics = {
        "storm.tenant.abuser.submitted_ops": 800.0,
        "storm.tenant.abuser.tick_docs": 80.0,
        "storm.tenant.abuser.sequenced_ops": 700.0,
        "storm.tenant.abuser.shed_ops": 100.0,
        "storm.tenant.abuser.pending_docs": 12.0,
        "storm.tenant.abuser.ack_s.p50": 0.2,
        "storm.tenant.abuser.ack_s.p99": 0.9,
        "storm.tenant.vic.submitted_ops": 80.0,
        "storm.tenant.vic.tick_docs": 20.0,
        "storm.tenant.vic.sequenced_ops": 80.0,
        "storm.tenant.vic.shed_ops": 0.0,
        "storm.tenant.vic.pending_docs": 0.0,
        "storm.tenant.vic.ack_s.p50": 0.01,
        "storm.tenant.vic.ack_s.p99": 0.02,
    }
    out = render_tenants(metrics, prev=None, interval=1.0)
    assert "abuser" in out and "vic" in out
    assert "80.0%" in out   # the abuser's share of tick slots
    assert "20.0%" in out
    assert "900.000ms" in out  # abuser ack p99
    # Windowed: a restart (negative delta) falls back to cumulative.
    prev = dict(metrics, **{"storm.tenant.vic.tick_docs": 90.0})
    out2 = render_tenants(metrics, prev=prev, interval=1.0)
    assert "vic" in out2
    # Empty scrape -> empty line (the watch loop skips it).
    assert render_tenants({}, None, 1.0) == ""


# -- tenant-record weights (round-18 residue): riddler tiers + journaling ------


class TestTenantRecordWeights:
    def test_weights_derive_from_riddler_paid_tier(self):
        """Weights come from the tenant RECORD (paid-tier column), not
        static config: a premium tenant out-shares a free one by the
        tier ratio, resolved lazily through weight_source."""
        from fluidframework_tpu.server.riddler import (
            TIER_WEIGHTS,
            TenantManager,
        )
        tenants = TenantManager()
        tenants.create_tenant("prem", tier="premium")
        tenants.create_tenant("free", tier="free")
        s = TenantScheduler(weight_source=tenants.weight_for)
        assert s.weight("prem") == TIER_WEIGHTS["premium"]
        assert s.weight("free") == TIER_WEIGHTS["free"]
        assert s.weight("unknown") == 1.0  # default, never a crash
        # Derived weights are consulted LIVE, never cached: a tier
        # upgrade takes effect on the very next compose, and idle
        # tenants never bloat the journaled roster (pending_cap counts
        # configured tenants as active).
        assert s.export_state()["weights"] == {}
        tenants.set_tier("free", "premium")
        assert s.weight("free") == TIER_WEIGHTS["premium"]
        tenants.set_tier("free", "free")
        backlog = [F("prem", [f"p{i}"]) for i in range(40)] \
            + [F("free", [f"f{i}"]) for i in range(40)]
        served = {"prem": 0, "free": 0}
        for _ in range(4):  # 40 slots for 80 docs: genuine contention
            plan = s.compose(backlog, budget=10)
            s.commit(plan)
            for f in plan["selected"]:
                served[f.tenant] += len(f.docs)
            sel = set(id(f) for f in plan["selected"])
            backlog = [f for f in backlog if id(f) not in sel]
        ratio = served["prem"] / max(1, served["free"])
        assert ratio >= 4.0, served  # 16x by weight; slack for caps

    def test_set_weight_journals_and_import_overrides(self):
        """A runtime set_weight is scheduler STATE: it rides
        export_state and import_state OVERRIDES constructor config —
        recovery composes with the weights the crashed host used."""
        s = TenantScheduler(weights={"a": 1.0, "b": 1.0})
        assert s.is_trivial()  # config alone stays unstamped
        s.set_weight("a", 3.0)
        assert not s.is_trivial()  # runtime change must journal
        snap = s.export_state()
        fresh = TenantScheduler(weights={"a": 1.0, "b": 1.0})
        fresh.import_state(snap)
        assert fresh.weight("a") == 3.0  # override, not setdefault
        # The restored change must KEEP journaling — a second restart
        # must not silently revert to constructor config.
        assert not fresh.is_trivial()
        fresh2 = TenantScheduler(weights={"a": 1.0, "b": 1.0})
        fresh2.import_state(fresh.export_state())
        assert fresh2.weight("a") == 3.0

    def test_tier_changes_persist_and_legacy_store_loads(self):
        """set_tier is durable; a legacy store (bare secrets) still
        loads — old tenants default to the standard tier."""
        from fluidframework_tpu.server.bus import StateStore
        from fluidframework_tpu.server.riddler import TenantManager
        store = StateStore()
        tenants = TenantManager(store)
        tenants.create_tenant("t0", secret="s0", tier="free")
        tenants.set_tier("t0", "pro")
        reopened = TenantManager(store)
        assert reopened.get_tenant("t0").tier == "pro"
        assert reopened.weight_for("t0") == 2.0
        # Legacy format: {tenant: secret-string}.
        legacy = StateStore()
        legacy.put(TenantManager.STORE_KEY, {"old": "sekrit"})
        mgr = TenantManager(legacy)
        assert mgr.get_tenant("old").secret == "sekrit"
        assert mgr.get_tenant("old").tier == "standard"
        assert mgr.weight_for("old") == 1.0
        with pytest.raises(ValueError):
            mgr.create_tenant("bad", tier="galactic")

    def test_storm_controller_threads_weight_source(self, tmp_path):
        """End to end: StormController(tenant_weight_source=) resolves
        tier weights LIVE at compose time (no caching — set_tier takes
        effect immediately) while multi-tenant scheduler state still
        journals in the tick's WAL header."""
        from fluidframework_tpu.server.durable_store import (
            GitSnapshotStore,
        )
        from fluidframework_tpu.server.riddler import TenantManager
        tenants = TenantManager()
        tenants.create_tenant("paid", tier="premium")
        tenants.create_tenant("free", tier="free")
        service, storm = _stack(
            4, tenant_weight_source=tenants.weight_for,
            tick_slot_budget=2,
            spill_dir=str(tmp_path / "spill"), durability="group",
            snapshots=GitSnapshotStore(str(tmp_path / "git")))
        docs = {"paid": ["p0", "p1"], "free": ["f0", "f1"]}
        clients = {d: service.connect(d, lambda m: None).client_id
                   for t in docs for d in docs[t]}
        service.pump()
        for tenant, ds in docs.items():
            for i, d in enumerate(ds):
                storm.submit_frame(
                    None, {"rid": d,
                           "docs": [[d, clients[d], 1, 1, K]]},
                    memoryview(_words(23, 0, i).tobytes()),
                    tenant_id=tenant)
        storm.flush()
        assert storm.qos.weight("paid") == 4.0
        assert storm.qos.weight("free") == 0.25
        assert "paid" not in storm.qos.weights  # live, not cached
        tenants.set_tier("free", "premium")
        assert storm.qos.weight("free") == 4.0  # upgrade is immediate
        header, _off = storm._parse_header(storm._read_blob(0))
        assert "qos" in header  # multi-tenant state still journals
        storm._group_wal.close()
