"""Sequence-parallel merge-tree (segment axis sharded over the mesh):
bit-identical to the unsharded kernel, with state genuinely distributed
and the walk running on collectives — the long-document scale-out path."""

from __future__ import annotations

import random

import numpy as np
import pytest

from fluidframework_tpu.ops import mergetree_kernel as mtk
from fluidframework_tpu.ops import mergetree_sharded as mts


def _assert_equal(a: mtk.MergeState, b: mtk.MergeState, ctx) -> None:
    for field in mtk.MergeState._fields:
        fa = np.asarray(getattr(a, field))
        fb = np.asarray(getattr(b, field))
        assert np.array_equal(fa, fb), (ctx, field)


def _random_stream(rng: random.Random, n_ops: int) -> list[dict]:
    ops = []
    length = 0
    for seq in range(1, n_ops + 1):
        client = rng.randrange(5)
        ref_seq = rng.randrange(max(seq - 3, 0), seq)
        if length > 4 and rng.random() < 0.45:
            start = rng.randrange(length - 2)
            end = start + rng.randint(0, min(4, length - start))
            kind = rng.choice([mtk.MT_REMOVE, mtk.MT_ANNOTATE])
            op = dict(kind=kind, pos=start, end=end, seq=seq,
                      ref_seq=ref_seq, client=client)
            if kind == mtk.MT_ANNOTATE:
                op.update(prop_key=rng.randrange(2),
                          prop_val=rng.randrange(1, 5))
            else:
                length -= end - start
            ops.append(op)
        else:
            tlen = rng.randint(1, 4)
            ops.append(dict(kind=mtk.MT_INSERT, pos=rng.randint(0, length),
                            seq=seq, ref_seq=ref_seq, client=client,
                            pool_start=seq * 10, text_len=tlen))
            length += tlen
    return ops


@pytest.mark.parametrize("seed", range(3))
def test_sharded_matches_unsharded(cpu_mesh_devices, seed):
    mesh = mts.make_seg_mesh(cpu_mesh_devices)
    n = len(cpu_mesh_devices)
    rng = random.Random(40 + seed)
    n_docs = rng.choice([1, 3])
    streams = [_random_stream(rng, rng.randrange(10, 40))
               for _ in range(n_docs)]
    # Segment capacity split across the mesh: each shard holds S/n slots.
    s = 32 * n
    state_x = mtk.init_state(n_docs, num_slots=s, num_props=2)
    state_s = mts.shard_merge_state(state_x, mesh)
    k = 8
    longest = max(len(st) for st in streams)
    for start in range(0, longest, k):
        chunk = [st[start:start + k] for st in streams]
        batch = mtk.make_merge_op_batch(chunk, n_docs, k)
        state_x = mtk.apply_tick(state_x, batch)
        state_s = mts.apply_tick_sharded(state_s, batch, mesh)
    _assert_equal(state_x, state_s, seed)


def test_long_document_spans_shards(cpu_mesh_devices):
    """One document whose live segments exceed any single shard's slice:
    the walk must keep working when splits/placements land on different
    chips (the sequence-parallel case)."""
    mesh = mts.make_seg_mesh(cpu_mesh_devices)
    n = len(cpu_mesh_devices)
    per_shard = 16
    s = per_shard * n
    rng = random.Random(7)
    stream = _random_stream(rng, 3 * per_shard)  # > one shard's capacity
    state_x = mtk.init_state(1, num_slots=s, num_props=2)
    state_s = mts.shard_merge_state(state_x, mesh)
    k = 8
    for start in range(0, len(stream), k):
        batch = mtk.make_merge_op_batch([stream[start:start + k]], 1, k)
        state_x = mtk.apply_tick(state_x, batch)
        state_s = mts.apply_tick_sharded(state_s, batch, mesh)
    _assert_equal(state_x, state_s, "long-doc")
    # The document's segments genuinely occupy multiple shards.
    assert int(np.asarray(state_x.count[0])) > per_shard
    # And the sharded state is device-resident across the mesh.
    devices = {shard.device for shard in state_s.length.addressable_shards}
    assert len(devices) == n

    # Text materializes identically from the sharded state.
    pool = mtk.TextPool(1)
    pool.append(0, "x" * 4096)
    assert mtk.materialize(state_s, pool, 0) == \
        mtk.materialize(state_x, pool, 0)
