"""Block-structured merge table: differential pins against the flat
kernel, the scalar engine, and the Pallas twin.

The contract (ISSUE 2 / VERDICT r5 next-round #1): the block kernel ≡
the flat per-op kernel ≡ the scalar MergeEngine byte-identically on the
same sequenced streams — live client streams from the real stack plus
randomized concurrent-ref streams — with the per-block summaries exact
(incremental updates ≡ from-scratch rebuild) and overflow atomic
(first failed op index reported, state frozen at the pre-overflow
frontier, flat-kernel tail replay converging to the same table).
"""

import random

import jax.numpy as jnp
import numpy as np
import pytest

from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.ops import mergetree_blocks as mtb
from fluidframework_tpu.ops import mergetree_blocks_pallas as mtbp
from fluidframework_tpu.ops import mergetree_kernel as mtk
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.local_server import LocalCollabServer
from tests.test_mergetree import get_string, make_string_doc, random_edit
from tests.test_mergetree_kernel import encode_log


def gen_stream(rng, n_ops, max_ref_lag=4, annotate=True):
    """Sequenced stream with genuinely concurrent refs (ref lags seq)."""
    ops, length, pool = [], 0, 0
    for seq in range(1, n_ops + 1):
        client = rng.randrange(5)
        ref_seq = rng.randrange(max(seq - max_ref_lag, 0), seq)
        if length > 4 and rng.random() < 0.45:
            start = rng.randrange(length - 2)
            end = start + rng.randint(0, min(4, length - start))
            kind = rng.choice([mtk.MT_REMOVE, mtk.MT_ANNOTATE]) \
                if annotate else mtk.MT_REMOVE
            op = dict(kind=kind, pos=start, end=end, seq=seq,
                      ref_seq=ref_seq, client=client)
            if kind == mtk.MT_ANNOTATE:
                op.update(prop_key=rng.randrange(2),
                          prop_val=rng.randrange(1, 5))
            else:
                length -= end - start
            ops.append(op)
        else:
            tlen = rng.randint(1, 4)
            ops.append(dict(kind=mtk.MT_INSERT, pos=rng.randint(0, length),
                            seq=seq, ref_seq=ref_seq, client=client,
                            pool_start=pool, text_len=tlen))
            pool += tlen
            length += tlen
    return ops


def occupied_rows(flat: mtk.MergeState, doc: int) -> list[tuple]:
    """Every occupied slot's full plane tuple in document order —
    tombstones, overlap words and prop slots included. Gaps (block
    tails) are skipped, so flat and block tables compare directly."""
    valid = np.asarray(flat.valid[doc])
    cols = {f: np.asarray(getattr(flat, f)[doc])
            for f in ("length", "ins_seq", "ins_client", "rem_seq",
                      "rem_client", "pool_start")}
    over = np.asarray(flat.rem_overlap[doc])
    props = np.asarray(flat.prop_val[doc])
    return [tuple(int(cols[f][i]) for f in cols)
            + (tuple(over[i]), tuple(props[i]))
            for i in range(valid.shape[0]) if valid[i]]


def drive(streams, k, flat_state, block_state, rebalance_every=1):
    """Apply the same chunked tick sequence to both kernels; rebalance
    the block table between ticks the way the serving host does."""
    n_docs = len(streams)
    longest = max(len(s) for s in streams)
    for t, start in enumerate(range(0, longest, k)):
        chunk = [s[start:start + k] for s in streams]
        batch = mtk.make_merge_op_batch(chunk, n_docs, k)
        flat_state = mtk.apply_tick(flat_state, batch)
        block_state, ovf = mtb.apply_tick_blocks(block_state, batch)
        assert np.all(np.asarray(ovf) == int(mtb.OVF_NONE)), (t, ovf)
        if (t + 1) % rebalance_every == 0:
            block_state = mtb.rebalance(
                block_state, jnp.zeros((n_docs,), jnp.int32))
    return flat_state, block_state


@pytest.mark.parametrize("seed", range(2))
def test_blocks_match_replicas_on_live_streams(seed):
    """The existing fuzz streams: live SharedString replicas over the
    local server; the block kernel replays the sequenced log and must
    reproduce the converged text byte-for-byte (and agree with the flat
    kernel slot-for-slot)."""
    rng = random.Random(seed)
    n_docs = 3
    server = LocalCollabServer()
    docs = []
    for d in range(n_docs):
        c1 = make_string_doc(server, f"doc{d}")
        others = [Container.load(LocalDocumentService(server, f"doc{d}"))
                  for _ in range(2)]
        docs.append([c1] + others)

    for _round in range(5):
        for containers in docs:
            paused = [c for c in containers if rng.random() < 0.3]
            for c in paused:
                c.inbound.pause()
            for _ in range(rng.randrange(3, 8)):
                random_edit(rng, get_string(
                    containers[rng.randrange(len(containers))]))
            for c in paused:
                c.inbound.resume()

    pool = mtk.TextPool(n_docs)
    client_slots: dict = {}
    key_slots: dict = {}
    val_ids: dict = {}
    streams = [encode_log(server.get_deltas(f"doc{d}", 0), pool, d,
                          client_slots, key_slots, val_ids)
               for d in range(n_docs)]
    flat, block = drive(
        streams, k=16,
        flat_state=mtk.init_state(n_docs, num_slots=512),
        block_state=mtb.init_state(n_docs, num_blocks=16, block_slots=32))
    for d in range(n_docs):
        expected = get_string(docs[d][0]).get_text()
        got = mtb.materialize(block, pool, d).replace("\x00", "")
        assert got == expected, (seed, d)
        assert got == mtk.materialize(flat, pool, d).replace("\x00", "")


@pytest.mark.parametrize("seed", range(6))
def test_blocks_match_flat_slot_level(seed):
    """Random concurrent-ref streams: every occupied slot — live AND
    tombstoned, overlap bitmasks and prop planes included — matches the
    flat kernel in document order, across interleaved rebalances."""
    rng = random.Random(7100 + seed)
    n_docs = rng.choice([1, 4])
    streams = [gen_stream(rng, rng.randrange(16, 60))
               for _ in range(n_docs)]
    flat, block = drive(
        streams, k=8,
        flat_state=mtk.init_state(n_docs, num_slots=512, num_props=2),
        block_state=mtb.init_state(n_docs, num_blocks=8, block_slots=64,
                                   num_props=2))
    # Rebalance drops nothing at min_seq 0, so occupied slots (incl.
    # tombstones) must be identical slot-for-slot.
    view = mtb.flat_view(block)
    for d in range(n_docs):
        assert occupied_rows(view, d) == occupied_rows(flat, d), (seed, d)


@pytest.mark.parametrize("seed", range(4))
def test_summaries_never_drift(seed):
    """The per-op incremental summary updates are exact: after every
    tick the carried summaries equal a from-scratch rebuild (the device
    analog of the scalar engine's settled-block invariant)."""
    rng = random.Random(7200 + seed)
    stream = gen_stream(rng, 48, max_ref_lag=5)
    state = mtb.init_state(1, num_blocks=4, block_slots=128, num_props=2)
    for start in range(0, 48, 8):
        batch = mtk.make_merge_op_batch([stream[start:start + 8]], 1, 8)
        state, ovf = mtb.apply_tick_blocks(state, batch)
        assert int(np.asarray(ovf)[0]) == int(mtb.OVF_NONE)
        rebuilt = mtb.recompute_summaries(state)
        for f in ("blk_live_len", "blk_max_seq", "blk_tomb", "count"):
            assert np.array_equal(np.asarray(getattr(state, f)),
                                  np.asarray(getattr(rebuilt, f))), \
                (seed, start, f)


@pytest.mark.parametrize("seed", range(3))
def test_pallas_twin_bit_identical(seed):
    """The VMEM twin (interpret mode off-TPU) reproduces every plane,
    every summary and the overflow index bit-for-bit."""
    rng = random.Random(7300 + seed)
    n_docs = rng.choice([1, 3])
    streams = [gen_stream(rng, rng.randrange(10, 30))
               for _ in range(n_docs)]
    sx = mtb.init_state(n_docs, num_blocks=8, block_slots=16, num_props=2)
    sp = sx
    longest = max(len(s) for s in streams)
    for start in range(0, longest, 8):
        chunk = [s[start:start + 8] for s in streams]
        batch = mtk.make_merge_op_batch(chunk, n_docs, 8)
        sx, ox = mtb.apply_tick_blocks(sx, batch)
        sp, op_ = mtbp.apply_tick_blocks_pallas(
            sp, batch, interpret=mtbp.default_interpret())
        assert np.array_equal(np.asarray(ox), np.asarray(op_))
        for f in mtb.BlockMergeState._fields:
            assert np.array_equal(np.asarray(getattr(sx, f)),
                                  np.asarray(getattr(sp, f))), (seed, f)
        # Shared rebalance keeps both twins inside block capacity.
        sx = mtb.rebalance(sx, jnp.zeros((n_docs,), jnp.int32))
        sp = sx


@pytest.mark.parametrize("seed", range(2))
def test_rebalance_preserves_future_resolution(seed):
    """Rebalance (incl. tombstone collection under an advanced window)
    must not change how FUTURE concurrent ops resolve — the block
    zamboni twin of test_compact_coalesce_preserves_semantics."""
    rng = random.Random(7400 + seed)
    history = gen_stream(rng, 64, max_ref_lag=1, annotate=False)
    ms = max(op["seq"] for op in history)
    pool_top = sum(op.get("text_len", 0) for op in history)

    flat = mtk.apply_tick(mtk.init_state(1, 512),
                          mtk.make_merge_op_batch([history], 1, 64))
    block, ovf = mtb.apply_tick_blocks(
        mtb.init_state(1, num_blocks=4, block_slots=256),
        mtk.make_merge_op_batch([history], 1, 64))
    assert int(np.asarray(ovf)[0]) == int(mtb.OVF_NONE)

    flat = mtk.compact(flat, jnp.asarray([ms], np.int32))
    block = mtb.rebalance(block, jnp.asarray([ms], np.int32))

    future, flen, fseq, pool = [], 0, ms, pool_top
    flen = int(np.asarray(jnp.sum(mtb.flat_view(block).length
                                  * mtb.flat_view(block).valid)))
    for _ in range(24):
        fseq += 1
        if flen > 8 and rng.random() < 0.4:
            start = rng.randrange(flen - 4)
            end = start + rng.randint(1, 4)
            future.append(dict(kind=mtk.MT_REMOVE, pos=start, end=end,
                               seq=fseq, ref_seq=rng.randint(ms, fseq - 1),
                               client=rng.randrange(4)))
            flen -= end - start
        else:
            tlen = rng.randint(1, 3)
            future.append(dict(kind=mtk.MT_INSERT,
                               pos=rng.randint(0, flen), seq=fseq,
                               ref_seq=rng.randint(ms, fseq - 1),
                               client=rng.randrange(4),
                               pool_start=pool, text_len=tlen))
            pool += tlen
            flen += tlen
    batch = mtk.make_merge_op_batch([future], 1, 32)
    flat2 = mtk.apply_tick(flat, batch)
    block2, ovf = mtb.apply_tick_blocks(block, batch)
    assert int(np.asarray(ovf)[0]) == int(mtb.OVF_NONE)
    view = mtb.flat_view(block2)
    live = [(r[0], r[5]) for r in occupied_rows(view, 0)
            if r[3] == int(mtk.NONE_SEQ)]
    live_flat = [(r[0], r[5]) for r in occupied_rows(flat2, 0)
                 if r[3] == int(mtk.NONE_SEQ)]
    assert live == live_flat, seed


def gen_head_stream(rng, n_ops):
    """Adversarial head-concentrated stream: every structural op lands
    at the document head (the BENCH_r06 known-loss shape — the
    incremental-rebalance trigger fires at the maximum rate)."""
    ops, length, pool = [], 0, 0
    for seq in range(1, n_ops + 1):
        if length > 8 and rng.random() < 0.25:
            end = rng.randint(1, 4)
            ops.append(dict(kind=mtk.MT_REMOVE, pos=0, end=end, seq=seq,
                            ref_seq=seq - 1, client=rng.randrange(4)))
            length -= end
        else:
            tlen = rng.randint(1, 4)
            ops.append(dict(kind=mtk.MT_INSERT, pos=0, seq=seq,
                            ref_seq=seq - 1, client=rng.randrange(4),
                            pool_start=pool, text_len=tlen))
            pool += tlen
            length += tlen
    return ops


def gen_tomb_stream(rng, n_ops):
    """Tombstone-heavy: half the ops remove — blk_tomb pressure builds
    toward the deferred-zamboni threshold."""
    ops, length, pool = [], 0, 0
    for seq in range(1, n_ops + 1):
        if length > 6 and rng.random() < 0.5:
            start = rng.randrange(length - 4)
            end = start + rng.randint(1, 4)
            ops.append(dict(kind=mtk.MT_REMOVE, pos=start, end=end,
                            seq=seq, ref_seq=seq - 1,
                            client=rng.randrange(4)))
            length -= end - start
        else:
            tlen = rng.randint(1, 3)
            ops.append(dict(kind=mtk.MT_INSERT,
                            pos=rng.randint(0, length), seq=seq,
                            ref_seq=seq - 1, client=rng.randrange(4),
                            pool_start=pool, text_len=tlen))
            pool += tlen
            length += tlen
    return ops


def _decide(block, k):
    """Host replica of the maybe_rebalance decision ladder (the
    determinism pin: the device must agree with this pure function of
    the state)."""
    nb, bk = block.blk_count.shape[1], block.length.shape[2]
    cap = bk - (2 * k + 2)
    c = np.asarray(block.blk_count)
    danger = bool((c.max(axis=1) + 2 * k + 2 > bk).any())
    e = np.maximum(c - cap, 0)
    e[:, -1] = 0
    c1 = c - e + np.roll(e, 1, axis=-1)
    h = np.maximum(c1 - cap, 0)
    h[:, 0] = 0
    c2 = c1 - h + np.roll(h, -1, axis=-1)
    local_ok = bool((c2 <= cap).all())
    tomb_heavy = bool((np.asarray(block.blk_tomb).sum(axis=1)
                       * mtb.TOMB_PRESSURE_DEN >= nb * bk).any())
    if not danger:
        return 0
    return 1 if (local_ok and not tomb_heavy) else 2


@pytest.mark.parametrize("shape", ["head", "spread", "tomb"])
@pytest.mark.parametrize("seed", range(2))
def test_incremental_rebalance_bit_identical(shape, seed):
    """The round-11 differential fuzz: across head-concentrated, spread
    and tombstone-heavy streams, the incremental spill is a PURE
    re-layout — every occupied slot (tombstones, overlap words, props
    included) stays bit-identical to the flat kernel in document order —
    its summaries never drift from the from-scratch rebuild, the
    per-block headroom truth is restored whenever the table has
    capacity, and the full-rebuild branch is bit-identical to
    ``rebalance`` (≡ flat ``compact`` + ``from_flat``, the pinned
    round-6 contract)."""
    rng = random.Random(7500 + seed)
    gen = {"head": gen_head_stream, "spread": gen_stream,
           "tomb": gen_tomb_stream}[shape]
    stream = gen(rng, 120)
    k, nb, bk = 8, 8, 64
    cap = bk - (2 * k + 2)
    flat = mtk.init_state(1, 1024, num_props=2)
    block = mtb.init_state(1, num_blocks=nb, block_slots=bk, num_props=2)
    zero = jnp.zeros((1,), jnp.int32)
    branches = set()
    for start in range(0, 120, k):
        batch = mtk.make_merge_op_batch([stream[start:start + k]], 1, k)
        flat = mtk.apply_tick(flat, batch)
        block, ovf = mtb.apply_tick_blocks(block, batch)
        assert int(np.asarray(ovf)[0]) == int(mtb.OVF_NONE), (shape, start)
        branch = _decide(block, k)
        branches.add(branch)
        ref_full = mtb.rebalance(block, zero)
        block2, rs = mtb.maybe_rebalance_stats(block, zero, k)
        rs = np.asarray(rs)
        assert (rs[0] == 1) == (branch > 0), (shape, start, branch, rs)
        if branch == 2:
            # Full branch ≡ rebalance() ≡ compact+from_flat, bit-exact.
            for f in mtb.BlockMergeState._fields:
                assert np.array_equal(np.asarray(getattr(block2, f)),
                                      np.asarray(getattr(ref_full, f))), \
                    (shape, start, f)
        if branch == 0:
            for f in mtb.BlockMergeState._fields:
                assert np.array_equal(np.asarray(getattr(block2, f)),
                                      np.asarray(getattr(block, f))), \
                    (shape, start, f)
        # Replay determinism: re-deciding from the same state re-lays
        # out byte-identically (the durable-log replay contract).
        block3, rs3 = mtb.maybe_rebalance_stats(block, zero, k)
        assert np.array_equal(rs, np.asarray(rs3))
        for f in mtb.BlockMergeState._fields:
            assert np.array_equal(np.asarray(getattr(block2, f)),
                                  np.asarray(getattr(block3, f))), \
                (shape, start, f)
        block = block2
        # Summaries never drift through the incremental path.
        rebuilt = mtb.recompute_summaries(block)
        for f in ("blk_live_len", "blk_max_seq", "blk_tomb", "count"):
            assert np.array_equal(np.asarray(getattr(block, f)),
                                  np.asarray(getattr(rebuilt, f))), \
                (shape, start, f)
        # Capacity truth (ADVICE item 4): whenever the table CAN satisfy
        # per-block headroom, the maintenance pass restored it.
        counts = np.asarray(block.blk_count)
        feasible = np.asarray(block.count) <= nb * cap
        assert np.all((counts.max(axis=1) <= cap) | ~feasible), \
            (shape, start, counts)
        # min_seq 0 drops nothing on either path: occupied slots (incl.
        # tombstones) must match the flat kernel slot-for-slot.
        assert occupied_rows(mtb.flat_view(block), 0) == \
            occupied_rows(flat, 0), (shape, start)
    assert 1 in branches, (shape, "incremental branch never exercised")


def test_deferred_zamboni_fires_on_tomb_pressure():
    """Tombstone drops stay OFF the hot tick until blk_tomb pressure
    crosses the threshold — then the full branch fires at the window
    and actually drops (count shrinks), matching rebalance() bit-exactly
    (exercised with an advancing MSN, unlike the fuzz's zero window)."""
    # Alternating head-insert / head-remove waves. The LIGHT remove
    # waves (below the pressure threshold of nb*bk/TOMB_PRESSURE_DEN =
    # 64 tombstones) leave tombstones aboard when the next insert wave
    # arms the danger trigger — the spill must ride them through
    # untouched (deferred). The final HEAVY wave (70 > 64) crosses the
    # pressure threshold while no danger fires, so the next insert
    # wave's first fire takes the full branch and the zamboni drops.
    ops = []
    seq = 0

    def insert_wave(n):
        nonlocal seq
        for _ in range(n):
            seq += 1
            ops.append(dict(kind=mtk.MT_INSERT, pos=0, seq=seq,
                            ref_seq=seq - 1, client=0,
                            pool_start=seq, text_len=1))

    def remove_wave(n):
        nonlocal seq
        for _ in range(n):
            seq += 1
            ops.append(dict(kind=mtk.MT_REMOVE, pos=0, end=1, seq=seq,
                            ref_seq=seq - 1, client=0))

    insert_wave(80)
    remove_wave(40)
    insert_wave(80)
    remove_wave(50)
    insert_wave(60)
    remove_wave(70)
    insert_wave(40)
    block = mtb.init_state(1, num_blocks=4, block_slots=64)
    k = 10
    saw_pressure_drop = False
    saw_deferred = False
    for start in range(0, len(ops), k):
        batch = mtk.make_merge_op_batch([ops[start:start + k]], 1, k)
        block, ovf = mtb.apply_tick_blocks(block, batch)
        assert int(np.asarray(ovf)[0]) == int(mtb.OVF_NONE), start
        ms = jnp.asarray([start], jnp.int32)  # advancing collab window
        branch = _decide(block, k)
        tomb_heavy = (int(np.asarray(block.blk_tomb).sum())
                      * mtb.TOMB_PRESSURE_DEN >= 4 * 64)
        if branch == 1 and int(np.asarray(block.blk_tomb).sum()) > 0:
            saw_deferred = True  # drops stayed off this hot tick
        if branch == 2:
            ref = mtb.rebalance(block, ms)
            nxt, _rs = mtb.maybe_rebalance_stats(block, ms, k)
            for f in mtb.BlockMergeState._fields:
                assert np.array_equal(np.asarray(getattr(nxt, f)),
                                      np.asarray(getattr(ref, f))), f
            if tomb_heavy and (int(np.asarray(nxt.count)[0])
                               < int(np.asarray(block.count)[0])):
                saw_pressure_drop = True
            block = nxt
        else:
            before = int(np.asarray(block.count)[0])
            block, _rs = mtb.maybe_rebalance_stats(block, ms, k)
            # The incremental/no-op branches NEVER drop.
            assert int(np.asarray(block.count)[0]) == before, start
    assert saw_deferred, "tombstones never rode through a hot-tick spill"
    assert saw_pressure_drop, "pressure-triggered zamboni never dropped"


def test_choose_block_geometry_head_fraction():
    """head_fraction=0 is the historical geometry bit-for-bit; higher
    observed concentration grows Bk monotonically (lane multiple, total
    capacity still admits min_slots) so the hot block absorbs more
    ticks per spill."""
    for slots, k in ((512, 32), (2048, 32), (8192, 32), (8192, 128)):
        base = mtb.choose_block_geometry(slots, k)
        assert base == mtb.choose_block_geometry(slots, k, 0.0)
        prev_bk = 0
        for hf in (0.0, 0.3, 0.6, 1.0):
            nb, bk = mtb.choose_block_geometry(slots, k, hf)
            assert bk % 128 == 0 and bk >= prev_bk
            prev_bk = bk
            worst = 2 * k + 8
            # Usable capacity (below the per-block worst-case reserve)
            # admits min_slots at every head_fraction.
            assert nb * (bk - worst) >= slots, (slots, k, hf, nb, bk)
        nb1, bk1 = mtb.choose_block_geometry(slots, k, 1.0)
        if slots >= 2048:
            assert bk1 > base[1], (slots, k)


def test_overflow_is_atomic_and_replayable():
    """Force a block overflow (tiny Bk, one-position insert storm): the
    kernel reports the first failed op index, the table is frozen at the
    pre-overflow frontier, and replaying the tail through the FLAT
    kernel (the host's fallback) converges to the flat-only result."""
    n_ops = 24
    ops = [dict(kind=mtk.MT_INSERT, pos=0, seq=s, ref_seq=s - 1, client=0,
                pool_start=s * 4, text_len=2)
           for s in range(1, n_ops + 1)]
    batch = mtk.make_merge_op_batch([ops], 1, n_ops)
    block = mtb.init_state(1, num_blocks=4, block_slots=4)
    block, ovf = mtb.apply_tick_blocks(block, batch)
    idx = int(np.asarray(ovf)[0])
    assert 0 < idx < n_ops  # overflowed mid-tick
    assert int(np.asarray(block.count)[0]) == idx  # frontier exact

    # Host fallback: pack the frozen table into a flat row and replay.
    packed = mtb.to_flat(block, slots=128)
    replay = mtk.make_merge_op_batch([ops[idx:]], 1, n_ops - idx)
    replayed = mtk.apply_tick(packed, replay)

    flat_only = mtk.apply_tick(mtk.init_state(1, 128), batch)
    assert occupied_rows(replayed, 0) == occupied_rows(flat_only, 0)


def test_block_to_sharded_conversion():
    """Sequence-parallel compatibility: a document leaving the block
    path for a sharded pool converts via from_block_state, and the
    sharded kernel continues the stream producing the same document as
    the block kernel continuing in place."""
    import jax

    from fluidframework_tpu.ops import mergetree_sharded as mts

    rng = random.Random(77)
    history = gen_stream(rng, 32)
    block, ovf = mtb.apply_tick_blocks(
        mtb.init_state(1, num_blocks=4, block_slots=64),
        mtk.make_merge_op_batch([history], 1, 32))
    assert int(np.asarray(ovf)[0]) == int(mtb.OVF_NONE)

    future = [dict(kind=mtk.MT_INSERT, pos=0, seq=33 + i, ref_seq=32 + i,
                   client=0, pool_start=1000 + 2 * i, text_len=2)
              for i in range(8)]
    batch = mtk.make_merge_op_batch([future], 1, 8)

    flat = mts.from_block_state(block, slots=128)
    mesh = mts.make_seg_mesh(jax.devices()[:8])
    sharded = mts.apply_tick_sharded(
        mts.shard_merge_state(flat, mesh), batch, mesh)
    block2, ovf = mtb.apply_tick_blocks(block, batch)
    assert int(np.asarray(ovf)[0]) == int(mtb.OVF_NONE)
    assert occupied_rows(sharded, 0) == \
        occupied_rows(mtb.flat_view(block2), 0)


def test_converters_roundtrip():
    """flat_view / from_flat / host_block_row agree with each other."""
    rng = random.Random(42)
    stream = gen_stream(rng, 40)
    flat = mtk.apply_tick(mtk.init_state(1, 256, num_props=2),
                          mtk.make_merge_op_batch([stream], 1, 40))
    packed = mtk.compact(flat, jnp.asarray([-1], np.int32))
    block = mtb.from_flat(packed, num_blocks=8)
    rebuilt = mtb.recompute_summaries(block)
    for f in ("blk_live_len", "blk_max_seq", "blk_tomb", "count"):
        assert np.array_equal(np.asarray(getattr(block, f)),
                              np.asarray(getattr(rebuilt, f))), f
    assert occupied_rows(mtb.flat_view(block), 0) == \
        occupied_rows(packed, 0)

    arrays = {f: np.asarray(getattr(packed, f)[0])
              for f in mtk.MergeState._fields}
    host = mtb.host_block_row(arrays, num_blocks=8, block_slots=32)
    for f in ("blk_count", "blk_live_len", "blk_max_seq", "blk_tomb"):
        assert np.array_equal(host[f], np.asarray(getattr(block, f)[0])), f
    for f in ("length", "ins_seq", "rem_seq", "pool_start"):
        assert np.array_equal(host[f], np.asarray(getattr(block, f)[0])), f


def test_serve_tick_blocks_best_composes_maintenance():
    """The serving-path composition the Pallas module exports (best
    apply + the conditional maintenance ladder) is bit-identical to
    calling the two legs explicitly — the fused shape storm._mixed_tick
    uses, kept honest on every backend."""
    from fluidframework_tpu.ops import mergetree_blocks_pallas as mtbp

    rng = random.Random(99)
    stream = gen_head_stream(rng, 48)
    k = 8
    a = mtb.init_state(1, num_blocks=8, block_slots=64, num_props=2)
    b = mtb.init_state(1, num_blocks=8, block_slots=64, num_props=2)
    zero = jnp.zeros((1,), jnp.int32)
    for start in range(0, 48, k):
        batch = mtk.make_merge_op_batch([stream[start:start + k]], 1, k)
        a, ovf_a, rs_a = mtbp.serve_tick_blocks_best(a, batch, zero, k)
        b, ovf_b = mtbp.apply_tick_blocks_best(b, batch)
        b, rs_b = mtb.maybe_rebalance_stats(b, zero, k)
        assert np.array_equal(np.asarray(ovf_a), np.asarray(ovf_b))
        assert np.array_equal(np.asarray(rs_a), np.asarray(rs_b))
        for f in mtb.BlockMergeState._fields:
            assert np.array_equal(np.asarray(getattr(a, f)),
                                  np.asarray(getattr(b, f))), (start, f)
