"""Audience roster (container.ts:1700 region) + idle-client ejection
(deli/lambda.ts:171 checkIdleClients) behind both service assemblies."""

from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.kernel_host import KernelSequencerHost
from fluidframework_tpu.server.local_server import LocalCollabServer
from fluidframework_tpu.server.routerlicious import RouterliciousService
from fluidframework_tpu.server.sequencer import DocumentSequencer


def make_doc(server, doc_id="doc"):
    service = LocalDocumentService(server, doc_id)
    container = Container.create_detached(service)
    datastore = container.runtime.create_datastore("default")
    datastore.create_channel("root", SharedMap.channel_type)
    container.attach()
    return container


class TestAudience:
    def test_roster_includes_read_only_clients(self):
        server = LocalCollabServer()
        c1 = make_doc(server)
        c2 = Container.load(LocalDocumentService(server, "doc"))
        reader = Container.load(LocalDocumentService(server, "doc"),
                                mode="read")
        all_ids = {c1.client_id, c2.client_id, reader.client_id}
        assert None not in all_ids and len(all_ids) == 3
        for c in (c1, c2, reader):
            assert set(c.audience.get_members()) == all_ids, c
        # Read-only clients are in the audience but NOT the quorum.
        assert reader.client_id not in c1.protocol.quorum.get_members()
        assert c1.audience.get_member(reader.client_id)["mode"] == "read"

    def test_join_leave_events_fire(self):
        server = LocalCollabServer()
        c1 = make_doc(server)
        added, removed = [], []
        c1.audience.on_add_member.append(lambda cid, m: added.append(cid))
        c1.audience.on_remove_member.append(
            lambda cid, m: removed.append(cid))
        c2 = Container.load(LocalDocumentService(server, "doc"))
        assert added == [c2.client_id]
        c2_id = c2.client_id
        c2.close()
        assert removed == [c2_id]
        assert c2_id not in c1.audience.get_members()

    def test_client_cannot_spoof_audience(self):
        """A client echoing the __audience__ payload shape must not touch
        peers' rosters — only service-crafted signals (client_id None)
        qualify; the spoof falls through as an ordinary app signal."""
        server = LocalCollabServer()
        c1 = make_doc(server)
        c2 = Container.load(LocalDocumentService(server, "doc"))
        seen = []
        c1.on_signal.append(seen.append)
        c2.submit_signal({"type": "__audience__", "event": "leave",
                          "client_id": c1.client_id})
        assert c1.client_id in c1.audience.get_members()
        assert c2.client_id in c1.audience.get_members()
        assert any(s.get("client_id") == c2.client_id for s in seen)

    def test_audience_over_routerlicious(self):
        class Adapter(LocalDocumentService):
            pass

        service = RouterliciousService()
        svc1 = Adapter(service, "doc")
        c1 = Container.create_detached(svc1)
        ds = c1.runtime.create_datastore("default")
        ds.create_channel("root", SharedMap.channel_type)
        c1.attach()
        c2 = Container.load(Adapter(service, "doc"))
        assert set(c1.audience.get_members()) \
            == set(c2.audience.get_members()) \
            == {c1.client_id, c2.client_id}
        c2_id = c2.client_id
        c2.close()
        assert c2_id not in c1.audience.get_members()


class TestIdleEjection:
    def _service(self, **kwargs):
        return RouterliciousService(
            sequencer_factory=lambda: DocumentSequencer(client_timeout_ms=5),
            **kwargs)

    def test_stuck_client_no_longer_pins_msn(self):
        service = self._service()
        seen_msns = []
        live = service.connect("doc", lambda msgs: seen_msns.extend(
            m.minimum_sequence_number for m in msgs))
        stuck = service.connect("doc", lambda msgs: None)
        # The stuck client joins then never speaks again; the live client
        # keeps working, which advances the service clock past the
        # stuck client's timeout.
        from fluidframework_tpu.protocol.messages import (
            DocumentMessage,
            MessageType,
        )
        for i in range(1, 12):
            live.submit([DocumentMessage(
                client_sequence_number=i, reference_sequence_number=2,
                type=MessageType.OPERATION, contents={"i": i})])
        msn_before = max(seen_msns)
        ejected = service.eject_idle_clients()
        assert (("doc", stuck.client_id) in ejected), ejected
        # With the stuck client's leave sequenced, the MSN tracks the live
        # client again instead of the stuck join.
        live.submit([DocumentMessage(
            client_sequence_number=12, reference_sequence_number=14,
            type=MessageType.OPERATION, contents={"i": 12})])
        assert max(seen_msns) > msn_before

    def test_pump_cadence_triggers_ejection(self):
        service = self._service(idle_check_interval=1)
        live = service.connect("doc", lambda msgs: None)
        stuck = service.connect("doc", lambda msgs: None)
        stuck_id = stuck.client_id
        from fluidframework_tpu.protocol.messages import (
            DocumentMessage,
            MessageType,
        )
        for i in range(1, 12):
            live.submit([DocumentMessage(
                client_sequence_number=i, reference_sequence_number=2,
                type=MessageType.OPERATION, contents={"i": i})])
        # No explicit call: the pump cadence crafted the leave.
        assert stuck_id not in {
            c["client_id"]
            for c in service.store.get("deli/doc")["clients"]}

    def test_batched_host_ejection(self):
        host = KernelSequencerHost(num_slots=4)
        service = RouterliciousService(batched_deli_host=host,
                                       auto_pump=False)
        live = service.connect("doc", lambda msgs: None)
        stuck = service.connect("doc", lambda msgs: None)
        service.pump()
        from fluidframework_tpu.protocol.messages import (
            DocumentMessage,
            MessageType,
        )
        for i in range(1, 8):
            live.submit([DocumentMessage(
                client_sequence_number=i, reference_sequence_number=2,
                type=MessageType.OPERATION, contents={"i": i})])
            service.pump()
        ejected = service.eject_idle_clients(timeout_ms=4)
        assert ("doc", stuck.client_id) in ejected
        service.pump()
        cp = service.store.get("deli/doc")
        assert stuck.client_id not in {c["client_id"]
                                       for c in cp["clients"]}
