"""Round-10 observability plane (server/storm.py + utils/metrics.py +
utils/telemetry.py): the per-tick stage ledger, the sampled per-op trace
joins, the device-side kstats counters riding the tick readback, and the
tracing overhead guard.

Oracles: (1) every serving tick commits exactly one fixed-shape ledger
record whose stage splits are non-negative and whose per-stage
histograms surface in the shared registry (alfred's get_metrics view);
(2) a frame stamped with a trace id gets a joined span whose hop marks
are monotonic in pipeline order, and its ack carries the marks back;
(3) the device stats plane agrees with the host-side sequenced/dup
accounting; (4) tracing at the default sample rate does not visibly tax
tick throughput."""

import time

import numpy as np
import pytest

from fluidframework_tpu.protocol.codec import stamp_trace
from fluidframework_tpu.server.kernel_host import KernelSequencerHost
from fluidframework_tpu.server.merge_host import KernelMergeHost
from fluidframework_tpu.server.routerlicious import RouterliciousService
from fluidframework_tpu.server.storm import StormController
from fluidframework_tpu.utils.metrics import STORM_STAGES


def make_service(num_docs=8, **storm_kwargs):
    seq_host = KernelSequencerHost(num_slots=2, initial_capacity=num_docs)
    merge_host = KernelMergeHost(flush_threshold=10**9)
    service = RouterliciousService(merge_host=merge_host,
                                   batched_deli_host=seq_host,
                                   auto_pump=False)
    storm = StormController(service, seq_host, merge_host,
                            flush_threshold_docs=10**9, **storm_kwargs)
    return service, storm, merge_host


def join_docs(service, docs):
    clients = {d: service.connect(d, lambda m: None).client_id
               for d in docs}
    service.pump()
    return clients


def make_words(rng, k, num_slots=16):
    kinds = rng.choice([0, 0, 0, 1, 2], size=k).astype(np.uint32)
    slots = rng.integers(0, num_slots, k).astype(np.uint32)
    vals = rng.integers(0, 1 << 20, k).astype(np.uint32)
    return (kinds | (slots << 2) | (vals << 12)).astype(np.uint32)


def run_ticks(storm, clients, docs, k=16, ticks=3, tc_from=None,
              push=None, cseq0=1):
    rng = np.random.default_rng(0)
    cseq = {d: cseq0 for d in docs}
    for t in range(ticks):
        hdr = {"op": "storm", "rid": t,
               "docs": [[d, clients[d], cseq[d], 1, k] for d in docs]}
        if tc_from is not None:
            stamp_trace(hdr, tc_from + t)
        body = b"".join(make_words(rng, k).tobytes() for _ in docs)
        storm.submit_frame(push, hdr, memoryview(body))
        storm.flush()
        for d in docs:
            cseq[d] += k
    return cseq


class TestStageLedger:
    def test_one_fixed_shape_record_per_tick(self):
        service, storm, merge_host = make_service()
        docs = ["a", "b", "c"]
        clients = join_docs(service, docs)
        run_ticks(storm, clients, docs, k=16, ticks=4)
        recs = storm.ledger.records()
        assert len(recs) == storm.stats["ticks"] == 4
        for rec in recs:
            # Fixed shape: every stage key present on every record.
            assert all(s in rec for s in STORM_STAGES)
            assert all(rec[s] >= 0 for s in STORM_STAGES)
            assert rec["batch_docs"] == 3
            assert rec["batch_ops"] == 3 * 16
        # The attributable splits cover real work: scatter + dispatch +
        # readback are never all zero on a tick that ran the device.
        assert all(rec["scatter"] + rec["device_dispatch"]
                   + rec["readback"] > 0 for rec in recs)

    def test_stage_histograms_reach_shared_registry(self):
        service, storm, merge_host = make_service()
        clients = join_docs(service, ["a"])
        run_ticks(storm, clients, ["a"], ticks=2)
        snap = merge_host.metrics.snapshot()
        for stage in ("scatter", "device_dispatch", "readback", "ack_pack"):
            assert snap[f"storm.stage.{stage}.count"] >= 2
            assert snap[f"storm.stage.{stage}.p99"] >= 0
        # merge_host.metrics IS the service registry when assembled by
        # RouterliciousService — the alfred get_metrics surface.
        assert service.metrics is merge_host.metrics

    def test_attribution_shares_sum_to_one(self):
        service, storm, _mh = make_service()
        clients = join_docs(service, ["a", "b"])
        run_ticks(storm, clients, ["a", "b"], ticks=3)
        att = storm.ledger.attribution()
        shares = [v["share"] for s, v in att.items() if s != "_window"]
        assert abs(sum(shares) - 1.0) < 0.01
        assert att["_window"]["ticks"] == 3
        assert att["_window"]["mean_batch_docs"] == 2.0

    def test_group_wal_commit_wait_backfilled(self, tmp_path):
        service, storm, _mh = make_service(
            spill_dir=str(tmp_path), durability="group")
        clients = join_docs(service, ["a"])
        run_ticks(storm, clients, ["a"], ticks=2)
        # Forced flush drains acks behind the fsync watermark, so the
        # records' commit-wait has been amended by now.
        for rec in storm.ledger.records():
            assert rec["wal_commit_wait"] > 0
        storm._group_wal.close()

    def test_replay_ticks_do_not_pollute_the_ledger(self, tmp_path):
        from fluidframework_tpu.server.durable_store import GitSnapshotStore
        from fluidframework_tpu.server.historian import Historian
        snapshots = Historian(GitSnapshotStore(str(tmp_path / "git")))
        service, storm, _mh = make_service(
            spill_dir=str(tmp_path / "wal"), durability="group",
            snapshots=snapshots)
        clients = join_docs(service, ["a"])
        run_ticks(storm, clients, ["a"], ticks=1)
        storm.checkpoint()
        run_ticks(storm, clients, ["a"], ticks=2, cseq0=17)
        n_before = len(storm.ledger)
        storm._group_wal.close()

        # Fresh controller stack over the same spill dir: recover()
        # replays 2 WAL ticks through the serving path — none of them
        # may append ledger records (they are reconstruction).
        service2, storm2, _mh2 = make_service(
            spill_dir=str(tmp_path / "wal"), durability="group",
            snapshots=snapshots)
        storm2.recover()
        assert storm2.stats["ticks"] == 2  # replayed
        assert len(storm2.ledger) == 0
        storm2._group_wal.close()
        assert n_before == 3


class TestPerOpTracing:
    def test_span_joined_and_ack_carries_hops(self):
        service, storm, _mh = make_service()
        clients = join_docs(service, ["a", "b"])
        acked = []
        run_ticks(storm, clients, ["a", "b"], ticks=2, tc_from=100,
                  push=acked.append)
        assert len(acked) == 2
        for t, ack in enumerate(acked):
            assert ack["tc"] == 100 + t
            hops = ack["hops"]
            order = ["ingress", "admit", "dispatch", "sequenced", "ack_tx"]
            assert list(hops) == order
            ts = [hops[h] for h in order]
            assert ts == sorted(ts)  # pipeline order, monotonic ns
        spans = list(storm.tracer.spans)
        assert len(spans) == 2
        assert spans[0]["total_ms"] >= 0
        assert set(spans[0]["deltas_ms"]) == {
            "ingress_to_admit", "admit_to_dispatch",
            "dispatch_to_sequenced", "sequenced_to_ack_tx"}
        # Hop histograms surface in the registry for get_metrics.
        snap = _mh.metrics.snapshot()
        assert snap["storm.hop.admit_to_dispatch.count"] == 2

    def test_durable_hop_present_under_group_wal(self, tmp_path):
        service, storm, _mh = make_service(
            spill_dir=str(tmp_path), durability="group")
        clients = join_docs(service, ["a"])
        acked = []
        run_ticks(storm, clients, ["a"], ticks=1, tc_from=7,
                  push=acked.append)
        hops = acked[0]["hops"]
        assert "durable" in hops
        assert hops["sequenced"] <= hops["durable"] <= hops["ack_tx"]
        storm._group_wal.close()

    def test_same_trace_id_from_two_sessions_never_collides(self):
        """Clients pick trace ids independently (every StormStream
        counts from 1), so two sessions sampling the SAME small integer
        in one tick must produce two clean spans — the server scopes
        its tracer key per session, never on the raw client id."""
        service, storm, _mh = make_service()
        clients = join_docs(service, ["a", "b"])
        rng = np.random.default_rng(5)
        acks_a, acks_b = [], []
        for doc, sink in (("a", acks_a.append), ("b", acks_b.append)):
            hdr = stamp_trace(
                {"op": "storm",
                 "docs": [[doc, clients[doc], 1, 1, 8]]}, 1)  # same tc!
            storm.submit_frame(sink, hdr,
                               memoryview(make_words(rng, 8).tobytes()))
        storm.flush()  # ONE tick sequences both frames
        assert storm.stats["ticks"] == 1
        for acked in (acks_a, acks_b):
            assert len(acked) == 1
            assert acked[0]["tc"] == 1  # the client's raw id, unscoped
            hops = acked[0]["hops"]
            assert list(hops) == ["ingress", "admit", "dispatch",
                                  "sequenced", "ack_tx"]
            ts = list(hops.values())
            assert ts == sorted(ts)
        assert len(storm.tracer.spans) == 2

    def test_server_caps_client_controlled_sampling(self):
        """One connection stamping EVERY frame must not commandeer the
        tracer: past max_traces_per_tick the extra ids are ignored (the
        frames still serve and ack normally, just untraced)."""
        service, storm, _mh = make_service()
        docs = ["a", "b", "c"]
        clients = join_docs(service, docs)
        storm.max_traces_per_tick = 2
        rng = np.random.default_rng(8)
        acked = []
        for i, doc in enumerate(docs):
            hdr = stamp_trace(
                {"op": "storm",
                 "docs": [[doc, clients[doc], 1, 1, 8]]}, 100 + i)
            storm.submit_frame(acked.append, hdr,
                               memoryview(make_words(rng, 8).tobytes()))
        storm.flush()
        assert [a.get("tc") for a in acked] == [100, 101, None]
        assert len(storm.tracer.spans) == 2
        assert storm.stats["sequenced_ops"] == 3 * 8  # all served
        # The cap is per tick round: the next round traces again.
        hdr = stamp_trace(
            {"op": "storm", "docs": [["a", clients["a"], 9, 1, 8]]}, 200)
        storm.submit_frame(acked.append, hdr,
                           memoryview(make_words(rng, 8).tobytes()))
        storm.flush()
        assert acked[-1]["tc"] == 200

    def test_shed_traced_frames_do_not_consume_cap_slots(self):
        """Traced frames refused at admission must not eat the per-tick
        trace budget — tracing has to keep working DURING the overload
        it exists to diagnose."""
        service, storm, _mh = make_service()
        clients = join_docs(service, ["a", "b", "c"])
        storm.max_traces_per_tick = 1
        storm.max_pending_docs = 2
        rng = np.random.default_rng(13)
        acked = []

        def submit(docs, tc=None):
            hdr = {"op": "storm",
                   "docs": [[d, clients[d], 1, 1, 8] for d in docs]}
            if tc is not None:
                stamp_trace(hdr, tc)
            storm.submit_frame(
                acked.append, hdr,
                memoryview(b"".join(make_words(rng, 8).tobytes()
                                    for _ in docs)))

        submit(["a"])               # untraced, buffered (pending=1)
        submit(["b", "c"], tc=2)    # traced, SHED at the queue bound
        assert acked[-1]["error"] == "busy"
        submit(["b"], tc=3)         # traced, admitted — the shed frame
        storm.flush()               # must not have burned its cap slot
        traced = [a for a in acked if a.get("tc") is not None]
        assert [a["tc"] for a in traced] == [3]
        assert len(storm.tracer.spans) == 1

    def test_quarantine_shed_refunds_staged_ns_and_trace_slot(self):
        """A buffered frame shed at quarantine must refund the ledger ns
        and sampling-cap slot it staged — the next tick never served it."""
        service, storm, _mh = make_service()
        clients = join_docs(service, ["a", "b"])
        rng = np.random.default_rng(17)
        for doc, tc in (("a", 1), ("b", 2)):
            hdr = stamp_trace(
                {"op": "storm",
                 "docs": [[doc, clients[doc], 1, 1, 8]]}, tc)
            storm.submit_frame(lambda p: None, hdr,
                               memoryview(make_words(rng, 8).tobytes()))
        staged_both = dict(storm._staged_ns)
        assert storm._traced_pending == 2
        storm._quarantine_doc("a", "test", 0)
        assert storm._traced_pending == 1
        assert 0 <= storm._staged_ns["ingress_decode"] \
            < staged_both["ingress_decode"]
        assert 0 <= storm._staged_ns["admission"] \
            < staged_both["admission"]
        # The surviving frame still serves and traces.
        storm.flush()
        assert storm.stats["sequenced_ops"] == 8
        assert [s["trace_id"][0] for s in storm.tracer.spans] == [2]

    def test_unhashable_trace_id_is_ignored_not_nacked(self):
        """The "tc" field is client-opaque JSON — a list/dict id cannot
        key the tracer, but the frame itself is valid and must serve."""
        service, storm, _mh = make_service()
        clients = join_docs(service, ["a"])
        rng = np.random.default_rng(21)
        acked = []
        hdr = stamp_trace(
            {"op": "storm", "docs": [["a", clients["a"], 1, 1, 8]]},
            [3, "x"])
        storm.submit_frame(acked.append, hdr,
                           memoryview(make_words(rng, 8).tobytes()))
        storm.flush()
        assert storm.stats["sequenced_ops"] == 8
        assert len(acked) == 1 and "error" not in acked[0]
        assert "tc" not in acked[0] and len(storm.tracer.spans) == 0

    def test_admission_keys_on_session_identity_not_frame_header(self):
        """The docstring's contract, pinned: the per-client admission
        identity is the submit_frame ARGUMENT (service-assigned), never
        the client-controlled writer ids inside the frame's doc entries
        (a self-stamped id would mint a fresh token bucket per frame)."""
        service, storm, _mh = make_service()
        clients = join_docs(service, ["a"])
        seen = []

        class Admission:
            def add_pressure_probe(self, probe):
                pass

            def admit_write(self, tenant_id, client_id, weight):
                seen.append((tenant_id, client_id))
                return None

        storm.admission = Admission()
        rng = np.random.default_rng(22)
        hdr = {"op": "storm",
               "docs": [["a", "forged-client-id", 1, 1, 8]]}
        storm.submit_frame(None, hdr,
                           memoryview(make_words(rng, 8).tobytes()),
                           tenant_id="t1", client_id="session-client")
        assert seen == [("t1", "session-client")]

    def test_untraced_frames_cost_no_span(self):
        service, storm, _mh = make_service()
        clients = join_docs(service, ["a"])
        acked = []
        run_ticks(storm, clients, ["a"], ticks=2, push=acked.append)
        assert all("tc" not in a for a in acked)
        assert len(storm.tracer.spans) == 0

    def test_e2e_stormstream_over_alfred_socket(self, tmp_path):
        """The full client join: StormStream samples a frame, the alfred
        asyncio front door stamps ingress, and the client's span spans
        client_send → server hops → client_rx in one clock domain."""
        import asyncio
        import threading

        from fluidframework_tpu.drivers.network_driver import (
            NetworkDocumentService, StormStream)
        from fluidframework_tpu.server.alfred import AlfredServer

        service, storm, _mh = make_service()
        server = AlfredServer(service)
        loop = asyncio.new_event_loop()
        started = threading.Event()

        async def run():
            await server.start()
            started.set()
            await server.serve_forever()

        thread = threading.Thread(target=loop.run_until_complete,
                                  args=(run(),), daemon=True)
        thread.start()
        assert started.wait(10)

        svc = NetworkDocumentService("127.0.0.1", server.port, "doc-x")
        try:
            conn = svc.connect(lambda msgs: None)
            service.pump()
            stream = StormStream(svc, sample_every=1)
            rng = np.random.default_rng(1)
            words = make_words(rng, 8)
            tc = stream.submit([["doc-x", conn.client_id, 1, 1, 8]],
                               words.tobytes())
            assert tc is not None
            deadline = time.monotonic() + 30
            while not stream.acked and time.monotonic() < deadline:
                # The tick must run on the server's loop thread (acks
                # push into the session outbox) — the wire op does that.
                svc._request({"op": "storm_flush"})
                time.sleep(0.02)
            assert stream.acked == 1
            deadline = time.monotonic() + 10
            while not stream.tracer.spans and time.monotonic() < deadline:
                time.sleep(0.01)
            span = stream.tracer.spans[0]
            hops = span["hops"]
            assert list(hops)[0] == "client_send"
            assert list(hops)[-1] == "client_rx"
            assert "sequenced" in hops and "ack_tx" in hops
            ts = list(hops.values())
            assert ts == sorted(ts)
            assert span["total_ms"] > 0
        finally:
            svc.close()
            loop.call_soon_threadsafe(lambda: None)


class TestDeviceKstats:
    def test_device_counters_match_host_accounting(self):
        service, storm, merge_host = make_service()
        clients = join_docs(service, ["a", "b"])
        run_ticks(storm, clients, ["a", "b"], k=16, ticks=2)
        snap = merge_host.metrics.snapshot()
        assert snap["storm.device.sequenced_ops"] == \
            storm.stats["sequenced_ops"] == 64
        assert snap["storm.device.dup_ops"] == 0
        assert snap["storm.device.sentinel_docs"] == 0

    def test_dup_resend_counted_on_device(self):
        service, storm, merge_host = make_service()
        clients = join_docs(service, ["a"])
        rng = np.random.default_rng(3)
        words = make_words(rng, 8)
        hdr = {"op": "storm",
               "docs": [["a", clients["a"], 1, 1, 8]]}
        storm.submit_frame(None, dict(hdr), memoryview(words.tobytes()))
        storm.flush()
        # Verbatim resend: kernel cseq dedup drops all 8 as duplicates —
        # the device-side dup counter must see them.
        storm.submit_frame(None, dict(hdr), memoryview(words.tobytes()))
        storm.flush()
        snap = merge_host.metrics.snapshot()
        assert snap["storm.device.dup_ops"] == 8
        assert snap["storm.device.sequenced_ops"] == 8


@pytest.mark.parametrize("shape", [(16, 16, 24)])
def test_tracing_overhead_guard(shape):
    """Overhead guard (satellite): tracing every frame must not visibly
    tax tick throughput — the per-frame cost is a couple of dict writes
    and ns reads. The bench (BENCH_r10) measures the <2% bar at the
    DEFAULT 1-in-64 sample on the full socket path; this smoke bounds
    the in-process worst case (sample EVERY frame) loosely enough to
    stay deterministic under CI noise."""
    num_docs, k, ticks = shape

    def timed_run(tc_from):
        service, storm, _mh = make_service(num_docs=num_docs)
        docs = [f"d{i}" for i in range(num_docs)]
        clients = join_docs(service, docs)
        run_ticks(storm, clients, docs, k=k, ticks=2, tc_from=None)  # warm
        t0 = time.perf_counter()
        run_ticks(storm, clients, docs, k=k, ticks=ticks,
                  tc_from=tc_from, cseq0=2 * k + 1)
        return (time.perf_counter() - t0) / ticks

    base = min(timed_run(None) for _ in range(2))
    traced = min(timed_run(10_000) for _ in range(2))
    # Loose CI bound; the real <2% acceptance figure is measured by
    # bench.py --e2e-r10 on the long socket run.
    assert traced <= base * 1.5, (traced, base)
