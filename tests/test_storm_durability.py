"""Crash-consistent storm serving (server/storm.py + durable_store):
group-commit WAL with acks withheld until fsync, device-pool snapshot +
WAL-tail replay reconvergence, torn-tail recovery of the tick WAL, and
the malloc_trim serving-loop rate limit."""

import json

import numpy as np
import pytest

from fluidframework_tpu.server import storm as storm_mod
from fluidframework_tpu.server.durable_store import GitSnapshotStore
from fluidframework_tpu.server.kernel_host import KernelSequencerHost
from fluidframework_tpu.server.merge_host import KernelMergeHost
from fluidframework_tpu.server.routerlicious import RouterliciousService
from fluidframework_tpu.server.storm import StormController, _TrimGate


def build_stack(tmp_path, durability="group", snapshots=True,
                num_docs=2, flush_threshold_docs=10**9):
    seq_host = KernelSequencerHost(num_slots=2, initial_capacity=num_docs)
    merge_host = KernelMergeHost(flush_threshold=10**9)
    service = RouterliciousService(merge_host=merge_host,
                                   batched_deli_host=seq_host,
                                   auto_pump=False)
    storm = StormController(
        service, seq_host, merge_host,
        flush_threshold_docs=flush_threshold_docs,
        spill_dir=str(tmp_path / "spill"), durability=durability,
        snapshots=GitSnapshotStore(tmp_path / "git") if snapshots else None)
    return service, storm, seq_host, merge_host


def tick_words(seed, k):
    rng = np.random.default_rng(seed)
    kinds = rng.choice([0, 0, 0, 1, 2], size=k).astype(np.uint32)
    slots = rng.integers(0, 16, k).astype(np.uint32)
    vals = rng.integers(0, 1 << 20, k).astype(np.uint32)
    return (kinds | (slots << 2) | (vals << 12)).astype(np.uint32)


def drive_tick(storm, docs, clients, r, k=8, push=None):
    entries = [[d, clients[d], 1 + r * k, 1, k] for d in docs]
    payload = b"".join(tick_words((r, i), k).tobytes()
                       for i in range(len(docs)))
    storm.submit_frame(push, {"rid": r, "docs": entries},
                       memoryview(payload))
    storm.flush()


class TestGroupCommitAcks:
    def test_acks_withheld_until_durable_and_carry_watermark(self, tmp_path):
        service, storm, *_ = build_stack(tmp_path)
        docs = ["a", "b"]
        clients = {d: service.connect(d, lambda m: None).client_id
                   for d in docs}
        service.pump()
        acks = []
        for r in range(3):
            drive_tick(storm, docs, clients, r, push=acks.append)
        # flush(force) is a durability barrier: every ack out, stamped
        # with a watermark covering its own tick.
        assert [a["rid"] for a in acks] == [0, 1, 2]
        for tick, ack in enumerate(acks):
            assert ack["dw"] >= tick + 1
            assert all(a[0] == 8 for a in ack["acks"])
        assert storm.durable_watermark == 3
        assert storm._unacked == []

    def test_sync_mode_acks_inline(self, tmp_path):
        service, storm, *_ = build_stack(tmp_path, durability="sync")
        clients = {"a": service.connect("a", lambda m: None).client_id}
        service.pump()
        acks = []
        drive_tick(storm, ["a"], clients, 0, push=acks.append)
        assert acks and acks[0]["dw"] == 1
        assert storm.durable_watermark == 1


class TestSnapshotRestore:
    def test_recover_restores_checkpoint_and_replays_wal_tail(self,
                                                              tmp_path):
        """Checkpoint after tick 1, keep serving through tick 3, then a
        FRESH stack over the same dirs recovers: snapshot restore + a
        2-tick WAL replay must reproduce every plane byte-identically."""
        service, storm, seq_host, merge_host = build_stack(tmp_path)
        docs = ["a", "b"]
        clients = {d: service.connect(d, lambda m: None).client_id
                   for d in docs}
        service.pump()
        for r in range(2):
            drive_tick(storm, docs, clients, r)
        storm.checkpoint()
        for r in range(2, 4):
            drive_tick(storm, docs, clients, r)
        storm.flush()

        def planes(storm, seq_host, merge_host):
            import dataclasses
            out = {}
            for d in docs:
                cp = dataclasses.asdict(seq_host.checkpoint(d))
                out[d] = {
                    "map": merge_host.map_entries(d, "default", "root"),
                    "cp": cp,
                    "recs": storm.records_overlapping(d, 0),
                }
            return json.dumps(out, sort_keys=True)

        expected = planes(storm, seq_host, merge_host)
        expected_ticks = storm._tick_counter

        service2, storm2, seq2, merge2 = build_stack(tmp_path)
        info = storm2.recover()
        assert info["restored_from"] is not None
        assert info["replayed_ticks"] == 2  # ticks past the checkpoint
        assert storm2._tick_counter == expected_ticks
        assert planes(storm2, seq2, merge2) == expected

        # The recovered stack still SERVES: a verbatim resend of tick 3
        # dedups (0 sequenced), then a fresh tick sequences normally.
        acks = []
        drive_tick(storm2, docs, clients, 3, push=acks.append)
        assert all(a[0] == 0 for a in acks[0]["acks"])
        acks = []
        drive_tick(storm2, docs, clients, 4, push=acks.append)
        assert all(a[0] == 8 for a in acks[0]["acks"])

    def test_crash_before_head_flip_recovers_previous_snapshot(self,
                                                               tmp_path):
        """A checkpoint that uploaded but never published (the
        snapshot.pre_publish kill window) must leave recovery on the
        PREVIOUS head + a longer WAL replay — never a torn snapshot."""
        service, storm, seq_host, merge_host = build_stack(tmp_path)
        clients = {"a": service.connect("a", lambda m: None).client_id}
        service.pump()
        drive_tick(storm, ["a"], clients, 0)
        storm.checkpoint()
        head_before = storm.snapshots.head(StormController.SNAPSHOT_DOC)
        drive_tick(storm, ["a"], clients, 1)
        # Simulate the torn checkpoint: upload without flipping the head.
        import dataclasses
        snap = {"kind": "storm-checkpoint",
                "tick_watermark": storm._tick_counter,
                "sequencer": {
                    d: dataclasses.asdict(cp)
                    for d, cp in seq_host.checkpoint_all().items()},
                "merge_host": merge_host.export_state()}
        storm.snapshots.upload(StormController.SNAPSHOT_DOC, snap)
        assert storm.snapshots.head(
            StormController.SNAPSHOT_DOC) == head_before

        service2, storm2, seq2, merge2 = build_stack(tmp_path)
        info = storm2.recover()
        assert info["restored_from"] == head_before
        assert info["replayed_ticks"] == 1  # tick 1 came from the WAL
        assert (merge2.map_entries("a", "default", "root")
                == merge_host.map_entries("a", "default", "root"))

    def test_auto_checkpoint_interval(self, tmp_path):
        service, storm, *_ = build_stack(tmp_path)
        storm.snapshot_interval_ticks = 2
        clients = {"a": service.connect("a", lambda m: None).client_id}
        service.pump()
        assert storm.snapshots.head(StormController.SNAPSHOT_DOC) is None
        for r in range(2):
            drive_tick(storm, ["a"], clients, r)
        head = storm.snapshots.head(StormController.SNAPSHOT_DOC)
        assert head is not None  # flipped by the flush-path cadence
        drive_tick(storm, ["a"], clients, 2)
        assert storm.snapshots.head(
            StormController.SNAPSHOT_DOC) == head  # interval not reached


class TestTornTickWal:
    def test_torn_tail_every_offset_recovers_last_complete_tick(
            self, tmp_path):
        """Truncate the tick WAL at EVERY byte offset inside the final
        frame: the CRC framing must recover exactly the first two ticks
        (never a torn third, never fewer)."""
        service, storm, *_ = build_stack(tmp_path, durability="sync",
                                         snapshots=False)
        clients = {"a": service.connect("a", lambda m: None).client_id}
        service.pump()
        for r in range(3):
            drive_tick(storm, ["a"], clients, r, k=4)
        path = tmp_path / "spill" / "storm_tick_words.log"
        full = path.read_bytes()
        from fluidframework_tpu.native import OpLog
        import struct
        lens = []
        pos = 0
        while pos < len(full):
            (n,) = struct.unpack_from("<I", full, pos)
            lens.append(pos)
            pos += 8 + n
        assert len(lens) == 3 and pos == len(full)
        last_start = lens[-1]
        probe = tmp_path / "probe.log"
        for cut in range(last_start, len(full)):
            probe.write_bytes(full[:cut])
            log = OpLog(probe)
            assert len(log) == 2, cut
            log.close()
        # Full controller rebuild at a few representative cuts: the tick
        # index and catch-up reads recover to the last complete tick.
        for cut in (last_start, last_start + 9, len(full) - 1):
            spill2 = tmp_path / f"re-{cut}" / "spill"
            spill2.mkdir(parents=True)
            (spill2 / "storm_tick_words.log").write_bytes(full[:cut])
            _svc, storm2, *_ = build_stack(tmp_path / f"re-{cut}",
                                           durability="sync",
                                           snapshots=False)
            assert storm2._tick_counter == 2
            recs = storm2.records_overlapping("a", 0)
            assert [r["tick"] for r in recs] == [0, 1]


class TestMallocTrimRateLimit:
    def test_trim_gate_floor_and_cadence(self):
        now = [0.0]
        gate = _TrimGate(every=4, floor_s=10.0, clock=lambda: now[0])
        # Tick cadence satisfied but wall-clock floor not: no trim.
        assert not gate.due(ticks=8)
        now[0] = 11.0
        assert gate.due(ticks=8)
        # Immediately after a trim neither gate is open.
        assert not gate.due(ticks=9)
        now[0] = 30.0
        assert not gate.due(ticks=11)  # < every ticks since last trim
        assert gate.due(ticks=12)

    def test_flush_round_trims_at_most_once(self, tmp_path, monkeypatch):
        """However many ticks one flush harvests, malloc_trim runs at
        most once per flush call (the round-5 stall suspect)."""
        calls = []
        monkeypatch.setattr(storm_mod, "_malloc_trim",
                            lambda: calls.append(1))
        service, storm, *_ = build_stack(tmp_path, durability="none",
                                         snapshots=False)
        storm._trim_gate = _TrimGate(every=1, floor_s=0.0)
        docs = ["a", "b"]
        clients = {d: service.connect(d, lambda m: None).client_id
                   for d in docs}
        service.pump()
        k = 4
        for r in range(6):  # buffer six ticks' frames without flushing
            entries = [[d, clients[d], 1 + r * k, 1, k] for d in docs]
            payload = b"".join(tick_words((r, i), k).tobytes()
                               for i in range(len(docs)))
            storm.submit_frame(None, {"rid": r, "docs": entries},
                               memoryview(payload))
        storm.flush()  # one flush, six harvested ticks
        assert storm.stats["ticks"] == 6
        assert len(calls) == 1

    def test_wall_clock_floor_suppresses_repeat_trims(self, tmp_path,
                                                      monkeypatch):
        calls = []
        monkeypatch.setattr(storm_mod, "_malloc_trim",
                            lambda: calls.append(1))
        service, storm, *_ = build_stack(tmp_path, durability="none",
                                         snapshots=False)
        now = [0.0]
        storm._trim_gate = _TrimGate(every=1, floor_s=60.0,
                                     clock=lambda: now[0])
        clients = {"a": service.connect("a", lambda m: None).client_id}
        service.pump()
        for r in range(5):
            drive_tick(storm, ["a"], clients, r, k=4)
        assert calls == []  # floor never elapsed
        now[0] = 61.0
        drive_tick(storm, ["a"], clients, 5, k=4)
        assert len(calls) == 1


class TestReviewHardening:
    def test_explicit_durability_without_spill_dir_is_rejected(self):
        seq_host = KernelSequencerHost(num_slots=2, initial_capacity=2)
        merge_host = KernelMergeHost(flush_threshold=10**9)
        service = RouterliciousService(merge_host=merge_host,
                                       batched_deli_host=seq_host,
                                       auto_pump=False)
        with pytest.raises(ValueError, match="needs a spill_dir"):
            StormController(service, seq_host, merge_host,
                            durability="group")

    def test_recover_pads_wal_when_watermark_ahead(self, tmp_path):
        """A host crash under durability='sync' can lose WAL records the
        snapshot watermark already covers (the fsync raced the
        checkpoint). recover() must realign tick ids to WAL indices by
        padding filler ticks — and keep serving, not assert-loop."""
        service, storm, seq_host, merge_host = build_stack(
            tmp_path, durability="sync")
        clients = {"a": service.connect("a", lambda m: None).client_id}
        service.pump()
        for r in range(2):
            drive_tick(storm, ["a"], clients, r)
        storm.checkpoint()  # watermark = 2
        expected_map = merge_host.map_entries("a", "default", "root")
        # Emulate the lost unfsynced tail: drop the LAST WAL record.
        path = tmp_path / "spill" / "storm_tick_words.log"
        full = path.read_bytes()
        import struct
        (n0,) = struct.unpack_from("<I", full, 0)
        path.write_bytes(full[:8 + n0])  # only tick 0 survives

        service2, storm2, seq2, merge2 = build_stack(tmp_path,
                                                     durability="sync")
        assert storm2._tick_counter == 1  # the truncated WAL
        info = storm2.recover()
        assert info["restored_from"] is not None
        assert storm2._tick_counter == 2  # realigned to the watermark
        # Snapshot state intact despite the lost record...
        assert (merge2.map_entries("a", "default", "root")
                == expected_map)
        # ...and the next live tick appends cleanly (id 2 == WAL index 2).
        acks = []
        drive_tick(storm2, ["a"], clients, 2, push=acks.append)
        assert acks and all(a[0] == 8 for a in acks[0]["acks"])
        recs = storm2.records_overlapping("a", 0)
        assert [r["tick"] for r in recs] == [0, 2]  # filler tick 1 silent

    def test_recover_refuses_empty_state_over_acked_history(self,
                                                            tmp_path):
        """A WAL with durable ticks but no readable snapshot must fail
        recovery loudly — serving empty state over an acked history
        would silently diverge from what clients already saw."""
        service, storm, *_ = build_stack(tmp_path)
        clients = {"a": service.connect("a", lambda m: None).client_id}
        service.pump()
        drive_tick(storm, ["a"], clients, 0)  # durable tick, NO checkpoint
        service2, storm2, *_ = build_stack(tmp_path)
        with pytest.raises(RuntimeError, match="no snapshot head"):
            storm2.recover()

    def test_catchup_read_barriers_group_commit(self, tmp_path):
        """A tick record must never leave the process ahead of its
        fsync: reading an in-flight tick forces the WAL barrier first,
        so storage reads remain durability proof for clients."""
        service, storm, *_ = build_stack(tmp_path)
        clients = {"a": service.connect("a", lambda m: None).client_id}
        service.pump()
        # Harvest WITHOUT the forced-flush barrier: threshold flush only.
        k = 8
        entries = [["a", clients["a"], 1, 1, k]]
        storm.submit_frame(None, {"rid": 0, "docs": entries},
                           memoryview(tick_words(0, k).tobytes()))
        storm._flush_round()
        storm._harvest()  # tick enqueued on the WAL, fsync maybe pending
        words = storm.read_tick_words(0)
        # The read itself proved durability.
        assert storm.durable_watermark >= 1
        assert len(words) == k * 4
