"""Differential: the Pallas VMEM map fold vs the XLA dense-winner path.

Byte-identical MapState on random storm word streams, including clears,
dup windows (lo > 0) and partial windows (hi < K) — the fused storm tick
feeds exactly those from the closed-form sequencer."""

import numpy as np
import pytest

import jax.numpy as jnp

from fluidframework_tpu.ops import map_kernel as mk
from fluidframework_tpu.ops import map_pallas as mp


def _rand_words(rng, b, k, slots):
    kinds = rng.choice([mk.MAP_SET, mk.MAP_DELETE, mk.MAP_CLEAR],
                       p=[0.7, 0.2, 0.1], size=(b, k)).astype(np.uint32)
    slot = rng.integers(0, slots, (b, k)).astype(np.uint32)
    value = rng.integers(1, 1 << 20, (b, k)).astype(np.uint32)
    return (kinds | (slot << 2) | (value << 12)).astype(np.int32)


def _assert_state_equal(a: mk.MapState, b: mk.MapState):
    for f in mk.MapState._fields:
        assert np.array_equal(np.asarray(getattr(a, f)),
                              np.asarray(getattr(b, f))), f


@pytest.mark.parametrize("seed", [0, 1])
def test_pallas_fold_matches_words_path(seed):
    rng = np.random.default_rng(seed)
    b, k, s = 24, 48, 16
    state = mk.init_state(b, s)
    for t in range(4):
        words = jnp.asarray(_rand_words(rng, b, k, s))
        counts = jnp.asarray(rng.integers(0, k + 1, b).astype(np.int32))
        base = jnp.asarray((t * k + rng.integers(0, 3, b)).astype(np.int32))
        want = mk.apply_tick_words(state, words, counts, base)
        got = mp.apply_tick_words_pallas(state, words, counts, base,
                                         interpret=True)
        _assert_state_equal(got, want)
        state = want


@pytest.mark.parametrize("seed", [0, 1])
def test_pallas_fold_windowed_matches_reference(seed):
    """lo > 0 (dup prefix) and hi < K windows: equivalent to the XLA path
    applied to the windowed slice with seq = base+1+i-lo."""
    rng = np.random.default_rng(100 + seed)
    b, k, s = 16, 32, 8
    state = mk.init_state(b, s)
    for t in range(3):
        words_np = _rand_words(rng, b, k, s)
        lo = rng.integers(0, k // 2, b).astype(np.int32)
        hi = np.minimum(k, lo + rng.integers(0, k, b)).astype(np.int32)
        base = np.full(b, t * k, np.int32)
        # Reference: shift each doc's window to the front, use counts.
        shifted = np.zeros_like(words_np)
        counts = (hi - lo).astype(np.int32)
        for d in range(b):
            shifted[d, :counts[d]] = words_np[d, lo[d]:hi[d]]
        want = mk.apply_tick_words(state, jnp.asarray(shifted),
                                   jnp.asarray(counts), jnp.asarray(base))
        got = mp.fold_words(state, jnp.asarray(words_np),
                            jnp.asarray(lo), jnp.asarray(hi),
                            jnp.asarray(base), interpret=True)
        _assert_state_equal(got, want)
        state = want
