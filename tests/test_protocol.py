"""Protocol layer tests: quorum propose/accept/commit, membership, handler.

Oracle behavior from reference protocol-base/src/quorum.ts:262-333 and
protocol.ts:47 (see SURVEY.md §2.6).
"""

from fluidframework_tpu.protocol import (
    ClientDetail,
    MessageType,
    ProtocolOpHandler,
    Quorum,
    QuorumClient,
    SequencedDocumentMessage,
)


def seq_msg(seq, msn, mtype=MessageType.NOOP, client_id="c1", contents=None,
            data=None, ref_seq=0, client_seq=0):
    return SequencedDocumentMessage(
        client_id=client_id,
        sequence_number=seq,
        minimum_sequence_number=msn,
        client_sequence_number=client_seq,
        reference_sequence_number=ref_seq,
        type=mtype,
        contents=contents,
        data=data,
    )


class TestQuorum:
    def test_proposal_accepted_when_msn_advances(self):
        q = Quorum()
        approved = []
        q.on_approve_proposal.append(lambda s, k, v, a: approved.append((s, k, v, a)))
        q.add_proposal("code", "pkg@1", sequence_number=5, local=False)
        assert not q.has("code")
        # MSN below proposal seq: still pending.
        q.update_minimum_sequence_number(seq_msg(6, 4))
        assert not q.has("code")
        # MSN reaches proposal seq: accepted.
        immediate = q.update_minimum_sequence_number(seq_msg(7, 5))
        assert immediate is True
        assert q.get("code") == "pkg@1"
        assert approved == [(5, "code", "pkg@1", 7)]
        committed = q.get_committed("code")
        assert committed.approval_sequence_number == 7
        assert committed.commit_sequence_number == -1
        # MSN passes approval seq: committed.
        q.update_minimum_sequence_number(seq_msg(9, 8))
        assert q.get_committed("code").commit_sequence_number == 9

    def test_rejected_proposal_never_becomes_value(self):
        q = Quorum()
        rejected = []
        q.on_reject_proposal.append(lambda s, k, v, r: rejected.append((s, k, r)))
        q.add_proposal("code", "pkg@1", sequence_number=3, local=True)
        assert q.reject_proposal("c2", 3)
        q.update_minimum_sequence_number(seq_msg(5, 3))
        assert not q.has("code")
        assert rejected == [(3, "code", ["c2"])]
        # Rejection after settlement is a no-op.
        assert not q.reject_proposal("c3", 3)

    def test_msn_never_regresses_settlement(self):
        q = Quorum()
        q.add_proposal("k", 1, sequence_number=2, local=False)
        q.update_minimum_sequence_number(seq_msg(4, 3))
        assert q.get("k") == 1
        # Stale MSN (<= current) is ignored.
        assert q.update_minimum_sequence_number(seq_msg(5, 2)) is False

    def test_later_proposal_wins_key(self):
        q = Quorum()
        q.add_proposal("k", "old", sequence_number=2, local=False)
        q.add_proposal("k", "new", sequence_number=3, local=False)
        q.update_minimum_sequence_number(seq_msg(5, 4))
        assert q.get("k") == "new"

    def test_snapshot_preserves_pending_commit(self):
        # A value approved but not yet committed must still get its commit
        # seq after a snapshot/load, identically to a live replica.
        live = Quorum()
        live.add_proposal("k", "v", sequence_number=1, local=False)
        live.update_minimum_sequence_number(seq_msg(2, 1))  # approved at 2
        restored = Quorum.load(live.snapshot())
        for q in (live, restored):
            q.update_minimum_sequence_number(seq_msg(3, 2))  # commits at 3
        assert live.snapshot() == restored.snapshot()
        assert restored.get_committed("k").commit_sequence_number == 3

    def test_snapshot_roundtrip(self):
        q = Quorum()
        q.add_member(
            "c1", QuorumClient(detail=ClientDetail(client_id="c1"), sequence_number=1)
        )
        q.add_proposal("k", {"x": 1}, sequence_number=4, local=False)
        q.update_minimum_sequence_number(seq_msg(6, 5))
        q2 = Quorum.load(q.snapshot())
        assert q2.get("k") == {"x": 1}
        assert "c1" in q2.get_members()
        assert q2.snapshot() == q.snapshot()


class TestProtocolOpHandler:
    def test_join_leave_propose_flow(self):
        h = ProtocolOpHandler()
        h.process_message(
            seq_msg(1, 0, MessageType.CLIENT_JOIN, client_id=None,
                    data=ClientDetail(client_id="c1")),
            local=False,
        )
        assert "c1" in h.quorum.get_members()
        h.process_message(
            seq_msg(2, 1, MessageType.PROPOSE,
                    contents={"key": "code", "value": "app@1"}),
            local=False,
        )
        # A noop that advances MSN past the proposal accepts it.
        out = h.process_message(seq_msg(3, 2), local=False)
        assert out["immediate_noop"] is True
        assert h.quorum.get("code") == "app@1"
        h.process_message(
            seq_msg(4, 3, MessageType.CLIENT_LEAVE, client_id=None, data="c1"),
            local=False,
        )
        assert "c1" not in h.quorum.get_members()
        assert h.sequence_number == 4
        assert h.minimum_sequence_number == 3

    def test_gap_detection(self):
        h = ProtocolOpHandler()
        h.process_message(seq_msg(1, 0), local=False)
        try:
            h.process_message(seq_msg(3, 0), local=False)
        except AssertionError:
            pass
        else:  # pragma: no cover
            raise AssertionError("expected gap assertion")

    def test_snapshot_roundtrip(self):
        h = ProtocolOpHandler()
        h.process_message(
            seq_msg(1, 0, MessageType.CLIENT_JOIN, client_id=None,
                    data=ClientDetail(client_id="c1")),
            local=False,
        )
        h2 = ProtocolOpHandler.load(h.snapshot())
        assert h2.sequence_number == 1
        assert "c1" in h2.quorum.get_members()
