"""Kill-mid-tick chaos harness (tools/chaos.py): the serving process is
hard-killed (os._exit via utils/faults.py crashpoints) at the dangerous
points of the durability pipeline, restarted over the same directory,
and every recovered plane — sequenced history, map state, sequencer
checkpoints — must be byte-identical to an uninterrupted twin run, with
no durably-acked op ever lost.

Tier-1 runs a seeded smoke over one kill point per failure class
(volatile-state loss / torn group commit / torn checkpoint); the full
randomized kill-point × seed matrix is the `slow` soak.
"""

import json

import pytest

from fluidframework_tpu.tools import chaos
from fluidframework_tpu.utils import faults

_CFG = dict(seed=0, docs=2, k=8, ticks=5, cp_every=2)

#: (kill point, hit count chosen so the plan actually fires mid-run)
_SMOKE = [("storm.mid_tick", 3), ("wal.pre_fsync", 2),
          ("snapshot.pre_publish", 1)]


@pytest.fixture(scope="session")
def twin_digest(tmp_path_factory):
    """One uninterrupted twin run shared by every smoke scenario."""
    life = chaos._spawn_life(
        str(tmp_path_factory.mktemp("twin")), resume_from=None,
        kill_env=None, timeout=300, **_CFG)
    assert life["returncode"] == 0, life["stderr"]
    assert life["digest"] is not None
    return life["digest"]


@pytest.mark.parametrize("point,hits", _SMOKE,
                         ids=[p for p, _ in _SMOKE])
def test_chaos_smoke_recovers_byte_identical(point, hits, tmp_path,
                                             twin_digest):
    report = chaos.run_chaos(str(tmp_path), point, kill_hits=hits,
                             twin_digest=twin_digest, **_CFG)
    # The plan must actually have killed the process — a smoke that never
    # crashes proves nothing.
    assert report["killed"], report
    assert report["lives"] >= 2
    # run_chaos already asserted digest equality + acked-op retention;
    # double-check the acked rounds cover the whole workload by the end.
    assert report["acked_rounds"] == list(range(_CFG["ticks"]))


def test_twin_digest_covers_every_plane(twin_digest):
    """The comparison surface is meaningful: history, map and sequencer
    planes all present and non-trivial (guards against the diff silently
    comparing empty dicts)."""
    docs = twin_digest["docs"]
    assert len(docs) == _CFG["docs"]
    for planes in docs.values():
        ops = [h for h in planes["history"] if h[4] == 8]  # OPERATION
        assert len(ops) == _CFG["ticks"] * _CFG["k"]
        assert planes["map"]  # converged LWW entries
        assert planes["sequencer"]["clients"]
        assert planes["sequencer"]["sequence_number"] > 0
    # Digest must be canonically serializable (the twin diff is bytewise).
    json.dumps(twin_digest, sort_keys=True)


@pytest.mark.soak  # multi-minute: ~26 serving-process lives per seed
@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1])
def test_chaos_full_matrix(seed, tmp_path):
    """Every kill point × two hit positions, per seed — the full
    randomized matrix (soak tier)."""
    reports = chaos.run_matrix(str(tmp_path), points=chaos.KILL_POINTS,
                               seeds=(seed,), hit_positions=(1, 2),
                               docs=2, k=8, ticks=6, cp_every=2)
    killed = [r for r in reports if r["killed"]]
    # Most plans fire; every report (killed or not) already passed the
    # twin diff inside run_chaos/run_matrix.
    assert len(killed) >= len(reports) // 2, \
        [(r["kill_point"], r["kill_hits"], r["killed"]) for r in reports]


def test_kill_exit_code_is_distinct():
    assert faults.KILL_EXIT_CODE == 137


# -- residency kill classes (ISSUE 9): tier-1 smoke + slow matrix --------------

#: Pool capped at 2 of 3 docs: every round's frame against the
#: round-robin cold doc forces an LRU eviction + a hydration, so the
#: residency crashpoints genuinely fire mid-transition.
_RES_CFG = dict(seed=0, docs=3, k=8, ticks=5, cp_every=2, residency=2)

_RES_SMOKE = [("residency.mid_hydrate", 2), ("residency.mid_evict", 1)]


@pytest.fixture(scope="session")
def residency_twin_digest(tmp_path_factory):
    """Uninterrupted twin of the capped-pool workload (shared)."""
    life = chaos._spawn_life(
        str(tmp_path_factory.mktemp("res_twin")), resume_from=None,
        kill_env=None, timeout=300, **_RES_CFG)
    assert life["returncode"] == 0, life["stderr"]
    assert life["digest"] is not None
    return life["digest"]


@pytest.mark.parametrize("point,hits", _RES_SMOKE,
                         ids=[p for p, _ in _RES_SMOKE])
def test_residency_chaos_smoke_recovers_byte_identical(
        point, hits, tmp_path, residency_twin_digest):
    """Kill mid-hydrate / mid-evict: recovery must reconverge
    byte-identically with the uninterrupted twin and lose zero
    acked-durable ops — whether each doc died hot, cold, or halfway
    through the transition (the acceptance bar of ISSUE 9)."""
    report = chaos.run_chaos(str(tmp_path), point, kill_hits=hits,
                             twin_digest=residency_twin_digest,
                             **_RES_CFG)
    assert report["killed"], report
    assert report["lives"] >= 2
    assert report["acked_rounds"] == list(range(_RES_CFG["ticks"]))


@pytest.mark.soak
@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1])
def test_residency_chaos_full_matrix(seed, tmp_path):
    """Every residency kill point × two hit positions, per seed."""
    reports = chaos.run_matrix(
        str(tmp_path), points=chaos.RESIDENCY_KILL_POINTS, seeds=(seed,),
        hit_positions=(1, 2), docs=3, k=8, ticks=6, cp_every=2,
        residency=2)
    killed = [r for r in reports if r["killed"]]
    assert len(killed) >= len(reports) // 2, \
        [(r["kill_point"], r["kill_hits"], r["killed"]) for r in reports]


# -- overlap-window kill classes (ISSUE 11): tier-1 smoke + slow matrix --------

#: Deterministically-firing overlap points for the smoke (the
#: fsync-complete-before-readback point needs the writer thread to win a
#: race, so it rides the slow matrix with the >=half-killed tolerance).
_OVERLAP_SMOKE = [("storm.overlap_dispatch", 2),
                  ("storm.readback_pre_wal", 2)]


@pytest.mark.parametrize("point,hits", _OVERLAP_SMOKE,
                         ids=[p for p, _ in _OVERLAP_SMOKE])
def test_overlap_chaos_smoke_recovers_byte_identical(point, hits, tmp_path,
                                                     twin_digest):
    """Kill inside the dispatch/fsync overlap window of the PIPELINED
    serving tick (ISSUE 11): tick N+1 dispatched while tick N's group
    commit is in flight, or results read back before the durable record
    reached the writer. Recovery must replay the durable prefix
    byte-identically and lose zero acked-durable ops — and because the
    shared twin ran UNPIPELINED, digest equality also proves pipelined
    serving converges identically to barrier serving."""
    report = chaos.run_chaos(str(tmp_path), point, kill_hits=hits,
                             twin_digest=twin_digest, pipelined=True,
                             **_CFG)
    assert report["killed"], report
    assert report["lives"] >= 2
    assert report["acked_rounds"] == list(range(_CFG["ticks"]))


def test_pipelined_clean_run_matches_unpipelined_twin(tmp_path,
                                                      twin_digest):
    """No kill at all: a pipelined child run (acks lagging the durable
    watermark, overlapped fsync/dispatch) must produce the exact same
    digest planes as the unpipelined twin — the pipelining is a
    scheduling change, never a semantic one."""
    life = chaos._spawn_life(str(tmp_path), resume_from=None,
                             kill_env=None, timeout=300, pipelined=True,
                             **_CFG)
    assert life["returncode"] == 0, life["stderr"]
    assert json.dumps(life["digest"], sort_keys=True) \
        == json.dumps(twin_digest, sort_keys=True)
    assert sorted(life["acked"]) == list(range(_CFG["ticks"]))


@pytest.mark.soak
@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1])
def test_overlap_chaos_full_matrix(seed, tmp_path):
    """Every overlap-window kill point × two hit positions, per seed,
    through the pipelined child."""
    reports = chaos.run_matrix(
        str(tmp_path), points=chaos.OVERLAP_KILL_POINTS, seeds=(seed,),
        hit_positions=(1, 2), docs=2, k=8, ticks=6, cp_every=2,
        pipelined=True)
    killed = [r for r in reports if r["killed"]]
    assert len(killed) >= len(reports) // 2, \
        [(r["kill_point"], r["kill_hits"], r["killed"]) for r in reports]


# -- overload fault classes (ISSUE 5): tier-1 smoke + slow matrix --------------


class TestOverloadSmoke:
    """One fast scenario per new fault class. Each run_* raises on any
    violated invariant; the asserts here double-check the report shape."""

    def test_throttle_under_storm(self, tmp_path):
        report = chaos.run_overload(str(tmp_path), num_docs=8, k=16,
                                    rounds=6)
        assert report["shed_rate"] == 0.5  # exactly the 2x overflow shed
        assert report["acked_frames"] == report["shed_frames"] == 48

    def test_wal_fsync_failure(self, tmp_path):
        report = chaos.run_fsync_failure(str(tmp_path), num_docs=2, k=8,
                                         rounds=2)
        assert report["events"] == {"degraded_entered": True,
                                    "acks_withheld": True,
                                    "healed": True,
                                    "acks_after_heal": 2}
        assert report["breaker_opens"] >= 1

    def test_reconnect_storm_1k_clients(self):
        report = chaos.run_reconnect_storm(n_clients=1000)
        assert report["peak_attempts_per_s_after_wave"] \
            <= report["window_limit"]
        # Bounded recovery: within 1.5x the ideal drain of the herd.
        assert report["makespan_s"] <= 1.5 * report["ideal_drain_s"]

    def test_poison_doc_quarantine(self, tmp_path):
        report = chaos.run_poison_quarantine(str(tmp_path), num_docs=3,
                                             k=8, rounds=4)
        assert report["stats"] == {"quarantined_docs": 1,
                                   "readmitted_docs": 1}


@pytest.mark.soak
@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_overload_full_matrix(seed, tmp_path):
    """The slow tier: every overload fault class at larger shapes and
    multiple seeds (the kill-point matrix has its own soak above).
    The overload shape uses serving-sized ticks (128x128) so the latency
    ratio measures device work, not per-frame Python overhead — tiny
    ticks make the fixed shed cost look like a latency regression."""
    chaos.run_overload(str(tmp_path / "ov"), num_docs=128, k=128,
                       rounds=12, seed=seed)
    chaos.run_fsync_failure(str(tmp_path / "fs"), num_docs=8, k=32,
                            rounds=4, fail_times=5, seed=seed)
    chaos.run_poison_quarantine(str(tmp_path / "pq"), num_docs=8, k=32,
                                rounds=6, seed=seed)
    for n in (1000, 2000):
        chaos.run_reconnect_storm(n_clients=n, seed=seed)


_REBALANCE_CHILD = """
import sys
from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.durable_store import (
    DurableMessageBus, FileStateStore)
from fluidframework_tpu.server.merge_host import KernelMergeHost
from fluidframework_tpu.server.routerlicious import RouterliciousService
from fluidframework_tpu.utils import faults

# Small flush ticks + head-of-document inserts: once the table outgrows
# one 128-lane block (nb > 1), every tick lands in block 0 and the
# conditional rebalance fires. Bus AND store must be the durable pair
# (deli checkpoints reference bus offsets).
host = KernelMergeHost(flush_threshold=8)
service = RouterliciousService(bus=DurableMessageBus(sys.argv[1] + "/bus"),
                               store=FileStateStore(sys.argv[1] + "/state"),
                               merge_host=host)
c = Container.create_detached(LocalDocumentService(service, "doc"))
ds = c.runtime.create_datastore("default")
ds.create_channel("text", SharedString.channel_type)
c.attach()
# A second writer that never submits pins the MSN at its join, so the
# zamboni cannot coalesce the head-insert run and the table genuinely
# grows past one 128-lane block — the rebalance trigger shape.
idle = Container.load(LocalDocumentService(service, "doc"))
text = c.runtime.get_datastore("default").get_channel("text")
faults.arm()
for i in range(300):
    text.insert_text(0, f"edit{i} ")
print("SURVIVED", flush=True)  # the kill plan never fired
"""


# Same workload, but the kill plan arms pool.mid_retune: after the
# head-insert storm the host autotunes its block geometry (the explicit
# head_fraction pins the decision so the kill plan deterministically
# reaches a real re-block), and the process dies while the pool layout
# is moving wholesale.
_RETUNE_CHILD = _REBALANCE_CHILD.replace(
    'print("SURVIVED", flush=True)  # the kill plan never fired',
    'host.autotune_block_geometry(min_observations=1, '
    'fire_threshold=0.0, head_fraction=1.0)\n'
    'print("SURVIVED", flush=True)  # the kill plan never fired')


def _recover_host(tmp_path):
    """Merger-lambda replay of the scriptorium durable log into a FRESH
    host (the pool.mid_* recovery path)."""
    from fluidframework_tpu.drivers.local_driver import LocalDocumentService
    from fluidframework_tpu.runtime.container import Container
    from fluidframework_tpu.server.durable_store import (
        DurableMessageBus, FileStateStore)
    from fluidframework_tpu.server.merge_host import KernelMergeHost
    from fluidframework_tpu.server.routerlicious import RouterliciousService

    host = KernelMergeHost(flush_threshold=8)
    service = RouterliciousService(
        bus=DurableMessageBus(str(tmp_path / "bus")),
        store=FileStateStore(str(tmp_path / "state")),
        merge_host=host)
    service.connect("doc", lambda msgs: None)
    c = Container.load(LocalDocumentService(service, "doc"))
    text = c.runtime.get_datastore("default") \
        .get_channel("text").get_text()
    return host, c, text


def test_kill_mid_retune_replay_redecides_identically(tmp_path):
    """The pool.mid_retune kill class (round 11): the process dies while
    a geometry retune is moving the whole pool layout. Device state is
    volatile, so recovery = durable-log replay into a fresh host — and
    because the retune is a pure function of (state, block_slots), two
    independent replays that re-run the same retune must agree
    byte-for-byte on every pool plane (replay re-decides identically)."""
    import subprocess
    import sys as _sys

    import numpy as np

    env = dict(__import__("os").environ)
    env["FFTPU_CRASHPOINT"] = "pool.mid_retune:1"
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [_sys.executable, "-c", _RETUNE_CHILD, str(tmp_path)],
        capture_output=True, text=True, timeout=300, env=env)
    assert proc.returncode == faults.KILL_EXIT_CODE, (proc.returncode,
                                                      proc.stdout,
                                                      proc.stderr)

    host1, c1, text1 = _recover_host(tmp_path)
    host2, _c2, text2 = _recover_host(tmp_path)
    assert text1  # edits before the kill were durably sequenced
    assert text1 == text2
    assert host1.text("doc", "default", "text") == text1
    # Re-run the same retune on both replicas: the decision ladder and
    # the re-block are deterministic in the replayed state, so every
    # pool plane must stay byte-identical between the two recoveries.
    ret1 = host1.autotune_block_geometry(min_observations=1,
                                         fire_threshold=0.0,
                                         head_fraction=1.0)
    ret2 = host2.autotune_block_geometry(min_observations=1,
                                         fire_threshold=0.0,
                                         head_fraction=1.0)
    assert ret1 == ret2
    assert sorted(host1._merge_pools) == sorted(host2._merge_pools)
    for slots, p1 in host1._merge_pools.items():
        p2 = host2._merge_pools[slots]
        if hasattr(p1, "nb"):
            assert (p1.nb, p1.bk) == (p2.nb, p2.bk), slots
        for f in type(p1.state)._fields:
            assert np.array_equal(np.asarray(getattr(p1.state, f)),
                                  np.asarray(getattr(p2.state, f))), \
                (slots, f)
    # And the recovered, retuned host keeps sequencing.
    c1.runtime.get_datastore("default").get_channel("text") \
        .insert_text(0, "recovered ")
    assert host1.text("doc", "default", "text").startswith("recovered ")


def test_kill_mid_rebalance_recovers_from_durable_log(tmp_path):
    """The pool.mid_rebalance kill class (per-op merge path): the block
    pool's layout is mid-move when the process dies. The device state is
    volatile, so recovery = merger-lambda replay of the scriptorium
    durable log into a FRESH host — and the recovered device replica
    must match a scalar client replaying the same log."""
    import subprocess
    import sys as _sys

    env = dict(__import__("os").environ)
    env["FFTPU_CRASHPOINT"] = "pool.mid_rebalance:1"
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [_sys.executable, "-c", _REBALANCE_CHILD, str(tmp_path)],
        capture_output=True, text=True, timeout=300, env=env)
    assert proc.returncode == faults.KILL_EXIT_CODE, (proc.returncode,
                                                      proc.stdout,
                                                      proc.stderr)

    from fluidframework_tpu.drivers.local_driver import LocalDocumentService
    from fluidframework_tpu.runtime.container import Container
    from fluidframework_tpu.server.durable_store import (
        DurableMessageBus, FileStateStore)
    from fluidframework_tpu.server.merge_host import KernelMergeHost
    from fluidframework_tpu.server.routerlicious import RouterliciousService

    host = KernelMergeHost(flush_threshold=8)
    service = RouterliciousService(
        bus=DurableMessageBus(str(tmp_path / "bus")),
        store=FileStateStore(str(tmp_path / "state")),
        merge_host=host)
    # A reconnecting client instantiates the merger lambda, which replays
    # the durable op log into the fresh device host.
    service.connect("doc", lambda msgs: None)
    c = Container.load(LocalDocumentService(service, "doc"))
    client_text = c.runtime.get_datastore("default") \
        .get_channel("text").get_text()
    assert client_text  # edits before the kill were durably sequenced
    assert host.text("doc", "default", "text") == client_text
    # And the recovered service keeps sequencing.
    c.runtime.get_datastore("default").get_channel("text") \
        .insert_text(0, "recovered ")
    assert host.text("doc", "default", "text").startswith("recovered ")


# -- mega-doc kill classes (ISSUE 12): tier-1 smoke + slow matrix --------------

_MEGA_CFG = dict(docs=1, k=8, ticks=4, cp_every=2, megadoc=2, seed=0)

#: Deterministically-firing mega points for the smoke: the promotion
#: window (control journaled, lanes unseeded) and the combiner window
#: (doc seqs assigned, tick neither dispatched nor journaled). The
#: demotion point rides the slow matrix alongside.
_MEGA_SMOKE = [("megadoc.mid_promotion", 1), ("megadoc.mid_combine", 3)]


@pytest.fixture(scope="session")
def megadoc_twin_digest(tmp_path_factory):
    """Uninterrupted twin of the co-written mega-doc workload."""
    life = chaos._spawn_life(
        str(tmp_path_factory.mktemp("mega_twin")), resume_from=None,
        kill_env=None, timeout=300, **_MEGA_CFG)
    assert life["returncode"] == 0, life["stderr"]
    assert life["digest"] is not None
    return life["digest"]


@pytest.mark.parametrize("point,hits", _MEGA_SMOKE,
                         ids=[p for p, _ in _MEGA_SMOKE])
def test_megadoc_chaos_smoke_recovers_byte_identical(
        point, hits, tmp_path, megadoc_twin_digest):
    """Kill mid-promotion / mid-combiner-tick: recovery must replay the
    whole promoted lifecycle (control records re-promote at the same
    point, lane ticks re-combine in the same order) and reconverge
    byte-identically with zero acked-durable ops lost for EVERY writer
    (the ISSUE 12 acceptance bar)."""
    report = chaos.run_chaos(str(tmp_path), point, kill_hits=hits,
                             twin_digest=megadoc_twin_digest, **_MEGA_CFG)
    assert report["killed"], report
    assert report["lives"] >= 2
    assert report["acked_rounds"] == list(range(_MEGA_CFG["ticks"]))


def test_megadoc_demotion_chaos_recovers_byte_identical(
        tmp_path, megadoc_twin_digest):
    """Kill mid-demotion (control journaled, cross-lane fold not yet
    applied): recovery replays promote + every lane tick + the demote
    control and re-folds the identical doc row."""
    report = chaos.run_chaos(str(tmp_path), "megadoc.mid_demotion",
                             kill_hits=1, twin_digest=megadoc_twin_digest,
                             **_MEGA_CFG)
    assert report["killed"], report
    assert report["acked_rounds"] == list(range(_MEGA_CFG["ticks"]))


@pytest.mark.soak
@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1])
def test_megadoc_chaos_full_matrix(seed, tmp_path):
    """Every mega kill point × two hit positions, per seed."""
    reports = chaos.run_matrix(
        str(tmp_path), points=chaos.MEGADOC_KILL_POINTS, seeds=(seed,),
        hit_positions=(1, 2), docs=1, k=8, ticks=5, cp_every=2,
        megadoc=2)
    killed = [r for r in reports if r["killed"]]
    assert len(killed) >= len(reports) // 2, \
        [(r["kill_point"], r["kill_hits"], r["killed"]) for r in reports]


# -- migration kill classes (ISSUE 13): tier-1 smoke + slow matrix -------------

_CLUSTER_CFG = dict(seed=0, docs=2, k=8, ticks=5, cp_every=2,
                    cluster=True, migrate_at=2)

#: Tier-1 smoke: the post-evict window (doc cold in the shared store,
#: NO host serving it, directory intent durable) — the nastiest phase.
#: The other two phases ride the slow matrix.
_MIGRATION_SMOKE = [("placement.post_evict", 1)]


@pytest.fixture(scope="session")
def cluster_twin_digest(tmp_path_factory):
    """The NEVER-MIGRATED twin cluster: digest equality against it is
    simultaneously the migrated ≡ never-migrated differential bar and
    the kill-recovery bar."""
    life = chaos._spawn_life(
        str(tmp_path_factory.mktemp("cluster_twin")), resume_from=None,
        kill_env=None, timeout=300,
        **dict(_CLUSTER_CFG, migrate_at=-1))
    assert life["returncode"] == 0, life["stderr"]
    assert life["digest"] is not None
    return life["digest"]


@pytest.mark.parametrize("point,hits", _MIGRATION_SMOKE,
                         ids=[p for p, _ in _MIGRATION_SMOKE])
def test_migration_chaos_smoke_recovers_byte_identical(
        point, hits, tmp_path, cluster_twin_digest):
    """Kill mid-migration: recovery rolls the durable intent FORWARD
    (the doc ends owned + served by the target) and the cluster
    reconverges byte-identical to a twin that never migrated, with
    zero acked-durable ops lost (the ISSUE 13 acceptance bar)."""
    report = chaos.run_chaos(str(tmp_path), point, kill_hits=hits,
                             twin_digest=cluster_twin_digest,
                             **_CLUSTER_CFG)
    assert report["killed"], report
    assert report["lives"] >= 2
    assert report["acked_rounds"] == list(range(_CLUSTER_CFG["ticks"]))


def test_cluster_clean_run_matches_never_migrated_twin(
        tmp_path, cluster_twin_digest):
    """No kill at all: the scripted live migration under writes alone
    must leave the cluster byte-identical to the never-migrated twin
    (migration is transparent to every compared plane)."""
    life = chaos._spawn_life(str(tmp_path), resume_from=None,
                             kill_env=None, timeout=300, **_CLUSTER_CFG)
    assert life["returncode"] == 0, life["stderr"]
    assert json.dumps(life["digest"], sort_keys=True) == json.dumps(
        cluster_twin_digest, sort_keys=True)
    assert life["acked"] == list(range(_CLUSTER_CFG["ticks"]))


@pytest.mark.soak
@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1])
def test_migration_chaos_full_matrix(seed, tmp_path):
    """Slow soak: every migration phase × seed × hit position."""
    reports = chaos.run_matrix(
        str(tmp_path), points=chaos.MIGRATION_KILL_POINTS,
        seeds=(seed,), hit_positions=(1,),
        **{k: v for k, v in _CLUSTER_CFG.items() if k != "seed"})
    assert all(r["killed"] for r in reports)


# -- multi-tenant QoS kill classes (ISSUE 14): tier-1 smoke + slow matrix ------

#: Three tenants, the first at 10x (QOS_TENANTS/QOS_ABUSE_FACTOR in
#: chaos.py), composed through the deficit scheduler with a tick slot
#: budget — one workload round spans several budget-limited ticks, so
#: scheduler state genuinely moves between durable records.
_QOS_CFG = dict(seed=0, docs=2, k=8, ticks=4, cp_every=2)

_QOS_SMOKE = [("storm.qos_mid_compose", 2), ("wal.pre_fsync", 1)]


@pytest.fixture(scope="session")
def qos_twin_digest(tmp_path_factory):
    """Tenant-BLIND twin of the abusive-tenant workload (same frames,
    one tenant, no weights, no budget): equality with the fair arm
    proves fairness never changes converged replica state."""
    life = chaos._spawn_life(
        str(tmp_path_factory.mktemp("qos_twin")), resume_from=None,
        kill_env=None, timeout=300, qos="blind", **_QOS_CFG)
    assert life["returncode"] == 0, life["stderr"]
    assert life["digest"] is not None
    return life["digest"]


@pytest.mark.parametrize("point,hits", _QOS_SMOKE,
                         ids=[p for p, _ in _QOS_SMOKE])
def test_qos_chaos_smoke_recovers_byte_identical(
        point, hits, tmp_path, qos_twin_digest):
    """Kill mid-composition (scheduler charged, tick neither dispatched
    nor journaled) and pre-fsync under the 10x-abuser workload:
    recovery restores the deficit scheduler from the WAL headers, the
    resent frames recompose against it, and every plane reconverges
    byte-identical to the tenant-BLIND twin with zero acked-durable
    ops lost (the ISSUE 14 robustness bar)."""
    report = chaos.run_chaos(str(tmp_path), point, kill_hits=hits,
                             twin_digest=qos_twin_digest, qos=True,
                             **_QOS_CFG)
    assert report["killed"], report
    assert report["lives"] >= 2
    assert report["acked_rounds"] == list(range(_QOS_CFG["ticks"]))


def test_qos_fair_clean_run_matches_tenant_blind_twin(
        tmp_path, qos_twin_digest):
    """No kill at all: deficit-fair composition under a 10x abuser
    must leave every compared plane byte-identical to the tenant-blind
    FIFO twin — fairness moves latency, never bytes."""
    life = chaos._spawn_life(str(tmp_path), resume_from=None,
                             kill_env=None, timeout=300, qos="fair",
                             **_QOS_CFG)
    assert life["returncode"] == 0, life["stderr"]
    assert json.dumps(life["digest"], sort_keys=True) == json.dumps(
        qos_twin_digest, sort_keys=True)
    assert life["acked"] == list(range(_QOS_CFG["ticks"]))


@pytest.mark.soak
@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1])
def test_qos_chaos_full_matrix(seed, tmp_path):
    """Slow soak: every QoS kill point × hit position, per seed."""
    reports = chaos.run_matrix(
        str(tmp_path), points=chaos.QOS_KILL_POINTS, seeds=(seed,),
        hit_positions=(1, 2), docs=2, k=8, ticks=5, cp_every=2,
        qos=True)
    killed = [r for r in reports if r["killed"]]
    assert len(killed) >= len(reports) // 2, \
        [(r["kill_point"], r["kill_hits"], r["killed"]) for r in reports]


# -- history-plane kill classes (ISSUE 15): tier-1 smoke + slow matrix ---------

#: Aggressive compaction cadence (summaries every ~2 rounds, retention
#: 1, trims under the checkpoint watermark) + a mid-run branch fork —
#: the mid-compaction/mid-fork windows genuinely fire.
_HIST_CFG = dict(seed=0, docs=2, k=8, ticks=6, cp_every=2)

_HIST_SMOKE = [("history.mid_compaction", 1), ("history.mid_fork", 1)]


@pytest.fixture(scope="session")
def history_twin_digest(tmp_path_factory):
    """NEVER-compacted twin (same frames, same fork, summarizer off):
    equality with the compacting arm proves summarization compaction +
    tail trim never change converged state."""
    life = chaos._spawn_life(
        str(tmp_path_factory.mktemp("hist_twin")), resume_from=None,
        kill_env=None, timeout=300, history="plain", **_HIST_CFG)
    assert life["returncode"] == 0, life["stderr"]
    assert life["digest"] is not None
    return life["digest"]


@pytest.mark.parametrize("point,hits", _HIST_SMOKE,
                         ids=[p for p, _ in _HIST_SMOKE])
def test_history_chaos_smoke_recovers_byte_identical(
        point, hits, tmp_path, history_twin_digest):
    """Kill mid-compaction (summary uploaded, head not flipped) and
    mid-fork (control journaled, branch not seeded): recovery must
    reconverge byte-identical to the never-compacted twin — converged
    maps, sequencer checkpoints, read_at-at-head, branch registry —
    with zero acked-durable ops lost (the ISSUE 15 chaos bar)."""
    report = chaos.run_chaos(str(tmp_path), point, kill_hits=hits,
                             twin_digest=history_twin_digest,
                             history=True, **_HIST_CFG)
    assert report["killed"], report
    assert report["lives"] >= 2
    assert report["acked_rounds"] == list(range(_HIST_CFG["ticks"]))


def test_history_compacting_clean_run_matches_plain_twin(
        tmp_path, history_twin_digest):
    """No kill at all: the compacting/trimming arm must digest
    byte-identical to the never-compacted twin — summaries move read
    cost and disk, never bytes."""
    life = chaos._spawn_life(str(tmp_path), resume_from=None,
                             kill_env=None, timeout=300,
                             history="compact", **_HIST_CFG)
    assert life["returncode"] == 0, life["stderr"]
    assert json.dumps(life["digest"], sort_keys=True) == json.dumps(
        history_twin_digest, sort_keys=True)
    assert life["acked"] == list(range(_HIST_CFG["ticks"]))


@pytest.mark.soak
@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1])
def test_history_chaos_full_matrix(seed, tmp_path):
    """Slow soak: every history kill point × hit position, per seed."""
    reports = chaos.run_matrix(
        str(tmp_path), points=chaos.HISTORY_KILL_POINTS, seeds=(seed,),
        hit_positions=(1, 2), docs=2, k=8, ticks=6, cp_every=2,
        history=True)
    killed = [r for r in reports if r["killed"]]
    assert len(killed) >= len(reports) // 2, \
        [(r["kill_point"], r["kill_hits"], r["killed"]) for r in reports]


# -- replication kill classes (ISSUE 17): tier-1 smoke + slow matrix -----------

#: Leader + 2 followers under concurrent writes, a scripted mid-run
#: migration riding the same window; the resumed life ALWAYS promotes a
#: follower (the leader's directory is never reopened) — so digest
#: equality proves the replicated log + journaled heads alone carry the
#: whole acked state through a leader loss.
_REPL_CFG = dict(seed=0, docs=2, k=8, ticks=6, cp_every=2,
                 replication=True, migrate_at=3)

#: Tier-1 smoke: killed AFTER the batch shipped and quorum-acked but
#: before the leader's watermark settles — the op is acked-replicated,
#: so losing it would be the headline data-loss bug.
_REPL_SMOKE = [(chaos.REPLICATION_SMOKE_POINT, 2)]


@pytest.fixture(scope="session")
def replication_twin_digest(tmp_path_factory):
    """The never-killed, never-migrated replicated twin: equality
    against it is simultaneously the failover-recovery bar and the
    replication-is-transparent differential bar."""
    life = chaos._spawn_life(
        str(tmp_path_factory.mktemp("repl_twin")), resume_from=None,
        kill_env=None, timeout=300,
        **dict(_REPL_CFG, migrate_at=-1))
    assert life["returncode"] == 0, life["stderr"]
    assert life["digest"] is not None
    assert life["failovers"] == []  # nothing died in the twin
    return life["digest"]


@pytest.mark.parametrize("point,hits", _REPL_SMOKE,
                         ids=[p for p, _ in _REPL_SMOKE])
def test_replication_chaos_smoke_promotes_follower(
        point, hits, tmp_path, replication_twin_digest):
    """kill -9 the replicated leader mid-storm (concurrent writes, an
    in-flight migration): a follower promotes under the same label,
    the converged digest is byte-identical to the never-killed twin,
    zero acked-replicated ops are lost, and the failover blackout is
    bounded and reported (the ISSUE 17 acceptance bar)."""
    report = chaos.run_chaos(str(tmp_path), point, kill_hits=hits,
                             twin_digest=replication_twin_digest,
                             **_REPL_CFG)
    assert report["killed"], report
    assert report["lives"] >= 2
    assert report["acked_rounds"] == list(range(_REPL_CFG["ticks"]))
    blackouts = report["failover_blackouts_ms"]
    assert len(blackouts) == report["lives"] - 1  # one per promotion
    assert all(0 < b < 30_000 for b in blackouts), blackouts


@pytest.mark.soak
@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1])
def test_replication_chaos_full_matrix(seed, tmp_path):
    """Slow soak: every replication kill class (either side of the
    ship, torn group commit, mid-tick) × hit position, per seed — with
    the failover blackout p99 bounded across the whole matrix."""
    reports = chaos.run_matrix(
        str(tmp_path), points=chaos.REPLICATION_CHAOS_POINTS,
        seeds=(seed,), hit_positions=(1, 2),
        **{k: v for k, v in _REPL_CFG.items() if k != "seed"})
    killed = [r for r in reports if r["killed"]]
    assert len(killed) >= len(reports) // 2, \
        [(r["kill_point"], r["kill_hits"], r["killed"]) for r in reports]
    blackouts = sorted(b for r in reports
                       for b in r["failover_blackouts_ms"])
    assert blackouts, "no promotion fired across the whole matrix"
    p99 = blackouts[min(len(blackouts) - 1,
                        int(0.99 * len(blackouts)))]
    assert p99 < 30_000, blackouts


# -- read-replica chaos (ISSUE 18: replica reads never change bytes) ----------

_REPLICAS_CFG = dict(seed=0, docs=2, k=8, ticks=6, cp_every=2,
                     replicas=True, migrate_at=3)

#: Tier-1 smoke: records applied/indexed on the replica but the tick's
#: viewer broadcast NOT yet published — the restarted replica (a fresh
#: from-zero re-poll of the durable follower WAL) must re-derive the
#: identical read surface.
_REPLICAS_SMOKE = [(chaos.REPLICAS_SMOKE_POINT, 2)]


@pytest.fixture(scope="session")
def replicas_twin_digest(tmp_path_factory):
    """The replica-LESS twin (same frames, every digest read served by
    the leader): equality against it is simultaneously the
    kill-recovery bar and the replica-reads-never-change-bytes bar."""
    life = chaos._spawn_life(
        str(tmp_path_factory.mktemp("replicas_twin")), resume_from=None,
        kill_env=None, timeout=300,
        **dict(_REPLICAS_CFG, replicas="off", migrate_at=-1))
    assert life["returncode"] == 0, life["stderr"]
    assert life["digest"] is not None
    return life["digest"]


@pytest.mark.parametrize("point,hits", _REPLICAS_SMOKE,
                         ids=[p for p, _ in _REPLICAS_SMOKE])
def test_replicas_chaos_smoke_rebuilds_read_surface(
        point, hits, tmp_path, replicas_twin_digest):
    """kill -9 the read replica mid-broadcast (viewers in the room,
    a directory-spread re-home mid-run): the restarted replica
    re-polls its durable follower WAL from zero, viewers re-home via
    the ordinary ``viewer_resync`` machinery, zero acked ops are lost,
    and every replica-served read digests byte-identical to the
    replica-less twin (the ISSUE 18 acceptance bar)."""
    report = chaos.run_chaos(str(tmp_path), point, kill_hits=hits,
                             twin_digest=replicas_twin_digest,
                             **_REPLICAS_CFG)
    assert report["killed"], report
    assert report["lives"] >= 2
    assert report["acked_rounds"] == list(range(_REPLICAS_CFG["ticks"]))


@pytest.mark.soak
@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1])
def test_replicas_chaos_full_matrix(seed, tmp_path):
    """Slow soak: both replica kill classes (mid-apply between index
    and broadcast, mid-read inside a replica-served ``read_at``) × hit
    position, per seed."""
    reports = chaos.run_matrix(
        str(tmp_path), points=chaos.REPLICAS_CHAOS_POINTS,
        seeds=(seed,), hit_positions=(1, 2),
        **{k: v for k, v in _REPLICAS_CFG.items() if k != "seed"})
    killed = [r for r in reports if r["killed"]]
    assert len(killed) >= len(reports) // 2, \
        [(r["kill_point"], r["kill_hits"], r["killed"]) for r in reports]


# -- netsplit chaos (ISSUE 20: real sockets, fault-injected links) ------------


def test_netsplit_smoke_partition_parks_then_drains(tmp_path):
    """Tier-1 cut-the-cord smoke, F=1 over a REAL socket to a follower
    child process: a scripted full partition outlives the lease, the
    failure detector flips ``quorum_ok``, and the rounds written during
    the blackout PARK — no shed, no false ack. On heal the heartbeat
    resyncs the follower, the parked backlog drains, the delayed acks
    print, and the final state digests byte-identical to an in-process
    fault-free twin of the same seeded workload — with the incarnation
    fence proven on the wire at the end (the ISSUE 20 acceptance
    bar)."""
    report = chaos.run_netsplit(
        str(tmp_path), followers=1, seed=3, docs=2, k=4, ticks=6,
        cp_every=3, timeout=240.0, lease_s=0.4,
        script=chaos.netsplit_smoke_script(0.4))
    assert report["lives"] == 1 and not report["killed"]
    assert report["acked_rounds"] == list(range(6))
    # The blackout rounds were withheld at round end (parked), yet
    # every one of them is in acked_rounds above — parked, not lost.
    assert 1 in report["parked_rounds"], report
    assert report["zombie_fenced"] >= 1


@pytest.mark.soak
@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1])
def test_netsplit_full_matrix_kill_leader_promotes_over_wire(
        seed, tmp_path):
    """Slow soak, F=2 follower child processes: the full scenario walk
    (follower partition with the quorum holding, leader cut from the
    whole quorum with writes parking, one-way ``partition_recv`` with
    real duplicate deliveries, a dup+reorder tail) and then a genuine
    ``kill -9`` of the leader at round 9. The resumed life promotes
    the most advanced follower OVER THE WIRE (graceful child shutdown
    releases its WAL), serves the remaining rounds, proves the dead
    incarnation is refused by the survivors, and the digest matches
    the fault-free twin with zero acked-round loss."""
    report = chaos.run_netsplit(
        str(tmp_path), followers=2, seed=seed, docs=2, k=8, ticks=12,
        cp_every=4, timeout=420.0, kill_at=9)
    assert report["killed"] and report["lives"] >= 2
    assert report["acked_rounds"] == list(range(12))
    # The scripted leader-from-quorum blackout parked its rounds.
    assert 4 in report["parked_rounds"], report
    blackouts = report["failover_blackouts_ms"]
    assert len(blackouts) == report["lives"] - 1
    assert all(0 < b < 30_000 for b in blackouts), blackouts
    assert report["zombie_fenced"] >= 2  # post-promotion + end-of-life
