"""Telemetry/metrics/config/event utilities (reference: common-utils,
telemetry-utils logger.ts, services-core metricClient.ts, nconf config)."""

from __future__ import annotations

from fluidframework_tpu.utils import (
    BatchManager,
    ChildLogger,
    CollectingLogger,
    Config,
    Deferred,
    Heap,
    Histogram,
    MetricsRegistry,
    MultiSinkLogger,
    PerformanceEvent,
    TypedEventEmitter,
    default_config,
)


class TestEvents:
    def test_on_emit_off(self):
        em = TypedEventEmitter()
        seen = []
        off = em.on("x", seen.append)
        em.emit("x", 1)
        em.emit("x", 2)
        off()
        em.emit("x", 3)
        assert seen == [1, 2]

    def test_once(self):
        em = TypedEventEmitter()
        seen = []
        em.once("x", seen.append)
        em.emit("x", 1)
        em.emit("x", 2)
        assert seen == [1]

    def test_once_is_per_event(self):
        em = TypedEventEmitter()
        seen = []
        em.once("a", seen.append)
        em.on("b", seen.append)  # same callable, persistent on "b"
        em.emit("b", 1)
        em.emit("b", 2)
        em.emit("a", 3)
        em.emit("a", 4)
        assert seen == [1, 2, 3]

    def test_deferred_reject_notifies(self):
        d: Deferred[int] = Deferred()
        errors = []
        d.then(lambda v: None, errors.append)
        d.reject(RuntimeError("x"))
        d.then(lambda v: None, errors.append)  # late subscriber
        assert len(errors) == 2

    def test_deferred(self):
        d: Deferred[int] = Deferred()
        seen = []
        d.then(seen.append)
        assert not d.is_completed
        d.resolve(7)
        d.resolve(8)  # set-once
        d.then(seen.append)  # late subscriber fires immediately
        assert seen == [7, 7] and d.value == 7

    def test_batch_manager_flush_on_max(self):
        batches = []
        bm: BatchManager[int] = BatchManager(
            lambda k, items: batches.append((k, items)), max_batch_size=3)
        for i in range(7):
            bm.add("doc", i)
        bm.drain()
        assert batches == [("doc", [0, 1, 2]), ("doc", [3, 4, 5]),
                           ("doc", [6])]

    def test_heap(self):
        h: Heap[tuple] = Heap(key=lambda t: t[0])
        for item in [(3, "c"), (1, "a"), (2, "b")]:
            h.push(item)
        assert [h.pop()[1] for _ in range(len(h))] == ["a", "b", "c"]


class TestTelemetry:
    def test_child_logger_namespacing_and_props(self):
        root = CollectingLogger(namespace="fluid:telemetry")
        child = ChildLogger.create(root, "DeltaManager", {"docId": "d1"})
        child.send_event("ConnectionStateChange", state="Connected")
        [event] = root.events
        assert event["eventName"] == \
            "fluid:telemetry:DeltaManager:ConnectionStateChange"
        assert event["docId"] == "d1" and event["state"] == "Connected"
        assert event["category"] == "generic"

    def test_multi_sink(self):
        a, b = CollectingLogger(), CollectingLogger()
        multi = MultiSinkLogger([a, b])
        multi.send_event("e")
        assert len(a.events) == len(b.events) == 1

    def test_performance_event_end_and_cancel(self):
        log = CollectingLogger()
        with PerformanceEvent(log, "summarize", emit_start=True):
            pass
        try:
            with PerformanceEvent(log, "load"):
                raise ValueError("boom")
        except ValueError:
            pass
        names = [e["eventName"] for e in log.events]
        assert names == ["summarize_start", "summarize_end", "load_cancel"]
        assert log.events[1]["duration"] >= 0
        assert "boom" in log.events[2]["error"]


class TestMetrics:
    def test_histogram_quantiles_bracket_true_p99(self):
        h = Histogram(min_bound=1e-6, max_bound=10.0)
        values = [i / 1000.0 for i in range(1, 1001)]  # 1ms..1s uniform
        for v in values:
            h.observe(v)
        p99 = h.quantile(0.99)
        # Log-bucketed estimate: within one bucket (~26%) of the true 0.99.
        assert 0.7 <= p99 <= 1.3
        assert h.count == 1000 and abs(h.mean - 0.5005) < 1e-9
        assert h.quantile(1.0) == h.max == 1.0

    def test_registry_snapshot(self):
        reg = MetricsRegistry()
        reg.counter("ops").inc(5)
        reg.gauge("depth").set(3)
        reg.histogram("lat").observe(0.01)
        snap = reg.snapshot()
        assert snap["ops"] == 5 and snap["depth"] == 3
        assert snap["lat.count"] == 1 and snap["lat.p99"] > 0

    def test_quantile_interpolates_within_the_winning_bucket(self):
        """The docstring's claim, pinned: with a known uniform
        distribution the interpolated quantile lands far closer to the
        true value than the winning bucket's ~26%-wide upper bound."""
        h = Histogram(min_bound=1e-6, max_bound=10.0)
        values = [i / 1000.0 for i in range(1, 1001)]  # 1ms..1s uniform
        for v in values:
            h.observe(v)
        # True quantiles of the uniform sample; log buckets are ~26%
        # wide, interpolation must do clearly better than an upper bound.
        for q, true in ((0.25, 0.25), (0.5, 0.5), (0.9, 0.9)):
            est = h.quantile(q)
            assert abs(est - true) / true < 0.15, (q, est)
            # And strictly better than the raw bucket upper bound ever
            # was: the estimate may not EXCEED the bucket bound.
            assert est <= true * 1.26
        # Monotone in q, exact at the edges.
        qs = [h.quantile(q / 20) for q in range(1, 21)]
        assert qs == sorted(qs)
        assert h.quantile(1.0) == h.max == 1.0
        # Single observation: any quantile returns it (clamped to max).
        h1 = Histogram()
        h1.observe(0.003)
        assert h1.quantile(0.5) == 0.003
        assert Histogram().quantile(0.5) == 0.0

    def test_concurrent_observe_loses_nothing(self):
        """Regression (round-10 satellite): the registry is shared by
        the bridge pump, serving and WAL-writer threads — concurrent
        inc/observe/snapshot must not drop or corrupt counts (the
        unlocked ``+=`` read-modify-write raced)."""
        import threading

        reg = MetricsRegistry()
        n_threads, per_thread = 8, 5_000
        snaps = []

        def hammer(tid):
            c = reg.counter("shared.ops")
            h = reg.histogram("shared.lat")
            g = reg.gauge("shared.depth")
            for i in range(per_thread):
                c.inc()
                h.observe((i % 100 + 1) / 1000.0)
                g.add(1)
                if i % 1000 == 0:
                    snaps.append(reg.snapshot())  # reader in the race

        threads = [threading.Thread(target=hammer, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = reg.snapshot()
        total = n_threads * per_thread
        assert snap["shared.ops"] == total
        assert snap["shared.lat.count"] == total
        assert snap["shared.depth"] == total
        h = reg.histogram("shared.lat")
        assert sum(h._counts) == total
        assert all(isinstance(s, dict) for s in snaps)


class TestStageLedger:
    def test_record_amend_attribution(self):
        from fluidframework_tpu.utils import STORM_STAGES, StageLedger
        reg = MetricsRegistry()
        led = StageLedger(registry=reg, prefix="s.stage", capacity=4)
        rec = led.record(0, queue_depth=5, batch_docs=2, batch_ops=64,
                         splits_ns={"scatter": 1_000_000,
                                    "device_dispatch": 3_000_000})
        assert all(s in rec for s in STORM_STAGES)
        assert rec["readback"] == 0
        led.amend(rec, "wal_commit_wait", 4_000_000)
        att = led.attribution()
        assert att["device_dispatch"]["share"] == 0.375
        assert att["wal_commit_wait"]["share"] == 0.5
        assert att["_window"]["ticks"] == 1
        snap = reg.snapshot()
        assert snap["s.stage.scatter.count"] == 1
        assert snap["s.stage.wal_commit_wait.count"] == 1

    def test_ring_bound_and_unknown_stage_rejected(self):
        import pytest

        from fluidframework_tpu.utils import StageLedger
        led = StageLedger(capacity=3)
        for i in range(10):
            led.record(i, 0, 1, 1, {"scatter": 1})
        assert len(led) == 3
        assert [r["tick"] for r in led.records()] == [7, 8, 9]
        with pytest.raises(ValueError, match="unknown ledger stages"):
            led.record(11, 0, 1, 1, {"not_a_stage": 1})
        with pytest.raises(ValueError, match="unknown ledger stage"):
            led.amend(led.records()[0], "not_a_stage", 1)


class TestTraceSpans:
    def test_mark_finish_joins_deltas(self):
        from fluidframework_tpu.utils import TraceSpans
        log = CollectingLogger()
        ts = TraceSpans(logger=log)
        ts.mark(1, "a", 1_000_000)
        ts.mark(1, "b", 3_000_000)
        ts.mark(1, "c", 4_500_000)
        assert ts.hops(1) == {"a": 1_000_000, "b": 3_000_000,
                              "c": 4_500_000}
        span = ts.finish(1, rid=9)
        assert span["deltas_ms"] == {"a_to_b": 2.0, "b_to_c": 1.5}
        assert span["total_ms"] == 3.5 and span["rid"] == 9
        assert ts.finish(1) is None  # double-finish is a no-op
        assert ts.finish(42) is None  # unknown id: nothing emitted
        events = log.matching("OpTraceSpan")
        assert len(events) == 1 and events[0]["category"] == "performance"

    def test_pending_eviction_bound(self):
        from fluidframework_tpu.utils import TraceSpans
        ts = TraceSpans(max_pending=4)
        for i in range(10):
            ts.mark(i, "hop", i)
        assert len(ts._marks) == 4
        assert ts.finish(0) is None  # evicted oldest-first
        assert ts.finish(9) is not None

    def test_percentile_nearest_rank_exact(self):
        from fluidframework_tpu.utils.metrics import percentile
        assert percentile([], 0.5) == 0.0
        assert percentile([7], 0.0) == 7
        assert percentile([1, 2], 0.5) == 1      # ceil(1)-1 = rank 0
        assert percentile([1, 2], 0.51) == 2
        vals = list(range(1, 101))
        assert percentile(vals, 0.99) == 99      # the 99th, not the max
        assert percentile(vals, 1.0) == 100

    def test_hop_quantiles_decompose(self):
        from fluidframework_tpu.utils import TraceSpans
        ts = TraceSpans()
        for i in range(100):
            ts.mark(i, "x", 0)
            ts.mark(i, "y", (i + 1) * 1_000_000)
            ts.finish(i)
        q = ts.hop_quantiles()
        assert q["x_to_y"]["count"] == 100
        assert 45 <= q["x_to_y"]["p50_ms"] <= 55
        assert 95 <= q["x_to_y"]["p99_ms"] <= 100


class TestConfig:
    def test_layering_env_over_file_defaults(self, tmp_path):
        f = tmp_path / "config.json"
        f.write_text('{"bus": {"partitions": 8}, "name": "file"}')
        cfg = Config(defaults={"bus": {"partitions": 4, "topic": "raw"},
                               "name": "default"},
                     file=f,
                     env={"FF_TPU_BUS__PARTITIONS": "16",
                          "FF_TPU_FLAG": "true", "HOME": "/x"},
                     overrides={"name": "override"})
        assert cfg.get("bus:partitions") == 16     # env beats file
        assert cfg.get("bus:topic") == "raw"       # default survives merge
        assert cfg.get("name") == "override"       # overrides beat all
        assert cfg.get("flag") is True             # env JSON parsing
        assert cfg.get("home") is None             # unprefixed env ignored
        assert cfg.get("nope", 42) == 42

    def test_default_config_sections(self):
        cfg = default_config(overrides={"alfred": {"max_message_size": 1024}})
        assert cfg.get("alfred:max_message_size") == 1024
        assert cfg.section("deli").get("client_timeout_ms") == 300_000
        assert cfg.require("bus:partitions") == 4


class TestServiceTraces:
    def test_op_traces_ride_sequenced_messages(self):
        from fluidframework_tpu.protocol.messages import (
            DocumentMessage, MessageType, Trace)
        from fluidframework_tpu.server.routerlicious import (
            RouterliciousService)

        service = RouterliciousService()
        received = []
        conn = service.connect("doc", lambda ms: received.extend(ms))
        conn.submit([DocumentMessage(
            client_sequence_number=1, reference_sequence_number=1,
            type=MessageType.OPERATION, contents={"x": 1},
            traces=(Trace("client", "submit"),))])
        ops = [m for m in received if m.type == MessageType.OPERATION]
        assert ops, "operation not broadcast"
        legs = [(t.service, t.action) for t in ops[-1].traces]
        assert legs == [("client", "submit"), ("alfred", "submit"),
                        ("deli", "start"), ("deli", "end")]
        assert service.metrics.snapshot()["deli.sequenced_ops"] >= 1

    def test_service_shares_registry_with_merge_host(self):
        from fluidframework_tpu.server.merge_host import KernelMergeHost
        from fluidframework_tpu.server.routerlicious import (
            RouterliciousService)

        host = KernelMergeHost()
        service = RouterliciousService(merge_host=host)
        assert host.metrics is service.metrics

    def test_merge_host_flush_metrics(self):
        from fluidframework_tpu.server.merge_host import KernelMergeHost
        from fluidframework_tpu.protocol.messages import (
            MessageType, SequencedDocumentMessage)

        host = KernelMergeHost()
        host.ingest("d", SequencedDocumentMessage(
            client_id="c", sequence_number=1, minimum_sequence_number=0,
            client_sequence_number=1, reference_sequence_number=0,
            type=MessageType.OPERATION,
            contents={"address": "ds", "contents": {
                "address": "map", "contents": {
                    "type": "set", "key": "k", "value": 1}}}))
        host.flush()
        snap = host.metrics.snapshot()
        assert snap["merge_host.merged_ops"] == 1
        assert snap["merge_host.tick_seconds.count"] == 1
