"""Native fan-out service (§2.9 row 3 — Redis pub/sub +
redisSocketIoAdapter analog) and its broadcast integration."""

from __future__ import annotations

import pytest

from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.native.fanout import PyFanout, make_fanout
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.routerlicious import RouterliciousService


def _impls():
    impls = [PyFanout()]
    native = make_fanout()
    if native.is_native:
        impls.append(native)
    return impls


@pytest.mark.parametrize("fanout", _impls(),
                         ids=lambda f: "native" if f.is_native else "python")
class TestFanoutCore:
    def test_rooms_fifo_and_membership(self, fanout):
        a = fanout.connect()
        b = fanout.connect()
        fanout.join(a, "doc1")
        fanout.join(b, "doc1")
        fanout.join(b, "doc2")

        assert fanout.publish("doc1", b"m1") == 2
        assert fanout.publish("doc2", b"m2") == 1
        assert fanout.publish("nobody-home", b"m3") == 0

        assert fanout.pending(a) == 1
        assert fanout.poll(a) == b"m1"
        assert fanout.poll(a) is None
        assert [fanout.poll(b), fanout.poll(b)] == [b"m1", b"m2"]

        fanout.leave(a, "doc1")
        assert fanout.publish("doc1", b"m4") == 1  # only b now
        assert fanout.poll(b) == b"m4"

    def test_disconnect_cleans_rooms_and_queue(self, fanout):
        a = fanout.connect()
        fanout.join(a, "doc")
        fanout.publish("doc", b"x")
        fanout.disconnect(a)
        assert fanout.poll(a) is None
        assert fanout.publish("doc", b"y") == 0
        with pytest.raises(KeyError):
            fanout.join(a, "doc")

    def test_empty_payload_drains(self, fanout):
        a = fanout.connect()
        fanout.join(a, "empty-room")
        fanout.publish("empty-room", b"")
        fanout.publish("empty-room", b"after")
        assert fanout.poll(a) == b""   # empty payloads are legal...
        assert fanout.poll(a) == b"after"  # ...and must not wedge the queue
        assert fanout.poll(a) is None
        fanout.disconnect(a)

    def test_large_payload_roundtrip(self, fanout):
        a = fanout.connect()
        fanout.join(a, "big")
        payload = bytes(range(256)) * 4096  # 1 MiB binary
        assert fanout.publish("big", payload) == 1
        assert fanout.poll(a) == payload

    def test_delivered_total(self, fanout):
        before = fanout.delivered_total()
        a = fanout.connect()
        b = fanout.connect()
        fanout.join(a, "r")
        fanout.join(b, "r")
        fanout.publish("r", b"z")
        assert fanout.delivered_total() == before + 2

    def test_room_membership_under_churn(self, fanout):
        """Round-13 satellite: join/leave/disconnect interleavings keep
        room membership exact, empty rooms reclaim, and publishing to a
        dead subscriber's old room never wedges or miscounts."""
        rooms_before = fanout.room_count()
        subs = [fanout.connect() for _ in range(8)]
        for i, sub in enumerate(subs):
            fanout.join(sub, "churn-a")
            if i % 2:
                fanout.join(sub, "churn-b")
        assert fanout.room_size("churn-a") == 8
        assert fanout.room_size("churn-b") == 4
        assert fanout.room_count() == rooms_before + 2

        # Interleave: leave a, disconnect mid-membership, re-join.
        fanout.leave(subs[0], "churn-a")
        fanout.disconnect(subs[1])  # was in both rooms
        fanout.join(subs[0], "churn-b")
        assert fanout.room_size("churn-a") == 6
        assert fanout.room_size("churn-b") == 4  # -subs[1] +subs[0]

        # Publish-to-dead-subscriber: disconnect then publish — dead
        # members are skipped, live members still count exactly.
        fanout.disconnect(subs[2])
        assert fanout.publish("churn-a", b"alive") == 5
        assert fanout.poll(subs[3]) == b"alive"
        # A dead sub cannot re-join and polls nothing.
        with pytest.raises(KeyError):
            fanout.join(subs[1], "churn-a")
        assert fanout.poll(subs[1]) is None

        # Empty-room reclamation: drain every member out both ways.
        for sub in subs:
            fanout.leave(sub, "churn-a")  # no-op for gone members
            fanout.disconnect(sub)
        assert fanout.room_size("churn-a") == 0
        assert fanout.room_size("churn-b") == 0
        assert fanout.room_count() == rooms_before
        assert fanout.publish("churn-a", b"nobody") == 0

    def test_per_subscriber_queue_limit(self, fanout):
        """Per-room outbox bounds: a shallow-limit subscriber (the
        viewer class) evicts early; default-limit peers are untouched;
        resetting the limit restores the default."""
        viewer = fanout.connect()
        writer = fanout.connect()
        fanout.join(viewer, "lim")
        fanout.join(writer, "lim")
        fanout.set_queue_limit(viewer, 3)
        for i in range(5):
            fanout.publish("lim", b"m%d" % i)
        assert fanout.was_evicted(viewer)
        assert not fanout.was_evicted(writer)
        assert fanout.pending(writer) == 5
        with pytest.raises(KeyError):
            fanout.set_queue_limit(viewer, None)  # evicted = unknown
        # A fresh subscriber with the limit RESET takes the default.
        fresh = fanout.connect()
        fanout.join(fresh, "lim")
        fanout.set_queue_limit(fresh, 2)
        fanout.set_queue_limit(fresh, None)
        for i in range(4):
            fanout.publish("lim", b"x")
        assert not fanout.was_evicted(fresh)
        fanout.disconnect(viewer)
        fanout.disconnect(writer)
        fanout.disconnect(fresh)

    def test_publish_batch_matches_sequential_publishes(self, fanout):
        """One batched call == the same per-room publishes, in order —
        the O(batch) broadcast hop of a storm tick."""
        a = fanout.connect()
        b = fanout.connect()
        fanout.join(a, "batch-1")
        fanout.join(b, "batch-1")
        fanout.join(b, "batch-2")
        delivered = fanout.publish_batch([
            ("batch-1", b"\x00storm1:8:1"),
            ("batch-2", b"\x00storm9:16:2"),
            ("batch-empty-room", b"zzz"),
            ("batch-1", b""),  # empty payloads stay legal in a batch
        ])
        assert delivered == 2 + 1 + 0 + 2
        assert fanout.poll(a) == b"\x00storm1:8:1"
        assert fanout.poll(a) == b""
        assert [fanout.poll(b) for _ in range(3)] == [
            b"\x00storm1:8:1", b"\x00storm9:16:2", b""]
        assert fanout.publish_batch([]) == 0
        fanout.disconnect(a)
        fanout.disconnect(b)


@pytest.mark.parametrize("fanout", _impls(),
                         ids=lambda f: "native" if f.is_native else "python")
def test_slow_consumer_evicted(fanout):
    # A subscriber that never polls is dropped once MAX_QUEUE payloads
    # queue up (socket.io Redis-adapter slow-client semantics) instead of
    # buffering without bound; healthy subscribers are untouched.
    from fluidframework_tpu.native import fanout as fanout_mod
    slow = fanout.connect()
    ok = fanout.connect()
    fanout.join(slow, "room")
    fanout.join(ok, "room")
    limit = fanout_mod.MAX_QUEUE
    for i in range(limit + 2):
        fanout.publish("room", b"p")
        if fanout.poll(ok) is None:  # ok drains as it goes
            raise AssertionError("healthy subscriber starved")
    assert fanout.was_evicted(slow)
    assert not fanout.was_evicted(ok)
    assert fanout.poll(slow) is None
    # The room still works for the healthy subscriber.
    assert fanout.publish("room", b"tail") == 1
    assert fanout.poll(ok) == b"tail"
    # Disconnecting the evicted sub succeeds and clears the flag (the
    # eviction set must not grow forever).
    fanout.disconnect(slow)
    assert not fanout.was_evicted(slow)


def test_native_fanout_builds_here():
    # This image has the toolchain; the native path must actually build
    # (elsewhere make_fanout falls back to the Python twin).
    assert make_fanout().is_native


@pytest.mark.parametrize("force_python", [True, False])
def test_service_broadcast_through_fanout(force_python):
    service = RouterliciousService(fanout=make_fanout(force_python))

    def make_doc(doc_id):
        svc = LocalServiceAdapter(service, doc_id)
        container = Container.create_detached(svc)
        ds = container.runtime.create_datastore("default")
        ds.create_channel("root", SharedMap.channel_type)
        container.attach()
        return container

    # The local driver duck-types over any service with the front-door
    # surface; RouterliciousService has it.
    class LocalServiceAdapter(LocalDocumentService):
        pass

    c1 = make_doc("doc")
    c2 = Container.load(LocalServiceAdapter(service, "doc"))
    m1 = c1.runtime.get_datastore("default").get_channel("root")
    m2 = c2.runtime.get_datastore("default").get_channel("root")
    m1.set("x", 1)
    m2.set("y", 2)
    assert m1.get("y") == 2 and m2.get("x") == 1
    assert service.fanout.delivered_total() > 0

    # Disconnect stops delivery to that subscriber but not others.
    c2.close()
    m1.set("z", 3)
    assert m1.get("z") == 3
