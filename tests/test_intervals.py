"""Interval collection tests: ranges tracking text through concurrent edits.

Reference model: intervalCollection.spec behaviors — intervals shift with
inserts, slide past removals, LWW per id, survive summaries.
"""

import random

import pytest

from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.local_server import LocalCollabServer
from tests.test_mergetree import get_string, make_string_doc


def setup_pair():
    server = LocalCollabServer()
    c1 = make_string_doc(server)
    c2 = Container.load(LocalDocumentService(server, "doc"))
    return server, c1, c2, get_string(c1), get_string(c2)


class TestIntervals:
    def test_interval_follows_inserts(self):
        _server, c1, c2, t1, t2 = setup_pair()
        t1.insert_text(0, "hello world")
        ic1 = t1.get_interval_collection("highlights")
        ic2 = t2.get_interval_collection("highlights")
        interval = ic1.add(6, 11, {"color": "yellow"})  # "world"
        assert ic2.resolved()[interval.id][:2] == (6, 11)
        # Insert before: both replicas' interval shifts right.
        t2.insert_text(0, ">>> ")
        assert ic1.resolved()[interval.id][:2] == (10, 15)
        assert ic2.resolved()[interval.id][:2] == (10, 15)
        # Insert before start: both endpoints shift.
        t1.insert_text(8, "XX")
        assert ic1.resolved()[interval.id][:2] == (12, 17)
        # Insert inside: interval stretches.
        t2.insert_text(14, "YY")
        assert ic1.resolved()[interval.id][:2] == (12, 19)
        assert ic1.resolved() == ic2.resolved()

    def test_interval_slides_past_removed_text(self):
        _server, c1, c2, t1, t2 = setup_pair()
        t1.insert_text(0, "abcdefghij")
        ic1 = t1.get_interval_collection("x")
        ic2 = t2.get_interval_collection("x")
        interval = ic1.add(3, 7)  # "defg"
        t2.remove_text(2, 5)      # removes "cde" including interval start
        r1, r2 = ic1.resolved()[interval.id], ic2.resolved()[interval.id]
        assert r1 == r2
        start, end, _ = r1
        assert 0 <= start <= end <= len(t1.get_text())

    def test_change_and_delete_lww(self):
        _server, c1, c2, t1, t2 = setup_pair()
        t1.insert_text(0, "0123456789")
        ic1 = t1.get_interval_collection("x")
        ic2 = t2.get_interval_collection("x")
        interval = ic1.add(1, 3)
        ic2.change(interval.id, start=5, end=8, props={"p": 1})
        assert ic1.resolved() == ic2.resolved()
        assert ic1.resolved()[interval.id] == (5, 8, {"p": 1})
        ic1.delete(interval.id)
        assert ic1.resolved() == ic2.resolved() == {}

    def test_summary_roundtrip_with_intervals(self):
        server, c1, c2, t1, t2 = setup_pair()
        t1.insert_text(0, "summary text")
        ic1 = t1.get_interval_collection("marks")
        ic1.add(0, 7, {"k": 1})
        assert c1.summarize() == c2.summarize()
        server.upload_snapshot("doc", c1.summarize())
        c3 = Container.load(LocalDocumentService(server, "doc"))
        t3 = get_string(c3)
        assert t3.get_interval_collection("marks").resolved() == ic1.resolved()
        assert c3.summarize() == c1.summarize()

    def test_reconnect_replays_interval_ops(self):
        _server, c1, c2, t1, t2 = setup_pair()
        t1.insert_text(0, "offline interval target")
        c2.disconnect()
        ic2 = t2.get_interval_collection("x")
        interval = ic2.add(0, 7, {"made": "offline"})
        t1.insert_text(0, "shift ")
        c2.reconnect()
        ic1 = t1.get_interval_collection("x")
        assert ic1.resolved() == ic2.resolved()
        assert interval.id in ic1.resolved()
        assert c1.summarize() == c2.summarize()


class TestIntervalRegressions:
    def test_concurrent_delete_vs_change_converges_on_delete(self):
        _server, c1, c2, t1, t2 = setup_pair()
        t1.insert_text(0, "0123456789")
        ic1 = t1.get_interval_collection("x")
        ic2 = t2.get_interval_collection("x")
        interval = ic1.add(1, 3)
        c1.inbound.pause()
        ic2.delete(interval.id)               # sequenced first
        ic1.change(interval.id, props={"p": 9})  # pending at c1
        c1.inbound.resume()
        # Delete wins: both replicas drop the interval.
        assert ic1.resolved() == ic2.resolved() == {}
        assert c1.summarize() == c2.summarize()

    def test_anchor_survives_zamboni_compaction(self):
        _server, c1, c2, t1, t2 = setup_pair()
        t1.insert_text(0, "abcdefghij")
        ic1 = t1.get_interval_collection("x")
        ic2 = t2.get_interval_collection("x")
        interval = ic1.add(5, 8)
        # Remove text before the interval, then churn ops so the collab
        # window advances far past the removal and zamboni compacts.
        t2.remove_text(0, 3)
        for _ in range(6):
            t1.insert_text(0, "z")
            t2.insert_text(0, "z")
        r1 = ic1.resolved()[interval.id]
        r2 = ic2.resolved()[interval.id]
        assert r1 == r2
        # Anchor must not have jumped to the end of the document.
        assert r1[0] < len(t1.get_text())

    def test_summary_positions_use_acked_view(self):
        server, c1, c2, t1, t2 = setup_pair()
        t1.insert_text(0, "abcdef")
        ic1 = t1.get_interval_collection("x")
        ic1.add(4, 5)
        # A pending (never-sequenced) local insert must not offset the
        # summarized interval positions.
        c1.disconnect()
        t1.insert_text(0, "XX")
        snap = t1.summarize_core()
        assert snap["interval_collections"][0]["intervals"][0]["start"] == 4
        c1.reconnect()
        assert c1.summarize() == c2.summarize()


@pytest.mark.parametrize("seed", range(2))
def test_interval_farm(seed):
    rng = random.Random(200 + seed)
    server = LocalCollabServer()
    c1 = make_string_doc(server)
    c2 = Container.load(LocalDocumentService(server, "doc"))
    t1, t2 = get_string(c1), get_string(c2)
    t1.insert_text(0, "x" * 30)
    collections = [t.get_interval_collection("f") for t in (t1, t2)]
    containers = [c1, c2]
    texts = [t1, t2]
    ids: list[str] = []

    for _round in range(5):
        paused = [c for c in containers if rng.random() < 0.3]
        for c in paused:
            c.inbound.pause()
        for _ in range(rng.randrange(3, 8)):
            i = rng.randrange(2)
            text, ic = texts[i], collections[i]
            n = len(text)
            r = rng.random()
            if r < 0.35 and n > 1:
                a = rng.randrange(n - 1)
                b = a + 1 + rng.randrange(min(4, n - a - 1) or 1)
                ids.append(ic.add(a, min(b, n)).id)
            elif r < 0.5 and ids:
                known = [x for x in ids if ic.get(x)]
                if known:
                    ic.delete(rng.choice(known))
            elif r < 0.8:
                text.insert_text(rng.randrange(n + 1), "ab")
            elif n > 2:
                a = rng.randrange(n - 1)
                text.remove_text(a, min(n, a + 2))
        for c in paused:
            c.inbound.resume()
        assert t1.get_text() == t2.get_text(), (seed, _round)
        assert collections[0].resolved() == collections[1].resolved(), (
            seed, _round)
    assert c1.summarize() == c2.summarize()


class TestIntervalIndex:
    """Overlap-query index — findOverlappingIntervals / previous / next
    (intervalCollection.ts:265-334) against a brute-force oracle."""

    def test_find_overlapping_basic(self):
        _server, c1, c2, t1, t2 = setup_pair()
        t1.insert_text(0, "x" * 40)
        ic = t1.get_interval_collection("q")
        a = ic.add(0, 5)
        b = ic.add(3, 12)
        c = ic.add(10, 20)
        d = ic.add(25, 30)
        got = [i.id for i in ic.find_overlapping_intervals(4, 11)]
        assert got == [a.id, b.id, c.id]
        assert [i.id for i in ic.find_overlapping_intervals(21, 24)] == []
        assert [i.id for i in ic.find_overlapping_intervals(30, 99)] == [d.id]
        # Inclusive endpoints, matching IntervalTree.match.
        assert [i.id for i in ic.find_overlapping_intervals(5, 5)] \
            == [a.id, b.id]

    def test_previous_next(self):
        _server, c1, c2, t1, t2 = setup_pair()
        t1.insert_text(0, "y" * 40)
        ic = t1.get_interval_collection("q")
        a = ic.add(2, 4)
        b = ic.add(10, 15)
        assert ic.previous_interval(1) is None
        assert ic.previous_interval(2).id == a.id
        assert ic.previous_interval(9).id == a.id
        assert ic.previous_interval(30).id == b.id
        assert ic.next_interval(0).id == a.id
        assert ic.next_interval(3).id == b.id
        assert ic.next_interval(16) is None
        assert [i.id for i in ic.iterate()] == [a.id, b.id]

    def test_index_tracks_edits_and_remote_ops(self):
        """The lazy index must match brute-force resolution after every
        kind of mutation: local/remote inserts, removes, interval
        add/change/delete from either replica."""
        _server, c1, c2, t1, t2 = setup_pair()
        rng = random.Random(11)
        t1.insert_text(0, "abcdefghijklmnopqrstuvwxyz" * 4)
        ic1 = t1.get_interval_collection("q")
        ic2 = t2.get_interval_collection("q")
        ids = []
        for step in range(120):
            roll = rng.random()
            text_len = len(t1.get_text())
            src_text, src_ic = (t1, ic1) if rng.random() < 0.5 else (t2, ic2)
            if roll < 0.3 or not ids:
                s = rng.randrange(max(1, text_len))
                e = min(text_len, s + rng.randrange(1, 9))
                ids.append(src_ic.add(s, e, interval_id=f"i{step}").id)
            elif roll < 0.45:
                src_ic.delete(ids.pop(rng.randrange(len(ids))))
            elif roll < 0.6:
                iid = rng.choice(ids)
                s = rng.randrange(max(1, text_len))
                src_ic.change(iid, start=s,
                              end=min(text_len, s + rng.randrange(1, 6)))
            elif roll < 0.8:
                pos = rng.randrange(max(1, text_len))
                src_text.insert_text(pos, "INS")
            elif text_len > 4:
                s = rng.randrange(text_len - 2)
                src_text.remove_text(s, min(text_len, s + rng.randrange(1, 4)))
            if step % 10 == 0:
                for ic in (ic1, ic2):
                    resolved = ic.resolved()
                    qs = rng.randrange(120)
                    qe = qs + rng.randrange(0, 30)
                    oracle = sorted(
                        iid for iid, (s, e, _p) in resolved.items()
                        if s <= qe and e >= qs)
                    got = sorted(
                        i.id for i in ic.find_overlapping_intervals(qs, qe))
                    assert got == oracle, (step, qs, qe)
                    pos = rng.randrange(120)
                    prev_oracle = max(
                        ((s, e, iid) for iid, (s, e, _p) in resolved.items()
                         if s <= pos), default=None)
                    prev = ic.previous_interval(pos)
                    assert (prev.id if prev else None) == (
                        prev_oracle[2] if prev_oracle else None)
                    nxt_oracle = min(
                        ((s, e, iid) for iid, (s, e, _p) in resolved.items()
                         if s >= pos), default=None)
                    nxt = ic.next_interval(pos)
                    assert (nxt.id if nxt else None) == (
                        nxt_oracle[2] if nxt_oracle else None)
        assert ic1.resolved() == ic2.resolved()
