"""KernelSequencerHost differential tests: device-batched sequencing through
the host (string client ids, slot allocation/reuse, multi-doc flush,
checkpoint round-trip) must match the scalar DocumentSequencer exactly, and
the e2e LocalCollabServer stack must converge identically on either."""

import random

import pytest

from fluidframework_tpu.ops import opcodes as oc
from fluidframework_tpu.protocol.messages import MessageType
from fluidframework_tpu.server.kernel_host import KernelSequencerHost
from fluidframework_tpu.server.local_server import LocalCollabServer
from fluidframework_tpu.server.sequencer import DocumentSequencer, RawOperation

from test_sequencer import join, leave, op, random_stream


def assert_tickets_equal(got, want, ctx):
    assert got.kind == want.kind, (ctx, got, want)
    if want.kind != oc.OUT_IGNORED:
        assert got.seq == want.seq, (ctx, got, want)
        assert got.msn == want.msn, (ctx, got, want)
    assert got.send == want.send, (ctx, got, want)
    assert got.nack_code == want.nack_code, (ctx, got, want)


@pytest.mark.parametrize("seed", range(4))
def test_sync_path_matches_scalar_fuzz(seed):
    rng = random.Random(seed)
    host = KernelSequencerHost(num_slots=8, initial_capacity=2)
    docs = ["alpha", "beta", "gamma"]  # 3 docs > capacity 2 forces growth
    scalars = {d: DocumentSequencer() for d in docs}
    for i in range(150):
        doc = rng.choice(docs)
        stream = random_stream(rng, 1, n_clients=6)
        if not stream:
            continue
        raw = stream[0]
        want = scalars[doc].ticket(raw)
        got = host.sequence(doc, raw)
        assert_tickets_equal(got, want, (seed, i, doc, raw))
    for doc in docs:
        cp_host = host.checkpoint(doc)
        cp_scalar = scalars[doc].checkpoint()
        assert cp_host.sequence_number == cp_scalar.sequence_number
        assert cp_host.minimum_sequence_number == \
            cp_scalar.minimum_sequence_number
        assert cp_host.last_sent_msn == cp_scalar.last_sent_msn
        assert cp_host.clients == cp_scalar.clients


@pytest.mark.parametrize("seed", range(4))
def test_flush_path_matches_scalar_fuzz(seed):
    rng = random.Random(100 + seed)
    host = KernelSequencerHost(num_slots=8, initial_capacity=4)
    docs = ["a", "b", "c", "d", "e"]
    scalars = {d: DocumentSequencer() for d in docs}
    for _tick in range(5):
        streams = {d: random_stream(rng, rng.randrange(12), 6) for d in docs}
        for d, stream in streams.items():
            for raw in stream:
                host.submit(d, raw)
        results = host.flush()
        for d, stream in streams.items():
            want = [scalars[d].ticket(raw) for raw in stream]
            got = results.get(d, [])
            assert len(got) == len(want)
            for i, (g, w) in enumerate(zip(got, want)):
                assert_tickets_equal(g, w, (seed, d, i))


def test_slot_reuse_after_leave():
    host = KernelSequencerHost(num_slots=2, initial_capacity=1)
    s = DocumentSequencer()
    # Cycle 5 distinct clients through 2 slots.
    for i in range(5):
        cid = f"c{i}"
        assert_tickets_equal(host.sequence("doc", join(cid, ts=i)),
                             s.ticket(join(cid, ts=i)), i)
        assert_tickets_equal(host.sequence("doc", op(cid, 1, i)),
                             s.ticket(op(cid, 1, i)), i)
        assert_tickets_equal(host.sequence("doc", leave(cid, ts=i)),
                             s.ticket(leave(cid, ts=i)), i)


def test_unknown_client_nacked_then_can_join():
    host = KernelSequencerHost(num_slots=4)
    s = DocumentSequencer()
    for raw in [op("ghost", 1, 0), join("ghost"), op("ghost", 1, 1),
                leave("nobody"), leave("ghost"), leave("ghost")]:
        assert_tickets_equal(host.sequence("doc", raw), s.ticket(raw), raw)


def test_nack_future_applies_mid_tick():
    # Ops after a control(nackFuture) in the SAME flush tick must NACK.
    host = KernelSequencerHost(num_slots=4)
    s = DocumentSequencer()
    control = RawOperation(client_id=None, type=MessageType.CONTROL,
                           contents={"type": "nackFuture"})
    stream = [join("a"), op("a", 1, 1), control, op("a", 2, 2),
              join("late"), leave("nobody")]
    for raw in stream:
        host.submit("doc", raw)
    got = host.flush()["doc"]
    want = [s.ticket(raw) for raw in stream]
    for i, (g, w) in enumerate(zip(got, want)):
        assert_tickets_equal(g, w, i)
    assert got[3].nack_code == oc.NACK_FUTURE
    assert got[4].nack_code == oc.NACK_FUTURE


def test_leave_rejoin_same_tick_keeps_mapping():
    # Regression: a leave then rejoin of one client inside a single flush
    # tick must keep the slot mapping live (and not leak the device lane).
    host = KernelSequencerHost(num_slots=4)
    s = DocumentSequencer()
    tick1 = [join("b"), join("a"), op("a", 1, 2)]
    tick2 = [leave("b"), leave("a"), join("a")]
    for raw in tick1 + tick2:
        host.submit("doc", raw)
        s.ticket(raw)
    host.flush()
    follow = op("a", 1, 3)
    assert_tickets_equal(host.sequence("doc", follow), s.ticket(follow),
                         "post-rejoin op")
    assert set(host._slots[0]) == {"a"}


def test_unknown_client_with_full_slots_nacks_not_raises():
    # Regression: with every lane taken, an op/leave from an unknown client
    # must produce the scalar's NACK/IGNORED (via the ghost lane), and a
    # further join must grow the slot axis rather than fail.
    host = KernelSequencerHost(num_slots=2)
    s = DocumentSequencer()
    stream = [join("a"), join("b"), op("ghost", 1, 0), leave("nobody"),
              join("c"), op("c", 1, 3)]
    for raw in stream:
        assert_tickets_equal(host.sequence("doc", raw), s.ticket(raw), raw)
    assert host._alloc_slots == 4


def test_restore_more_clients_than_slots():
    s = DocumentSequencer()
    for i in range(20):
        s.ticket(join(f"c{i}", ts=i))
    host = KernelSequencerHost(num_slots=16)
    host.restore("doc", s.checkpoint())
    follow = op("c3", 1, 5)
    assert_tickets_equal(host.sequence("doc", follow), s.ticket(follow),
                         "post-restore")


def test_checkpoint_restore_roundtrip():
    host = KernelSequencerHost(num_slots=4)
    for raw in [join("a"), join("b"), op("a", 1, 1), op("b", 1, 2)]:
        host.sequence("doc", raw)
    cp = host.checkpoint("doc", log_offset=7)
    assert cp.log_offset == 7

    # Restore into a fresh host and into a scalar; both continue identically.
    host2 = KernelSequencerHost(num_slots=4)
    host2.restore("doc", cp)
    scalar = DocumentSequencer.restore(cp)
    for raw in [op("a", 2, 3), leave("b"), op("a", 3, 4)]:
        assert_tickets_equal(host2.sequence("doc", raw), scalar.ticket(raw),
                             raw)


def test_bad_timestamp_rejected_before_mutation():
    host = KernelSequencerHost(num_slots=4)
    host.sequence("doc", join("a"))
    with pytest.raises(ValueError):
        host.submit("doc", op("a", 1, 1, ts=2**40))  # epoch-ms mistake
    # Host is not poisoned: normal flow continues.
    s = DocumentSequencer()
    s.ticket(join("a"))
    assert_tickets_equal(host.sequence("doc", op("a", 1, 1)),
                         s.ticket(op("a", 1, 1)), "after rejection")


def test_sync_call_drains_pending_first():
    # A sequence() call may not overtake ops queued via submit().
    host = KernelSequencerHost(num_slots=4)
    s = DocumentSequencer()
    host.sequence("doc", join("a"))
    s.ticket(join("a"))
    host.submit("doc", op("a", 1, 1))
    want_queued = s.ticket(op("a", 1, 1))
    got_leave = host.sequence("doc", leave("a"))
    want_leave = s.ticket(leave("a"))
    assert_tickets_equal(got_leave, want_leave, "leave after drain")
    assert want_queued.seq < got_leave.seq


def test_restore_preserves_client_timeout():
    s = DocumentSequencer(client_timeout_ms=100)
    s.ticket(join("a", ts=0))
    host = KernelSequencerHost(num_slots=4)
    host.restore("doc", s.checkpoint())
    assert host.idle_clients(now=500) == [("doc", "a")]


def test_min_one_slot_even_if_zero_requested():
    host = KernelSequencerHost(num_slots=0)
    s = DocumentSequencer()
    for raw in [join("a"), op("a", 1, 1), join("b"), op("b", 1, 2)]:
        assert_tickets_equal(host.sequence("doc", raw), s.ticket(raw), raw)


def test_idle_clients_across_docs():
    host = KernelSequencerHost(num_slots=4)
    host.sequence("d1", join("a", ts=0))
    host.sequence("d1", join("b", ts=0))
    host.sequence("d2", join("c", ts=0))
    host.sequence("d1", op("b", 1, 1, ts=900))
    idle = set(host.idle_clients(now=1000, timeout_ms=500))
    assert idle == {("d1", "a"), ("d2", "c")}


def test_e2e_server_on_kernel_sequencer():
    """The full client stack over LocalCollabServer runs identically on the
    device-kernel sequencer and the scalar default."""
    from fluidframework_tpu.dds.map import SharedMap
    from fluidframework_tpu.drivers.local_driver import LocalDocumentService
    from fluidframework_tpu.runtime.container import Container

    def run(server):
        c1 = Container.create_detached(LocalDocumentService(server, "doc"))
        ds1 = c1.runtime.create_datastore("default")
        m1 = ds1.create_channel("root", SharedMap.channel_type)
        c1.attach()
        c2 = Container.load(LocalDocumentService(server, "doc"))
        m2 = c2.runtime.get_datastore("default").get_channel("root")
        m1.set("x", 1)
        m2.set("y", 2)
        m1.set("x", 3)
        m2.delete("y")
        assert c1.summarize() == c2.summarize()
        return dict(m1.items()), dict(m2.items())

    host = KernelSequencerHost(num_slots=8)
    a1, a2 = run(LocalCollabServer(
        sequencer_factory=host.document_factory()))
    b1, b2 = run(LocalCollabServer())
    assert a1 == a2 == b1 == b2 == {"x": 3}
