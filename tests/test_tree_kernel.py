"""Differential test: batched tree kernel vs scalar Transaction semantics,
including device-side sibling ordering and constraint validation."""

import random

import numpy as np
import pytest

from fluidframework_tpu.dds.tree_core import (
    ROOT_ID, Transaction, TreeSnapshot, VALID,
)
from fluidframework_tpu.ops import tree_kernel as tk


def _trait_label(op):
    return f"t{op.get('trait', 0)}"


def scalar_apply(snapshot, op_dicts, slot_names):
    """Apply kernel-shaped ops through the scalar Transaction; returns
    (snapshot, applied flags)."""
    applied = []
    for op in op_dicts:
        name = slot_names[op.get("node", 0)]
        kind = op["kind"]
        if kind == tk.TREE_SET_VALUE:
            changes = [{"type": "set_value", "node": name,
                        "payload": op["payload"]}]
        elif kind == tk.TREE_DETACH:
            changes = [{"type": "detach", "source": {
                "start": {"referenceSibling": name, "side": "before"},
                "end": {"referenceSibling": name, "side": "after"}}}]
        elif kind == tk.TREE_CONSTRAINT_EXISTS:
            changes = [{"type": "constraint", "range": {
                "start": {"referenceSibling": name, "side": "before"},
                "end": {"referenceSibling": name, "side": "after"}}}]
        elif kind == tk.TREE_CONSTRAINT_COUNT:
            # Scalar analog computed directly: trait child count equality.
            parent = slot_names[op["parent"]]
            count = (len(snapshot.get(parent).traits.get(_trait_label(op),
                                                         ()))
                     if snapshot.has(parent) else None)
            applied.append(count is not None and count == op["payload"])
            continue
        else:
            if kind in (tk.TREE_INSERT_BEFORE, tk.TREE_INSERT_AFTER):
                place = {"referenceSibling": slot_names[op["parent"]],
                         "side": "before" if kind == tk.TREE_INSERT_BEFORE
                         else "after"}
            else:
                place = {"referenceTrait": {
                    "parent": slot_names[op["parent"]],
                    "label": _trait_label(op)},
                    "side": "start" if kind == tk.TREE_INSERT_START
                    else "end"}
            changes = [
                {"type": "build",
                 "source": [{"id": name, "definition": "n",
                             "payload": op["payload"]}],
                 "destination": f"b-{name}-{len(applied)}"},
                {"type": "insert", "source": f"b-{name}-{len(applied)}",
                 "destination": place},
            ]
        txn = Transaction(snapshot)
        ok = txn.apply_edit({"id": "e", "changes": changes}) == VALID
        if ok:
            snapshot = txn.snapshot
        applied.append(ok)
    return snapshot, applied


def assert_state_matches(state, d, snapshot, slot_names, ctx):
    """Topology, payload AND sibling order equality vs the scalar."""
    n_slots = state.exists.shape[1]
    exists = np.asarray(state.exists[d])
    payload = np.asarray(state.payload[d])
    parent = np.asarray(state.parent[d])
    trait = np.asarray(state.trait[d])
    for slot in range(n_slots):
        name = slot_names[slot]
        assert bool(exists[slot]) == snapshot.has(name), (*ctx, slot)
        if exists[slot] and slot != 0:
            node = snapshot.get(name)
            assert node.payload == int(payload[slot]) or (
                node.payload is None and payload[slot] == 0)
            assert slot_names[int(parent[slot])] == node.parent[0]
            assert f"t{int(trait[slot])}" == node.parent[1]
    # Sibling order within every live (parent, trait) pair.
    for slot in range(n_slots):
        if not exists[slot]:
            continue
        node = snapshot.get(slot_names[slot])
        for label, children in node.traits.items():
            got = tk.trait_order(state, d, slot, int(label[1:]))
            assert [slot_names[s] for s in got] == children, \
                (*ctx, slot, label)


@pytest.mark.parametrize("seed", range(4))
def test_tree_kernel_matches_scalar(seed):
    rng = random.Random(seed)
    n_docs, n_slots, k, ticks = 3, 24, 12, 4
    slot_names = {0: ROOT_ID, **{i: f"s{i}" for i in range(1, n_slots)}}

    state = tk.init_state(n_docs, n_slots)
    snapshots = [TreeSnapshot() for _ in range(n_docs)]
    all_applied_scalar = [[] for _ in range(n_docs)]
    all_applied_kernel = [[] for _ in range(n_docs)]

    for _tick in range(ticks):
        ops_per_doc = []
        for d in range(n_docs):
            ops = []
            for _ in range(rng.randrange(k + 1)):
                r = rng.random()
                if r < 0.45:
                    ops.append(dict(kind=tk.TREE_INSERT,
                                    node=rng.randrange(1, n_slots),
                                    parent=rng.randrange(n_slots),
                                    payload=rng.randrange(1, 100)))
                elif r < 0.75:
                    ops.append(dict(kind=tk.TREE_SET_VALUE,
                                    node=rng.randrange(n_slots),
                                    payload=rng.randrange(1, 100)))
                else:
                    ops.append(dict(kind=tk.TREE_DETACH,
                                    node=rng.randrange(n_slots)))
            ops_per_doc.append(ops)

        state, out = tk.apply_tick(
            state, tk.make_tree_op_batch(ops_per_doc, n_docs, k))
        for d in range(n_docs):
            snapshots[d], applied = scalar_apply(
                snapshots[d], ops_per_doc[d], slot_names)
            all_applied_scalar[d].extend(applied)
            all_applied_kernel[d].extend(
                np.asarray(out.applied[d][:len(ops_per_doc[d])]).tolist())

    for d in range(n_docs):
        assert all_applied_kernel[d] == all_applied_scalar[d], (seed, d)
        assert_state_matches(state, d, snapshots[d], slot_names, (seed, d))


@pytest.mark.parametrize("seed", range(4))
def test_tree_kernel_sibling_order_fuzz(seed):
    """before/after/start/end placements + traits + constraints must keep
    device sibling order byte-identical to the scalar Transaction."""
    rng = random.Random(1000 + seed)
    n_docs, n_slots, k, ticks = 2, 32, 10, 5
    slot_names = {0: ROOT_ID, **{i: f"s{i}" for i in range(1, n_slots)}}

    state = tk.init_state(n_docs, n_slots)
    snapshots = [TreeSnapshot() for _ in range(n_docs)]

    for tick in range(ticks):
        ops_per_doc = []
        for d in range(n_docs):
            ops = []
            for _ in range(rng.randrange(k + 1)):
                r = rng.random()
                if r < 0.55:
                    kind = rng.choice([
                        tk.TREE_INSERT, tk.TREE_INSERT_START,
                        tk.TREE_INSERT_BEFORE, tk.TREE_INSERT_AFTER])
                    ops.append(dict(kind=kind,
                                    node=rng.randrange(1, n_slots),
                                    parent=rng.randrange(n_slots),
                                    trait=rng.randrange(2),
                                    payload=rng.randrange(1, 100)))
                elif r < 0.7:
                    ops.append(dict(kind=tk.TREE_DETACH,
                                    node=rng.randrange(1, n_slots)))
                elif r < 0.85:
                    ops.append(dict(kind=tk.TREE_CONSTRAINT_EXISTS,
                                    node=rng.randrange(1, n_slots)))
                else:
                    ops.append(dict(kind=tk.TREE_CONSTRAINT_COUNT,
                                    parent=rng.randrange(n_slots),
                                    trait=rng.randrange(2),
                                    payload=rng.randrange(4)))
            ops_per_doc.append(ops)

        state, out = tk.apply_tick(
            state, tk.make_tree_op_batch(ops_per_doc, n_docs, k))
        assert not bool(np.asarray(out.overflow).any()), (seed, tick)
        for d in range(n_docs):
            snapshots[d], applied = scalar_apply(
                snapshots[d], ops_per_doc[d], slot_names)
            got = np.asarray(out.applied[d][:len(ops_per_doc[d])]).tolist()
            assert got == applied, (seed, tick, d)
            assert_state_matches(state, d, snapshots[d], slot_names,
                                 (seed, tick, d))


def test_tree_kernel_order_before_after_chain():
    # Deterministic shape: root -> [s3, s1, s4] in trait t0, s2 in t1.
    state = tk.init_state(1, 8)
    ops = [
        dict(kind=tk.TREE_INSERT, node=1, parent=0, trait=0, payload=1),
        dict(kind=tk.TREE_INSERT_BEFORE, node=3, parent=1, payload=3),
        dict(kind=tk.TREE_INSERT_AFTER, node=4, parent=1, payload=4),
        dict(kind=tk.TREE_INSERT, node=2, parent=0, trait=1, payload=2),
    ]
    state, out = tk.apply_tick(state, tk.make_tree_op_batch([ops], 1, 4))
    assert np.asarray(out.applied).all()
    assert tk.trait_order(state, 0, 0, 0) == [3, 1, 4]
    assert tk.trait_order(state, 0, 0, 1) == [2]


def test_tree_kernel_rank_overflow_flags():
    # Repeated inserts immediately before a FIXED sibling land between it
    # and an ever-closer left neighbour, halving the rank gap each time;
    # once exhausted the op must flag overflow, not corrupt order.
    n = 64
    state = tk.init_state(1, n)
    state, out = tk.apply_tick(state, tk.make_tree_op_batch(
        [[dict(kind=tk.TREE_INSERT, node=1, parent=0, payload=1),
          dict(kind=tk.TREE_INSERT, node=2, parent=0, payload=2)]], 1, 2))
    anchor = 2  # every insert goes between the current left run and slot 2
    overflowed = False
    for slot in range(3, 40):
        state, out = tk.apply_tick(state, tk.make_tree_op_batch(
            [[dict(kind=tk.TREE_INSERT_BEFORE, node=slot, parent=anchor,
                   payload=slot)]], 1, 1))
        if bool(np.asarray(out.overflow)[0, 0]):
            overflowed = True
            assert not bool(np.asarray(out.applied)[0, 0])
            assert not bool(np.asarray(state.exists)[0, slot])
            break
    assert overflowed, "gap never exhausted — overflow path untested"
    # Order of everything that did apply is still strictly maintained.
    order = tk.trait_order(state, 0, 0, 0)
    assert order[0] == 1 and order[-1] == 2
    assert len(order) == len(set(order))


def test_tree_kernel_constraint_count_detach_interplay():
    state = tk.init_state(1, 8)
    ops = [
        dict(kind=tk.TREE_INSERT, node=1, parent=0, payload=1),
        dict(kind=tk.TREE_INSERT, node=2, parent=0, payload=2),
        dict(kind=tk.TREE_CONSTRAINT_COUNT, parent=0, trait=0, payload=2),
        dict(kind=tk.TREE_DETACH, node=1),
        dict(kind=tk.TREE_CONSTRAINT_COUNT, parent=0, trait=0, payload=2),
        dict(kind=tk.TREE_CONSTRAINT_COUNT, parent=0, trait=0, payload=1),
        dict(kind=tk.TREE_CONSTRAINT_EXISTS, node=2),
        dict(kind=tk.TREE_CONSTRAINT_EXISTS, node=1),
        dict(kind=tk.TREE_CONSTRAINT_EXISTS, node=0),  # root: scalar-invalid
        dict(kind=tk.TREE_CONSTRAINT_EXISTS, node=100),  # out of range
    ]
    state, out = tk.apply_tick(state, tk.make_tree_op_batch([ops], 1, 10))
    assert np.asarray(out.applied)[0].tolist() == [
        True, True, True, True, False, True, True, False, False, False]


def test_tree_kernel_detach_deep_chain():
    # Regression: propagation must remove descendants deeper than a few
    # passes (chain of 20).
    depth = 20
    state = tk.init_state(1, depth + 2)
    ops = [dict(kind=tk.TREE_INSERT, node=i, parent=i - 1, payload=i)
           for i in range(1, depth + 1)]
    state, out = tk.apply_tick(
        state, tk.make_tree_op_batch([ops], 1, depth + 2))
    assert bool(np.asarray(out.applied)[0, :depth].all())
    state, out = tk.apply_tick(
        state, tk.make_tree_op_batch([[dict(kind=tk.TREE_DETACH, node=1)]],
                                     1, 2))
    exists = np.asarray(state.exists[0])
    assert exists[0] and not exists[1:depth + 1].any()


def test_tree_kernel_detach_removes_descendants():
    state = tk.init_state(1, 8)
    ops = [
        dict(kind=tk.TREE_INSERT, node=1, parent=0, payload=1),
        dict(kind=tk.TREE_INSERT, node=2, parent=1, payload=2),
        dict(kind=tk.TREE_INSERT, node=3, parent=2, payload=3),
        dict(kind=tk.TREE_DETACH, node=1),
        dict(kind=tk.TREE_SET_VALUE, node=3, payload=9),  # invalid: gone
    ]
    state, out = tk.apply_tick(state, tk.make_tree_op_batch([ops], 1, 8))
    assert np.asarray(state.exists[0]).tolist()[:4] == [True, False, False,
                                                        False]
    assert np.asarray(out.applied[0]).tolist()[:5] == [True, True, True,
                                                       True, False]
