"""Differential test: batched tree kernel vs scalar Transaction semantics."""

import random

import numpy as np
import pytest

from fluidframework_tpu.dds.tree_core import (
    ROOT_ID, Transaction, TreeSnapshot, VALID,
)
from fluidframework_tpu.ops import tree_kernel as tk


def scalar_apply(snapshot, op_dicts, slot_names):
    """Apply kernel-shaped ops through the scalar Transaction; returns
    (snapshot, applied flags)."""
    applied = []
    for op in op_dicts:
        name = slot_names[op["node"]]
        if op["kind"] == tk.TREE_SET_VALUE:
            changes = [{"type": "set_value", "node": name,
                        "payload": op["payload"]}]
        elif op["kind"] == tk.TREE_DETACH:
            changes = [{"type": "detach", "source": {
                "start": {"referenceSibling": name, "side": "before"},
                "end": {"referenceSibling": name, "side": "after"}}}]
        else:
            parent = slot_names[op["parent"]]
            changes = [
                {"type": "build",
                 "source": [{"id": name, "definition": "n",
                             "payload": op["payload"]}],
                 "destination": f"b-{name}-{len(applied)}"},
                {"type": "insert", "source": f"b-{name}-{len(applied)}",
                 "destination": {"referenceTrait": {
                     "parent": parent, "label": "c"}, "side": "end"}},
            ]
        txn = Transaction(snapshot)
        ok = txn.apply_edit({"id": "e", "changes": changes}) == VALID
        if ok:
            snapshot = txn.snapshot
        applied.append(ok)
    return snapshot, applied


@pytest.mark.parametrize("seed", range(4))
def test_tree_kernel_matches_scalar(seed):
    rng = random.Random(seed)
    n_docs, n_slots, k, ticks = 3, 24, 12, 4
    slot_names = {0: ROOT_ID, **{i: f"s{i}" for i in range(1, n_slots)}}

    state = tk.init_state(n_docs, n_slots)
    snapshots = [TreeSnapshot() for _ in range(n_docs)]
    all_applied_scalar = [[] for _ in range(n_docs)]
    all_applied_kernel = [[] for _ in range(n_docs)]

    for _tick in range(ticks):
        ops_per_doc = []
        for d in range(n_docs):
            ops = []
            for _ in range(rng.randrange(k + 1)):
                r = rng.random()
                if r < 0.45:
                    ops.append(dict(kind=tk.TREE_INSERT,
                                    node=rng.randrange(1, n_slots),
                                    parent=rng.randrange(n_slots),
                                    payload=rng.randrange(1, 100)))
                elif r < 0.75:
                    ops.append(dict(kind=tk.TREE_SET_VALUE,
                                    node=rng.randrange(n_slots),
                                    payload=rng.randrange(1, 100)))
                else:
                    ops.append(dict(kind=tk.TREE_DETACH,
                                    node=rng.randrange(n_slots)))
            ops_per_doc.append(ops)

        state, ok = tk.apply_tick(
            state, tk.make_tree_op_batch(ops_per_doc, n_docs, k))
        for d in range(n_docs):
            snapshots[d], applied = scalar_apply(
                snapshots[d], ops_per_doc[d], slot_names)
            all_applied_scalar[d].extend(applied)
            all_applied_kernel[d].extend(
                np.asarray(ok[d][:len(ops_per_doc[d])]).tolist())

    for d in range(n_docs):
        assert all_applied_kernel[d] == all_applied_scalar[d], (seed, d)
        # Topology + payload equality (order is host-side by design).
        exists = np.asarray(state.exists[d])
        payload = np.asarray(state.payload[d])
        parent = np.asarray(state.parent[d])
        for slot in range(n_slots):
            name = slot_names[slot]
            assert bool(exists[slot]) == snapshots[d].has(name), (seed, d, slot)
            if exists[slot] and slot != 0:
                node = snapshots[d].get(name)
                assert node.payload == int(payload[slot]) or (
                    node.payload is None and payload[slot] == 0)
                assert slot_names[int(parent[slot])] == node.parent[0]


def test_tree_kernel_detach_deep_chain():
    # Regression: pointer-doubling must remove descendants deeper than the
    # number of passes (chain of 20 > 16 passes).
    depth = 20
    state = tk.init_state(1, depth + 2)
    ops = [dict(kind=tk.TREE_INSERT, node=i, parent=i - 1, payload=i)
           for i in range(1, depth + 1)]
    state, ok = tk.apply_tick(
        state, tk.make_tree_op_batch([ops], 1, depth + 2))
    assert bool(np.asarray(ok)[0, :depth].all())
    state, ok = tk.apply_tick(
        state, tk.make_tree_op_batch([[dict(kind=tk.TREE_DETACH, node=1)]],
                                     1, 2))
    exists = np.asarray(state.exists[0])
    assert exists[0] and not exists[1:depth + 1].any()


def test_tree_kernel_detach_removes_descendants():
    state = tk.init_state(1, 8)
    ops = [
        dict(kind=tk.TREE_INSERT, node=1, parent=0, payload=1),
        dict(kind=tk.TREE_INSERT, node=2, parent=1, payload=2),
        dict(kind=tk.TREE_INSERT, node=3, parent=2, payload=3),
        dict(kind=tk.TREE_DETACH, node=1),
        dict(kind=tk.TREE_SET_VALUE, node=3, payload=9),  # invalid: gone
    ]
    state, ok = tk.apply_tick(state, tk.make_tree_op_batch([ops], 1, 8))
    assert np.asarray(state.exists[0]).tolist()[:4] == [True, False, False,
                                                        False]
    assert np.asarray(ok[0]).tolist()[:5] == [True, True, True, True, False]
