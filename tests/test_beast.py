"""Literature-corpus merge-tree stress — the beastTest shape.

Reference parity: packages/dds/merge-tree/src/test/beastTest.ts drives
merge-tree with a real text corpus (src/test/literature) — long
documents, word-granular concurrent edits, realistic segment shapes —
rather than synthetic 3-char tokens. Here the corpus is the ~300KB of
real English prose shipped in /usr/share/common-licenses (deterministic
fallback text when absent), streamed word-by-word through concurrent
replicas AND the device merge host.

The always-on case runs a bounded slice; the full corpus tier is
@soak (pytest -m soak).
"""

import os
import random
from pathlib import Path

import pytest

from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.local_server import LocalCollabServer
from fluidframework_tpu.server.merge_host import KernelMergeHost
from tests.test_mergetree import get_string, make_string_doc

_LICENSE_DIR = Path("/usr/share/common-licenses")


def load_corpus(max_chars: int) -> list[str]:
    """Real prose words (licenses ship ~300KB of English); deterministic
    synthetic prose as fallback so the farm never silently no-ops."""
    text = ""
    if _LICENSE_DIR.is_dir():
        for name in sorted(os.listdir(_LICENSE_DIR)):
            p = _LICENSE_DIR / name
            if p.is_file():
                text += p.read_text(errors="ignore") + "\n"
            if len(text) >= max_chars:
                break
    if len(text) < 10_000:
        rng = random.Random(0)
        vocab = ("the quick brown fox jumps over lazy dogs while many "
                 "collaborative editors converge deterministically").split()
        text = " ".join(rng.choice(vocab) for _ in range(max_chars // 6))
    words = text[:max_chars].split()
    assert len(words) > 500
    return words


def _beast_farm(n_clients: int, n_ops: int, corpus_chars: int,
                seed: int = 13) -> None:
    words = load_corpus(corpus_chars)
    rng = random.Random(seed)
    host = KernelMergeHost(flush_threshold=256)
    server = LocalCollabServer(merge_host=host)
    c1 = make_string_doc(server)
    containers = [c1] + [Container.load(LocalDocumentService(server, "doc"))
                         for _ in range(n_clients - 1)]
    strings = [get_string(c) for c in containers]
    cursor = 0

    for step in range(n_ops):
        t = strings[rng.randrange(n_clients)]
        length = len(t.get_text())
        roll = rng.random()
        if roll < 0.6 or length < 64:
            # Stream the NEXT corpus span in (1-8 words, as typed prose).
            n = rng.randrange(1, 9)
            span = " ".join(words[(cursor + i) % len(words)]
                            for i in range(n)) + " "
            cursor += n
            t.insert_text(rng.randrange(length + 1), span)
        elif roll < 0.85:
            start = rng.randrange(length - 16)
            t.remove_text(start, start + rng.randrange(1, 32))
        else:
            start = rng.randrange(length - 8)
            t.annotate_range(start, start + rng.randrange(1, 16),
                             {"style": step % 7})
        if step % 500 == 499:
            texts = [s.get_text() for s in strings]
            assert all(x == texts[0] for x in texts), step
            assert host.text("doc", "default", "text") == texts[0], step

    texts = [s.get_text() for s in strings]
    assert all(x == texts[0] for x in texts)
    assert host.text("doc", "default", "text") == texts[0]
    assert host.stats["overflow_routed"] == 0
    assert host.stats["scalar_ops"] == 0
    assert host.stats["device_ops"] > 0
    # Real-prose sanity: the converged doc is corpus words, not tokens.
    assert len(texts[0]) > 1000
    summaries = [c.summarize() for c in containers[:4]]
    assert all(s == summaries[0] for s in summaries)


def test_beast_corpus_farm_small():
    """Always-on slice: 6 clients streaming real prose concurrently."""
    _beast_farm(n_clients=6, n_ops=1500, corpus_chars=60_000)


@pytest.mark.soak
@pytest.mark.slow
def test_beast_corpus_farm_full():
    """The full-corpus tier (beastTest scale): 16 clients over the whole
    ~300KB corpus with heavier edit volume."""
    _beast_farm(n_clients=16, n_ops=8000, corpus_chars=300_000)
