"""Headless agents: foreman assignments → agent runs → insights in-doc.

Reference parity: server/headless-agent + packages/agents/
intelligence-runner-agent; foreman/lambda.ts help assignment flow.
"""

import pytest

from fluidframework_tpu.agents import (
    HeadlessAgentRunner,
    INSIGHTS_CHANNEL,
    SpellCheckerAgent,
    TextAnalyticsAgent,
)
from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.protocol.messages import MessageType
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.routerlicious import RouterliciousService


def _make_text_doc(service, doc_id, text):
    container = Container.create_detached(
        LocalDocumentService(service, doc_id))
    datastore = container.runtime.create_datastore("default")
    datastore.create_channel("body", SharedString.channel_type)
    container.attach()
    datastore.get_channel("body").insert_text(0, text)
    return container


def _request_help(container, tasks):
    container.delta_manager.submit(MessageType.REMOTE_HELP,
                                   {"tasks": tasks},
                                   container.allocate_client_seq())


class TestHeadlessAgents:
    def test_intelligence_flow_end_to_end(self):
        service = RouterliciousService(help_agents=["runner-1"])
        author = _make_text_doc(service, "doc", "hello world hello again")
        _request_help(author, ["intelligence", "spell"])

        runner = HeadlessAgentRunner(
            service, lambda doc: LocalDocumentService(service, doc),
            [TextAnalyticsAgent(), SpellCheckerAgent()])
        assert runner.run_once() == 2
        assert runner.run_once() == 0  # completed durably, not re-claimed

        # The author sees the insights as ordinary converged state.
        insights = (author.runtime.get_datastore("default")
                    .get_channel(INSIGHTS_CHANNEL))
        analysis = insights.get("intelligence")
        assert analysis["word_count"] == 4
        assert analysis["top_words"][0] == "hello"
        assert insights.get("spell")["misspelled"] == ["again"]

    def test_runner_claims_only_its_assignments(self):
        service = RouterliciousService(help_agents=["a", "b"])
        author = _make_text_doc(service, "doc", "text")
        _request_help(author, ["intelligence", "intelligence"])

        runner_a = HeadlessAgentRunner(
            service, lambda doc: LocalDocumentService(service, doc),
            [TextAnalyticsAgent()], agent_name="a")
        assert runner_a.run_once() == 1  # round-robin gave one to "b"
        assert len(service.help_tasks()) == 1
        assert service.help_tasks()[0]["agent"] == "b"

    def test_unknown_task_left_pending(self):
        service = RouterliciousService()
        author = _make_text_doc(service, "doc", "text")
        _request_help(author, ["translate"])
        runner = HeadlessAgentRunner(
            service, lambda doc: LocalDocumentService(service, doc),
            [TextAnalyticsAgent()])
        assert runner.run_once() == 0
        assert len(service.help_tasks()) == 1

    def test_multi_document_discovery(self):
        service = RouterliciousService()
        a = _make_text_doc(service, "doc-a", "alpha words")
        b = _make_text_doc(service, "doc-b", "beta words words")
        _request_help(a, ["intelligence"])
        _request_help(b, ["intelligence"])
        runner = HeadlessAgentRunner(
            service, lambda doc: LocalDocumentService(service, doc),
            [TextAnalyticsAgent()])
        assert runner.run_once() == 2  # doc_id=None spans all documents
        for container, count in ((a, 2), (b, 3)):
            insights = (container.runtime.get_datastore("default")
                        .get_channel(INSIGHTS_CHANNEL))
            assert insights.get("intelligence")["word_count"] == count


class TestAgentControlAuth:
    def test_agent_control_requires_agent_scope(self, secure_alfred):
        from fluidframework_tpu.drivers.network_driver import (
            NetworkDocumentService)
        from fluidframework_tpu.protocol.messages import ScopeType
        from fluidframework_tpu.server.riddler import sign_token

        port, tenant = secure_alfred
        # No token → rejected; write-scoped token → rejected.
        bare = NetworkDocumentService("127.0.0.1", port, "_agent")
        try:
            with pytest.raises(RuntimeError, match="token"):
                bare.help_tasks()
        finally:
            bare.close()
        writer_token = sign_token("acme", tenant.secret, "_agent",
                                  [ScopeType.WRITE])
        writer = NetworkDocumentService("127.0.0.1", port, "_agent",
                                        token=writer_token)
        try:
            with pytest.raises(RuntimeError, match="scope"):
                writer.help_tasks()
        finally:
            writer.close()
        # Agent-scoped token → allowed.
        agent_token = sign_token("acme", tenant.secret, "_agent",
                                 [ScopeType.AGENT])
        agent = NetworkDocumentService("127.0.0.1", port, "_agent",
                                       token=agent_token)
        try:
            assert agent.help_tasks() == []
        finally:
            agent.close()


class TestAgentsOverNetwork:
    def test_network_control_surface(self, tmp_path):
        import subprocess
        import sys
        import time

        from fluidframework_tpu.drivers.network_driver import (
            NetworkDocumentService)

        proc = subprocess.Popen(
            [sys.executable, "-m", "fluidframework_tpu.server.alfred",
             "--port", "0", "--no-merge-host"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        try:
            line = proc.stdout.readline()
            assert line.startswith("READY "), (line, proc.stderr.read())
            port = int(line.split()[1])

            author_svc = NetworkDocumentService("127.0.0.1", port, "doc")
            author = Container.create_detached(author_svc)
            datastore = author.runtime.create_datastore("default")
            datastore.create_channel("body", SharedString.channel_type)
            author.attach()
            with author_svc.dispatch_lock:
                datastore.get_channel("body").insert_text(0, "hello net")
                _request_help(author, ["intelligence"])

            control = NetworkDocumentService("127.0.0.1", port, "_agent")
            deadline = time.monotonic() + 15
            while not control.help_tasks() and time.monotonic() < deadline:
                time.sleep(0.05)
            runner = HeadlessAgentRunner(
                control,
                lambda doc: NetworkDocumentService("127.0.0.1", port, doc),
                [TextAnalyticsAgent()])
            assert runner.run_once() == 1
            assert control.help_tasks() == []

            # Author converges on the insights written over the wire.
            def insight():
                with author_svc.dispatch_lock:
                    channel = (author.runtime.get_datastore("default")
                               .channels.get(INSIGHTS_CHANNEL))
                    return channel.get("intelligence") if channel else None
            while insight() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            assert insight()["word_count"] == 2
            author_svc.close()
            control.close()
        finally:
            proc.terminate()
            proc.wait(timeout=10)
