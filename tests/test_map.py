"""SharedMap engine tests: pending-local semantics + device-kernel equivalence.

The MiniSequencer mirrors the reference's MockContainerRuntimeFactory
(test-runtime-utils/src/mocks.ts:193): local ops queue centrally, process_all
stamps seq numbers and delivers to every replica. Fuzz asserts (a) all
replicas converge, (b) the batched LWW device kernel over the same sequenced
stream produces the identical map.
"""

import random

import numpy as np
import pytest

from fluidframework_tpu.dds.map_data import MapData
from fluidframework_tpu.ops import map_kernel as mk


class MiniSequencer:
    """Central op queue assigning sequence numbers, delivering to replicas."""

    def __init__(self, replicas: list[MapData]):
        self.replicas = replicas
        self.queue: list[tuple[int, dict, int]] = []  # (origin, op, metadata)
        self.seq = 0
        self.log: list[tuple[int, dict]] = []  # sequenced (seq, op)

    def submit(self, origin: int, op_meta: tuple[dict, int]) -> None:
        op, metadata = op_meta
        self.queue.append((origin, op, metadata))

    def process_all(self) -> None:
        while self.queue:
            origin, op, metadata = self.queue.pop(0)
            self.seq += 1
            self.log.append((self.seq, op))
            for i, replica in enumerate(self.replicas):
                local = i == origin
                replica.process(op, local, metadata if local else None)


def contents(m: MapData) -> dict:
    return dict(m.items())


class TestMapPendingSemantics:
    def test_basic_set_converges(self):
        a, b = MapData(), MapData()
        seq = MiniSequencer([a, b])
        seq.submit(0, a.local_set("k", 1))
        seq.submit(1, b.local_set("k", 2))
        seq.process_all()
        assert contents(a) == contents(b) == {"k": 2}

    def test_pending_local_shadows_remote(self):
        a, b = MapData(), MapData()
        seq = MiniSequencer([a, b])
        seq.submit(0, a.local_set("k", "mine"))
        # Remote set sequenced FIRST, but a's local pending op shadows it
        # until a's own op acks — and a's op wins the total order anyway.
        seq.submit(1, b.local_set("k", "theirs"))
        # Before processing: each replica sees only its local value.
        assert a.get("k") == "mine" and b.get("k") == "theirs"
        seq.process_all()
        assert contents(a) == contents(b)

    def test_remote_clear_preserves_pending_keys(self):
        a, b = MapData(), MapData()
        seq = MiniSequencer([a, b])
        seq.submit(0, a.local_set("stay", 1))
        seq.process_all()
        # b clears; a has a NEW pending key when the clear arrives.
        seq.submit(1, b.local_clear())
        seq.submit(0, a.local_set("pend", 2))
        seq.process_all()
        assert contents(a) == contents(b) == {"pend": 2}

    def test_pending_clear_shadows_key_ops(self):
        a, b = MapData(), MapData()
        seq = MiniSequencer([a, b])
        seq.submit(0, a.local_set("k", 1))
        seq.process_all()
        seq.submit(0, a.local_clear())
        seq.submit(1, b.local_set("k", 9))
        seq.process_all()
        # a's clear sequenced before b's set: set wins on both.
        assert contents(a) == contents(b) == {"k": 9}

    def test_key_ack_under_pending_clear_unshadows_key(self):
        # Regression for a reference bug (mapKernel.ts:617-624): local set,
        # then local clear; after both ack, a remote set on the key must
        # apply — the stale pendingKeys entry must not shadow it forever.
        a, b = MapData(), MapData()
        seq = MiniSequencer([a, b])
        seq.submit(0, a.local_set("k", 1))
        seq.submit(0, a.local_clear())
        seq.process_all()
        seq.submit(1, b.local_set("k", 92))
        seq.process_all()
        assert contents(a) == contents(b) == {"k": 92}

    def test_delete_and_resubmit(self):
        a, b = MapData(), MapData()
        seq = MiniSequencer([a, b])
        seq.submit(0, a.local_set("k", 1))
        seq.process_all()
        op, meta = a.local_delete("k")
        # Simulate reconnect: the op is re-stamped before submission.
        seq.submit(0, a.resubmit(op, meta))
        seq.process_all()
        assert contents(a) == contents(b) == {}


def lww_oracle(log):
    """Plain LWW fold of the sequenced stream."""
    state = {}
    for _seq, op in log:
        if op["type"] == "set":
            state[op["key"]] = op["value"]
        elif op["type"] == "delete":
            state.pop(op["key"], None)
        else:
            state.clear()
    return state


@pytest.mark.parametrize("seed", range(6))
def test_map_fuzz_replicas_and_kernel_converge(seed):
    rng = random.Random(seed)
    n_replicas, n_docs = 4, 3
    keys = [f"key{i}" for i in range(10)]

    docs = []
    for _ in range(n_docs):
        replicas = [MapData() for _ in range(n_replicas)]
        docs.append((replicas, MiniSequencer(replicas)))

    for _round in range(8):
        for replicas, seq in docs:
            for _ in range(rng.randrange(6)):
                origin = rng.randrange(n_replicas)
                r = rng.random()
                replica = replicas[origin]
                if r < 0.55:
                    seq.submit(origin, replica.local_set(
                        rng.choice(keys), rng.randrange(100)))
                elif r < 0.85:
                    seq.submit(origin, replica.local_delete(rng.choice(keys)))
                else:
                    seq.submit(origin, replica.local_clear())
            # Interleave partial delivery across rounds.
            if rng.random() < 0.7:
                seq.process_all()
    for _replicas, seq in docs:
        seq.process_all()

    # (a) replica convergence per doc
    for replicas, _seq in docs:
        reference = contents(replicas[0])
        for replica in replicas[1:]:
            assert contents(replica) == reference

    # (b) device kernel over the same sequenced streams (split into ticks)
    key_slot = {k: i for i, k in enumerate(keys)}
    state = mk.init_state(n_docs, len(keys))
    max_len = max(len(seq.log) for _r, seq in docs)
    tick_size = 16
    for start in range(0, max_len, tick_size):
        ops_per_doc = []
        for _replicas, seq in docs:
            chunk = seq.log[start:start + tick_size]
            enc = []
            for s, op in chunk:
                if op["type"] == "set":
                    enc.append(dict(kind=mk.MAP_SET, slot=key_slot[op["key"]],
                                    value=op["value"], seq=s))
                elif op["type"] == "delete":
                    enc.append(dict(kind=mk.MAP_DELETE,
                                    slot=key_slot[op["key"]], seq=s))
                else:
                    enc.append(dict(kind=mk.MAP_CLEAR, seq=s))
            ops_per_doc.append(enc)
        state = mk.apply_tick(
            state, mk.make_map_op_batch(ops_per_doc, n_docs, tick_size))

    for d, (replicas, seq) in enumerate(docs):
        expected = contents(replicas[0])
        assert expected == lww_oracle(seq.log)
        device = {
            keys[slot]: int(state.value[d, slot])
            for slot in range(len(keys))
            if bool(state.present[d, slot])
        }
        assert device == expected, (seed, d)


def test_map_snapshot_roundtrip():
    a = MapData()
    seq = MiniSequencer([a])
    seq.submit(0, a.local_set("x", [1, 2]))
    seq.submit(0, a.local_set("y", {"n": 3}))
    seq.process_all()
    b = MapData.load(a.snapshot())
    assert contents(b) == contents(a)
    assert b.snapshot() == a.snapshot()


def test_map_kernel_words_path_matches_full_batch():
    """The fused 4-byte/op wire entry must produce the same state as the
    explicit MapOpBatch path for the same op stream."""
    import numpy as np

    rng = np.random.default_rng(7)
    num_docs, k, num_slots, ticks = 16, 32, 32, 4
    state_a = mk.init_state(num_docs, num_slots)
    state_b = mk.init_state(num_docs, num_slots)
    for t in range(ticks):
        kinds = rng.choice([mk.MAP_SET, mk.MAP_DELETE, mk.MAP_CLEAR],
                           p=[0.7, 0.2, 0.1],
                           size=(num_docs, k)).astype(np.uint32)
        slots = rng.integers(0, num_slots, (num_docs, k)).astype(np.uint32)
        values = rng.integers(1, 1 << 20, (num_docs, k)).astype(np.uint32)
        words = kinds | (slots << 2) | (values << 12)
        counts = np.full((num_docs,), k, np.int32)
        base_seq = np.full((num_docs,), t * k, np.int32)
        state_a = mk.apply_tick_words(state_a, words, counts, base_seq)

        ops_per_doc = [
            [dict(kind=int(kinds[d, i]), slot=int(slots[d, i]),
                  value=int(values[d, i]), seq=t * k + i + 1)
             for i in range(k)]
            for d in range(num_docs)]
        state_b = mk.apply_tick(
            state_b, mk.make_map_op_batch(ops_per_doc, num_docs, k))

    for field_a, field_b in zip(state_a, state_b):
        assert (np.asarray(field_a) == np.asarray(field_b)).all()
