"""Pallas sequencer tick kernel: differential tests vs the XLA scan path
(which is itself pinned to the scalar DocumentSequencer oracle)."""

from __future__ import annotations

import random

import numpy as np
import pytest

from fluidframework_tpu.ops import sequencer as seqk
from fluidframework_tpu.ops import sequencer_pallas as seqp
from fluidframework_tpu.protocol.messages import MessageType


def _random_stream(rng: random.Random, n_ops: int, n_clients: int):
    """Mixed op stream exercising joins/leaves/dups/gaps/nacks/noops."""
    ops = []
    cseq = [0] * n_clients
    joined = [False] * n_clients
    for i in range(n_ops):
        r = rng.random()
        if r < 0.12:
            target = rng.randrange(n_clients)
            kind = (MessageType.CLIENT_JOIN if r < 0.08
                    else MessageType.CLIENT_LEAVE)
            ops.append(dict(kind=int(kind), slot=-1, target=target,
                            timestamp=i + 1))
            if kind == MessageType.CLIENT_JOIN:
                joined[target] = True
                cseq[target] = 0
            else:
                joined[target] = False
        elif r < 0.2:
            slot = rng.randrange(n_clients)
            ops.append(dict(kind=int(MessageType.NOOP), slot=slot,
                            client_seq=cseq[slot] + 1, ref_seq=max(0, i - 3),
                            timestamp=i + 1,
                            has_contents=rng.random() < 0.5))
            cseq[slot] += 1
        else:
            slot = rng.randrange(n_clients)
            bump = rng.choice([1, 1, 1, 0, 2])  # dups and gaps
            cseq[slot] += bump
            ops.append(dict(kind=int(MessageType.OPERATION), slot=slot,
                            client_seq=cseq[slot],
                            ref_seq=rng.randrange(max(1, i)) if i else 0,
                            timestamp=i + 1))
    return ops


@pytest.mark.parametrize("seed", range(3))
def test_pallas_sequencer_matches_xla(seed):
    rng = random.Random(seed)
    n_docs = rng.choice([1, 5, 9])
    n_clients = 5
    k = 16
    ticks = 4
    streams = [_random_stream(rng, k * ticks, n_clients)
               for _ in range(n_docs)]

    state_x = seqk.init_state(n_docs, n_clients + 2)
    state_p = state_x
    for t in range(ticks):
        chunk = [s[t * k:(t + 1) * k] for s in streams]
        # ragged ticks: drop a few trailing ops per doc
        chunk = [c[:rng.randrange(len(c) // 2, len(c) + 1)] for c in chunk]
        batch = seqk.make_op_batch(chunk, n_docs, k)
        state_x, tickets_x = seqk.process_batch(state_x, batch)
        state_p, tickets_p = seqp.process_batch_pallas(
            state_p, batch, interpret=seqp.default_interpret())
        for field in seqk.TicketBatch._fields:
            assert np.array_equal(np.asarray(getattr(tickets_x, field)),
                                  np.asarray(getattr(tickets_p, field))), \
                (seed, t, field)
    for field in seqk.SequencerState._fields:
        assert np.array_equal(np.asarray(getattr(state_x, field)),
                              np.asarray(getattr(state_p, field))), \
            (seed, field)
