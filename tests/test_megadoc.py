"""Mega-doc write scale-out (round 15): one document's merge served
from sharded device lanes.

The differential discipline extends to the new tier: sharded (promoted,
L lanes) ≡ single-lane (unpromoted twin) ≡ scalar (the MapData fold of
the materialized records) must be BYTE-IDENTICAL on live + adversarial
streams — converged entries, per-frame ack quads, materialized op
history (seqs/cseqs/refs/MSNs), and the demoted sequencer checkpoint.
The doc-space combiner itself is pinned against the device closed-form
ticket by its own differential test. Tier-1 runs all of this on the
FORCED multi-device CPU mesh (conftest forces platform + an 8-device
host mesh programmatically before first device use — the
jax.config.update route; the JAX_PLATFORMS env var alone does not stick
in this container), so the sequence-parallel tier is exercised by every
CI run, not only where real devices exist.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np
import pytest

from fluidframework_tpu.server.kernel_host import KernelSequencerHost
from fluidframework_tpu.server.megadoc import (
    DocSequencerMirror,
    MegaDocManager,
    fold_map_rows,
    lane_of_writer,
)
from fluidframework_tpu.server.merge_host import KernelMergeHost
from fluidframework_tpu.server.routerlicious import RouterliciousService
from fluidframework_tpu.server.storm import (
    StormController,
    choose_pipeline_depth,
    materialize_storm_records,
)

K = 6  # ops per frame in the fuzz


def build_stack(tmp_path=None, lanes=None, **storm_kw):
    seq = KernelSequencerHost(num_slots=2, initial_capacity=4)
    mh = KernelMergeHost(flush_threshold=10**9)
    kwargs = {}
    if tmp_path is not None:
        from fluidframework_tpu.server.durable_store import (
            DurableMessageBus,
            FileStateStore,
            GitSnapshotStore,
        )
        kwargs["bus"] = DurableMessageBus(os.path.join(tmp_path, "bus"))
        kwargs["store"] = FileStateStore(os.path.join(tmp_path, "state"))
        storm_kw.setdefault("spill_dir", os.path.join(tmp_path, "spill"))
        storm_kw.setdefault("durability", "group")
        storm_kw.setdefault(
            "snapshots", GitSnapshotStore(os.path.join(tmp_path, "git")))
    svc = RouterliciousService(merge_host=mh, batched_deli_host=seq,
                               auto_pump=False, idle_check_interval=10**9,
                               **kwargs)
    svc._clock = lambda: 5  # deterministic ts: clu planes must compare
    storm = StormController(svc, seq, mh, flush_threshold_docs=10**9,
                            **storm_kw)
    mgr = MegaDocManager(storm, default_lanes=lanes) if lanes else None
    return svc, storm, seq, mh, mgr


def storm_words(seed, r, w, k=K, slots=16):
    rng = np.random.default_rng([seed, r, w])
    kinds = rng.choice([0, 0, 0, 1], size=k).astype(np.uint32)
    kslots = rng.integers(0, slots, k).astype(np.uint32)
    vals = rng.integers(0, 1 << 20, k).astype(np.uint32)
    return (kinds | (kslots << 2) | (vals << 12)).astype(np.uint32)


# -- the combiner's scalar ticket vs the device closed form -------------------


def test_mirror_matches_device_storm_tickets():
    """DocSequencerMirror is an EXACT scalar twin of storm_tickets:
    random batches (fresh / dup / overlap / gap / stale-ref) through
    both, every outcome and every client plane equal."""
    import jax.numpy as jnp

    from fluidframework_tpu.ops import sequencer as seqk

    rng = np.random.default_rng(11)
    n_clients = 3
    state = seqk.init_state(1, n_clients)
    # Join the clients the way a sequenced CLIENT_JOIN leaves the row.
    state = state._replace(
        active=state.active.at[0, :].set(True),
        cref=state.cref.at[0, :].set(0))
    mirror = DocSequencerMirror()
    for c in range(n_clients):
        # Adoption IS join-at-msn semantics; msn is 0 here, matching
        # the cref=0 the device join left.
        mirror.adopt(f"c{c}", 1, clu=0)
    next_cseq = [1] * n_clients
    for step in range(80):
        c = int(rng.integers(n_clients))
        kind = rng.choice(["fresh", "dup", "overlap", "gap", "stale"],
                          p=[0.55, 0.15, 0.1, 0.1, 0.1])
        n = int(rng.integers(1, 5))
        if kind == "fresh":
            cseq0 = next_cseq[c]
        elif kind == "dup":
            cseq0 = max(1, next_cseq[c] - n)
        elif kind == "overlap":
            cseq0 = max(1, next_cseq[c] - 1)
        elif kind == "gap":
            cseq0 = next_cseq[c] + 2
        else:
            cseq0 = next_cseq[c]
        ref = 0 if kind == "stale" else int(rng.integers(1, 4))
        ts = 100 + step
        state, dups, n_seq, msn = seqk.storm_tickets(
            state, jnp.asarray([c]), jnp.asarray([cseq0]),
            jnp.asarray([ref]), jnp.asarray([ts]), jnp.asarray([n]))
        dec = mirror.decide(f"c{c}", cseq0, ref, n, ts)
        assert dec.n_seq == int(np.asarray(n_seq)[0]), (step, kind)
        assert dec.msn == int(np.asarray(msn)[0]), (step, kind)
        assert mirror.seq == int(np.asarray(state.seq)[0]), (step, kind)
        for cc in range(n_clients):
            w = mirror.writers[f"c{cc}"]
            assert w.cseq == int(np.asarray(state.cseq)[0, cc]), (step, cc)
            assert w.ref == int(np.asarray(state.cref)[0, cc]), (step, cc)
            assert w.nack == bool(np.asarray(state.cnack)[0, cc]), (step,
                                                                    cc)
        assert mirror.last_sent_msn == int(
            np.asarray(state.last_sent_msn)[0])
        # Track what the client would resend next (sequenced advances).
        if dec.n_seq > 0:
            next_cseq[c] = cseq0 + n
    assert mirror.seq > 0  # the stream actually sequenced work


# -- the serving-level differential fuzz --------------------------------------


def _adversarial_frames(seed, writers, rounds):
    """Per-(round, writer) frame plans: mostly fresh contiguous batches,
    plus verbatim dup resends, partial-overlap resends, gaps (NACK), and
    one stale-ref (refseq-below-MSN mark; the marked client retires, as
    the device contract dictates)."""
    rng = np.random.default_rng(seed)
    plans = []
    cseqs = {w: 1 for w in range(writers)}
    prev = {}
    stale_used = False
    for r in range(rounds):
        row = []
        for w in range(writers):
            action = rng.choice(["fresh", "fresh", "fresh", "dup",
                                 "overlap", "gap", "stale"])
            words = storm_words(seed, r, w)
            if action == "dup" and w in prev:
                cseq0, words = prev[w]
                ref = 1
            elif action == "overlap" and w in prev and cseqs[w] > K:
                p_cseq0, p_words = prev[w]
                cseq0 = p_cseq0 + K - 2
                words = np.concatenate([p_words[-2:], words])[:K + 2]
                cseqs[w] = cseq0 + len(words)
                ref = 1
            elif action == "gap":
                cseq0 = cseqs[w] + 3
                ref = 1  # whole batch gap-rejected; cseq unchanged
            elif action == "stale" and not stale_used and r > 1:
                stale_used = True
                cseq0 = cseqs[w]
                ref = 0  # below MSN once anything sequenced -> mark
            else:
                cseq0 = cseqs[w]
                cseqs[w] = cseq0 + K
                ref = 1
                prev[w] = (cseq0, words)
            row.append((w, cseq0, ref, words))
        plans.append(row)
    return plans


def _play(plans, writers, mega_lanes):
    svc, storm, seq, mh, mgr = build_stack(lanes=mega_lanes)
    doc = "hot"
    clients = {w: svc.connect(doc, lambda m: None).client_id
               for w in range(writers)}
    svc.pump()
    if mega_lanes:
        mgr.promote(doc, lanes=mega_lanes)
    acks = {}
    for r, row in enumerate(plans):
        for w, cseq0, ref, words in row:
            storm.submit_frame(
                lambda p, key=(r, w): acks.__setitem__(key, p),
                {"rid": f"{r}-{w}",
                 "docs": [[doc, clients[w], int(cseq0), int(ref),
                           len(words)]]},
                memoryview(np.ascontiguousarray(words).tobytes()))
        storm.flush()
    storm.flush()
    if mega_lanes:
        entries = mgr.map_entries(doc)
        mgr.demote(doc)
        assert mh.map_entries(doc, storm.datastore, storm.channel) \
            == entries  # the demotion fold IS the promoted read
    else:
        entries = mh.map_entries(doc, storm.datastore, storm.channel)
    recs = storm.records_overlapping(doc, 0)
    history = [(m.sequence_number, m.client_sequence_number, m.client_id,
                m.minimum_sequence_number, m.reference_sequence_number,
                repr(m.contents["contents"]["contents"]))
               for m in materialize_storm_records(
                   recs, storm.datastore, storm.channel,
                   blob_reader=storm.read_tick_words)]
    cp = dataclasses.asdict(seq.checkpoint(doc))
    ack_rows = {key: np.asarray(a.rows).tolist() for key, a in acks.items()}
    # Scalar oracle: fold the materialized history through the scalar
    # MapData state machine — converged entries must agree.
    from fluidframework_tpu.dds.map_data import MapData
    data = MapData()
    for m in materialize_storm_records(recs, storm.datastore,
                                       storm.channel,
                                       blob_reader=storm.read_tick_words):
        data.process(m.contents["contents"]["contents"], False, None)
    assert dict(data.items()) == entries
    return entries, ack_rows, history, cp, storm.stats["ticks"]


@pytest.mark.parametrize("seed", [0, 1])
def test_sharded_equals_single_lane_equals_scalar(seed):
    """THE acceptance bar: promoted (L lanes) ≡ unpromoted ≡ scalar,
    byte-identical converged entries / ack quads / materialized history
    / demoted checkpoint on live + adversarial streams — and the
    promoted run takes FEWER ticks (the write path genuinely widened)."""
    writers, rounds, lanes = 5, 6, 2
    plans = _adversarial_frames(100 + seed, writers, rounds)
    e1, a1, h1, cp1, t1 = _play(plans, writers, mega_lanes=None)
    e2, a2, h2, cp2, t2 = _play(plans, writers, mega_lanes=lanes)
    assert e1 == e2
    assert a1 == a2
    assert h1 == h2
    assert cp1 == cp2
    assert t2 < t1, (t2, t1)  # lanes combined writer frames into ticks


def test_zero_op_outcomes_synthesize_identical_acks():
    """Gap / dup / stale-ref frames never touch a lane; their
    synthesized ack quads equal the single-lane device quads (including
    the refseq mark's MSN) — covered broadly by the fuzz, pinned
    narrowly here."""
    writers = 2
    plans = [
        [(0, 1, 1, storm_words(1, 0, 0)), (1, 1, 1, storm_words(1, 0, 1))],
        [(0, 1 + K, 1, storm_words(1, 1, 0)),   # fresh
         (1, 1, 1, storm_words(1, 0, 1))],      # verbatim dup resend
        [(0, 1 + 2 * K, 0, storm_words(1, 2, 0)),  # stale ref -> mark
         (1, 1 + K + 5, 1, storm_words(1, 2, 1))],  # gap -> reject
    ]
    e1, a1, h1, cp1, _ = _play(plans, writers, mega_lanes=None)
    e2, a2, h2, cp2, _ = _play(plans, writers, mega_lanes=2)
    assert (e1, a1, h1, cp1) == (e2, a2, h2, cp2)
    # The dup and the gap really did zero-op (n_seq == 0 quads).
    assert a1[(1, 1)][0][0] == 0
    assert a1[(2, 1)][0][0] == 0
    assert a1[(2, 0)][0][0] == 0  # the stale-ref mark


# -- multi-lane CPU mesh smoke (the tier-1 satellite) -------------------------


def test_sharded_tier_runs_on_forced_multidevice_mesh(cpu_mesh_devices):
    """Tier-1 exercises the sequence-parallel tier on the FORCED
    8-device CPU mesh (programmatic jax.config platform override +
    host-device-count flag set before first device use — see conftest;
    the env-var-only route hangs in this container). One promoted doc's
    text row serves from a mesh-sharded pool and stays byte-identical
    to the unpromoted twin through promote -> serve -> demote."""
    import random

    import jax

    from fluidframework_tpu.ops.mergetree_sharded import make_seg_mesh
    from tests.test_mergetree import get_string, make_string_doc, random_edit

    assert len(jax.devices()) >= 8, "forced host mesh missing"
    mesh = make_seg_mesh(cpu_mesh_devices)

    def play(promote: bool) -> tuple[str, dict]:
        from fluidframework_tpu.server.local_server import LocalCollabServer
        host = KernelMergeHost(merge_slots=16, seg_mesh=mesh,
                               sharded_slot_threshold=4096)
        server = LocalCollabServer(merge_host=host)
        c1 = make_string_doc(server, "mega")
        rng = random.Random(9)
        for _ in range(40):
            random_edit(rng, get_string(c1))
        host.flush()
        key = next(iter(host._merge_rows))
        if promote:
            host.promote_merge_row(key)
            assert host.is_mega_row(key)
            row = host._merge_rows[key]
            devices = {s.device for s in
                       row.pool.state.length.addressable_shards}
            assert len(devices) == len(cpu_mesh_devices)
        for _ in range(30):
            random_edit(rng, get_string(c1))
        host.flush()
        text_mid = host.text("mega", "default", "text")
        if promote:
            assert host.demote_merge_row(key)
            assert not host.is_mega_row(key)
            assert host.text("mega", "default", "text") == text_mid
        for _ in range(10):
            random_edit(rng, get_string(c1))
        host.flush()
        return host.text("mega", "default", "text"), dict(host.stats)

    t_twin, _ = play(False)
    t_mega, stats = play(True)
    assert t_mega == t_twin
    assert stats["megadoc_promotions"] == 1
    assert stats["megadoc_demotions"] == 1


# -- adaptive pipeline depth (satellite) --------------------------------------


def _attribution(commit_ms, dispatch_ms, ticks=16):
    return {"_window": {"ticks": ticks},
            "wal_commit_wait": {"total_ms": commit_ms},
            "device_dispatch": {"total_ms": dispatch_ms}}


def test_choose_pipeline_depth_pins_both_regimes():
    """BENCH_r14's two regimes: commit-wait commensurate with dispatch
    (the 10k shape: 0.52 vs 0.41 shares) -> overlap; fsync cheap (the
    2048 shape) -> serial. The band between is hysteresis, and a short
    ledger window never flips the depth."""
    # 10k-doc regime: commit 0.52 / dispatch 0.41 of the tick.
    assert choose_pipeline_depth(_attribution(520.0, 410.0), 0) == 1
    assert choose_pipeline_depth(_attribution(520.0, 410.0), 2) == 2
    # 2048-doc regime: fsync far below the dispatch -> serial wins.
    assert choose_pipeline_depth(_attribution(20.0, 400.0), 1) == 0
    assert choose_pipeline_depth(_attribution(20.0, 400.0), 0) == 0
    # Hysteresis band: keep whatever is running.
    assert choose_pipeline_depth(_attribution(150.0, 400.0), 0) == 0
    assert choose_pipeline_depth(_attribution(150.0, 400.0), 1) == 1
    # Too little evidence: no change.
    assert choose_pipeline_depth(_attribution(520.0, 410.0, ticks=3),
                                 0) == 0
    assert choose_pipeline_depth({}, 1) == 1


def test_auto_depth_adapts_from_observed_ledger(tmp_path):
    """pipeline_depth="auto" re-decides from the REAL ledger at the
    adaptation cadence: a run whose commit-wait stays trivial adapts
    down to the serial tick."""
    svc, storm, seq, mh, _ = build_stack(str(tmp_path),
                                         pipeline_depth="auto")
    assert storm.pipeline_depth == 1 and storm._auto_depth
    storm.depth_adapt_every = 1
    doc = "d"
    client = svc.connect(doc, lambda m: None).client_id
    svc.pump()
    for r in range(12):
        storm.submit_frame(None, {"rid": r,
                                  "docs": [[doc, client, 1 + r * 4, 1, 4]]},
                           memoryview(storm_words(3, r, 0, k=4).tobytes()))
        storm.flush()
    # Tiny ticks on tmpfs: the fsync is far below the dispatch, so the
    # auto policy must have settled on the serial fallback.
    assert storm.pipeline_depth == 0
    att = storm.ledger.attribution()
    assert att["_window"]["ticks"] >= 8
    storm._group_wal.close()


def test_set_pipeline_depth_settles_inflight(tmp_path):
    svc, storm, seq, mh, _ = build_stack(str(tmp_path), pipeline_depth=2)
    doc = "d"
    client = svc.connect(doc, lambda m: None).client_id
    svc.pump()
    storm.submit_frame(None, {"rid": 0, "docs": [[doc, client, 1, 1, 4]]},
                       memoryview(storm_words(4, 0, 0, k=4).tobytes()))
    storm._flush_round()
    assert storm._inflight
    storm.set_pipeline_depth(0)
    assert not storm._inflight
    assert storm.pipeline_depth == 0
    assert mh.metrics.gauge("storm.pipeline.depth").value == 0
    storm.flush()
    storm._group_wal.close()


# -- auto promotion / demotion ------------------------------------------------


def test_auto_promotion_and_idle_demotion():
    svc, storm, seq, mh, mgr = build_stack(lanes=2)
    mgr.writer_threshold = 3
    mgr.writer_window_ticks = 1
    mgr.demote_idle_ticks = 3
    hot, cold = "hot", "side"
    hclients = {w: svc.connect(hot, lambda m: None).client_id
                for w in range(3)}
    sclient = svc.connect(cold, lambda m: None).client_id
    svc.pump()
    cseqs = {w: 1 for w in range(3)}
    for r in range(2):
        for w in range(3):
            storm.submit_frame(None, {
                "rid": f"{r}{w}",
                "docs": [[hot, hclients[w], cseqs[w], 1, K]]},
                memoryview(storm_words(5, r, w).tobytes()))
            cseqs[w] += K
        storm.flush()
    assert mgr.is_promoted(hot)  # the writer window crossed the bar
    # Idle: only the side doc ticks from here — the hot doc cools and
    # demotes after demote_idle_ticks harvests.
    sq = 1
    for r in range(8):
        storm.submit_frame(None, {
            "rid": f"s{r}", "docs": [[cold, sclient, sq, 1, K]]},
            memoryview(storm_words(6, r, 0).tobytes()))
        sq += K
        storm.flush()
        if not mgr.is_promoted(hot):
            break
    assert not mgr.is_promoted(hot)
    assert mgr.has_history(hot)  # records still translate
    m = mh.metrics
    assert m.counter("megadoc.promotions").value == 1
    assert m.counter("megadoc.demotions").value == 1


# -- durable lifecycle: snapshot + WAL replay ---------------------------------


def test_recover_replays_promoted_lifecycle(tmp_path):
    """Crash after promoted serving: a fresh stack over the same spill
    dir restores the snapshot (combiner mirrors + lane rows included),
    replays the WAL tail (control records re-promote at the identical
    point), and converges to the live run's entries and combiner
    state."""
    writers = 4
    d = str(tmp_path)
    svc, storm, seq, mh, mgr = build_stack(d, lanes=2)
    doc = "hot"
    clients = {w: svc.connect(doc, lambda m: None).client_id
               for w in range(writers)}
    svc.pump()
    storm.checkpoint()
    mgr.promote(doc, lanes=2)
    cseqs = {w: 1 for w in range(writers)}
    for r in range(3):
        for w in range(writers):
            storm.submit_frame(None, {
                "rid": f"{r}{w}",
                "docs": [[doc, clients[w], cseqs[w], 1, K]]},
                memoryview(storm_words(8, r, w).tobytes()))
            cseqs[w] += K
        storm.flush()
    storm.checkpoint()  # snapshot WITH the promoted combiner state
    for r in range(3, 5):
        for w in range(writers):
            storm.submit_frame(None, {
                "rid": f"{r}{w}",
                "docs": [[doc, clients[w], cseqs[w], 1, K]]},
                memoryview(storm_words(8, r, w).tobytes()))
            cseqs[w] += K
        storm.flush()
    live_entries = mgr.map_entries(doc)
    live_state = mgr.export_state()
    storm._group_wal.close()

    svc2, storm2, seq2, mh2, mgr2 = build_stack(d, lanes=2)
    info = storm2.recover()
    assert info["restored_from"] is not None
    assert info["replayed_ticks"] > 0
    assert mgr2.map_entries(doc) == live_entries
    assert mgr2.export_state() == live_state
    mgr2.demote(doc)
    assert mh2.map_entries(doc, storm2.datastore, storm2.channel) \
        == live_entries
    storm2._group_wal.close()


def test_residency_refuses_evicting_promoted_doc(tmp_path):
    from fluidframework_tpu.server.residency import (
        EvictionRefused,
        ResidencyManager,
    )
    svc, storm, seq, mh, mgr = build_stack(str(tmp_path), lanes=2)
    res = ResidencyManager(storm, max_resident=8, idle_evict_s=1e9,
                           hydration_rate_per_s=1e9)
    doc = "hot"
    client = svc.connect(doc, lambda m: None).client_id
    svc.pump()
    storm.checkpoint()
    mgr.promote(doc, lanes=2)
    storm.submit_frame(None, {"rid": 0, "docs": [[doc, client, 1, 1, K]]},
                       memoryview(storm_words(9, 0, 0).tobytes()))
    storm.flush()
    with pytest.raises(EvictionRefused, match="mega-promoted"):
        res.evict(doc)
    mgr.demote(doc)
    storm._group_wal.close()


# -- the cross-lane fold ------------------------------------------------------


def test_fold_map_rows_delete_and_clear_semantics():
    """Tombstones and clears fold exactly like the single-lane LWW law:
    the latest EVENT wins; a delete winner renders absent; clears erase
    everything older across every lane."""
    def src(present, value, vseq, cleared=-1):
        return {"present": np.asarray(present, bool),
                "value": np.asarray(value, np.int64),
                "vseq": np.asarray(vseq, np.int64),
                "cleared_seq": cleared}

    # Lane B's delete (vseq 7) beats lane A's older set (vseq 3).
    fold = fold_map_rows([
        src([True, True], [10, 20], [3, 5]),
        src([False, False], [0, 0], [7, -1]),
    ])
    assert fold["present"].tolist() == [False, True]
    assert fold["value"].tolist() == [0, 20]
    # A clear at doc seq 6 in lane B erases lane A's older sets but not
    # its newer one.
    fold = fold_map_rows([
        src([True, True], [10, 20], [3, 9]),
        src([False, False], [0, 0], [-1, -1], cleared=6),
    ])
    assert fold["present"].tolist() == [False, True]
    assert fold["value"].tolist() == [0, 20]


def test_lane_of_writer_is_stable():
    assert lane_of_writer("client-1", 4) == lane_of_writer("client-1", 4)
    lanes = {lane_of_writer(f"client-{i}", 4) for i in range(64)}
    assert lanes == set(range(4))  # the hash actually spreads writers


def test_refnack_mark_control_orders_after_inflight_ticks(tmp_path):
    """pipeline_depth=2 regression: a refseq mark decided while an
    earlier tick is still IN FLIGHT must journal its control record
    AFTER that tick's WAL record (the combiner settles the pipeline
    before appending), or replay applies the mark ahead of ops it
    logically followed and the recovered mirror diverges."""
    d = str(tmp_path)
    svc, storm, seq, mh, mgr = build_stack(d, lanes=2, pipeline_depth=2)
    doc = "hot"
    c1 = svc.connect(doc, lambda m: None).client_id
    c2 = svc.connect(doc, lambda m: None).client_id
    svc.pump()
    storm.checkpoint()
    mgr.promote(doc, lanes=2)
    # c1 holds the MSN at 1, c2 refs ahead at 2 — so tick A below MOVES
    # the MSN, and the mark's captured value depends on whether tick A
    # was applied before it (the ordering under test).
    for rid, c, ref in ((0, c1, 1), (1, c2, 2)):
        storm.submit_frame(None, {"rid": rid,
                                  "docs": [[doc, c, 1, ref, K]]},
                           memoryview(storm_words(21, rid, 0).tobytes()))
    storm.flush()
    assert mgr.docs[doc].mirror.msn == 1
    # Tick A: c1 re-refs at 2 (MSN 1 -> 2), dispatches, and STAYS in
    # flight (depth 2: the harvest-first loop settles nothing yet).
    storm.submit_frame(None, {"rid": 2,
                              "docs": [[doc, c1, 1 + K, 2, K]]},
                       memoryview(storm_words(22, 0, 0).tobytes()))
    storm._flush_round()
    assert storm._inflight, "tick A should still be in flight"
    assert mgr.docs[doc].mirror.msn == 2
    # Stale-ref frame from c2 (1 < MSN 2): the refnack mark captures
    # cref = MSN = 2 — but only if tick A's record precedes it on
    # replay.
    storm.submit_frame(None, {"rid": 3,
                              "docs": [[doc, c2, 1 + K, 1, K]]},
                       memoryview(storm_words(22, 1, 0).tobytes()))
    storm._flush_round()
    storm.flush()
    live_state = mgr.export_state()
    assert live_state["docs"][doc]["mirror"]["writers"][c2][3] == 1  # nacked
    storm._group_wal.close()
    svc2, storm2, seq2, mh2, mgr2 = build_stack(d, lanes=2,
                                                pipeline_depth=2)
    storm2.recover()
    assert mgr2.export_state() == live_state
    storm2._group_wal.close()


def test_same_cohort_refnack_mark_replays_identically(tmp_path):
    """Same-COHORT ordering regression: a refseq mark journals BEFORE
    its cohort's tick record, yet an earlier frame in that very cohort
    may have moved the MSN the mark captured. The mark control is
    self-describing (it carries the captured cref), so replay lands the
    exact live value regardless of position."""
    from fluidframework_tpu.server.megadoc import lane_of_writer

    d = str(tmp_path)
    svc, storm, seq, mh, mgr = build_stack(d, lanes=2)
    doc = "hot"
    clients = [svc.connect(doc, lambda m: None).client_id
               for _ in range(4)]
    svc.pump()
    storm.checkpoint()
    mgr.promote(doc, lanes=2)
    c1 = clients[0]
    c2 = next(c for c in clients[1:]
              if lane_of_writer(c, 2) != lane_of_writer(c1, 2))
    # Round 0: EVERY writer sequences (an idle writer's join-time cref
    # would pin the MSN at 0); c1 refs at 1 and becomes the MSN holder,
    # everyone else at 2.
    for i, c in enumerate(clients):
        storm.submit_frame(None, {
            "rid": i, "docs": [[doc, c, 1, 1 if c == c1 else 2, K]]},
            memoryview(storm_words(31, i, 0).tobytes()))
    storm.flush()
    assert mgr.docs[doc].mirror.msn == 1
    # ONE cohort: c1 re-refs at 2 (MSN 1 -> 2) and c2 sends a stale
    # ref 1 — decided in the same _flush_round, distinct lanes.
    storm.submit_frame(None, {"rid": 10,
                              "docs": [[doc, c1, 1 + K, 2, K]]},
                       memoryview(storm_words(32, 0, 0).tobytes()))
    storm.submit_frame(None, {"rid": 11,
                              "docs": [[doc, c2, 1 + K, 1, K]]},
                       memoryview(storm_words(32, 1, 0).tobytes()))
    storm.flush()
    live = mgr.export_state()
    w2 = live["docs"][doc]["mirror"]["writers"][c2]
    assert (w2[1], w2[3]) == (2, 1)  # marked at the POST-c1 MSN of 2
    live_entries = mgr.map_entries(doc)
    storm._group_wal.close()
    svc2, storm2, seq2, mh2, mgr2 = build_stack(d, lanes=2)
    storm2.recover()
    assert mgr2.export_state() == live
    assert mgr2.map_entries(doc) == live_entries
    storm2._group_wal.close()


# -- round-16 satellites: viewer frames, combine-log trim, re-promotion --------


def _mega_serve(storm, doc, writers, rounds, r0=0, ref=-1):
    """One frame per writer per round through the promoted tier
    (``ref=-1`` rides the head so the doc MSN advances — the trim
    horizon's input)."""
    for r in range(r0, r0 + rounds):
        for w, client in enumerate(writers):
            storm.submit_frame(None, {
                "rid": f"{r}.{w}",
                "docs": [[doc, client, 1 + r * K, ref, K]]},
                memoryview(storm_words(11, r, w).tobytes()))
        storm.flush()


def test_viewer_frames_keyed_by_parent_for_promoted_doc():
    """ISSUE 13 satellite: viewer rooms key by the PARENT doc at
    harvest, so per-tick viewer frames KEEP flowing for a promoted doc
    (they used to pause — lane ids never matched the room) and carry
    the combiner's doc-space windows, continuous across lanes."""
    from fluidframework_tpu.protocol.codec import (
        decode_storm_push,
        is_storm_body,
    )
    from fluidframework_tpu.server.broadcaster import ViewerPlane

    svc, storm, seq, mh, mgr = build_stack(lanes=2)
    plane = ViewerPlane(svc)
    doc = "mega-viewer"
    writers = [svc.connect(doc, lambda m: None).client_id
               for _ in range(2)]
    svc.pump()
    events = []

    def push(p):
        if isinstance(p, (bytes, bytearray, memoryview)) \
                and is_storm_body(bytes(p)):
            events.append(decode_storm_push(bytes(p)))

    plane.join(doc, push)
    mgr.promote(doc, lanes=2)
    encodes0 = plane.stats["tick_encodes"]
    rounds = 4
    _mega_serve(storm, doc, writers, rounds)
    ticks = [e for e in events if e.get("event") == "storm_tick"]
    # Frames flowed (one encode per LANE batch per tick — L>1 means
    # several doc-space windows per tick, never zero).
    assert len(ticks) == 2 * rounds
    assert plane.stats["tick_encodes"] - encodes0 == 2 * rounds
    assert all(t["doc"] == doc for t in ticks)
    # Doc-space continuity: the windows tile the doc's op seq range
    # with no lane-space aliasing and the MSN column is doc-space.
    seqs = sorted(s for t in ticks
                  for s in range(t["first"], t["last"] + 1))
    assert seqs == list(range(seqs[0], seqs[0] + 2 * rounds * K))


def test_combine_log_trim_bounds_memory_with_exact_reads():
    """ISSUE 13 satellite (ROADMAP mega residue): with
    ``trim_combine_logs`` armed, a long promotion's per-lane segment
    lists stay bounded by the collab window instead of growing one
    segment per combined batch — while converged reads stay EXACT
    (equal to an untrimmed twin serving the same frames) and catch-up
    below the horizon fails with the reload-from-snapshot contract."""
    doc = "mega-trim"

    def play(trim):
        svc, storm, seq, mh, mgr = build_stack(lanes=2)
        mgr.trim_combine_logs = trim
        writers = [svc.connect(doc, lambda m: None).client_id
                   for _ in range(2)]
        svc.pump()
        mgr.promote(doc, lanes=2)
        _mega_serve(storm, doc, writers, 24)
        st = mgr.docs[doc]
        return mgr, storm, st, mgr.map_entries(doc)

    mgr_t, storm_t, st_t, entries_t = play(trim=True)
    mgr_u, _storm_u, st_u, entries_u = play(trim=False)
    # Exactness: trimmed ≡ untrimmed converged map.
    assert entries_t == entries_u and entries_t
    # Bounded memory: the untrimmed twin holds one segment per combined
    # batch; the trimmed run holds a small suffix above the MSN floor.
    untrimmed = sum(len(log.lane_firsts) for log in st_u.logs)
    trimmed = sum(len(log.lane_firsts) for log in st_t.logs)
    assert untrimmed == 48  # 2 writers x 24 rounds
    assert trimmed <= 8, (trimmed, untrimmed)
    assert any(log.floor_lane > 0 for log in st_t.logs)
    # Recent catch-up (at/above the horizon) still serves...
    floor_doc = max(log.floor_doc for log in st_t.logs)
    recent = storm_t.records_overlapping(doc, floor_doc)
    assert recent
    # ...and below-horizon catch-up fails LOUDLY with the documented
    # reload-from-snapshot contract, never a silent gap.
    with pytest.raises(ValueError, match="reload from a snapshot"):
        storm_t.records_overlapping(doc, 0)


def test_re_promotion_epochs_match_never_promoted_twin(tmp_path):
    """ISSUE 13 satellite: a demoted doc RE-promotes into a fresh lane
    EPOCH (``::~mg1.<i>`` ids) — previously refused — and the full
    two-cycle lifecycle converges byte-identical to a never-promoted
    twin on entries, history and the sequencer checkpoint; a recovered
    stack replays BOTH cycles identically."""
    doc = "mega-epochs"

    def digest(svc, storm, seq, mh):
        cp = dataclasses.asdict(seq.checkpoint(doc))
        cp.pop("log_offset", None)
        for c in cp["clients"]:
            c["last_update"] = 0
        return {
            "map": mh.map_entries(doc, storm.datastore, storm.channel),
            "history": [[m.sequence_number, m.client_sequence_number,
                         m.client_id]
                        for m in svc.get_deltas(doc, 0)],
            "sequencer": cp,
        }

    def play(root, promote):
        svc, storm, seq, mh, mgr = build_stack(root, lanes=2)
        writers = [svc.connect(doc, lambda m: None).client_id
                   for _ in range(2)]
        svc.pump()
        storm.checkpoint()  # genesis: the recovery restore source
        if promote:
            mgr.promote(doc, lanes=2)
            assert mgr.docs[doc].epoch == 0
        _mega_serve(storm, doc, writers, 2, r0=0)
        if promote:
            mgr.demote(doc)
            mgr.promote(doc, lanes=2)  # the re-promotion under test
            assert mgr.docs[doc].epoch == 1
            assert all("::~mg1." in lid for lid in mgr.lane_ids(doc))
        _mega_serve(storm, doc, writers, 2, r0=2)
        if promote:
            mgr.demote(doc)
        storm.flush()
        return svc, storm, seq, mh, digest(svc, storm, seq, mh)

    root = str(tmp_path / "cycles")
    *_stack, cycled = play(root, promote=True)
    *_twin, plain = play(str(tmp_path / "twin"), promote=False)
    assert cycled == plain
    # Recovery replays both promotion cycles from the WAL controls.
    svc2, storm2, seq2, mh2, mgr2 = build_stack(root, lanes=2)
    storm2.recover()
    assert mgr2.has_history(doc) and not mgr2.is_promoted(doc)
    assert mgr2.docs[doc].epoch == 1
    assert mgr2.past_epochs[doc][0].epoch == 0
    assert digest(svc2, storm2, seq2, mh2) == cycled


def test_join_mid_promotion_matches_single_lane_twin(tmp_path):
    """Round-17 satellite (ROADMAP item 3 residue): a CLIENT_JOIN that
    lands WHILE the doc is promoted now sequences at the doc's TRUE
    head — routerlicious routes membership through the mirror, which
    fast-forwards the frozen doc row, lets the join take mirror.seq+1
    through the normal deli path, and journals a ``member`` control.
    The full lifecycle (promote → serve → join → post-join writes →
    demote) must converge byte-identical to a never-promoted twin, and
    a recovered stack must replay the membership control identically.
    Before the interception the join was adopt-without-sequence: its
    stale doc-row seq collided with the lane-combined stream and the
    twin's histories diverged."""
    doc = "mega-join"

    def digest(svc, storm, seq, mh):
        cp = dataclasses.asdict(seq.checkpoint(doc))
        cp.pop("log_offset", None)
        for c in cp["clients"]:
            c["last_update"] = 0
        return {
            "map": mh.map_entries(doc, storm.datastore, storm.channel),
            "history": [[m.sequence_number, m.client_sequence_number,
                         int(m.type), m.client_id]
                        for m in svc.get_deltas(doc, 0)],
            "sequencer": cp,
        }

    def serve(storm, participants, r0, rounds):
        # participants: (client, base_round) — cseqs restart per client.
        for r in range(r0, r0 + rounds):
            for w, (client, base) in enumerate(participants):
                storm.submit_frame(None, {
                    "rid": f"{r}.{w}",
                    "docs": [[doc, client, 1 + (r - base) * K, -1, K]]},
                    memoryview(storm_words(21, r, w).tobytes()))
            storm.flush()

    def play(root, promote):
        svc, storm, seq, mh, mgr = build_stack(root, lanes=2)
        writers = [svc.connect(doc, lambda m: None).client_id
                   for _ in range(2)]
        svc.pump()
        storm.checkpoint()
        if promote:
            mgr.promote(doc, lanes=2)
        serve(storm, [(w, 0) for w in writers], 0, 2)
        # THE mid-promotion join: a third client connects while the
        # doc is sharded (the twin connects at the same point).
        late = svc.connect(doc, lambda m: None).client_id
        svc.pump()
        if promote:
            # Sequenced, not just adopted: the mirror's head advanced
            # by exactly the join op.
            st = mgr.docs[doc]
            assert late in st.mirror.writers
        serve(storm, [(w, 0) for w in writers] + [(late, 2)], 2, 2)
        if promote:
            mgr.demote(doc)
        storm.flush()
        return svc, storm, seq, mh, digest(svc, storm, seq, mh)

    root = str(tmp_path / "sharded")
    *_s, sharded = play(root, promote=True)
    *_t, plain = play(str(tmp_path / "twin"), promote=False)
    assert sharded == plain
    # The join is IN the doc history exactly once, at the same seq.
    from fluidframework_tpu.protocol.messages import MessageType
    joins = [h for h in sharded["history"]
             if h[2] == int(MessageType.CLIENT_JOIN)]
    assert joins == [h for h in plain["history"]
                     if h[2] == int(MessageType.CLIENT_JOIN)]
    assert len(joins) == 3
    # Recovery replays the membership control at the identical point.
    svc2, storm2, seq2, mh2, mgr2 = build_stack(root, lanes=2)
    storm2.recover()
    assert digest(svc2, storm2, seq2, mh2) == sharded


def test_idle_eject_inside_round_defers_membership(tmp_path):
    """Round-18 satellite (the promotion-window seam's last gap): a
    membership op firing INSIDE a storm round (the idle-eject cadence
    runs off the round's pump) no longer falls back to legacy
    adopt-at-decide — it parks on the deferred queue and the flush
    maintenance cadence orders it through the FULL mirror path, so the
    leave sequences at the doc's true head exactly like a top-level
    membership op."""
    from fluidframework_tpu.protocol.messages import MessageType
    from fluidframework_tpu.server.sequencer import RawOperation

    doc = "mega-defer"
    svc, storm, seq, mh, mgr = build_stack(str(tmp_path), lanes=2)
    writers = [svc.connect(doc, lambda m: None).client_id
               for _ in range(2)]
    svc.pump()
    storm.checkpoint()
    mgr.promote(doc, lanes=2)
    for r in range(2):
        for w, client in enumerate(writers):
            storm.submit_frame(None, {
                "rid": f"{r}.{w}",
                "docs": [[doc, client, 1 + r * K, -1, K]]},
                memoryview(storm_words(21, r, w).tobytes()))
        storm.flush()
    leave = RawOperation(client_id=None, type=MessageType.CLIENT_LEAVE,
                         data=writers[1], timestamp=5)
    # Simulate the idle-eject path firing mid-round: the intercept must
    # DEFER (never order, never legacy-adopt).
    storm._in_round = True
    try:
        svc._order_membership(doc, leave)
    finally:
        storm._in_round = False
    assert len(mgr._deferred_members) == 1
    assert mgr.docs[doc].mirror.writers[writers[1]].active  # not yet
    # The next flush's maintenance cadence drains it through the full
    # mirror path: settled, sequenced at the true head, journaled.
    storm.flush()
    assert not mgr._deferred_members
    assert not mgr.docs[doc].mirror.writers[writers[1]].active
    mirror_seq = mgr.docs[doc].mirror.seq
    leaves = [m for m in svc.get_deltas(doc, 0)
              if m.type == MessageType.CLIENT_LEAVE]
    assert [m.sequence_number for m in leaves] == [mirror_seq]
    # Post-leave serving + demotion stay exact, and recovery replays
    # the deferred-then-ordered member control identically.
    storm.submit_frame(None, {
        "rid": "post", "docs": [[doc, writers[0], 1 + 2 * K, -1, K]]},
        memoryview(storm_words(21, 2, 0).tobytes()))
    storm.flush()
    mgr.demote(doc)
    storm.flush()
    live = mh.map_entries(doc, storm.datastore, storm.channel)
    storm._group_wal.close()
    svc2, storm2, seq2, mh2, mgr2 = build_stack(str(tmp_path), lanes=2)
    storm2.recover()
    assert mh2.map_entries(doc, storm2.datastore,
                           storm2.channel) == live
    storm2._group_wal.close()
