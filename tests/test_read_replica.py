"""Read-replica tier (round 20 tentpole, server/read_replica.py):
follower-tailing read hosts serving the ENTIRE read surface.

The acceptance bar is byte-exactness: every replica-served read —
``read_at`` at EVERY tested seq, ``get_deltas`` catch-up, branch
reads, viewer tick frames — must be byte-identical to the leader
serving the same request, with staleness surfaced as an explicit
bound (wait-then-shed ``moved`` redirects), never as silently wrong
bytes. The kill -9 story rides tests/test_chaos.py's ``--replicas``
smoke + soak.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from fluidframework_tpu.parallel.placement import ReplicaBalancer
from fluidframework_tpu.protocol.codec import to_wire
from fluidframework_tpu.protocol.messages import MessageType
from fluidframework_tpu.server.durable_store import GitSnapshotStore
from fluidframework_tpu.server.history import HistoryError, HistoryPlane
from fluidframework_tpu.server.read_replica import (
    READ_KINDS,
    ReadReplica,
    ReplicaDirectory,
    ReplicaRedirect,
    ReplicaRouter,
)
from fluidframework_tpu.server.replication import make_replicated_host

K = 8


def _words(seed, r, i, k=K):
    rng = np.random.default_rng([seed, r, i])
    kinds = rng.choice([0, 0, 0, 1, 2], size=k).astype(np.uint32)
    slots = rng.integers(0, 16, k).astype(np.uint32)
    vals = rng.integers(0, 1 << 20, k).astype(np.uint32)
    return (kinds | (slots << 2) | (vals << 12)).astype(np.uint32)


def _build(tmp_path, followers=1, label="hostA", num_docs=8,
           **hist_kw):
    git = GitSnapshotStore(str(tmp_path / "git"))
    f_dirs = [str(tmp_path / f"f{i}") for i in range(followers)]
    storm, plane = make_replicated_host(
        label, str(tmp_path / label), git, f_dirs, num_docs=num_docs)
    hist = HistoryPlane(storm, **hist_kw)
    return git, storm, plane, hist


def _serve(storm, docs, rounds, seed=7, clients=None, cseq=None):
    if clients is None:
        clients = {d: storm.service.connect(d, lambda m: None).client_id
                   for d in docs}
        storm.service.pump()
    cseq = cseq if cseq is not None else {d: 1 for d in docs}
    for _r in range(rounds):
        for i, d in enumerate(docs):
            w = _words(seed, cseq[d], i)
            storm.submit_frame(
                lambda p: None,
                {"rid": (cseq[d], d),
                 "docs": [[d, clients[d], cseq[d], 1, K]]},
                memoryview(w.tobytes()))
            cseq[d] += K
        storm.flush()
    return clients, cseq


def _wire_ops(messages):
    """Canonical wire form of the replicated (storm) message tier."""
    return [to_wire(m) for m in messages
            if m.type == MessageType.OPERATION]


def _close(storm):
    if storm._group_wal is not None:
        storm._group_wal.close()


# -- differential byte-exactness ----------------------------------------------


class TestReplicaByteExactness:

    def test_read_surface_byte_identical(self, tmp_path):
        """THE tentpole bar: replica-served ``read_at`` at EVERY seq
        up to the head, ``get_deltas``, and ``head_seq`` are
        byte-identical to the leader serving the same request."""
        git, storm, plane, hist = _build(tmp_path)
        docs = ["doc-0", "doc-1"]
        _serve(storm, docs, rounds=4)

        rep = ReadReplica(plane.links[0].node, git, "replica0",
                          leader_label="hostA")
        assert rep.lag == 0
        for d in docs:
            head = storm.service.read_at(d, 0)["head_seq"]
            assert rep.head_seq(d) == head
            for s in range(head + 1):
                leader = storm.service.read_at(d, s)
                assert rep.read_at(d, s) == leader, (d, s)
            assert _wire_ops(rep.get_deltas(d, 0, head)) \
                == _wire_ops(storm.service.get_deltas(d, 0, head))
            # Unbounded catch-up (the viewer resync shape) too.
            assert _wire_ops(rep.get_deltas(d, head // 2)) \
                == _wire_ops(storm.service.get_deltas(d, head // 2,
                                                      head))
        _close(storm)

    def test_branch_reads_and_write_redirects(self, tmp_path):
        """Branch forks tail through WAL controls: the replica serves
        the branch (and below-fork parent delegation) byte-identically;
        every write verb sheds a ``moved`` redirect at the leader."""
        git, storm, plane, hist = _build(tmp_path)
        _serve(storm, ["doc-0"], rounds=3)
        branch = hist.fork("doc-0", 16, name="b1")
        storm.flush()

        rep = ReadReplica(plane.links[0].node, git, "replica0",
                          leader_label="hostA")
        assert rep.branches[branch]["parent"] == "doc-0"
        for s in (0, 7, 16):  # below-fork delegation + the fork seq
            assert rep.read_at(branch, s) \
                == storm.service.read_at(branch, s)
        for verb in (lambda: rep.connect("doc-0"),
                     lambda: rep.fork_doc("doc-0", 8),
                     lambda: rep.merge_back(branch)):
            with pytest.raises(ReplicaRedirect) as err:
                verb()
            assert err.value.moved_to == "hostA"
        _close(storm)

    def test_stale_reads_wait_then_shed(self, tmp_path):
        """A seq above the replica's watermark waits ``read_wait_s``
        then sheds a retryable redirect naming the leader — staleness
        is a BOUND, never silently wrong bytes."""
        git, storm, plane, hist = _build(tmp_path)
        _serve(storm, ["doc-0"], rounds=2)
        rep = ReadReplica(plane.links[0].node, git, "replica0",
                          leader_label="hostA", read_wait_s=0.02)
        head = rep.head_seq("doc-0")
        with pytest.raises(ReplicaRedirect) as err:
            rep.read_at("doc-0", head + 100)
        assert err.value.moved_to == "hostA"
        with pytest.raises(ReplicaRedirect):
            rep.get_deltas("doc-0", 0, head + 100)
        assert rep.stats["stale_redirects"] == 2
        assert rep.metrics.counter(
            "replica.stale_redirects").value == 2
        _close(storm)

    def test_mega_promoted_doc_redirects(self, tmp_path):
        """Mega-promoted docs are the documented scope limit: their
        lane-era records translate only through the leader's combine
        logs, so the replica sheds them to the leader — even after a
        demote (the lane era stays leader-only)."""
        from fluidframework_tpu.server.megadoc import MegaDocManager

        git, storm, plane, hist = _build(tmp_path)
        mgr = MegaDocManager(storm, default_lanes=2)
        _serve(storm, ["hot"], rounds=1)
        mgr.promote("hot")
        _serve(storm, ["plain"], rounds=1)

        rep = ReadReplica(plane.links[0].node, git, "replica0",
                          leader_label="hostA")
        assert not rep.can_serve("hot")
        assert rep.can_serve("plain")
        with pytest.raises(ReplicaRedirect) as err:
            rep.read_at("hot", 1)
        assert err.value.moved_to == "hostA"
        # The self-router sheds them at the front door, pre-read.
        assert rep.read_router.route_read("hot", "read_at") == "hostA"
        assert rep.read_router.route_read("plain", "read_at") is None
        _close(storm)


# -- viewer plane on the replica ----------------------------------------------


class TestReplicaViewerPlane:

    def test_rebroadcast_matches_leader_frames(self, tmp_path):
        """A viewer re-homed onto the replica sees byte-identical
        ``storm_tick`` frames: same doc/seq window/op words as the
        leader's own broadcast of the same ticks."""
        from fluidframework_tpu.protocol.codec import (
            decode_body,
            decode_storm_push,
            is_storm_body,
        )

        def collector(events):
            def push(payload):
                if isinstance(payload, (bytes, bytearray)):
                    events.append(decode_storm_push(payload)
                                  if is_storm_body(payload)
                                  else decode_body(payload))
                else:
                    events.append(payload)
            return push

        git, storm, plane, hist = _build(tmp_path)
        leader_events: list = []
        storm.service.connect("doc-0", collector(leader_events),
                              mode="viewer")
        clients, cseq = _serve(storm, ["doc-0"], rounds=1)

        rep = ReadReplica(plane.links[0].node, git, "replica0",
                          leader_label="hostA")
        replica_events: list = []
        hello = rep.viewers.join("doc-0", collector(replica_events))
        assert hello["seq"] == 0  # joined before any replica broadcast
        _serve(storm, ["doc-0"], rounds=2, clients=clients, cseq=cseq)
        rep.poll()

        def ticks(events):
            return [(e["doc"], e["n"], e["first"], e["last"],
                     list(e["words"]))
                    for e in events if isinstance(e, dict)
                    and e.get("event") == "storm_tick"]

        leader_ticks = ticks(leader_events)
        assert ticks(replica_events) == leader_ticks[1:]  # post-join
        assert rep.stats["broadcast_ticks"] == 2
        _close(storm)


# -- retention + restart ------------------------------------------------------


class TestReplicaRetentionRestart:

    def test_trim_then_restart_serves_identical_bytes(self, tmp_path):
        """Checkpoint ships the retention floor (PR 19 residue): the
        follower WAL trims below it, and a RESTARTED replica (fresh
        ReadReplica re-polling the durable follower WAL from zero)
        still serves every addressable read byte-identically — the
        trimmed range answers with the leader's own compaction error."""
        git, storm, plane, hist = _build(
            tmp_path, num_docs=4, tail_retention_summaries=0,
            trim_batch_ticks=1)
        clients, cseq = _serve(storm, ["doc-0"], rounds=4)
        assert hist.compact("doc-0")  # summary + tail trim below it
        _serve(storm, ["doc-0"], rounds=2, clients=clients, cseq=cseq)
        storm.checkpoint()  # ships the follower retention floor
        node = plane.links[0].node
        assert node.retained_floor > 0  # the trim actually shipped

        rep = ReadReplica(node, git, "replica0", leader_label="hostA")
        restarted = ReadReplica(node, git, "replica0b",
                                leader_label="hostA")
        assert restarted.applied == rep.applied
        head = storm.service.read_at("doc-0", 0)["head_seq"]
        floor = hist.tail_floor("doc-0")
        for s in range(head + 1):
            try:
                leader = storm.service.read_at("doc-0", s)
            except HistoryError:
                if s > floor:
                    raise
                for r in (rep, restarted):
                    with pytest.raises(HistoryError):
                        r.read_at("doc-0", s)
                continue
            assert rep.read_at("doc-0", s) == leader, s
            assert restarted.read_at("doc-0", s) == leader, s
        assert _wire_ops(restarted.get_deltas("doc-0", floor, head)) \
            == _wire_ops(storm.service.get_deltas("doc-0", floor,
                                                  head))
        _close(storm)


# -- directory + routing ------------------------------------------------------


class TestDirectoryAndRouting:

    def test_directory_assignment_and_hash_spread(self, tmp_path):
        git = GitSnapshotStore(str(tmp_path / "git"))
        d = ReplicaDirectory(git)
        d.register("r0")
        d.register("r1")
        d.assign_room("hot", ["r0", "r1"])
        # Same client key always lands on the same label; the audience
        # spreads across BOTH labels.
        seen = {d.replica_for("hot", "viewer", key=f"c{i}")
                for i in range(16)}
        assert seen == {"r0", "r1"}
        assert d.replica_for("hot", "viewer", key="c1") \
            == d.replica_for("hot", "viewer", key="c1")
        # Room assignment wins over read-class default; no assignment
        # at all means the leader serves.
        d.assign_reads("read_at", "r1")
        assert d.replica_for("cold", "read_at") == "r1"
        assert d.replica_for("cold", "viewer") is None
        with pytest.raises(ValueError):
            d.assign_reads("write", "r0")
        # A second directory over the SAME store sees flips (the
        # shared-store cross-host contract), and a deregistered label
        # never routes.
        d2 = ReplicaDirectory(git)
        assert d2.rooms() == {"hot": ["r0", "r1"]}
        d.deregister("r1")
        d2.reload()
        assert d2.replica_for("cold", "read_at") is None
        assert set(d2.rooms()["hot"]) == {"r0"}

    def test_router_local_short_circuit(self, tmp_path):
        git = GitSnapshotStore(str(tmp_path / "git"))
        d = ReplicaDirectory(git)
        d.register("r0")
        d.assign_room("hot", "r0")
        router = ReplicaRouter(d, local_label="hostA")
        assert router.route_read("hot", "viewer") == "r0"
        assert router.route_read("hot", "write") is None
        assert router.route_read("cold", "viewer") is None
        # The replica's own router never redirects to itself.
        local = ReplicaRouter(d, local_label="r0")
        assert local.route_read("hot", "viewer") is None
        assert router.metrics.counter("replica.redirects").value == 1

    def test_balancer_spread_rehomes_room(self, tmp_path):
        """ReplicaBalancer flips the directory then re-homes the
        leader's live room: every member gets a ``moved`` directive
        naming a replica label, staleness scrapes to the shared
        registry, and ``unspread`` returns reads to the leader."""
        git, storm, plane, hist = _build(tmp_path, followers=2)
        moved: list = []

        def _viewer(payload):
            if isinstance(payload, dict) \
                    and payload.get("event") == "viewer_resync":
                moved.append(payload.get("moved_to"))

        for _ in range(3):
            storm.service.connect("doc-0", _viewer, mode="viewer")
        _serve(storm, ["doc-0"], rounds=2)
        reps = {f"replica{i}": ReadReplica(plane.links[i].node, git,
                                           f"replica{i}",
                                           leader_label="hostA")
                for i in range(2)}
        directory = ReplicaDirectory(git)
        bal = ReplicaBalancer(directory, reps, leader_storm=storm)
        out = bal.spread_room("doc-0", n=2)
        assert sorted(out["labels"]) == ["replica0", "replica1"]
        assert sum(out["rehomed"].values()) == 3
        assert sorted(moved) == sorted(
            l for l, n in out["rehomed"].items() for _ in range(n))
        # Caught-up replicas: every room staleness gap is 0.
        assert bal.room_staleness() == {
            "doc-0": {"replica0": 0, "replica1": 0}}
        m = bal.metrics
        assert m.gauge("replica.hosts").value == 2
        assert m.gauge("replica.rooms").value == 1
        assert m.gauge("replica.staleness_worst").value == 0
        bal.unspread_room("doc-0")
        assert directory.rooms() == {}
        _close(storm)


# -- promoted fork ≡ demote-then-fork (ROADMAP 5b satellite) ------------------


class TestPromotedFork:

    def test_promoted_fork_equals_demote_then_fork(self, tmp_path):
        """ROADMAP 5b pin: fork() of a mega-PROMOTED doc direct (the
        lane-era records translating through the combine logs) yields a
        branch byte-identical to the old demote-first route — entries,
        every branch read_at, and the parent's materialized history."""
        from fluidframework_tpu.server.megadoc import MegaDocManager

        def play(demote_first: bool, root):
            git, storm, plane, hist = _build(root, num_docs=4)
            mgr = MegaDocManager(storm, default_lanes=2)
            clients, cseq = _serve(storm, ["hot"], rounds=1, seed=11)
            mgr.promote("hot")
            _serve(storm, ["hot"], rounds=3, seed=11,
                   clients=clients, cseq=cseq)
            if demote_first:
                mgr.demote("hot")
                storm.flush()
            branch = hist.fork("hot", 20, name="fb")
            storm.flush()
            reads = {s: hist.read_at(branch, s)["entries"]
                     for s in (0, 10, 20)}
            history = _wire_ops(storm.service.get_deltas("hot", 0, 20))
            _close(storm)
            return reads, history

        direct = play(False, tmp_path / "direct")
        demoted = play(True, tmp_path / "demoted")
        assert direct == demoted


# -- per-room staleness: shed early, score per room (round 21 satellite) -------


class TestRoomStaleness:

    def test_room_staleness_bound_and_early_shed(self, tmp_path):
        """``room_staleness`` is the per-room gap against a known
        leader watermark; an IDLE stream sheds a stale read at once
        instead of burning the whole ``read_wait_s`` grace."""
        import time as _time

        git, storm, plane, hist = _build(tmp_path)
        _serve(storm, ["doc-0"], rounds=2)
        rep = ReadReplica(plane.links[0].node, git, "replica0",
                          leader_label="hostA", read_wait_s=5.0)
        head = rep.head_seq("doc-0")
        # Caught up: zero gap whichever way it is measured.
        assert rep.room_staleness("doc-0") == 0  # FIFO stream bound
        assert rep.room_staleness("doc-0", leader_seq=head) == 0
        assert rep.room_staleness("doc-0", leader_seq=head + 7) == 7
        assert rep.room_staleness("doc-0", leader_seq=head - 3) == 0
        # Early shed: everything shipped is applied and the stream is
        # idle, so the missing seq cannot materialize here — the shed
        # fires in milliseconds, NOT after read_wait_s (5 s).
        t0 = _time.monotonic()
        with pytest.raises(ReplicaRedirect) as err:
            rep.read_at("doc-0", head + 50)
        assert _time.monotonic() - t0 < 2.0
        assert err.value.moved_to == "hostA"
        with pytest.raises(ReplicaRedirect):
            rep.get_deltas("doc-0", 0, head + 50)
        assert rep.stats["room_stale_sheds"] == 2
        assert rep.metrics.counter(
            "replica.room_stale_sheds").value == 2
        _close(storm)

    def test_balancer_scores_per_room_gap_and_gauges_stale_rooms(
            self, tmp_path):
        """The balancer's score is (rooms, worst PER-ROOM gap, lag):
        a replica behind on its assigned room stops winning new rooms
        even against an equally-loaded peer, and the gap surfaces as
        ``replica.stale_rooms`` / ``replica.staleness_worst``."""
        git, storm, plane, hist = _build(tmp_path, followers=2)
        _serve(storm, ["doc-0"], rounds=1)
        reps = {f"replica{i}": ReadReplica(plane.links[i].node, git,
                                           f"replica{i}",
                                           leader_label="hostA")
                for i in range(2)}
        directory = ReplicaDirectory(git)
        bal = ReplicaBalancer(directory, reps, leader_storm=storm)
        directory.assign_room("doc-0", ["replica0", "replica1"])
        # replica0 tails the stream; replica1 stops polling and the
        # leader keeps writing — replica1 is now behind on ITS room.
        _serve(storm, ["doc-0"], rounds=2)
        reps["replica0"].poll()
        stale = bal.room_staleness()
        gap = stale["doc-0"]["replica1"]
        assert stale["doc-0"]["replica0"] == 0 and gap > 0
        s0, s1 = bal.score("replica0"), bal.score("replica1")
        assert s0[0] == s1[0] == 1  # equally loaded (rooms)...
        assert s0[1] == 0 and s1[1] == gap  # ...split by room gap
        assert s0 < s1
        assert bal.pick(1) == ["replica0"]
        out = bal.spread_room("doc-1", n=1)
        assert out["labels"] == ["replica0"]  # fresh replica wins
        bal.update_gauges()
        m = bal.metrics
        assert m.gauge("replica.stale_rooms").value == 1
        assert m.gauge("replica.staleness_worst").value == gap
        # The laggard catches up: gap closes, gauges clear.
        reps["replica1"].poll()
        bal.update_gauges()
        assert bal.room_staleness()["doc-0"]["replica1"] == 0
        assert m.gauge("replica.stale_rooms").value == 0
        _close(storm)
