"""Routerlicious-equivalent assembly tests: partitioned lambdas, offset
checkpoints, restart recovery.

Reference parity model: server/routerlicious lambda tests (deli ticket +
checkpoint restore, scriptorium idempotence) + local-server e2e flows run
over the full partitioned assembly instead of the collapsed in-proc server.
"""

import pytest

from fluidframework_tpu.dds.counter import SharedCounter
from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.runtime.summarizer import SummaryConfig, SummaryManager
from fluidframework_tpu.server.bus import MessageBus, StateStore, partition_for
from fluidframework_tpu.server.routerlicious import RouterliciousService


def make_doc(server, doc_id="doc"):
    service = LocalDocumentService(server, doc_id)
    container = Container.create_detached(service)
    ds = container.runtime.create_datastore("default")
    ds.create_channel("root", SharedMap.channel_type)
    ds.create_channel("clicks", SharedCounter.channel_type)
    ds.create_channel("text", SharedString.channel_type)
    container.attach()
    return container


def parts(container):
    ds = container.runtime.get_datastore("default")
    return (ds.get_channel("root"), ds.get_channel("clicks"),
            ds.get_channel("text"))


class TestE2EOverAssembly:
    def test_two_clients_converge(self):
        server = RouterliciousService()
        c1 = make_doc(server)
        c2 = Container.load(LocalDocumentService(server, "doc"))
        root1, clicks1, text1 = parts(c1)
        root2, clicks2, text2 = parts(c2)

        clicks1.increment(3)
        clicks2.increment(4)
        root1.set("a", 1)
        root2.set("b", 2)
        text1.insert_text(0, "hello ")
        text2.insert_text(len(text2), "world")

        assert clicks1.value == clicks2.value == 7
        assert text1.get_text() == text2.get_text()
        assert c1.summarize() == c2.summarize()

    def test_multiple_documents_partitioned(self):
        server = RouterliciousService(num_partitions=3)
        docs = [f"doc-{i}" for i in range(8)]
        # The docs really spread over >1 partition.
        assert len({partition_for(d, 3) for d in docs}) > 1
        containers = [make_doc(server, d) for d in docs]
        for i, c in enumerate(containers):
            parts(c)[1].increment(i + 1)
        for i, d in enumerate(docs):
            c = Container.load(LocalDocumentService(server, d))
            assert parts(c)[1].value == i + 1

    def test_nack_on_stale_ref_seq_roundtrip(self):
        server = RouterliciousService()
        c1 = make_doc(server)
        # Force a bogus submit under the MSN to provoke a NACK path.
        from fluidframework_tpu.protocol.messages import (
            DocumentMessage, MessageType)
        conn = c1.delta_manager._connection
        conn.submit([DocumentMessage(
            client_sequence_number=999,
            reference_sequence_number=-100,
            type=MessageType.OPERATION,
            contents={"address": "default",
                      "contents": {"address": "clicks",
                                   "contents": {"type": "increment",
                                                "delta": 1}}},
        )])
        assert c1.nacks, "stale refSeq must be NACKed back to the client"

    def test_summarize_ack_flow_over_scribe(self):
        server = RouterliciousService()
        c1 = make_doc(server)
        _root1, clicks1, _ = parts(c1)
        manager = SummaryManager(c1, SummaryConfig(max_ops=1000))
        clicks1.increment(5)
        handle = manager.summarize_now()
        assert handle is not None
        acked = [e for e in manager.events if e.kind == "acked"]
        assert acked and acked[-1].handle == handle, manager.events
        c2 = Container.load(LocalDocumentService(server, "doc"))
        assert parts(c2)[1].value == 5
        assert c1.summarize() == c2.summarize()


class TestRestartRecovery:
    def test_service_restart_resumes_from_checkpoints(self):
        bus, store = MessageBus(), StateStore()
        server1 = RouterliciousService(bus, store)
        c1 = make_doc(server1)
        root1, clicks1, text1 = parts(c1)
        clicks1.increment(2)
        text1.insert_text(0, "abc")
        seq_before = c1.last_processed_seq

        # Crash: connections and lambda instances die; bus + store survive.
        server2 = RouterliciousService(bus, store)
        c2 = Container.load(LocalDocumentService(server2, "doc"))
        _, clicks2, text2 = parts(c2)
        assert clicks2.value == 2
        assert text2.get_text() == "abc"

        # The restored sequencer continues the SAME numbering (no reuse, no
        # gap beyond the join): deli restarted from its checkpoint.
        clicks2.increment(1)
        assert clicks2.value == 3
        assert c2.last_processed_seq > seq_before

        # A third client sees everything.
        c3 = Container.load(LocalDocumentService(server2, "doc"))
        assert parts(c3)[1].value == 3
        assert c2.summarize() == c3.summarize()

    def test_scribe_crash_replay_does_not_duplicate_summary_ack(self):
        # Crash window: scribe produced its SUMMARY_ACK into rawdeltas but
        # died before committing its offsets. The replayed SUMMARIZE op makes
        # scribe produce a SECOND ack raw-op (new offset) — deli must dedupe
        # by summary_sequence_number so only one sequenced ack exists.
        bus, store = MessageBus(), StateStore()
        server1 = RouterliciousService(bus, store)
        c1 = make_doc(server1)
        parts(c1)[1].increment(5)
        manager = SummaryManager(c1, SummaryConfig(max_ops=1000))
        handle = manager.summarize_now()
        assert handle is not None

        from fluidframework_tpu.protocol.messages import MessageType
        acks_before = sum(
            1 for m in store.get("ops/doc")
            if m.type == MessageType.SUMMARY_ACK)
        assert acks_before == 1

        # Wipe scribe's committed offsets: a new instance replays deltas
        # (including the SUMMARIZE op) from the beginning.
        for key in list(bus._offsets):
            if key[1] == "scribe":
                del bus._offsets[key]
        server2 = RouterliciousService(bus, store)
        server2.pump()
        acks_after = sum(
            1 for m in store.get("ops/doc")
            if m.type == MessageType.SUMMARY_ACK)
        assert acks_after == 1, "replayed SUMMARIZE must not re-ack"

    def test_scriptorium_idempotent_on_replay(self):
        bus, store = MessageBus(), StateStore()
        server1 = RouterliciousService(bus, store)
        c1 = make_doc(server1)
        parts(c1)[1].increment(4)

        # Simulate a crash BEFORE scriptorium committed its offsets: wipe
        # the group's offsets so a new instance replays the whole topic.
        for key in list(bus._offsets):
            if key[1] == "scriptorium":
                del bus._offsets[key]
        server2 = RouterliciousService(bus, store)
        server2.pump()
        log = store.get("ops/doc")
        seqs = [m.sequence_number for m in log]
        assert seqs == sorted(set(seqs)), "replay must not duplicate ops"


class TestKernelSequencerPlug:
    def test_batched_kernel_behind_lambda_framework(self):
        """The device-batched sequencer host plugs in at the
        IPartitionLambdaFactory seam (BASELINE.json)."""
        from fluidframework_tpu.server.kernel_host import (
            KernelSequencerHost)
        host = KernelSequencerHost()
        server = RouterliciousService(
            sequencer_factory=host.document_factory())
        c1 = make_doc(server)
        c2 = Container.load(LocalDocumentService(server, "doc"))
        root1, clicks1, _ = parts(c1)
        clicks1.increment(2)
        parts(c2)[1].increment(3)
        assert parts(c2)[1].value == 5 == clicks1.value
        assert c1.summarize() == c2.summarize()
