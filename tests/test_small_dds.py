"""Tests for the smaller DDSes: directory, consensus collections, ink,
summary block — convergence + consensus semantics over the local server."""

from fluidframework_tpu.dds.directory import SharedDirectory
from fluidframework_tpu.dds.ink import Ink
from fluidframework_tpu.dds.ordered_collection import ConsensusQueue
from fluidframework_tpu.dds.register_collection import (
    ConsensusRegisterCollection,
)
from fluidframework_tpu.dds.summary_block import SharedSummaryBlock
from fluidframework_tpu.drivers.local_driver import LocalDocumentService
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.local_server import LocalCollabServer


def make_doc(server, channel_type, doc_id="doc"):
    service = LocalDocumentService(server, doc_id)
    container = Container.create_detached(service)
    datastore = container.runtime.create_datastore("default")
    datastore.create_channel("x", channel_type)
    container.attach()
    return container


def open_doc(server, doc_id="doc"):
    return Container.load(LocalDocumentService(server, doc_id))


def chan(container):
    return container.runtime.get_datastore("default").get_channel("x")


class TestSharedDirectory:
    def test_nested_dirs_converge(self):
        server = LocalCollabServer()
        c1 = make_doc(server, SharedDirectory.channel_type)
        c2 = open_doc(server)
        d1, d2 = chan(c1), chan(c2)
        d1.set("top", 1)
        sub = d1.create_sub_directory("settings")
        sub.set("theme", "dark")
        nested = sub.create_sub_directory("advanced")
        nested.set("flag", True)
        assert d2.get("top") == 1
        assert d2.get_sub_directory("settings").get("theme") == "dark"
        s2 = d2.get_sub_directory("settings")
        assert s2.get_sub_directory("advanced").get("flag") is True
        assert s2.subdirectories() == ["advanced"]
        assert c1.summarize() == c2.summarize()

    def test_conflicts_and_clear_per_subdir(self):
        server = LocalCollabServer()
        c1 = make_doc(server, SharedDirectory.channel_type)
        c2 = open_doc(server)
        d1, d2 = chan(c1), chan(c2)
        sub1 = d1.create_sub_directory("s")
        sub1.set("k", "one")
        d2.get_sub_directory("s").set("k", "two")
        assert d1.get_sub_directory("s").get("k") == "two"
        d1.set("rootk", 1)
        d2.get_sub_directory("s").clear()
        assert d1.get_sub_directory("s").get("k") is None
        assert d1.get("rootk") == 1  # clear scoped to the subdirectory
        assert c1.summarize() == c2.summarize()

    def test_reconnect_replay(self):
        server = LocalCollabServer()
        c1 = make_doc(server, SharedDirectory.channel_type)
        c2 = open_doc(server)
        d2 = chan(c2)
        c2.disconnect()
        d2.set("offline", "yes")
        c2.reconnect()
        assert chan(c1).get("offline") == "yes"
        assert c1.summarize() == c2.summarize()


class TestConsensusRegister:
    def test_write_wins_when_saw_previous(self):
        server = LocalCollabServer()
        c1 = make_doc(server, ConsensusRegisterCollection.channel_type)
        c2 = open_doc(server)
        r1, r2 = chan(c1), chan(c2)
        r1.write("leader", "alice")
        assert r1.read("leader") == r2.read("leader") == "alice"
        r2.write("leader", "bob")  # saw alice's write → supersedes
        assert r1.read("leader") == "bob"
        assert r1.read_versions("leader") == ["bob"]

    def test_concurrent_writes_keep_versions(self):
        server = LocalCollabServer()
        c1 = make_doc(server, ConsensusRegisterCollection.channel_type)
        c2 = open_doc(server)
        r1, r2 = chan(c1), chan(c2)
        c1.inbound.pause()
        c2.inbound.pause()
        r1.write("k", "from1")
        r2.write("k", "from2")  # concurrent: neither saw the other
        c1.inbound.resume()
        c2.inbound.resume()
        assert r1.read_versions("k") == r2.read_versions("k")
        assert len(r1.read_versions("k")) == 2
        # Atomic read = first sequenced; LWW = last.
        assert r1.read("k") == "from1"
        assert r1.read("k", policy=r1.LWW) == "from2"
        assert c1.summarize() == c2.summarize()


class TestConsensusQueue:
    def test_exactly_once_acquire(self):
        server = LocalCollabServer()
        c1 = make_doc(server, ConsensusQueue.channel_type)
        c2 = open_doc(server)
        q1, q2 = chan(c1), chan(c2)
        q1.add("job-a")
        q1.add("job-b")
        # Both clients race to acquire: exactly one gets each item.
        q1.acquire()
        q2.acquire()
        got1, got2 = q1.acquired_items(), q2.acquired_items()
        assert len(got1) == 1 and len(got2) == 1
        assert set(got1.values()) | set(got2.values()) == {"job-a", "job-b"}
        assert len(q1) == len(q2) == 0
        # Complete one, release the other: released returns to the queue.
        (id1,) = got1
        (id2,) = got2
        q1.complete(id1)
        q2.release(id2)
        assert len(q1) == len(q2) == 1
        assert c1.summarize() == c2.summarize()

    def test_departed_client_leases_auto_release(self):
        # Regression: a leaving client's leased items return to the queue.
        server = LocalCollabServer()
        c1 = make_doc(server, ConsensusQueue.channel_type)
        c2 = open_doc(server)
        q1, q2 = chan(c1), chan(c2)
        q1.add("orphanable")
        q2.acquire()
        assert len(q1) == 0 and q2.acquired_items()
        c2.close()  # leave sequences; lease must release on c1
        assert len(q1) == 1
        assert q1.jobs == {}

    def test_acquire_on_empty_queue_is_noop(self):
        server = LocalCollabServer()
        c1 = make_doc(server, ConsensusQueue.channel_type)
        q1 = chan(c1)
        q1.acquire()
        assert q1.acquired_items() == {}


class TestInk:
    def test_concurrent_same_stroke_points_order_identically(self):
        # Regression: points apply at sequencing so interleavings match.
        server = LocalCollabServer()
        c1 = make_doc(server, Ink.channel_type)
        c2 = open_doc(server)
        ink1, ink2 = chan(c1), chan(c2)
        stroke = ink1.create_stroke({})
        c1.inbound.pause()
        c2.inbound.pause()
        ink1.append_point(stroke, 1, 1)
        ink2.append_point(stroke, 2, 2)
        c1.inbound.resume()
        c2.inbound.resume()
        p1 = [p["x"] for p in ink1.get_stroke(stroke)["points"]]
        p2 = [p["x"] for p in ink2.get_stroke(stroke)["points"]]
        assert p1 == p2
        assert c1.summarize() == c2.summarize()

    def test_strokes_converge(self):
        server = LocalCollabServer()
        c1 = make_doc(server, Ink.channel_type)
        c2 = open_doc(server)
        ink1, ink2 = chan(c1), chan(c2)
        stroke = ink1.create_stroke({"color": "red"})
        ink1.append_point(stroke, 1.0, 2.0)
        ink1.append_point(stroke, 3.0, 4.0)
        stroke2 = ink2.create_stroke({"color": "blue"})
        ink2.append_point(stroke2, 9.0, 9.0)
        assert ink2.get_stroke(stroke)["points"][1]["x"] == 3.0
        assert ink1.get_stroke(stroke2)["pen"] == {"color": "blue"}
        assert c1.summarize() == c2.summarize()


class TestSummaryBlock:
    def test_data_rides_summaries_only(self):
        server = LocalCollabServer()
        c1 = make_doc(server, SharedSummaryBlock.channel_type)
        block = chan(c1)
        block.set("checkpoint", {"stats": 42})
        # Not replicated live: a joiner from the pre-set attach snapshot
        # does not see it...
        c2 = open_doc(server)
        assert chan(c2).get("checkpoint") is None
        # ...but a joiner from a later ACKED summary does (a bare upload is
        # not load-visible until the sequenced summarize→ack makes it so).
        from fluidframework_tpu.runtime.summarizer import (
            SummaryConfig,
            SummaryManager,
        )
        SummaryManager(c1, SummaryConfig(max_ops=10**6)).summarize_now()
        c3 = open_doc(server)
        assert chan(c3).get("checkpoint") == {"stats": 42}
