"""Orderer seam + tinylicious driver preset + timed batched cadence
(kafka-orderer, tinylicious-driver, and the continuous-serving shape)."""

import subprocess
import sys
import time

from fluidframework_tpu.dds.map import SharedMap
from fluidframework_tpu.drivers.tinylicious_driver import (
    TinyliciousDocumentServiceFactory,
)
from fluidframework_tpu.protocol.messages import MessageType
from fluidframework_tpu.runtime.container import Container
from fluidframework_tpu.server.bus import Consumer, MessageBus
from fluidframework_tpu.server.orderer import BusOrderer
from fluidframework_tpu.server.routerlicious import RouterliciousService
from fluidframework_tpu.server.sequencer import RawOperation


class TestBusOrderer:
    def test_connection_orders_into_partitioned_topic(self):
        bus = MessageBus()
        bus.create_topic("rawdeltas", num_partitions=4)
        orderer = BusOrderer(bus)
        connection = orderer.connect("doc-a", "client-1")
        raws = [RawOperation(client_id="client-1",
                             type=MessageType.OPERATION, client_seq=i,
                             ref_seq=0, timestamp=i) for i in range(1, 4)]
        connection.order(raws)
        orderer.order_system("doc-a", RawOperation(
            client_id=None, type=MessageType.CLIENT_LEAVE,
            data="client-1", timestamp=9))

        consumer = Consumer(bus, "rawdeltas", "test")
        seen = []
        for partition in range(consumer.num_partitions):
            seen += [m.value for m in consumer.poll(partition)]
        assert seen == raws + [seen[-1]]  # FIFO per doc, one partition
        assert seen[-1].type == MessageType.CLIENT_LEAVE

    def test_service_routes_through_orderer(self):
        # The front door must never touch the bus directly; swapping the
        # orderer swaps the transport for every write.
        service = RouterliciousService()
        ordered = []
        real_system = service.orderer.order_system

        def spy(doc_id, raw):
            ordered.append(raw.type)
            real_system(doc_id, raw)

        service.orderer.order_system = spy
        conn = service.connect("doc", lambda ms: None)
        conn.close()
        assert ordered == [MessageType.CLIENT_JOIN, MessageType.CLIENT_LEAVE]


class TestTinyliciousPreset:
    def test_factory_connects_to_standalone_service(self):
        proc = subprocess.Popen(
            [sys.executable, "-m", "fluidframework_tpu.server.alfred",
             "--port", "0", "--no-merge-host"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        try:
            line = proc.stdout.readline()
            assert line.startswith("READY "), (line, proc.stderr.read())
            port = int(line.split()[1])
            factory = TinyliciousDocumentServiceFactory(port=port)
            service = factory("doc")  # Loader service-factory shape
            container = Container.create_detached(service)
            datastore = container.runtime.create_datastore("default")
            datastore.create_channel("root", SharedMap.channel_type)
            with service.dispatch_lock:
                container.attach()
                datastore.get_channel("root").set("k", 1)
            deadline = time.monotonic() + 15
            while (container.runtime.pending.has_pending
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert not container.runtime.pending.has_pending
            service.close()
        finally:
            proc.terminate()
            proc.wait(timeout=10)


class TestTimedCadence:
    def test_cadence_loop_sequences_without_inline_pump(self):
        proc = subprocess.Popen(
            [sys.executable, "-m", "fluidframework_tpu.server.alfred",
             "--port", "0", "--no-merge-host", "--cadence-ms", "20"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        try:
            line = proc.stdout.readline()
            assert line.startswith("READY "), (line, proc.stderr.read())
            port = int(line.split()[1])
            factory = TinyliciousDocumentServiceFactory(port=port)
            svc1 = factory("doc")
            c1 = Container.create_detached(svc1)
            ds = c1.runtime.create_datastore("default")
            ds.create_channel("root", SharedMap.channel_type)
            with svc1.dispatch_lock:
                c1.attach()
            # Ops sequence only when the service's own tick fires. Each
            # phase gets its own deadline: the first one absorbs the
            # server's one-time JIT compile of the batched deli kernel.
            deadline = time.monotonic() + 60
            while (c1.runtime.pending.has_pending
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert not c1.runtime.pending.has_pending
            svc2 = factory("doc")
            c2 = Container.load(svc2)
            with svc1.dispatch_lock:
                ds.get_channel("root").set("k", 42)

            def remote_value():
                with svc2.dispatch_lock:
                    return (c2.runtime.get_datastore("default")
                            .get_channel("root").get("k"))
            deadline = time.monotonic() + 60
            while remote_value() != 42 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert remote_value() == 42
            svc1.close()
            svc2.close()
        finally:
            proc.terminate()
            proc.wait(timeout=10)
